"""Host-side driver for the JAX backend.

``JaxEngine`` mirrors the spec engine's interface closely enough for
the parity/differential harnesses: build state from traces, run to
quiescence (fully on device via ``lax.while_loop``), read back
dump-at-local-completion snapshots and final state as ``NodeDump``s.

``run_capturing_candidates`` runs the same jitted step cycle-by-cycle
from the host, recording every legal dump-timing state per node
(matching ``spec_engine.Node.dump_candidates``) — used by fixture
parity tests; the all-on-device path is the production/benchmark one.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax

import jax.numpy as jnp

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import CacheState, Instr, MsgType
from hpa2_tpu.models.spec_engine import StallDiagnostic, StallError
from hpa2_tpu.ops import bits
from hpa2_tpu.ops.state import (
    MB_ADDR,
    MB_SENDER,
    MB_TYPE,
    SimState,
    init_state,
)
from hpa2_tpu.ops.step import (
    build_elided_body,
    build_fast_forward,
    build_propose,
    build_run,
    build_step,
    build_step_jitted,
    quiescent,
)
from hpa2_tpu.utils.dump import NodeDump
from hpa2_tpu.utils.trace import IssueRecord


def _node_dump_from(arrs, node_id: int, with_owner: bool = False) -> NodeDump:
    mem, dstate, dsh, down, caddr, cval, cstate = arrs
    return NodeDump(
        proc_id=node_id,
        memory=[int(x) for x in mem[node_id]],
        dir_state=[int(x) for x in dstate[node_id]],
        dir_sharers=[bits.to_int(m) for m in dsh[node_id]],
        cache_addr=[int(x) for x in caddr[node_id]],
        cache_value=[int(x) for x in cval[node_id]],
        cache_state=[int(x) for x in cstate[node_id]],
        dir_owner=(
            [int(x) for x in down[node_id]] if with_owner else None
        ),
    )


def _owner_dumped(config: SystemConfig) -> bool:
    """Owner-plane protocols carry dir_owner in their dumps; MESI keeps
    NodeDump.dir_owner = None so parity fixtures compare unchanged
    (mirrors the spec engine's gate)."""
    from hpa2_tpu.protocols.compiler import planes_for

    return planes_for(config.protocol, config.semantics).has_owner_plane


class JaxEngine:
    def __init__(
        self,
        config: SystemConfig,
        traces: Sequence[Sequence[Instr]],
        replay_order: Optional[Sequence[IssueRecord]] = None,
        max_cycles: int = 1_000_000,
        watchdog_cycles: int = 10_000,
    ):
        self.config = config
        self.max_cycles = max_cycles
        self.watchdog_cycles = watchdog_cycles
        self.replay = replay_order is not None
        if self.replay:
            # fail fast like the spec engine instead of simulating a
            # wrong-but-plausible run from a mismatched order log
            from hpa2_tpu.utils.trace import validate_order_against_traces

            validate_order_against_traces(replay_order, traces)
        self.state: SimState = init_state(config, traces, replay_order)
        self._run = build_run(
            config, replay=self.replay, max_cycles=max_cycles,
            watchdog_cycles=watchdog_cycles,
        )
        self.dump_candidates: List[List[NodeDump]] = [
            [] for _ in range(config.num_procs)
        ]

    # -- production path: whole run on device -------------------------

    def run(self) -> "JaxEngine":
        st = self._run(self.state)
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        self.state = st
        self._check_completed(st)
        return self

    def _check_completed(self, st: SimState) -> None:
        if bool(st.overflow):
            # unreachable by construction: delivery accepts at most
            # cap - count candidates per receiver (backpressure); kept
            # as a cheap engine-bug tripwire
            raise StallError(
                "internal invariant violated: mailbox exceeded capacity "
                "despite backpressure (engine bug)"
            )
        if not bool(quiescent(st)):
            cycle = int(st.cycle)
            stalled_for = cycle - int(st.last_progress)
            if (
                self.watchdog_cycles
                and cycle < self.max_cycles
                and stalled_for >= self.watchdog_cycles
            ):
                raise self._stall_diagnostic(
                    "watchdog: no instruction retired and no mailbox "
                    f"drained for {stalled_for} cycles"
                )
            raise StallError(
                f"no quiescence after {cycle} cycles "
                "(livelock: stale intervention dropped? use "
                "Semantics.intervention_miss_policy='nack')"
            )

    def _stall_diagnostic(self, reason: str) -> StallDiagnostic:
        return stall_diagnostic(self.config, self.state, reason)

    # -- parity path: per-cycle stepping with candidate capture -------

    def run_capturing_candidates(self) -> "JaxEngine":
        step = build_step_jitted(self.config, replay=self.replay)
        st = self.state
        n = self.config.num_procs
        completed = np.zeros(n, dtype=bool)
        cycles = 0
        while not bool(quiescent(st)):
            if cycles >= self.max_cycles or bool(st.overflow):
                self.state = st
                self._check_completed(st)
                break
            handled = (np.asarray(st.mb_count) > 0) & ~np.any(
                np.asarray(st.ob_valid), axis=1
            )
            st = step(st)
            cycles += 1
            snap_taken = np.asarray(st.snap_taken)
            # a node that ended the cycle send-blocked is not a legal
            # dump timing (spec engine phase 4 gates on empty
            # pending_sends) — and is never captured later either
            post_blocked = np.any(np.asarray(st.ob_valid), axis=1)
            capture = [
                i
                for i in range(n)
                if (snap_taken[i] and not completed[i])
                or (completed[i] and handled[i] and not post_blocked[i])
            ]
            if capture:
                arrs = self._live_arrays(st)
                wo = _owner_dumped(self.config)
                for i in capture:
                    if not completed[i]:
                        completed[i] = True
                    self.dump_candidates[i].append(
                        _node_dump_from(arrs, i, wo)
                    )
        self.state = st
        return self

    # -- readback -----------------------------------------------------

    @staticmethod
    def _live_arrays(st: SimState):
        return tuple(
            np.asarray(x)
            for x in (
                st.mem, st.dir_state, st.dir_sharers, st.dir_owner,
                st.cache_addr, st.cache_val, st.cache_state,
            )
        )

    @staticmethod
    def _snap_arrays(st: SimState):
        return tuple(
            np.asarray(x)
            for x in (
                st.snap_mem, st.snap_dir_state, st.snap_dir_sharers,
                st.snap_dir_owner,
                st.snap_cache_addr, st.snap_cache_val, st.snap_cache_state,
            )
        )

    def snapshots(self) -> List[NodeDump]:
        """Canonical (earliest) dump-at-local-completion per node."""
        arrs = self._snap_arrays(self.state)
        wo = _owner_dumped(self.config)
        return [
            _node_dump_from(arrs, i, wo)
            for i in range(self.config.num_procs)
        ]

    def final_dumps(self) -> List[NodeDump]:
        arrs = self._live_arrays(self.state)
        wo = _owner_dumped(self.config)
        return [
            _node_dump_from(arrs, i, wo)
            for i in range(self.config.num_procs)
        ]

    @property
    def cycle(self) -> int:
        return int(self.state.cycle)

    @property
    def instructions(self) -> int:
        return int(self.state.n_instr)

    @property
    def messages(self) -> int:
        return int(self.state.n_msgs)

    def stats(self) -> dict:
        """Counter dict with the spec engine's key names (the
        reference has no observability at all — SURVEY.md §5)."""
        return engine_stats(self.state)

    def link_stats(self) -> dict:
        """Per-link interconnect counters ({} under the ideal
        topology)."""
        return link_stats(self.state, self.config)


def stall_diagnostic(
    config: SystemConfig, st: SimState, reason: str
) -> StallDiagnostic:
    """Structured post-mortem from an UNBATCHED device state (mirrors
    SpecEngine.stall_diagnostic; the JAX engine has no host-side
    flight recorder, so "recent" messages are the still-queued mailbox
    heads — exactly the traffic the stall left in flight).  Shared by
    the single-system engine and the batched/sharded engines, which
    pass the stalled system's slice — so the diagnostic is identical
    whatever partitioning ran the system."""
    from hpa2_tpu.utils.invariants import check_invariants

    n = config.num_procs
    mb_count = np.asarray(st.mb_count)
    waiting = np.asarray(st.waiting)
    blocked = np.any(np.asarray(st.ob_valid), axis=1)
    caddr = np.asarray(st.cache_addr)
    cval = np.asarray(st.cache_val)
    cstate = np.asarray(st.cache_state)
    line_states = {}
    for i in range(n):
        lines = []
        for idx in range(config.cache_size):
            a = int(caddr[i, idx])
            if a == -1:
                continue
            lines.append(
                f"[{idx}] 0x{a:02X}="
                f"{CacheState(int(cstate[i, idx])).name}"
                f"({int(cval[i, idx])})"
            )
        line_states[i] = lines
    mb_data = np.asarray(st.mb_data)
    queued = []
    for i in range(n):
        for s_i in range(min(int(mb_count[i]), 4)):
            row = mb_data[i, s_i]
            queued.append(
                f"queued at node {i}[{s_i}]: from "
                f"{int(row[MB_SENDER])} "
                f"{MsgType(int(row[MB_TYPE])).name} "
                f"0x{int(row[MB_ADDR]):02X}"
            )
    arrs = JaxEngine._live_arrays(st)
    wo = _owner_dumped(config)
    dumps = [_node_dump_from(arrs, i, wo) for i in range(n)]
    return StallDiagnostic(
        reason=reason,
        cycle=int(st.cycle),
        mailbox_depths={i: int(mb_count[i]) for i in range(n)},
        waiting=[i for i in range(n) if waiting[i]],
        blocked=[i for i in range(n) if blocked[i]],
        line_states=line_states,
        recent_msgs=queued,
        invariant_violations=check_invariants(
            dumps, config, mid_flight=True
        ),
        counters=engine_stats(st),
    )


def format_stats(core: dict, msg_counts) -> dict:
    """Shared counter-dict shape (spec-engine key names) for all
    engines — the single place the naming lives."""
    from hpa2_tpu.models.protocol import MsgType

    out = dict(core)
    for t in MsgType:
        if msg_counts[int(t)]:
            out[f"msg_{t.name}"] = int(msg_counts[int(t)])
    return out


def engine_stats(st: SimState) -> dict:
    mc = np.asarray(st.msg_counts)
    if mc.ndim == 2:  # batched state: aggregate over the ensemble
        mc = mc.sum(axis=0)
    tot = lambda x: int(np.sum(np.asarray(x)))
    core = {
        "instructions": tot(st.n_instr),
        "msgs_total": tot(st.n_msgs),
        "read_hits": tot(st.n_read_hits),
        "read_misses": tot(st.n_read_miss),
        "write_hits": tot(st.n_write_hits),
        "write_misses": tot(st.n_write_miss),
        "evictions": tot(st.n_evictions),
        "invalidations": tot(st.n_invalidations),
    }
    # fault-layer counters: present only when nonzero, so fault-free
    # counter parity with the spec engine is key-for-key exact
    for name, field in (
        ("fault_retransmissions", st.n_retrans),
        ("fault_dups_filtered", st.n_dup_filtered),
        ("fault_reorders_fixed", st.n_reorder_fixed),
        ("fault_delays", st.n_delays),
        ("fault_link_stalls", st.n_wire_stalls),
        # interconnect counters: same only-when-nonzero convention,
        # so topology="ideal" keeps the schema byte-for-byte
        ("topo_delay_cycles", st.n_topo_delay),
        ("topo_multicast_saved", st.n_multicast_saved),
        ("topo_combined", st.n_combined),
        # elision counters (ISSUE-12): zero (hence absent) under
        # Config.elide=False and on lockstep backends, so the schema
        # is unchanged wherever elision never fired
        ("elided_cycles", st.n_elided),
        ("multi_hit_retired", st.n_multi_hit),
        # protocol-variant counters (ISSUE-13): MESI builds never
        # touch them, so the reference schema stays exact
        ("forwards", st.n_forwards),
        ("owner_transfers", st.n_owner_xfer),
        ("dir_overflows", st.n_dir_overflow),
        # cross-shard exchange telemetry (ISSUE-15): identically zero
        # on single-chip runs, so their schema never changes
        ("exchange_sent", st.n_exch_sent),
        ("exchange_multicast_saved", st.n_exch_mc_saved),
        ("exchange_combined", st.n_exch_combined),
    ):
        val = tot(field)
        if val:
            core[name] = val
    # the slot high-water mark is a max, not a sum (batched states
    # report the worst lane)
    hwm = int(np.max(np.asarray(st.n_exch_hwm)))
    if hwm:
        core["exchange_slot_hwm"] = hwm
    return format_stats(core, mc)


def link_stats(st: SimState, config: SystemConfig) -> dict:
    """Per-link interconnect counters keyed by link name (mirrors
    LinkTracker.link_stats on the spec side, minus the occupancy
    histogram — the device step keeps only totals and maxima).
    Batched states aggregate over the ensemble (max of maxima)."""
    if not config.interconnect.enabled:
        return {}
    from hpa2_tpu.interconnect.topology import build_topology

    topo = build_topology(
        config.interconnect.topology,
        config.num_procs,
        config.interconnect.hop_latency,
    )
    trav = np.asarray(st.link_traversals)
    peak = np.asarray(st.link_max_load)
    if trav.ndim == 2:
        trav = trav.sum(axis=0)
        peak = peak.max(axis=0)
    return {
        "traversals": {
            name: int(trav[i])
            for i, name in enumerate(topo.link_names)
            if trav[i]
        },
        "max_load": {
            name: int(peak[i])
            for i, name in enumerate(topo.link_names)
            if peak[i]
        },
    }


# ---------------------------------------------------------------------------
# Batched ensembles: B independent systems advanced by one vmapped step
# (the data-parallel axis — BASELINE.json config 5)
# ---------------------------------------------------------------------------

def stack_states(states: Sequence[SimState]) -> SimState:
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


@functools.lru_cache(maxsize=16)
def build_batched_run(config: SystemConfig, max_cycles: int = 1_000_000,
                      watchdog_cycles: int = 0):
    """Jitted run-to-quiescence for a batch of systems.

    One ``lax.while_loop`` drives a vmapped step until EVERY system in
    the batch is quiescent; already-quiescent systems no-op (their
    mailboxes are empty and traces exhausted, so the step leaves them
    unchanged apart from the cycle counter).

    ``watchdog_cycles`` > 0 also stops once no still-live system has
    made progress for that many cycles (the batched analog of
    ops/step.py's single-system watchdog), so a severed-link livelock
    surfaces as a :class:`StallDiagnostic` instead of burning to
    ``max_cycles``.

    With ``config.elide`` the loop body is the event-driven one (one
    shared jump per device step — the minimum over every lane's
    proposal, so the batch-wide cycle counter stays exactly lockstep's;
    see ops/step.py).
    """
    if config.elide:
        body = build_elided_body(
            config, max_cycles, watchdog_cycles, batched=True
        )
    else:
        body = jax.vmap(build_step(config, replay=False))
    vquiet = jax.vmap(quiescent)

    def cond(st):
        live = ~vquiet(st)
        go = (
            jnp.any(live)
            & jnp.all(st.cycle < max_cycles)
            & ~jnp.any(st.overflow)
        )
        if watchdog_cycles:
            fresh = (st.cycle - st.last_progress) < watchdog_cycles
            go = go & jnp.any(live & fresh)
        return go

    def run(st: SimState) -> SimState:
        return jax.lax.while_loop(cond, body, st)

    return jax.jit(run)


def _chunk_loop(config: SystemConfig, chunk: int):
    """cond/body pair shared by the bounded-advance chunk programs.

    The chunk budget ``c`` counts SIMULATED cycles, not device steps:
    under elision each jump is capped at the chunk boundary and
    advances ``c`` by its full width, so every interval barrier lands
    on exactly the lockstep cycle (row ages, admission timing and
    occupancy accounting stay byte-identical).  Host-side watchdogs
    compare ``cycle - last_progress`` at the barrier, both in
    simulated cycles, so a jump can never mask a stall.
    """
    vstep = jax.vmap(build_step(config, replay=False))
    vquiet = jax.vmap(quiescent)

    def cond(c_st):
        c, st = c_st
        return (
            (c < chunk)
            & jnp.any(~vquiet(st))
            & ~jnp.any(st.overflow)
        )

    if config.elide:
        # the chunk clamp bounds every jump, so propose needs no
        # max_cycles/watchdog terms of its own (both are enforced by
        # the host at barriers, in simulated cycles)
        vprop = jax.vmap(build_propose(config, max_cycles=2**31 - 1))
        vff = jax.vmap(build_fast_forward(config), in_axes=(0, None))

        def body(c_st):
            c, st = c_st
            j = jnp.minimum(jnp.min(vprop(st)), chunk - c)
            st = jax.lax.cond(j > 0, lambda s: vff(s, j), vstep, st)
            return c + jnp.maximum(j, 1), st

    else:

        def body(c_st):
            c, st = c_st
            return c + 1, vstep(st)

    return cond, body


@functools.lru_cache(maxsize=16)
def build_batched_run_chunk(config: SystemConfig, chunk: int):
    """Jitted bounded advance: up to ``chunk`` cycles (or quiescence),
    then return to the host — the checkpointing granule.  Repeated
    calls continue bit-identically, so `run_chunk^k` == one long run
    (tests/test_checkpoint.py gates this)."""
    cond, body = _chunk_loop(config, chunk)

    def run(st: SimState) -> SimState:
        return jax.lax.while_loop(
            cond, body, (jnp.zeros((), dtype=jnp.int32), st)
        )[1]

    return jax.jit(run)


@functools.lru_cache(maxsize=16)
def build_fused_batched_run(config: SystemConfig,
                            max_cycles: int = 1_000_000,
                            watchdog_cycles: int = 0):
    """The fused scheduled run for the vmapped backend: ONE jitted
    program scans the precomputed wave plan — each wave is a stacked
    batch of ``resident`` rows driven to quiescence by the exact
    unscheduled :func:`build_batched_run` while-loop — then gathers
    every system's harvest-time row out of the stacked wave results.
    Rows are independent, so waves-to-quiescence is bit-exact with the
    PR-5 host chunk loop by construction, with zero host barriers.

    ``xs`` is the wave-stacked initial state ([n_waves, r, ...] on
    every leaf); ``sys_src[b]`` flat-indexes (wave * r + row) the row
    that carried system ``b``."""
    run = build_batched_run(config, max_cycles, watchdog_cycles)

    def fused(xs: SimState, sys_src) -> SimState:
        _, outs = jax.lax.scan(lambda c, w: (c, run(w)), 0, xs)
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), outs
        )
        return jax.tree_util.tree_map(lambda x: x[sys_src], flat)

    return jax.jit(fused)


class BatchJaxEngine:
    """An ensemble of B independent systems (vmap over the batch axis).

    ``data_shards`` > 1 splits the ensemble across that many local
    devices — the same knob (name and semantics) as
    :class:`~hpa2_tpu.parallel.sharding.DataShardedPallasEngine`, so
    both backends scale out through one API.  The sharded run is the
    ``shard_map(vmap(step))`` grid path (node_shards=1) and stays
    bit-identical to the unsharded one.

    ``schedule=Schedule(...)`` turns on the occupancy scheduler: the
    run becomes a host loop of ``schedule.interval``-cycle chunks
    (``build_batched_run_chunk``, the checkpointing granule), and at
    each chunk barrier quiesced rows are harvested and backfilled from
    an admission queue of not-yet-resident systems
    (``schedule.resident < b`` streams the ensemble through the
    device).  Per-system dumps and activity counters are bit-exact
    versus the unscheduled run — including with fault injection, since
    each system carries its own ``rng_key`` seeded independently of
    batch position — but per-system ``cycle`` is NOT schedule
    invariant here (the vmapped step ticks it unconditionally until
    its cohort drains).  Requires ``snapshots`` semantics unchanged;
    ``self.occupancy`` holds the
    :class:`~hpa2_tpu.ops.schedule.OccupancyStats` after the run.

    ``Schedule(fused=True)`` (the default) runs the whole scheduled
    ensemble as ONE device program (:func:`build_fused_batched_run`):
    a ``lax.scan`` over precomputed admission waves of ``resident``
    rows, zero host barriers, occupancy stats from the static replay
    model.  ``fused=False`` keeps the PR-5 host chunk loop.
    """

    def __init__(
        self,
        config: SystemConfig,
        batch_traces: Sequence[Sequence[Sequence[Instr]]],
        max_cycles: int = 1_000_000,
        data_shards: int = 1,
        watchdog_cycles: int = 0,
        schedule=None,
    ):
        self.config = config
        self.b = len(batch_traces)
        self.max_cycles = max_cycles
        self.watchdog_cycles = watchdog_cycles
        self.data_shards = data_shards
        self.mesh = None
        self.schedule = schedule
        self.occupancy = None
        max_t = max(
            (len(tr) for traces in batch_traces for tr in traces), default=1
        )
        self._max_t = max_t
        if data_shards != 1:
            # deferred import: parallel.sharding imports this module
            from hpa2_tpu.parallel.sharding import make_mesh

            if self.b % data_shards != 0:
                raise ValueError(
                    f"batch {self.b} not divisible by "
                    f"data_shards={data_shards}"
                )
            self.mesh = make_mesh(node_shards=1, data_shards=data_shards)
        if schedule is not None:
            self._resident = schedule.resident or self.b
            if not (0 < self._resident <= self.b):
                raise ValueError(
                    f"schedule.resident={schedule.resident} outside "
                    f"1..{self.b}"
                )
            if self._resident % data_shards or self.b % data_shards:
                raise ValueError(
                    f"schedule.resident={self._resident} and batch "
                    f"{self.b} must divide data_shards={data_shards}"
                )
            # resident rows are built lazily in _run_scheduled; the
            # full-ensemble state exists only after the run (in system
            # order, reconstructed from the harvest store)
            self._batch_traces = list(batch_traces)
            self.state = None
            self._run = None
            return
        self.state = stack_states(
            [init_state(config, t, max_trace_len=max_t) for t in batch_traces]
        )
        if data_shards != 1:
            from hpa2_tpu.parallel.sharding import (
                _place,
                build_node_sharded_run,
                state_specs,
            )

            self.state = _place(
                self.state, self.mesh, state_specs(batched=True)
            )
            self._run = build_node_sharded_run(
                config, self.mesh, batched=True, max_cycles=max_cycles,
                watchdog_cycles=watchdog_cycles,
            )
        else:
            self._run = build_batched_run(
                config, max_cycles=max_cycles,
                watchdog_cycles=watchdog_cycles,
            )

    def run(self) -> "BatchJaxEngine":
        if self.schedule is not None:
            if self.schedule.fused:
                return self._run_scheduled_fused()
            return self._run_scheduled()
        st = self._run(self.state)
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        self.state = st
        if bool(jnp.any(st.overflow)):
            raise StallError("internal invariant violated: mailbox overflow despite backpressure")
        vq = np.asarray(jax.vmap(quiescent)(st))
        if not vq.all():
            raise self._batch_stall(vq)
        return self

    def _group_order(
        self, tr_len: np.ndarray, g: int, gs: int
    ) -> np.ndarray:
        """Group-local admission order honoring the Schedule's
        multi-tenant metadata.  fair-drr charges one wave slot per
        system (keys of one), keeping the wave plan in the same order
        as the ones-cost occupancy replay that models it."""
        from hpa2_tpu.ops.schedule import policy_order

        sl = slice(g * gs, (g + 1) * gs)
        sc = self.schedule
        keys = (
            np.ones(gs, dtype=np.int64) if sc.policy == "fair-drr"
            else tr_len[sl]
        )
        return policy_order(
            keys, sc.policy,
            deadline=(
                None if sc.deadlines is None
                else np.asarray(sc.deadlines[sl], dtype=np.int64)
            ),
            tenant=(
                None if sc.tenants is None
                else np.asarray(sc.tenants[sl], dtype=np.int64)
            ),
            weights=sc.tenant_weights,
        )

    def _run_scheduled_fused(self) -> "BatchJaxEngine":
        """The fused scheduled run: ONE device program consumes a
        precomputed wave plan (rows independent -> run each wave of
        ``resident`` rows to quiescence, ``lax.scan`` over waves) —
        zero host barriers.  Dumps and activity counters are bit-exact
        vs the host chunk loop and vs unscheduled (per-system ``cycle``
        stays non-invariant here, exactly as in the PR-5 path)."""
        cfg = self.config
        r, b = self._resident, self.b
        groups = self.data_shards
        gl, gs = r // groups, b // groups
        n_waves = -(-gs // gl)
        # wave plan: group g's rows sweep its system slice gl at a time
        # in admission-policy order — exactly the admission order of
        # the PR-5 host-loop queues (row order within group, group-local)
        tr_len = np.array([
            max((len(t) for t in self._batch_traces[s]), default=0)
            for s in range(b)
        ], dtype=np.int64)
        wave_sys = np.full((n_waves, r), -1, dtype=np.int64)
        for g in range(groups):
            order = g * gs + self._group_order(tr_len, g, gs)
            for k in range(n_waves):
                chunk_s = order[k * gl:(k + 1) * gl]
                wave_sys[k, g * gl:g * gl + len(chunk_s)] = chunk_s

        empty_traces = [[] for _ in range(cfg.num_procs)]

        def fresh(s):
            traces = self._batch_traces[s] if s >= 0 else empty_traces
            return init_state(cfg, traces, max_trace_len=self._max_t)

        # dead rows (final partial wave) carry an empty-trace state:
        # quiescent from cycle 0, so they never hold a wave open, and
        # their results are not gathered
        xs = jax.tree_util.tree_map(
            lambda *a: jnp.stack(a),
            *[
                stack_states([fresh(s) for s in wave_sys[k]])
                for k in range(n_waves)
            ],
        )
        sys_src = np.empty(b, dtype=np.int64)
        for k in range(n_waves):
            live = wave_sys[k] >= 0
            sys_src[wave_sys[k][live]] = k * r + np.nonzero(live)[0]
        if self.mesh is not None:
            from hpa2_tpu.parallel.sharding import _place, state_specs

            from jax.sharding import PartitionSpec as P

            wave_specs = jax.tree_util.tree_map(
                lambda s: P(None, *s), state_specs(batched=True)
            )
            xs = _place(xs, self.mesh, wave_specs)
        runner = build_fused_batched_run(
            cfg, self.max_cycles, self.watchdog_cycles
        )
        st = runner(xs, jnp.asarray(sys_src))
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        if self.mesh is not None:
            from hpa2_tpu.parallel.sharding import _place, state_specs

            st = _place(st, self.mesh, state_specs(batched=True))
        self.state = st
        if bool(jnp.any(st.overflow)):
            raise StallError(
                "internal invariant violated: mailbox overflow despite "
                "backpressure"
            )
        vq = np.asarray(jax.vmap(quiescent)(st))
        if not vq.all():
            raise self._batch_stall(vq)
        # occupancy stats flow from the same static replay model the
        # plan builder uses — one segment per system per wave
        from hpa2_tpu.ops.schedule import simulate

        self.occupancy = simulate(
            np.ones(b, dtype=np.int64), resident=r, block=1,
            groups=groups, threshold=self.schedule.threshold,
            fused=True, policy=self.schedule.policy,
            deadline=self.schedule.deadlines,
            tenant=self.schedule.tenants,
            tenant_weights=self.schedule.tenant_weights,
        ).attach_elision(st)
        return self

    def _run_scheduled(self) -> "BatchJaxEngine":
        from collections import deque

        from hpa2_tpu.ops.schedule import OccupancyStats

        cfg = self.config
        r = self._resident
        chunk = max(1, self.schedule.interval)
        runner = build_batched_run_chunk(cfg, chunk)
        vq = jax.vmap(quiescent)
        if self.mesh is not None:
            from hpa2_tpu.parallel.sharding import _place, state_specs

            place = lambda st: _place(
                st, self.mesh, state_specs(batched=True)
            )
        else:
            place = lambda st: st

        def fresh(s):
            return init_state(
                cfg, self._batch_traces[s], max_trace_len=self._max_t
            )

        # contiguous group partition, mirroring the Pallas scheduler:
        # each data shard owns a contiguous slice of rows and systems
        # and never exchanges work with its neighbors
        tr_len = np.array([
            max((len(t) for t in self._batch_traces[s]), default=0)
            for s in range(self.b)
        ], dtype=np.int64)
        groups = self.data_shards
        gl, gs = r // groups, self.b // groups
        row_sys = np.full(r, -1, dtype=np.int64)
        queues = []
        for g in range(groups):
            order = g * gs + self._group_order(tr_len, g, gs)
            row_sys[g * gl:(g + 1) * gl] = order[:gl]
            queues.append(deque(int(s) for s in order[gl:]))
        st = place(stack_states([fresh(s) for s in row_sys]))
        store: list = [None] * self.b
        stats = OccupancyStats()
        row_age = np.zeros(r, dtype=np.int64)  # cycles since admission
        while (row_sys >= 0).any():
            live = row_sys >= 0
            stats.intervals += 1
            stats.live_lane_intervals += int(live.sum())
            stats.lane_intervals += r
            stats.block_segments += int(live.sum())
            st = runner(st)
            row_age += chunk
            if bool(jnp.any(st.overflow)):
                raise StallError(
                    "internal invariant violated: mailbox overflow "
                    "despite backpressure"
                )
            q = np.asarray(vq(st))
            for row in np.nonzero(live & q)[0]:
                store[row_sys[row]] = jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[row], st
                )
                row_sys[row] = -1
            stuck = (row_sys >= 0) & ~q & (row_age > self.max_cycles)
            if stuck.any():
                row = int(np.argmax(stuck))
                st_row = jax.tree_util.tree_map(
                    lambda x: np.asarray(x)[row], st
                )
                raise stall_diagnostic(
                    cfg, st_row,
                    f"no quiescence within {self.max_cycles} cycles "
                    f"(system {int(row_sys[row])} of {self.b}, "
                    "scheduled run)",
                )
            repl = []
            for g in range(groups):
                qd = queues[g]
                for row in range(g * gl, (g + 1) * gl):
                    if not qd:
                        break
                    if row_sys[row] < 0:
                        s = qd.popleft()
                        row_sys[row] = s
                        row_age[row] = 0
                        repl.append((row, s))
            if repl:
                stats.admissions += len(repl)
                init_b = stack_states([fresh(s) for _, s in repl])
                idx = jnp.asarray(np.array([row for row, _ in repl]))
                st = place(jax.tree_util.tree_map(
                    lambda a, v: a.at[idx].set(v), st, init_b
                ))
        # invert the row->system assignment history: full-ensemble
        # state in system order, so all readback works unchanged
        self.state = place(stack_states(store))
        self.occupancy = stats.set_mode(fused=False).attach_elision(
            self.state
        )
        return self

    def _batch_stall(self, vq: np.ndarray) -> Exception:
        """A watchdog-tripped batch raises the structured diagnostic of
        the first stalled system — identical to the single-system
        engine's, whatever data partitioning ran it."""
        st = self.state
        b = int(np.argmin(vq))  # first non-quiescent system
        cycle = int(np.asarray(st.cycle)[b])
        stalled_for = cycle - int(np.asarray(st.last_progress)[b])
        if (
            self.watchdog_cycles
            and cycle < self.max_cycles
            and stalled_for >= self.watchdog_cycles
        ):
            st_b = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[b], st
            )
            return stall_diagnostic(
                self.config, st_b,
                "watchdog: no instruction retired and no mailbox "
                f"drained for {stalled_for} cycles "
                f"(system {b} of {self.b})",
            )
        return StallError("batch did not reach quiescence (livelock?)")

    def system_snapshots(self, b: int) -> List[NodeDump]:
        st_b = jax.tree_util.tree_map(lambda x: np.asarray(x)[b], self.state)
        arrs = JaxEngine._snap_arrays(st_b)
        wo = _owner_dumped(self.config)
        return [
            _node_dump_from(arrs, i, wo)
            for i in range(self.config.num_procs)
        ]

    def system_final_dumps(self, b: int) -> List[NodeDump]:
        st_b = jax.tree_util.tree_map(lambda x: np.asarray(x)[b], self.state)
        arrs = JaxEngine._live_arrays(st_b)
        wo = _owner_dumped(self.config)
        return [
            _node_dump_from(arrs, i, wo)
            for i in range(self.config.num_procs)
        ]

    def stats(self) -> dict:
        return engine_stats(self.state)

    def link_stats(self) -> dict:
        """Ensemble-aggregated per-link interconnect counters ({}
        under the ideal topology)."""
        return link_stats(self.state, self.config)

    @property
    def instructions(self) -> int:
        return int(jnp.sum(self.state.n_instr))


# ---------------------------------------------------------------------------
# Resident-row serving session (hpa2_tpu/serving/): the always-on
# analog of the scheduled chunk loop above.  Unlike the Pallas
# session, row completion is NOT host-predictable — quiescence is a
# device property — so the serving loop syncs once per chunk; ingest
# staging (parsing jobs and building fresh row states) still overlaps
# the in-flight chunk.


def _session_donate() -> tuple:
    """Donate the carried state through the jit boundary on device
    backends; CPU has no donation (XLA would only warn and copy)."""
    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    return (0,) if on_tpu else ()


@functools.lru_cache(maxsize=16)
def _build_session_chunk(config: SystemConfig, chunk: int):
    """The bounded-advance chunk program of the scheduled path, jitted
    with the carried rows donated (device backends), so a serving
    session reuses its resident HBM planes across every chunk."""
    cond, body = _chunk_loop(config, chunk)

    def run(st: SimState) -> SimState:
        return jax.lax.while_loop(
            cond, body, (jnp.zeros((), dtype=jnp.int32), st)
        )[1]

    return jax.jit(run, donate_argnums=_session_donate())


class BatchLaneSession:
    """Resident-row serving session for the XLA batch engine.

    Holds ``resident`` rows of :class:`SimState` at fixed shapes; dead
    rows carry an empty-trace state (quiescent from cycle 0, a fixed
    point of the step), so they cost nothing but their lane.  The
    serving loop drives chunks of ``interval`` cycles:

    1. ``row = fresh_row(batch_traces)`` — stage an arriving job's
       initial state (the ingest cost the loop hides behind the
       in-flight chunk).
    2. ``admit(idx, row)`` / ``retire(idx)`` — scatter a job into a
       free row / reset a finished row to the empty state.
    3. ``advance()`` — dispatch one chunk (async).
    4. ``quiescent_rows()`` — sync; rows quiescent with a job resident
       are finished (quiescence is a fixed point, so overshoot between
       chunk boundaries never changes the dump).
    5. ``take_row(idx)`` — gather one row's state for readback.

    All programs are shape-stable: ``compile_counts()`` backs the
    serving loop's zero-recompile guard, exactly as in
    :class:`~hpa2_tpu.ops.pallas_engine.PallasLaneSession`.

    This backend supports the fault-injection layer (the Pallas kernel
    has no link-layer fault model), so it is the served analog of the
    `--faults` CLI path.
    """

    def __init__(
        self,
        config: SystemConfig,
        resident: int,
        max_trace_len: int,
        *,
        interval: int = 256,
        max_cycles: int = 1_000_000,
        data_shards: int = 1,
        window: Optional[int] = None,
    ):
        self.config = config
        self.r = int(resident)
        self._max_t = int(max_trace_len)
        self.interval = max(1, int(interval))
        self.max_cycles = max_cycles
        # window schedule emulation (ISSUE-16): ``window=w`` replays
        # the Pallas path's segment schedule — each row sees its trace
        # clipped to successive w-entry windows with a quiescence
        # barrier between (the serving loop extends via
        # ``window_extend``), so a job migrated pallas -> jax keeps
        # byte-identical dumps.  ``None`` (the default) is the native
        # unwindowed schedule — existing behavior, untouched.
        self.window = None if window is None else max(1, int(window))
        self._full_len = np.zeros((self.r, config.num_procs), np.int32)
        self._seg = np.ones(self.r, np.int64)
        self.mesh = None
        if data_shards != 1:
            from hpa2_tpu.parallel.sharding import (
                _place, make_mesh, state_specs)

            self.mesh = make_mesh(node_shards=1, data_shards=data_shards)
            specs = state_specs(batched=True)
            self._place = lambda st: _place(st, self.mesh, specs)
        else:
            self._place = lambda st: st
        self._runner = _build_session_chunk(config, self.interval)
        self._vq = jax.jit(jax.vmap(quiescent))
        empty = [[] for _ in range(config.num_procs)]
        self._empty_row = init_state(
            config, empty, max_trace_len=self._max_t
        )
        self.state = self._place(
            stack_states([self._empty_row] * self.r)
        )

        @jax.jit
        def _admit(st, idx, row):
            return jax.tree_util.tree_map(
                lambda a, v: jax.lax.dynamic_update_index_in_dim(
                    a, v, idx, 0
                ),
                st, row,
            )

        @jax.jit
        def _take(st, idx):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, idx, 0, keepdims=False
                ),
                st,
            )

        self._admit_jit = _admit
        self._take_jit = _take

    def fresh_row(self, batch_traces) -> SimState:
        """Build an arriving job's initial row state — the identical
        ``init_state`` call (same rng seeding) the one-shot scheduled
        engine uses, so served dumps match batch dumps byte-for-byte."""
        return init_state(
            self.config, batch_traces, max_trace_len=self._max_t
        )

    def admit(self, idx: int, row: SimState) -> None:
        if self.window is not None:
            full = np.asarray(row.tr_len, np.int32)
            self._full_len[idx] = full
            self._seg[idx] = 1
            row = row._replace(tr_len=jnp.asarray(
                np.minimum(full, self.window), jnp.int32))
        self.state = self._place(
            self._admit_jit(self.state, jnp.int32(idx), row)
        )

    def window_done(self, idx: int) -> bool:
        """Under window emulation: is a quiescent row truly finished
        (every node's full trace visible), or just at a barrier?"""
        if self.window is None:
            return True
        return bool(
            (self._seg[idx] * self.window >= self._full_len[idx]).all()
        )

    def window_extend(self, idx: int) -> None:
        """Cross one window barrier: reveal the next ``window`` trace
        entries to a quiescent row (take → bump tr_len → re-admit;
        the quiescent state is a fixed point, so where the chunk
        boundary falls never changes the result)."""
        self._seg[idx] += 1
        clip = np.minimum(
            self._full_len[idx],
            self._seg[idx] * self.window,
        ).astype(np.int32)
        row = self._take_jit(self.state, jnp.int32(idx))
        row = row._replace(tr_len=jnp.asarray(clip, jnp.int32))
        self.state = self._place(
            self._admit_jit(self.state, jnp.int32(idx), row)
        )

    def retire(self, idx: int) -> None:
        """Reset a harvested row to the empty-trace state so it stops
        holding its chunk's while-loop open."""
        self.admit(idx, self._empty_row)

    def advance(self) -> None:
        """Dispatch one chunk of up to ``interval`` cycles over every
        row (async; all-quiescent chunks return immediately)."""
        self.state = self._runner(self.state)

    def quiescent_rows(self) -> np.ndarray:
        """Sync: per-row quiescence after the in-flight chunk, plus the
        overflow invariant check."""
        st = self.state
        if bool(jnp.any(st.overflow)):
            raise StallError(
                "internal invariant violated: mailbox overflow despite "
                "backpressure"
            )
        return np.asarray(self._vq(st))

    def take_row(self, idx: int) -> SimState:
        """Async gather of one row's state (single-system leaves)."""
        return self._take_jit(self.state, jnp.int32(idx))

    def dumps_of(self, row: SimState) -> List[NodeDump]:
        arrs = JaxEngine._live_arrays(row)
        wo = _owner_dumped(self.config)
        return [
            _node_dump_from(arrs, i, wo)
            for i in range(self.config.num_procs)
        ]

    def counters_of(self, row: SimState) -> dict:
        return {
            "instructions": int(np.sum(np.asarray(row.n_instr))),
            "cycles": int(np.asarray(row.cycle)),
            "messages": int(np.sum(np.asarray(row.n_msgs))),
        }

    def stall_of(self, idx: int, reason: str) -> StallDiagnostic:
        row = jax.tree_util.tree_map(
            lambda x: np.asarray(x), self.take_row(idx)
        )
        return stall_diagnostic(self.config, row, reason)

    def compile_counts(self) -> dict:
        return {
            "runner": int(self._runner._cache_size()),
            "admit": int(self._admit_jit._cache_size()),
            "take_row": int(self._take_jit._cache_size()),
            "quiescent": int(self._vq._cache_size()),
        }
