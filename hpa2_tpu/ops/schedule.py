"""Occupancy scheduling for the ensemble engines.

Lockstep ensembles pay the per-block cycle cost until the *slowest*
lane in the block drains: on heterogeneous workloads (zipf trace
lengths, divergent quiescence times) most vector lanes are dead for
most of the run while wall-clock is unchanged.  The run programs
already force every lane to quiescence at trace-window segment
boundaries (``_build_run`` / ``_build_stream_run``), which makes the
segment barrier a legal reschedule point: any lane may carry any
system's state into the next window, because systems are independent
along the lane axis and the pc restarts from the window base.

This module is the *policy*: a deterministic host-side lane scheduler
that, at each barrier,

1. **harvests** lanes whose system has run out of segments,
2. **backfills** freed lanes from a per-group admission queue of
   not-yet-resident systems (ensembles larger than the device-resident
   batch stream through continuously), and
3. **compacts** — once the queue is dry and a block's occupancy falls
   below ``Schedule.threshold`` — by stably packing live lanes into
   dense blocks so whole blocks go quiescent and skip.

The same policy object is replayed, with no simulator attached, by the
static occupancy model (``hpa2_tpu/analysis/occupancy.py``) — so the
model's predicted block-segment count and the engines' measured
counters agree *exactly*, and the tier-1 pinning assertions are not a
10%-band fit but an equality.

Groups exist for ``data_shards=``: each shard is one scheduling group
with its own queue, and lane moves never cross a group boundary — the
permutation is block-diagonal, preserving the zero-collective cycle
body of the sharded run program.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np


@dataclasses.dataclass(frozen=True)
class Schedule:
    """The ``schedule=`` knob shared by both ensemble backends.

    ``resident``: device-resident lanes (rows, for the XLA batch
    engine).  ``None`` keeps the whole ensemble resident; smaller
    values stream the ensemble through the device via the admission
    queue.  ``threshold``: compact a scheduling group once every block
    is backfilled and some live block's occupancy falls below this
    fraction (1.0 = compact whenever it frees a block).  ``interval``:
    cycles per barrier for the XLA batch engine (the Pallas engines
    barrier at trace-window boundaries instead).  ``fused``: drive the
    whole scheduled run as ONE device program from a precomputed
    :class:`SchedulePlan` (compaction/backfill applied on-device at
    the barriers); ``fused=False`` keeps the PR-5 host-barrier loop,
    which relaunches one device program per interval.  ``policy``:
    admission order within each group's queue — ``"fcfs"`` admits in
    ensemble order, ``"longest-first"`` admits systems with the most
    remaining segments first, which packs stragglers early so the tail
    of the run is short traces draining together, ``"deadline-edf"``
    admits earliest-absolute-deadline first (deadline-less systems
    last, arrival order among ties), and ``"fair-drr"`` interleaves
    tenants by weighted deficit round robin (deficit in segments, so a
    heavy job charges its tenant proportionally).

    The multi-tenant metadata rides as optional hashable tuples so the
    knob stays usable as a cache key: ``deadlines[s]`` is system s's
    completion deadline in scheduling intervals (-1 = none),
    ``tenants[s]`` its integer tenant id, and ``tenant_weights[t]``
    tenant t's DRR weight (indexed by tenant id; omitted tenants weigh
    1.0).
    """

    resident: Optional[int] = None
    threshold: float = 0.5
    interval: int = 256
    fused: bool = True
    policy: str = "fcfs"
    deadlines: Optional[Tuple[int, ...]] = None
    tenants: Optional[Tuple[int, ...]] = None
    tenant_weights: Optional[Tuple[float, ...]] = None


#: Admission-queue orderings understood by :class:`LaneScheduler`.
POLICIES = ("fcfs", "longest-first", "deadline-edf", "fair-drr")

#: Per-tenant DRR weights: a dict keyed by tenant id, or a sequence
#: indexed by tenant id.  Missing tenants weigh 1.0.
TenantWeights = Union[Dict[int, float], Sequence[float], None]


def _weight_of(weights: TenantWeights, tenant: int) -> float:
    if weights is None:
        return 1.0
    if isinstance(weights, dict):
        w = float(weights.get(tenant, 1.0))
    elif 0 <= tenant < len(weights):
        w = float(weights[tenant])
    else:
        w = 1.0
    if w <= 0:
        raise ValueError(
            f"tenant {tenant} has non-positive DRR weight {w}"
        )
    return w


def _drr_order(
    keys: np.ndarray, tenant: np.ndarray, weights: TenantWeights
) -> np.ndarray:
    """Deterministic weighted deficit-round-robin total order: tenants
    take turns in sorted-id order, each turn banking ``weight`` segments
    of deficit and releasing queued jobs (arrival order within a
    tenant) while the bank covers the head job's segment cost.  An
    emptied tenant forfeits its bank (classic DRR), so fairness is over
    *backlogged* tenants only."""
    cost = np.maximum(np.asarray(keys, dtype=np.float64), 1.0)
    queues: Dict[int, deque] = {}
    for i, t in enumerate(tenant):
        queues.setdefault(int(t), deque()).append(i)
    order = sorted(queues)
    deficit = {t: 0.0 for t in order}
    out: List[int] = []
    remaining = len(cost)
    while remaining:
        for t in order:
            q = queues[t]
            if not q:
                continue
            deficit[t] += _weight_of(weights, t)
            while q and deficit[t] >= cost[q[0]]:
                i = q.popleft()
                deficit[t] -= cost[i]
                out.append(i)
                remaining -= 1
            if not q:
                deficit[t] = 0.0
    return np.asarray(out, dtype=np.int64)


def policy_order(
    keys: np.ndarray,
    policy: str,
    *,
    deadline: Optional[np.ndarray] = None,
    tenant: Optional[np.ndarray] = None,
    weights: TenantWeights = None,
) -> np.ndarray:
    """Indices of ``keys`` in the admission order ``policy`` dictates.

    ``keys`` are per-system segment counts.  ``fcfs`` preserves the
    given order; ``longest-first`` sorts by descending key, stably, so
    equal-length systems keep their arrival order and the replay stays
    deterministic.  ``deadline-edf`` sorts by ascending ``deadline``
    (absolute interval index; -1 = no deadline, ordered last), stably.
    ``fair-drr`` runs the deterministic weighted deficit round robin
    over ``tenant`` ids with ``keys`` as the per-job segment cost.
    The metadata arrays are ignored by the policies that don't use
    them, so existing two-argument callers are unchanged.
    """
    keys = np.asarray(keys)
    ids = np.arange(len(keys), dtype=np.int64)
    if policy == "fcfs":
        return ids
    if policy == "longest-first":
        return ids[np.argsort(-keys, kind="stable")]
    if policy == "deadline-edf":
        if deadline is None:
            return ids
        dl = np.asarray(deadline, dtype=np.int64)
        eff = np.where(dl < 0, np.iinfo(np.int64).max, dl)
        return ids[np.argsort(eff, kind="stable")]
    if policy == "fair-drr":
        if tenant is None:
            tenant = np.zeros(len(keys), dtype=np.int64)
        return _drr_order(keys, np.asarray(tenant), weights)
    raise ValueError(f"unknown policy {policy!r}; expected one of {POLICIES}")


@dataclasses.dataclass
class OccupancyStats:
    """Counters from a scheduled run (or its static replay)."""

    intervals: int = 0
    #: blocks with >= 1 live lane, summed over intervals — the unit of
    #: device work the gate cannot skip
    block_segments: int = 0
    #: what unscheduled lockstep would execute for the same workload
    lockstep_block_segments: int = 0
    live_lane_intervals: int = 0
    lane_intervals: int = 0
    compactions: int = 0
    admissions: int = 0
    #: host round-trips the run pays for scheduling: one per interval
    #: on the PR-5 host-barrier path, zero when the plan is fused into
    #: the device program
    host_barriers: int = 0
    #: separately launched device programs per run: ``intervals`` on
    #: the host-barrier path, exactly 1 when fused
    device_programs: int = 0
    #: admission-queue depth sampled at every begin_interval (peak and
    #: running sum for the mean) — for a batch run this is the not-yet-
    #: resident backlog; for a served run it is the live job queue
    queue_depth_peak: int = 0
    queue_depth_sum: int = 0
    #: lane-wait (admission latency) in intervals: how long admitted
    #: systems sat queued between enqueue and their backfill barrier
    wait_intervals_total: int = 0
    wait_intervals_max: int = 0
    #: event-driven elision counters (ISSUE-12), attached from the
    #: device state after a scheduled run — zero (hence absent from
    #: ``as_dict``) under ``Config.elide=False``, on lockstep
    #: backends, and in the static replay model, so the artifact
    #: schema is unchanged wherever elision never fired
    elided_cycles: int = 0
    multi_hit_retired: int = 0
    #: multi-tenant service counters (ISSUE-14) — deadline outcomes at
    #: harvest (absolute-interval deadlines only; -1 jobs count in
    #: neither) and live-lane-intervals per tenant id.  Like the
    #: elision counters, absent from ``as_dict`` unless the run carried
    #: deadlines / nontrivial tenants, so legacy artifacts are byte-
    #: identical
    deadline_met: int = 0
    deadline_missed: int = 0
    tenant_live: Dict[int, int] = dataclasses.field(default_factory=dict)
    #: graceful-degradation counter (ISSUE-16): batch-class jobs the
    #: admission ledger shed under overload.  Absent from ``as_dict``
    #: when zero so every pre-shedding artifact is byte-identical.
    shed_jobs: int = 0

    @property
    def mean_live_fraction(self) -> float:
        if not self.lane_intervals:
            return 0.0
        return self.live_lane_intervals / self.lane_intervals

    @property
    def speedup(self) -> float:
        """Lockstep block-segments over scheduled block-segments."""
        if not self.block_segments:
            return 0.0
        return self.lockstep_block_segments / self.block_segments

    @property
    def queue_depth_mean(self) -> float:
        if not self.intervals:
            return 0.0
        return self.queue_depth_sum / self.intervals

    @property
    def wait_intervals_mean(self) -> float:
        if not self.admissions:
            return 0.0
        return self.wait_intervals_total / self.admissions

    def as_dict(self) -> dict:
        out = {
            "intervals": self.intervals,
            "block_segments": self.block_segments,
            "lockstep_block_segments": self.lockstep_block_segments,
            "mean_live_fraction": round(self.mean_live_fraction, 4),
            "speedup": round(self.speedup, 3),
            "compactions": self.compactions,
            "admissions": self.admissions,
            "host_barriers": self.host_barriers,
            "device_programs": self.device_programs,
            "queue_depth_peak": self.queue_depth_peak,
            "queue_depth_mean": round(self.queue_depth_mean, 3),
            "wait_intervals_mean": round(self.wait_intervals_mean, 3),
            "wait_intervals_max": self.wait_intervals_max,
        }
        if self.elided_cycles:
            out["elided_cycles"] = self.elided_cycles
        if self.multi_hit_retired:
            out["multi_hit_retired"] = self.multi_hit_retired
        if self.deadline_met or self.deadline_missed:
            total = self.deadline_met + self.deadline_missed
            out["deadline_met"] = self.deadline_met
            out["deadline_missed"] = self.deadline_missed
            out["deadline_hit_rate"] = round(self.deadline_met / total, 4)
        if self.shed_jobs:
            out["shed_jobs"] = self.shed_jobs
        if self.tenant_live:
            total = sum(self.tenant_live.values())
            out["tenant_share"] = {
                int(t): round(v / total, 4) if total else 0.0
                for t, v in sorted(self.tenant_live.items())
            }
        return out

    def attach_elision(self, state) -> "OccupancyStats":
        """Fold the device elision counters from a finished run's
        state into the scheduler stats (lane-summed, matching
        ``engine_stats``)."""
        self.elided_cycles = int(np.sum(np.asarray(state.n_elided)))
        self.multi_hit_retired = int(np.sum(np.asarray(state.n_multi_hit)))
        return self

    def set_mode(self, fused: bool) -> "OccupancyStats":
        """Fill the execution-shape counters for a run mode: the fused
        path compiles the whole plan into ONE device program with zero
        host barriers; the host-barrier path launches (and syncs) once
        per interval."""
        self.host_barriers = 0 if fused else self.intervals
        self.device_programs = 1 if fused else self.intervals
        return self


@dataclasses.dataclass
class BarrierPlan:
    """What the engine must do to its carried state at one barrier.

    Apply in order: harvest ``finished`` lane columns (pre-permute
    indices), gather-permute lanes by ``perm`` (None = identity), then
    reset ``admitted`` lane columns to the init state (post-permute
    indices; a group never permutes and admits at the same barrier, so
    the two never interact).
    """

    finished: List[Tuple[int, int]]   # (lane, system)
    admitted: List[Tuple[int, int]]   # (lane, system)
    perm: Optional[np.ndarray]        # [R] gather indices or None

    @property
    def trivial(self) -> bool:
        return not self.admitted and self.perm is None


def lockstep_block_segments(nseg: np.ndarray, block: int) -> int:
    """Block-segments an *unscheduled* lockstep run executes: systems
    sit at their ensemble index, and every block runs until its slowest
    lane's last segment (blocks whose lanes have all finished skip at
    the gate for ~free)."""
    nseg = np.asarray(nseg)
    total = 0
    for lo in range(0, len(nseg), block):
        total += int(nseg[lo:lo + block].max(initial=0))
    return total


class LaneScheduler:
    """Deterministic lane->system scheduler, replayed identically by
    the engines (with the simulator in the middle) and by the static
    occupancy model (without one).

    ``nseg[s]`` is the number of trace-window segments system ``s``
    needs (>= 1).  ``resident`` lanes are split into ``groups`` equal
    contiguous lane ranges; systems are partitioned contiguously over
    groups and never migrate between them.

    ``policy`` orders each group's admission queue (see
    :data:`POLICIES`).  The default ``"fcfs"`` reproduces the PR-5/6
    replay bit-for-bit.

    A scheduler built with :meth:`serving` starts with *no* systems
    and grows by :meth:`extend` as jobs arrive — the serving loop's
    rolling extension of the batch replay.  Lanes, groups, and blocks
    keep their fixed shapes; only the system table grows.
    """

    def __init__(
        self,
        nseg: np.ndarray,
        *,
        resident: Optional[int] = None,
        block: int = 1,
        groups: int = 1,
        threshold: float = 0.5,
        policy: str = "fcfs",
        deadline: Optional[np.ndarray] = None,
        tenant: Optional[np.ndarray] = None,
        tenant_weights: TenantWeights = None,
        _serving: bool = False,
    ):
        nseg = np.asarray(nseg, dtype=np.int64)
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; expected one of {POLICIES}"
            )
        if nseg.ndim != 1 or (len(nseg) == 0 and not _serving):
            raise ValueError("nseg must be a non-empty 1-D array")
        if (nseg < 1).any():
            raise ValueError("every system needs >= 1 segment")
        b = len(nseg)
        r = b if resident is None else int(resident)
        if not _serving and not (0 < r <= b):
            raise ValueError(f"resident={r} outside 1..{b}")
        if (b % groups and not _serving) or r % groups:
            raise ValueError(
                f"batch {b} and resident {r} must divide into "
                f"{groups} groups"
            )
        if (r // groups) % block:
            raise ValueError(
                f"per-group lanes {r // groups} not divisible by "
                f"block {block}"
            )
        self.nseg = nseg
        self.b, self.r = b, r
        self.block, self.groups = block, groups
        self.threshold = float(threshold)
        self.policy = policy
        gl = r // groups
        self._gl = gl
        self._serving = _serving
        #: absolute deadline in intervals (-1 = none); at construction
        #: enqueue time is interval 0 so relative == absolute
        if deadline is None:
            self._deadline = np.full(b, -1, dtype=np.int64)
        else:
            self._deadline = np.asarray(deadline, dtype=np.int64).copy()
            if self._deadline.shape != (b,):
                raise ValueError(
                    f"deadline must have shape ({b},), got "
                    f"{self._deadline.shape}"
                )
        if tenant is None:
            self._tenant = np.zeros(b, dtype=np.int64)
        else:
            self._tenant = np.asarray(tenant, dtype=np.int64).copy()
            if self._tenant.shape != (b,):
                raise ValueError(
                    f"tenant must have shape ({b},), got "
                    f"{self._tenant.shape}"
                )
        #: kept by reference: the serving loop grows its weight table
        #: as tenants first appear, and order-time lookups must see it
        self._tenant_weights = tenant_weights
        self._track_tenants = bool(
            (self._tenant != 0).any() or tenant_weights
        )
        self.lane_sys = np.full(r, -1, dtype=np.int64)
        self.lane_seg = np.zeros(r, dtype=np.int64)
        self._queues: List[deque] = [deque() for _ in range(groups)]
        #: stats.intervals at the moment each system was enqueued, for
        #: the lane-wait (admission latency) counters
        self._enq_at: List[int] = [0] * b
        self.stats = OccupancyStats(
            lockstep_block_segments=lockstep_block_segments(nseg, block)
        )
        if not _serving:
            gs = b // groups  # systems per group
            for g in range(groups):
                sys0 = g * gs
                order = self._order_ids(
                    sys0 + np.arange(gs, dtype=np.int64)
                )
                fill = min(gl, gs)
                self.lane_sys[g * gl:g * gl + fill] = order[:fill]
                self._queues[g] = deque(int(s) for s in order[fill:])
        self._in_interval = False

    def _order_ids(self, ids: np.ndarray) -> np.ndarray:
        """System ids reordered by ``policy`` with their metadata."""
        ids = np.asarray(ids, dtype=np.int64)
        order = policy_order(
            self.nseg[ids], self.policy,
            deadline=self._deadline[ids],
            tenant=self._tenant[ids],
            weights=self._tenant_weights,
        )
        return ids[order]

    @classmethod
    def serving(
        cls,
        resident: int,
        *,
        block: int = 1,
        groups: int = 1,
        threshold: float = 0.5,
        policy: str = "fcfs",
        tenant_weights: TenantWeights = None,
    ) -> "LaneScheduler":
        """An initially-empty scheduler for the always-on serving loop:
        all admissions flow through :meth:`extend` + barrier plans."""
        return cls(
            np.zeros(0, dtype=np.int64), resident=resident, block=block,
            groups=groups, threshold=threshold, policy=policy,
            tenant_weights=tenant_weights, _serving=True,
        )

    def extend(
        self,
        nseg_new: np.ndarray,
        *,
        deadline: Optional[np.ndarray] = None,
        tenant: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Enqueue newly-arrived systems (serving mode): each joins the
        group with the shortest queue (ties to the lowest group), and
        each group's queue is re-ordered by ``policy``.  ``deadline``
        is *relative* — intervals from now — and is converted to the
        absolute interval index here.  Returns the new system ids, in
        arrival order."""
        if not self._serving:
            raise RuntimeError("extend() only valid on a serving scheduler")
        nseg_new = np.asarray(nseg_new, dtype=np.int64)
        if nseg_new.ndim != 1 or len(nseg_new) == 0:
            raise ValueError("nseg_new must be a non-empty 1-D array")
        if (nseg_new < 1).any():
            raise ValueError("every system needs >= 1 segment")
        n = len(nseg_new)
        sys0 = self.b
        new_ids = sys0 + np.arange(n, dtype=np.int64)
        self.nseg = np.concatenate([self.nseg, nseg_new])
        self.b = len(self.nseg)
        now = self.stats.intervals
        if deadline is None:
            dl_abs = np.full(n, -1, dtype=np.int64)
        else:
            dl = np.asarray(deadline, dtype=np.int64)
            if dl.shape != (n,):
                raise ValueError(
                    f"deadline must have shape ({n},), got {dl.shape}"
                )
            dl_abs = np.where(dl >= 0, now + dl, -1)
        self._deadline = np.concatenate([self._deadline, dl_abs])
        if tenant is None:
            t_new = np.zeros(n, dtype=np.int64)
        else:
            t_new = np.asarray(tenant, dtype=np.int64)
            if t_new.shape != (n,):
                raise ValueError(
                    f"tenant must have shape ({n},), got {t_new.shape}"
                )
        self._tenant = np.concatenate([self._tenant, t_new])
        if (t_new != 0).any():
            self._track_tenants = True
        self._enq_at.extend([now] * n)
        self.stats.lockstep_block_segments += lockstep_block_segments(
            nseg_new, self.block
        )
        touched = set()
        for s in new_ids:
            # live lanes count toward a group's load so arrivals spread
            # across shards instead of piling onto the first queue
            load = [
                len(self._queues[g])
                + int((self.lane_sys[g * self._gl:(g + 1) * self._gl]
                       >= 0).sum())
                for g in range(self.groups)
            ]
            g = int(np.argmin(load))
            self._queues[g].append(int(s))
            touched.add(g)
        if self.policy != "fcfs":
            for g in touched:
                order = self._order_ids(
                    np.asarray(self._queues[g], dtype=np.int64)
                )
                self._queues[g] = deque(int(s) for s in order)
        return new_ids

    # -- interval protocol -------------------------------------------

    def done(self) -> bool:
        return not (self.lane_sys >= 0).any() and not any(
            self._queues
        )

    def live(self) -> np.ndarray:
        return self.lane_sys >= 0

    def begin_interval(self) -> np.ndarray:
        """Account one interval's device work; returns the live mask
        (every live lane runs exactly one trace-window segment)."""
        if self._in_interval:
            raise RuntimeError("begin_interval called twice")
        self._in_interval = True
        live = self.live()
        st = self.stats
        st.intervals += 1
        st.live_lane_intervals += int(live.sum())
        st.lane_intervals += self.r
        blk = live.reshape(-1, self.block)
        st.block_segments += int(blk.any(axis=1).sum())
        depth = sum(len(q) for q in self._queues)
        st.queue_depth_sum += depth
        st.queue_depth_peak = max(st.queue_depth_peak, depth)
        if self._track_tenants and live.any():
            tenants, counts = np.unique(
                self._tenant[self.lane_sys[live]], return_counts=True
            )
            for t, c in zip(tenants, counts):
                st.tenant_live[int(t)] = (
                    st.tenant_live.get(int(t), 0) + int(c)
                )
        return live

    def end_interval(self) -> BarrierPlan:
        """Advance every live lane one segment and plan the barrier:
        harvest finished systems, backfill from the queues, compact
        under-occupied groups once their queue is dry."""
        if not self._in_interval:
            raise RuntimeError("end_interval before begin_interval")
        self._in_interval = False
        live = self.live()
        self.lane_seg[live] += 1
        finished: List[Tuple[int, int]] = []
        for lane in np.nonzero(live)[0]:
            s = self.lane_sys[lane]
            if self.lane_seg[lane] >= self.nseg[s]:
                finished.append((int(lane), int(s)))
                self.lane_sys[lane] = -1
                self.lane_seg[lane] = 0
                dl = self._deadline[s]
                if dl >= 0:
                    if self.stats.intervals <= dl:
                        self.stats.deadline_met += 1
                    else:
                        self.stats.deadline_missed += 1
        return self._plan_barrier(finished)

    def flush_admissions(self) -> BarrierPlan:
        """Backfill + compact *between* intervals — the serving loop's
        way of admitting queued jobs when no lanes are live (nothing is
        running, so there is no end-of-interval barrier to ride)."""
        if self._in_interval:
            raise RuntimeError("flush_admissions inside an interval")
        return self._plan_barrier([])

    def _plan_barrier(
        self, finished: List[Tuple[int, int]]
    ) -> BarrierPlan:
        admitted: List[Tuple[int, int]] = []
        perm = None
        gl = self._gl
        st = self.stats
        for g in range(self.groups):
            lo, hi = g * gl, (g + 1) * gl
            q = self._queues[g]
            for lane in range(lo, hi):
                if not q:
                    break
                if self.lane_sys[lane] < 0:
                    s = q.popleft()
                    self.lane_sys[lane] = s
                    self.lane_seg[lane] = 0
                    admitted.append((lane, s))
                    wait = st.intervals - self._enq_at[s]
                    st.wait_intervals_total += wait
                    st.wait_intervals_max = max(
                        st.wait_intervals_max, wait
                    )
            if q:
                continue  # group is full again; nothing to compact
            gperm = self._plan_compaction(lo, hi)
            if gperm is not None:
                if perm is None:
                    perm = np.arange(self.r, dtype=np.int64)
                perm[lo:hi] = gperm
        st.admissions += len(admitted)
        return BarrierPlan(finished=finished, admitted=admitted, perm=perm)

    def _plan_compaction(self, lo: int, hi: int) -> Optional[np.ndarray]:
        """Stable live-lane packing for one group, or None if the
        occupancy threshold / block-count test says it isn't worth a
        gather.  Updates lane_sys/lane_seg to the packed layout."""
        sys_g = self.lane_sys[lo:hi]
        seg_g = self.lane_seg[lo:hi]
        live_idx = np.nonzero(sys_g >= 0)[0]
        n_live = len(live_idx)
        if not n_live:
            return None
        per_block = (sys_g >= 0).reshape(-1, self.block).sum(axis=1)
        live_blocks = int((per_block > 0).sum())
        needed = -(-n_live // self.block)
        min_frac = per_block[per_block > 0].min() / self.block
        if needed >= live_blocks or min_frac >= self.threshold:
            return None
        gperm = np.arange(hi - lo, dtype=np.int64)
        gperm[:n_live] = live_idx
        new_sys = np.full(hi - lo, -1, dtype=np.int64)
        new_seg = np.zeros(hi - lo, dtype=np.int64)
        new_sys[:n_live] = sys_g[live_idx]
        new_seg[:n_live] = seg_g[live_idx]
        self.lane_sys[lo:hi] = new_sys
        self.lane_seg[lo:hi] = new_seg
        self.stats.compactions += 1
        return gperm + lo


def simulate(
    nseg: np.ndarray,
    *,
    resident: Optional[int] = None,
    block: int = 1,
    groups: int = 1,
    threshold: float = 0.5,
    fused: bool = True,
    policy: str = "fcfs",
    deadline: Optional[np.ndarray] = None,
    tenant: Optional[np.ndarray] = None,
    tenant_weights: TenantWeights = None,
) -> OccupancyStats:
    """The static occupancy model: replay the scheduling policy from a
    per-system segment-count vector alone.  Because the engines drive
    the *same* ``LaneScheduler``, the returned ``block_segments``
    equals a real scheduled run's counter exactly.  ``fused`` selects
    which execution shape the ``host_barriers``/``device_programs``
    counters describe (the policy itself is mode-invariant)."""
    sched = LaneScheduler(
        nseg, resident=resident, block=block, groups=groups,
        threshold=threshold, policy=policy, deadline=deadline,
        tenant=tenant, tenant_weights=tenant_weights,
    )
    while not sched.done():
        sched.begin_interval()
        sched.end_interval()
    return sched.stats.set_mode(fused)


@dataclasses.dataclass
class SchedulePlan:
    """The whole scheduled run, precomputed: one row per interval of
    the exact ``LaneScheduler`` replay, in the form the fused device
    program consumes.

    Row ``i`` describes interval ``i``: ``sys[i, l]`` is the system
    resident in lane ``l`` (-1 = dead lane), ``seg[i, l]`` its
    trace-window segment index, and the barrier to apply BEFORE the
    interval runs is ``state[l] <- reset[i, l] ? init : state[perm[i,
    l]]`` — exactly the PR-5 host barrier transform
    (``pallas_engine._barrier_fn``), so the fused path is bit-exact by
    construction.  Row 0's barrier is the identity.  Harvest needs no
    plan: a system's state only changes while it is resident, so
    scattering every live lane to its system's store column after
    every interval leaves each column holding the harvest-time value.
    """

    sys: np.ndarray    # [n_int, R] int32, -1 = dead lane
    seg: np.ndarray    # [n_int, R] int32
    perm: np.ndarray   # [n_int, R] int32 gather indices
    reset: np.ndarray  # [n_int, R] int32 0/1
    stats: OccupancyStats

    @property
    def n_intervals(self) -> int:
        return self.sys.shape[0]

    @property
    def resident(self) -> int:
        return self.sys.shape[1]


def build_plan(
    nseg: np.ndarray,
    *,
    resident: Optional[int] = None,
    block: int = 1,
    groups: int = 1,
    threshold: float = 0.5,
    policy: str = "fcfs",
    deadline: Optional[np.ndarray] = None,
    tenant: Optional[np.ndarray] = None,
    tenant_weights: TenantWeights = None,
) -> SchedulePlan:
    """Replay the scheduling policy once, up-front, into the dense
    per-interval arrays the fused run program scans over."""
    sched = LaneScheduler(
        nseg, resident=resident, block=block, groups=groups,
        threshold=threshold, policy=policy, deadline=deadline,
        tenant=tenant, tenant_weights=tenant_weights,
    )
    r = sched.r
    ident = np.arange(r, dtype=np.int32)
    sys_rows, seg_rows, perm_rows, reset_rows = [], [], [], []
    next_perm = ident
    next_reset = np.zeros(r, dtype=np.int32)
    while not sched.done():
        sched.begin_interval()
        sys_rows.append(sched.lane_sys.astype(np.int32))
        seg_rows.append(sched.lane_seg.astype(np.int32))
        perm_rows.append(next_perm)
        reset_rows.append(next_reset)
        plan = sched.end_interval()
        next_perm = (
            ident if plan.perm is None
            else plan.perm.astype(np.int32)
        )
        next_reset = np.zeros(r, dtype=np.int32)
        for lane, _s in plan.admitted:
            next_reset[lane] = 1
    # the final barrier is harvest-only (nothing left to admit or
    # compact), and harvest is implicit in the per-interval scatter
    return SchedulePlan(
        sys=np.stack(sys_rows) if sys_rows else np.zeros(
            (0, r), np.int32),
        seg=np.stack(seg_rows) if seg_rows else np.zeros(
            (0, r), np.int32),
        perm=np.stack(perm_rows) if perm_rows else np.zeros(
            (0, r), np.int32),
        reset=np.stack(reset_rows) if reset_rows else np.zeros(
            (0, r), np.int32),
        stats=sched.stats.set_mode(fused=True),
    )


def segments_needed(tr_len: np.ndarray, window: int) -> np.ndarray:
    """Per-system segment counts from a ``[N, B]`` (or ``[B, N]``-
    transposed caller-side) per-node trace-length plane: a system needs
    ``ceil(longest node trace / window)`` segments, minimum one."""
    longest = np.asarray(tr_len).max(axis=0)
    return np.maximum(1, -(-longest // int(window)))
