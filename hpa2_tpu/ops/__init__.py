"""JAX execution backend: lockstep SoA step function and run loops."""
