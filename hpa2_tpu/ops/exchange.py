"""Targeted cross-shard message exchange for node-axis sharding.

The node-sharded engines partition the node/directory planes into
contiguous blocks of ``n_local = num_procs // node_shards`` nodes per
mesh shard.  Phase C (deterministic delivery) is the only point where
nodes talk across the partition, and it used to be a full
``all_gather`` of the candidate-message tensor — O(num_procs) ICI
bytes per cycle regardless of how many messages actually cross.

This module holds the shared machinery for the replacement, a
*targeted* exchange (used by both ``ops/step.py`` and the XLA-level
node-sharded cycle in ``ops/pallas_engine.py``):

1. **Bucket by destination.** Every send candidate names its receivers
   (point sends: ``recv``, so the owning shard is ``recv // n_local``;
   INV multicasts: the sharer-mask bits that fall in a shard's node
   range).  For each peer shard the sender builds a boolean dest mask
   over its candidate axis.

2. **Order-preserving compaction.** Candidates headed to one peer are
   compacted into a fixed ``K``-entry buffer by an exclusive-cumsum
   position (:func:`compact`), which preserves the global candidate
   order *within* the buffer.  ``K`` defaults to the capacity-exact
   bound (every local candidate could target one peer); a tighter
   ``K`` trades ICI bytes for a loud overflow status — never a silent
   drop, because the sender cannot know whether a dropped entry would
   have been accepted.

3. **Pairwise rounds.** Round ``r`` (1..D-1) ships each shard's buffer
   to shard ``(i + r) % D`` with one ``ppermute`` (:func:`fwd_perm`);
   the acceptance feedback returns along :func:`rev_perm`.  A cycle
   therefore costs exactly ``2*(D-1)`` ppermutes plus ONE stacked psum
   (counters + quiescence), pinned by the collective-count guards in
   tests.

4. **Ordered-rank acceptance.** The receiver sees one *local* block
   plus ``D-1`` received buffers, each tagged with a traced origin
   shard.  Delivery order must equal the single-chip engine's global
   candidate order (all phase-A candidates ascending (origin, slot),
   then all phase-B).  :func:`ordered_rank` computes each entry's rank
   in that order without materializing it: per-block exclusive prefix
   sums plus cross-block offsets gated on ``origin_b' < origin_b`` —
   the received blocks can stay in arrival (round) order, which is
   shard-dependent and therefore cannot be permuted statically.

Everything here is plain XLA (collectives cannot run inside a Mosaic
kernel), shared by the retrofitted ``build_step`` and the node-sharded
cycle program.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

I32 = jnp.int32
U32 = jnp.uint32

# rank sentinel for invalid entries: larger than any mailbox capacity
# but far from i32 overflow when compared against count2 + rank
RANK_INVALID = 1 << 30


def fwd_perm(d: int, r: int) -> List[Tuple[int, int]]:
    """Round-``r`` forward permutation: shard i sends to (i+r) % d."""
    return [(i, (i + r) % d) for i in range(d)]


def rev_perm(d: int, r: int) -> List[Tuple[int, int]]:
    """Feedback permutation for round ``r``: shard i sends back to
    (i-r) % d — the shard whose buffer it received in :func:`fwd_perm`."""
    return [(i, (i - r) % d) for i in range(d)]


def origin_of_round(me, d: int, r: int):
    """The (traced) origin shard of the buffer received in round r."""
    return (me - r) % d


def _ones_below(k, bpw: int):
    """uint32 mask of the low ``clip(k, 0, bpw)`` bits, for traced
    ``k`` (sign-safe up to bpw == 32)."""
    kk = jnp.clip(k, 0, bpw)
    mask = (U32(1) << jnp.clip(kk, 0, 31).astype(U32)) - U32(1)
    if bpw >= 32:
        mask = jnp.where(kk >= 32, U32(0xFFFFFFFF), mask)
    return mask


def range_mask_words(lo, hi, nwords: int, bpw: int):
    """Per-word uint32 masks selecting mask bits whose *global* node id
    falls in [lo, hi): word ``w`` covers ids [w*bpw, w*bpw + bpw).
    ``lo``/``hi`` may be traced (the peer shard id is)."""
    return jnp.stack(
        [
            _ones_below(hi - w * bpw, bpw) & ~_ones_below(lo - w * bpw, bpw)
            for w in range(nwords)
        ]
    )


def compact(dest, payload, k: int):
    """Order-preserving compaction along candidate axis.

    ``dest``: [J, ...] bool/i32 destination mask; ``payload``:
    [R, J, ...] entry rows.  Returns ``(buf [R, k, ...], sel
    [J, k, ...] i32, overflow [...] i32)`` where ``sel`` is the
    one-hot candidate->entry placement (reused to scatter the
    acceptance feedback back onto candidates) and ``overflow`` counts
    candidates that did not fit ``k`` entries (0 when ``k`` is the
    capacity-exact bound)."""
    db = dest if dest.dtype == jnp.bool_ else (dest != 0)
    d = db.astype(I32)
    pos = jnp.cumsum(d, axis=0) - d
    tail = (1,) * (dest.ndim - 1)
    iota_k = jnp.arange(k, dtype=I32).reshape((1, k) + tail)
    sel = jnp.where(
        db[:, None] & (pos[:, None] == iota_k), 1, 0
    ).astype(I32)
    buf = jnp.einsum("rj...,jk...->rk...", payload, sel)
    overflow = jnp.sum(jnp.where(db & (pos >= k), 1, 0), axis=0)
    return buf, sel, overflow


def uncompact(fb, sel):
    """Scatter per-entry feedback rows [R, k, ...] back onto the
    candidate axis via the saved placement: -> [R, J, ...]."""
    return jnp.einsum("rk...,jk...->rj...", fb, sel)


def ordered_rank(
    v_a,
    v_b,
    bounds: Sequence[int],
    origins: Sequence,
    axis: int = 1,
):
    """Global delivery rank per entry over origin-ordered blocks.

    ``v_a``/``v_b``: i32/bool masks of valid phase-A / phase-B entries
    over the concatenated entry axis ``axis`` (blocks are contiguous
    slices ``bounds[b]:bounds[b+1]``, in arbitrary physical order).
    ``origins``: one (possibly traced) shard id per block.  The global
    candidate order is: all A entries ascending (origin, in-block
    index), then all B entries likewise — which matches the single-chip
    candidate grid because shards own contiguous node ranges and
    compaction preserves in-block order.

    Returns ``rank`` with the entry's 0-based position among valid
    entries in that global order (``RANK_INVALID`` where neither mask
    is set).  ``rank`` is the drop-in replacement for the single-chip
    ``cumsum(valid) - valid`` prefix."""
    va = v_a.astype(I32)
    vb = v_b.astype(I32)
    cum_a = jnp.cumsum(va, axis=axis)
    cum_b = jnp.cumsum(vb, axis=axis)
    nb = len(bounds) - 1

    def at(c, idx):
        return jax.lax.index_in_dim(c, idx, axis=axis, keepdims=True)

    base_a, base_b, cnt_a, cnt_b = [], [], [], []
    for b in range(nb):
        s, e = bounds[b], bounds[b + 1]
        ba = at(cum_a, s - 1) if s else jnp.zeros_like(at(cum_a, 0))
        bb_ = at(cum_b, s - 1) if s else jnp.zeros_like(at(cum_b, 0))
        base_a.append(ba)
        base_b.append(bb_)
        cnt_a.append(at(cum_a, e - 1) - ba)
        cnt_b.append(at(cum_b, e - 1) - bb_)
    total_a = at(cum_a, bounds[-1] - 1)

    adj_a, adj_b = [], []
    for b in range(nb):
        off_a = -base_a[b]
        off_b = -base_b[b]
        for b2 in range(nb):
            if b2 == b:
                continue
            earlier = origins[b2] < origins[b]
            off_a = off_a + jnp.where(earlier, cnt_a[b2], 0)
            off_b = off_b + jnp.where(earlier, cnt_b[b2], 0)
        width = bounds[b + 1] - bounds[b]
        shape = list(v_a.shape)
        shape[axis] = width
        adj_a.append(jnp.broadcast_to(off_a, shape))
        adj_b.append(jnp.broadcast_to(off_b, shape))
    adj_a = jnp.concatenate(adj_a, axis=axis)
    adj_b = jnp.concatenate(adj_b, axis=axis)

    rank_a = cum_a - va + adj_a
    rank_b = cum_b - vb + adj_b + total_a
    return jnp.where(
        va != 0,
        rank_a,
        jnp.where(vb != 0, rank_b, RANK_INVALID),
    )
