"""Targeted cross-shard message exchange for node-axis sharding.

The node-sharded engines partition the node/directory planes into
contiguous blocks of ``n_local = num_procs // node_shards`` nodes per
mesh shard.  Phase C (deterministic delivery) is the only point where
nodes talk across the partition, and it used to be a full
``all_gather`` of the candidate-message tensor — O(num_procs) ICI
bytes per cycle regardless of how many messages actually cross.

This module holds the shared machinery for the replacement, a
*targeted* exchange (used by both ``ops/step.py`` and the XLA-level
node-sharded cycle in ``ops/pallas_engine.py``):

1. **Bucket by destination.** Every send candidate names its receivers
   (point sends: ``recv``, so the owning shard is ``recv // n_local``;
   INV multicasts: the sharer-mask bits that fall in a shard's node
   range).  For each peer shard the sender builds a boolean dest mask
   over its candidate axis.

2. **Order-preserving compaction.** Candidates headed to one peer are
   compacted into a fixed ``K``-entry buffer by an exclusive-cumsum
   position (:func:`compact`), which preserves the global candidate
   order *within* the buffer.  ``K`` defaults to the capacity-exact
   bound (every local candidate could target one peer); a tighter
   ``K`` trades ICI bytes for a loud overflow status — never a silent
   drop, because the sender cannot know whether a dropped entry would
   have been accepted.

3. **Transport.** How the per-peer buffers actually move is a pluggable
   *plan* (:func:`make_plan` / :func:`forward` / :func:`feedback`),
   selected by ``SystemConfig.exchange_mode``:

   * ``pairwise`` — the original schedule: round ``r`` (1..D-1) ships
     each shard's buffer to ``(i + r) % D`` with one ``ppermute``
     (:func:`fwd_perm`); feedback returns along :func:`rev_perm`.
     ``2*(D-1)`` serial collectives per cycle — O(D) depth, the
     scaling bottleneck ISSUE-15 replaces.
   * ``a2a`` — all D destination buckets stacked destination-major and
     moved by ONE batched ``all_to_all`` (feedback: one more).  O(1)
     collective depth per cycle.
   * ``butterfly`` — log2(D) stages of stacked ppermutes along an XOR
     (hypercube) schedule; each stage pairs shard ``i`` with
     ``i ^ 2^s`` and ships the half of the bucket stack whose
     destinations differ in bit ``s``.  O(log D) depth for meshes
     whose ``all_to_all`` lowering is slow.
   * ``hier`` — two-tier exchange for meshes that factor as
     ``outer x inner`` (cf. create_hybrid_device_mesh): inner-tier
     rounds first, same-directory READ_REQUESTs are counted as
     combinable at the tier boundary (``exchange_combined``), then
     outer-tier rounds ship only tier-crossing traffic —
     ``2*(Di + Do - 2)`` collectives.

   A cycle costs the plan's collectives plus ONE stacked psum
   (counters + quiescence) and one stacked pmax (slot high-water mark
   + overflow diagnostics), pinned by the collective-count guards in
   tests.

4. **Ordered-rank acceptance.** The receiver sees one *local* block
   plus ``D-1`` received buffers, each tagged with a traced origin
   shard.  Delivery order must equal the single-chip engine's global
   candidate order (all phase-A candidates ascending (origin, slot),
   then all phase-B).  :func:`ordered_rank` computes each entry's rank
   in that order without materializing it: per-block exclusive prefix
   sums plus cross-block offsets gated on ``origin_b' < origin_b`` —
   the received blocks can stay in arrival (round) order, which is
   shard-dependent and therefore cannot be permuted statically.

Everything here is plain XLA (collectives cannot run inside a Mosaic
kernel), shared by the retrofitted ``build_step`` and the node-sharded
cycle program.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

I32 = jnp.int32
U32 = jnp.uint32

# rank sentinel for invalid entries: larger than any mailbox capacity
# but far from i32 overflow when compared against count2 + rank
RANK_INVALID = 1 << 30


def fwd_perm(d: int, r: int) -> List[Tuple[int, int]]:
    """Round-``r`` forward permutation: shard i sends to (i+r) % d."""
    return [(i, (i + r) % d) for i in range(d)]


def rev_perm(d: int, r: int) -> List[Tuple[int, int]]:
    """Feedback permutation for round ``r``: shard i sends back to
    (i-r) % d — the shard whose buffer it received in :func:`fwd_perm`."""
    return [(i, (i - r) % d) for i in range(d)]


def origin_of_round(me, d: int, r: int):
    """The (traced) origin shard of the buffer received in round r."""
    return (me - r) % d


def _ones_below(k, bpw: int):
    """uint32 mask of the low ``clip(k, 0, bpw)`` bits, for traced
    ``k`` (sign-safe up to bpw == 32)."""
    kk = jnp.clip(k, 0, bpw)
    mask = (U32(1) << jnp.clip(kk, 0, 31).astype(U32)) - U32(1)
    if bpw >= 32:
        mask = jnp.where(kk >= 32, U32(0xFFFFFFFF), mask)
    return mask


def range_mask_words(lo, hi, nwords: int, bpw: int):
    """Per-word uint32 masks selecting mask bits whose *global* node id
    falls in [lo, hi): word ``w`` covers ids [w*bpw, w*bpw + bpw).
    ``lo``/``hi`` may be traced (the peer shard id is)."""
    return jnp.stack(
        [
            _ones_below(hi - w * bpw, bpw) & ~_ones_below(lo - w * bpw, bpw)
            for w in range(nwords)
        ]
    )


def compact(dest, payload, k: int):
    """Order-preserving compaction along candidate axis.

    ``dest``: [J, ...] bool/i32 destination mask; ``payload``:
    [R, J, ...] entry rows.  Returns ``(buf [R, k, ...], sel
    [J, k, ...] i32, overflow [...] i32)`` where ``sel`` is the
    one-hot candidate->entry placement (reused to scatter the
    acceptance feedback back onto candidates) and ``overflow`` counts
    candidates that did not fit ``k`` entries (0 when ``k`` is the
    capacity-exact bound)."""
    db = dest if dest.dtype == jnp.bool_ else (dest != 0)
    d = db.astype(I32)
    pos = jnp.cumsum(d, axis=0) - d
    tail = (1,) * (dest.ndim - 1)
    iota_k = jnp.arange(k, dtype=I32).reshape((1, k) + tail)
    sel = jnp.where(
        db[:, None] & (pos[:, None] == iota_k), 1, 0
    ).astype(I32)
    buf = jnp.einsum("rj...,jk...->rk...", payload, sel)
    overflow = jnp.sum(jnp.where(db & (pos >= k), 1, 0), axis=0)
    return buf, sel, overflow


def uncompact(fb, sel):
    """Scatter per-entry feedback rows [R, k, ...] back onto the
    candidate axis via the saved placement: -> [R, J, ...]."""
    return jnp.einsum("rk...,jk...->rj...", fb, sel)


def ordered_rank(
    v_a,
    v_b,
    bounds: Sequence[int],
    origins: Sequence,
    axis: int = 1,
):
    """Global delivery rank per entry over origin-ordered blocks.

    ``v_a``/``v_b``: i32/bool masks of valid phase-A / phase-B entries
    over the concatenated entry axis ``axis`` (blocks are contiguous
    slices ``bounds[b]:bounds[b+1]``, in arbitrary physical order).
    ``origins``: one (possibly traced) shard id per block.  The global
    candidate order is: all A entries ascending (origin, in-block
    index), then all B entries likewise — which matches the single-chip
    candidate grid because shards own contiguous node ranges and
    compaction preserves in-block order.

    Returns ``rank`` with the entry's 0-based position among valid
    entries in that global order (``RANK_INVALID`` where neither mask
    is set).  ``rank`` is the drop-in replacement for the single-chip
    ``cumsum(valid) - valid`` prefix."""
    va = v_a.astype(I32)
    vb = v_b.astype(I32)
    cum_a = jnp.cumsum(va, axis=axis)
    cum_b = jnp.cumsum(vb, axis=axis)
    nb = len(bounds) - 1

    def at(c, idx):
        return jax.lax.index_in_dim(c, idx, axis=axis, keepdims=True)

    base_a, base_b, cnt_a, cnt_b = [], [], [], []
    for b in range(nb):
        s, e = bounds[b], bounds[b + 1]
        ba = at(cum_a, s - 1) if s else jnp.zeros_like(at(cum_a, 0))
        bb_ = at(cum_b, s - 1) if s else jnp.zeros_like(at(cum_b, 0))
        base_a.append(ba)
        base_b.append(bb_)
        cnt_a.append(at(cum_a, e - 1) - ba)
        cnt_b.append(at(cum_b, e - 1) - bb_)
    total_a = at(cum_a, bounds[-1] - 1)

    adj_a, adj_b = [], []
    for b in range(nb):
        off_a = -base_a[b]
        off_b = -base_b[b]
        for b2 in range(nb):
            if b2 == b:
                continue
            earlier = origins[b2] < origins[b]
            off_a = off_a + jnp.where(earlier, cnt_a[b2], 0)
            off_b = off_b + jnp.where(earlier, cnt_b[b2], 0)
        width = bounds[b + 1] - bounds[b]
        shape = list(v_a.shape)
        shape[axis] = width
        adj_a.append(jnp.broadcast_to(off_a, shape))
        adj_b.append(jnp.broadcast_to(off_b, shape))
    adj_a = jnp.concatenate(adj_a, axis=axis)
    adj_b = jnp.concatenate(adj_b, axis=axis)

    rank_a = cum_a - va + adj_a
    rank_b = cum_b - vb + adj_b + total_a
    return jnp.where(
        va != 0,
        rank_a,
        jnp.where(vb != 0, rank_b, RANK_INVALID),
    )


# ======================================================================
# Transport plans (ISSUE-15): how the destination buckets move.
#
# ``forward`` buckets a [R, J0, ...] payload by destination shard
# (``dest_fn(block, peer) -> bool [J, ...]`` must work on *any* payload
# block, because the hier relays re-bucket received entries) and
# returns the received entry blocks plus one traced origin shard id per
# block.  Delivery correctness only needs the (origin block, in-block
# order) pair to be *bijective* — every (origin, destination) pair has
# exactly one route — because :func:`ordered_rank` reconstructs the
# global order from the origin ids; the physical arrival order is
# plan-dependent and irrelevant.
#
# ``feedback`` routes additive per-entry acceptance rows back along the
# exact reverse schedule and scatters them onto the sender's candidate
# axis through the saved compaction placements.  Feedback rows are
# SUMS (bit words from disjoint receivers never collide), so the hier
# relays can simply add the contributions arriving from different
# outer rounds before shipping them down the inner tier.
# ======================================================================

EXCHANGE_MODES = ("pairwise", "a2a", "butterfly", "hier")


class Plan(NamedTuple):
    """Static description of one exchange schedule."""

    mode: str
    d: int
    di: int  # hier inner-tier size (1 for flat modes)
    do: int  # hier outer-tier size (== d for flat modes)


def _auto_inner(d: int) -> int:
    """Largest divisor of ``d`` not above sqrt(d) (1 when d is prime)."""
    best = 1
    f = 1
    while f * f <= d:
        if d % f == 0:
            best = f
        f += 1
    return best


def make_plan(d: int, mode: str = "pairwise", inner: int = 0) -> Plan:
    """Validate + normalize an exchange plan for ``d`` node shards.

    ``inner`` only matters for ``hier``: the inner-tier size (0 = auto,
    the largest divisor of ``d`` <= sqrt(d)).  ``butterfly`` needs a
    power-of-two shard count; ``a2a``/``pairwise`` work for any ``d``.
    """
    if mode not in EXCHANGE_MODES:
        raise ValueError(
            f"unknown exchange_mode {mode!r}; expected one of "
            f"{EXCHANGE_MODES}"
        )
    if d < 1:
        raise ValueError(f"node shard count {d} must be >= 1")
    if mode == "butterfly" and (d & (d - 1)) != 0:
        raise ValueError(
            f"exchange_mode='butterfly' needs a power-of-two shard "
            f"count, got {d}; use 'a2a' (any D) or 'hier'"
        )
    di, do = 1, d
    if mode == "hier":
        di = inner or _auto_inner(d)
        if di < 1 or d % di != 0:
            raise ValueError(
                f"exchange_inner={inner} does not divide node "
                f"shards={d}"
            )
        do = d // di
    return Plan(mode=mode, d=d, di=di, do=do)


def plan_collectives(plan: Plan) -> dict:
    """Per-cycle cross-shard collective budget of a plan (forward +
    feedback; the stacked counter psum/pmax are extra and mode-free).
    Keys: ``ppermute``, ``all_to_all``."""
    d = plan.d
    if d <= 1:
        return {"ppermute": 0, "all_to_all": 0}
    if plan.mode == "pairwise":
        return {"ppermute": 2 * (d - 1), "all_to_all": 0}
    if plan.mode == "a2a":
        return {"ppermute": 0, "all_to_all": 2}
    if plan.mode == "butterfly":
        return {"ppermute": 2 * (d.bit_length() - 1), "all_to_all": 0}
    return {"ppermute": 2 * (plan.di + plan.do - 2), "all_to_all": 0}


def _trail_zeros(payload) -> jnp.ndarray:
    return jnp.zeros(payload.shape[2:], dtype=I32)


def _source_stats(d, me, payload, dest_fn, fan_fn):
    """Mode-independent source-side telemetry over the D-1 peer
    buckets: total entries shipped, per-bucket demand high-water mark,
    and the unicast slots a mask-less INV fan-out would have cost
    (``fan - 1`` per shipped multicast entry)."""
    sent = _trail_zeros(payload)
    hwm = _trail_zeros(payload)
    mc = _trail_zeros(payload)
    for rnd in range(1, d):
        peer = (me + rnd) % d
        mask = dest_fn(payload, peer)
        dcount = jnp.sum(mask.astype(I32), axis=0)
        sent = sent + dcount
        hwm = jnp.maximum(hwm, dcount)
        if fan_fn is not None:
            fan = fan_fn(payload, peer)
            mc = mc + jnp.sum(
                jnp.where(mask, jnp.maximum(fan - 1, 0), 0), axis=0
            )
    return sent, hwm, mc


def _ovf_note(fs: dict, ovf, demand, src, dst) -> None:
    """Fold one compaction's overflow into the running stats: count,
    and a packed max-demand event word ``demand<<16 | src<<8 | dst``
    (field-clipped; the max over shards/cycles therefore names the
    worst offender)."""
    fs["overflow"] = fs["overflow"] + ovf
    word = (
        (jnp.minimum(demand, 0xFFFF) << 16)
        | ((src % 256) << 8)
        | (dst % 256)
    )
    fs["ovf_diag"] = jnp.maximum(
        fs["ovf_diag"], jnp.where(ovf > 0, word, 0)
    )


def _compact_to(fs: dict, mask, block, k: int, src, dst):
    """compact + overflow bookkeeping (statically free when ``k`` can
    hold every entry of the block)."""
    buf, sel, ovf = compact(mask, block, k)
    if k < int(block.shape[1]):
        demand = jnp.sum(mask.astype(I32), axis=0)
        fs["hwm"] = jnp.maximum(fs["hwm"], demand)
        _ovf_note(fs, ovf, demand, src, dst)
    return buf, sel


def forward(
    plan: Plan,
    axis_name,
    me,
    payload,
    dest_fn: Callable,
    k: int,
    fan_fn: Optional[Callable] = None,
    ckey_row: Optional[int] = None,
    nkeys: int = 0,
):
    """Run the plan's forward exchange.

    ``payload``: [R, J0, ...] candidate rows; ``dest_fn(block, peer)``
    -> bool [J, ...] destination mask (``peer`` may be traced);
    ``k``: entries per exchange buffer; ``fan_fn(block, peer)`` -> i32
    [J, ...] receiver count of an entry within ``peer`` (for the
    multicast-savings counter); ``ckey_row``/``nkeys``: payload row
    holding the combining key (0 = not combinable, else key+1) and the
    key-space size — only read by ``hier`` relays.

    Returns ``(bufs, origins, ctx, fstats)``: the received [R, k, ...]
    entry blocks, one origin shard id per block with the local block's
    ``me`` prepended (feed both to :func:`ordered_rank`), the opaque
    feedback context, and the telemetry dict (``sent``, ``hwm``,
    ``mc_saved``, ``combined``, ``overflow``, ``ovf_diag`` — i32 with
    the payload's trailing shape)."""
    d = plan.d
    z = _trail_zeros(payload)
    fs = {
        "sent": z, "hwm": z, "mc_saved": z, "combined": z,
        "overflow": z, "ovf_diag": z,
    }
    if d <= 1:
        return [], [me], (plan.mode, []), fs
    fs["sent"], fs["hwm"], fs["mc_saved"] = _source_stats(
        d, me, payload, dest_fn, fan_fn
    )
    if plan.mode == "pairwise":
        bufs, sels, origins = [], [], [me]
        for rnd in range(1, d):
            peer = (me + rnd) % d
            buf, sel = _compact_to(
                fs, dest_fn(payload, peer), payload, k, me, peer
            )
            bufs.append(
                jax.lax.ppermute(buf, axis_name, fwd_perm(d, rnd))
            )
            sels.append(sel)
            origins.append(origin_of_round(me, d, rnd))
        return bufs, origins, ("pairwise", sels), fs

    if plan.mode == "a2a":
        # one destination-major bucket stack, one tiled all_to_all:
        # received block b arrives from source shard b.  The self block
        # is zero-filled; rolling the received stack by -(me+1) parks
        # it at static position d-1, so the receiver pipeline (rank +
        # delivery scatters) only ever processes d-1 real blocks — the
        # same count as pairwise
        outs, sels = [], []
        for p in range(d):
            mask = dest_fn(payload, p) & (me != p)
            buf, sel = _compact_to(fs, mask, payload, k, me, p)
            outs.append(buf)
            sels.append(sel)
        recv = jax.lax.all_to_all(
            jnp.stack(outs, axis=0), axis_name,
            split_axis=0, concat_axis=0, tiled=True,
        )
        recv = jnp.roll(recv, -(me + 1), axis=0)
        bufs = [recv[b] for b in range(d - 1)]
        origins = [me] + [(me + 1 + b) % d for b in range(d - 1)]
        return bufs, origins, ("a2a", sels), fs

    if plan.mode == "butterfly":
        # XOR fold: bucket rel holds entries for shard me ^ rel; stage
        # s ships (stacked, ONE ppermute) every odd cell to partner
        # i ^ 2^s and concatenates what arrives — after log2(D) stages
        # the surviving cell holds D blocks with block b from source
        # me ^ b (self-inverse routing: each hop fixes one dest bit)
        stages = d.bit_length() - 1
        # rel-0 is the self bucket: never shipped, identically zero —
        # seed it without a compaction and drop it from the delivery
        # set at the end, so the receiver pipeline processes d-1 real
        # blocks like every other mode
        zero_block = jnp.zeros(
            (payload.shape[0], k) + tuple(payload.shape[2:]),
            dtype=payload.dtype,
        )
        blocks, sels = [zero_block], [None]
        for rel in range(1, d):
            buf, sel = _compact_to(
                fs, dest_fn(payload, me ^ rel), payload, k, me, me ^ rel
            )
            blocks.append(buf)
            sels.append(sel)
        cells = [[b] for b in blocks]
        for s in range(stages):
            perm = [(i, i ^ (1 << s)) for i in range(d)]
            ship = jnp.stack(
                [
                    jnp.stack(cells[2 * t + 1])
                    for t in range(len(cells) // 2)
                ]
            )
            got = jax.lax.ppermute(ship, axis_name, perm)
            cells = [
                cells[2 * t] + [got[t, b] for b in range(1 << s)]
                for t in range(len(cells) // 2)
            ]
        bufs = cells[0][1:]
        origins = [me] + [me ^ b for b in range(1, d)]
        return bufs, origins, ("butterfly", sels), fs

    # hier: route (origin -> relay -> dest) with the relay in the
    # origin's outer group at the destination's inner index.  Inner
    # round r ships everything bound for inner index (me_i + r); the
    # relay pool (local payload + the Di-1 inner arrivals) is then
    # re-bucketed per outer round, so DCN-class outer links carry each
    # entry exactly once per destination group.
    di, do = plan.di, plan.do
    me_i = me % di
    me_o = me // di
    j0 = int(payload.shape[1])

    def union_inner(block, ti):
        m = None
        for o in range(do):
            mo = dest_fn(block, o * di + ti)
            m = mo if m is None else (m | mo)
        return m

    inner_sels, bufs, origins = [], [], [me]
    for r in range(1, di):
        ti = (me_i + r) % di
        buf, sel = _compact_to(
            fs, union_inner(payload, ti), payload, k, me, me_o * di + ti
        )
        perm = [
            (o * di + i, o * di + (i + r) % di)
            for o in range(do) for i in range(di)
        ]
        bufs.append(jax.lax.ppermute(buf, axis_name, perm))
        inner_sels.append(sel)
        origins.append(me_o * di + (me_i - r) % di)
    pool = [payload] + list(bufs)  # entries bound for inner index me_i

    outer_sels = []
    for r in range(1, do):
        tgt = ((me_o + r) % do) * di + me_i
        subs, sels_r = [], []
        cnt = None
        for q, blk in enumerate(pool):
            mq = dest_fn(blk, tgt)
            sub, sq = _compact_to(fs, mq, blk, k, me, tgt)
            subs.append(sub)
            sels_r.append(sq)
            if ckey_row is not None and nkeys > 0:
                # tier-boundary combining (modeled, PR-11 style: the
                # duplicates still ship so delivery stays bit-exact;
                # the counter reports what an in-network combiner
                # would have merged on the outer links)
                key = blk[ckey_row]
                kk = jnp.arange(1, nkeys + 1, dtype=I32).reshape(
                    (nkeys,) + (1,) * key.ndim
                )
                hot = jnp.where(
                    (key[None] == kk) & mq[None], 1, 0
                )
                c = jnp.sum(hot, axis=1)
                cnt = c if cnt is None else cnt + c
        if cnt is not None:
            fs["combined"] = fs["combined"] + jnp.sum(
                jnp.maximum(cnt - 1, 0), axis=0
            )
        perm = [
            (o * di + i, ((o + r) % do) * di + i)
            for o in range(do) for i in range(di)
        ]
        got = jax.lax.ppermute(jnp.stack(subs), axis_name, perm)
        og = (me_o - r) % do
        for q in range(di):
            bufs.append(got[q])
            origins.append(
                og * di + (me_i if q == 0 else (me_i - q) % di)
            )
        outer_sels.append(sels_r)
    return bufs, origins, ("hier", (inner_sels, outer_sels, plan)), fs


def feedback(plan: Plan, axis_name, fb_blocks: List, ctx):
    """Route additive acceptance rows back to the senders.

    ``fb_blocks``: one [R2, k, ...] feedback slice per received block,
    in :func:`forward`'s block order.  Returns the [R2, J0, ...]
    contribution to the *local* candidate axis (add it to the local
    feedback slice)."""
    mode, saved = ctx
    d = plan.d
    if d <= 1 or not fb_blocks:
        return 0
    if mode == "pairwise":
        acc = None
        for i, (fb, sel) in enumerate(zip(fb_blocks, saved)):
            fbp = jax.lax.ppermute(fb, axis_name, rev_perm(d, i + 1))
            c = uncompact(fbp, sel)
            acc = c if acc is None else acc + c
        return acc
    if mode == "a2a":
        # undo forward's roll: fb block r answers the sender at
        # (me+1+r) % d, so rolling by +(me+1) puts each chunk at its
        # destination-major position (the zero pad lands on self)
        me = jax.lax.axis_index(axis_name)
        pad = jnp.zeros_like(fb_blocks[0])
        out = jnp.roll(
            jnp.stack(list(fb_blocks) + [pad], axis=0), me + 1, axis=0
        )
        ret = jax.lax.all_to_all(
            out, axis_name, split_axis=0, concat_axis=0, tiled=True,
        )
        acc = None
        for b in range(d):
            c = uncompact(ret[b], saved[b])
            acc = c if acc is None else acc + c
        return acc
    if mode == "butterfly":
        stages = d.bit_length() - 1
        # forward dropped the inert rel-0 block; restore its slot so
        # the reverse fold sees the full d-cell structure
        cells = [[jnp.zeros_like(fb_blocks[0])] + list(fb_blocks)]
        for s in reversed(range(stages)):
            half = 1 << s
            perm = [(i, i ^ (1 << s)) for i in range(d)]
            ship = jnp.stack([jnp.stack(c[half:]) for c in cells])
            got = jax.lax.ppermute(ship, axis_name, perm)
            nxt = []
            for t, c in enumerate(cells):
                nxt.append(c[:half])
                nxt.append([got[t, b] for b in range(half)])
            cells = nxt
        acc = None
        for rel in range(1, d):
            c = uncompact(cells[rel][0], saved[rel])
            acc = c if acc is None else acc + c
        return acc
    # hier: reverse the outer rounds first (scattering relay feedback
    # onto the pool blocks — contributions for the same inner buffer
    # from different outer rounds ADD, matching the single-route
    # delivery), then the inner rounds
    inner_sels, outer_sels, p = saved
    di, do = p.di, p.do
    nb_inner = di - 1
    fb_inner = list(fb_blocks[:nb_inner])
    local_acc = None
    idx = nb_inner
    for ri, r in enumerate(range(1, do)):
        perm = [
            (o * di + i, ((o - r) % do) * di + i)
            for o in range(do) for i in range(di)
        ]
        ret = jax.lax.ppermute(
            jnp.stack(fb_blocks[idx : idx + di]), axis_name, perm
        )
        idx += di
        for q in range(di):
            c = uncompact(ret[q], outer_sels[ri][q])
            if q == 0:
                local_acc = c if local_acc is None else local_acc + c
            else:
                fb_inner[q - 1] = fb_inner[q - 1] + c
    for ri, r in enumerate(range(1, di)):
        perm = [
            (o * di + i, o * di + (i - r) % di)
            for o in range(do) for i in range(di)
        ]
        ret = jax.lax.ppermute(fb_inner[ri], axis_name, perm)
        c = uncompact(ret, inner_sels[ri])
        local_acc = c if local_acc is None else local_acc + c
    return local_acc
