"""Multi-word sharer-bitmask primitives (vectorized, fixed shape).

The reference caps node count at 8 via a 1-byte ``bitVector``
(assignment.c:49, README.md:51).  Here sharer sets are ``[..., W]``
arrays of uint32 words (W = ceil(num_procs/32)), so node count is an
array dimension — the "long-context" scaling axis of this framework
(SURVEY.md §5).  All ops are branch-free and shape-stable for XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

WORD_BITS = 32
_U32 = jnp.uint32


def bit_mask(proc, words: int):
    """One-hot sharer mask for node id(s) ``proc`` (int array [...])
    -> [..., W].  Negative ids produce an all-zero mask."""
    proc = jnp.asarray(proc)
    word_idx = jnp.arange(words, dtype=jnp.int32)
    target = proc[..., None] // WORD_BITS
    shift = (proc[..., None] % WORD_BITS).astype(_U32)
    valid = (proc[..., None] >= 0) & (word_idx == target)
    return jnp.where(valid, _U32(1) << shift, _U32(0))


def test_bit(mask, proc):
    """mask [..., W], proc int [...] -> bool [...]."""
    return jnp.any(mask & bit_mask(proc, mask.shape[-1]) != 0, axis=-1)


def popcount(mask):
    """mask [..., W] -> int32 [...]: number of sharers."""
    return jnp.sum(
        jax.lax.population_count(mask).astype(jnp.int32), axis=-1
    )


def find_owner(mask):
    """Lowest set bit index [..., W] -> int32 [...] (-1 if empty).

    Matches the reference's findOwner (assignment.c:98-105).
    """
    lsb = mask & (~mask + _U32(1))  # isolate lowest set bit per word
    ctz = jax.lax.population_count(lsb - _U32(1)).astype(jnp.int32)
    word_idx = jnp.arange(mask.shape[-1], dtype=jnp.int32)
    big = jnp.int32(1 << 30)
    cand = jnp.where(mask != 0, word_idx * WORD_BITS + ctz, big)
    low = jnp.min(cand, axis=-1)
    return jnp.where(low >= big, jnp.int32(-1), low)


def to_int(mask) -> int:
    """[W] uint32 array -> Python int (host-side readback)."""
    import numpy as np

    arr = np.asarray(mask, dtype=np.uint64)
    out = 0
    for w in range(arr.shape[-1]):
        out |= int(arr[w]) << (WORD_BITS * w)
    return out
