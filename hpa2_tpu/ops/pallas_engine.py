"""VMEM-resident Pallas engine: the TPU-native fast path.

The XLA ``lax.while_loop`` engine (ops/step.py) round-trips the whole
simulator state through HBM every cycle — the measured per-cycle floor
is HBM traffic + fusion overhead.  This engine runs ``K`` lockstep
cycles per ``pallas_call`` with all state resident in VMEM, so HBM is
touched once per K cycles instead of twice per cycle.

Layout: every array carries the ensemble axis **last** so it maps onto
TPU vector lanes (blocks of ``BB`` systems per grid step), and the
per-system structure (nodes, cache/memory/queue slots) lives in
sublanes:

    cachew  [N, C, B]     state | value<<2 | (addr+1)<<10
    dirw    [N, M, B]     mem | dir_state<<8 | sharers<<10
    mb{w}   [N, cap, B]   packed message words, head at slot 0
    tr      [N, T, B]     packed instruction words
    scalars/counters      [SC, B] rows

The round-4 perf redesign, driven by scripts/micro_kernels.py on a
v5e chip (per-op dispatch overhead ~15-30ns dominates; data size is
nearly free at small blocks):

* messages pack into W config-derived words (W=1 for the reference
  geometry) so the deterministic-delivery loop issues one masked
  write per candidate instead of six;
* the directory row (memory byte, dir state, sharer mask) and the
  cache line (state, value byte, tag) each pack into one word, so a
  handler touches one one-hot read + one one-hot write per structure
  instead of three;
* the per-cycle quiescence early-exit (a scalar reduce + branch,
  ~8.5us/cycle measured) runs every ``_GATE`` cycles instead of every
  cycle;
* blocks default to 1024 lanes so each op amortizes its fixed cost
  over 8x more systems, with a sliding ``trace_window`` keeping the
  trace plane — the VMEM whale — small for long workloads;
* (round 5) put sites pre-encode their wire words: a candidate slot
  is its packed words plus a receiver row (-1 = empty), so phase A
  maintains no per-field slot rows, there is no end-of-phase encode,
  and deferred sends merge back without a decode/re-encode round
  trip — roughly halving the phase-A/C bookkeeping op count.

Message fields are type(4) | sender | second+1 | addr | aux, packed to
31 bits per word.  ``aux`` is a union the protocol never uses twice
at once: byte value | excl<<8 for REPLY_RD, the sharer mask for
REPLY_ID, the rd/wr flag for NACK, the byte value for FLUSH*/EVICT*/
WRITE_REQUEST.  Values are bytes by construction (trace parse is
``%hhu`` mod 256, assignment.c:804-818, and memory is byte-typed,
assignment.c:48).  Instructions pack as op(1) | value(8) | addr into
one word.

Semantics are *identical* to ops/step.py (fixture semantics + optional
NACK robustness, SURVEY.md §6.2/§6.3): the cycle body below is a
re-lowering of the same spec — phase A handle-one-message, phase B
issue, phase C deterministic delivery in (phase, sender, slot) order,
phase D dump-at-local-completion snapshots.  Differential tests gate
it against the spec engine and the XLA engine; scripts/
tpu_differential.py gates the Mosaic path on hardware.  A
``trace_window`` run inserts quiescence barriers between windows —
a legal schedule of the same program, differential-tested against the
spec engine run on the same segment schedule.

Event-driven cycle elision (``Config.elide``, ISSUE-12) is an XLA-path
knob: the Pallas family accepts the config but keeps running pure
lockstep, so its ``elided_cycles`` / ``multi_hit_retired`` counters
stay zero (hence absent from the stats schema — only-when-nonzero).
That is deliberate, not a gap: the in-kernel quiescence gate already
skips fully-drained blocks at ``_GATE`` granularity for ~free, which
on this engine's throughput-ensemble workloads captures most of what
per-cycle elision buys, and a data-dependent jump width would break
the streamed path's window-prefetch contract (the double-buffered
trace DMA schedule is precomputed from the lockstep cycle count; a
mid-window fast-forward would have to re-aim in-flight copies).
Event-driven Pallas blocks stay an open item (ROADMAP).

Mosaic constraints honored throughout: no bool tensor is ever stored,
selected against a scalar bool constant, or reduced (`arith.trunci
i8->i1`, the BENCH_r03 compile failure) — masks live as i32 0/1 and
comparisons happen at use sites; reductions are integer sums.

Node-count scaling (round 5): below 22 nodes the sharer mask shares
the packed directory word (the fast path); beyond, the engine
switches to SPLIT-PLANE mode — sharers live in ``SW = ceil(n/31)``
dedicated ``dirs{w}`` planes and ride dedicated ``shr{w}`` message
fields — same cycle semantics at any node count (the widened
bitVector scaling axis, SURVEY.md §5; the reference caps at 8 via its
1-byte bitVector, assignment.c:49).  Remaining restrictions:
addresses < 2^21, no replay mode (fixture replays run on the
XLA/spec engines).  The unrolled delivery loop is O(nodes) python at
trace time, so very wide systems pay a long compile.
"""

from __future__ import annotations

import functools
import warnings
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import MsgType
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.ops import exchange
from hpa2_tpu.protocols.compiler import planes_for
from hpa2_tpu.utils.dump import NodeDump

I32 = jnp.int32
U32 = jnp.uint32

# The Mosaic kernel is specialized to the MESI/full-bitvector build
# (PallasEngine gates on that below); its state constants come from
# the compiled MESI table so the lowered planes stay the single
# source of truth.  The state indices are semantics-invariant, so any
# Semantics() works as the cache key here.
_MESI_PLANES = planes_for("mesi", Semantics())
_M = _MESI_PLANES.M
_E = _MESI_PLANES.E
_S = _MESI_PLANES.S
_I = _MESI_PLANES.I
_EM = _MESI_PLANES.EM
_DS = _MESI_PLANES.DS
_DU = _MESI_PLANES.DU

_NO_MSG = -1
_INVALID_ADDR = -1

# candidate-grid slots, in delivery order: phase A point sends, the
# INV fanout, then phase B point sends
_NSLOTS = 5

# scalar counter rows (scalars[row, :])
(_SC_CYCLE, _SC_INSTR, _SC_MSGS, _SC_OVERFLOW, _SC_RH, _SC_RM,
 _SC_WH, _SC_WM, _SC_EV, _SC_INV) = range(10)
_NSCALAR = 10

_NTYPES = len(MsgType)

# trace word: op(1) | value(8) | addr(rest)
_TR_ADDR_SHIFT = 9

# packed directory word: mem(8) | dir_state(2) | sharers(<=21)
_DW_STATE_SHIFT = 8
_DW_SH_SHIFT = 10
# packed cache word: state(2) | value(8) | addr+1(<=21)
_CW_VAL_SHIFT = 2
_CW_ADDR_SHIFT = 10

# quiescence early-exit granularity (cycles); the gate is a scalar
# reduce + branch measured at ~8.5us — amortize it
_GATE = 8


def _bits_for(n_values: int) -> int:
    """Bits to store 0 .. n_values-1."""
    b = 1
    while (1 << b) < n_values:
        b += 1
    return b


# bits per sharer word in split-plane mode (sign-safe i32 shifts)
_SPLIT_BPW = 31


def choose_block(lanes: int, requested: int) -> int:
    """Largest divisor of ``lanes`` not exceeding ``requested`` — the
    grid tiles the lane (ensemble) axis exactly, so the block must
    divide it.  Warns when the best divisor is under half the (capped)
    request: a near-prime lane count silently degrading to tiny blocks
    (b=509 -> block=1 under the old decrement loop) costs up to the
    full lane-parallelism factor; pad the ensemble to a composite size
    instead."""
    cap = max(1, min(requested, lanes))
    best = 1
    for d in range(1, int(lanes ** 0.5) + 1):
        if lanes % d:
            continue
        for c in (d, lanes // d):
            if best < c <= cap:
                best = c
    if best * 2 < cap:
        warnings.warn(
            f"ensemble of {lanes} lanes has no block divisor near the "
            f"requested {requested}: using block={best} (< half the "
            "request), which costs lane parallelism; pad the batch to "
            "a composite size (e.g. a multiple of 256)",
            RuntimeWarning,
            stacklevel=3,
        )
    return best


def _split_mode(config: SystemConfig) -> bool:
    """num_procs <= 21: the sharer mask shares the packed directory
    word (the fast path).  Beyond, sharers live in SW dedicated
    ``dirs{w}`` planes of 31 bits each and messages carry them in
    dedicated ``shr{w}`` fields — same cycle semantics, wider state
    (the widened-bitVector scaling axis, SURVEY.md §5)."""
    return config.num_procs > 21


def _sharer_words(config: SystemConfig) -> int:
    if not _split_mode(config):
        return 1
    return -(-config.num_procs // _SPLIT_BPW)


@functools.lru_cache(maxsize=64)
def _mb_layout(config: SystemConfig):
    """Field -> (word, offset, width) packing for one message, plus the
    word count W.  Words hold at most 31 bits (sign-safe shifts).

    In split-plane mode (num_procs > 21) the ``aux`` union narrows to
    its 9-bit value|excl role and sharer masks ride dedicated
    ``shr{w}`` fields (one 31-bit field per sharer word, each on its
    own message word).

    A trailing "recv" field (stored recv+1; only meaningful in
    DEFERRED outbox words) is added when it fits the last word for
    free — it then replaces the separate ob_recv plane in VMEM.  The
    reference geometry packs type4+sender3+second4+addr7+aux9+recv4 =
    31 bits exactly.  Mailbox decodes never read those bits (a wire
    word delivered from a deferred outbox entry carries them)."""
    n = config.num_procs
    split = _split_mode(config)
    fields = [
        ("type", 4),
        ("sender", _bits_for(n)),
        ("second", _bits_for(n + 1)),   # stored as second+1
        ("addr", _bits_for(config.num_addresses)),
        ("aux", 9 if split else max(n, 9)),  # byte value | excl<<8
    ]
    if split:
        fields += [
            (f"shr{w}", _SPLIT_BPW) for w in range(_sharer_words(config))
        ]
    layout = {}
    word, off = 0, 0
    for name, wd in fields:
        if off + wd > 31:
            word, off = word + 1, 0
        layout[name] = (word, off, wd)
        off += wd
    recv_wd = _bits_for(n + 1)          # stored as recv+1
    if off + recv_wd <= 31:
        layout["recv"] = (word, off, recv_wd)
    return layout, word + 1


def _check_geometry(config: SystemConfig) -> None:
    if config.num_addresses >= (1 << 21):
        raise ValueError("pallas engine supports addresses < 2^21")
    if config.protocol != "mesi" or config.directory_format != "full":
        raise ValueError(
            "the Pallas kernel is specialized to the MESI/full-bitvector "
            "build; use the spec or XLA engines for "
            f"protocol={config.protocol!r} "
            f"directory_format={config.directory_format!r}"
        )


def _scalar_layout(config: SystemConfig, t_dim: int):
    """Offsets for the packed per-node scalar row ``nsw``:
    mb_count | waiting | pending_write | pc in one i32 [N, B] plane
    (three VMEM rows per node saved vs separate planes).  Raises when
    the fields cannot share 31 bits — pass a trace_window."""
    count_bits = _bits_for(config.msg_buffer_size + 1)
    pc_bits = _bits_for(t_dim + 1)
    off_wait = count_bits
    off_pw = count_bits + 1
    off_pc = count_bits + 9
    total = off_pc + pc_bits
    if total > 31:
        raise ValueError(
            f"packed scalar row needs {total} bits (msg_buffer_size="
            f"{config.msg_buffer_size}, trace window {t_dim}); use a "
            "smaller trace_window"
        )
    return {
        "count_mask": (1 << count_bits) - 1,
        "off_wait": off_wait,
        "off_pw": off_pw,
        "pw_mask": 0xFF,
        "off_pc": off_pc,
        "pc_mask": (1 << pc_bits) - 1,
    }


# ---------------------------------------------------------------------------
# Packed state planes (the VMEM-rent halving): in ``packed=True`` mode
# the two word planes that dominate carried rows split into narrow
# unsigned planes —
#
#     cachew [N,C] i32  ->  cvalw  [N,C] u8   (the value byte)
#                           cmetaw [N,C] u8/u16 (state | (addr+1)<<2)
#     dirw   [N,M] i32  ->  dmemw  [N,M] u8   (the memory byte)
#                           dmetaw [N,M] u8/u16 (dir_state | sharers<<2)
#
# and their snapshot twins likewise.  The cycle body is UNCHANGED: at
# cycle entry the narrow planes are promoted and recombined into the
# exact legacy words through the sanctioned ``_widen`` helper, and at
# cycle exit the words are re-split through ``_narrow`` — so packed
# runs are bit-exact by construction, and the narrow dtypes are what
# the loop carries (where the VMEM rent is paid).  The AST lint
# (analysis/lint.py, dtype-widening rule) flags any op that touches a
# packed plane without going through ``_widen`` first.
# ---------------------------------------------------------------------------

_PACKED_CACHE = ("cvalw", "cmetaw")
_PACKED_DIR = ("dmemw", "dmetaw")


def _meta_dtype(bits: int):
    """Narrowest unsigned dtype holding ``bits`` bits, or None when
    only int32 would fit (no byte win -> packing unsupported)."""
    if bits <= 8:
        return np.dtype(np.uint8)
    if bits <= 16:
        return np.dtype(np.uint16)
    return None


def packed_plane_dtypes(config: SystemConfig):
    """Dtypes of the four packed planes, or raise when the meta fields
    cannot narrow below int32 (packing would then only add planes)."""
    cmeta_bits = 2 + _bits_for(config.num_addresses + 1)
    # below 22 nodes the sharer mask shares the directory word; in
    # split-plane mode the dirs{w} planes carry it and dmetaw holds
    # only the 2-bit directory state
    dmeta_bits = 2 + (0 if _split_mode(config) else config.num_procs)
    cd, dd = _meta_dtype(cmeta_bits), _meta_dtype(dmeta_bits)
    if cd is None or dd is None:
        raise ValueError(
            f"packed planes need cache meta <= 16 bits (got "
            f"{cmeta_bits}: num_addresses={config.num_addresses}) and "
            f"dir meta <= 16 bits (got {dmeta_bits}: num_procs="
            f"{config.num_procs}); run this geometry with packed=False"
        )
    return {
        "cvalw": np.dtype(np.uint8), "cmetaw": cd,
        "dmemw": np.dtype(np.uint8), "dmetaw": dd,
    }


def _widen(x) -> jnp.ndarray:
    """THE sanctioned promotion of a packed (u8/u16) plane to the i32
    the cycle body computes in.  Packed planes hold nonnegative bit
    patterns, so the zero-extend is exact."""
    return x.astype(I32)


def _narrow(x, dtype) -> jnp.ndarray:
    """THE sanctioned demotion back to a packed plane's storage dtype
    (the value is a bit pattern that fits by construction)."""
    return x.astype(dtype)


def _widen_cache(cvalw, cmetaw) -> jnp.ndarray:
    """Packed cache planes -> the legacy cachew word."""
    cv, cm = _widen(cvalw), _widen(cmetaw)
    return (cm & 3) | (cv << _CW_VAL_SHIFT) | (
        (cm >> 2) << _CW_ADDR_SHIFT
    )


def _narrow_cache(cachew, meta_dtype):
    """The legacy cachew word -> (cvalw, cmetaw).  The word has no
    bits above the addr field, so ``>> _CW_ADDR_SHIFT`` is exact."""
    cvalw = _narrow((cachew >> _CW_VAL_SHIFT) & 0xFF, jnp.uint8)
    cmetaw = _narrow(
        (cachew & 3) | ((cachew >> _CW_ADDR_SHIFT) << 2), meta_dtype
    )
    return cvalw, cmetaw


def _widen_dir(dmemw, dmetaw) -> jnp.ndarray:
    """Packed directory planes -> the legacy dirw word."""
    dm, dmt = _widen(dmemw), _widen(dmetaw)
    return dm | ((dmt & 3) << _DW_STATE_SHIFT) | (
        (dmt >> 2) << _DW_SH_SHIFT
    )


def _narrow_dir(dirw, meta_dtype):
    dmemw = _narrow(dirw & 0xFF, jnp.uint8)
    dmetaw = _narrow(
        ((dirw >> _DW_STATE_SHIFT) & 3)
        | ((dirw >> _DW_SH_SHIFT) << 2),
        meta_dtype,
    )
    return dmemw, dmetaw


#: per-engine carried state names, in kernel argument order
def _state_fields(W: int, snapshots: bool, recv_packed: bool,
                  split_sw: int = 0, packed: bool = False):
    """``split_sw`` > 0 adds the split-plane sharer words (dirs{w},
    plus their snapshot twins); ``packed`` swaps the cachew/dirw word
    planes (and snapshot twins) for their narrow split planes."""
    f = (
        list(_PACKED_CACHE + _PACKED_DIR) if packed
        else ["cachew", "dirw"]
    )
    f += [f"dirs{w}" for w in range(split_sw)]
    f += [f"mb{w}" for w in range(W)]
    f += ["nsw"]  # packed mb_count | waiting | pending_write | pc
    f += [f"ob{w}" for w in range(W)]
    f += [] if recv_packed else ["ob_recv"]
    if snapshots:
        f += ["snap_taken"]
        f += (
            [f"snap_{p}" for p in _PACKED_CACHE + _PACKED_DIR]
            if packed else ["snap_cachew", "snap_dirw"]
        )
        f += [f"snap_dirs{w}" for w in range(split_sw)]
    f += ["scalars", "msg_counts"]
    return tuple(f)


def deferred_valid(config: SystemConfig, s) -> jnp.ndarray:
    """[N, 5, ...] i32 validity of the deferred outbox slots, derived
    from the packed outbox words — there is no ob_valid plane.  Point
    slots (0, 1, 3, 4) are valid iff their receiver is present (the
    recv+1 field bits, or the ob_recv plane's non-negative sentinel);
    the INV slot (2) iff its remainder mask bits are nonzero.  ob_new
    zeroes non-deferred slots, so the derivation is exact."""
    layout, W = _mb_layout(config)
    obw = [s[f"ob{w}"] for w in range(W)]

    def field(name):
        w, off, wd = layout[name]
        x = obw[w]
        if off:
            x = x >> off
        if wd < 32:
            x = x & ((1 << wd) - 1)
        return x

    point = field("recv") if "recv" in layout else s["ob_recv"] + 1
    if _split_mode(config):
        inv = field("shr0")
        for w_ in range(1, _sharer_words(config)):
            inv = inv | field(f"shr{w_}")
    else:
        inv = field("aux")
    iota5 = jax.lax.broadcasted_iota(I32, point.shape, 1)
    sel = jnp.where(iota5 == 2, inv, point)
    return jnp.where(sel != 0, 1, 0)


TRACE_FIELDS = ("tr", "tr_len")


def state_shapes(config: SystemConfig, snapshots: bool,
                 packed: bool = False):
    """Per-field carried-state shapes WITHOUT the trailing lane axis.
    Single source of truth for the kernel builders and the static
    VMEM budget model (hpa2_tpu/analysis/vmem.py)."""
    n, c, m = config.num_procs, config.cache_size, config.mem_size
    cap, nt = config.msg_buffer_size, _NTYPES
    layout, W = _mb_layout(config)
    split_sw = _sharer_words(config) if _split_mode(config) else 0
    if packed:
        shapes = {
            "cvalw": (n, c), "cmetaw": (n, c),
            "dmemw": (n, m), "dmetaw": (n, m),
        }
    else:
        shapes = {"cachew": (n, c), "dirw": (n, m)}
    shapes.update({
        "nsw": (n,),
        "scalars": (_NSCALAR,), "msg_counts": (nt,),
    })
    if "recv" not in layout:
        shapes["ob_recv"] = (n, _NSLOTS)
    if snapshots:
        shapes["snap_taken"] = (n,)
        if packed:
            shapes.update({
                "snap_cvalw": (n, c), "snap_cmetaw": (n, c),
                "snap_dmemw": (n, m), "snap_dmetaw": (n, m),
            })
        else:
            shapes.update({
                "snap_cachew": (n, c), "snap_dirw": (n, m),
            })
    for w in range(split_sw):
        shapes[f"dirs{w}"] = (n, m)
        if snapshots:
            shapes[f"snap_dirs{w}"] = (n, m)
    for w in range(W):
        shapes[f"mb{w}"] = (n, cap)
        shapes[f"ob{w}"] = (n, _NSLOTS)
    return shapes


def state_dtypes(config: SystemConfig, snapshots: bool,
                 packed: bool = False):
    """Per-field carried-state numpy dtypes — int32 everywhere except
    the packed planes (and their snapshot twins)."""
    dtypes = {
        f: np.dtype(np.int32)
        for f in state_shapes(config, snapshots, packed)
    }
    if packed:
        for f, dt in packed_plane_dtypes(config).items():
            dtypes[f] = dt
            if snapshots:
                dtypes[f"snap_{f}"] = dt
    return dtypes


def _popcount(x):
    """popcount on int32 bit patterns (SWAR; Mosaic-safe)."""
    u = x.astype(U32)
    u = u - ((u >> 1) & U32(0x55555555))
    u = (u & U32(0x33333333)) + ((u >> 2) & U32(0x33333333))
    u = (u + (u >> 4)) & U32(0x0F0F0F0F)
    return ((u * U32(0x01010101)) >> 24).astype(I32)


def _find_owner(x):
    """Lowest set bit index of an int32 mask; -1 when empty
    (reference findOwner, assignment.c:98-105)."""
    u = x.astype(U32)
    lsb = u & (U32(0) - u)
    pos = _popcount((lsb - U32(1)).astype(I32))
    return jnp.where(u == 0, I32(-1), pos)


def _bit(proc):
    """One-hot int32 mask for node id(s); negative -> 0."""
    p = jnp.clip(proc, 0, 31)
    return jnp.where(proc >= 0, I32(1) << p, I32(0))


def _test_bit(mask, proc):
    return (mask >> jnp.clip(proc, 0, 31)) & 1 == 1


def build_cycle(config: SystemConfig, bb: int, snapshots: bool = True,
                ablate: frozenset = frozenset(), packed: bool = False,
                axis_name: Optional[str] = None, shards: int = 1,
                exchange_slots: Optional[int] = None):
    """One lockstep cycle over a block of ``bb`` systems in transposed
    layout.  Pure jnp on a state dict — runs inside the Pallas kernel
    and, for validation, directly under jit/CPU.

    ``packed``: the state dict carries the narrow packed planes
    (cvalw/cmetaw/dmemw/dmetaw) instead of the cachew/dirw words; the
    cycle body itself is unchanged — packed planes are ``_widen``-ed
    into the legacy words at entry and re-``_narrow``-ed at exit, so a
    packed cycle is bit-exact with the unpacked one by construction.

    ``axis_name``/``shards``: node-sharded SPMD mode.  The body sees
    the local block of ``num_procs // shards`` node rows and phase C
    runs the targeted cross-shard exchange (``ops/exchange.py``) on
    the ``config.exchange_mode`` collective schedule (see
    ``exchange.plan_collectives``) plus ONE stacked psum and ONE
    stacked pmax per cycle.  This mode is plain XLA under
    ``shard_map`` (collectives cannot run inside a Mosaic kernel) and
    carries transient [1, bb] rows in the state dict: ``activeg``
    (psum'd global activity, the quiescence signal), ``xmsgs``
    (cumulative cross-shard messages), ``exchov`` (sticky
    exchange-overflow flag), ``exchhw``/``exchmc``/``exchcb``
    (exchange slot high-water mark, multicast and combining savings)
    and ``exchdg``/``exchdc`` (packed worst-overflow diagnostics:
    demand/shard-pair and demand/cycle words).  ``exchange_slots``
    caps the per-peer buffer (default: the capacity-exact
    ``5 * n_local``, which cannot overflow); a tighter cap trades ICI
    bytes for a loud overflow status.

    ``ablate`` (perf tooling only, scripts/perf_sweep.py --ablate):
    named cycle stages are stubbed out to attribute per-cycle time on
    real hardware.  An ablated cycle is semantically WRONG — never use
    outside timing runs."""
    n, c, m = config.num_procs, config.cache_size, config.mem_size
    cap = config.msg_buffer_size
    sem = config.semantics
    _check_geometry(config)
    if sem.overloaded_evict_shared_notify:
        raise ValueError("pallas engine implements fixture semantics only")
    if config.messages_per_cycle != 1:
        raise ValueError(
            "the pallas engine drains one message per node per cycle; "
            "messages_per_cycle > 1 runs on the spec engine"
        )
    nack = sem.intervention_miss_policy == "nack"
    sharded = axis_name is not None and shards > 1
    if sharded:
        if n % shards != 0:
            raise ValueError(
                f"num_procs={n} not divisible by node shards={shards}"
            )
        if ablate:
            raise ValueError("--ablate is single-node-shard only")
    nl = n // shards if sharded else n
    k_slots = 5 * nl if exchange_slots is None else int(exchange_slots)
    if sharded and not (1 <= k_slots <= 5 * nl):
        raise ValueError(
            f"exchange_slots={exchange_slots} out of range [1, {5 * nl}]"
        )
    xplan = (
        exchange.make_plan(
            shards, config.exchange_mode, config.exchange_inner
        )
        if sharded
        else None
    )
    layout, W = _mb_layout(config)
    recv_packed = "recv" in layout
    split = _split_mode(config)
    SW = _sharer_words(config)
    sh_mask = (1 << min(n, _SPLIT_BPW)) - 1
    addr_mask = (1 << 21) - 1

    def dec(words, name):
        w, off, wd = layout[name]
        x = words[w]
        if off:
            x = x >> off
        if wd < 32:
            x = x & ((1 << wd) - 1)
        return x

    def cycle(s: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        s = dict(s)
        # iotas are built inside the traced body (a pallas kernel may
        # not capture array constants from the closure)
        iota_n = jax.lax.broadcasted_iota(I32, (nl, bb), 0)
        iota_c = jax.lax.broadcasted_iota(I32, (nl, c, bb), 1)
        iota_m = jax.lax.broadcasted_iota(I32, (nl, m, bb), 1)
        iota_cap = jax.lax.broadcasted_iota(I32, (nl, cap, bb), 1)
        # global node ids of the local rows (aliases iota_n when
        # unsharded: zero extra ops, the jaxpr op-count guard holds)
        if sharded:
            gids = (
                iota_n
                + jax.lax.axis_index(axis_name).astype(I32) * nl
            )
        else:
            gids = iota_n

        def read_c(arr, idx):  # [N,C,B] by [N,B] -> [N,B]
            return jnp.sum(
                jnp.where(iota_c == idx[:, None, :], arr, 0), axis=1
            )

        def read_m(arr, idx):
            return jnp.sum(
                jnp.where(iota_m == idx[:, None, :], arr, 0), axis=1
            )

        # masked one-hot writes fold the mask into the index compare
        # (idx is always >= 0): one 3D compare, and no 2D->3D broadcast
        # of a bool vector — those round-trip through i8 inside Mosaic
        # and newer libtpu rejects the i8->i1 trunci (BENCH_r04 driver
        # AOT failure)
        def write_c(arr, idx, mask, val):
            hot = iota_c == jnp.where(mask, idx, -1)[:, None, :]
            return jnp.where(hot, val[:, None, :], arr)

        def write_m(arr, idx, mask, val):
            hot = iota_m == jnp.where(mask, idx, -1)[:, None, :]
            return jnp.where(hot, val[:, None, :], arr)
        # nodes with deferred sends are blocked (no handle, no issue);
        # validity is derived from the outbox words themselves
        dv = deferred_valid(config, s)                      # [N, 5, B]
        blocked = jnp.sum(dv, axis=1) > 0                   # [N, B]

        # per-node scalars ride ONE packed row (three VMEM planes
        # saved); decode once here, re-encode once at the end
        slsc = _scalar_layout(config, s["tr"].shape[1])
        nsw_in = s["nsw"]
        mb_count_in = nsw_in & slsc["count_mask"]
        waiting_in = (nsw_in >> slsc["off_wait"]) & 1
        pw_in = (nsw_in >> slsc["off_pw"]) & slsc["pw_mask"]
        pc_in = (nsw_in >> slsc["off_pc"]) & slsc["pc_mask"]

        # ===== phase A: handle one message per node ==================
        has_msg = (mb_count_in > 0) & ~blocked
        heads = [s[f"mb{w}"][:, 0, :] for w in range(W)]    # [N, B]
        mt = jnp.where(has_msg, dec(heads, "type"), _NO_MSG)
        if "phase_a" in ablate:  # handlers fold to no-ops
            mt = jnp.full((nl, bb), _NO_MSG, I32)
        snd = dec(heads, "sender")
        sr = dec(heads, "second") - 1
        a = dec(heads, "addr")
        aux = dec(heads, "aux")
        v = aux & 0xFF

        has_msg_i = has_msg.astype(I32)
        qdata = []
        for w in range(W):
            rolled = jnp.concatenate(
                [s[f"mb{w}"][:, 1:, :], s[f"mb{w}"][:, :1, :]], axis=1
            )
            qdata.append(jnp.where(has_msg_i[:, None, :] != 0, rolled,
                                   s[f"mb{w}"]))
        count2 = mb_count_in - has_msg_i

        home = a // m
        blk = a % m
        ci = a % c
        is_home = gids == home
        is_second = gids == sr

        cw = read_c(s["cachew"], ci)
        line_state = cw & 3
        line_val = (cw >> _CW_VAL_SHIFT) & 0xFF
        line_addr = ((cw >> _CW_ADDR_SHIFT) & addr_mask) - 1
        dw = read_m(s["dirw"], blk)
        mem_blk = dw & 0xFF
        ds = (dw >> _DW_STATE_SHIFT) & 3
        pw = pw_in

        zero = jnp.zeros((nl, bb), dtype=I32)
        false = jnp.zeros((nl, bb), dtype=bool)
        neg1_nb = jnp.full((nl, bb), -1, I32)

        # --- sharer sets as SW-word vectors (SW == 1 packed in the
        # directory word below 22 nodes; split dirs{w} planes beyond).
        # All helpers reduce to the single-word ops when SW == 1.
        if split:
            dshw = [read_m(s[f"dirs{w}"], blk) for w in range(SW)]
        else:
            dshw = [(dw >> _DW_SH_SHIFT) & sh_mask]

        def sv_bit(proc):
            """One-hot sharer vector for node id(s); negative -> 0."""
            if SW == 1:
                return [_bit(proc)]
            return [
                _bit(
                    jnp.where(
                        (proc >= w * _SPLIT_BPW)
                        & (proc < (w + 1) * _SPLIT_BPW),
                        proc - w * _SPLIT_BPW,
                        -1,
                    )
                )
                for w in range(SW)
            ]

        def sv_test(sv, proc):
            if SW == 1:
                return _test_bit(sv[0], proc)
            hit = zero
            for w in range(SW):
                b = proc - w * _SPLIT_BPW
                vw = (sv[w] >> jnp.clip(b, 0, _SPLIT_BPW - 1)) & 1
                hit = hit | jnp.where(
                    (b >= 0) & (b < _SPLIT_BPW), vw, 0
                )
            return hit == 1

        def sv_count(sv):
            cnt = _popcount(sv[0])
            for w in range(1, SW):
                cnt = cnt + _popcount(sv[w])
            return cnt

        def sv_owner(sv):
            """Lowest set bit across words (reference findOwner)."""
            own = _find_owner(sv[SW - 1])
            if SW > 1:
                own = jnp.where(
                    own >= 0, own + (SW - 1) * _SPLIT_BPW, own
                )
            for w in range(SW - 2, -1, -1):
                cand = _find_owner(sv[w])
                own = jnp.where(
                    sv[w] != 0, cand + w * _SPLIT_BPW, own
                )
            return own

        line_match = line_addr == a
        line_me = (line_state == _M) | (line_state == _E)
        owner = sv_owner(dshw)
        owner_is_snd = owner == snd
        snd_bitw = sv_bit(snd)

        # --- pre-encoded put-words (PERF.md round-4 lever 2) ---------
        # A candidate slot is its WIRE WORDS plus a receiver row
        # (-1 = empty).  Each put site ORs compile-time-constant
        # fields (the message type, usually the second-receiver
        # sentinel) into the runtime ones directly, so there are no
        # per-field slot rows to maintain, no end-of-phase re-encode,
        # and deferred outbox entries merge back as already-packed
        # words.  This halves the phase-A op count vs the field-row
        # formulation (the kernel cost is op dispatch, not data width
        # — scripts/micro_kernels.py).  The sender field (always the
        # row index) is OR'd in once per slot at delivery prep.
        def slot():
            d = {"recv": neg1_nb}
            for w in range(W):
                d[f"w{w}"] = zero
            return d

        def pack(type_, addr, aux=None, second=None, shr=None):
            """Wire words [W x [N,B]] with the sender field left zero.
            ``type_``/``aux`` may be python ints (constant-folded);
            ``second`` is the node id (stored +1; None = none); ``shr``
            is an SW-word sharer vector (split mode: rides the shr{w}
            fields; packed mode: the single word IS the aux union)."""
            vals = {"type": type_, "addr": addr}
            if shr is not None:
                if split:
                    for w_ in range(SW):
                        vals[f"shr{w_}"] = shr[w_]
                else:
                    aux = shr[0]
            if aux is not None:
                vals["aux"] = aux
            if second is not None:
                vals["second"] = second + 1
            out = []
            for w in range(W):
                acc = None
                const = 0
                for name, x in vals.items():
                    ww, off, _ = layout[name]
                    if ww != w:
                        continue
                    if isinstance(x, int):
                        const |= x << off
                        continue
                    if off:
                        x = x << off
                    acc = x if acc is None else acc | x
                if const:
                    acc = const if acc is None else acc | const
                out.append(zero if acc is None else acc)
            return out

        def put(sl, mask, recv, words):
            sl["recv"] = jnp.where(mask, recv, sl["recv"])
            for w in range(W):
                sl[f"w{w}"] = jnp.where(mask, words[w], sl[f"w{w}"])

        def evict_msg(sl, mask, l_addr, l_val, l_state):
            """handleCacheReplacement (assignment.c:742-773)."""
            vv = mask & (l_addr != _INVALID_ADDR) & (l_state != _I)
            sane = jnp.maximum(l_addr, 0)
            et = jnp.where(
                l_state == _M,
                int(MsgType.EVICT_MODIFIED),
                int(MsgType.EVICT_SHARED),
            )
            put(sl, vv, sane // m, pack(et, sane, aux=l_val))
            return vv

        sA0, sA1 = slot(), slot()
        inv_shw = [zero] * SW
        inv_addr = zero

        nl_addr, nl_val, nl_state = line_addr, line_val, line_state
        upd_line = false
        nd_state, nd_shw = ds, list(dshw)
        upd_dir = false
        mem_write = false
        mem_val = mem_blk
        # `waiting` stays i32 (0/1) through the whole cycle: Mosaic
        # cannot lower selects/broadcasts that materialize i1 vectors
        # from scalar bool constants (arith.trunci i8->i1, the
        # BENCH_r03 compile failure), so bool state is never stored or
        # selected — only compared at use sites.
        waiting = waiting_in

        def typ(t):
            return mt == int(t)

        # --- READ_REQUEST (assignment.c:188-236) ---------------------
        mk = typ(MsgType.READ_REQUEST) & is_home
        du, dss, dem = ds == _DU, ds == _DS, ds == _EM
        reply_mask = mk & (du | dss | (dem & owner_is_snd))
        excl = du | (dem & owner_is_snd)
        put(sA0, reply_mask, snd,
            pack(int(MsgType.REPLY_RD), a,
                 aux=mem_blk | jnp.where(excl, I32(256), I32(0))))
        fwd = mk & dem & ~owner_is_snd
        put(sA0, fwd, owner,
            pack(int(MsgType.WRITEBACK_INT), a, second=snd))
        upd_dir = upd_dir | (mk & (du | dss | fwd))
        nd_state = jnp.where(mk & du, _EM, nd_state)
        nd_state = jnp.where(fwd, _DS, nd_state)
        nd_shw = [
            jnp.where(mk & du, snd_bitw[w], nd_shw[w]) for w in range(SW)
        ]
        nd_shw = [
            jnp.where(mk & (dss | fwd), nd_shw[w] | snd_bitw[w],
                      nd_shw[w])
            for w in range(SW)
        ]

        # --- REPLY_RD (assignment.c:238-247) -------------------------
        mk = typ(MsgType.REPLY_RD)
        ev_replyrd = evict_msg(
            sA0, mk & ~line_match, line_addr, line_val, line_state
        )
        upd_line = upd_line | mk
        nl_addr = jnp.where(mk, a, nl_addr)
        nl_val = jnp.where(mk, v, nl_val)
        nl_state = jnp.where(
            mk, jnp.where((aux >> 8) & 1 == 1, _E, _S), nl_state
        )
        waiting = jnp.where(mk, 0, waiting)

        # --- WRITEBACK_INT (assignment.c:249-271) --------------------
        mk = typ(MsgType.WRITEBACK_INT)
        ok = mk & line_match & line_me
        flush_w = pack(int(MsgType.FLUSH), a, aux=line_val, second=sr)
        put(sA0, ok, home, flush_w)
        put(sA1, ok & (sr != home), sr, flush_w)
        upd_line = upd_line | ok
        nl_state = jnp.where(ok, _S, nl_state)
        if nack:
            put(sA0, mk & ~(line_match & line_me), home,
                pack(int(MsgType.NACK), a, second=sr))

        # --- FLUSH (assignment.c:273-296) ----------------------------
        mk = typ(MsgType.FLUSH)
        mem_write = mem_write | (mk & is_home)
        mem_val = jnp.where(mk & is_home, v, mem_val)
        rq = mk & is_second
        ev_flush = evict_msg(
            sA0, rq & ~line_match, line_addr, line_val, line_state
        )
        upd_line = upd_line | rq
        nl_addr = jnp.where(rq, a, nl_addr)
        nl_val = jnp.where(rq, v, nl_val)
        nl_state = jnp.where(rq, _S, nl_state)
        waiting = jnp.where(rq, 0, waiting)

        # --- UPGRADE (assignment.c:298-328) --------------------------
        mk = typ(MsgType.UPGRADE) & is_home
        reply_shw = [
            jnp.where(mk & (ds == _DS), dshw[w] & ~snd_bitw[w], 0)
            for w in range(SW)
        ]
        put(sA0, mk, snd, pack(int(MsgType.REPLY_ID), a, shr=reply_shw))
        upd_dir = upd_dir | mk
        nd_state = jnp.where(mk, _EM, nd_state)
        nd_shw = [
            jnp.where(mk, snd_bitw[w], nd_shw[w]) for w in range(SW)
        ]

        # --- REPLY_ID (assignment.c:330-364) -------------------------
        mk = typ(MsgType.REPLY_ID)
        fill = mk & line_match & (line_state != _M)
        upd_line = upd_line | fill
        nl_val = jnp.where(fill, pw, nl_val)
        nl_state = jnp.where(fill, _M, nl_state)
        fan = mk & line_match
        if split:
            msg_shw = [dec(heads, f"shr{w}") for w in range(SW)]
        else:
            msg_shw = [aux]
        self_bitw = sv_bit(gids)
        inv_shw = [
            jnp.where(fan, msg_shw[w] & ~self_bitw[w], inv_shw[w])
            for w in range(SW)
        ]
        inv_addr = jnp.where(fan, a, inv_addr)
        waiting = jnp.where(mk, 0, waiting)

        # --- INV (assignment.c:366-373) ------------------------------
        mk = typ(MsgType.INV)
        inv_applied = mk & line_match & (
            (line_state == _S) | (line_state == _E)
        )
        upd_line = upd_line | inv_applied
        nl_state = jnp.where(inv_applied, _I, nl_state)

        # --- WRITE_REQUEST (assignment.c:375-435) --------------------
        mk = typ(MsgType.WRITE_REQUEST) & is_home
        if sem.eager_write_request_memory:
            mem_write = mem_write | mk
            mem_val = jnp.where(mk, v, mem_val)
        du, dss, dem = ds == _DU, ds == _DS, ds == _EM
        put(sA0, mk & (du | (dem & owner_is_snd)), snd,
            pack(int(MsgType.REPLY_WR), a))
        put(sA0, mk & dss, snd,
            pack(int(MsgType.REPLY_ID), a,
                 shr=[dshw[w] & ~snd_bitw[w] for w in range(SW)]))
        wr_fwd = mk & dem & ~owner_is_snd
        put(sA0, wr_fwd, owner,
            pack(int(MsgType.WRITEBACK_INV), a, second=snd))
        upd_dir = upd_dir | (mk & (du | dss | wr_fwd))
        nd_state = jnp.where(mk & (du | dss), _EM, nd_state)
        nd_shw = [
            jnp.where(mk & (du | dss | wr_fwd), snd_bitw[w], nd_shw[w])
            for w in range(SW)
        ]

        # --- REPLY_WR (assignment.c:437-449) -------------------------
        mk = typ(MsgType.REPLY_WR)
        upd_line = upd_line | mk
        nl_addr = jnp.where(mk, a, nl_addr)
        nl_val = jnp.where(mk, pw, nl_val)
        nl_state = jnp.where(mk, _M, nl_state)
        waiting = jnp.where(mk, 0, waiting)

        # --- WRITEBACK_INV (assignment.c:451-473) --------------------
        mk = typ(MsgType.WRITEBACK_INV)
        ok = mk & line_match & line_me
        invack_w = pack(int(MsgType.FLUSH_INVACK), a, aux=line_val,
                        second=sr)
        put(sA0, ok, home, invack_w)
        put(sA1, ok & (sr != home), sr, invack_w)
        upd_line = upd_line | ok
        nl_state = jnp.where(ok, _I, nl_state)
        if nack:
            put(sA0, mk & ~(line_match & line_me), home,
                pack(int(MsgType.NACK), a, aux=1, second=sr))

        # --- FLUSH_INVACK (assignment.c:475-496) ---------------------
        mk = typ(MsgType.FLUSH_INVACK)
        hm = mk & is_home
        mem_write = mem_write | hm
        mem_val = jnp.where(hm, v, mem_val)
        upd_dir = upd_dir | hm
        nd_state = jnp.where(hm, _EM, nd_state)
        sr_bitw = sv_bit(sr)
        nd_shw = [
            jnp.where(hm, sr_bitw[w], nd_shw[w]) for w in range(SW)
        ]
        rq = mk & is_second
        upd_line = upd_line | rq
        nl_addr = jnp.where(rq, a, nl_addr)
        nl_val = jnp.where(
            rq, v if sem.flush_invack_fills_old_value else pw, nl_val
        )
        nl_state = jnp.where(rq, _M, nl_state)
        waiting = jnp.where(rq, 0, waiting)

        # --- EVICT_SHARED home role (assignment.c:498-521) -----------
        mk = typ(MsgType.EVICT_SHARED) & is_home & sv_test(dshw, snd)
        after = [dshw[w] & ~snd_bitw[w] for w in range(SW)]
        cnt = sv_count(after)
        upd_dir = upd_dir | mk
        nd_shw = [
            jnp.where(mk, after[w], nd_shw[w]) for w in range(SW)
        ]
        nd_state = jnp.where(mk & (cnt == 0), _DU, nd_state)
        upg = mk & (cnt == 1) & (ds == _DS)
        nd_state = jnp.where(upg, _EM, nd_state)
        put(sA0, upg, sv_owner(after),
            pack(int(MsgType.UPGRADE_NOTIFY), a))

        # --- UPGRADE_NOTIFY (fixture semantics; spec_engine) ---------
        mk = typ(MsgType.UPGRADE_NOTIFY) & (snd == home)
        hit_un = mk & line_match & (line_state == _S)
        upd_line = upd_line | hit_un
        nl_state = jnp.where(hit_un, _E, nl_state)

        # --- EVICT_MODIFIED (assignment.c:541-561) -------------------
        mk = typ(MsgType.EVICT_MODIFIED) & is_home
        mem_write = mem_write | mk
        mem_val = jnp.where(mk, v, mem_val)
        drop = mk & (ds == _EM) & sv_test(dshw, snd)
        upd_dir = upd_dir | drop
        nd_state = jnp.where(drop, _DU, nd_state)
        nd_shw = [
            jnp.where(drop, 0, nd_shw[w]) for w in range(SW)
        ]

        # --- NACK re-serve (robust mode; spec_engine) ----------------
        if nack:
            mk = typ(MsgType.NACK) & is_home
            rd = mk & (aux == 0)
            wr = mk & (aux != 0)
            nack_sr_bitw = sv_bit(sr)
            upd_dir = upd_dir | mk
            nd_state = jnp.where(rd, _DS, nd_state)
            nd_state = jnp.where(wr, _EM, nd_state)
            nd_shw = [
                jnp.where(rd, nd_shw[w] | nack_sr_bitw[w], nd_shw[w])
                for w in range(SW)
            ]
            nd_shw = [
                jnp.where(wr, nack_sr_bitw[w], nd_shw[w])
                for w in range(SW)
            ]
            put(sA0, rd, sr, pack(int(MsgType.REPLY_RD), a, aux=mem_blk))
            put(sA0, wr, sr, pack(int(MsgType.REPLY_WR), a))

        # apply phase-A updates: the three cache/directory structures
        # share their packed word, so each applies in ONE one-hot write
        cw_val = (
            nl_state | (nl_val << _CW_VAL_SHIFT)
            | ((nl_addr + 1) << _CW_ADDR_SHIFT)
        )
        cachew = write_c(s["cachew"], ci, upd_line, cw_val)
        new_mem = jnp.where(mem_write, mem_val, mem_blk)
        new_ds = jnp.where(upd_dir, nd_state, ds)
        new_dshw = [
            jnp.where(upd_dir, nd_shw[w], dshw[w]) for w in range(SW)
        ]
        if split:
            dw_val = new_mem | (new_ds << _DW_STATE_SHIFT)
            dirsp = [
                write_m(s[f"dirs{w}"], blk, upd_dir, new_dshw[w])
                for w in range(SW)
            ]
        else:
            dw_val = (
                new_mem | (new_ds << _DW_STATE_SHIFT)
                | (new_dshw[0] << _DW_SH_SHIFT)
            )
            dirsp = []
        dirw = write_m(s["dirw"], blk, mem_write | upd_dir, dw_val)

        # ===== phase B: instruction issue ============================
        tr_len = s["tr_len"]
        elig = (
            (count2 == 0) & (waiting == 0) & ~blocked & (pc_in < tr_len)
        )
        if "phase_b" in ablate:
            elig = false
        t_dim = s["tr"].shape[1]
        pcc = jnp.minimum(pc_in, t_dim - 1)
        iota_tr = jax.lax.broadcasted_iota(I32, (nl, t_dim, bb), 1)
        hot_tr = iota_tr == pcc[:, None, :]
        wi = jnp.sum(jnp.where(hot_tr, s["tr"], 0), axis=1)
        op = wi & 1
        iv = (wi >> 1) & 0xFF
        ia = wi >> _TR_ADDR_SHIFT
        ci2 = ia % c
        home2 = ia // m

        cw2 = read_c(cachew, ci2)
        l2_state = cw2 & 3
        l2_val = (cw2 >> _CW_VAL_SHIFT) & 0xFF
        l2_addr = ((cw2 >> _CW_ADDR_SHIFT) & addr_mask) - 1
        hit = (l2_addr == ia) & (l2_state != _I)
        is_rd = elig & (op == 0)
        is_wr = elig & (op == 1)

        sB0, sB1 = slot(), slot()
        rm = is_rd & ~hit
        wm = is_wr & ~hit
        ev_issue = evict_msg(sB0, rm | wm, l2_addr, l2_val, l2_state)
        put(sB1, rm, home2, pack(int(MsgType.READ_REQUEST), ia))
        put(sB1, wm, home2,
            pack(int(MsgType.WRITE_REQUEST), ia, aux=iv))
        wh_me = is_wr & hit & ((l2_state == _M) | (l2_state == _E))
        wh_s = is_wr & hit & (l2_state == _S)
        put(sB1, wh_s, home2, pack(int(MsgType.UPGRADE), ia))

        pending_write = jnp.where(is_wr, iv, pw_in)
        waiting = jnp.where(rm | wm | wh_s, 1, waiting)

        i_upd = rm | wm | wh_me | wh_s
        n2_addr = jnp.where(rm | wm, ia, l2_addr)
        n2_val = jnp.where(rm | wm, 0, jnp.where(wh_me | wh_s, iv, l2_val))
        n2_state = jnp.where(
            rm | wm, _I, jnp.where(wh_me | wh_s, _M, l2_state)
        )
        cw2_val = (
            n2_state | (n2_val << _CW_VAL_SHIFT)
            | ((n2_addr + 1) << _CW_ADDR_SHIFT)
        )
        cachew = write_c(cachew, ci2, i_upd, cw2_val)
        pc = pc_in + elig.astype(I32)

        # merge deferred sends back into their candidate-grid slots as
        # ALREADY-PACKED words (blocked nodes made no new sends, so the
        # where-merge is exact).  Stray recv-field bits riding a merged
        # wire word are harmless: no mailbox decode reads them.  The
        # INV slot stays decoded (its remainder mask must be re-derived
        # each cycle, and its word re-packed clean of the old mask).
        def merge_slot(sl, k):
            pv = dv[:, k, :] != 0
            words = [s[f"ob{w}"][:, k, :] for w in range(W)]
            old_recv = (
                dec(words, "recv") - 1 if recv_packed
                else s["ob_recv"][:, k, :]
            )
            sl["recv"] = jnp.where(pv, old_recv, sl["recv"])
            for w in range(W):
                sl[f"w{w}"] = jnp.where(pv, words[w], sl[f"w{w}"])

        merge_slot(sA0, 0)
        merge_slot(sA1, 1)
        pend_inv = dv[:, 2, :] != 0
        ob2 = [s[f"ob{w}"][:, 2, :] for w in range(W)]
        if split:
            ob2_shw = [dec(ob2, f"shr{w}") for w in range(SW)]
        else:
            ob2_shw = [dec(ob2, "aux")]
        inv_shw = [
            jnp.where(pend_inv, ob2_shw[w], inv_shw[w])
            for w in range(SW)
        ]
        inv_addr = jnp.where(pend_inv, dec(ob2, "addr"), inv_addr)
        merge_slot(sB0, 3)
        merge_slot(sB1, 4)

        # ===== phase C: deterministic delivery =======================
        # candidate order matches ops/step.py exactly: phase A sends
        # sender-major over slots [sA0, sA1, inv], then phase B over
        # [sB0, sB1] (assignment.c:711-739's locked enqueue becomes a
        # fixed traversal).  Each candidate is accepted only while the
        # receiver's queue has space; rejected candidates defer to the
        # sender's outbox (capacity backpressure, as in ops/step.py).
        # NOTE a fully vectorized [J, N, B] formulation (cumsum over
        # the candidate axis) measured 2.4x SLOWER on v5e than this
        # per-candidate loop of small ops — fat 3D temporaries cost
        # more than the saved op dispatch.  Encoding and bookkeeping
        # ARE hoisted: per-slot encodes before the loop, stacked
        # counter/rejection sums after it (order-free), leaving only
        # position/acceptance/write ops inside.
        sinv = slot()
        for w, wd_ in zip(range(W), pack(int(MsgType.INV), inv_addr)):
            sinv[f"w{w}"] = wd_
        slots5 = (sA0, sA1, sinv, sB0, sB1)
        # wire words [N, B] per slot: the sender field (the node's own
        # row index) is OR'd in once here, not at every put site
        sender_w, sender_off, _ = layout["sender"]
        base_sender = gids << sender_off if sender_off else gids
        words5 = [
            [
                sl[f"w{w}"] | base_sender if w == sender_w
                else sl[f"w{w}"]
                for w in range(W)
            ]
            for sl in slots5
        ]

        mbs = qdata
        xmsg_loc = exch_over = None
        if not sharded:
            acc = zero  # running enqueue offset per receiver
            # accepted-receiver masks per candidate:
            # [slot][sender] -> [N, B]
            acc_masks = [[None] * nl for _ in range(_NSLOTS)]

            def enqueue(mbs, acc, valid_nb, words_r):
                """Queue-write core: accept ``valid_nb`` receivers at
                the current offsets, writing per-receiver word rows."""
                pos = count2 + acc
                accepted = valid_nb & (pos < cap)
                acc_i = accepted.astype(I32)
                # mask folded into the position compare (pos >= 0
                # always): no bool-vector broadcast (Mosaic i8->i1
                # hazard)
                hot = iota_cap == jnp.where(accepted, pos, -1)[:, None, :]
                mbs = [
                    jnp.where(hot, words_r[w][:, None, :], mbs[w])
                    for w in range(W)
                ]
                return mbs, acc + acc_i, accepted, acc_i

            def candidate(mbs, acc, k, sender, valid_nb):
                words_r = [
                    words5[k][w][sender][None, :] for w in range(W)
                ]
                mbs, acc, _, acc_i = enqueue(mbs, acc, valid_nb, words_r)
                acc_masks[k][sender] = acc_i
                return mbs, acc

            # the receiver row IS the validity map (-1 = empty slot),
            # so the per-sender check is ONE i32 row broadcast +
            # compare (bool rows can't be indexed/broadcast
            # Mosaic-safely)
            def point_valid(sl, sender):
                return iota_n == sl["recv"][sender][None, :]

            def inv_valid(sender):
                # the same sign-safe per-word bit probe as directory
                # tests
                return sv_test(
                    [x[sender][None, :] for x in inv_shw], iota_n
                )

            if "deliver" in ablate:
                for k_ in range(_NSLOTS):
                    for sender in range(nl):
                        acc_masks[k_][sender] = zero
            else:
                # One message per node per cycle makes a sender's three
                # phase-A slots RECEIVER-DISJOINT by construction: A1
                # only exists for dual-destination FLUSH/FLUSH_INVACK
                # with second != home (the A0 receiver), and the INV
                # fan comes only from REPLY_ID, which makes no point
                # sends.  Deferral preserves disjointness (blocked
                # nodes make no fresh sends).  So the three deliver as
                # ONE candidate — valid masks OR'd, the word a
                # per-receiver select — which is order-equivalent to
                # the sequential walk because disjoint receivers never
                # contend for the same queue slot.  Delivery drops
                # from 5 to 3 candidates per sender (measured by jaxpr
                # op count: the unrolled loop was 44% of the cycle).
                for sender in range(nl):
                    vA0 = point_valid(sA0, sender)
                    vA1 = point_valid(sA1, sender)
                    vInv = inv_valid(sender)
                    wsel = [
                        jnp.where(
                            vA1, words5[1][w][sender][None, :],
                            jnp.where(
                                vInv, words5[2][w][sender][None, :],
                                words5[0][w][sender][None, :],
                            ),
                        )
                        for w in range(W)
                    ]
                    mbs, acc, accepted, _ = enqueue(
                        mbs, acc, vA0 | vA1 | vInv, wsel
                    )
                    acc_masks[0][sender] = jnp.where(vA0 & accepted, 1, 0)
                    acc_masks[1][sender] = jnp.where(vA1 & accepted, 1, 0)
                    acc_masks[2][sender] = jnp.where(vInv & accepted, 1, 0)
                for sender in range(nl):
                    mbs, acc = candidate(mbs, acc, 3, sender,
                                         point_valid(sB0, sender))
                    mbs, acc = candidate(mbs, acc, 4, sender,
                                         point_valid(sB1, sender))

            # post-loop bookkeeping on stacked masks (sums are
            # order-free; masks are already i32 — stacking bool
            # vectors is a Mosaic i8->i1 hazard)
            accs = jnp.stack(
                [jnp.stack(acc_masks[k], axis=0) for k in range(_NSLOTS)],
                axis=1,
            )                                  # [S(sender), 5, R(recv), B]
            dcount = jnp.sum(accs, axis=2)     # [S, 5, B] per candidate

            # rejected candidates defer to the sender outbox; the INV
            # remainder (mask minus accepted receivers) rides the
            # deferred word's aux union (packed) or shr{w} fields
            # (split)
            if SW == 1:
                io_r = jax.lax.broadcasted_iota(I32, (nl, n, bb), 1)
                remaining = [
                    inv_shw[0] & ~jnp.sum(accs[:, 2, :, :] << io_r, axis=1)
                ]
            else:
                remaining = []
                for w in range(SW):
                    lo = w * _SPLIT_BPW
                    hi = min(n, lo + _SPLIT_BPW)
                    io_r = jax.lax.broadcasted_iota(
                        I32, (nl, hi - lo, bb), 1
                    )
                    remaining.append(
                        inv_shw[w]
                        & ~jnp.sum(accs[:, 2, lo:hi, :] << io_r, axis=1)
                    )
        else:
            # ---- targeted cross-shard exchange (ops/exchange.py) ----
            # Vectorized candidate-axis delivery at the XLA level:
            # this branch never runs inside a Mosaic kernel
            # (collectives are host-lowered under shard_map), so bool
            # temporaries and fat [nl, J, bb] intermediates are fine.
            # Entry order is the global candidate grid of ops/step.py:
            # A-grid sender-major [A0, A1, INV] then B-grid [B0, B1]
            # — which the unsharded per-sender walk is
            # order-equivalent to, so dumps stay bit-identical.
            me = jax.lax.axis_index(axis_name).astype(I32)
            bpw = _SPLIT_BPW

            def interleave(arrs):  # k x [nl, bb] -> [k*nl, bb]
                return jnp.stack(arrs, axis=1).reshape(-1, bb)

            cand_words = [
                jnp.concatenate(
                    [
                        interleave([words5[0][w], words5[1][w],
                                    words5[2][w]]),
                        interleave([words5[3][w], words5[4][w]]),
                    ],
                    axis=0,
                )
                for w in range(W)
            ]                                  # W x [J0, bb]
            # per-candidate INV fan-mask words (A slot 2 only)
            mask_words = [
                jnp.concatenate(
                    [
                        interleave([zero, zero, inv_shw[sw]]),
                        jnp.zeros((2 * nl, bb), I32),
                    ],
                    axis=0,
                )
                for sw in range(SW)
            ]                                  # SW x [J0, bb]
            # recv shipped +1 so zero-filled exchange slots (word 0)
            # can never match receiver node 0
            recv_p1 = jnp.concatenate(
                [
                    interleave(
                        [slots5[k]["recv"] + 1 for k in (0, 1, 2)]
                    ),
                    interleave(
                        [slots5[k]["recv"] + 1 for k in (3, 4)]
                    ),
                ],
                axis=0,
            )                                  # [J0, bb]; 0 = no point
            isa_col = jnp.concatenate(
                [
                    jnp.ones((3 * nl, bb), I32),
                    jnp.zeros((2 * nl, bb), I32),
                ],
                axis=0,
            )
            # tier-boundary combining key (hier relays only): addr+1
            # for READ requests, 0 = not combinable
            ckey5 = [
                jnp.where(
                    (slots5[k]["recv"] >= 0)
                    & (dec(words5[k], "type")
                       == int(MsgType.READ_REQUEST)),
                    dec(words5[k], "addr") + 1,
                    0,
                )
                for k in range(_NSLOTS)
            ]
            ckey_col = jnp.concatenate(
                [
                    interleave([ckey5[0], ckey5[1], ckey5[2]]),
                    interleave([ckey5[3], ckey5[4]]),
                ],
                axis=0,
            )
            j0 = 5 * nl
            payload = jnp.stack(
                cand_words + mask_words + [recv_p1, isa_col, ckey_col],
                axis=0,
            )                                  # [W + SW + 3, J0, bb]

            def dest_fn(blk, peer):
                lo = peer * nl
                recv = blk[W + SW]
                pt = (recv >= lo + 1) & (recv < lo + nl + 1)
                rm_i = jax.lax.bitcast_convert_type(
                    exchange.range_mask_words(lo, lo + nl, SW, bpw), I32
                )
                mhit = (blk[W] & rm_i[0]) != 0
                for sw in range(1, SW):
                    mhit = mhit | ((blk[W + sw] & rm_i[sw]) != 0)
                return pt | mhit

            def fan_fn(blk, peer):
                # receivers within shard ``peer``: fan-mask popcount
                # for INV entries (>= 1 whenever shipped), 1 for point
                # sends (popcount 0 on a point entry's zero mask)
                lo = peer * nl
                rm_i = jax.lax.bitcast_convert_type(
                    exchange.range_mask_words(lo, lo + nl, SW, bpw), I32
                )
                pop = _popcount(blk[W] & rm_i[0])
                for sw in range(1, SW):
                    pop = pop + _popcount(blk[W + sw] & rm_i[sw])
                return jnp.maximum(pop, 1)

            bufs, origins, xctx, xfs = exchange.forward(
                xplan, axis_name, me, payload, dest_fn, k_slots,
                fan_fn=fan_fn, ckey_row=W + SW + 2, nkeys=n * m,
            )
            nb = len(bufs)
            xmsg_loc = xfs["sent"][None, :]
            exch_over = jnp.minimum(xfs["overflow"], 1)[None, :]
            xhw_loc = xfs["hwm"][None, :]
            xmc_loc = xfs["mc_saved"][None, :]
            xcb_loc = xfs["combined"][None, :]
            # overflow diagnostics: the packed worst-offender word
            # (demand<<16 | src<<8 | dst) plus a companion word keyed
            # by the same demand with the lane cycle in the low half,
            # so one pmax selects a consistent (shard pair, cycle) pair
            xdg_loc = xfs["ovf_diag"][None, :]
            xdc_loc = jnp.where(
                xdg_loc > 0,
                (xdg_loc & ~0xFFFF)
                | (s["scalars"][_SC_CYCLE][None, :] & 0xFFFF),
                0,
            )

            def cat(i, local_row):
                return jnp.concatenate(
                    [local_row] + [b_[i] for b_ in bufs], axis=0
                )

            all_words = [cat(w, cand_words[w]) for w in range(W)]
            all_mask = [cat(W + sw, mask_words[sw]) for sw in range(SW)]
            all_recv = cat(W + SW, recv_p1)
            all_isa = cat(W + SW + 1, isa_col)
            bounds = [0, j0] + [
                j0 + (i + 1) * k_slots for i in range(nb)
            ]
            # validity per (receiver row, entry): point match on the
            # shifted recv, or a fan-mask bit probe at the receiver's
            # global id (zero-filled slots fail both)
            pv_rj = gids[:, None, :] + 1 == all_recv[None, :, :]
            # broadcast-safe fan-mask probe over [nl, J, bb] (sv_test's
            # split path accumulates from a [nl, bb] zero and cannot
            # broadcast against the entry axis)
            g3 = gids[:, None, :]
            inv_rj = None
            for sw in range(SW):
                b_ = g3 - sw * bpw
                vw = (all_mask[sw][None, :, :] >> jnp.clip(b_, 0, 31)) & 1
                h_ = jnp.where((b_ >= 0) & (b_ < bpw), vw, 0)
                inv_rj = h_ if inv_rj is None else inv_rj | h_
            inv_rj = inv_rj != 0
            valid_rj = pv_rj | inv_rj          # [nl, J, bb]
            # global delivery rank across [local | received] blocks —
            # the received blocks sit in arrival (round) order, which
            # is shard-dependent, so the rank is computed against the
            # traced origin ids instead of a static permutation
            offs = exchange.ordered_rank(
                valid_rj & (all_isa[None, :, :] != 0),
                valid_rj & (all_isa[None, :, :] == 0),
                bounds, origins, axis=1,
            )
            pos = count2[:, None, :] + offs
            accept = valid_rj & (pos < cap)
            acc_i3 = accept.astype(I32)
            acc = jnp.sum(acc_i3, axis=1)      # delivered per receiver
            hot = (
                iota_cap[:, :, None, :]
                == jnp.where(accept, pos, -1)[:, None, :, :]
            ).astype(I32)                      # [nl, cap, J, bb]
            mbs = [
                jnp.where(
                    jnp.sum(hot, axis=2) > 0,
                    jnp.sum(hot * all_words[w][None, None, :, :], axis=2),
                    qdata[w],
                )
                for w in range(W)
            ]
            # acceptance feedback to the senders: per-entry accepted
            # count + accepted-receiver bit words ride the plan's
            # reverse collective schedule and scatter back onto the
            # local candidate axis via the saved compaction placement
            acc_e = jnp.sum(acc_i3, axis=0)    # [J, bb]
            fb_bits = []
            for sw in range(SW):
                b_ = gids - sw * bpw
                inw = (b_ >= 0) & (b_ < bpw)
                fb_bits.append(
                    jnp.sum(
                        jnp.where(
                            inw[:, None, :],
                            acc_i3 << jnp.clip(b_, 0, 31)[:, None, :],
                            0,
                        ),
                        axis=0,
                    )
                )                              # [J, bb]
            fbrows = jnp.stack([acc_e] + fb_bits, axis=0)
            fb_blocks = [
                fbrows[:, bounds[i + 1]:bounds[i + 2]]
                for i in range(nb)
            ]
            acc_tot = fbrows[:, :j0] + exchange.feedback(
                xplan, axis_name, fb_blocks, xctx
            )
            acc_j = acc_tot[0]                 # [J0, bb] global accepts
            dcount = jnp.concatenate(
                [
                    acc_j[: 3 * nl].reshape(nl, 3, bb),
                    acc_j[3 * nl:].reshape(nl, 2, bb),
                ],
                axis=1,
            )                                  # [S, 5, B] per candidate
            remaining = [
                inv_shw[sw] & ~acc_tot[1 + sw, 2: 3 * nl: 3]
                for sw in range(SW)
            ]

        md = jnp.sum(dcount, axis=(0, 1))[None, :]          # [1, B]
        # message-type decode straight off the wire word (empty slots
        # decode as type 0 but contribute dcount 0)
        type_arr = jnp.stack(
            [dec(words5[k], "type") for k in range(_NSLOTS)], axis=1
        )                                      # [S, 5, B]
        mc = jnp.sum(
            jnp.where(
                type_arr[None, :, :, :] == jax.lax.broadcasted_iota(
                    I32, (_NTYPES, nl, _NSLOTS, bb), 0
                ),
                dcount[None, :, :, :], 0,
            ),
            axis=(1, 2),
        )                                      # [NTYPES, B]

        rem_any = remaining[0]
        for w in range(1, SW):
            rem_any = rem_any | remaining[w]
        rej = [
            jnp.where(
                (dcount[:, k, :] == 0) & (slots5[k]["recv"] >= 0), 1, 0
            )
            for k in (0, 1, 3, 4)
        ]
        # per-slot deferral masks: slots 0,1,3,4 defer on rejection;
        # the INV slot defers iff its remainder mask is nonempty.
        # NON-deferred slots write a ZERO word (and -1 ob_recv) so the
        # next cycle's deferred_valid() derivation is exact — this
        # replaces the ob_valid plane entirely.
        defer5 = [rej[0], rej[1], (rem_any != 0).astype(I32),
                  rej[2], rej[3]]
        recvs5 = tuple(sl["recv"] for sl in slots5)   # sinv recv = -1
        if not recv_packed:
            ob_recv_new = jnp.stack(
                [
                    jnp.where(defer5[k] != 0, recvs5[k], -1)
                    for k in range(_NSLOTS)
                ],
                axis=1,
            )
        ob_new = []
        if recv_packed:
            recv_w, recv_off, _ = layout["recv"]
        rem_fields = (
            [(f"shr{w}", remaining[w]) for w in range(SW)]
            if split
            else [("aux", remaining[0])]
        )
        rem_by_word = {}
        for fname, rw in rem_fields:
            fw, foff, _ = layout[fname]
            rem_by_word.setdefault(fw, []).append(
                rw << foff if foff else rw
            )
        for w in range(W):
            ws = [words5[k][w] for k in range(_NSLOTS)]
            for rw in rem_by_word.get(w, ()):
                ws[2] = ws[2] | rw
            if recv_packed and w == recv_w:
                # idempotent for merged-deferred rows (their words
                # already carry the same recv bits)
                ws = [
                    wk | ((recvs5[k] + 1) << recv_off)
                    for k, wk in enumerate(ws)
                ]
            ws = [
                jnp.where(defer5[k] != 0, wk, 0)
                for k, wk in enumerate(ws)
            ]
            ob_new.append(jnp.stack(ws, axis=1))
        if "deliver" in ablate:
            # timing fiction, matching the pre-hoist ablation: sends
            # vanish without deferral (otherwise every candidate would
            # defer and block issue, and the outbox ops would stay in
            # the ablated graph instead of constant-folding away)
            z5 = jnp.zeros((nl, _NSLOTS, bb), I32)
            ob_recv_new = z5 - 1
            ob_new = [z5 for _ in range(W)]
            defer5 = [zero] * _NSLOTS
        blocked_next = (
            defer5[0] + defer5[1] + defer5[2] + defer5[3] + defer5[4]
        ) > 0

        mb_count3 = count2 + acc
        ov_inc = jnp.minimum(
            jnp.sum((mb_count3 > cap).astype(I32), axis=0, keepdims=True),
            1,
        )

        out = {
            "cachew": cachew, "dirw": dirw,
            "nsw": (
                mb_count3
                | (waiting << slsc["off_wait"])
                | (pending_write << slsc["off_pw"])
                | (pc << slsc["off_pc"])
            ),
            "tr": s["tr"], "tr_len": s["tr_len"],
        }
        for w in range(SW if split else 0):
            out[f"dirs{w}"] = dirsp[w]
        if not recv_packed:
            out["ob_recv"] = ob_recv_new
        for w in range(W):
            out[f"mb{w}"] = mbs[w]
            out[f"ob{w}"] = ob_new[w]

        # ===== phase D: dump-at-local-completion snapshots ===========
        if snapshots:
            done_node = (
                (pc >= tr_len) & (waiting == 0) & (mb_count3 == 0)
                & ~blocked_next
            )
            snap_now = done_node & ~(s["snap_taken"] != 0)
            s2 = snap_now.astype(I32)[:, None, :] != 0
            out["snap_taken"] = (
                (s["snap_taken"] != 0) | done_node
            ).astype(I32)
            out["snap_cachew"] = jnp.where(s2, cachew, s["snap_cachew"])
            out["snap_dirw"] = jnp.where(s2, dirw, s["snap_dirw"])
            for w in range(SW if split else 0):
                out[f"snap_dirs{w}"] = jnp.where(
                    s2, dirsp[w], s[f"snap_dirs{w}"]
                )

        # ===== counters ==============================================
        row = lambda x: jnp.sum(x.astype(I32), axis=0, keepdims=True)
        sc = s["scalars"]
        if not sharded:
            # a lane only accrues a cycle while it has outstanding work
            # at cycle start — the quiescence gate runs every _GATE
            # cycles (or never, gate=False), so an unconditional
            # increment would overshoot quiescence by up to the gate
            # window and diverge from the spec/native cycle counters
            lane_active = (
                jnp.sum(jnp.maximum(s["tr_len"] - pc_in, 0), axis=0,
                        keepdims=True)
                + jnp.sum(waiting_in, axis=0, keepdims=True)
                + jnp.sum(mb_count_in, axis=0, keepdims=True)
                + jnp.sum(dv, axis=(0, 1))[None, :]
            )
            upd = [
                (_SC_CYCLE, jnp.minimum(lane_active, 1)),
                (_SC_INSTR, row(elig)),
                (_SC_MSGS, md),
                (_SC_OVERFLOW, ov_inc),
                (_SC_RH, row(is_rd & hit)),
                (_SC_RM, row(rm)),
                (_SC_WH, row(is_wr & hit)),
                (_SC_WM, row(wm)),
                (_SC_EV, row(ev_replyrd | ev_flush | ev_issue)),
                (_SC_INV, row(inv_applied)),
            ]
            mc_g = mc
        else:
            # ONE stacked psum carries every cross-shard summed
            # reduction of the cycle: end-of-cycle global activity
            # (next cycle's lane-active gate — end state at cycle t IS
            # start state at t+1), cross-shard message count, exchange
            # overflow, mailbox overflow, the 8 + NTYPES counter rows,
            # and the multicast/combining savings.  A second stacked
            # pmax replicates the max-telemetry (slot high-water mark
            # and the packed overflow diagnostics).  The
            # collective-count guard pins the loop to the plan's
            # exchange collectives plus exactly this psum + pmax.
            end_active = (
                jnp.sum(jnp.maximum(tr_len - pc, 0), axis=0,
                        keepdims=True)
                + jnp.sum(waiting, axis=0, keepdims=True)
                + jnp.sum(mb_count3, axis=0, keepdims=True)
                + sum(jnp.sum(d5, axis=0, keepdims=True)
                      for d5 in defer5)
            )
            g = jax.lax.psum(
                jnp.concatenate(
                    [
                        end_active, xmsg_loc, exch_over, ov_inc,
                        row(elig), md, row(is_rd & hit), row(rm),
                        row(is_wr & hit), row(wm),
                        row(ev_replyrd | ev_flush | ev_issue),
                        row(inv_applied), mc, xmc_loc, xcb_loc,
                    ],
                    axis=0,
                ),
                axis_name,
            )                              # [14 + NTYPES, B] replicated
            pm = jax.lax.pmax(
                jnp.concatenate([xhw_loc, xdg_loc, xdc_loc], axis=0),
                axis_name,
            )                              # [3, B] replicated
            upd = [
                # previous cycle's psum'd end-activity == this cycle's
                # start activity (the runner seeds activeg with one
                # psum of the initial state, outside the loop)
                (_SC_CYCLE, jnp.minimum(s["activeg"], 1)),
                (_SC_INSTR, g[4:5]),
                (_SC_MSGS, g[5:6]),
                (_SC_OVERFLOW, jnp.minimum(g[3:4], 1)),
                (_SC_RH, g[6:7]),
                (_SC_RM, g[7:8]),
                (_SC_WH, g[8:9]),
                (_SC_WM, g[9:10]),
                (_SC_EV, g[10:11]),
                (_SC_INV, g[11:12]),
            ]
            mc_g = g[12:12 + _NTYPES]
            # transient rows threaded by the node-sharded runner (not
            # part of state_shapes): global activity for the quiescence
            # gate, cumulative cross-shard messages, sticky exchange
            # overflow, and the exchange telemetry (slot high-water
            # mark, multicast/combining savings, overflow diagnostics)
            out["activeg"] = g[0:1]
            out["xmsgs"] = s["xmsgs"] + g[1:2]
            out["exchov"] = jnp.maximum(s["exchov"], g[2:3])
            out["exchmc"] = s["exchmc"] + g[12 + _NTYPES:13 + _NTYPES]
            out["exchcb"] = s["exchcb"] + g[13 + _NTYPES:14 + _NTYPES]
            out["exchhw"] = jnp.maximum(s["exchhw"], pm[0:1])
            out["exchdg"] = jnp.maximum(s["exchdg"], pm[1:2])
            out["exchdc"] = jnp.maximum(s["exchdc"], pm[2:3])
        iota_sc = jax.lax.broadcasted_iota(I32, (_NSCALAR, bb), 0)
        inc = jnp.zeros((_NSCALAR, bb), I32)
        for rid, val in upd:
            inc = jnp.where(iota_sc == rid, val, inc)
        # overflow row is sticky-OR, everything else accumulates
        out["scalars"] = jnp.where(
            iota_sc == _SC_OVERFLOW, jnp.maximum(sc, inc), sc + inc
        )
        out["msg_counts"] = s["msg_counts"] + mc_g
        return out

    if not packed:
        return cycle

    pdt = packed_plane_dtypes(config)

    def packed_cycle(s: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        wide = dict(s)
        for pre in ([""] + (["snap_"] if snapshots else [])):
            wide[f"{pre}cachew"] = _widen_cache(
                wide.pop(f"{pre}cvalw"), wide.pop(f"{pre}cmetaw")
            )
            wide[f"{pre}dirw"] = _widen_dir(
                wide.pop(f"{pre}dmemw"), wide.pop(f"{pre}dmetaw")
            )
        out = cycle(wide)
        for pre in ([""] + (["snap_"] if snapshots else [])):
            cv, cm = _narrow_cache(
                out.pop(f"{pre}cachew"), pdt["cmetaw"]
            )
            dm, dmt = _narrow_dir(out.pop(f"{pre}dirw"), pdt["dmetaw"])
            out[f"{pre}cvalw"], out[f"{pre}cmetaw"] = cv, cm
            out[f"{pre}dmemw"], out[f"{pre}dmetaw"] = dm, dmt
        return out

    return packed_cycle


# ---------------------------------------------------------------------------
# Kernel wrapper + host runner
# ---------------------------------------------------------------------------

def _pack_traces(config: SystemConfig, tr_op, tr_addr, tr_val, tr_len):
    """[B, N, T] op/addr/val arrays -> packed [N, T, B] word array.
    Padding beyond tr_len is sanitized to zero (never fetched — the
    pc < tr_len gate)."""
    t = tr_op.shape[2]
    valid = np.arange(t)[None, None, :] < tr_len[:, :, None]
    opx = tr_op.astype(np.int64)
    valx = tr_val.astype(np.int64)
    addrx = tr_addr.astype(np.int64)
    if valid.any():
        if not ((opx[valid] >= 0) & (opx[valid] <= 1)).all():
            raise ValueError("trace ops must be 0 (RD) or 1 (WR)")
        if not ((valx[valid] >= 0) & (valx[valid] < 256)).all():
            raise ValueError("trace values must be bytes (mod 256)")
        if not (
            (addrx[valid] >= 0) & (addrx[valid] < config.num_addresses)
        ).all():
            raise ValueError("trace addresses out of range")
    tr = np.where(
        valid, opx | (valx << 1) | (addrx << _TR_ADDR_SHIFT), 0
    ).astype(np.int32)
    return np.ascontiguousarray(np.moveaxis(tr, 0, -1))


def _split_word_planes_np(config: SystemConfig, cachew, dirw):
    """Numpy split of legacy cachew/dirw word planes into the four
    packed planes (the inverse of ``_widen_cache``/``_widen_dir``)."""
    pdt = packed_plane_dtypes(config)
    cw = cachew.astype(np.int64)
    dw = dirw.astype(np.int64)
    return {
        "cvalw": ((cw >> _CW_VAL_SHIFT) & 0xFF).astype(np.uint8),
        "cmetaw": (
            (cw & 3) | ((cw >> _CW_ADDR_SHIFT) << 2)
        ).astype(pdt["cmetaw"]),
        "dmemw": (dw & 0xFF).astype(np.uint8),
        "dmetaw": (
            ((dw >> _DW_STATE_SHIFT) & 3) | ((dw >> _DW_SH_SHIFT) << 2)
        ).astype(pdt["dmetaw"]),
    }


def _join_word_planes_np(cvalw, cmetaw, dmemw, dmetaw):
    """Numpy inverse of :func:`_split_word_planes_np` — rebuild legacy
    int32 cachew/dirw words for readback/dump decoding."""
    cm = cmetaw.astype(np.int64)
    dmt = dmetaw.astype(np.int64)
    cachew = (
        (cm & 3)
        | (cvalw.astype(np.int64) << _CW_VAL_SHIFT)
        | ((cm >> 2) << _CW_ADDR_SHIFT)
    ).astype(np.int32)
    dirw = (
        dmemw.astype(np.int64)
        | ((dmt & 3) << _DW_STATE_SHIFT)
        | ((dmt >> 2) << _DW_SH_SHIFT)
    ).astype(np.int32)
    return cachew, dirw


def decode_dumps(config: SystemConfig, cachew, dirw, sys_idx: int,
                 dirs=None) -> List[NodeDump]:
    """Decode one system's column of the packed word planes into the
    reference per-node dump records (bit layout of assignment.c's
    dumpProcessorState).  ``dirs`` supplies the split sharer-word
    planes on geometries whose sharer mask outgrows the directory
    word."""
    n = config.num_procs
    sh_mask = (1 << min(n, _SPLIT_BPW)) - 1
    addr_mask = (1 << 21) - 1

    def sharers_of(i):
        if dirs is None:
            return [
                int(x)
                for x in (dirw[i, :, sys_idx] >> _DW_SH_SHIFT)
                & sh_mask
            ]
        return [
            sum(
                int(dirs[w][i, j, sys_idx]) << (w * _SPLIT_BPW)
                for w in range(len(dirs))
            )
            for j in range(config.mem_size)
        ]

    return [
        NodeDump(
            proc_id=i,
            memory=[int(x) for x in dirw[i, :, sys_idx] & 0xFF],
            dir_state=[
                int(x)
                for x in (dirw[i, :, sys_idx] >> _DW_STATE_SHIFT) & 3
            ],
            dir_sharers=sharers_of(i),
            cache_addr=[
                int(x) - 1
                for x in (cachew[i, :, sys_idx] >> _CW_ADDR_SHIFT)
                & addr_mask
            ],
            cache_value=[
                int(x)
                for x in (cachew[i, :, sys_idx] >> _CW_VAL_SHIFT)
                & 0xFF
            ],
            cache_state=[
                int(x) for x in cachew[i, :, sys_idx] & 3
            ],
        )
        for i in range(n)
    ]


def _init_state(config: SystemConfig, b: int, snapshots: bool = True,
                packed: bool = False):
    """Initial packed state dict in transposed layout
    (initializeProcessor semantics, assignment.c:776-822)."""
    n, c, m = config.num_procs, config.cache_size, config.mem_size
    cap = config.msg_buffer_size
    layout, W = _mb_layout(config)
    _check_geometry(config)

    mem0 = np.array(
        [[(20 * i + j) % 256 for j in range(m)] for i in range(n)],
        dtype=np.int32,
    )
    dirw0 = np.broadcast_to(
        (mem0 | (_DU << _DW_STATE_SHIFT))[:, :, None], (n, m, b)
    ).copy()
    # invalid line: state I, value 0, addr -1 (stored +1 = 0)
    cachew0 = np.full((n, c, b), _I, np.int32)

    def words(cw, dw, prefix=""):
        if packed:
            return {
                f"{prefix}{f}": v
                for f, v in _split_word_planes_np(config, cw, dw).items()
            }
        return {f"{prefix}cachew": cw, f"{prefix}dirw": dw}

    z2 = np.zeros((n, b), dtype=np.int32)
    state = dict(words(cachew0.copy(), dirw0))
    state.update({
        "nsw": z2.copy(),  # mb_count | waiting | pending_write | pc
        "scalars": np.zeros((_NSCALAR, b), np.int32),
        "msg_counts": np.zeros((_NTYPES, b), np.int32),
    })
    split_sw = _sharer_words(config) if _split_mode(config) else 0
    for w in range(split_sw):
        state[f"dirs{w}"] = np.zeros((n, m, b), np.int32)
    for w in range(W):
        state[f"mb{w}"] = np.zeros((n, cap, b), np.int32)
        state[f"ob{w}"] = np.zeros((n, _NSLOTS, b), np.int32)
    if "recv" not in layout:
        # -1 = empty (deferred_valid's point-slot sentinel)
        state["ob_recv"] = np.full((n, _NSLOTS, b), -1, np.int32)
    if snapshots:
        state["snap_taken"] = z2.copy()
        state.update(words(cachew0.copy(), dirw0.copy(), "snap_"))
        for w in range(split_sw):
            state[f"snap_dirs{w}"] = np.zeros((n, m, b), np.int32)
    return state


@functools.lru_cache(maxsize=16)
def _build_call(config: SystemConfig, b: int, bb: int, k: int,
                interpret: bool, snapshots: bool,
                ablate: frozenset = frozenset(), gate: bool = True,
                packed: bool = False):
    """Jitted pallas_call advancing every system by up to ``k`` cycles
    (quiesced blocks skip at ``_GATE`` granularity), state resident in
    VMEM for the duration."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if b % bb != 0:
        raise ValueError(f"batch {b} not divisible by block {bb}")
    cycle = build_cycle(config, bb, snapshots, ablate, packed)
    n = config.num_procs
    layout, W = _mb_layout(config)
    split_sw = _sharer_words(config) if _split_mode(config) else 0
    fields = _state_fields(W, snapshots, "recv" in layout, split_sw,
                           packed)
    outer, inner = -(-k // _GATE), _GATE
    shapes = state_shapes(config, snapshots=True, packed=packed)
    dtypes = state_dtypes(config, snapshots=True, packed=packed)

    def kernel(*refs):
        ntr = len(TRACE_FIELDS)
        nst = len(fields)
        tr_refs = refs[:ntr]
        in_refs = refs[ntr:ntr + nst]
        out_refs = refs[ntr + nst:]
        s = {name: in_refs[i][:] for i, name in enumerate(fields)}
        s.update(
            {name: tr_refs[i][:] for i, name in enumerate(TRACE_FIELDS)}
        )

        def run_gate(st):
            return jax.lax.fori_loop(
                0, inner, lambda _, x: cycle(x), st
            )

        def body(_, st):
            # integer quiescence check: bool-vector reductions are not
            # Mosaic-lowerable (i8->i1 trunci), so count outstanding
            # work and compare the scalar.  Checked once per _GATE
            # cycles (the reduce+branch costs ~8.5us, measured).
            slsc = _scalar_layout(config, st["tr"].shape[1])
            nswv = st["nsw"]
            pcv = (nswv >> slsc["off_pc"]) & slsc["pc_mask"]
            active = (
                jnp.sum(jnp.maximum(st["tr_len"] - pcv, 0))
                + jnp.sum((nswv >> slsc["off_wait"]) & 1)
                + jnp.sum(nswv & slsc["count_mask"])
                + jnp.sum(deferred_valid(config, st))
            )
            return jax.lax.cond(active == 0, lambda x: x, run_gate, st)

        if gate:
            s = jax.lax.fori_loop(0, outer, body, s)
        else:
            # no in-kernel early exit: the lax.cond doubles the live
            # carry in VMEM; the host-level while_loop already bounds
            # overshoot to < k cycles per quiesced block
            s = jax.lax.fori_loop(0, k, lambda _, x: cycle(x), s)
        for i, name in enumerate(fields):
            out_refs[i][:] = s[name]

    def block_spec(prefix_shape):
        shape = tuple(prefix_shape) + (bb,)
        nd = len(shape)
        return pl.BlockSpec(
            shape,
            (lambda i, _nd=nd: (0,) * (_nd - 1) + (i,)),
            memory_space=pltpu.VMEM,
        )

    def call(state: Dict[str, jnp.ndarray], traces: Dict[str, jnp.ndarray]):
        t_dim = traces["tr"].shape[1]
        tr_shapes = {"tr": (n, t_dim), "tr_len": (n,)}
        in_specs = (
            [block_spec(tr_shapes[f]) for f in TRACE_FIELDS]
            + [block_spec(shapes[f]) for f in fields]
        )
        out_specs = [block_spec(shapes[f]) for f in fields]
        out_shape = [
            jax.ShapeDtypeStruct(tuple(shapes[f]) + (b,), dtypes[f])
            for f in fields
        ]
        aliases = {
            len(TRACE_FIELDS) + i: i for i in range(len(fields))
        }
        fn = pl.pallas_call(
            kernel,
            grid=(b // bb,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            input_output_aliases=aliases,
            interpret=interpret,
        )
        args = [traces[f] for f in TRACE_FIELDS] + [
            state[f] for f in fields
        ]
        outs = fn(*args)
        return dict(zip(fields, outs))

    return jax.jit(call)


@functools.lru_cache(maxsize=16)
def _make_run(config: SystemConfig, b: int, bb: int, k: int,
              interpret: bool, snapshots: bool, window: int, n_seg: int,
              max_calls: int, ablate: frozenset = frozenset(),
              gate: bool = True, packed: bool = False):
    """One jitted program driving the WHOLE run on-device: fori over
    trace windows x while-to-quiescence around the pallas_call, one
    status scalar out.  Host<->device round trips through the axon
    tunnel cost ~10^2 ms each (measured round 4); the per-call python
    loop was paying two per 128 cycles, dwarfing the kernel itself."""
    call = _build_call(config, b, bb, k, interpret, snapshots, ablate,
                       gate, packed)
    slsc = _scalar_layout(config, window)

    def all_quiescent(st, tl):
        nswv = st["nsw"]
        return (
            jnp.all(((nswv >> slsc["off_pc"]) & slsc["pc_mask"]) >= tl)
            & jnp.all(((nswv >> slsc["off_wait"]) & 1) == 0)
            & jnp.all((nswv & slsc["count_mask"]) == 0)
            & jnp.all(deferred_valid(config, st) == 0)
        )

    def run_all(state, tr_full, tr_len_full):
        def seg_body(si, carry):
            st, stalled, calls0 = carry
            tr_seg = jax.lax.dynamic_slice_in_dim(
                tr_full, si * window, window, axis=1
            )
            tl_seg = jnp.clip(tr_len_full - si * window, 0, window)
            # window base: every system is quiescent here (enforced
            # below via the stalled flag), so the pc restart is a
            # plain field clear in the packed scalar row
            st = {
                **st,
                "nsw": st["nsw"]
                & ~(slsc["pc_mask"] << slsc["off_pc"]),
            }
            traces = {"tr": tr_seg, "tr_len": tl_seg}

            def cond(c):
                s2, calls = c
                return (~all_quiescent(s2, tl_seg)) & (calls < max_calls)

            def body(c):
                s2, calls = c
                return call(s2, traces), calls + 1

            # the call counter carries ACROSS windows so max_calls
            # (derived from the caller's max_cycles) bounds the whole
            # run, not each window separately
            st, calls1 = jax.lax.while_loop(cond, body, (st, calls0))
            stalled = stalled | ~all_quiescent(st, tl_seg)
            return st, stalled, calls1

        state, stalled, _ = jax.lax.fori_loop(
            0, n_seg, seg_body, (state, jnp.bool_(False), jnp.int32(0))
        )
        overflow = jnp.any(state["scalars"][_SC_OVERFLOW] > 0)
        status = (
            stalled.astype(jnp.int32)
            | (overflow.astype(jnp.int32) << 1)
        )
        return state, status

    return run_all


@functools.lru_cache(maxsize=16)
def _build_run(config: SystemConfig, b: int, bb: int, k: int,
               interpret: bool, snapshots: bool, window: int, n_seg: int,
               max_calls: int, ablate: frozenset = frozenset(),
               gate: bool = True, packed: bool = False):
    """Jitted wrapper around :func:`_make_run` (the raw program is
    cached separately so the fused scheduled runner can embed the
    SAME interval program inside its scan — identity, not equality)."""
    return jax.jit(_make_run(config, b, bb, k, interpret, snapshots,
                             window, n_seg, max_calls, ablate, gate,
                             packed))


@functools.lru_cache(maxsize=16)
def _make_stream_run(config: SystemConfig, b: int, bb: int, k: int,
                     interpret: bool, snapshots: bool, window: int,
                     n_seg: int, max_calls: int,
                     ablate: frozenset = frozenset(),
                     gate: bool = True, packed: bool = False):
    """The HBM-streaming run program: ONE pallas_call drives the whole
    run (fori over trace windows x while-to-quiescence), with the
    windowed trace plane living in HBM (``memory_space=pltpu.ANY``)
    and streamed through a 2-slot VMEM scratch by double-buffered
    ``make_async_copy`` — window i+1 prefetches while window i runs,
    so the copy overlaps the while-to-quiescence loop and only the
    2*window-row scratch (not the whole trace) counts against the
    16 MB VMEM cap.  The phase-D snapshot planes likewise stay in HBM
    and are DMA-staged through VMEM scratch once per run (they must be
    VMEM-resident across cycles — phase D writes them every cycle —
    but their pipelined in/out block copies are gone).  Stall status
    leaves through a per-lane plane so the host keeps its single
    readback."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if b % bb != 0:
        raise ValueError(f"batch {b} not divisible by block {bb}")
    cycle = build_cycle(config, bb, snapshots, ablate, packed)
    n = config.num_procs
    layout, W = _mb_layout(config)
    split_sw = _sharer_words(config) if _split_mode(config) else 0
    fields = _state_fields(W, snapshots, "recv" in layout, split_sw,
                           packed)
    shapes = state_shapes(config, snapshots=True, packed=packed)
    dtypes = state_dtypes(config, snapshots=True, packed=packed)
    slsc = _scalar_layout(config, window)
    outer, inner = -(-k // _GATE), _GATE
    # snapshot planes stream; everything else stays VMEM-resident
    snap_fields = tuple(f for f in fields if f.startswith("snap_"))
    vmem_fields = tuple(f for f in fields if not f.startswith("snap_"))
    nst, nsnap = len(vmem_fields), len(snap_fields)

    def active_count(st, tl):
        # integer quiescence check (bool-vector reductions are not
        # Mosaic-lowerable): outstanding instrs + waiting + queued
        # messages + deferred outbox slots
        nswv = st["nsw"]
        pcv = (nswv >> slsc["off_pc"]) & slsc["pc_mask"]
        return (
            jnp.sum(jnp.maximum(tl - pcv, 0))
            + jnp.sum((nswv >> slsc["off_wait"]) & 1)
            + jnp.sum(nswv & slsc["count_mask"])
            + jnp.sum(deferred_valid(config, st))
        )

    def kernel(*refs):
        tr_len_ref = refs[0]
        tr_hbm = refs[1]
        in_vmem = refs[2:2 + nst]
        in_snap = refs[2 + nst:2 + nst + nsnap]
        o = 2 + nst + nsnap
        out_vmem = refs[o:o + nst]
        out_snap = refs[o + nst:o + nst + nsnap]
        status_ref = refs[o + nst + nsnap]
        sc = o + nst + nsnap + 1
        tr_buf, tr_sem = refs[sc], refs[sc + 1]
        snap_bufs = refs[sc + 2:sc + 2 + nsnap]
        snap_sem = refs[sc + 2 + nsnap] if snapshots else None

        i = pl.program_id(0)

        def lane_block(ref):
            idx = (slice(None),) * (len(ref.shape) - 1)
            return ref.at[idx + (pl.ds(i * bb, bb),)]

        def tr_dma(slot, seg):
            return pltpu.make_async_copy(
                tr_hbm.at[
                    :, pl.ds(seg * window, window), pl.ds(i * bb, bb)
                ],
                tr_buf.at[slot],
                tr_sem.at[slot],
            )

        tr_dma(0, 0).start()
        for j in range(nsnap):
            pltpu.make_async_copy(
                lane_block(in_snap[j]), snap_bufs[j], snap_sem.at[j]
            ).start()

        s = {f: in_vmem[j][:] for j, f in enumerate(vmem_fields)}
        tl_full = tr_len_ref[:]

        for j in range(nsnap):
            pltpu.make_async_copy(
                lane_block(in_snap[j]), snap_bufs[j], snap_sem.at[j]
            ).wait()
        s.update(
            {f: snap_bufs[j][:] for j, f in enumerate(snap_fields)}
        )

        def seg_body(si, carry):
            st, stalled, calls0 = carry
            slot = jax.lax.rem(si, 2)
            tr_dma(slot, si).wait()

            @pl.when(si + 1 < n_seg)
            def _():
                tr_dma(1 - slot, si + 1).start()

            # the window plane and its lengths are CLOSED OVER by the
            # burst loops, not threaded through their carries: a loop
            # invariant costs one live copy, where a carried operand
            # would double again under the gate's lax.cond
            trw = jax.lax.cond(
                slot == 0, lambda: tr_buf[0], lambda: tr_buf[1]
            )
            tl_seg = jnp.clip(tl_full - si * window, 0, window)
            # window base: every lane is quiescent here (enforced via
            # the stalled flag), so the pc restart is a field clear
            st = {
                **st,
                "nsw": st["nsw"]
                & ~(slsc["pc_mask"] << slsc["off_pc"]),
            }

            def cyc(x):
                out = cycle({**x, "tr": trw, "tr_len": tl_seg})
                return {f: out[f] for f in fields}

            def run_gate(st2):
                return jax.lax.fori_loop(
                    0, inner, lambda _, x: cyc(x), st2
                )

            def k_cycles(st2):
                if not gate:
                    return jax.lax.fori_loop(
                        0, k, lambda _, x: cyc(x), st2
                    )

                def gbody(_, x):
                    return jax.lax.cond(
                        active_count(x, tl_seg) == 0,
                        lambda y: y, run_gate, x,
                    )

                return jax.lax.fori_loop(0, outer, gbody, st2)

            def cond(c):
                st2, calls = c
                return (active_count(st2, tl_seg) != 0) & (
                    calls < max_calls
                )

            def body(c):
                st2, calls = c
                return k_cycles(st2), calls + 1

            # the call counter carries ACROSS windows so max_calls
            # bounds the whole run, not each window separately
            (st, calls1) = jax.lax.while_loop(cond, body, (st, calls0))
            stalled = stalled | jnp.where(
                active_count(st, tl_seg) != 0, 1, 0
            )
            return st, stalled, calls1

        s, stalled, _ = jax.lax.fori_loop(
            0, n_seg, seg_body, (s, jnp.int32(0), jnp.int32(0))
        )

        for j, f in enumerate(vmem_fields):
            out_vmem[j][:] = s[f]
        for j, f in enumerate(snap_fields):
            snap_bufs[j][:] = s[f]
        for j in range(nsnap):
            pltpu.make_async_copy(
                snap_bufs[j], lane_block(out_snap[j]), snap_sem.at[j]
            ).start()
        for j in range(nsnap):
            pltpu.make_async_copy(
                snap_bufs[j], lane_block(out_snap[j]), snap_sem.at[j]
            ).wait()
        status_ref[:] = jnp.zeros((1, bb), I32) + stalled

    def block_spec(prefix_shape):
        shape = tuple(prefix_shape) + (bb,)
        nd = len(shape)
        return pl.BlockSpec(
            shape,
            (lambda i, _nd=nd: (0,) * (_nd - 1) + (i,)),
            memory_space=pltpu.VMEM,
        )

    hbm_spec = pl.BlockSpec(memory_space=pltpu.ANY)

    in_specs = (
        [block_spec((n,)), hbm_spec]
        + [block_spec(shapes[f]) for f in vmem_fields]
        + [hbm_spec] * nsnap
    )
    out_specs = (
        [block_spec(shapes[f]) for f in vmem_fields]
        + [hbm_spec] * nsnap
        + [block_spec((1,))]
    )
    out_shape = (
        [
            jax.ShapeDtypeStruct(tuple(shapes[f]) + (b,), dtypes[f])
            for f in vmem_fields
        ]
        + [
            jax.ShapeDtypeStruct(tuple(shapes[f]) + (b,), dtypes[f])
            for f in snap_fields
        ]
        + [jax.ShapeDtypeStruct((1, b), jnp.int32)]
    )
    aliases = {2 + j: j for j in range(nst + nsnap)}
    scratch_shapes = [
        pltpu.VMEM((2, n, window, bb), jnp.int32),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    if snapshots:
        scratch_shapes += [
            pltpu.VMEM(tuple(shapes[f]) + (bb,), dtypes[f])
            for f in snap_fields
        ]
        scratch_shapes += [pltpu.SemaphoreType.DMA((nsnap,))]

    fn = pl.pallas_call(
        kernel,
        grid=(b // bb,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )

    def run_all(state, tr_full, tr_len_full):
        outs = fn(
            tr_len_full,
            tr_full,
            *[state[f] for f in vmem_fields],
            *[state[f] for f in snap_fields],
        )
        new_state = dict(zip(vmem_fields, outs[:nst]))
        new_state.update(zip(snap_fields, outs[nst:nst + nsnap]))
        stalled = jnp.any(outs[-1] != 0)
        overflow = jnp.any(new_state["scalars"][_SC_OVERFLOW] > 0)
        status = (
            stalled.astype(jnp.int32)
            | (overflow.astype(jnp.int32) << 1)
        )
        return new_state, status

    return run_all


@functools.lru_cache(maxsize=16)
def _build_stream_run(config: SystemConfig, b: int, bb: int, k: int,
                      interpret: bool, snapshots: bool, window: int,
                      n_seg: int, max_calls: int,
                      ablate: frozenset = frozenset(),
                      gate: bool = True, packed: bool = False):
    """Jitted wrapper around :func:`_make_stream_run` (the raw program
    is cached separately so the fused scheduled runner can embed the
    SAME interval program inside its scan — identity, not equality)."""
    return jax.jit(_make_stream_run(config, b, bb, k, interpret,
                                    snapshots, window, n_seg, max_calls,
                                    ablate, gate, packed))


@functools.lru_cache(maxsize=16)
def _make_fused_run(config: SystemConfig, r: int, bsys: int, bb: int,
                    k: int, interpret: bool, window: int, nseg_max: int,
                    max_calls: int, ablate: frozenset = frozenset(),
                    gate: bool = True, stream: bool = True,
                    packed: bool = False):
    """The fused scheduled run: the WHOLE multi-interval scheduled run
    as one traceable program — ``lax.scan`` over the precomputed
    :class:`~hpa2_tpu.ops.schedule.SchedulePlan` rows, with the PR-5
    barrier transform (gather-permute compaction + admission resets)
    applied on-device between intervals.  Each scan step runs the
    EXACT single-interval program (the same cached
    :func:`_make_stream_run`/:func:`_make_run` object the host-barrier
    path jits), so the cycle loop is bit-identical by construction and
    the compaction ops are confined to the barrier step.

    Returns raw (unjitted) ``fused(state, tr_full, tr_len_full, sys,
    seg, perm, reset) -> (state_by_system [..., bsys], status)``:

    - ``state``: initial carried state over the ``r`` resident lanes.
    - ``tr_full``/``tr_len_full``: the FULL packed trace planes over
      all ``bsys`` systems ([n, nseg_max*window, bsys] / [n, bsys]).
    - plan rows, all [n_int, r] int32: ``sys``/``seg`` = system id
      (-1 = idle lane) and starting segment per lane per interval;
      ``perm``/``reset`` = the barrier applied BEFORE that interval.

    Per interval the step gathers each lane's trace window from the
    pre-transposed plane, runs the interval program, and scatters
    every live lane's state to its system column of the result (a
    lane's state only changes while its system is resident, so the
    last scatter holds exactly the harvest-time value; idle lanes
    scatter to a trash column that is dropped).  Dead lanes read a
    clamped (valid) trace window with ``tr_len = 0`` — every trace use
    is eligibility-gated, so the content is inert, exactly as the
    zero-padded windows of the host-barrier path."""
    raw = (_make_stream_run if stream else _make_run)(
        config, r, bb, k, interpret, False, window, 1, max_calls,
        ablate, gate, packed
    )
    n = config.num_procs
    layout, W = _mb_layout(config)
    split_sw = _sharer_words(config) if _split_mode(config) else 0
    fields = _state_fields(W, False, "recv" in layout, split_sw, packed)
    shapes = state_shapes(config, snapshots=False, packed=packed)
    dtypes = state_dtypes(config, snapshots=False, packed=packed)
    init_np = _init_state(config, r, snapshots=False, packed=packed)

    def fused(state, tr_full, tr_len_full, sys, seg, perm, reset):
        init = {f: jnp.asarray(init_np[f]) for f in fields}
        # [n, nseg_max*w, bsys] -> [nseg_max*bsys, n, w]: one gather
        # row per (segment, system), so a lane's window is one
        # dynamic-index take inside the scan
        trf = jnp.transpose(
            tr_full.reshape(n, nseg_max, window, bsys), (1, 3, 0, 2)
        ).reshape(nseg_max * bsys, n, window)
        store = {
            f: jnp.zeros(tuple(shapes[f]) + (bsys + 1,), dtypes[f])
            for f in fields
        }

        def step(carry, xs):
            st, acc, status = carry
            sys_i, seg_i, perm_i, reset_i = xs
            # the PR-5 barrier transform, verbatim: gather-permute
            # compaction, then fresh init at the admitted lanes
            st = {
                f: jnp.where(
                    reset_i != 0, init[f], jnp.take(v, perm_i, axis=-1)
                )
                for f, v in st.items()
            }
            sysc = jnp.clip(sys_i, 0, bsys - 1)
            gidx = jnp.clip(seg_i, 0, nseg_max - 1) * bsys + sysc
            tr_i = jnp.transpose(trf[gidx], (1, 2, 0))
            tl_i = jnp.where(
                sys_i >= 0,
                jnp.clip(
                    tr_len_full[:, sysc] - seg_i[None, :] * window,
                    0, window,
                ),
                0,
            )
            st, s_int = raw(st, tr_i, tl_i)
            tgt = jnp.where(sys_i >= 0, sys_i, bsys)
            acc = {
                f: acc[f].at[..., tgt].set(st[f]) for f in fields
            }
            return (st, acc, status | s_int), None

        (st, store, status), _ = jax.lax.scan(
            step, (state, store, jnp.int32(0)),
            (sys, seg, perm, reset),
        )
        return {f: store[f][..., :bsys] for f in fields}, status

    return fused


@functools.lru_cache(maxsize=16)
def _build_fused_run(config: SystemConfig, r: int, bsys: int, bb: int,
                     k: int, interpret: bool, window: int,
                     nseg_max: int, max_calls: int,
                     ablate: frozenset = frozenset(), gate: bool = True,
                     stream: bool = True, packed: bool = False):
    """Jitted wrapper around :func:`_make_fused_run`."""
    return jax.jit(_make_fused_run(config, r, bsys, bb, k, interpret,
                                   window, nseg_max, max_calls, ablate,
                                   gate, stream, packed))


class PallasEngine:
    """Ensemble engine with VMEM-resident cycles (the fast path).

    Same observable behavior as :class:`BatchJaxEngine` — fixture
    semantics, dump-at-local-completion snapshots, counters — at a
    fraction of the per-cycle cost.  ``interpret=True`` runs the
    kernel in the Pallas interpreter (CPU differential tests).
    ``snapshots=False`` drops the phase-D snapshot planes from VMEM
    (the bench configuration; final state and counters only).

    ``trace_window=w`` runs traces longer than ``w`` as successive
    windows of ``w`` instructions per core, quiescing between windows
    — a legal schedule of the same per-node programs that keeps the
    trace plane (the dominant VMEM tenant) bounded for arbitrarily
    long workloads (the reference caps traces at 32 instructions,
    assignment.c:13; this is the uncapped analog).

    ``stream=True`` (the default) moves the whole run loop inside one
    pallas_call and streams the trace plane from HBM through a 2-slot
    double-buffered VMEM scratch (snapshot planes likewise DMA-staged)
    — the trace no longer counts against the per-block VMEM budget,
    which is what lets block 1024/2048 fit under the 16 MB cap.
    ``stream=False`` keeps the legacy host-composed window loop with
    the fully VMEM-resident per-call kernel.

    ``packed=True`` carries the cache/directory word planes as narrow
    uint8/uint16 split planes (cvalw/cmetaw/dmemw/dmetaw) and widens
    them to the legacy int32 words only inside the cycle body — the
    dominant VMEM tenants shrink ~2x, admitting ~2x the block size at
    the same budget (``analysis vmem --packed``), with bit-exact
    results (the widen/narrow round-trip is lossless by construction).
    Requires cache meta (state + addr tag) and directory meta (state +
    sharer mask) to fit 16 bits; larger geometries raise.

    ``schedule=Schedule(...)`` turns on the occupancy scheduler
    (hpa2_tpu/ops/schedule.py): the run becomes a host loop of
    single-segment intervals of the SAME run program (``n_seg=1``, so
    the cycle-loop body is bit-identical), and at each segment barrier
    finished lanes are harvested, freed lanes are backfilled from an
    admission queue of not-yet-resident systems
    (``schedule.resident < b`` streams the ensemble through the
    device), and under-occupied blocks are gather-compacted so whole
    blocks quiesce and skip.  Per-system results are bit-exact versus
    the unscheduled run — systems are independent along the lane axis
    and every per-system counter (including ``_SC_CYCLE``, which only
    accrues while a lane is active) is schedule-invariant.  Requires
    ``snapshots=False``; ``self.occupancy`` holds the measured
    :class:`~hpa2_tpu.ops.schedule.OccupancyStats` after the run.

    ``Schedule(fused=True)`` (the default) drives the whole scheduled
    run as ONE device program: the exact same interval/barrier
    sequence is precomputed host-side by the
    :func:`~hpa2_tpu.ops.schedule.build_plan` replay and consumed by a
    ``lax.scan`` on-device, so there are ZERO host barriers
    (``self.occupancy.host_barriers``) and exactly one program launch
    — bit-exact vs ``fused=False`` (the PR-5 host-barrier loop) and vs
    unscheduled runs.
    """

    def __init__(
        self,
        config: SystemConfig,
        tr_op: np.ndarray,
        tr_addr: np.ndarray,
        tr_val: np.ndarray,
        tr_len: np.ndarray,
        block: int = 1024,
        cycles_per_call: int = 128,
        interpret: Optional[bool] = None,
        snapshots: bool = True,
        trace_window: Optional[int] = None,
        gate: bool = True,
        stream: bool = True,
        schedule=None,
        packed: bool = False,
        _ablate: frozenset = frozenset(),
    ):
        if config.interconnect.enabled:
            raise ValueError(
                "the Pallas kernel implements the ideal topology only; "
                "use the spec or XLA engines for "
                f"topology={config.interconnect.topology!r}"
            )
        if interpret is None:
            # the Mosaic kernel path needs a TPU; interpret elsewhere
            # (match on the device, not default_backend(): the axon
            # plugin reports platform "axon" for a real TPU chip)
            interpret = not any(
                "tpu" in str(d).lower() for d in jax.devices()
            )
        b, _, t = tr_op.shape
        self.config = config
        self.b = b
        self._interpret_active = interpret
        self._snapshots = snapshots
        self._packed = packed
        if packed:
            packed_plane_dtypes(config)  # raises on unpackable geometry
        self.schedule = schedule
        self.occupancy = None
        if schedule is not None:
            if snapshots:
                raise ValueError(
                    "the occupancy scheduler reorders and reuses lanes;"
                    " dump-at-local-completion snapshots are defined on"
                    " the whole-trace lockstep run — build with"
                    " snapshots=False"
                )
            self._resident = schedule.resident or b
            if not (0 < self._resident <= b):
                raise ValueError(
                    f"schedule.resident={schedule.resident} outside "
                    f"1..{b}"
                )
            # the device carries `resident` lanes, so the grid tiles
            # that lane count, not the full ensemble
            self.block = choose_block(self._resident, block)
        else:
            self._resident = b
            self.block = choose_block(b, block)
        self.cycles_per_call = cycles_per_call

        tr_len = tr_len.astype(np.int32)
        tr_words = _pack_traces(config, tr_op, tr_addr, tr_val, tr_len)
        w = trace_window if trace_window else t
        w = max(1, min(w, t))
        self._window = w
        self._n_seg = -(-t // w)
        if snapshots and self._n_seg > 1:
            raise ValueError(
                "dump-at-local-completion snapshots are defined on the "
                "whole trace; run windowed traces with snapshots=False"
            )
        t_pad = self._n_seg * w
        if t_pad != t:
            tr_words = np.pad(
                tr_words, ((0, 0), (0, t_pad - t), (0, 0))
            )
        tr_len_nb = np.ascontiguousarray(np.moveaxis(tr_len, 0, 1))
        if schedule is not None:
            from hpa2_tpu.ops.schedule import segments_needed

            # host-side copies drive per-interval window assembly
            self._tr_np = tr_words
            self._tr_len_np = tr_len_nb
            self._nseg = segments_needed(tr_len_nb, w)
            self._sched_groups = 1
        self._tr_full = jnp.asarray(tr_words)
        self._tr_len_full = jnp.asarray(tr_len_nb)
        state = _init_state(config, b, snapshots, packed)
        self.state = {f: jnp.asarray(v) for f, v in state.items()}
        # first-window traces, for direct _call users (perf tooling)
        self.traces = {
            "tr": self._tr_full[:, :w, :],
            "tr_len": jnp.clip(self._tr_len_full, 0, w),
        }
        self._ablate = _ablate
        self._interpret = interpret
        self._gate = gate
        self._stream = stream
        self._completed = False
        self._poisoned = False
        self._call = _build_call(
            config, b, self.block, cycles_per_call, interpret,
            snapshots, _ablate, gate, packed
        )

    def _runner(self, max_cycles: int):
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        build = _build_stream_run if self._stream else _build_run
        return build(
            self.config, self.b, self.block, self.cycles_per_call,
            self._interpret, self._snapshots, self._window, self._n_seg,
            max_calls, self._ablate, self._gate, self._packed,
        )

    # -- occupancy scheduling (hpa2_tpu/ops/schedule.py) --------------

    def _interval_runner(self, max_cycles: int):
        """One scheduling interval = the UNSCHEDULED run program built
        at ``n_seg=1`` over the resident lanes — the lru_cache returns
        the identical object an unscheduled single-segment engine gets,
        so scheduling provably adds zero ops to the cycle loop
        (tests/test_occupancy.py pins the identity)."""
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        build = _build_stream_run if self._stream else _build_run
        return build(
            self.config, self._resident, self.block,
            self.cycles_per_call, self._interpret, False, self._window,
            1, max_calls, self._ablate, self._gate, self._packed,
        )

    def _sched_put(self, x):
        """Operand placement hook for the scheduled path (the sharded
        subclass pins the lane axis to the mesh)."""
        return x

    def _barrier_fn(self):
        """Jitted segment-barrier transform: gather-permute every
        carried plane along the lane axis, then reset newly admitted
        lanes to the (system-independent) init state.  This is the ONLY
        program that touches lanes outside the run kernel — compaction
        ops live here, never in the cycle loop."""
        cached = getattr(self, "_barrier_cache", None)
        if cached is not None:
            return cached
        init = {
            f: jnp.asarray(v)
            for f, v in _init_state(
                self.config, self._resident, snapshots=False,
                packed=self._packed,
            ).items()
        }

        @jax.jit
        def apply(state, perm, reset):
            out = {}
            for f, v in state.items():
                g = jnp.take(v, perm, axis=-1)
                out[f] = jnp.where(reset, init[f], g)
            return out

        self._barrier_cache = apply
        return apply

    def _fused_runner(self, max_cycles: int):
        """The whole-plan device program (the sharded subclass wraps
        it in shard_map over per-shard plan slices)."""
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        return _build_fused_run(
            self.config, self._resident, self.b, self.block,
            self.cycles_per_call, self._interpret, self._window,
            self._n_seg, max_calls, self._ablate, self._gate,
            self._stream, self._packed,
        )

    def _fused_plan_arrays(self, plan):
        """Plan rows as device operands (the sharded subclass localizes
        system/lane indices to the shard-local frame here)."""
        return tuple(
            jnp.asarray(x)
            for x in (plan.sys, plan.seg, plan.perm, plan.reset)
        )

    def _run_scheduled_fused(self, max_cycles: int) -> "PallasEngine":
        """The fused scheduled run: ONE device program consumes the
        whole precomputed plan — zero host barriers.  Bit-exact vs the
        host-barrier loop (the scan step applies the identical barrier
        transform and runs the identical interval program)."""
        from hpa2_tpu.ops.schedule import build_plan

        plan = build_plan(
            self._nseg, resident=self._resident, block=self.block,
            groups=self._sched_groups,
            threshold=self.schedule.threshold,
            policy=self.schedule.policy,
            deadline=self.schedule.deadlines,
            tenant=self.schedule.tenants,
            tenant_weights=self.schedule.tenant_weights,
        )
        runner = self._fused_runner(max_cycles)
        state = {
            f: self._sched_put(jnp.asarray(v))
            for f, v in _init_state(
                self.config, self._resident, snapshots=False,
                packed=self._packed,
            ).items()
        }
        new_state, status = runner(
            state, self._tr_full, self._tr_len_full,
            *self._fused_plan_arrays(plan),
        )
        self.state = new_state
        self._check_status(int(status), max_cycles)
        self.occupancy = plan.stats
        self._completed = True
        return self

    def _run_scheduled(self, max_cycles: int) -> "PallasEngine":
        from hpa2_tpu.ops.schedule import LaneScheduler

        cfg = self.config
        r, w, n = self._resident, self._window, cfg.num_procs
        sched = LaneScheduler(
            self._nseg, resident=r, block=self.block,
            groups=self._sched_groups,
            threshold=self.schedule.threshold,
            policy=self.schedule.policy,
            deadline=self.schedule.deadlines,
            tenant=self.schedule.tenants,
            tenant_weights=self.schedule.tenant_weights,
        )
        runner = self._interval_runner(max_cycles)
        fields = list(self.state.keys())
        shapes = state_shapes(cfg, snapshots=False, packed=self._packed)
        dtypes = state_dtypes(cfg, snapshots=False, packed=self._packed)
        store = {
            f: np.zeros(tuple(shapes[f]) + (self.b,), dtypes[f])
            for f in fields
        }
        state = {
            f: self._sched_put(jnp.asarray(v))
            for f, v in _init_state(
                cfg, r, snapshots=False, packed=self._packed
            ).items()
        }
        tr_np, tl_np = self._tr_np, self._tr_len_np
        arange_w = np.arange(w)
        while not sched.done():
            live = sched.begin_interval()
            tr_int = np.zeros((n, w, r), np.int32)
            tl_int = np.zeros((n, r), np.int32)
            lanes = np.nonzero(live)[0]
            if len(lanes):
                sys_ = sched.lane_sys[lanes]
                base = sched.lane_seg[lanes] * w
                idx = np.broadcast_to(
                    base[None, None, :] + arange_w[None, :, None],
                    (n, w, len(lanes)),
                )
                tr_int[:, :, lanes] = np.take_along_axis(
                    tr_np[:, :, sys_], idx, axis=1
                )
                tl_int[:, lanes] = np.clip(
                    tl_np[:, sys_] - base[None, :], 0, w
                )
            state, status = runner(
                state,
                self._sched_put(jnp.asarray(tr_int)),
                self._sched_put(jnp.asarray(tl_int)),
            )
            self._check_status(int(status), max_cycles)
            plan = sched.end_interval()
            if plan.finished:
                lane_idx = jnp.asarray(
                    np.array([l for l, _ in plan.finished])
                )
                cols = {
                    f: np.asarray(jnp.take(state[f], lane_idx, axis=-1))
                    for f in fields
                }
                for i, (_, s) in enumerate(plan.finished):
                    for f in fields:
                        store[f][..., s] = cols[f][..., i]
            if not plan.trivial:
                perm = (
                    plan.perm
                    if plan.perm is not None
                    else np.arange(r, dtype=np.int64)
                )
                reset = np.zeros(r, bool)
                for lane, _ in plan.admitted:
                    reset[lane] = True
                state = self._barrier_fn()(
                    state, jnp.asarray(perm), jnp.asarray(reset)
                )
                state = {
                    f: self._sched_put(v) for f, v in state.items()
                }
        # reconstruct the full-ensemble planes in system order so every
        # readback accessor (dumps, counters, stats) works unchanged —
        # the lane->system permutation is inverted here
        self.state = {
            f: self._sched_put(jnp.asarray(store[f])) for f in fields
        }
        self.occupancy = sched.stats.set_mode(fused=False)
        self._completed = True
        return self

    def lower_run(self, max_cycles: int = 1_000_000):
        """Lower (without executing) the whole-run program — the
        compile-gate entry point: ``lower_run().compile()`` on a TPU
        reports the kernel's real VMEM footprint."""
        return self._runner(max_cycles).lower(
            self.state, self._tr_full, self._tr_len_full
        )

    def _check_status(self, status: int, max_cycles: int) -> None:
        if status:
            self._poisoned = True
        if status & 2:
            raise StallError(
                "internal invariant violated: mailbox overflow despite "
                "backpressure"
            )
        if status & 1:
            raise StallError(
                f"no quiescence within ~{max_cycles} cycles over the "
                "whole run (livelock? use Semantics.robust(); raise "
                "max_cycles for long windowed workloads)"
            )

    def run(self, max_cycles: int = 1_000_000) -> "PallasEngine":
        # the on-device driver resets pc at every window base, so a
        # run is not resumable: completed runs are a no-op, stalled
        # runs leave in-flight state that only a rebuild can clear
        if self._completed:
            return self
        if self._poisoned:
            raise StallError(
                "engine state is mid-flight after a failed run; "
                "rebuild the engine to retry"
            )
        if self.schedule is not None:
            if self.schedule.fused:
                return self._run_scheduled_fused(max_cycles)
            return self._run_scheduled(max_cycles)
        runner = self._runner(max_cycles)
        state, status = runner(
            self.state, self._tr_full, self._tr_len_full
        )
        self.state = state
        self._check_status(int(status), max_cycles)  # single host sync
        self._completed = True
        return self

    # -- readback -----------------------------------------------------

    def _dump(self, cachew, dirw, sys_idx: int,
              dirs=None) -> List[NodeDump]:
        return decode_dumps(self.config, cachew, dirw, sys_idx, dirs)

    def _split_planes(self, prefix: str):
        if not _split_mode(self.config):
            return None
        return [
            np.asarray(self.state[f"{prefix}{w}"])
            for w in range(_sharer_words(self.config))
        ]

    def _word_planes(self, prefix: str = ""):
        """(cachew, dirw) in the legacy int32 word encoding — packed
        engines rebuild them from the narrow planes at readback."""
        if self._packed:
            return _join_word_planes_np(
                np.asarray(self.state[f"{prefix}cvalw"]),
                np.asarray(self.state[f"{prefix}cmetaw"]),
                np.asarray(self.state[f"{prefix}dmemw"]),
                np.asarray(self.state[f"{prefix}dmetaw"]),
            )
        return (
            np.asarray(self.state[f"{prefix}cachew"]),
            np.asarray(self.state[f"{prefix}dirw"]),
        )

    def system_snapshots(self, sys_idx: int) -> List[NodeDump]:
        if not self._snapshots:
            raise ValueError(
                "engine built with snapshots=False has no phase-D state"
            )
        cachew, dirw = self._word_planes("snap_")
        return self._dump(
            cachew, dirw, sys_idx, dirs=self._split_planes("snap_dirs")
        )

    def system_final_dumps(self, sys_idx: int) -> List[NodeDump]:
        cachew, dirw = self._word_planes()
        return self._dump(
            cachew, dirw, sys_idx, dirs=self._split_planes("dirs")
        )

    # single-system aliases matching the other engines' interface
    # (the CLI `run --backend pallas` path)

    def snapshots(self) -> List[NodeDump]:
        if self.b != 1:
            raise ValueError(
                "snapshots() is the batch-1 interface; use "
                "system_snapshots(b) on ensembles"
            )
        return self.system_snapshots(0)

    def final_dumps(self) -> List[NodeDump]:
        if self.b != 1:
            raise ValueError(
                "final_dumps() is the batch-1 interface; use "
                "system_final_dumps(b) on ensembles"
            )
        return self.system_final_dumps(0)

    @property
    def instructions(self) -> int:
        return int(np.sum(np.asarray(self.state["scalars"][_SC_INSTR])))

    @property
    def messages(self) -> int:
        return int(np.sum(np.asarray(self.state["scalars"][_SC_MSGS])))

    @property
    def cycle(self) -> int:
        """Max per-system cycle count (lockstep wall cycles)."""
        return int(np.max(np.asarray(self.state["scalars"][_SC_CYCLE])))

    def stats(self) -> dict:
        from hpa2_tpu.ops.engine import format_stats

        sc = np.asarray(self.state["scalars"])
        return format_stats(
            {
                "instructions": int(sc[_SC_INSTR].sum()),
                "msgs_total": int(sc[_SC_MSGS].sum()),
                "read_hits": int(sc[_SC_RH].sum()),
                "read_misses": int(sc[_SC_RM].sum()),
                "write_hits": int(sc[_SC_WH].sum()),
                "write_misses": int(sc[_SC_WM].sum()),
                "evictions": int(sc[_SC_EV].sum()),
                "invalidations": int(sc[_SC_INV].sum()),
            },
            np.asarray(self.state["msg_counts"]).sum(axis=1),
        )


# ---------------------------------------------------------------------------
# Resident-lane serving session (hpa2_tpu/serving/): the always-on
# analog of the scheduled run.  The engine classes above run ONE
# ensemble to completion; a session keeps a fixed set of resident
# lanes alive indefinitely and lets the serving loop drive intervals,
# barriers, and per-lane harvests one step at a time, so ingest and
# readback overlap device execution.


@functools.lru_cache(maxsize=16)
def _build_session_run(config: SystemConfig, r: int, bb: int, k: int,
                       interpret: bool, window: int, max_calls: int,
                       ablate: frozenset = frozenset(),
                       gate: bool = True, stream: bool = True,
                       packed: bool = False):
    """The single-interval program of the scheduled path (``n_seg=1``),
    jitted with the carried state donated (device backends only — the
    interpreter has no donation), so the resident planes are reused
    across every interval of an arbitrarily long serving session
    instead of reallocated."""
    raw = (_make_stream_run if stream else _make_run)(
        config, r, bb, k, interpret, False, window, 1, max_calls,
        ablate, gate, packed
    )
    return jax.jit(raw, donate_argnums=() if interpret else (0,))


class PallasLaneSession:
    """Resident-lane session for the Pallas fast path.

    Holds ``resident`` lanes of carried state at fixed shapes forever;
    the serving loop (:mod:`hpa2_tpu.serving.loop`) drives one
    trace-window segment at a time:

    1. ``tr, tl = stage(tr_np, tl_np)`` — ``device_put`` the next
       interval's host-assembled trace windows (ahead of the barrier).
    2. ``status = advance(tr, tl)`` — dispatch the interval program
       (async; returns a device scalar, NOT synced).
    3. ``cols = harvest(lane)`` — async gather of a retiring lane's
       state column; must precede the barrier, whose donation retires
       the planes the gather reads.
    4. ``barrier(perm, reset)`` — the PR-5 compaction/admission
       transform.
    5. ``check(status)`` — sync and raise on stall/overflow, typically
       one interval behind ``advance`` so the host stays ahead.

    Every jitted program here is shape-stable, so after the first
    interval the session never compiles again — ``compile_counts()``
    exposes the jit cache sizes for the serving loop's zero-recompile
    guard.
    """

    def __init__(
        self,
        config: SystemConfig,
        resident: int,
        window: int,
        *,
        block: int = 1024,
        cycles_per_call: int = 128,
        interpret: Optional[bool] = None,
        gate: bool = True,
        stream: bool = True,
        packed: bool = False,
        max_cycles: int = 1_000_000,
    ):
        if config.interconnect.enabled:
            raise ValueError(
                "the Pallas kernel implements the ideal topology only; "
                "use the spec or XLA engines for "
                f"topology={config.interconnect.topology!r}"
            )
        if interpret is None:
            interpret = not any(
                "tpu" in str(d).lower() for d in jax.devices()
            )
        _check_geometry(config)
        if packed:
            packed_plane_dtypes(config)
        self.config = config
        self.r = int(resident)
        self.window = int(window)
        self.block = choose_block(self.r, block)
        self.cycles_per_call = cycles_per_call
        self.max_cycles = max_cycles
        self._interpret = interpret
        self._gate = gate
        self._stream = stream
        self._packed = packed
        self._runner = self._build_runner()
        init = _init_state(config, self.r, snapshots=False, packed=packed)
        self._init = {f: jnp.asarray(v) for f, v in init.items()}
        self.fields = list(init.keys())
        self.state = {f: self._put(v) for f, v in self._init.items()}

        init_ref = self._init
        donate = () if interpret or not self._donate_barrier() else (0,)

        @functools.partial(jax.jit, donate_argnums=donate)
        def _barrier(state, perm, reset):
            return {
                f: jnp.where(
                    reset, init_ref[f], jnp.take(v, perm, axis=-1)
                )
                for f, v in state.items()
            }

        @jax.jit
        def _take_lane(state, lane):
            return {
                f: jax.lax.dynamic_index_in_dim(
                    v, lane, axis=v.ndim - 1, keepdims=True
                )
                for f, v in state.items()
            }

        self._barrier_jit = _barrier
        self._take_lane = _take_lane

    # -- backend hooks (the sharded subclass overrides) ----------------

    def _build_runner(self):
        max_calls = max(1, -(-self.max_cycles // self.cycles_per_call))
        return _build_session_run(
            self.config, self.r, self.block, self.cycles_per_call,
            self._interpret, self.window, max_calls, frozenset(),
            self._gate, self._stream, self._packed,
        )

    def _put(self, x):
        return jnp.asarray(x)

    def _donate_barrier(self) -> bool:
        return True

    # -- serving protocol ----------------------------------------------

    def stage(self, tr_int: np.ndarray, tl_int: np.ndarray):
        """Ship the next interval's assembled ``[n, w, r]`` trace plane
        and ``[n, r]`` window lengths to the device (async)."""
        return (
            self._put(jnp.asarray(tr_int)),
            self._put(jnp.asarray(tl_int)),
        )

    def advance(self, tr, tl):
        """Run every resident lane one trace-window segment (async
        dispatch; the carried state is donated on device backends)."""
        self.state, status = self._runner(self.state, tr, tl)
        return status

    def harvest(self, lane: int):
        """Async gather of one lane's state columns (leaves ``[..., 1]``).
        Call after :meth:`advance` and before :meth:`barrier`."""
        return self._take_lane(self.state, jnp.int32(lane))

    def barrier(self, perm: np.ndarray, reset: np.ndarray) -> None:
        """Apply a :class:`~hpa2_tpu.ops.schedule.BarrierPlan`'s lane
        permutation + admission resets to the carried state."""
        st = self._barrier_jit(
            self.state,
            self._put(jnp.asarray(perm)),
            self._put(jnp.asarray(reset)),
        )
        self.state = {f: self._put(v) for f, v in st.items()}

    def check(self, status) -> None:
        """Sync on an interval's status word; raises on stall/overflow
        exactly like the batch engines."""
        status = int(status)
        if status & 2:
            raise StallError(
                "internal invariant violated: mailbox overflow despite "
                "backpressure"
            )
        if status & 1:
            raise StallError(
                f"no quiescence within ~{self.max_cycles} cycles in one "
                "serving interval (livelock? use Semantics.robust())"
            )

    def compile_counts(self) -> dict:
        """Jit-cache sizes of every device program the session owns —
        the serving loop's zero-recompile guard reads this after
        warmup and again at shutdown."""
        return {
            "runner": int(self._runner._cache_size()),
            "barrier": int(self._barrier_jit._cache_size()),
            "take_lane": int(self._take_lane._cache_size()),
        }

    # -- readback ------------------------------------------------------

    def _lane_word_planes(self, cols):
        npc = {f: np.asarray(v) for f, v in cols.items()}
        if self._packed:
            cachew, dirw = _join_word_planes_np(
                npc["cvalw"], npc["cmetaw"], npc["dmemw"], npc["dmetaw"]
            )
        else:
            cachew, dirw = npc["cachew"], npc["dirw"]
        dirs = None
        if _split_mode(self.config):
            dirs = [
                npc[f"dirs{w}"]
                for w in range(_sharer_words(self.config))
            ]
        return cachew, dirw, dirs

    def dumps_of(self, cols) -> List[NodeDump]:
        """Decode a harvested lane column into per-node dump records —
        identical bytes to ``system_final_dumps`` of a one-shot run."""
        cachew, dirw, dirs = self._lane_word_planes(cols)
        return decode_dumps(self.config, cachew, dirw, 0, dirs)

    def counters_of(self, cols) -> dict:
        """The retiring job's scalar counters."""
        sc = np.asarray(cols["scalars"])[:, 0]
        return {
            "instructions": int(sc[_SC_INSTR]),
            "cycles": int(sc[_SC_CYCLE]),
            "messages": int(sc[_SC_MSGS]),
        }
