"""VMEM-resident Pallas engine: the TPU-native fast path.

The XLA ``lax.while_loop`` engine (ops/step.py) round-trips the whole
simulator state through HBM every cycle — the measured per-cycle floor
is HBM traffic + fusion overhead.  This engine runs ``K`` lockstep
cycles per ``pallas_call`` with all state resident in VMEM, so HBM is
touched once per K cycles instead of twice per cycle.

Layout: every array carries the ensemble axis **last** so it maps onto
TPU vector lanes (blocks of ``BB`` systems per grid step), and the
per-system structure (nodes, cache/memory/queue slots) lives in
sublanes:

    cache_*   [N, C, B]      mem/dir_* [N, M, B]
    mb        [N, F, cap, B] (packed message fields, head at slot 0)
    tr_*      [N, T, B]      scalars/counters [SC, B] rows

Semantics are *identical* to ops/step.py (fixture semantics + optional
NACK robustness, SURVEY.md §6.2/§6.3): the cycle body below is a
re-lowering of the same spec — phase A handle-one-message, phase B
issue, phase C deterministic delivery in (phase, sender, slot) order,
phase D dump-at-local-completion snapshots.  Differential tests gate
it against the spec engine and the XLA engine.

Restrictions: ``num_procs <= 32`` (single sharer word), no replay mode
(fixture replays run on the XLA/spec engines), ``5 * num_procs`` send
candidates must fit the mailbox capacity check as usual.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import CacheState, DirState, MsgType
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.utils.dump import NodeDump

I32 = jnp.int32
U32 = jnp.uint32

_M = int(CacheState.MODIFIED)
_E = int(CacheState.EXCLUSIVE)
_S = int(CacheState.SHARED)
_I = int(CacheState.INVALID)
_EM = int(DirState.EM)
_DS = int(DirState.S)
_DU = int(DirState.U)

_NO_MSG = -1
_INVALID_ADDR = -1

# packed mailbox field rows (mb[:, row, slot, :])
_F_TYPE, _F_SENDER, _F_ADDR, _F_VALUE, _F_SECOND, _F_SHARERS = range(6)
_NFIELD = 6

# deferred-send outbox rows (ob[:, row, slot, :]): the mailbox rows
# plus the receiver; slots are the candidate grid [A0, A1, AINV, B0,
# B1].  Slot 2 (AINV) keeps the *remaining* INV delivery mask in its
# SHARERS row.  A node with any valid slot is blocked (capacity
# backpressure; mirrors ops/step.py and the spec engine).
_OB_RECV = _NFIELD
_OB_NROWS = _NFIELD + 1
_NSLOTS = 5

# scalar counter rows (scalars[row, :])
(_SC_CYCLE, _SC_INSTR, _SC_MSGS, _SC_OVERFLOW, _SC_RH, _SC_RM,
 _SC_WH, _SC_WM, _SC_EV, _SC_INV) = range(10)
_NSCALAR = 10

_NTYPES = len(MsgType)

#: carried state field names, in kernel argument order
STATE_FIELDS = (
    "cache_addr", "cache_val", "cache_state",
    "mem", "dir_state", "dir_sharers",
    "mb", "mb_count", "pc", "waiting", "pending_write",
    "ob", "ob_valid",
    "snap_taken", "snap_mem", "snap_dir_state", "snap_dir_sharers",
    "snap_cache_addr", "snap_cache_val", "snap_cache_state",
    "scalars", "msg_counts",
)
TRACE_FIELDS = ("tr_op", "tr_addr", "tr_val", "tr_len")


def _popcount(x):
    """popcount on int32 bit patterns (SWAR; Mosaic-safe)."""
    u = x.astype(U32)
    u = u - ((u >> 1) & U32(0x55555555))
    u = (u & U32(0x33333333)) + ((u >> 2) & U32(0x33333333))
    u = (u + (u >> 4)) & U32(0x0F0F0F0F)
    return ((u * U32(0x01010101)) >> 24).astype(I32)


def _find_owner(x):
    """Lowest set bit index of an int32 mask; -1 when empty
    (reference findOwner, assignment.c:98-105)."""
    u = x.astype(U32)
    lsb = u & (U32(0) - u)
    pos = _popcount((lsb - U32(1)).astype(I32))
    return jnp.where(u == 0, I32(-1), pos)


def _bit(proc):
    """One-hot int32 mask for node id(s); negative -> 0."""
    p = jnp.clip(proc, 0, 31)
    return jnp.where(proc >= 0, I32(1) << p, I32(0))


def _test_bit(mask, proc):
    return (mask >> jnp.clip(proc, 0, 31)) & 1 == 1


def build_cycle(config: SystemConfig, bb: int):
    """One lockstep cycle over a block of ``bb`` systems in transposed
    layout.  Pure jnp on a state dict — runs inside the Pallas kernel
    and, for validation, directly under jit/CPU."""
    n, c, m = config.num_procs, config.cache_size, config.mem_size
    cap = config.msg_buffer_size
    sem = config.semantics
    if n > 32:
        raise ValueError("pallas engine supports num_procs <= 32")
    if sem.overloaded_evict_shared_notify:
        raise ValueError("pallas engine implements fixture semantics only")
    nack = sem.intervention_miss_policy == "nack"

    def cycle(s: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        s = dict(s)
        # iotas are built inside the traced body (a pallas kernel may
        # not capture array constants from the closure)
        iota_n = jax.lax.broadcasted_iota(I32, (n, bb), 0)
        iota_c = jax.lax.broadcasted_iota(I32, (n, c, bb), 1)
        iota_m = jax.lax.broadcasted_iota(I32, (n, m, bb), 1)
        iota_cap = jax.lax.broadcasted_iota(I32, (n, cap, bb), 1)
        iota_t = jax.lax.broadcasted_iota(I32, (_NTYPES, bb), 0)

        def read_c(arr, idx):  # [N,C,B] by [N,B] -> [N,B]
            return jnp.sum(
                jnp.where(iota_c == idx[:, None, :], arr, 0), axis=1
            )

        def read_m(arr, idx):
            return jnp.sum(
                jnp.where(iota_m == idx[:, None, :], arr, 0), axis=1
            )

        def write_c(arr, idx, mask, val):
            hot = (iota_c == idx[:, None, :]) & mask[:, None, :]
            return jnp.where(hot, val[:, None, :], arr)

        def write_m(arr, idx, mask, val):
            hot = (iota_m == idx[:, None, :]) & mask[:, None, :]
            return jnp.where(hot, val[:, None, :], arr)
        # nodes with deferred sends are blocked (no handle, no issue)
        blocked = jnp.sum(s["ob_valid"], axis=1) > 0        # [N, B]

        # ===== phase A: handle one message per node ==================
        has_msg = (s["mb_count"] > 0) & ~blocked
        head = s["mb"][:, :, 0, :]                       # [N, F, B]
        mt = jnp.where(has_msg, head[:, _F_TYPE, :], _NO_MSG)
        snd = head[:, _F_SENDER, :]
        a = jnp.maximum(head[:, _F_ADDR, :], 0)
        v = head[:, _F_VALUE, :]
        sr = head[:, _F_SECOND, :]
        msh = head[:, _F_SHARERS, :]

        rolled = jnp.concatenate(
            [s["mb"][:, :, 1:, :], s["mb"][:, :, :1, :]], axis=2
        )
        qdata = jnp.where(has_msg[:, None, None, :], rolled, s["mb"])
        count2 = s["mb_count"] - has_msg.astype(I32)

        home = a // m
        blk = a % m
        ci = a % c
        is_home = iota_n == home
        is_second = iota_n == sr

        line_addr = read_c(s["cache_addr"], ci)
        line_val = read_c(s["cache_val"], ci)
        line_state = read_c(s["cache_state"], ci)
        ds = read_m(s["dir_state"], blk)
        dsh = read_m(s["dir_sharers"], blk)
        mem_blk = read_m(s["mem"], blk)
        pw = s["pending_write"]

        line_match = line_addr == a
        line_me = (line_state == _M) | (line_state == _E)
        owner = _find_owner(dsh)
        owner_is_snd = owner == snd
        snd_bit = _bit(snd)

        zero = jnp.zeros((n, bb), dtype=I32)
        false = jnp.zeros((n, bb), dtype=bool)

        def slot():
            return {
                "valid": false, "recv": zero, "type": zero, "addr": zero,
                "value": zero, "second": jnp.full((n, bb), -1, I32),
                "sharers": zero,
            }

        def put(sl, mask, recv, type_, addr, value=None, sharers=None,
                second=None):
            sl["valid"] = sl["valid"] | mask
            sl["recv"] = jnp.where(mask, recv, sl["recv"])
            sl["type"] = jnp.where(mask, type_, sl["type"])
            sl["addr"] = jnp.where(mask, addr, sl["addr"])
            if value is not None:
                sl["value"] = jnp.where(mask, value, sl["value"])
            if sharers is not None:
                sl["sharers"] = jnp.where(mask, sharers, sl["sharers"])
            if second is not None:
                sl["second"] = jnp.where(mask, second, sl["second"])

        def evict_msg(sl, mask, l_addr, l_val, l_state):
            """handleCacheReplacement (assignment.c:742-773)."""
            vv = mask & (l_addr != _INVALID_ADDR) & (l_state != _I)
            put(
                sl, vv,
                recv=jnp.maximum(l_addr, 0) // m,
                type_=jnp.where(
                    l_state == _M,
                    int(MsgType.EVICT_MODIFIED),
                    int(MsgType.EVICT_SHARED),
                ),
                addr=l_addr,
                value=l_val,
            )
            return vv

        sA0, sA1 = slot(), slot()
        inv_sharers = zero
        inv_addr = zero

        nl_addr, nl_val, nl_state = line_addr, line_val, line_state
        upd_line = false
        nd_state, nd_sharers = ds, dsh
        upd_dir = false
        mem_write = false
        mem_val = mem_blk
        # `waiting` stays i32 (0/1) through the whole cycle: Mosaic
        # cannot lower selects/broadcasts that materialize i1 vectors
        # from scalar bool constants (arith.trunci i8->i1, the
        # BENCH_r03 compile failure), so bool state is never stored or
        # selected — only compared at use sites.
        waiting = s["waiting"]

        def typ(t):
            return mt == int(t)

        # --- READ_REQUEST (assignment.c:188-236) ---------------------
        mk = typ(MsgType.READ_REQUEST) & is_home
        du, dss, dem = ds == _DU, ds == _DS, ds == _EM
        reply_mask = mk & (du | dss | (dem & owner_is_snd))
        excl = du | (dem & owner_is_snd)
        put(sA0, reply_mask, recv=snd, type_=int(MsgType.REPLY_RD),
            addr=a, value=mem_blk,
            sharers=jnp.where(excl, I32(2), I32(0)))
        fwd = mk & dem & ~owner_is_snd
        put(sA0, fwd, recv=owner, type_=int(MsgType.WRITEBACK_INT),
            addr=a, second=snd)
        upd_dir = upd_dir | (mk & (du | dss | fwd))
        nd_state = jnp.where(mk & du, _EM, nd_state)
        nd_state = jnp.where(fwd, _DS, nd_state)
        nd_sharers = jnp.where(mk & du, snd_bit, nd_sharers)
        nd_sharers = jnp.where(
            mk & (dss | fwd), nd_sharers | snd_bit, nd_sharers
        )

        # --- REPLY_RD (assignment.c:238-247) -------------------------
        mk = typ(MsgType.REPLY_RD)
        ev_replyrd = evict_msg(
            sA0, mk & ~line_match, line_addr, line_val, line_state
        )
        upd_line = upd_line | mk
        nl_addr = jnp.where(mk, a, nl_addr)
        nl_val = jnp.where(mk, v, nl_val)
        nl_state = jnp.where(mk, jnp.where(msh == 2, _E, _S), nl_state)
        waiting = jnp.where(mk, 0, waiting)

        # --- WRITEBACK_INT (assignment.c:249-271) --------------------
        mk = typ(MsgType.WRITEBACK_INT)
        ok = mk & line_match & line_me
        put(sA0, ok, recv=home, type_=int(MsgType.FLUSH), addr=a,
            value=line_val, second=sr)
        put(sA1, ok & (sr != home), recv=sr, type_=int(MsgType.FLUSH),
            addr=a, value=line_val, second=sr)
        upd_line = upd_line | ok
        nl_state = jnp.where(ok, _S, nl_state)
        if nack:
            put(sA0, mk & ~(line_match & line_me), recv=home,
                type_=int(MsgType.NACK), addr=a, second=sr)

        # --- FLUSH (assignment.c:273-296) ----------------------------
        mk = typ(MsgType.FLUSH)
        mem_write = mem_write | (mk & is_home)
        mem_val = jnp.where(mk & is_home, v, mem_val)
        rq = mk & is_second
        ev_flush = evict_msg(
            sA0, rq & ~line_match, line_addr, line_val, line_state
        )
        upd_line = upd_line | rq
        nl_addr = jnp.where(rq, a, nl_addr)
        nl_val = jnp.where(rq, v, nl_val)
        nl_state = jnp.where(rq, _S, nl_state)
        waiting = jnp.where(rq, 0, waiting)

        # --- UPGRADE (assignment.c:298-328) --------------------------
        mk = typ(MsgType.UPGRADE) & is_home
        reply_sh = jnp.where(mk & (ds == _DS), dsh & ~snd_bit, 0)
        put(sA0, mk, recv=snd, type_=int(MsgType.REPLY_ID), addr=a,
            sharers=reply_sh)
        upd_dir = upd_dir | mk
        nd_state = jnp.where(mk, _EM, nd_state)
        nd_sharers = jnp.where(mk, snd_bit, nd_sharers)

        # --- REPLY_ID (assignment.c:330-364) -------------------------
        mk = typ(MsgType.REPLY_ID)
        fill = mk & line_match & (line_state != _M)
        upd_line = upd_line | fill
        nl_val = jnp.where(fill, pw, nl_val)
        nl_state = jnp.where(fill, _M, nl_state)
        fan = mk & line_match
        inv_sharers = jnp.where(fan, msh & ~_bit(iota_n), inv_sharers)
        inv_addr = jnp.where(fan, a, inv_addr)
        waiting = jnp.where(mk, 0, waiting)

        # --- INV (assignment.c:366-373) ------------------------------
        mk = typ(MsgType.INV)
        inv_applied = mk & line_match & (
            (line_state == _S) | (line_state == _E)
        )
        upd_line = upd_line | inv_applied
        nl_state = jnp.where(inv_applied, _I, nl_state)

        # --- WRITE_REQUEST (assignment.c:375-435) --------------------
        mk = typ(MsgType.WRITE_REQUEST) & is_home
        if sem.eager_write_request_memory:
            mem_write = mem_write | mk
            mem_val = jnp.where(mk, v, mem_val)
        du, dss, dem = ds == _DU, ds == _DS, ds == _EM
        put(sA0, mk & (du | (dem & owner_is_snd)), recv=snd,
            type_=int(MsgType.REPLY_WR), addr=a)
        put(sA0, mk & dss, recv=snd, type_=int(MsgType.REPLY_ID),
            addr=a, sharers=dsh & ~snd_bit)
        wr_fwd = mk & dem & ~owner_is_snd
        put(sA0, wr_fwd, recv=owner, type_=int(MsgType.WRITEBACK_INV),
            addr=a, second=snd)
        upd_dir = upd_dir | (mk & (du | dss | wr_fwd))
        nd_state = jnp.where(mk & (du | dss), _EM, nd_state)
        nd_sharers = jnp.where(mk & (du | dss | wr_fwd), snd_bit, nd_sharers)

        # --- REPLY_WR (assignment.c:437-449) -------------------------
        mk = typ(MsgType.REPLY_WR)
        upd_line = upd_line | mk
        nl_addr = jnp.where(mk, a, nl_addr)
        nl_val = jnp.where(mk, pw, nl_val)
        nl_state = jnp.where(mk, _M, nl_state)
        waiting = jnp.where(mk, 0, waiting)

        # --- WRITEBACK_INV (assignment.c:451-473) --------------------
        mk = typ(MsgType.WRITEBACK_INV)
        ok = mk & line_match & line_me
        put(sA0, ok, recv=home, type_=int(MsgType.FLUSH_INVACK),
            addr=a, value=line_val, second=sr)
        put(sA1, ok & (sr != home), recv=sr,
            type_=int(MsgType.FLUSH_INVACK), addr=a, value=line_val,
            second=sr)
        upd_line = upd_line | ok
        nl_state = jnp.where(ok, _I, nl_state)
        if nack:
            put(sA0, mk & ~(line_match & line_me), recv=home,
                type_=int(MsgType.NACK), addr=a, sharers=jnp.full_like(zero, 1),
                second=sr)

        # --- FLUSH_INVACK (assignment.c:475-496) ---------------------
        mk = typ(MsgType.FLUSH_INVACK)
        hm = mk & is_home
        mem_write = mem_write | hm
        mem_val = jnp.where(hm, v, mem_val)
        upd_dir = upd_dir | hm
        nd_state = jnp.where(hm, _EM, nd_state)
        nd_sharers = jnp.where(hm, _bit(sr), nd_sharers)
        rq = mk & is_second
        upd_line = upd_line | rq
        nl_addr = jnp.where(rq, a, nl_addr)
        nl_val = jnp.where(
            rq, v if sem.flush_invack_fills_old_value else pw, nl_val
        )
        nl_state = jnp.where(rq, _M, nl_state)
        waiting = jnp.where(rq, 0, waiting)

        # --- EVICT_SHARED home role (assignment.c:498-521) -----------
        mk = typ(MsgType.EVICT_SHARED) & is_home & _test_bit(dsh, snd)
        after = dsh & ~snd_bit
        cnt = _popcount(after)
        upd_dir = upd_dir | mk
        nd_sharers = jnp.where(mk, after, nd_sharers)
        nd_state = jnp.where(mk & (cnt == 0), _DU, nd_state)
        upg = mk & (cnt == 1) & (ds == _DS)
        nd_state = jnp.where(upg, _EM, nd_state)
        put(sA0, upg, recv=_find_owner(after),
            type_=int(MsgType.UPGRADE_NOTIFY), addr=a)

        # --- UPGRADE_NOTIFY (fixture semantics; spec_engine) ---------
        mk = typ(MsgType.UPGRADE_NOTIFY) & (snd == home)
        hit_un = mk & line_match & (line_state == _S)
        upd_line = upd_line | hit_un
        nl_state = jnp.where(hit_un, _E, nl_state)

        # --- EVICT_MODIFIED (assignment.c:541-561) -------------------
        mk = typ(MsgType.EVICT_MODIFIED) & is_home
        mem_write = mem_write | mk
        mem_val = jnp.where(mk, v, mem_val)
        drop = mk & (ds == _EM) & _test_bit(dsh, snd)
        upd_dir = upd_dir | drop
        nd_state = jnp.where(drop, _DU, nd_state)
        nd_sharers = jnp.where(drop, 0, nd_sharers)

        # --- NACK re-serve (robust mode; spec_engine) ----------------
        if nack:
            mk = typ(MsgType.NACK) & is_home
            rd = mk & (msh == 0)
            wr = mk & (msh != 0)
            sr_bit = _bit(sr)
            upd_dir = upd_dir | mk
            nd_state = jnp.where(rd, _DS, nd_state)
            nd_state = jnp.where(wr, _EM, nd_state)
            nd_sharers = jnp.where(rd, nd_sharers | sr_bit, nd_sharers)
            nd_sharers = jnp.where(wr, sr_bit, nd_sharers)
            put(sA0, rd, recv=sr, type_=int(MsgType.REPLY_RD), addr=a,
                value=mem_blk)
            put(sA0, wr, recv=sr, type_=int(MsgType.REPLY_WR), addr=a)

        # apply phase-A updates
        cache_addr = write_c(s["cache_addr"], ci, upd_line, nl_addr)
        cache_val = write_c(s["cache_val"], ci, upd_line, nl_val)
        cache_state = write_c(s["cache_state"], ci, upd_line, nl_state)
        dir_state = write_m(s["dir_state"], blk, upd_dir, nd_state)
        dir_sharers = write_m(s["dir_sharers"], blk, upd_dir, nd_sharers)
        mem = write_m(s["mem"], blk, mem_write, mem_val)

        # ===== phase B: instruction issue ============================
        tr_len = s["tr_len"]
        elig = (count2 == 0) & (waiting == 0) & ~blocked & (s["pc"] < tr_len)
        t_dim = s["tr_op"].shape[1]
        pcc = jnp.minimum(s["pc"], t_dim - 1)
        iota_tr = jax.lax.broadcasted_iota(I32, (n, t_dim, bb), 1)
        hot_tr = iota_tr == pcc[:, None, :]
        fetch = lambda arr: jnp.sum(jnp.where(hot_tr, arr, 0), axis=1)
        op = fetch(s["tr_op"])
        ia = fetch(s["tr_addr"])
        iv = fetch(s["tr_val"])
        ci2 = ia % c
        home2 = ia // m

        l2_addr = read_c(cache_addr, ci2)
        l2_val = read_c(cache_val, ci2)
        l2_state = read_c(cache_state, ci2)
        hit = (l2_addr == ia) & (l2_state != _I)
        is_rd = elig & (op == 0)
        is_wr = elig & (op == 1)

        sB0, sB1 = slot(), slot()
        rm = is_rd & ~hit
        wm = is_wr & ~hit
        ev_issue = evict_msg(sB0, rm | wm, l2_addr, l2_val, l2_state)
        put(sB1, rm, recv=home2, type_=int(MsgType.READ_REQUEST), addr=ia)
        put(sB1, wm, recv=home2, type_=int(MsgType.WRITE_REQUEST),
            addr=ia, value=iv)
        wh_me = is_wr & hit & ((l2_state == _M) | (l2_state == _E))
        wh_s = is_wr & hit & (l2_state == _S)
        put(sB1, wh_s, recv=home2, type_=int(MsgType.UPGRADE), addr=ia)

        pending_write = jnp.where(is_wr, iv, s["pending_write"])
        waiting = jnp.where(rm | wm | wh_s, 1, waiting)

        i_upd = rm | wm | wh_me | wh_s
        n2_addr = jnp.where(rm | wm, ia, l2_addr)
        n2_val = jnp.where(rm | wm, 0, jnp.where(wh_me | wh_s, iv, l2_val))
        n2_state = jnp.where(
            rm | wm, _I, jnp.where(wh_me | wh_s, _M, l2_state)
        )
        cache_addr = write_c(cache_addr, ci2, i_upd, n2_addr)
        cache_val = write_c(cache_val, ci2, i_upd, n2_val)
        cache_state = write_c(cache_state, ci2, i_upd, n2_state)
        pc = s["pc"] + elig.astype(I32)

        # merge deferred sends back into their candidate-grid slots
        # (blocked nodes made no new sends, so the where-merge is exact)
        ob, obv = s["ob"], s["ob_valid"]

        def merge_slot(sl, k):
            pv = obv[:, k, :] != 0
            sl["valid"] = sl["valid"] | pv
            for name, row in (
                ("recv", _OB_RECV), ("type", _F_TYPE), ("addr", _F_ADDR),
                ("value", _F_VALUE), ("second", _F_SECOND),
                ("sharers", _F_SHARERS),
            ):
                sl[name] = jnp.where(pv, ob[:, row, k, :], sl[name])

        merge_slot(sA0, 0)
        merge_slot(sA1, 1)
        pend_inv = obv[:, 2, :] != 0
        inv_sharers = jnp.where(pend_inv, ob[:, _F_SHARERS, 2, :], inv_sharers)
        inv_addr = jnp.where(pend_inv, ob[:, _F_ADDR, 2, :], inv_addr)
        merge_slot(sB0, 3)
        merge_slot(sB1, 4)

        # ===== phase C: deterministic delivery =======================
        # candidate order matches ops/step.py exactly: phase A sends
        # sender-major over slots [sA0, sA1, inv], then phase B over
        # [sB0, sB1] (assignment.c:711-739's locked enqueue becomes a
        # fixed traversal).  Each candidate is accepted only while the
        # receiver's queue has space; rejected candidates defer to the
        # sender's outbox (capacity backpressure, as in ops/step.py).
        mb = qdata
        acc = zero  # running enqueue offset per receiver
        msgs_delivered = jnp.zeros((1, bb), dtype=I32)
        mc_inc = jnp.zeros((_NTYPES, bb), dtype=I32)
        # rejected-candidate collectors: [slot][sender] -> [B] rows
        rej_valid = [[None] * n for _ in range(_NSLOTS)]
        rej_rows = [
            [[None] * n for _ in range(_NSLOTS)] for _ in range(_OB_NROWS)
        ]

        def deliver(mb, acc, md, mc, valid_nb, type_v, fields):
            """Enqueue one candidate: fields are [B] rows in mb-row
            order (type, sender, addr, value, second, sharers).
            Returns the accepted [N, B] mask as well."""
            pos = count2 + acc
            accepted = valid_nb & (pos < cap)
            hot = (iota_cap == pos[:, None, :]) & accepted[:, None, :]
            planes = []
            for frow in range(_NFIELD):
                planes.append(
                    jnp.where(hot, fields[frow][None, None, :],
                              mb[:, frow, :, :])
                )
            mb = jnp.stack(planes, axis=1)
            dcount = jnp.sum(accepted.astype(I32), axis=0, keepdims=True)
            md = md + dcount
            mc = mc + jnp.where(iota_t == type_v[None, :], dcount, 0)
            return mb, acc + accepted.astype(I32), md, mc, accepted

        def record_reject(k, sender, valid_b, recv_b, fields):
            rej_valid[k][sender] = valid_b.astype(I32)
            for frow in range(_NFIELD):
                rej_rows[frow][k][sender] = fields[frow]
            rej_rows[_OB_RECV][k][sender] = recv_b

        def point_candidate(mb, acc, md, mc, sl, k, sender):
            valid_s = sl["valid"][sender]                  # [B]
            recv_s = sl["recv"][sender]
            valid_nb = valid_s[None, :] & (iota_n == recv_s[None, :])
            type_v = sl["type"][sender]
            fields = [
                type_v,
                jnp.full((bb,), sender, I32),
                sl["addr"][sender],
                sl["value"][sender],
                sl["second"][sender],
                sl["sharers"][sender],
            ]
            mb, acc, md, mc, accepted = deliver(
                mb, acc, md, mc, valid_nb, type_v, fields
            )
            rejected = valid_s & ~jnp.any(accepted, axis=0)
            record_reject(k, sender, rejected, recv_s, fields)
            return mb, acc, md, mc

        def inv_candidate(mb, acc, md, mc, sender):
            mask_s = inv_sharers[sender]                   # [B]
            valid_nb = ((mask_s[None, :] >> iota_n) & 1) == 1
            type_v = jnp.full((bb,), int(MsgType.INV), I32)
            addr_s = inv_addr[sender]
            fields = [
                type_v,
                jnp.full((bb,), sender, I32),
                addr_s,
                jnp.zeros((bb,), I32),
                jnp.full((bb,), -1, I32),
                jnp.zeros((bb,), I32),
            ]
            mb, acc, md, mc, accepted = deliver(
                mb, acc, md, mc, valid_nb, type_v, fields
            )
            remaining = mask_s & ~jnp.sum(
                accepted.astype(I32) << iota_n, axis=0
            )
            rej_valid[2][sender] = (remaining != 0).astype(I32)
            for frow in range(_NFIELD):
                rej_rows[frow][2][sender] = fields[frow]
            rej_rows[_F_SHARERS][2][sender] = remaining
            rej_rows[_F_ADDR][2][sender] = addr_s
            rej_rows[_OB_RECV][2][sender] = jnp.full((bb,), -1, I32)
            return mb, acc, md, mc

        md = msgs_delivered
        mc = mc_inc
        for sender in range(n):
            mb, acc, md, mc = point_candidate(mb, acc, md, mc, sA0, 0, sender)
            mb, acc, md, mc = point_candidate(mb, acc, md, mc, sA1, 1, sender)
            mb, acc, md, mc = inv_candidate(mb, acc, md, mc, sender)
        for sender in range(n):
            mb, acc, md, mc = point_candidate(mb, acc, md, mc, sB0, 3, sender)
            mb, acc, md, mc = point_candidate(mb, acc, md, mc, sB1, 4, sender)

        ob_valid_new = jnp.stack(
            [jnp.stack(rej_valid[k], axis=0) for k in range(_NSLOTS)], axis=1
        )                                                  # [N, 5, B]
        ob_new = jnp.stack(
            [
                jnp.stack(
                    [jnp.stack(rej_rows[r][k], axis=0) for k in range(_NSLOTS)],
                    axis=1,
                )
                for r in range(_OB_NROWS)
            ],
            axis=1,
        )                                                  # [N, 7, 5, B]
        blocked_next = jnp.sum(ob_valid_new, axis=1) > 0

        mb_count3 = count2 + acc
        overflow_now = jnp.any(mb_count3 > cap, axis=0, keepdims=True)

        # ===== phase D: dump-at-local-completion snapshots ===========
        done_node = (
            (pc >= tr_len) & (waiting == 0) & (mb_count3 == 0) & ~blocked_next
        )
        snap_now = done_node & ~(s["snap_taken"] != 0)
        s2 = snap_now[:, None, :]
        snap_mem = jnp.where(s2, mem, s["snap_mem"])
        snap_dir_state = jnp.where(s2, dir_state, s["snap_dir_state"])
        snap_dir_sharers = jnp.where(s2, dir_sharers, s["snap_dir_sharers"])
        snap_cache_addr = jnp.where(s2, cache_addr, s["snap_cache_addr"])
        snap_cache_val = jnp.where(s2, cache_val, s["snap_cache_val"])
        snap_cache_state = jnp.where(s2, cache_state, s["snap_cache_state"])

        # ===== counters ==============================================
        row = lambda x: jnp.sum(x.astype(I32), axis=0, keepdims=True)
        sc = s["scalars"]
        upd = [
            (_SC_CYCLE, jnp.ones((1, bb), I32)),
            (_SC_INSTR, row(elig)),
            (_SC_MSGS, md),
            (_SC_OVERFLOW, overflow_now.astype(I32)),
            (_SC_RH, row(is_rd & hit)),
            (_SC_RM, row(rm)),
            (_SC_WH, row(is_wr & hit)),
            (_SC_WM, row(wm)),
            (_SC_EV, row(ev_replyrd | ev_flush | ev_issue)),
            (_SC_INV, row(inv_applied)),
        ]
        iota_sc = jax.lax.broadcasted_iota(I32, (_NSCALAR, bb), 0)
        inc = jnp.zeros((_NSCALAR, bb), I32)
        for rid, val in upd:
            inc = jnp.where(iota_sc == rid, val, inc)
        # overflow row is sticky-OR, everything else accumulates
        sc = jnp.where(
            iota_sc == _SC_OVERFLOW, jnp.maximum(sc, inc), sc + inc
        )

        return {
            "cache_addr": cache_addr, "cache_val": cache_val,
            "cache_state": cache_state, "mem": mem,
            "dir_state": dir_state, "dir_sharers": dir_sharers,
            "mb": mb, "mb_count": mb_count3, "pc": pc,
            "waiting": waiting,
            "pending_write": pending_write,
            "ob": ob_new, "ob_valid": ob_valid_new,
            "snap_taken": ((s["snap_taken"] != 0) | done_node).astype(I32),
            "snap_mem": snap_mem, "snap_dir_state": snap_dir_state,
            "snap_dir_sharers": snap_dir_sharers,
            "snap_cache_addr": snap_cache_addr,
            "snap_cache_val": snap_cache_val,
            "snap_cache_state": snap_cache_state,
            "scalars": sc, "msg_counts": s["msg_counts"] + mc,
            "tr_op": s["tr_op"], "tr_addr": s["tr_addr"],
            "tr_val": s["tr_val"], "tr_len": s["tr_len"],
        }

    return cycle


def quiescent_block(s) -> jnp.ndarray:
    """[B] bool: per-system quiescence in transposed layout."""
    return (
        jnp.all(s["pc"] >= s["tr_len"], axis=0)
        & jnp.all(s["waiting"] == 0, axis=0)
        & jnp.all(s["mb_count"] == 0, axis=0)
        & jnp.all(s["ob_valid"] == 0, axis=(0, 1))
    )


# ---------------------------------------------------------------------------
# Kernel wrapper + host runner
# ---------------------------------------------------------------------------

def _init_transposed(config: SystemConfig, tr_op, tr_addr, tr_val, tr_len):
    """Initial state dict in transposed layout from [B, N, T] traces
    (initializeProcessor semantics, assignment.c:776-822)."""
    b, n, t = tr_op.shape
    c, m, cap = config.cache_size, config.mem_size, config.msg_buffer_size
    mem0 = np.broadcast_to(
        np.array(
            [[(20 * i + j) % 256 for j in range(m)] for i in range(n)],
            dtype=np.int32,
        )[:, :, None],
        (n, m, b),
    )
    mb0 = np.zeros((n, _NFIELD, cap, b), dtype=np.int32)
    mb0[:, _F_TYPE] = -1
    mb0[:, _F_SECOND] = -1
    z2 = np.zeros((n, b), dtype=np.int32)
    state = {
        "cache_addr": np.full((n, c, b), _INVALID_ADDR, np.int32),
        "cache_val": np.zeros((n, c, b), np.int32),
        "cache_state": np.full((n, c, b), _I, np.int32),
        "mem": mem0.copy(),
        "dir_state": np.full((n, m, b), _DU, np.int32),
        "dir_sharers": np.zeros((n, m, b), np.int32),
        "mb": mb0,
        "mb_count": z2.copy(), "pc": z2.copy(),
        "waiting": z2.copy(), "pending_write": z2.copy(),
        "ob": np.zeros((n, _OB_NROWS, _NSLOTS, b), np.int32),
        "ob_valid": np.zeros((n, _NSLOTS, b), np.int32),
        "snap_taken": z2.copy(),
        "snap_mem": mem0.copy(),
        "snap_dir_state": np.full((n, m, b), _DU, np.int32),
        "snap_dir_sharers": np.zeros((n, m, b), np.int32),
        "snap_cache_addr": np.full((n, c, b), _INVALID_ADDR, np.int32),
        "snap_cache_val": np.zeros((n, c, b), np.int32),
        "snap_cache_state": np.full((n, c, b), _I, np.int32),
        "scalars": np.zeros((_NSCALAR, b), np.int32),
        "msg_counts": np.zeros((_NTYPES, b), np.int32),
    }
    traces = {
        "tr_op": np.ascontiguousarray(
            np.moveaxis(tr_op.astype(np.int32), 0, -1)),
        "tr_addr": np.ascontiguousarray(
            np.moveaxis(tr_addr.astype(np.int32), 0, -1)),
        "tr_val": np.ascontiguousarray(
            np.moveaxis(tr_val.astype(np.int32), 0, -1)),
        "tr_len": np.ascontiguousarray(
            np.moveaxis(tr_len.astype(np.int32), 0, 1)),
    }
    return state, traces


@functools.lru_cache(maxsize=16)
def _build_call(config: SystemConfig, b: int, bb: int, k: int,
                interpret: bool):
    """Jitted pallas_call advancing every system by up to ``k`` cycles
    (quiesced blocks skip), state resident in VMEM for the duration."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if b % bb != 0:
        raise ValueError(f"batch {b} not divisible by block {bb}")
    cycle = build_cycle(config, bb)
    n, c, m = config.num_procs, config.cache_size, config.mem_size
    cap, nt = config.msg_buffer_size, _NTYPES

    shapes = {
        "cache_addr": (n, c), "cache_val": (n, c), "cache_state": (n, c),
        "mem": (n, m), "dir_state": (n, m), "dir_sharers": (n, m),
        "mb": (n, _NFIELD, cap), "mb_count": (n,), "pc": (n,),
        "waiting": (n,), "pending_write": (n,),
        "ob": (n, _OB_NROWS, _NSLOTS), "ob_valid": (n, _NSLOTS),
        "snap_taken": (n,), "snap_mem": (n, m),
        "snap_dir_state": (n, m), "snap_dir_sharers": (n, m),
        "snap_cache_addr": (n, c), "snap_cache_val": (n, c),
        "snap_cache_state": (n, c),
        "scalars": (_NSCALAR,), "msg_counts": (nt,),
    }

    def kernel(*refs):
        ntr = len(TRACE_FIELDS)
        nst = len(STATE_FIELDS)
        tr_refs = refs[:ntr]
        in_refs = refs[ntr:ntr + nst]
        out_refs = refs[ntr + nst:]
        s = {name: in_refs[i][:] for i, name in enumerate(STATE_FIELDS)}
        s.update(
            {name: tr_refs[i][:] for i, name in enumerate(TRACE_FIELDS)}
        )

        def body(_, st):
            done = jnp.all(quiescent_block(st))
            return jax.lax.cond(done, lambda x: x, cycle, st)

        s = jax.lax.fori_loop(0, k, body, s)
        for i, name in enumerate(STATE_FIELDS):
            out_refs[i][:] = s[name]

    def block_spec(prefix_shape):
        shape = tuple(prefix_shape) + (bb,)
        nd = len(shape)
        return pl.BlockSpec(
            shape,
            (lambda i, _nd=nd: (0,) * (_nd - 1) + (i,)),
            memory_space=pltpu.VMEM,
        )

    def call(state: Dict[str, jnp.ndarray], traces: Dict[str, jnp.ndarray]):
        t_dim = traces["tr_op"].shape[1]
        tr_shapes = {
            "tr_op": (n, t_dim), "tr_addr": (n, t_dim),
            "tr_val": (n, t_dim), "tr_len": (n,),
        }
        in_specs = (
            [block_spec(tr_shapes[f]) for f in TRACE_FIELDS]
            + [block_spec(shapes[f]) for f in STATE_FIELDS]
        )
        out_specs = [block_spec(shapes[f]) for f in STATE_FIELDS]
        out_shape = [
            jax.ShapeDtypeStruct(tuple(shapes[f]) + (b,), jnp.int32)
            for f in STATE_FIELDS
        ]
        aliases = {
            len(TRACE_FIELDS) + i: i for i in range(len(STATE_FIELDS))
        }
        fn = pl.pallas_call(
            kernel,
            grid=(b // bb,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            input_output_aliases=aliases,
            interpret=interpret,
        )
        args = [traces[f] for f in TRACE_FIELDS] + [
            state[f] for f in STATE_FIELDS
        ]
        outs = fn(*args)
        return dict(zip(STATE_FIELDS, outs))

    return jax.jit(call)


class PallasEngine:
    """Ensemble engine with VMEM-resident cycles (the fast path).

    Same observable behavior as :class:`BatchJaxEngine` — fixture
    semantics, dump-at-local-completion snapshots, counters — at a
    fraction of the per-cycle cost.  ``interpret=True`` runs the
    kernel in the Pallas interpreter (CPU differential tests).
    """

    def __init__(
        self,
        config: SystemConfig,
        tr_op: np.ndarray,
        tr_addr: np.ndarray,
        tr_val: np.ndarray,
        tr_len: np.ndarray,
        block: int = 128,
        cycles_per_call: int = 128,
        interpret: Optional[bool] = None,
    ):
        if interpret is None:
            # the Mosaic kernel path needs a TPU; interpret elsewhere
            # (match on the device, not default_backend(): the axon
            # plugin reports platform "axon" for a real TPU chip)
            interpret = not any(
                "tpu" in str(d).lower() for d in jax.devices()
            )
        b = tr_op.shape[0]
        self.config = config
        self.b = b
        self._interpret_active = interpret
        # largest divisor of the batch not exceeding the requested
        # block (the grid tiles the ensemble axis exactly)
        block = min(block, b)
        while b % block != 0:
            block -= 1
        self.block = block
        self.cycles_per_call = cycles_per_call
        state, traces = _init_transposed(
            config, tr_op, tr_addr, tr_val, tr_len
        )
        self.state = {f: jnp.asarray(v) for f, v in state.items()}
        self.traces = {f: jnp.asarray(v) for f, v in traces.items()}
        self._call = _build_call(
            config, b, self.block, cycles_per_call, interpret
        )

    def run(self, max_cycles: int = 1_000_000) -> "PallasEngine":
        calls = 0
        limit = max(1, -(-max_cycles // self.cycles_per_call))
        while True:
            self.state = self._call(self.state, self.traces)
            calls += 1
            if bool(jnp.any(self.state["scalars"][_SC_OVERFLOW] > 0)):
                raise StallError(
                    "internal invariant violated: mailbox overflow despite backpressure"
                )
            if bool(
                jnp.all(
                    quiescent_block(
                        {**self.state, "tr_len": self.traces["tr_len"]}
                    )
                )
            ):
                return self
            if calls >= limit:
                raise StallError(
                    f"no quiescence after ~{calls * self.cycles_per_call} "
                    "cycles (livelock? use Semantics.robust())"
                )

    # -- readback -----------------------------------------------------

    def _dump(self, arrs, sys_idx: int) -> List[NodeDump]:
        mem, dstate, dsh, caddr, cval, cstate = arrs
        return [
            NodeDump(
                proc_id=i,
                memory=[int(x) for x in mem[i, :, sys_idx]],
                dir_state=[int(x) for x in dstate[i, :, sys_idx]],
                dir_sharers=[
                    int(np.uint32(x)) for x in dsh[i, :, sys_idx]
                ],
                cache_addr=[int(x) for x in caddr[i, :, sys_idx]],
                cache_value=[int(x) for x in cval[i, :, sys_idx]],
                cache_state=[int(x) for x in cstate[i, :, sys_idx]],
            )
            for i in range(self.config.num_procs)
        ]

    def system_snapshots(self, sys_idx: int) -> List[NodeDump]:
        arrs = tuple(
            np.asarray(self.state[f])
            for f in ("snap_mem", "snap_dir_state", "snap_dir_sharers",
                      "snap_cache_addr", "snap_cache_val",
                      "snap_cache_state")
        )
        return self._dump(arrs, sys_idx)

    def system_final_dumps(self, sys_idx: int) -> List[NodeDump]:
        arrs = tuple(
            np.asarray(self.state[f])
            for f in ("mem", "dir_state", "dir_sharers",
                      "cache_addr", "cache_val", "cache_state")
        )
        return self._dump(arrs, sys_idx)

    @property
    def instructions(self) -> int:
        return int(np.sum(np.asarray(self.state["scalars"][_SC_INSTR])))

    def stats(self) -> dict:
        from hpa2_tpu.ops.engine import format_stats

        sc = np.asarray(self.state["scalars"])
        return format_stats(
            {
                "instructions": int(sc[_SC_INSTR].sum()),
                "msgs_total": int(sc[_SC_MSGS].sum()),
                "read_hits": int(sc[_SC_RH].sum()),
                "read_misses": int(sc[_SC_RM].sum()),
                "write_hits": int(sc[_SC_WH].sum()),
                "write_misses": int(sc[_SC_WM].sum()),
                "evictions": int(sc[_SC_EV].sum()),
                "invalidations": int(sc[_SC_INV].sum()),
            },
            np.asarray(self.state["msg_counts"]).sum(axis=1),
        )
