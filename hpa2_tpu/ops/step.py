"""The jitted lockstep cycle: handle → issue → deliver → snapshot.

Semantics are exactly ``hpa2_tpu.models.spec_engine`` (the executable
spec); this module is its TPU-native lowering:

* the per-thread 13-case message switch (assignment.c:187-566) becomes
  masked vectorized updates over the node axis — every node handles at
  most one message per cycle, all 13 handler bodies are evaluated as
  cheap elementwise/gather ops and merged by type masks (no
  data-dependent control flow, fixed shapes, XLA-fusable);
* ``sendMessage``'s locked enqueue (assignment.c:711-739) becomes a
  deterministic scatter: each cycle's outgoing messages form a
  fixed-shape candidate tensor ordered by (phase, sender, slot); an
  exclusive prefix-sum per receiver assigns ring-buffer positions
  (SURVEY.md §7.4.3);
* the INV fan-out of REPLY_ID (variable fan-out, assignment.c:350-362)
  rides the sharer bitmask directly: receiver r tests bit r of the
  sender's INV mask — an [senders, receivers] bit-probe instead of a
  variable-length message loop (SURVEY.md §7.4.1);
* instruction issue (assignment.c:590-697) issues at most one
  instruction per ready node per cycle (a node is ready when its
  mailbox is empty and it is not waiting — the reference's
  drain-all-then-issue loop shape).

Replay mode gates issue on a recorded ``instruction_order.txt``
schedule so fixture interleavings are reproducible under ``jit``.

Multi-chip: ``build_step(config, axis_name=..., shards=D)`` builds the
*same* cycle as a per-shard SPMD program for ``jax.shard_map`` over a
mesh axis holding ``num_procs / D`` nodes per device.  Phases A/B/D are
purely node-local; phase C's delivery — the reference's shared-memory
mailbox enqueue (assignment.c:711-739) — is a *targeted* exchange
(``ops/exchange.py``): each shard buckets its candidates by destination
shard (point sends by ``recv // n_local``, INV multicasts by which
shards hold sharer-mask bits), compacts each bucket and ships it with
one ``ppermute`` per round; acceptance feedback returns along the
reverse permutation and all global counters fold into ONE stacked
``psum`` — 2*(D-1) ppermutes + 1 psum per cycle, no per-cycle
``all_gather`` of the world.  Delivery order is reconstructed exactly
(``exchange.ordered_rank`` over origin-tagged blocks), so the sharded
engine is bit-identical to the single-chip one (see
tests/test_parallel.py).  Fault injection composes: the node-shard
index is folded into the link-layer mask keys so each shard draws an
independent stream, and the retransmission masking invariant keeps
dumps byte-identical to the unsharded faulty run.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import MsgType
from hpa2_tpu.ops import bits, exchange
from hpa2_tpu.ops.state import (
    MB_ADDR,
    MB_SECOND,
    MB_SENDER,
    MB_SHARERS,
    MB_TYPE,
    MB_VALUE,
    SimState,
)
from hpa2_tpu.protocols.compiler import ProtocolPlanes, planes_for, state_in
from hpa2_tpu.protocols.directory import group_mask_words, parse_format

I32 = jnp.int32
U32 = jnp.uint32

# Every state constant below comes from the compiled ``ProtocolPlanes``
# (hpa2_tpu.protocols.compiler) — the transition masks are lowered from
# the declarative TransitionTable, never restated by hand.  The AST
# lint (analysis/lint.py) pins this: no CacheState/DirState member
# access in this module.

_INVALID_ADDR = -1
_NO_MSG = -1


def _gather_n(arr, idx):
    """arr [N, K], idx [N] -> [N] (one element per row).

    One-hot masked reduction rather than take_along_axis: TPU
    scalarizes gathers fused into larger computations (measured
    ~100x slower than this dense form for the small K used here).
    """
    k = arr.shape[1]
    hot = jnp.arange(k, dtype=I32)[None, :] == idx[:, None]
    return jnp.sum(jnp.where(hot, arr, arr.dtype.type(0)), axis=1)


def _gather_nw(arr, idx):
    """arr [N, K, W], idx [N] -> [N, W]."""
    k = arr.shape[1]
    hot = jnp.arange(k, dtype=I32)[None, :] == idx[:, None]
    return jnp.sum(
        jnp.where(hot[:, :, None], arr, arr.dtype.type(0)), axis=1
    )


# above ~this K the one-hot mask streams more HBM than the scalarized
# gather costs; long-trace fetches switch back to take_along_axis
_ONEHOT_MAX_K = 512


def _fetch_n(arr, idx):
    """_gather_n that stays O(N) for large trailing axes (traces)."""
    if arr.shape[1] <= _ONEHOT_MAX_K:
        return _gather_n(arr, idx)
    return jnp.take_along_axis(arr, idx[:, None], axis=1)[:, 0]


class _SendSlots:
    """Fixed number of point-message slots per sender per phase."""

    def __init__(self, n, w):
        z = lambda dt: jnp.zeros((n,), dtype=dt)
        self.valid = jnp.zeros((n,), dtype=bool)
        self.recv = z(I32)
        self.type = jnp.full((n,), _NO_MSG, dtype=I32)
        self.addr = z(I32)
        self.value = z(I32)
        self.sharers = jnp.zeros((n, w), dtype=U32)
        self.second = jnp.full((n,), -1, dtype=I32)

    def put(self, mask, recv, type_, addr, value=None, sharers=None,
            second=None):
        """Masked write into the slot (types are mutually exclusive per
        cycle, so masks never overlap)."""
        self.valid = self.valid | mask
        self.recv = jnp.where(mask, recv, self.recv)
        self.type = jnp.where(mask, type_, self.type)
        self.addr = jnp.where(mask, addr, self.addr)
        if value is not None:
            self.value = jnp.where(mask, value, self.value)
        if sharers is not None:
            self.sharers = jnp.where(mask[:, None], sharers, self.sharers)
        if second is not None:
            self.second = jnp.where(mask, second, self.second)


def _evict_msg(slots, mask, line_addr, line_val, line_state, mem_size, P):
    """handleCacheReplacement (assignment.c:742-773) as a masked send:
    EVICT_SHARED for clean victims, EVICT_MODIFIED (with value) for the
    protocol's dirty states (``P.dirty_evict_states``)."""
    victim_valid = mask & (line_addr != _INVALID_ADDR) & (line_state != P.I)
    home = jnp.maximum(line_addr, 0) // mem_size
    is_mod = state_in(line_state, P.dirty_evict_states, P.n_cache_states)
    slots.put(
        victim_valid,
        recv=home,
        type_=jnp.where(
            is_mod, int(MsgType.EVICT_MODIFIED), int(MsgType.EVICT_SHARED)
        ),
        addr=line_addr,
        value=line_val,
    )
    return victim_valid


@functools.lru_cache(maxsize=64)
def build_step_jitted(config: SystemConfig, replay: bool = False):
    """Cached jitted single-system step (host-driven cycle loops)."""
    return jax.jit(build_step(config, replay=replay))


def build_step(
    config: SystemConfig,
    replay: bool = False,
    axis_name: Optional[str] = None,
    shards: int = 1,
    planes: Optional[ProtocolPlanes] = None,
):
    """Build the single-system step function (vmap for batches).

    With ``axis_name``/``shards`` the returned function is the
    per-shard SPMD body for ``jax.shard_map``: every node-leading array
    it sees is the local block of ``num_procs // shards`` nodes, and
    phase C moves only the candidates that actually cross shards via
    the targeted exchange (``ops/exchange.py``).  The collective
    schedule follows ``config.exchange_mode`` — see
    ``exchange.plan_collectives`` — plus one stacked counter ``psum``
    and one stacked telemetry ``pmax`` per cycle.
    """
    n = config.num_procs
    c = config.cache_size
    m = config.mem_size
    w = config.sharer_words
    cap = config.msg_buffer_size
    sem = config.semantics
    # the compiled protocol: every transition mask below is built from
    # these planes (``planes`` overrides the config's table — the
    # mutation fuzzer injects deliberately-broken planes this way)
    P = planes if planes is not None else planes_for(config.protocol, sem)
    NC = P.n_cache_states
    _M, _S, _I = P.M, P.S, P.I
    _EM, _DS, _DU, _SO = P.EM, P.DS, P.DU, P.SO
    if len(P.reply_rd_fill) != 2:
        raise ValueError(
            f"the {P.protocol} table compiles to "
            f"{len(P.reply_rd_fill)} REPLY_RD fill kinds; the kernel "
            "lowering needs exactly two (a flag-selected pair)"
        )
    dir_kind, dir_param = parse_format(config.directory_format, n)
    if sem.overloaded_evict_shared_notify:
        raise ValueError(
            "the JAX backend implements fixture semantics only; the "
            "overloaded EVICT_SHARED notify (HEAD quirk) is available "
            "in the Python spec engine for differential study"
        )
    if config.messages_per_cycle != 1:
        raise ValueError(
            "the JAX backend drains one message per node per cycle; "
            "messages_per_cycle > 1 runs on the spec engine"
        )
    if axis_name is not None:
        if replay:
            raise ValueError(
                "replay mode is single-shard only (fixture replays are "
                "tiny 4-node systems; shard the batch axis instead)"
            )
        if shards < 1 or n % shards != 0:
            raise ValueError(
                f"num_procs={n} not divisible by shards={shards}"
            )
        if config.protocol != "mesi" or dir_kind != "full":
            raise ValueError(
                "node sharding runs the MESI/full-bitvector build "
                "only; protocol and directory-format variants are "
                "single-shard (shard the batch axis instead)"
            )
    nack = sem.intervention_miss_policy == "nack"
    fault = config.fault
    fault_on = fault.enabled  # static: fault-free builds add zero ops
    drop_p = float(fault.drop)
    n_local = n // shards
    local_ids = jnp.arange(n_local, dtype=I32)
    xplan = (
        exchange.make_plan(
            shards, config.exchange_mode, config.exchange_inner
        )
        if axis_name is not None and shards > 1
        else None
    )

    # -- interconnect topology (static: ideal builds add zero ops and
    # keep the exact pre-topology mb_data layout / op counts) ---------
    ic = config.interconnect
    topo_on = ic.enabled
    if topo_on:
        if axis_name is not None:
            raise ValueError(
                "non-ideal interconnect topologies run single-shard "
                "only; node sharding composes with topology='ideal'"
            )
        if replay:
            raise ValueError(
                "replay mode supports the ideal topology only"
            )
        from hpa2_tpu.interconnect.topology import build_topology

        topo = build_topology(ic.topology, n, ic.hop_latency)
        # flat candidate order is sender-static: A grid (3 slots per
        # node, node-major) then B grid (2 slots per node) — bake the
        # per-candidate path/latency tensors as jit constants
        send_np = np.concatenate(
            [np.repeat(np.arange(n), 3), np.repeat(np.arange(n), 2)]
        )
        paths_np = topo.path_mat[send_np]  # [J0, N, L] bool
        if paths_np.shape[2] == 0:  # linkless (n == 1): keep L >= 1
            paths_np = np.zeros((5 * n, n, 1), dtype=bool)
        # ideal-equivalent minimum is one cycle (next-cycle handling)
        base_np = np.maximum(topo.base_lat[send_np], 1).astype(np.int32)
        n_links = paths_np.shape[2]
        mb_deliver = 5 + w  # deliver-at column (after sharer words)

    # -- directory-format fan-out constants (static; the full format
    # adds zero ops and keeps the exact MESI candidate tensors) --------
    if dir_kind == "limited":
        _all_int = (1 << n) - 1
        all_words_np = np.array(
            [(_all_int >> (32 * i)) & 0xFFFFFFFF for i in range(w)],
            dtype=np.uint32,
        )
    elif dir_kind == "coarse":
        gm_np = group_mask_words(dir_param, n, w, 32).view(np.uint32)

    def step(st: SimState) -> SimState:
        if axis_name is None:
            node_ids = local_ids
        else:
            node_ids = (
                jax.lax.axis_index(axis_name).astype(I32) * n_local
                + local_ids
            )
        # nodes with deferred sends are blocked: no handle, no issue —
        # the lockstep analog of the reference's blocking enqueue
        # (assignment.c:715-724; capacity backpressure, SURVEY.md §5)
        blocked = jnp.any(st.ob_valid, axis=1)

        # ============== phase A: handle one message per node ==========
        # head is always slot 0 (shift-down queue): reads are static
        # slices — a fused gather would be scalarized by the TPU
        # backend (measured ~1000x slower than this formulation)
        hm = st.mb_data[:, 0, :]
        has_msg = (st.mb_count > 0) & ~blocked
        if topo_on:
            # interconnect gating: the head blocks until its delivery
            # cycle (FIFO order preserved — an ordered virtual channel,
            # mirrors the spec engine's mailbox[0].deliver_at check)
            has_msg = has_msg & (hm[:, mb_deliver] <= st.cycle)
        mt = jnp.where(has_msg, hm[:, MB_TYPE], _NO_MSG)
        snd = hm[:, MB_SENDER]
        a = jnp.maximum(hm[:, MB_ADDR], 0)
        v = hm[:, MB_VALUE]
        msh = jax.lax.bitcast_convert_type(
            hm[:, MB_SHARERS : MB_SHARERS + w], U32
        )
        sr = hm[:, MB_SECOND]

        # consume the head: shift the queue down one slot
        qdata = jnp.where(
            has_msg[:, None, None],
            jnp.roll(st.mb_data, -1, axis=1),
            st.mb_data,
        )
        mb_count2 = st.mb_count - has_msg.astype(I32)

        home = a // m
        blk = a % m
        ci = a % c
        is_home = node_ids == home
        is_second = node_ids == sr

        line_addr = _gather_n(st.cache_addr, ci)
        line_val = _gather_n(st.cache_val, ci)
        line_state = _gather_n(st.cache_state, ci)
        ds = _gather_n(st.dir_state, blk)
        dsh = _gather_nw(st.dir_sharers, blk)
        mem_blk = _gather_n(st.mem, blk)
        pw = st.pending_write

        if P.has_owner_plane:
            dow = _gather_n(st.dir_owner, blk)

        line_match = line_addr == a
        owner = bits.find_owner(dsh)
        owner_is_snd = owner == snd
        snd_bit = bits.bit_mask(snd, w)

        def fanout(base):
            """REPLY_ID fan-out through the directory-format lens:
            (sharers minus requester) in, (mask, overflowed) out.  The
            internal bitvector stays exact — precision is lost only
            here, when the home composes an invalidation set."""
            if dir_kind == "full":
                return base, None
            if dir_kind == "limited":
                cnt = jnp.sum(
                    jax.lax.population_count(base).astype(I32), axis=1
                )
                over = cnt > dir_param
                allm = jnp.asarray(all_words_np)[None, :] & ~snd_bit
                return jnp.where(over[:, None], allm, base), over
            gm = jnp.asarray(gm_np)  # [G, W] disjoint group masks
            hasg = jnp.any(
                (base[:, None, :] & gm[None, :, :]) != 0, axis=2
            )  # [N, G]
            # disjoint masks: the summed words are an exact OR
            spread = jnp.sum(
                jnp.where(hasg[:, :, None], gm[None, :, :], U32(0)),
                axis=1,
                dtype=U32,
            )
            return spread & ~snd_bit, None

        sA0 = _SendSlots(n_local, w)
        sA1 = _SendSlots(n_local, w)
        inv_valid = jnp.zeros((n_local,), dtype=bool)
        inv_sharers = jnp.zeros((n_local, w), dtype=U32)
        inv_addr = jnp.zeros((n_local,), dtype=I32)

        # accumulated updates (start = current values)
        nl_addr, nl_val, nl_state = line_addr, line_val, line_state
        upd_line = jnp.zeros((n_local,), dtype=bool)
        nd_state, nd_sharers = ds, dsh
        if P.has_owner_plane:
            nd_owner = dow
        upd_dir = jnp.zeros((n_local,), dtype=bool)
        over_inc = (
            jnp.zeros((), dtype=I32) if dir_kind == "limited" else None
        )
        mem_write = jnp.zeros((n_local,), dtype=bool)
        mem_val = mem_blk
        waiting = st.waiting

        def typ(t):
            return mt == int(t)

        # --- READ_REQUEST (home only; assignment.c:188-236) ----------
        mk = typ(MsgType.READ_REQUEST) & is_home
        du, dss, dem = ds == _DU, ds == _DS, ds == _EM
        excl = du | (dem & owner_is_snd)
        if P.has_so:
            # MOESI: the tracked OWNED cache answers reads while SO
            dso = ds == _SO
            so_self = mk & dso & (dow == snd)  # owner lost its line
            so_fwd = mk & dso & (dow != snd)
            reply_mask = mk & (du | dss | (dem & owner_is_snd)) | so_self
            fwd = (mk & dem & ~owner_is_snd) | so_fwd
            fwd_to = jnp.where(so_fwd, dow, owner)
        elif P.has_fwd:
            # MESIF: a live forwarder serves dir-S reads cache-to-cache
            live_f = dss & (dow >= 0) & (dow != snd)
            reply_mask = mk & (du | (dss & ~live_f) | (dem & owner_is_snd))
            fwd = mk & ((dem & ~owner_is_snd) | live_f)
            fwd_to = jnp.where(mk & live_f, dow, owner)
        else:
            reply_mask = mk & (du | dss | (dem & owner_is_snd))
            fwd = mk & dem & ~owner_is_snd
            fwd_to = owner
        excl_flag = jnp.where(excl, U32(P.rr_u_flag), U32(P.rr_s_flag))
        sA0.put(
            reply_mask,
            recv=snd,
            type_=int(MsgType.REPLY_RD),
            addr=a,
            value=mem_blk,
            sharers=excl_flag[:, None] * jnp.eye(1, w, dtype=U32)[0][None, :],
        )
        sA0.put(
            fwd, recv=fwd_to, type_=int(MsgType.WRITEBACK_INT), addr=a,
            second=snd,
        )
        upd_dir = upd_dir | (mk & (du | dss | fwd))
        nd_state = jnp.where(mk & du, _EM, nd_state)
        if P.has_so:
            # EM read-forward keeps the dirty owner: -> SO (a re-write
            # of SO on the so_fwd part is a no-op); the abandoned-owner
            # case demotes to clean-shared
            upd_dir = upd_dir | so_self
            nd_state = jnp.where(fwd, _SO, nd_state)
            nd_state = jnp.where(so_self, _DS, nd_state)
            nd_owner = jnp.where(mk & dem & ~owner_is_snd, owner, nd_owner)
            nd_owner = jnp.where(so_self, -1, nd_owner)
        else:
            # optimistic pre-flush transition (assignment.c:230-231)
            nd_state = jnp.where(fwd, _DS, nd_state)
            if P.has_fwd:
                # the newest reader becomes the forwarder
                nd_owner = jnp.where(
                    mk & ((dss & (dow != snd)) | (dem & ~owner_is_snd)),
                    snd,
                    nd_owner,
                )
        nd_sharers = jnp.where(
            (mk & du)[:, None], snd_bit, nd_sharers
        )
        share_join = mk & (dss | fwd)
        if P.has_so:
            share_join = share_join | so_self
        nd_sharers = jnp.where(
            share_join[:, None], nd_sharers | snd_bit, nd_sharers
        )

        # --- REPLY_RD (assignment.c:238-247) -------------------------
        mk = typ(MsgType.REPLY_RD)
        ev = mk & ~line_match
        ev_replyrd = _evict_msg(
            sA0, ev, line_addr, line_val, line_state, m, P
        )
        upd_line = upd_line | mk
        nl_addr = jnp.where(mk, a, nl_addr)
        nl_val = jnp.where(mk, v, nl_val)
        (rd_lo_flag, rd_lo_fill), (rd_hi_flag, rd_hi_fill) = P.reply_rd_fill
        del rd_lo_flag  # the fill pair is flag-keyed; low is the default
        nl_state = jnp.where(
            mk,
            jnp.where(msh[:, 0] == rd_hi_flag, rd_hi_fill, rd_lo_fill),
            nl_state,
        )
        waiting = jnp.where(mk, False, waiting)

        # --- WRITEBACK_INT (assignment.c:249-271) --------------------
        mk = typ(MsgType.WRITEBACK_INT)
        resp = state_in(line_state, P.wbint_resp_states, NC)
        ok = mk & line_match & resp
        if P.fwd_count_states:
            # cache-to-cache responders (MOESI OWNED keeps the dirty
            # line; MESIF FORWARD is already clean): ONE flush to the
            # requester, no home copy
            c2c = ok & state_in(line_state, P.fwd_count_states, NC)
            ok_home = ok & ~c2c
            second_mask = (ok_home & (sr != home)) | c2c
            fwd_inc = jnp.sum(c2c.astype(I32))
        else:
            ok_home = ok
            second_mask = ok & (sr != home)
            fwd_inc = None
        sA0.put(
            ok_home, recv=home, type_=int(MsgType.FLUSH), addr=a,
            value=line_val, second=sr,
        )
        sA1.put(
            second_mask, recv=sr, type_=int(MsgType.FLUSH), addr=a,
            value=line_val, second=sr,
        )
        upd_line = upd_line | ok
        nl_state = jnp.where(ok, P.wbint_next_state, nl_state)
        if nack:
            sA0.put(
                mk & ~(line_match & resp), recv=home,
                type_=int(MsgType.NACK), addr=a, second=sr,
            )

        # --- FLUSH (assignment.c:273-296) ----------------------------
        mk = typ(MsgType.FLUSH)
        mem_write = mem_write | (mk & is_home)
        mem_val = jnp.where(mk & is_home, v, mem_val)
        rq = mk & is_second
        ev = rq & ~line_match
        ev_flush = _evict_msg(
            sA0, ev, line_addr, line_val, line_state, m, P
        )
        upd_line = upd_line | rq
        nl_addr = jnp.where(rq, a, nl_addr)
        nl_val = jnp.where(rq, v, nl_val)
        nl_state = jnp.where(rq, P.flush_fill_state, nl_state)
        waiting = jnp.where(rq, False, waiting)

        # --- UPGRADE (home only; assignment.c:298-328) ---------------
        mk = typ(MsgType.UPGRADE) & is_home
        if P.has_so:
            trk = (ds == _DS) | (ds == _SO)
        else:
            trk = ds == _DS
        up_fan, up_over = fanout(dsh & ~snd_bit)
        reply_sh = jnp.where(
            (mk & trk)[:, None], up_fan, jnp.zeros_like(dsh)
        )
        sA0.put(
            mk, recv=snd, type_=int(MsgType.REPLY_ID), addr=a,
            sharers=reply_sh,
        )
        upd_dir = upd_dir | mk
        nd_state = jnp.where(mk, _EM, nd_state)
        nd_sharers = jnp.where(mk[:, None], snd_bit, nd_sharers)
        if P.has_owner_plane:
            nd_owner = jnp.where(mk & trk, -1, nd_owner)
        if over_inc is not None:
            over_inc = over_inc + jnp.sum((mk & trk & up_over).astype(I32))

        # --- REPLY_ID (assignment.c:330-364) -------------------------
        mk = typ(MsgType.REPLY_ID)
        fill = mk & line_match & (line_state != _M)
        upd_line = upd_line | fill
        nl_val = jnp.where(fill, pw, nl_val)
        nl_state = jnp.where(fill, _M, nl_state)
        fan = mk & line_match
        inv_valid = inv_valid | fan
        inv_sharers = jnp.where(
            fan[:, None], msh & ~bits.bit_mask(node_ids, w), inv_sharers
        )
        inv_addr = jnp.where(fan, a, inv_addr)
        waiting = jnp.where(mk, False, waiting)

        # --- INV (assignment.c:366-373) ------------------------------
        mk = typ(MsgType.INV)
        inv_applied = mk & line_match & state_in(
            line_state, P.inv_states, NC
        )
        upd_line = upd_line | inv_applied
        nl_state = jnp.where(inv_applied, _I, nl_state)

        # --- WRITE_REQUEST (home only; assignment.c:375-435) ---------
        mk = typ(MsgType.WRITE_REQUEST) & is_home
        if sem.eager_write_request_memory:
            mem_write = mem_write | mk
            mem_val = jnp.where(mk, v, mem_val)
        du, dss, dem = ds == _DU, ds == _DS, ds == _EM
        if P.has_so:
            # the writer invalidates everyone, incl. the tracked owner
            dss = dss | (ds == _SO)
        wr_reply = mk & (du | (dem & owner_is_snd))
        sA0.put(wr_reply, recv=snd, type_=int(MsgType.REPLY_WR), addr=a)
        wr_id = mk & dss
        wr_fan, wr_over = fanout(dsh & ~snd_bit)
        sA0.put(
            wr_id, recv=snd, type_=int(MsgType.REPLY_ID), addr=a,
            sharers=wr_fan,
        )
        wr_fwd = mk & dem & ~owner_is_snd
        sA0.put(
            wr_fwd, recv=owner, type_=int(MsgType.WRITEBACK_INV), addr=a,
            second=snd,
        )
        upd_dir = upd_dir | (mk & (du | dss | wr_fwd))
        nd_state = jnp.where(mk & (du | dss), _EM, nd_state)
        nd_sharers = jnp.where(
            (mk & (du | dss | wr_fwd))[:, None], snd_bit, nd_sharers
        )
        if P.has_owner_plane:
            nd_owner = jnp.where(wr_id, -1, nd_owner)
        if over_inc is not None:
            over_inc = over_inc + jnp.sum((wr_id & wr_over).astype(I32))

        # --- REPLY_WR (assignment.c:437-449) -------------------------
        mk = typ(MsgType.REPLY_WR)
        upd_line = upd_line | mk
        nl_addr = jnp.where(mk, a, nl_addr)
        nl_val = jnp.where(mk, pw, nl_val)
        nl_state = jnp.where(mk, _M, nl_state)
        waiting = jnp.where(mk, False, waiting)

        # --- WRITEBACK_INV (assignment.c:451-473) --------------------
        mk = typ(MsgType.WRITEBACK_INV)
        wbinv_resp = state_in(line_state, P.wbinv_resp_states, NC)
        ok = mk & line_match & wbinv_resp
        sA0.put(
            ok, recv=home, type_=int(MsgType.FLUSH_INVACK), addr=a,
            value=line_val, second=sr,
        )
        sA1.put(
            ok & (sr != home), recv=sr, type_=int(MsgType.FLUSH_INVACK),
            addr=a, value=line_val, second=sr,
        )
        upd_line = upd_line | ok
        nl_state = jnp.where(ok, _I, nl_state)
        if nack:
            sA0.put(
                mk & ~(line_match & wbinv_resp), recv=home,
                type_=int(MsgType.NACK), addr=a,
                sharers=jnp.ones((n_local, 1), dtype=U32)
                * jnp.eye(1, w, dtype=U32)[0][None, :],
                second=sr,
            )

        # --- FLUSH_INVACK (assignment.c:475-496) ---------------------
        mk = typ(MsgType.FLUSH_INVACK)
        hm = mk & is_home
        mem_write = mem_write | hm
        mem_val = jnp.where(hm, v, mem_val)
        upd_dir = upd_dir | hm
        nd_state = jnp.where(hm, _EM, nd_state)
        nd_sharers = jnp.where(hm[:, None], bits.bit_mask(sr, w), nd_sharers)
        if P.has_owner_plane:
            nd_owner = jnp.where(hm, -1, nd_owner)
        rq = mk & is_second
        upd_line = upd_line | rq
        nl_addr = jnp.where(rq, a, nl_addr)
        fill_val = v if sem.flush_invack_fills_old_value else pw
        nl_val = jnp.where(rq, fill_val, nl_val)
        nl_state = jnp.where(rq, _M, nl_state)
        waiting = jnp.where(rq, False, waiting)

        # --- EVICT_SHARED (home role; assignment.c:498-521) ----------
        mk = typ(MsgType.EVICT_SHARED) & is_home & bits.test_bit(dsh, snd)
        after = dsh & ~snd_bit
        cnt = bits.popcount(after)
        upd_dir = upd_dir | mk
        nd_sharers = jnp.where(mk[:, None], after, nd_sharers)
        nd_state = jnp.where(mk & (cnt == 0), _DU, nd_state)
        if P.has_so:
            es_trk = (ds == _DS) | (ds == _SO)
        else:
            es_trk = ds == _DS
        upg = mk & (cnt == 1) & es_trk
        nd_state = jnp.where(upg, _EM, nd_state)
        survivor = bits.find_owner(after)
        sA0.put(
            upg, recv=survivor, type_=int(MsgType.UPGRADE_NOTIFY), addr=a,
        )
        if P.has_so:
            # SO loses owner tracking only when the set collapses;
            # several-left keeps SO and the owner pointer
            nd_owner = jnp.where(
                mk & (ds == _SO) & (cnt <= 1), -1, nd_owner
            )
        elif P.has_fwd:
            # an evicting forwarder abdicates; set-collapse clears too
            nd_owner = jnp.where(
                mk & (ds == _DS) & ((cnt <= 1) | (dow == snd)),
                -1,
                nd_owner,
            )

        # --- UPGRADE_NOTIFY (fixture-semantics notify; spec_engine) --
        mk = typ(MsgType.UPGRADE_NOTIFY) & (snd == home)
        hit = mk & line_match
        for _frm, _to in P.notify_pairs:
            pm = hit & (line_state == _frm)
            upd_line = upd_line | pm
            nl_state = jnp.where(pm, _to, nl_state)

        # --- EVICT_MODIFIED (home only; assignment.c:541-561) --------
        mk = typ(MsgType.EVICT_MODIFIED) & is_home
        mem_write = mem_write | mk
        mem_val = jnp.where(mk, v, mem_val)
        drop = mk & (ds == _EM) & bits.test_bit(dsh, snd)
        upd_dir = upd_dir | drop
        nd_state = jnp.where(drop, _DU, nd_state)
        nd_sharers = jnp.where(
            drop[:, None], jnp.zeros_like(dsh), nd_sharers
        )
        if P.has_so:
            # the OWNED cache wrote back: remaining sharers (if any)
            # are clean-shared against the freshened memory
            somod = mk & (ds == _SO) & (dow == snd)
            so_after = dsh & ~snd_bit
            upd_dir = upd_dir | somod
            nd_sharers = jnp.where(somod[:, None], so_after, nd_sharers)
            nd_state = jnp.where(
                somod,
                jnp.where(bits.popcount(so_after) == 0, _DU, _DS),
                nd_state,
            )
            nd_owner = jnp.where(somod, -1, nd_owner)

        # --- NACK (robust mode re-serve; spec_engine) ----------------
        if nack:
            mk = typ(MsgType.NACK) & is_home
            rd = mk & (msh[:, 0] == 0)
            wr = mk & (msh[:, 0] != 0)
            sr_bit = bits.bit_mask(sr, w)
            upd_dir = upd_dir | mk
            nd_state = jnp.where(rd, _DS, nd_state)
            nd_state = jnp.where(wr, _EM, nd_state)
            nd_sharers = jnp.where(rd[:, None], nd_sharers | sr_bit, nd_sharers)
            nd_sharers = jnp.where(wr[:, None], sr_bit, nd_sharers)
            if P.has_owner_plane:
                if P.has_fwd:
                    # the re-served reader becomes the forwarder
                    nd_owner = jnp.where(rd, sr, nd_owner)
                else:
                    # owner tracking is stale by construction
                    nd_owner = jnp.where(rd, -1, nd_owner)
                nd_owner = jnp.where(wr, -1, nd_owner)
            if P.nack_rd_flag:
                sA0.put(
                    rd, recv=sr, type_=int(MsgType.REPLY_RD), addr=a,
                    value=mem_blk,
                    sharers=jnp.full((n_local, 1), P.nack_rd_flag, U32)
                    * jnp.eye(1, w, dtype=U32)[0][None, :],
                )
            else:
                sA0.put(
                    rd, recv=sr, type_=int(MsgType.REPLY_RD), addr=a,
                    value=mem_blk,
                )
            sA0.put(wr, recv=sr, type_=int(MsgType.REPLY_WR), addr=a)

        # owner/forwarder pointer migrations this cycle (exact at one
        # message per node: nd_owner diverges from dow only where a
        # handler wrote it; clearing to -1 is a release, not counted)
        if P.has_owner_plane:
            xfer_inc = jnp.sum(
                ((nd_owner != dow) & (nd_owner >= 0)).astype(I32)
            )
        else:
            xfer_inc = None

        # scatter phase-A updates back into the SoA arrays
        ci_hot = jnp.arange(c, dtype=I32)[None, :] == ci[:, None]
        lmask = ci_hot & upd_line[:, None]
        cache_addr = jnp.where(lmask, nl_addr[:, None], st.cache_addr)
        cache_val = jnp.where(lmask, nl_val[:, None], st.cache_val)
        cache_state = jnp.where(lmask, nl_state[:, None], st.cache_state)

        blk_hot = jnp.arange(m, dtype=I32)[None, :] == blk[:, None]
        dmask = blk_hot & upd_dir[:, None]
        dir_state = jnp.where(dmask, nd_state[:, None], st.dir_state)
        dir_sharers = jnp.where(
            dmask[:, :, None], nd_sharers[:, None, :], st.dir_sharers
        )
        if P.has_owner_plane:
            dir_owner = jnp.where(dmask, nd_owner[:, None], st.dir_owner)
        else:
            dir_owner = st.dir_owner
        mem = jnp.where(
            blk_hot & mem_write[:, None], mem_val[:, None], st.mem
        )

        # ============== phase B: instruction issue ====================
        elig = (mb_count2 == 0) & ~waiting & ~blocked & (st.pc < st.tr_len)
        if replay:
            pos = jnp.minimum(st.order_pos, st.order_node.shape[0] - 1)
            cur = st.order_node[pos]
            elig = elig & (node_ids == cur) & (st.order_pos < st.order_len)

        pcc = jnp.minimum(st.pc, st.tr_op.shape[1] - 1)
        op = _fetch_n(st.tr_op, pcc)
        ia = _fetch_n(st.tr_addr, pcc)
        iv = _fetch_n(st.tr_val, pcc)
        ci2 = ia % c
        home2 = ia // m

        l2_addr = _gather_n(cache_addr, ci2)
        l2_val = _gather_n(cache_val, ci2)
        l2_state = _gather_n(cache_state, ci2)
        hit = (l2_addr == ia) & (l2_state != _I)
        is_rd = elig & (op == 0)
        is_wr = elig & (op == 1)

        sB0 = _SendSlots(n_local, w)
        sB1 = _SendSlots(n_local, w)

        rm = is_rd & ~hit
        wm = is_wr & ~hit
        ev_issue = _evict_msg(
            sB0, rm | wm, l2_addr, l2_val, l2_state, m, P
        )
        sB1.put(rm, recv=home2, type_=int(MsgType.READ_REQUEST), addr=ia)
        sB1.put(
            wm, recv=home2, type_=int(MsgType.WRITE_REQUEST), addr=ia,
            value=iv,
        )
        wh_me = is_wr & hit & state_in(l2_state, P.silent_write_states, NC)
        wh_s = is_wr & hit & state_in(l2_state, P.upgrade_write_states, NC)
        sB1.put(wh_s, recv=home2, type_=int(MsgType.UPGRADE), addr=ia)

        pending_write = jnp.where(is_wr, iv, st.pending_write)
        waiting = waiting | rm | wm | wh_s

        # cache updates: write-hit value/state; miss placeholder
        i_upd = rm | wm | wh_me | wh_s
        n2_addr = jnp.where(rm | wm, ia, l2_addr)
        n2_val = jnp.where(rm | wm, 0, jnp.where(wh_me | wh_s, iv, l2_val))
        n2_state = jnp.where(
            rm | wm, _I, jnp.where(wh_me | wh_s, _M, l2_state)
        )
        ci2_hot = jnp.arange(c, dtype=I32)[None, :] == ci2[:, None]
        l2mask = ci2_hot & i_upd[:, None]
        cache_addr = jnp.where(l2mask, n2_addr[:, None], cache_addr)
        cache_val = jnp.where(l2mask, n2_val[:, None], cache_val)
        cache_state = jnp.where(l2mask, n2_state[:, None], cache_state)

        pc = st.pc + elig.astype(I32)
        if replay:
            order_pos = st.order_pos + jnp.any(elig).astype(I32)
        else:
            order_pos = st.order_pos

        # merge deferred sends back into their candidate-grid slots:
        # blocked nodes produced no new sends this cycle, so pending
        # and new are exclusive per node and a where-merge is exact
        def _merge_pending(slots, k):
            pv = st.ob_valid[:, k]
            slots.valid = slots.valid | pv
            slots.recv = jnp.where(pv, st.ob_recv[:, k], slots.recv)
            slots.type = jnp.where(pv, st.ob_type[:, k], slots.type)
            slots.addr = jnp.where(pv, st.ob_addr[:, k], slots.addr)
            slots.value = jnp.where(pv, st.ob_value[:, k], slots.value)
            slots.second = jnp.where(pv, st.ob_second[:, k], slots.second)
            slots.sharers = jnp.where(
                pv[:, None], st.ob_sharers[:, k], slots.sharers
            )

        _merge_pending(sA0, 0)
        _merge_pending(sA1, 1)
        pend_inv = st.ob_valid[:, 2]
        inv_valid = inv_valid | pend_inv
        inv_sharers = jnp.where(
            pend_inv[:, None], st.ob_sharers[:, 2], inv_sharers
        )
        inv_addr = jnp.where(pend_inv, st.ob_addr[:, 2], inv_addr)
        _merge_pending(sB0, 3)
        _merge_pending(sB1, 4)

        # ============== phase C: deterministic delivery ===============
        # candidate order per receiver: phase A (sender-major, slots
        # [point0, point1, inv]) then phase B (slots [point0, point1]).
        def stack_slots(slots_list, inv=None):
            fields = {}
            for name in ("valid", "recv", "type", "addr", "value", "second"):
                cols = [getattr(s, name) for s in slots_list]
                if inv is not None:
                    if name == "valid":
                        cols.append(inv_valid)
                    elif name == "recv":
                        cols.append(jnp.full((n_local,), -1, dtype=I32))
                    elif name == "type":
                        cols.append(
                            jnp.full((n_local,), int(MsgType.INV), dtype=I32)
                        )
                    elif name == "addr":
                        cols.append(inv_addr)
                    else:
                        cols.append(jnp.zeros((n_local,), dtype=I32))
                fields[name] = jnp.stack(cols, axis=1).reshape(-1)
            shcols = [s.sharers for s in slots_list]
            if inv is not None:
                shcols.append(jnp.zeros((n_local, w), dtype=U32))
            fields["sharers"] = jnp.stack(shcols, axis=1).reshape(-1, w)
            k = len(slots_list) + (1 if inv is not None else 0)
            fields["sender"] = jnp.repeat(node_ids, k)
            fields["is_inv"] = jnp.tile(
                jnp.array(
                    [False] * len(slots_list)
                    + ([True] if inv is not None else [])
                ),
                n_local,
            )
            return fields

        fa = stack_slots([sA0, sA1], inv=True)
        fb = stack_slots([sB0, sB1])
        floc = {
            key: jnp.concatenate([fa[key], fb[key]], axis=0)
            for key in fa
        }
        j0 = floc["valid"].shape[0]  # 5 * n_local local candidates
        # per-candidate INV fan mask (A-grid slot 2; zero elsewhere)
        zw = jnp.zeros((n_local, w), dtype=U32)
        mask_loc = jnp.concatenate(
            [
                jnp.stack([zw, zw, inv_sharers], axis=1).reshape(-1, w),
                jnp.zeros((2 * n_local, w), dtype=U32),
            ],
            axis=0,
        )
        # global candidate-grid ids: the delivery / per-edge FIFO order
        # key (for one shard this is just arange(j0))
        gid_loc = jnp.concatenate(
            [
                (
                    3 * node_ids[:, None]
                    + jnp.arange(3, dtype=I32)[None, :]
                ).reshape(-1),
                3 * n
                + (
                    2 * node_ids[:, None]
                    + jnp.arange(2, dtype=I32)[None, :]
                ).reshape(-1),
            ]
        )
        isa_loc = jnp.concatenate(
            [
                jnp.ones((3 * n_local,), dtype=I32),
                jnp.zeros((2 * n_local,), dtype=I32),
            ]
        )
        pv_loc = floc["valid"] & ~floc["is_inv"]
        # one shipped word set per candidate: point entries carry their
        # sharer words, INV entries their fan mask (the other side is
        # zero by construction; receivers split the union on is_inv)
        comb_loc = mask_loc | floc["sharers"]

        sharded = axis_name is not None and shards > 1
        if not sharded:
            f = floc
            gid = gid_loc
            isa = isa_loc
            comb = comb_loc
            bounds = [0, j0]
            origins = [jnp.zeros((), dtype=I32)]
            nb = 0
            xctx = None
            xstats = None
        else:
            # targeted exchange (ops/exchange.py): bucket candidates by
            # destination shard (point sends by recv // n_local, INV
            # multicasts by which shards hold fan-mask bits), compact
            # each bucket into a capacity-exact K = 5*n_local buffer
            # (overflow-free by construction) and ship it on the
            # configured collective schedule (exchange_mode: pairwise
            # ppermute rounds, one batched all_to_all, a log-D
            # butterfly, or the two-tier hierarchy) — the old tiled
            # all_gather moved the whole 5N grid every cycle instead.
            me = jax.lax.axis_index(axis_name).astype(I32)
            payload = jnp.stack(
                [
                    floc["type"], floc["sender"], floc["addr"],
                    floc["value"], floc["second"], floc["recv"],
                    gid_loc, floc["is_inv"].astype(I32), isa_loc,
                    pv_loc.astype(I32),
                ]
                + [
                    jax.lax.bitcast_convert_type(comb_loc[:, wi], I32)
                    for wi in range(w)
                ]
                + [
                    # tier-boundary combining key: addr+1 for READ
                    # requests, 0 = not combinable (only hier reads it)
                    jnp.where(
                        pv_loc
                        & (floc["type"] == int(MsgType.READ_REQUEST)),
                        floc["addr"] + 1,
                        0,
                    )
                ],
                axis=0,
            )  # [10 + W + 1, J0]
            k_slots = j0

            def dest_fn(blk, peer):
                pt = (blk[9] != 0) & (blk[5] // n_local == peer)
                lo = peer * n_local
                rmask = exchange.range_mask_words(
                    lo, lo + n_local, w, 32
                )
                cw = jax.lax.bitcast_convert_type(
                    jnp.stack(
                        [blk[10 + wi] for wi in range(w)], axis=-1
                    ),
                    U32,
                )  # [J, W]
                inv = (blk[7] != 0) & jnp.any((cw & rmask) != 0, axis=-1)
                return pt | inv

            def fan_fn(blk, peer):
                # receivers of an entry within shard ``peer``: INV
                # fan-mask popcount over the peer's node range, 1 for
                # point sends
                lo = peer * n_local
                rmask = exchange.range_mask_words(
                    lo, lo + n_local, w, 32
                )
                cw = jax.lax.bitcast_convert_type(
                    jnp.stack(
                        [blk[10 + wi] for wi in range(w)], axis=-1
                    ),
                    U32,
                )
                pop = jnp.sum(
                    jax.lax.population_count(cw & rmask), axis=-1
                ).astype(I32)
                return jnp.where(blk[7] != 0, pop, 1)

            bufs, origins, xctx, xstats = exchange.forward(
                xplan, axis_name, me, payload, dest_fn, k_slots,
                fan_fn=fan_fn, ckey_row=10 + w, nkeys=n * m,
            )
            nb = len(bufs)

            def cat(i, local_row):
                return jnp.concatenate(
                    [local_row] + [b[i] for b in bufs], axis=0
                )

            f = {
                "type": cat(0, floc["type"]),
                "sender": cat(1, floc["sender"]),
                "addr": cat(2, floc["addr"]),
                "value": cat(3, floc["value"]),
                "second": cat(4, floc["second"]),
                "recv": cat(5, floc["recv"]),
                "is_inv": cat(7, floc["is_inv"].astype(I32)) != 0,
            }
            gid = cat(6, gid_loc)
            isa = cat(8, isa_loc)
            pv_row = cat(9, pv_loc.astype(I32)) != 0
            comb = jax.lax.bitcast_convert_type(
                jnp.stack(
                    [
                        cat(
                            10 + wi,
                            jax.lax.bitcast_convert_type(
                                comb_loc[:, wi], I32
                            ),
                        )
                        for wi in range(w)
                    ],
                    axis=1,
                ),
                U32,
            )  # [J, W]
            # zero-filled buffer slots are inert: both masks stay false
            f["valid"] = pv_row | f["is_inv"]
            f["sharers"] = jnp.where(f["is_inv"][:, None], U32(0), comb)
            bounds = [0, j0] + [
                j0 + (i + 1) * k_slots for i in range(nb)
            ]
        j = f["valid"].shape[0]

        # validity per (receiver, candidate)
        point_valid = f["valid"] & ~f["is_inv"]  # [J]
        # inv candidate j is valid for receiver r iff bit r set in the
        # sender's fan mask (shipped per candidate — no gather)
        inv_mask_j = jnp.where(
            f["is_inv"][:, None], comb, jnp.zeros((j, w), dtype=U32)
        )  # [J, W]
        r_word = node_ids // 32
        r_bit = (node_ids % 32).astype(U32)
        inv_hit = (
            (inv_mask_j[:, r_word] >> r_bit[None, :]) & U32(1)
        ).astype(bool).T  # [N_recv, J]
        valid_rj = (
            point_valid[None, :] & (f["recv"][None, :] == node_ids[:, None])
        ) | inv_hit

        # -- link-layer fault injection (static no-op when fault-free) -
        # every valid (receiver, candidate) pair must cross the wire:
        # dropped copies retransmit in-cycle, with the geometric retry
        # count sampled in closed form (failures = floor(ln u / ln p)).
        # A candidate that exhausts ``max_retries`` rounds is treated
        # like a capacity rejection — it defers to the sender's outbox
        # and the link retries next cycle with fresh randomness.  At
        # f32 precision u >= ~1e-37, so failures <= ln(1e-37)/ln(p) —
        # far below any sane budget at moderate rates, which makes the
        # masked schedule (and the final dumps) exactly the fault-free
        # one.  A stalled edge also stalls its later candidates this
        # cycle, keeping per-edge FIFO exact (mirrors spec _deliver).
        if fault_on:
            k_drop, k_dup, k_reo, k_del, rng_key = jax.random.split(
                st.rng_key, 5
            )
            if sharded:
                # each node shard draws an independent link-layer
                # stream (the carried rng_key stays replicated); the
                # retransmission masking invariant makes the dumps
                # byte-identical to the unsharded faulty run anyway
                sid = jax.lax.axis_index(axis_name)
                k_drop = jax.random.fold_in(k_drop, sid)
                k_dup = jax.random.fold_in(k_dup, sid)
                k_reo = jax.random.fold_in(k_reo, sid)
                k_del = jax.random.fold_in(k_del, sid)
            applies = jnp.ones((n_local, j), dtype=bool)
            if fault.edge_sender != -1:
                applies = applies & (
                    f["sender"] == fault.edge_sender
                )[None, :]
            if fault.edge_receiver != -1:
                applies = applies & (
                    node_ids == fault.edge_receiver
                )[:, None]
            if drop_p <= 0.0:
                failures = jnp.zeros((n_local, j), dtype=I32)
            elif drop_p >= 1.0:
                failures = jnp.full((n_local, j), fault.max_retries, I32)
            else:
                u = jax.random.uniform(
                    k_drop, (n_local, j), minval=1e-37, maxval=1.0
                )
                failures = jnp.minimum(
                    jnp.floor(jnp.log(u) / jnp.log(drop_p)).astype(I32),
                    fault.max_retries,
                )
            failures = jnp.where(applies & valid_rj, failures, 0)
            wire_fail = failures >= fault.max_retries
            # same_sender[k, j'] = candidate j' precedes k on k's edge
            # (keyed by the global grid id, which is the edge order in
            # every sharding; zero-filled exchange slots have gid 0 but
            # contribute nothing — their failures are masked to 0)
            cand_ids = gid
            same_sender = (
                f["sender"][:, None] == f["sender"][None, :]
            ) & (cand_ids[:, None] > cand_ids[None, :])
            wire_stall = wire_fail | (
                jnp.einsum(
                    "rj,kj->rk",
                    wire_fail.astype(I32),
                    same_sender.astype(I32),
                )
                > 0
            )
            valid_ok = valid_rj & ~wire_stall
        else:
            rng_key = st.rng_key
            valid_ok = valid_rj

        # capacity backpressure: accept valid candidates in global
        # order until the receiver's mailbox is full; the rest defer to
        # the sender's outbox.  Acceptance is prefix-monotone per
        # receiver (the queue only grows during delivery), so for every
        # ACCEPTED candidate the exclusive prefix count of valid
        # candidates equals the prefix count of accepted ones — offs
        # stays the exact enqueue position.
        if not sharded:
            offs = (
                jnp.cumsum(valid_ok.astype(I32), axis=1)
                - valid_ok.astype(I32)
            )
        else:
            # the received blocks sit in arrival (round) order, which
            # is shard-dependent; rank every entry in the global
            # (phase, origin, slot) candidate order instead — the
            # drop-in sharded replacement for the prefix sum
            isa_r = isa[None, :] != 0
            offs = exchange.ordered_rank(
                valid_ok & isa_r,
                valid_ok & ~isa_r,
                bounds,
                origins,
                axis=1,
            )
        avail = jnp.maximum(cap - mb_count2, 0)
        accept_rj = valid_ok & (offs < avail[:, None])
        delivered = jnp.sum(accept_rj.astype(I32), axis=1)

        # -- interconnect delays (static no-op for the ideal topology) -
        # every ACCEPTED message is charged base path latency plus the
        # per-link queueing penalty of finite bandwidth, computed over
        # the same global walk order the spec engine's _deliver uses
        # (flat candidate-major, receiver-minor = (phase, sender,
        # emission, receiver-ascending)).  Contention is memoryless per
        # cycle, so the whole computation is a pure function of this
        # cycle's accept mask — exactly LinkTracker.on_accept, but
        # vectorized: an exclusive cumsum over the flat walk replaces
        # the sequential per-link load counters.
        if topo_on:
            paths_c = jnp.asarray(paths_np)              # [J, N, L]
            base_c = jnp.asarray(base_np)                # [J, N]
            acc_jr = accept_rj.T                         # [J, N]
            use = acc_jr[:, :, None] & paths_c           # [J, N, L]
            contrib = use
            mc_saved_inc = comb_inc = jnp.zeros((), dtype=I32)
            if ic.multicast:
                # one INV payload per shared link: within a fan-out
                # only the first receiver (ascending) to touch a link
                # contributes; riders still queue behind that single
                # traversal (their penalty prefix includes it)
                u_i = use.astype(I32)
                prior_r = jnp.cumsum(u_i, axis=1) - u_i
                saved = use & f["is_inv"][:, None, None] & (prior_r > 0)
                contrib = contrib & ~saved
                mc_saved_inc = jnp.sum(saved.astype(I32))
            if ic.combining:
                # same-address READ_REQUESTs merge in-network: only the
                # first accepted request per address traverses; merged
                # riders contribute zero occupancy on every link
                jidx = jnp.arange(j, dtype=I32)
                acc_any = jnp.any(acc_jr, axis=1)
                is_read = acc_any & (
                    f["type"] == int(MsgType.READ_REQUEST)
                )
                tbl = jnp.full((n * m,), j, dtype=I32).at[
                    f["addr"]
                ].min(jnp.where(is_read, jidx, j))
                merged_rd = is_read & (tbl[f["addr"]] != jidx)
                contrib = contrib & ~merged_rd[:, None, None]
                comb_inc = jnp.sum(merged_rd.astype(I32))
            c_flat = contrib.reshape(j * n_local, n_links).astype(I32)
            prefix = jnp.cumsum(c_flat, axis=0) - c_flat  # exclusive
            pen_flat = jnp.sum(
                (prefix // ic.link_bandwidth)
                * use.reshape(j * n_local, n_links).astype(I32),
                axis=1,
            )
            penalty = pen_flat.reshape(j, n_local)       # [J, N]
            deliver_rj = (st.cycle + base_c + penalty).T  # [N, J]
            load_l = jnp.sum(c_flat, axis=0)             # [L]
            link_traversals = st.link_traversals + load_l
            link_max_load = jnp.maximum(st.link_max_load, load_l)
            topo_delay_inc = jnp.sum(
                jnp.where(acc_jr, base_c - 1 + penalty, 0)
            )
        else:
            link_traversals = st.link_traversals
            link_max_load = st.link_max_load
            topo_delay_inc = mc_saved_inc = comb_inc = jnp.zeros(
                (), dtype=I32
            )

        # TPU gathers/scatters fused into this graph get scalarized
        # (measured ms-scale); deliver instead by one-hot placement:
        # candidate j lands at queue slot count2 + offs — a dense
        # [N, cap, J] mask reduced against the packed field matrix.
        # Exact in int32: at most one candidate is hot per (node, slot).
        sh_i32 = jax.lax.bitcast_convert_type(f["sharers"], I32)  # [J, w]
        fmat = jnp.concatenate(
            [f["type"][:, None], f["sender"][:, None], f["addr"][:, None],
             f["value"][:, None], f["second"][:, None], sh_i32],
            axis=1,
        )  # [J, F]
        pos = mb_count2[:, None] + offs                       # [N, J]
        slot = jnp.arange(cap, dtype=I32)
        hot = accept_rj[:, None, :] & (pos[:, None, :] == slot[None, :, None])
        # lower the placement to an MXU matmul: split each int32 field
        # into 4 byte planes (exact in bf16 — every product is
        # one-hot x byte, and at most one candidate is hot per slot so
        # sums have at most one nonzero term), multiply, recombine.
        fm_u = jax.lax.bitcast_convert_type(fmat, U32)        # [J, F]
        planes = jnp.concatenate(
            [((fm_u >> (8 * p)) & U32(0xFF)) for p in range(4)], axis=1
        ).astype(jnp.bfloat16)                                # [J, 4F]
        pl = jnp.einsum(
            "ncj,jf->ncf",
            hot.astype(jnp.bfloat16),
            planes,
            preferred_element_type=jnp.float32,
        ).astype(U32)                                         # [N, cap, 4F]
        nf = fmat.shape[1]
        placed_u = (
            pl[..., 0 * nf : 1 * nf]
            | (pl[..., 1 * nf : 2 * nf] << 8)
            | (pl[..., 2 * nf : 3 * nf] << 16)
            | (pl[..., 3 * nf : 4 * nf] << 24)
        )
        placed = jax.lax.bitcast_convert_type(placed_u, I32)  # [N, cap, F]
        if topo_on:
            # the deliver-at column carries cycle magnitudes the bf16
            # byte-plane trick can't represent exactly; place it with a
            # separate int32 one-hot contraction (at most one candidate
            # hot per slot, so the sum has one term — exact)
            dcol = jnp.einsum("ncj,nj->nc", hot.astype(I32), deliver_rj)
            placed = jnp.concatenate([placed, dcol[:, :, None]], axis=2)
        krel = slot[None, :] - mb_count2[:, None]
        write = (krel >= 0) & (krel < delivered[:, None])
        mb_data = jnp.where(write[:, :, None], placed, qdata)
        mb_count3 = mb_count2 + delivered
        ov_now = jnp.any(mb_count3 > cap)

        # -- acceptance feedback to the senders -----------------------
        # per-ENTRY accepted count plus accepted-receiver bit words;
        # remote entries return to their origin shard with one reverse
        # ppermute per round and are scattered back onto the local
        # candidate axis via the saved compaction placement (replacing
        # the old whole-grid psum).  Bits from different shards never
        # collide, so an int32 sum is an exact OR.
        acc_e = jnp.sum(accept_rj.astype(I32), axis=0)        # [J]
        shifted = jax.lax.bitcast_convert_type(
            accept_rj.astype(U32)
            << (node_ids % 32).astype(U32)[:, None],
            I32,
        ).T                                                   # [J, Nl]
        word_sel = (
            (node_ids // 32)[None, :] == jnp.arange(w, dtype=I32)[:, None]
        )                                                     # [W, Nl]
        done_bits = jnp.sum(
            jnp.where(word_sel[:, None, :], shifted[None, :, :], 0),
            axis=2,
        )                                                     # [W, J]
        fbrows = jnp.concatenate([acc_e[None, :], done_bits], axis=0)
        acc_tot = fbrows[:, :j0]
        if sharded and nb:
            fb_blocks = [
                fbrows[:, bounds[i + 1] : bounds[i + 2]]
                for i in range(nb)
            ]
            acc_tot = acc_tot + exchange.feedback(
                xplan, axis_name, fb_blocks, xctx
            )
        acc_j = acc_tot[0]                                    # [J0]
        # a point candidate has exactly one receiver, so "accepted" is
        # acc_j > 0; inv candidates read their accepted-receiver bits
        # back from the fan-out rows (A-grid slot 2 per local sender)
        delivered_inv = jax.lax.bitcast_convert_type(
            acc_tot[1:, 2 : 3 * n_local : 3].T, U32
        )                                                     # [Nl, W]
        rejected_pt = pv_loc & (acc_j == 0)
        rejA = rejected_pt[: 3 * n_local].reshape(n_local, 3)
        rejB = rejected_pt[3 * n_local :].reshape(n_local, 2)
        rem_inv = inv_sharers & ~delivered_inv
        ob_valid = jnp.stack(
            [
                rejA[:, 0],
                rejA[:, 1],
                jnp.any(rem_inv != 0, axis=1),
                rejB[:, 0],
                rejB[:, 1],
            ],
            axis=1,
        )

        def _ob_field(name):
            arr = floc[name]
            fa_l = arr[: 3 * n_local].reshape(n_local, 3)
            fb_l = arr[3 * n_local :].reshape(n_local, 2)
            return jnp.concatenate([fa_l, fb_l], axis=1)      # [Nl, 5]

        ob_recv = _ob_field("recv")
        ob_type = _ob_field("type")
        ob_addr = _ob_field("addr")
        ob_value = _ob_field("value")
        ob_second = _ob_field("second")
        sh_l = jnp.concatenate(
            [
                floc["sharers"][: 3 * n_local].reshape(n_local, 3, w),
                floc["sharers"][3 * n_local :].reshape(n_local, 2, w),
            ],
            axis=1,
        )                                                     # [Nl, 5, W]
        slot_is_inv = jnp.arange(5, dtype=I32) == 2
        ob_sharers = jnp.where(
            slot_is_inv[None, :, None], rem_inv[:, None, :], sh_l
        )
        blocked_next = jnp.any(ob_valid, axis=1)
        instr_inc = jnp.sum(elig.astype(I32))
        msgs_inc = jnp.sum(delivered)
        # observability counters (names match spec_engine.counters)
        cnt = lambda mask: jnp.sum(mask.astype(I32))
        rd_hit_inc = cnt(is_rd & hit)
        rd_miss_inc = cnt(rm)
        wr_hit_inc = cnt(is_wr & hit)
        wr_miss_inc = cnt(wm)
        ev_inc = cnt(ev_replyrd | ev_flush | ev_issue)
        inv_inc = cnt(inv_applied)
        # sends by transaction type: global fan-out count per local
        # candidate (the feedback total), bucketed by the type column
        type_ids = jnp.arange(len(MsgType), dtype=I32)
        mc_inc = jnp.sum(
            jnp.where(
                floc["type"][None, :] == type_ids[:, None],
                acc_j[None, :],
                0,
            ),
            axis=1,
        )  # [len(MsgType)]
        handled_cnt = cnt(has_msg)

        # fault-layer counters (stay exactly zero when fault-free)
        zero = jnp.zeros((), dtype=I32)
        retrans_inc = dup_inc = reo_inc = del_inc = wstall_inc = zero
        if fault_on:
            retrans_inc = jnp.sum(jnp.where(accept_rj, failures, 0))
            wstall_inc = jnp.sum((valid_rj & wire_stall).astype(I32))

            def _event_cnt(key, p):
                if p <= 0.0:
                    return zero
                uu = jax.random.uniform(key, (n_local, j))
                return cnt(accept_rj & applies & (uu < p))

            dup_inc = _event_cnt(k_dup, float(fault.duplicate))
            reo_inc = _event_cnt(k_reo, float(fault.reorder))
            del_inc = _event_cnt(k_del, float(fault.delay))

        # cross-shard exchange telemetry (zero off the sharded path)
        xsent_inc = xmc_inc = xcomb_inc = xhwm = zero
        if sharded:
            xsent_inc = xstats["sent"]
            xmc_inc = xstats["mc_saved"]
            xcomb_inc = xstats["combined"]
            xhwm = xstats["hwm"]
        if axis_name is not None:
            # replicate every global counter (out_specs stay P()) with
            # ONE stacked psum — the collective-count guards pin the
            # cycle loop to the exchange collectives plus this psum
            # (and one pmax for the slot high-water mark)
            parts = [
                jnp.stack(
                    [
                        ov_now.astype(I32), instr_inc, msgs_inc,
                        rd_hit_inc, rd_miss_inc, wr_hit_inc,
                        wr_miss_inc, ev_inc, inv_inc, handled_cnt,
                    ]
                ),
                mc_inc,
            ]
            if fault_on:
                parts.append(
                    jnp.stack(
                        [retrans_inc, wstall_inc, dup_inc, reo_inc,
                         del_inc]
                    )
                )
            if sharded:
                parts.append(
                    jnp.stack([xsent_inc, xmc_inc, xcomb_inc])
                )
            vec = jax.lax.psum(jnp.concatenate(parts), axis_name)
            nt = len(MsgType)
            ov_now = vec[0] > 0
            (instr_inc, msgs_inc, rd_hit_inc, rd_miss_inc, wr_hit_inc,
             wr_miss_inc, ev_inc, inv_inc, handled_cnt) = [
                vec[i] for i in range(1, 10)
            ]
            mc_inc = vec[10 : 10 + nt]
            if fault_on:
                (retrans_inc, wstall_inc, dup_inc, reo_inc, del_inc) = [
                    vec[10 + nt + i] for i in range(5)
                ]
            if sharded:
                base = 10 + nt + (5 if fault_on else 0)
                xsent_inc, xmc_inc, xcomb_inc = [
                    vec[base + i] for i in range(3)
                ]
                xhwm = jax.lax.pmax(xhwm, axis_name)
        overflow = st.overflow | ov_now

        # watchdog progress: an instruction retired or a mailbox
        # drained this cycle (matches SpecEngine.last_activity_cycle)
        progressed = (instr_inc > 0) | (handled_cnt > 0)
        last_progress = jnp.where(progressed, st.cycle, st.last_progress)

        # ============== phase D: dump-at-local-completion =============
        done_node = (
            (pc >= st.tr_len) & ~waiting & (mb_count3 == 0) & ~blocked_next
        )
        snap_now = done_node & ~st.snap_taken
        s2 = snap_now[:, None]
        s3 = snap_now[:, None, None]
        snap_mem = jnp.where(s2, mem, st.snap_mem)
        snap_dir_state = jnp.where(s2, dir_state, st.snap_dir_state)
        snap_dir_sharers = jnp.where(s3, dir_sharers, st.snap_dir_sharers)
        if P.has_owner_plane:
            snap_dir_owner = jnp.where(s2, dir_owner, st.snap_dir_owner)
        else:
            snap_dir_owner = st.snap_dir_owner
        snap_cache_addr = jnp.where(s2, cache_addr, st.snap_cache_addr)
        snap_cache_val = jnp.where(s2, cache_val, st.snap_cache_val)
        snap_cache_state = jnp.where(s2, cache_state, st.snap_cache_state)

        return SimState(
            cache_addr=cache_addr,
            cache_val=cache_val,
            cache_state=cache_state,
            mem=mem,
            dir_state=dir_state,
            dir_sharers=dir_sharers,
            dir_owner=dir_owner,
            mb_data=mb_data,
            mb_count=mb_count3,
            pc=pc,
            waiting=waiting,
            pending_write=pending_write,
            ob_valid=ob_valid,
            ob_recv=ob_recv,
            ob_type=ob_type,
            ob_addr=ob_addr,
            ob_value=ob_value,
            ob_second=ob_second,
            ob_sharers=ob_sharers,
            tr_op=st.tr_op,
            tr_addr=st.tr_addr,
            tr_val=st.tr_val,
            tr_len=st.tr_len,
            order_node=st.order_node,
            order_pos=order_pos,
            order_len=st.order_len,
            snap_taken=st.snap_taken | done_node,
            snap_mem=snap_mem,
            snap_dir_state=snap_dir_state,
            snap_dir_sharers=snap_dir_sharers,
            snap_dir_owner=snap_dir_owner,
            snap_cache_addr=snap_cache_addr,
            snap_cache_val=snap_cache_val,
            snap_cache_state=snap_cache_state,
            cycle=st.cycle + 1,
            n_instr=st.n_instr + instr_inc,
            n_msgs=st.n_msgs + msgs_inc,
            overflow=overflow,
            n_read_hits=st.n_read_hits + rd_hit_inc,
            n_read_miss=st.n_read_miss + rd_miss_inc,
            n_write_hits=st.n_write_hits + wr_hit_inc,
            n_write_miss=st.n_write_miss + wr_miss_inc,
            n_evictions=st.n_evictions + ev_inc,
            n_invalidations=st.n_invalidations + inv_inc,
            msg_counts=st.msg_counts + mc_inc,
            rng_key=rng_key,
            last_progress=last_progress,
            n_retrans=st.n_retrans + retrans_inc,
            n_dup_filtered=st.n_dup_filtered + dup_inc,
            n_reorder_fixed=st.n_reorder_fixed + reo_inc,
            n_delays=st.n_delays + del_inc,
            n_wire_stalls=st.n_wire_stalls + wstall_inc,
            link_traversals=link_traversals,
            link_max_load=link_max_load,
            n_topo_delay=st.n_topo_delay + topo_delay_inc,
            n_multicast_saved=st.n_multicast_saved + mc_saved_inc,
            n_combined=st.n_combined + comb_inc,
            n_elided=st.n_elided,
            n_multi_hit=st.n_multi_hit,
            n_forwards=(
                st.n_forwards if fwd_inc is None
                else st.n_forwards + fwd_inc
            ),
            n_owner_xfer=(
                st.n_owner_xfer if xfer_inc is None
                else st.n_owner_xfer + xfer_inc
            ),
            n_dir_overflow=(
                st.n_dir_overflow if over_inc is None
                else st.n_dir_overflow + over_inc
            ),
            n_exch_sent=st.n_exch_sent + xsent_inc,
            n_exch_hwm=jnp.maximum(st.n_exch_hwm, xhwm),
            n_exch_mc_saved=st.n_exch_mc_saved + xmc_inc,
            n_exch_combined=st.n_exch_combined + xcomb_inc,
        )

    return step


def quiescent(st: SimState) -> jnp.ndarray:
    """Global quiescence: traces exhausted, nobody waiting, mailboxes
    empty (and the replay schedule consumed).  Fixes the reference's
    nontermination (assignment.c:153; SURVEY.md §2.3)."""
    done = (
        jnp.all(st.pc >= st.tr_len)
        & jnp.all(~st.waiting)
        & jnp.all(st.mb_count == 0)
        & jnp.all(~st.ob_valid)
    )
    replay_done = (st.order_len < 0) | (st.order_pos >= st.order_len)
    return done & replay_done


# ===================== event-driven cycle elision =====================
#
# The lockstep loop pays one full device step per simulated cycle even
# when the cycle is provably quiet.  Elision (ISSUE-12) makes the loop
# event-driven, bit-exactly: a cheap on-device reduction (``propose``)
# computes how many upcoming cycles are *certain* to be uneventful —
# no deliverable message, no blocked sender retry, every ready issuer
# sitting on a run of silent cache hits — and a single fast-forward
# step (``fast_forward``) advances the state across all of them at
# once.  Two event classes are collapsed:
#
# * **idle cycles**: nothing in flight (or, under a non-ideal
#   topology, every mailbox head still in transit) — time jumps to the
#   earliest ``deliver_at`` / watchdog / max_cycles boundary;
# * **multi-hit runs**: a node whose next k trace entries are all
#   silent cache hits (read hit on M/E/S, write hit on M/E — no
#   message, no directory or remote-visible transition) retires all k
#   in one step.  Write hits collapse by last-write-wins per cache
#   slot, exactly the serial lockstep result.
#
# A cycle is elidable only when *no* node can act differently from
# "retire a silent hit or idle": any blocked sender (outbox retries
# consume fault-layer randomness and can succeed), any deliverable
# mailbox head, or any ready issuer whose next entry is not a silent
# hit forces a normal lockstep step.  Under fault injection the
# carried PRNG key is split once per simulated cycle by the lockstep
# step, so the fast-forward replays exactly j splits to keep the fault
# stream aligned.  Bit-exactness (dumps, cycle counts, every stat) is
# the contract; ``Config.elide=False`` rebuilds the pure lockstep
# loop.  Device steps executed == ``cycle - n_elided``.

# static trace-window bound for the multi-hit scan: a run longer than
# this retires in ceil(h / window) fast-forward steps (still far ahead
# of lockstep's h steps)
_ELISION_WINDOW = 64
# "no event" distance marker; every real candidate is far smaller
_FAR = np.iinfo(np.int32).max


def _fetch_window(arr, idx):
    """arr [N, T], idx [N, L] -> [N, L]; one-hot below _ONEHOT_MAX_K
    (same TPU scalarized-gather avoidance as ``_fetch_n``)."""
    t = arr.shape[1]
    if t <= _ONEHOT_MAX_K:
        hot = jnp.arange(t, dtype=I32)[None, None, :] == idx[:, :, None]
        return jnp.sum(
            jnp.where(hot, arr[:, None, :], arr.dtype.type(0)), axis=2
        )
    return jnp.take_along_axis(arr, idx, axis=1)


def _issuers(st: SimState, blocked):
    """Nodes that would issue an instruction this cycle (phase-B
    eligibility for a cycle in which no message is handled)."""
    return (
        (st.mb_count == 0) & ~st.waiting & ~blocked & (st.pc < st.tr_len)
    )


def _hit_window(config: SystemConfig, st: SimState):
    """Per-node silent-hit scan over the next ``_ELISION_WINDOW`` trace
    entries -> (op, ia, iv, run_len) with run_len the prefix length of
    entries that retire without any remote-visible transition.

    The predicate is evaluated against the *current* cache planes,
    which is exact for the whole prefix: silent hits never change a
    tag, and the only state transition they make (E -> M on a write
    hit) changes neither the read predicate (state != I) nor the write
    predicate (state in {M, E}) of any later entry.
    """
    c = config.cache_size
    P = planes_for(config.protocol, config.semantics)
    t = st.tr_op.shape[1]
    lw = min(_ELISION_WINDOW, t)
    karr = jnp.arange(lw, dtype=I32)
    pos = st.pc[:, None] + karr[None, :]
    idx = jnp.minimum(pos, t - 1)
    op = _fetch_window(st.tr_op, idx)
    ia = _fetch_window(st.tr_addr, idx)
    iv = _fetch_window(st.tr_val, idx)
    ci = ia % c
    tag = _fetch_window(st.cache_addr, ci)
    stt = _fetch_window(st.cache_state, ci)
    is_w = op == 1
    silent = (
        (pos < st.tr_len[:, None])
        & (tag == ia)
        & jnp.where(
            is_w,
            state_in(stt, P.silent_write_states, P.n_cache_states),
            state_in(stt, P.read_hit_states, P.n_cache_states),
        )
    )
    run_len = jnp.sum(jnp.cumprod(silent.astype(I32), axis=1), axis=1)
    return op, ia, iv, run_len


def build_propose(config: SystemConfig, max_cycles: int = 1_000_000,
                  watchdog_cycles: int = 0):
    """Build ``propose(st) -> [3N + 2] int32`` candidate distances.

    ``min(propose(st))`` is the number of cycles that can be
    fast-forwarded in one device step: 0 means "this cycle may be
    eventful — run the lockstep step"; j >= 1 means cycles
    ``cycle .. cycle + j - 1`` are all provably silent.  Returning the
    un-reduced candidate vector lets every runner fold its own lane /
    shard axes into ONE ``reduce_min`` (the jaxpr guard in
    tests/test_elision.py pins exactly one added reduction).

    Candidate classes (``_FAR`` = no constraint from that source):
    per-node must-step (0 when blocked or a head is deliverable now),
    per-node topology gate (head ``deliver_at - cycle``), per-node
    issuer hit-run length (0 when the next entry is not a silent hit),
    plus two scalars: the watchdog boundary (idle time may not jump
    past ``last_progress + watchdog_cycles`` — simulated-cycle stall
    accounting survives elision) and the ``max_cycles`` boundary.

    Shape-polymorphic over the node axis: under node sharding each
    shard proposes from its local block and the runner folds the shard
    axis into the same ``reduce_min`` (a ``pmin``).  The watchdog
    candidate then keys on the *local* ``any(issuer)`` — a shard that
    sees remote-only progress proposes a conservative (smaller) jump,
    which costs extra device steps but never overshoots, so dumps and
    cycle counts stay exact.
    """
    w = config.sharer_words
    topo_on = config.interconnect.enabled
    mb_deliver = 5 + w  # deliver-at column (topology builds only)

    def propose(st: SimState) -> jnp.ndarray:
        far = jnp.full_like(st.pc, _FAR)
        blocked = jnp.any(st.ob_valid, axis=1)
        has_mail = st.mb_count > 0
        if topo_on:
            head_at = st.mb_data[:, 0, mb_deliver]
            ready_now = has_mail & (head_at <= st.cycle)
            gate = jnp.where(has_mail & ~ready_now, head_at - st.cycle,
                             far)
        else:
            ready_now = has_mail
            gate = far
        issuer = _issuers(st, blocked)
        _, _, _, run_len = _hit_window(config, st)
        must = jnp.where(blocked | ready_now, 0, far)
        hits = jnp.where(issuer, run_len, far)
        if watchdog_cycles:
            gap = st.last_progress + watchdog_cycles - st.cycle
            # issuers advance last_progress every elided cycle, and a
            # lane already past its boundary (possible mid-batch when a
            # sibling lane holds the loop open) idles unchanged either
            # way — both propose no constraint
            wd = jnp.where(jnp.any(issuer) | (gap < 1), _FAR, gap)
        else:
            wd = jnp.asarray(_FAR, dtype=I32)
        cap = jnp.asarray(max_cycles, dtype=I32) - st.cycle
        return jnp.concatenate(
            [must, gate, hits, jnp.stack([wd, cap])]
        )

    return propose


def build_fast_forward(config: SystemConfig,
                       axis_name: Optional[str] = None):
    """Build ``fast_forward(st, j) -> SimState``: advance j >= 1
    provably-silent cycles (j <= min(propose(st))) in one device step.

    With ``axis_name`` the function is a node-sharded SPMD body: the
    retired-instruction counters are replicated with one stacked
    ``psum`` and the watchdog progress trail keys on the *global*
    retire count (an issuer retires at least one hit whenever j >= 1,
    so ``retired > 0`` is exactly ``any(issuer)`` across shards).

    Issuers retire exactly j silent hits each (j never exceeds any
    issuer's run length, so trace completion can only land on the jump
    end); everyone else idles.  No message moves, so mailboxes,
    outboxes, directories and memory are untouched; write hits apply
    last-write-wins per cache slot and the final ``pending_write``
    mirrors lockstep's per-write overwrite.  Under fault injection the
    PRNG key replays the j per-cycle splits the lockstep step would
    have drawn (their samples are never observed in a silent cycle —
    no candidate crosses the wire).
    """
    fault_on = config.fault.enabled
    c = config.cache_size
    P = planes_for(config.protocol, config.semantics)

    def fast_forward(st: SimState, j: jnp.ndarray) -> SimState:
        blocked = jnp.any(st.ob_valid, axis=1)  # all-false given j >= 1
        issuer = _issuers(st, blocked)
        op, ia, iv, _ = _hit_window(config, st)
        lw = op.shape[1]
        karr = jnp.arange(lw, dtype=I32)
        in_run = issuer[:, None] & (karr[None, :] < j)
        is_w = in_run & (op == 1)
        # last write per cache slot wins — the serial per-cycle write
        # hits collapsed into one scatter ([N, L, C] one-hot; lastk is
        # 1-based so 0 = "slot untouched")
        slot_hot = (
            (ia % c)[:, :, None] == jnp.arange(c, dtype=I32)[None, None, :]
        )
        wslot = is_w[:, :, None] & slot_hot
        lastk = jnp.max(
            jnp.where(wslot, karr[None, :, None] + 1, 0), axis=1
        )
        wrote = lastk > 0
        wval = jnp.sum(
            jnp.where(
                (karr[None, :, None] + 1) == lastk[:, None, :],
                iv[:, :, None], 0,
            ),
            axis=1,
        )
        cache_val = jnp.where(wrote, wval, st.cache_val)
        cache_state = jnp.where(wrote, P.M, st.cache_state)
        # lockstep overwrites pending_write on EVERY write issue (hits
        # included): the jump leaves the last written value behind
        lastw = jnp.max(jnp.where(is_w, karr[None, :] + 1, 0), axis=1)
        pwval = jnp.sum(
            jnp.where((karr[None, :] + 1) == lastw[:, None], iv, 0),
            axis=1,
        )
        pending_write = jnp.where(lastw > 0, pwval, st.pending_write)

        retired = jnp.sum(in_run.astype(I32))
        rd_inc = jnp.sum((in_run & (op == 0)).astype(I32))
        wr_inc = jnp.sum(is_w.astype(I32))
        if axis_name is not None:
            g = jax.lax.psum(
                jnp.stack([retired, rd_inc, wr_inc]), axis_name
            )
            retired, rd_inc, wr_inc = g[0], g[1], g[2]
            any_issuer = retired > 0
        else:
            any_issuer = jnp.any(issuer)
        pc = st.pc + jnp.where(issuer, j, 0)
        cycle = st.cycle + j
        # every elided cycle with issuers retires instructions, so the
        # watchdog sees the same progress trail as lockstep
        last_progress = jnp.where(
            any_issuer, cycle - 1, st.last_progress
        )
        if fault_on:
            # lockstep splits the carried key once per cycle whether or
            # not anything crosses the wire; replay exactly j splits
            rng_key = jax.lax.fori_loop(
                0, j, lambda _, k: jax.random.split(k, 5)[4], st.rng_key
            )
        else:
            rng_key = st.rng_key
        # phase D at the jump end: completion only lands there (mid-run
        # pc + t < tr_len), and an already-done node's planes are
        # untouched by other nodes' silent hits, so the snapshot equals
        # the one lockstep would have taken at its completion cycle
        done_node = (
            (pc >= st.tr_len) & ~st.waiting & (st.mb_count == 0) & ~blocked
        )
        snap_now = done_node & ~st.snap_taken
        s2 = snap_now[:, None]
        s3 = snap_now[:, None, None]
        return st._replace(
            cache_val=cache_val,
            cache_state=cache_state,
            pending_write=pending_write,
            pc=pc,
            snap_taken=st.snap_taken | done_node,
            snap_mem=jnp.where(s2, st.mem, st.snap_mem),
            snap_dir_state=jnp.where(s2, st.dir_state, st.snap_dir_state),
            snap_dir_sharers=jnp.where(
                s3, st.dir_sharers, st.snap_dir_sharers
            ),
            snap_cache_addr=jnp.where(
                s2, st.cache_addr, st.snap_cache_addr
            ),
            snap_cache_val=jnp.where(s2, cache_val, st.snap_cache_val),
            snap_cache_state=jnp.where(
                s2, cache_state, st.snap_cache_state
            ),
            cycle=cycle,
            n_instr=st.n_instr + retired,
            n_read_hits=st.n_read_hits + rd_inc,
            n_write_hits=st.n_write_hits + wr_inc,
            rng_key=rng_key,
            last_progress=last_progress,
            n_elided=st.n_elided + j - 1,
            n_multi_hit=st.n_multi_hit + retired,
        )

    return fast_forward


def build_elided_body(config: SystemConfig, max_cycles: int = 1_000_000,
                      watchdog_cycles: int = 0, batched: bool = False):
    """The event-driven while-loop body: one reduction picks the jump
    distance, one ``lax.cond`` selects fast-forward vs lockstep.

    Batched: the jump is the minimum over every lane's candidates —
    lanes share one cycle counter in batched runs, so a single shared
    jump keeps all per-lane schedules exactly lockstep's.
    """
    step = build_step(config)
    propose = build_propose(config, max_cycles, watchdog_cycles)
    ff = build_fast_forward(config)
    if batched:
        vstep = jax.vmap(step)
        vff = jax.vmap(ff, in_axes=(0, None))

        def body(st: SimState) -> SimState:
            j = jnp.min(jax.vmap(propose)(st))
            return jax.lax.cond(j > 0, lambda s: vff(s, j), vstep, st)

    else:

        def body(st: SimState) -> SimState:
            j = jnp.min(propose(st))
            return jax.lax.cond(j > 0, lambda s: ff(s, j), step, st)

    return body


@functools.lru_cache(maxsize=64)
def build_run(config: SystemConfig, replay: bool = False,
              max_cycles: int = 1_000_000, watchdog_cycles: int = 0):
    """Jitted run-to-quiescence via lax.while_loop (stays on device).

    Cached per (config, replay, max_cycles, watchdog_cycles) so
    repeated engine instances reuse the compiled executable
    (SystemConfig is frozen / hashable).

    ``watchdog_cycles > 0`` adds the stall watchdog to the loop
    condition: the loop exits early once no instruction has retired
    and no mailbox has drained for that many consecutive cycles —
    the only on-device early-exit for livelocks, which otherwise
    burn the full ``max_cycles`` budget before the host notices.

    With ``config.elide`` (and outside replay mode, which pins a
    per-cycle issue schedule) the loop body is the event-driven one —
    bit-identical results in fewer device steps (``st.n_elided``
    counts the skipped cycles).
    """
    if config.elide and not replay:
        step = build_elided_body(config, max_cycles, watchdog_cycles)
    else:
        step = build_step(config, replay=replay)

    def cond(st):
        live = (~quiescent(st)) & (st.cycle < max_cycles) & (~st.overflow)
        if watchdog_cycles:
            live = live & (
                (st.cycle - st.last_progress) < watchdog_cycles
            )
        return live

    def run(st: SimState) -> SimState:
        return jax.lax.while_loop(cond, step, st)

    return jax.jit(run)
