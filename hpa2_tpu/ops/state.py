"""Struct-of-arrays simulator state (the JAX backend's data model).

The reference keeps each node's state in a per-thread C struct
(assignment.c:70-81) and communicates through locked ring-buffer
mailboxes (assignment.c:63-68, 90-91).  The TPU-native layout turns
every field into an array over the node axis (and, via vmap, a batch
axis), and the mailboxes into fixed-capacity ring buffers
``[nodes, cap]`` updated by masked scatters inside one jitted step —
no locks: lockstep scheduling makes delivery deterministic
(SURVEY.md §2.4, §5).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import (
    Instr,
    INVALID_ADDR,
    CacheState,
    DirState,
    MsgType,
)
from hpa2_tpu.utils.trace import IssueRecord

I32 = jnp.int32
U32 = jnp.uint32

# mb_data column layout.  Sharer words occupy [MB_SHARERS, MB_SHARERS+W);
# non-ideal interconnect builds append one deliver-at column at 5 + W
# (ideal keeps the exact historical 5 + W row, so ideal states — and
# their checkpoints — stay byte-identical to pre-topology builds).
MB_TYPE, MB_SENDER, MB_ADDR, MB_VALUE, MB_SECOND, MB_SHARERS = 0, 1, 2, 3, 4, 5


def mb_width(config: SystemConfig) -> int:
    """mb_data row width: 5 + sharer words (+ deliver-at column when a
    non-ideal topology is configured)."""
    return 5 + config.sharer_words + (1 if config.interconnect.enabled else 0)


def num_links(config: SystemConfig) -> int:
    """Length of the per-link counter planes (>= 1 so ideal states
    keep a fixed-shape placeholder instead of a zero-width array)."""
    ic = config.interconnect
    if not ic.enabled:
        return 1
    from hpa2_tpu.interconnect.topology import build_topology

    return max(
        1, build_topology(ic.topology, config.num_procs,
                          ic.hop_latency).num_links
    )


def _mb_empty_row(w: int, deliver: bool = False) -> np.ndarray:
    """Packed empty-slot sentinel (type=-1, second=-1)."""
    return np.array(
        [-1, 0, 0, 0, -1] + [0] * w + ([0] if deliver else []),
        dtype=np.int32,
    )


def _mem_init(n: int, m: int) -> np.ndarray:
    """Reference memory init ``(20*id + i) mod 256`` (assignment.c:779)."""
    return np.array(
        [[(20 * i + j) % 256 for j in range(m)] for i in range(n)],
        dtype=np.int32,
    )


class SimState(NamedTuple):
    """One simulated system (no batch axis; vmap adds it)."""

    # caches [N, C]
    cache_addr: jnp.ndarray
    cache_val: jnp.ndarray
    cache_state: jnp.ndarray
    # home memory + directory [N, M] (+ [W] sharer words)
    mem: jnp.ndarray
    dir_state: jnp.ndarray
    dir_sharers: jnp.ndarray  # [N, M, W] uint32
    # protocol-variant owner/forwarder pointer [N, M] (node id, -1 =
    # none).  MOESI: the OWNED cache while dir_state == SO; MESIF: the
    # FORWARD cache while dir_state == S.  Always present with uniform
    # shape; MESI carries it untouched at -1.
    dir_owner: jnp.ndarray
    # mailboxes: shift-down FIFO queues, head always at slot 0 (reads
    # are static slices; no gather — TPU scalarizes fused gathers).
    # One packed [N, cap, F] int32 array, columns = MB_* below
    # (sharer words bitcast to int32).
    mb_data: jnp.ndarray  # [N, cap, 5 + W]
    mb_count: jnp.ndarray  # [N]
    # core state [N]
    pc: jnp.ndarray
    waiting: jnp.ndarray  # bool
    pending_write: jnp.ndarray
    # deferred-send outbox (capacity backpressure): the candidate-grid
    # slots [A0, A1, AINV, B0, B1] of this node's last action that did
    # not fit their receiver's mailbox.  While any slot is valid the
    # node is BLOCKED (no handle, no issue) — the lockstep analog of
    # the reference's blocking enqueue (assignment.c:715-724).  Slot 2
    # (AINV) keeps the *remaining* INV delivery mask in ob_sharers.
    ob_valid: jnp.ndarray    # [N, 5] bool
    ob_recv: jnp.ndarray     # [N, 5]
    ob_type: jnp.ndarray     # [N, 5]
    ob_addr: jnp.ndarray     # [N, 5]
    ob_value: jnp.ndarray    # [N, 5]
    ob_second: jnp.ndarray   # [N, 5]
    ob_sharers: jnp.ndarray  # [N, 5, W] uint32
    # traces [N, T]
    tr_op: jnp.ndarray  # 0 = RD, 1 = WR
    tr_addr: jnp.ndarray
    tr_val: jnp.ndarray
    tr_len: jnp.ndarray  # [N]
    # replay schedule [L] (L=1 dummy when not replaying)
    order_node: jnp.ndarray
    order_pos: jnp.ndarray  # scalar
    order_len: jnp.ndarray  # scalar
    # dump-at-local-completion snapshots
    snap_taken: jnp.ndarray  # [N] bool
    snap_mem: jnp.ndarray
    snap_dir_state: jnp.ndarray
    snap_dir_sharers: jnp.ndarray
    snap_dir_owner: jnp.ndarray
    snap_cache_addr: jnp.ndarray
    snap_cache_val: jnp.ndarray
    snap_cache_state: jnp.ndarray
    # bookkeeping (scalars)
    cycle: jnp.ndarray
    n_instr: jnp.ndarray
    n_msgs: jnp.ndarray
    overflow: jnp.ndarray  # bool: a mailbox exceeded capacity
    # observability counters (the reference has none — SURVEY.md §5);
    # names/semantics match spec_engine.counters for differential tests
    n_read_hits: jnp.ndarray
    n_read_miss: jnp.ndarray
    n_write_hits: jnp.ndarray
    n_write_miss: jnp.ndarray
    n_evictions: jnp.ndarray
    n_invalidations: jnp.ndarray
    msg_counts: jnp.ndarray  # [len(MsgType)] sends by transaction type
    # link-layer fault injection + watchdog bookkeeping (scalars;
    # rng_key is a raw uint32[2] PRNG key, split once per cycle)
    rng_key: jnp.ndarray        # [2] uint32
    last_progress: jnp.ndarray  # last cycle that retired/drained
    n_retrans: jnp.ndarray      # link retransmission rounds
    n_dup_filtered: jnp.ndarray
    n_reorder_fixed: jnp.ndarray
    n_delays: jnp.ndarray
    n_wire_stalls: jnp.ndarray  # retry budget exhausted -> deferred
    # interconnect model counters (hpa2_tpu/interconnect/): per-link
    # planes are [num_links(config)] ([1] zero placeholders for ideal)
    link_traversals: jnp.ndarray   # [L] accepted traversals per link
    link_max_load: jnp.ndarray     # [L] max single-cycle occupancy
    n_topo_delay: jnp.ndarray      # extra delay cycles beyond ideal
    n_multicast_saved: jnp.ndarray # link traversals saved by multicast
    n_combined: jnp.ndarray        # READ_REQUESTs merged in-network
    # event-driven elision counters (ISSUE-12; scalars).  device_steps
    # executed == cycle - n_elided; both stay zero under Config.elide
    # =False and on engines that run lockstep (spec, pallas).
    n_elided: jnp.ndarray     # simulated cycles skipped by fast-forward
    n_multi_hit: jnp.ndarray  # instructions retired inside fast-forwards
    # protocol-variant counters (ISSUE-13; scalars, zero under MESI/full)
    n_forwards: jnp.ndarray      # cache-to-cache fills w/o a home copy
    n_owner_xfer: jnp.ndarray    # owner/forwarder pointer migrations
    n_dir_overflow: jnp.ndarray  # limited-pointer broadcast fallbacks
    # cross-shard exchange telemetry (ISSUE-15; scalars, zero off the
    # node-sharded path).  hwm is a running max, the rest accumulate.
    n_exch_sent: jnp.ndarray      # entries shipped across node shards
    n_exch_hwm: jnp.ndarray       # per-bucket slot demand high-water
    n_exch_mc_saved: jnp.ndarray  # INV unicast slots saved by masks
    n_exch_combined: jnp.ndarray  # same-addr reads combinable at tier


def init_state_batched(
    config: SystemConfig,
    tr_op: np.ndarray,
    tr_addr: np.ndarray,
    tr_val: np.ndarray,
    tr_len: np.ndarray,
) -> SimState:
    """Batched initial state straight from trace arrays.

    ``tr_op/tr_addr/tr_val`` are ``[B, N, T]`` (op: 0=RD, 1=WR, -1=pad),
    ``tr_len`` is ``[B, N]``.  Equivalent to ``stack_states([init_state(
    config, traces_b) for b ...])`` but without the per-system Python
    loops — the only viable construction path for large ensembles.
    """
    b, n, t = tr_op.shape
    c, m, w = config.cache_size, config.mem_size, config.sharer_words
    cap = config.msg_buffer_size
    if n != config.num_procs:
        raise ValueError(f"trace node axis {n} != num_procs {config.num_procs}")
    for name, arr in (("tr_addr", tr_addr), ("tr_val", tr_val)):
        if arr.shape != (b, n, t):
            raise ValueError(f"{name} shape {arr.shape} != {(b, n, t)}")
    if tr_len.shape != (b, n):
        raise ValueError(f"tr_len shape {tr_len.shape} != {(b, n)}")
    if np.any(tr_len < 0) or np.any(tr_len > t):
        raise ValueError(f"tr_len out of range 0..{t}")

    mem0 = np.broadcast_to(_mem_init(n, m), (b, n, m))
    topo_on = config.interconnect.enabled
    links = num_links(config)
    full = lambda shape, val, dt: jnp.full(shape, val, dtype=dt)
    zeros = lambda shape, dt: jnp.zeros(shape, dtype=dt)
    return SimState(
        cache_addr=full((b, n, c), INVALID_ADDR, I32),
        cache_val=zeros((b, n, c), I32),
        cache_state=full((b, n, c), int(CacheState.INVALID), I32),
        mem=jnp.asarray(mem0),
        dir_state=full((b, n, m), int(DirState.U), I32),
        dir_sharers=zeros((b, n, m, w), U32),
        dir_owner=full((b, n, m), -1, I32),
        mb_data=jnp.broadcast_to(
            jnp.asarray(_mb_empty_row(w, topo_on)),
            (b, n, cap, 5 + w + topo_on),
        ),
        mb_count=zeros((b, n), I32),
        pc=zeros((b, n), I32),
        waiting=zeros((b, n), bool),
        pending_write=zeros((b, n), I32),
        ob_valid=zeros((b, n, 5), bool),
        ob_recv=zeros((b, n, 5), I32),
        ob_type=full((b, n, 5), -1, I32),
        ob_addr=zeros((b, n, 5), I32),
        ob_value=zeros((b, n, 5), I32),
        ob_second=full((b, n, 5), -1, I32),
        ob_sharers=zeros((b, n, 5, w), U32),
        tr_op=jnp.asarray(tr_op, dtype=I32),
        tr_addr=jnp.asarray(tr_addr, dtype=I32),
        tr_val=jnp.asarray(tr_val, dtype=I32),
        tr_len=jnp.asarray(tr_len, dtype=I32),
        order_node=full((b, 1), -1, I32),
        order_pos=zeros((b,), I32),
        order_len=full((b,), -1, I32),
        snap_taken=zeros((b, n), bool),
        snap_mem=jnp.asarray(mem0),
        snap_dir_state=full((b, n, m), int(DirState.U), I32),
        snap_dir_sharers=zeros((b, n, m, w), U32),
        snap_dir_owner=full((b, n, m), -1, I32),
        snap_cache_addr=full((b, n, c), INVALID_ADDR, I32),
        snap_cache_val=zeros((b, n, c), I32),
        snap_cache_state=full((b, n, c), int(CacheState.INVALID), I32),
        cycle=zeros((b,), I32),
        n_instr=zeros((b,), I32),
        n_msgs=zeros((b,), I32),
        overflow=zeros((b,), bool),
        n_read_hits=zeros((b,), I32),
        n_read_miss=zeros((b,), I32),
        n_write_hits=zeros((b,), I32),
        n_write_miss=zeros((b,), I32),
        n_evictions=zeros((b,), I32),
        n_invalidations=zeros((b,), I32),
        msg_counts=zeros((b, len(MsgType)), I32),
        rng_key=jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            jax.random.PRNGKey(config.fault.seed), jnp.arange(b)
        ),
        last_progress=zeros((b,), I32),
        n_retrans=zeros((b,), I32),
        n_dup_filtered=zeros((b,), I32),
        n_reorder_fixed=zeros((b,), I32),
        n_delays=zeros((b,), I32),
        n_wire_stalls=zeros((b,), I32),
        link_traversals=zeros((b, links), I32),
        link_max_load=zeros((b, links), I32),
        n_topo_delay=zeros((b,), I32),
        n_multicast_saved=zeros((b,), I32),
        n_combined=zeros((b,), I32),
        n_elided=zeros((b,), I32),
        n_multi_hit=zeros((b,), I32),
        n_forwards=zeros((b,), I32),
        n_owner_xfer=zeros((b,), I32),
        n_dir_overflow=zeros((b,), I32),
        n_exch_sent=zeros((b,), I32),
        n_exch_hwm=zeros((b,), I32),
        n_exch_mc_saved=zeros((b,), I32),
        n_exch_combined=zeros((b,), I32),
    )


def init_state(
    config: SystemConfig,
    traces: Sequence[Sequence[Instr]],
    replay_order: Optional[Sequence[IssueRecord]] = None,
    max_trace_len: Optional[int] = None,
) -> SimState:
    """Build the initial SoA state (mirrors initializeProcessor,
    assignment.c:776-822: memory ``(20*id+i) mod 256``, directory all
    U/empty, caches invalid)."""
    n, c, m, w = (
        config.num_procs,
        config.cache_size,
        config.mem_size,
        config.sharer_words,
    )
    cap = config.msg_buffer_size
    t = max(
        max_trace_len or 0, max((len(tr) for tr in traces), default=0), 1
    )

    tr_op = np.full((n, t), -1, dtype=np.int32)
    tr_addr = np.zeros((n, t), dtype=np.int32)
    tr_val = np.zeros((n, t), dtype=np.int32)
    tr_len = np.zeros((n,), dtype=np.int32)
    for i, tr in enumerate(traces):
        tr_len[i] = len(tr)
        for j, ins in enumerate(tr):
            tr_op[i, j] = 0 if ins.op == "R" else 1
            tr_addr[i, j] = ins.address
            tr_val[i, j] = ins.value

    if replay_order is not None:
        order_node = np.array([r.proc for r in replay_order], dtype=np.int32)
        if order_node.size == 0:
            order_node = np.array([-1], dtype=np.int32)
        order_len = np.int32(len(replay_order))
    else:
        order_node = np.array([-1], dtype=np.int32)
        order_len = np.int32(-1)  # -1 = free-run

    mem0 = _mem_init(n, m)
    topo_on = config.interconnect.enabled
    links = num_links(config)

    return SimState(
        cache_addr=jnp.full((n, c), INVALID_ADDR, dtype=I32),
        cache_val=jnp.zeros((n, c), dtype=I32),
        cache_state=jnp.full((n, c), int(CacheState.INVALID), dtype=I32),
        mem=jnp.asarray(mem0),
        dir_state=jnp.full((n, m), int(DirState.U), dtype=I32),
        dir_sharers=jnp.zeros((n, m, w), dtype=U32),
        dir_owner=jnp.full((n, m), -1, dtype=I32),
        mb_data=jnp.broadcast_to(
            jnp.asarray(_mb_empty_row(w, topo_on)),
            (n, cap, 5 + w + topo_on),
        ),
        mb_count=jnp.zeros((n,), dtype=I32),
        pc=jnp.zeros((n,), dtype=I32),
        waiting=jnp.zeros((n,), dtype=bool),
        pending_write=jnp.zeros((n,), dtype=I32),
        ob_valid=jnp.zeros((n, 5), dtype=bool),
        ob_recv=jnp.zeros((n, 5), dtype=I32),
        ob_type=jnp.full((n, 5), -1, dtype=I32),
        ob_addr=jnp.zeros((n, 5), dtype=I32),
        ob_value=jnp.zeros((n, 5), dtype=I32),
        ob_second=jnp.full((n, 5), -1, dtype=I32),
        ob_sharers=jnp.zeros((n, 5, w), dtype=U32),
        tr_op=jnp.asarray(tr_op),
        tr_addr=jnp.asarray(tr_addr),
        tr_val=jnp.asarray(tr_val),
        tr_len=jnp.asarray(tr_len),
        order_node=jnp.asarray(order_node),
        order_pos=jnp.zeros((), dtype=I32),
        order_len=jnp.asarray(order_len),
        snap_taken=jnp.zeros((n,), dtype=bool),
        snap_mem=jnp.asarray(mem0),
        snap_dir_state=jnp.full((n, m), int(DirState.U), dtype=I32),
        snap_dir_sharers=jnp.zeros((n, m, w), dtype=U32),
        snap_dir_owner=jnp.full((n, m), -1, dtype=I32),
        snap_cache_addr=jnp.full((n, c), INVALID_ADDR, dtype=I32),
        snap_cache_val=jnp.zeros((n, c), dtype=I32),
        snap_cache_state=jnp.full((n, c), int(CacheState.INVALID), dtype=I32),
        cycle=jnp.zeros((), dtype=I32),
        n_instr=jnp.zeros((), dtype=I32),
        n_msgs=jnp.zeros((), dtype=I32),
        overflow=jnp.zeros((), dtype=bool),
        n_read_hits=jnp.zeros((), dtype=I32),
        n_read_miss=jnp.zeros((), dtype=I32),
        n_write_hits=jnp.zeros((), dtype=I32),
        n_write_miss=jnp.zeros((), dtype=I32),
        n_evictions=jnp.zeros((), dtype=I32),
        n_invalidations=jnp.zeros((), dtype=I32),
        msg_counts=jnp.zeros((len(MsgType),), dtype=I32),
        rng_key=jax.random.PRNGKey(config.fault.seed),
        last_progress=jnp.zeros((), dtype=I32),
        n_retrans=jnp.zeros((), dtype=I32),
        n_dup_filtered=jnp.zeros((), dtype=I32),
        n_reorder_fixed=jnp.zeros((), dtype=I32),
        n_delays=jnp.zeros((), dtype=I32),
        n_wire_stalls=jnp.zeros((), dtype=I32),
        link_traversals=jnp.zeros((links,), dtype=I32),
        link_max_load=jnp.zeros((links,), dtype=I32),
        n_topo_delay=jnp.zeros((), dtype=I32),
        n_multicast_saved=jnp.zeros((), dtype=I32),
        n_combined=jnp.zeros((), dtype=I32),
        n_elided=jnp.zeros((), dtype=I32),
        n_multi_hit=jnp.zeros((), dtype=I32),
        n_forwards=jnp.zeros((), dtype=I32),
        n_owner_xfer=jnp.zeros((), dtype=I32),
        n_dir_overflow=jnp.zeros((), dtype=I32),
        n_exch_sent=jnp.zeros((), dtype=I32),
        n_exch_hwm=jnp.zeros((), dtype=I32),
        n_exch_mc_saved=jnp.zeros((), dtype=I32),
        n_exch_combined=jnp.zeros((), dtype=I32),
    )
