"""The always-on serving loop: continuous-batching admission with
overlapped host-device staging.

One-shot scheduled runs (PR-6) take a *closed* ensemble and replay the
:class:`~hpa2_tpu.ops.schedule.LaneScheduler` over it.  Serving keeps
the same resident lanes alive forever and grows the schedule as jobs
arrive — admissions ride the existing segment-barrier transform, so
the device programs never see a new shape and **never recompile**
after warmup (pinned by :meth:`ServingStats.compile_counts`).

The perf core is the double-buffered admission pipeline on the Pallas
path (:class:`ServingSession`).  Per interval ``k`` the host:

1. polls the job source and packs arrivals into the
   :class:`TracePool` (host staging),
2. assembles + ``device_put``\\ s interval ``k``'s trace windows
   (host staging),
3. dispatches ``advance`` — JAX async dispatch returns immediately,
4. plans the barrier, dispatches harvest gathers then the barrier,
5. only *then* syncs on interval ``k-1``'s status and decodes
   ``k-1``'s harvested dumps.

So while the device runs interval ``k``, the host is already parsing
and staging interval ``k+1``'s admission wave.  ``overlap=False``
forces the sync right after each dispatch — the serial baseline the
benchmark uses to show how much staging time the pipeline hides.

:class:`BatchServingSession` is the XLA-backend analog (and the only
one with the fault-injection layer).  Row completion there is a device
property (quiescence), so the loop syncs once per chunk; ingest
staging — building arriving jobs' initial row states — still overlaps
the in-flight chunk.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.ops.schedule import (
    LaneScheduler, OccupancyStats, policy_order, segments_needed)
from hpa2_tpu.serving.ingest import JobSource
from hpa2_tpu.serving.jobs import Job, JobResult


class TracePool:
    """Packed trace words for every admitted-but-unfinished system, in
    one growing ``[N, columns]`` plane so per-interval window assembly
    is a single vectorized gather (no per-lane Python at 32k lanes).

    Each system ``s`` owns ``nseg[s] * window`` contiguous columns at
    ``off[s]`` (its packed trace zero-padded to whole windows).  Freed
    blocks accumulate as waste; when waste exceeds the live half the
    pool compacts live blocks down (system ids are stable — only
    offsets move).
    """

    def __init__(self, config: SystemConfig, window: int,
                 capacity: int = 4096):
        from hpa2_tpu.ops.pallas_engine import _pack_traces

        self._pack = _pack_traces
        self.config = config
        self.window = int(window)
        n = config.num_procs
        self._words = np.zeros((n, max(self.window, capacity)), np.int32)
        self._off = np.zeros(64, np.int64)
        self._plen = np.zeros((n, 64), np.int32)
        self._nseg = np.zeros(64, np.int64)
        self.count = 0          # systems ever added (== scheduler's b)
        self._used = 0          # columns handed out (tail pointer)
        self._waste = 0         # columns owned by freed systems
        self._freed: set = set()

    def _grow_meta(self) -> None:
        if self.count < len(self._off):
            return
        cap = 2 * len(self._off)
        self._off = np.resize(self._off, cap)
        self._nseg = np.resize(self._nseg, cap)
        plen = np.zeros((self._plen.shape[0], cap), np.int32)
        plen[:, : self._plen.shape[1]] = self._plen
        self._plen = plen

    def _reserve(self, cols: int) -> None:
        need = self._used + cols
        if need <= self._words.shape[1]:
            return
        cap = self._words.shape[1]
        while cap < need:
            cap *= 2
        words = np.zeros((self._words.shape[0], cap), np.int32)
        words[:, : self._used] = self._words[:, : self._used]
        self._words = words

    def add(self, job: Job) -> int:
        """Pack one arriving job; returns its system id (the next
        scheduler id, in arrival order)."""
        w = self.window
        ln = np.asarray(job.tr_len, np.int32)
        nseg = int(segments_needed(ln[:, None], w)[0])
        cols = nseg * w
        self._grow_meta()
        self._reserve(cols)
        # the packer keeps the input array width; columns past
        # nseg * window are guaranteed zero (beyond every tr_len), so
        # truncate to this system's allocation
        packed = self._pack(
            self.config,
            np.asarray(job.tr_op)[None],
            np.asarray(job.tr_addr)[None],
            np.asarray(job.tr_val)[None],
            ln[None],
        )[:, :cols, 0]
        s = self.count
        off = self._used
        self._words[:, off:off + packed.shape[1]] = packed
        self._words[:, off + packed.shape[1]:off + cols] = 0
        self._off[s] = off
        self._plen[:, s] = ln
        self._nseg[s] = nseg
        self.count += 1
        self._used += cols
        return s

    def nseg_of(self, s: int) -> int:
        return int(self._nseg[s])

    def free(self, s: int) -> None:
        """Release a retired system's columns (lazily — reclaimed by
        the next compaction)."""
        if s in self._freed:
            return
        self._freed.add(s)
        self._waste += int(self._nseg[s]) * self.window
        if self._waste > max(4 * self.window,
                             (self._used - self._waste)):
            self._compact()

    def _compact(self) -> None:
        live = [s for s in range(self.count) if s not in self._freed
                and self._nseg[s] > 0]
        live.sort(key=lambda s: int(self._off[s]))
        dst = 0
        for s in live:
            cols = int(self._nseg[s]) * self.window
            src = int(self._off[s])
            if src != dst:
                self._words[:, dst:dst + cols] = \
                    self._words[:, src:src + cols]
                self._off[s] = dst
            dst += cols
        for s in self._freed:
            self._nseg[s] = 0
        self._used = dst
        self._waste = 0

    def windows(self, lanes: np.ndarray, lane_sys: np.ndarray,
                lane_seg: np.ndarray, resident: int):
        """Assemble one interval's ``[N, W, R]`` trace plane and
        ``[N, R]`` window lengths for the live lanes — the vectorized
        analog of the one-shot engine's per-interval gather."""
        n, w = self.config.num_procs, self.window
        tr_int = np.zeros((n, w, resident), np.int32)
        tl_int = np.zeros((n, resident), np.int32)
        if len(lanes):
            sys_ = lane_sys[lanes]
            base = lane_seg[lanes] * w
            cols = (self._off[sys_] + base)[None, :] \
                + np.arange(w, dtype=np.int64)[:, None]
            tr_int[:, :, lanes] = self._words[:, cols]
            tl_int[:, lanes] = np.clip(
                self._plen[:, sys_] - base[None, :], 0, w
            )
        return tr_int, tl_int


def _percentile(xs: List[float], q: float) -> float:
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


@dataclasses.dataclass
class ServingStats:
    """End-of-feed serving report: job latency distribution, sustained
    throughput, the wall-clock phase split, and the occupancy counters
    (one schema with the batch scheduler — ``occupancy`` embeds
    :meth:`~hpa2_tpu.ops.schedule.OccupancyStats.as_dict`)."""

    backend: str
    policy: str
    resident: int
    overlap: bool
    jobs_submitted: int = 0
    jobs_completed: int = 0
    instructions: int = 0
    wall_s: float = 0.0
    host_staging_s: float = 0.0
    device_wait_s: float = 0.0
    readback_s: float = 0.0
    latencies_s: List[float] = dataclasses.field(default_factory=list)
    occupancy: Dict = dataclasses.field(default_factory=dict)
    compile_counts: Dict[str, int] = dataclasses.field(
        default_factory=dict)

    @property
    def ops_per_s(self) -> float:
        return self.instructions / self.wall_s if self.wall_s else 0.0

    def as_dict(self) -> dict:
        ls = self.latencies_s
        return {
            "backend": self.backend,
            "policy": self.policy,
            "resident": self.resident,
            "overlap": self.overlap,
            "jobs_submitted": self.jobs_submitted,
            "jobs_completed": self.jobs_completed,
            "instructions": self.instructions,
            "wall_s": round(self.wall_s, 6),
            "sustained_ops_per_s": round(self.ops_per_s, 1),
            "phases": {
                "host_staging_s": round(self.host_staging_s, 6),
                "device_wait_s": round(self.device_wait_s, 6),
                "readback_s": round(self.readback_s, 6),
            },
            "latency_s": {
                "p50": round(_percentile(ls, 50), 6),
                "p99": round(_percentile(ls, 99), 6),
                "mean": round(float(np.mean(ls)) if ls else 0.0, 6),
                "max": round(max(ls, default=0.0), 6),
            },
            "occupancy": self.occupancy,
            "compile_counts": self.compile_counts,
        }


def _guard_compiles(counts: Dict[str, int], enabled: bool) -> None:
    if enabled and any(c > 1 for c in counts.values()):
        raise RuntimeError(
            f"serving session recompiled after warmup: jit cache "
            f"sizes {counts} (every program must stay at <= 1 entry)"
        )


class ServingSession:
    """Always-on serving over a resident-lane Pallas session
    (:class:`~hpa2_tpu.ops.pallas_engine.PallasLaneSession` or the
    data-sharded subclass).  See the module docstring for the pipeline;
    ``run()`` drives the source to exhaustion and returns
    (:class:`JobResult` list, :class:`ServingStats`).  ``emit`` streams
    each result the moment its lane's dumps decode."""

    def __init__(
        self,
        session,
        source: JobSource,
        *,
        policy: str = "fcfs",
        groups: int = 1,
        threshold: float = 0.5,
        overlap: bool = True,
        decode_dumps: bool = True,
        emit: Optional[Callable[[JobResult], None]] = None,
        compile_guard: bool = True,
        backend: str = "pallas",
        tenant_weights: Optional[Dict[str, float]] = None,
        interval_hook: Optional[Callable[[int, "ServingSession"],
                                         None]] = None,
    ):
        self.session = session
        self.source = source
        self.interval_hook = interval_hook
        # tenant names -> dense integer ids, default tenant "" first;
        # the id-keyed weight dict is handed to the scheduler BY
        # REFERENCE so names first seen later still order correctly
        self._tenant_ids: Dict[str, int] = {}
        self._tenant_weights_named = dict(tenant_weights or {})
        self._tenant_weights_by_id: Dict[int, float] = {}
        self._tenant_id("")
        for name in self._tenant_weights_named:
            self._tenant_id(name)
        self.sched = LaneScheduler.serving(
            session.r, block=session.block, groups=groups,
            threshold=threshold, policy=policy,
            tenant_weights=self._tenant_weights_by_id,
        )
        self.pool = TracePool(session.config, session.window)
        self.overlap = overlap
        self.decode_dumps = decode_dumps
        self.emit = emit
        self.compile_guard = compile_guard
        self._jobs: List[Job] = []
        self._submitted: List[float] = []
        self.stats = ServingStats(
            backend=backend, policy=policy, resident=session.r,
            overlap=overlap,
        )

    # -- pipeline pieces ----------------------------------------------

    def _tenant_id(self, name: str) -> int:
        tid = self._tenant_ids.get(name)
        if tid is None:
            tid = len(self._tenant_ids)
            self._tenant_ids[name] = tid
            w = self._tenant_weights_named.get(name)
            if w is not None:
                self._tenant_weights_by_id[tid] = float(w)
        return tid

    def _ingest(self) -> None:
        t0 = time.perf_counter()
        arrived = self.source.poll()
        if arrived:
            now = time.perf_counter()
            nseg, dls, tns = [], [], []
            for job in arrived:
                s = self.pool.add(job)
                assert s == len(self._jobs)
                self._jobs.append(job)
                self._submitted.append(now)
                nseg.append(self.pool.nseg_of(s))
                dls.append(int(job.deadline))
                tns.append(self._tenant_id(job.tenant))
            self.sched.extend(
                np.asarray(nseg, np.int64),
                deadline=np.asarray(dls, np.int64),
                tenant=np.asarray(tns, np.int64),
            )
            self.stats.jobs_submitted += len(arrived)
        self.stats.host_staging_s += time.perf_counter() - t0

    def _apply_barrier(self, plan) -> List[Tuple[int, object]]:
        """Dispatch harvest gathers then the barrier transform for one
        plan; returns the pending (system, device cols) list."""
        sess, st = self.session, self.sched.stats
        pending = []
        for lane, s in plan.finished:
            pending.append((s, sess.harvest(lane)))
        if not plan.trivial:
            perm = (
                plan.perm if plan.perm is not None
                else np.arange(self.sched.r, dtype=np.int64)
            )
            reset = np.zeros(self.sched.r, bool)
            for lane, _ in plan.admitted:
                reset[lane] = True
            sess.barrier(perm, reset)
        for _, s in plan.admitted:
            self._wait_of[s] = (
                st.intervals - self.sched._enq_at[s]
            )
        return pending

    def _drain(self, pending) -> None:
        """Decode harvested lane columns into streamed results."""
        if not pending:
            return
        t0 = time.perf_counter()
        sess = self.session
        for s, cols in pending:
            job = self._jobs[s]
            dumps = sess.dumps_of(cols) if self.decode_dumps else []
            counters = sess.counters_of(cols)
            res = JobResult(
                job_id=job.job_id,
                dumps=dumps,
                counters=counters,
                submitted_s=self._submitted[s],
                retired_s=time.perf_counter(),
                wait_intervals=self._wait_of.get(s, 0),
                tenant=job.tenant,
            )
            self.pool.free(s)
            self.results.append(res)
            self.stats.jobs_completed += 1
            self.stats.instructions += counters.get("instructions", 0)
            self.stats.latencies_s.append(res.latency_s)
            if self.emit:
                self.emit(res)
        self.stats.readback_s += time.perf_counter() - t0

    def _sync(self, status) -> None:
        if status is None:
            return
        t0 = time.perf_counter()
        self.session.check(status)
        self.stats.device_wait_s += time.perf_counter() - t0

    # -- the loop -----------------------------------------------------

    def run(self) -> Tuple[List[JobResult], ServingStats]:
        sess, sched, st = self.session, self.sched, self.stats
        self.results: List[JobResult] = []
        self._wait_of: Dict[int, int] = {}
        prev_status = None          # interval k-1's un-synced status
        prev_pending: list = []     # interval k-1's un-decoded harvests
        wall0 = time.perf_counter()
        while True:
            self._ingest()
            if not sched.live().any():
                # nothing running: admissions can't ride an interval
                # barrier, so flush them between intervals
                plan = sched.flush_admissions()
                if not plan.trivial:
                    t0 = time.perf_counter()
                    self._apply_barrier(plan)
                    st.host_staging_s += time.perf_counter() - t0
                    continue
                self._sync(prev_status)
                prev_status = None
                self._drain(prev_pending)
                prev_pending = []
                if self.source.exhausted and sched.done():
                    break
                self.source.wait(0.002)
                continue
            lanes = np.nonzero(sched.begin_interval())[0]
            t0 = time.perf_counter()
            tr_int, tl_int = self.pool.windows(
                lanes, sched.lane_sys, sched.lane_seg, sched.r
            )
            tr, tl = sess.stage(tr_int, tl_int)
            st.host_staging_s += time.perf_counter() - t0
            status = sess.advance(tr, tl)
            plan = sched.end_interval()
            pending = self._apply_barrier(plan)
            if self.overlap:
                # sync one interval behind: the device is already off
                # running interval k while we block on k-1's status
                # and decode k-1's harvests
                self._sync(prev_status)
                self._drain(prev_pending)
                prev_status, prev_pending = status, pending
            else:
                self._sync(status)
                self._drain(pending)
            if self.interval_hook is not None:
                # the supervisor's tap: interval k's barrier has been
                # applied — checkpoint/failure-injection point
                self.interval_hook(sched.stats.intervals, self)
        self._sync(prev_status)
        self._drain(prev_pending)
        st.wall_s = time.perf_counter() - wall0
        sched.stats.shed_jobs = int(
            getattr(self.source, "shed_jobs", 0) or 0)
        st.occupancy = sched.stats.set_mode(fused=False).as_dict()
        st.compile_counts = sess.compile_counts()
        _guard_compiles(st.compile_counts, self.compile_guard)
        return self.results, st


class BatchServingSession:
    """Always-on serving over :class:`~hpa2_tpu.ops.engine.\
BatchLaneSession` rows.  Row completion is device quiescence, so the
    loop syncs once per chunk; with ``overlap=True`` the host builds
    arriving jobs' initial row states *while* the chunk is in flight
    and scatters them in at the chunk boundary.  This is the serving
    backend with the fault-injection layer."""

    def __init__(
        self,
        session,
        source: JobSource,
        *,
        policy: str = "fcfs",
        overlap: bool = True,
        decode_dumps: bool = True,
        emit: Optional[Callable[[JobResult], None]] = None,
        compile_guard: bool = True,
        backend: str = "jax",
        tenant_weights: Optional[Dict[str, float]] = None,
        interval_hook: Optional[Callable[[int, "BatchServingSession"],
                                         None]] = None,
    ):
        self.session = session
        self.source = source
        self.interval_hook = interval_hook
        self.policy = policy
        self.overlap = overlap
        self.decode_dumps = decode_dumps
        self.emit = emit
        self.compile_guard = compile_guard
        self._jobs: List[Job] = []
        self._submitted: List[float] = []
        self._tenant_ids: Dict[str, int] = {"": 0}
        self._tenant_weights_named = dict(tenant_weights or {})
        self._tenant_weights_by_id: Dict[int, float] = {}
        for name in self._tenant_weights_named:
            self._tid(name)
        self._tenant_of: Dict[int, int] = {}   # system -> tenant id
        self._dl_abs: Dict[int, int] = {}      # system -> abs deadline
        self.stats = ServingStats(
            backend=backend, policy=policy, resident=session.r,
            overlap=overlap,
        )

    def _tid(self, name: str) -> int:
        tid = self._tenant_ids.get(name)
        if tid is None:
            tid = len(self._tenant_ids)
            self._tenant_ids[name] = tid
            w = self._tenant_weights_named.get(name)
            if w is not None:
                self._tenant_weights_by_id[tid] = float(w)
        return tid

    def _poll(self, queue: deque, enq_at: Dict[int, int],
              chunk: int) -> None:
        t0 = time.perf_counter()
        arrived = self.source.poll()
        if arrived:
            now = time.perf_counter()
            for job in arrived:
                s = len(self._jobs)
                self._jobs.append(job)
                self._submitted.append(now)
                queue.append(s)
                enq_at[s] = chunk
                self._tenant_of[s] = self._tid(job.tenant)
                self._dl_abs[s] = (
                    chunk + job.deadline if job.deadline >= 0 else -1
                )
            if self.policy != "fcfs":
                # fair-drr charges one row per job (keys of one) —
                # row-granularity serving has no segment cost
                if self.policy == "fair-drr":
                    keys = np.ones(len(queue), dtype=np.int64)
                else:
                    keys = np.asarray(
                        [self._jobs[s].max_len for s in queue]
                    )
                order = policy_order(
                    keys, self.policy,
                    deadline=np.asarray(
                        [self._dl_abs[s] for s in queue], np.int64
                    ),
                    tenant=np.asarray(
                        [self._tenant_of[s] for s in queue], np.int64
                    ),
                    weights=self._tenant_weights_by_id,
                )
                items = list(queue)
                queue.clear()
                queue.extend(items[int(i)] for i in order)
            self.stats.jobs_submitted += len(arrived)
        self.stats.host_staging_s += time.perf_counter() - t0

    def _stage(self, queue: deque, free: List[int]) -> list:
        """Build fresh row states for as many queued jobs as there are
        free rows (the ingest cost hidden behind the in-flight chunk)."""
        t0 = time.perf_counter()
        staged = []
        for idx in free:
            if not queue:
                break
            s = queue.popleft()
            staged.append(
                (idx, s, self.session.fresh_row(
                    self._jobs[s].batch_traces()))
            )
        self.stats.host_staging_s += time.perf_counter() - t0
        return staged

    def _harvest(self, row_sys: np.ndarray, quiet: np.ndarray,
                 wait_of: Dict[int, int], occ: OccupancyStats,
                 chunk: int) -> None:
        sess = self.session
        done_rows = [
            int(i) for i in np.nonzero((row_sys >= 0) & quiet)[0]
        ]
        if getattr(sess, "window", None) is not None:
            # window-schedule emulation: a quiescent row at a window
            # barrier extends instead of retiring (and made progress,
            # so its stall-watchdog age resets)
            barrier = [i for i in done_rows if not sess.window_done(i)]
            for i in barrier:
                sess.window_extend(i)
                self._row_age[i] = 0
            done_rows = [i for i in done_rows if i not in barrier]
        if not done_rows:
            return
        t0 = time.perf_counter()
        rows = [sess.take_row(i) for i in done_rows]
        for idx, row in zip(done_rows, rows):
            s = int(row_sys[idx])
            job = self._jobs[s]
            dl = self._dl_abs.get(s, -1)
            if dl >= 0:
                if chunk <= dl:
                    occ.deadline_met += 1
                else:
                    occ.deadline_missed += 1
            counters = sess.counters_of(row)
            res = JobResult(
                job_id=job.job_id,
                dumps=sess.dumps_of(row) if self.decode_dumps else [],
                counters=counters,
                submitted_s=self._submitted[s],
                retired_s=time.perf_counter(),
                wait_intervals=wait_of.get(s, 0),
                tenant=job.tenant,
            )
            self.results.append(res)
            self.stats.jobs_completed += 1
            self.stats.instructions += counters.get("instructions", 0)
            self.stats.latencies_s.append(res.latency_s)
            if self.emit:
                self.emit(res)
            sess.retire(idx)
            row_sys[idx] = -1
        self.stats.readback_s += time.perf_counter() - t0

    def _account_chunk(self, occ: OccupancyStats, row_sys: np.ndarray,
                       row_age: np.ndarray, queue: deque) -> None:
        occ.intervals += 1
        live = int((row_sys >= 0).sum())
        occ.live_lane_intervals += live
        occ.lane_intervals += self.session.r
        # row granularity = block 1; serving has no lockstep baseline,
        # so both segment counters accrue the live-row work
        occ.block_segments += live
        occ.lockstep_block_segments += live
        if self._tenant_weights_by_id or len(self._tenant_ids) > 1:
            for s in row_sys[row_sys >= 0]:
                t = self._tenant_of.get(int(s), 0)
                occ.tenant_live[t] = occ.tenant_live.get(t, 0) + 1
        depth = len(queue)
        occ.queue_depth_sum += depth
        occ.queue_depth_peak = max(occ.queue_depth_peak, depth)
        row_age[row_sys >= 0] += 1
        max_chunks = -(-self.session.max_cycles
                       // self.session.interval)
        if (row_age > max_chunks).any():
            bad = int(np.argmax(row_age))
            raise StallError(
                f"job {self._jobs[int(row_sys[bad])].job_id!r} made "
                f"no quiescence within ~{self.session.max_cycles} "
                f"cycles: "
                f"{self.session.stall_of(bad, 'serving chunk limit')}"
            )

    def run(self) -> Tuple[List[JobResult], ServingStats]:
        sess = self.session
        st = self.stats
        self.results: List[JobResult] = []
        occ = OccupancyStats(lockstep_block_segments=0)
        row_sys = np.full(sess.r, -1, np.int64)
        row_age = np.zeros(sess.r, np.int64)  # chunks since admission
        queue: deque = deque()
        enq_at: Dict[int, int] = {}
        wait_of: Dict[int, int] = {}
        # live handles for the recovery supervisor: the interval hook
        # reads these to checkpoint mid-run state at chunk barriers
        self.row_sys = row_sys
        self.wait_of = wait_of
        self.occ = occ
        self._row_age = row_age
        chunk = 0
        wall0 = time.perf_counter()
        while True:
            self._poll(queue, enq_at, chunk)
            free = [int(i) for i in np.nonzero(row_sys < 0)[0]]
            if not (row_sys >= 0).any() and not queue:
                if self.source.exhausted:
                    break
                self.source.wait(0.002)
                continue
            if self.overlap and (row_sys >= 0).any():
                # chunk k in flight while the host inits arrivals
                sess.advance()
                staged = self._stage(queue, free)
                t0 = time.perf_counter()
                quiet = sess.quiescent_rows()
                st.device_wait_s += time.perf_counter() - t0
                chunk += 1
                self._account_chunk(occ, row_sys, row_age, queue)
                self._harvest(row_sys, quiet, wait_of, occ, chunk)
                if self.interval_hook is not None:
                    self.interval_hook(chunk, self)
            else:
                staged = self._stage(queue, free)
            for idx, s, row in staged:
                t0 = time.perf_counter()
                sess.admit(idx, row)
                st.host_staging_s += time.perf_counter() - t0
                row_sys[idx] = s
                row_age[idx] = 0
                occ.admissions += 1
                wait = chunk - enq_at[s]
                wait_of[s] = wait
                occ.wait_intervals_total += wait
                occ.wait_intervals_max = max(
                    occ.wait_intervals_max, wait
                )
            if not self.overlap and (row_sys >= 0).any():
                sess.advance()
                t0 = time.perf_counter()
                quiet = sess.quiescent_rows()
                st.device_wait_s += time.perf_counter() - t0
                chunk += 1
                self._account_chunk(occ, row_sys, row_age, queue)
                self._harvest(row_sys, quiet, wait_of, occ, chunk)
                if self.interval_hook is not None:
                    self.interval_hook(chunk, self)
        st.wall_s = time.perf_counter() - wall0
        occ.shed_jobs = int(getattr(self.source, "shed_jobs", 0) or 0)
        st.occupancy = occ.as_dict()
        st.compile_counts = sess.compile_counts()
        _guard_compiles(st.compile_counts, self.compile_guard)
        return self.results, st


def build_serving(
    config: SystemConfig,
    source: JobSource,
    *,
    backend: str = "pallas",
    resident: int = 8,
    window: int = 16,
    block: Optional[int] = None,
    policy: str = "fcfs",
    data_shards: int = 1,
    node_shards: int = 1,
    overlap: bool = True,
    interval: int = 256,
    max_trace_len: int = 1024,
    threshold: float = 0.5,
    max_cycles: int = 1_000_000,
    decode_dumps: bool = True,
    emit: Optional[Callable[[JobResult], None]] = None,
    compile_guard: bool = True,
    interpret: Optional[bool] = None,
    tenant_weights: Optional[Dict[str, float]] = None,
    interval_hook: Optional[Callable] = None,
    jax_window: Optional[int] = None,
):
    """Build the right resident session + serving driver for
    ``backend`` without running it — the recovery supervisor uses this
    to keep the driver handle (and its interval hook) while a plain
    ``serve()`` is just ``build_serving(...).run()``."""
    if backend == "pallas":
        from hpa2_tpu.ops.pallas_engine import PallasLaneSession

        sess = PallasLaneSession(
            config, resident, window, block=block or 1024,
            interpret=interpret, max_cycles=max_cycles,
        )
        drv = ServingSession(
            sess, source, policy=policy, threshold=threshold,
            overlap=overlap, decode_dumps=decode_dumps, emit=emit,
            compile_guard=compile_guard, backend=backend,
            tenant_weights=tenant_weights, interval_hook=interval_hook,
        )
    elif backend == "pallas-sharded":
        from hpa2_tpu.parallel.sharding import DataShardedLaneSession

        sess = DataShardedLaneSession(
            config, resident, window, data_shards=data_shards,
            block=block or 1024, interpret=interpret,
            max_cycles=max_cycles,
        )
        drv = ServingSession(
            sess, source, policy=policy, groups=sess.data_shards,
            threshold=threshold, overlap=overlap,
            decode_dumps=decode_dumps, emit=emit,
            compile_guard=compile_guard, backend=backend,
            tenant_weights=tenant_weights, interval_hook=interval_hook,
        )
    elif backend == "pallas-node-sharded":
        from hpa2_tpu.parallel.sharding import NodeShardedLaneSession

        sess = NodeShardedLaneSession(
            config, resident, window, node_shards=node_shards,
            data_shards=data_shards, block=block or 1024,
            interpret=interpret, max_cycles=max_cycles,
        )
        drv = ServingSession(
            sess, source, policy=policy, groups=sess.data_shards,
            threshold=threshold, overlap=overlap,
            decode_dumps=decode_dumps, emit=emit,
            compile_guard=compile_guard, backend=backend,
            tenant_weights=tenant_weights, interval_hook=interval_hook,
        )
    elif backend == "jax":
        from hpa2_tpu.ops.engine import BatchLaneSession

        # jax_window opts the batch engine into the Pallas window
        # schedule (quiescence barrier every jax_window trace entries)
        # so a migrated job's dumps stay byte-identical to the pallas
        # run; None (default) keeps the native unwindowed schedule
        sess = BatchLaneSession(
            config, resident, max_trace_len, interval=interval,
            max_cycles=max_cycles, data_shards=data_shards,
            window=jax_window,
        )
        drv = BatchServingSession(
            sess, source, policy=policy, overlap=overlap,
            decode_dumps=decode_dumps, emit=emit,
            compile_guard=compile_guard, backend=backend,
            tenant_weights=tenant_weights, interval_hook=interval_hook,
        )
    else:
        raise ValueError(
            f"unknown serving backend {backend!r}; expected "
            "pallas | pallas-sharded | pallas-node-sharded | jax"
        )
    return drv


def serve(config: SystemConfig, source: JobSource,
          **kwargs) -> Tuple[List[JobResult], ServingStats]:
    """Build the right resident session for ``backend`` and drive the
    source to exhaustion.  Backends: ``pallas`` (the fast path),
    ``pallas-sharded`` (data-parallel lanes over ``data_shards``
    devices), ``pallas-node-sharded`` (each system's node axis split
    over ``node_shards`` devices — jobs bigger than a chip), ``jax``
    (the XLA batch engine).  Accepts every :func:`build_serving`
    keyword."""
    return build_serving(config, source, **kwargs).run()
