"""Serving jobs: the unit of work the always-on loop admits.

A *job* is one simulated DSM system — per-node instruction traces plus
an id and an optional arrival offset.  Jobs travel as JSONL records
(one job per line), either read from a jobs file or streamed over a
socket:

    {"id": "j0", "traces": [[["R", 3], ["W", 5, 7]], [], ...]}
    {"id": "j1", "arrival": 0.25,
     "workload": {"kind": "uniform", "instrs": 32, "seed": 7}}

``traces`` lists one trace per node, each instruction ``["R", addr]``
or ``["W", addr, value]`` (integer ops 0/1 are accepted).
``workload`` generates the traces server-side from the same seeded
generators the benchmarks use — the compact form for load testing.
``arrival`` is the feed-relative release time in seconds (omitted =
release immediately).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.utils.dump import NodeDump


@dataclasses.dataclass
class Job:
    """One simulation job: ``[n, t]`` per-node trace arrays.

    The multi-tenant service fields default to "anonymous, best
    effort": ``tenant`` names the submitting tenant ("" = the default
    tenant), ``priority`` breaks ties in the admission scheduler
    (higher first, reserved), and ``deadline`` is the completion
    deadline in scheduling intervals relative to admission enqueue
    (-1 = none)."""

    job_id: str
    tr_op: np.ndarray    # [n, t] int, 0=RD 1=WR
    tr_addr: np.ndarray  # [n, t] int
    tr_val: np.ndarray   # [n, t] int
    tr_len: np.ndarray   # [n] int
    arrival: float = 0.0
    tenant: str = ""
    priority: int = 0
    deadline: int = -1

    @property
    def max_len(self) -> int:
        return int(self.tr_len.max(initial=0))

    @property
    def instructions(self) -> int:
        return int(self.tr_len.sum())

    def batch_traces(self):
        """The per-node ``Instr`` lists the batch backends consume."""
        from hpa2_tpu.models.protocol import Instr

        return [
            [
                Instr(
                    "RW"[int(self.tr_op[i, j])],
                    int(self.tr_addr[i, j]),
                    int(self.tr_val[i, j]),
                )
                for j in range(int(self.tr_len[i]))
            ]
            for i in range(len(self.tr_len))
        ]


@dataclasses.dataclass
class JobResult:
    """What the serving loop streams back as a job's lanes retire."""

    job_id: str
    dumps: List[NodeDump]
    counters: Dict[str, int]
    submitted_s: float
    retired_s: float
    wait_intervals: int
    tenant: str = ""

    @property
    def latency_s(self) -> float:
        return self.retired_s - self.submitted_s

    def to_record(self) -> dict:
        rec = {
            "id": self.job_id,
            "latency_s": round(self.latency_s, 6),
            "wait_intervals": self.wait_intervals,
            **self.counters,
        }
        if self.tenant:
            rec["tenant"] = self.tenant
        return rec


def _trace_arrays(config: SystemConfig, traces: Sequence[Sequence]):
    n = config.num_procs
    if len(traces) != n:
        raise ValueError(
            f"job needs one trace per node ({n}), got {len(traces)}"
        )
    t = max((len(tr) for tr in traces), default=0)
    t = max(t, 1)
    op = np.zeros((n, t), np.int32)
    addr = np.zeros((n, t), np.int32)
    val = np.zeros((n, t), np.int32)
    ln = np.zeros(n, np.int32)
    ops = {"R": 0, "W": 1, 0: 0, 1: 1}
    for i, tr in enumerate(traces):
        ln[i] = len(tr)
        for j, ins in enumerate(tr):
            if len(ins) not in (2, 3):
                raise ValueError(f"bad instruction {ins!r}")
            o = ops.get(ins[0])
            if o is None:
                raise ValueError(f"bad instruction op {ins[0]!r}")
            op[i, j] = o
            addr[i, j] = int(ins[1])
            val[i, j] = int(ins[2]) if len(ins) == 3 else 0
    return op, addr, val, ln


def _workload_job(
    config: SystemConfig, job_id: str, spec: dict, arrival: float
) -> Job:
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    kind = spec.get("kind", "uniform")
    if kind != "uniform":
        raise ValueError(f"unknown workload kind {kind!r}")
    instrs = int(spec.get("instrs", 32))
    seed = int(spec.get("seed", 0))
    write_frac = float(spec.get("write_frac", 0.33))
    op, addr, val, ln = gen_uniform_random_arrays(
        config, 1, instrs, seed=seed, write_frac=write_frac
    )
    length = spec.get("length")
    if length is not None:
        ln = np.minimum(ln, int(length))
    return Job(job_id, op[0], addr[0], val[0], ln[0].astype(np.int32),
               arrival=arrival)


def job_from_record(config: SystemConfig, record: dict) -> Job:
    """One JSONL record -> :class:`Job` (see the module docstring for
    the format)."""
    if "id" not in record:
        raise ValueError("job record needs an 'id'")
    job_id = str(record["id"])
    arrival = float(record.get("arrival", 0.0))
    if ("traces" in record) == ("workload" in record):
        raise ValueError(
            f"job {job_id!r} needs exactly one of 'traces'/'workload'"
        )
    if "workload" in record:
        job = _workload_job(config, job_id, record["workload"], arrival)
    else:
        op, addr, val, ln = _trace_arrays(config, record["traces"])
        job = Job(job_id, op, addr, val, ln, arrival=arrival)
    job.tenant = str(record.get("tenant", ""))
    job.priority = int(record.get("priority", 0))
    job.deadline = int(record.get("deadline", -1))
    return job


def job_to_record(job: Job) -> dict:
    """Inverse of :func:`job_from_record` (explicit-traces form) — the
    record/replay serializer."""
    traces = []
    for i in range(len(job.tr_len)):
        tr = []
        for j in range(int(job.tr_len[i])):
            if int(job.tr_op[i, j]):
                tr.append(["W", int(job.tr_addr[i, j]),
                           int(job.tr_val[i, j])])
            else:
                tr.append(["R", int(job.tr_addr[i, j])])
        traces.append(tr)
    rec = {"id": job.job_id, "traces": traces}
    if job.arrival:
        rec["arrival"] = job.arrival
    if job.tenant:
        rec["tenant"] = job.tenant
    if job.priority:
        rec["priority"] = job.priority
    if job.deadline >= 0:
        rec["deadline"] = job.deadline
    return rec


def parse_jobs_lines(
    config: SystemConfig, lines: Sequence[str]
) -> List[Job]:
    jobs = []
    for ix, line in enumerate(lines):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"jobs line {ix + 1}: bad JSON: {e}") from e
        jobs.append(job_from_record(config, rec))
    return jobs


def load_jobs_file(config: SystemConfig, path: str) -> List[Job]:
    with open(path) as fh:
        return parse_jobs_lines(config, fh.readlines())


def synthetic_jobs(
    config: SystemConfig,
    count: int,
    max_instrs: int,
    *,
    seed: int = 0,
    write_frac: float = 0.33,
    dist: str = "zipf",
    spread: float = 4.0,
    arrivals: Optional[np.ndarray] = None,
) -> List[Job]:
    """A seeded feed of heterogeneous-length jobs (the benchmark and
    smoke-test workload): uniform random traces, per-job lengths drawn
    from ``dist`` exactly like ``gen_heterogeneous_random_arrays``."""
    from hpa2_tpu.utils.trace import (
        gen_uniform_random_arrays, heterogeneous_lengths)

    op, addr, val, ln = gen_uniform_random_arrays(
        config, count, max_instrs, seed=seed, write_frac=write_frac
    )
    lens = heterogeneous_lengths(count, max_instrs, dist, spread, seed)
    ln = np.minimum(ln, np.asarray(lens)[:, None]).astype(np.int32)
    jobs = []
    for s in range(count):
        t = float(arrivals[s]) if arrivals is not None else 0.0
        jobs.append(
            Job(f"job-{s:05d}", op[s], addr[s], val[s], ln[s], arrival=t)
        )
    return jobs
