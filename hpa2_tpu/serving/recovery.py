"""Checkpointed live migration: the fault-tolerant serving supervisor.

:func:`supervised_serve` wraps the plain serving loops
(:mod:`hpa2_tpu.serving.loop`) in a recovery driver:

- **checkpoint** — at every ``checkpoint_every``-th interval barrier
  the supervisor snapshots the run through the existing checkpoint
  machinery: on the jax backend a schema-v2 ``save_state`` npz of the
  whole resident-row :class:`~hpa2_tpu.ops.state.SimState` (gathered
  to host via :func:`~hpa2_tpu.parallel.sharding.fetch_host_state`,
  so sharded layouts checkpoint identically) plus a row→job manifest;
  on the pallas backends a JSON manifest (lane state lives inside the
  kernel, so pallas recovery is replay-based — see the migration
  matrix in the README);
- **detect** — :class:`~hpa2_tpu.service.failover.FailureInjector`
  raises :class:`InjectedFailure` per the seeded plan, and genuine
  :class:`StallError`\\ s from the watchdog path are caught the same
  way;
- **recover** — in-flight jobs *evacuate* to the next target spec
  (``kill``/``hang`` rotate to a different backend or shard count —
  a *migration*; ``poison`` re-runs on a fresh session of the same
  spec).  When both the checkpoint and the target are the jax batch
  engine, live rows resume **mid-state** from the npz (the
  checkpoint's bit-identical resume contract); otherwise jobs replay
  from their manifests — either way the final dumps are byte-identical
  to an unfailed run, because each job's simulation is deterministic
  and independent of lane placement, admission timing, backend, and
  shard count (pinned across backends by the tier-1 suite, and for
  failover by ``tests/test_failover.py``).

Determinism: the failure plan is config data; the supervisor adds no
RNG and keys every decision off interval barriers and admission
order.  Two runs of the same plan take the same checkpoints, fire the
same failures, and migrate the same jobs.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from hpa2_tpu.config import FailurePlan, SystemConfig
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.serving.ingest import JobSource
from hpa2_tpu.serving.jobs import Job, JobResult
from hpa2_tpu.serving.loop import ServingStats, build_serving
from hpa2_tpu.service.failover import (
    FailureInjector, InjectedFailure, RecoveryLog)
from hpa2_tpu.utils.checkpoint import load_state, save_state

#: serve()/build_serving() keywords that define a migration target —
#: everything else (resident, window, policy, ...) is shared geometry.
SPEC_KEYS = ("backend", "data_shards", "node_shards")


def default_targets(backend: str) -> List[Dict]:
    """Where to migrate when the caller names no targets: cross the
    pallas ↔ jax divide (kills must land on a *different* backend),
    and fold sharded sessions back to single-chip lanes — a shard
    failure shouldn't require the same mesh to still exist."""
    if backend == "jax":
        return [{"backend": "pallas", "data_shards": 1}]
    if backend == "pallas-node-sharded":
        return [{"backend": "pallas", "node_shards": 1}]
    return [{"backend": "jax", "data_shards": 1}]


class _RecordingSource(JobSource):
    """Wraps the real feed; remembers every job it ever handed out (in
    admission order, with its poll timestamp) so the supervisor can
    rebuild the outstanding work-list after a failure."""

    def __init__(self, inner: JobSource):
        self.inner = inner
        self.seen: List[Job] = []
        self.seen_at: Dict[str, float] = {}

    def poll(self) -> List[Job]:
        jobs = self.inner.poll()
        if jobs:
            now = time.perf_counter()
            for j in jobs:
                self.seen.append(j)
                self.seen_at.setdefault(j.job_id, now)
        return jobs

    @property
    def exhausted(self) -> bool:
        return self.inner.exhausted

    def wait(self, timeout_s: float) -> None:
        self.inner.wait(timeout_s)

    def close(self) -> None:
        self.inner.close()

    @property
    def shed_jobs(self) -> int:
        return int(getattr(self.inner, "shed_jobs", 0) or 0)


class _ReplaySource(JobSource):
    """Evacuated jobs first (one wave, original admission order), then
    whatever the live feed still delivers."""

    def __init__(self, replay: List[Job], inner: JobSource):
        self._replay = list(replay)
        self.inner = inner

    def poll(self) -> List[Job]:
        wave, self._replay = self._replay, []
        return wave + self.inner.poll()

    @property
    def exhausted(self) -> bool:
        return not self._replay and self.inner.exhausted

    def wait(self, timeout_s: float) -> None:
        self.inner.wait(timeout_s)

    def close(self) -> None:
        self.inner.close()

    @property
    def shed_jobs(self) -> int:
        return int(getattr(self.inner, "shed_jobs", 0) or 0)


def _write_json(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


class ServeSupervisor:
    """One fault-tolerant serving run (see the module docstring).

    ``targets`` is the migration rotation — a list of dicts over
    :data:`SPEC_KEYS` tried in order (cycling) on each ``kill``/
    ``hang``; ``poison`` always re-runs on the failed spec.  Every
    serve keyword not in SPEC_KEYS is shared across attempts.
    """

    def __init__(
        self,
        config: SystemConfig,
        source: JobSource,
        *,
        plan: Optional[FailurePlan] = None,
        targets: Optional[List[Dict]] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        detect_after: int = 2,
        max_recoveries: int = 8,
        emit: Optional[Callable[[JobResult], None]] = None,
        **serve_kwargs,
    ):
        if plan is None:
            plan = config.failures
        self.config = config
        self.plan = plan
        self.recorder = _RecordingSource(source)
        self.primary = {
            "backend": serve_kwargs.pop("backend", "pallas"),
            "data_shards": serve_kwargs.pop("data_shards", 1),
            "node_shards": serve_kwargs.pop("node_shards", 1),
        }
        self.targets = list(
            targets if targets is not None
            else default_targets(self.primary["backend"]))
        self.ck_dir = checkpoint_dir
        self.ck_every = max(1, int(checkpoint_every))
        self.max_recoveries = int(max_recoveries)
        self.user_emit = emit
        self.kwargs = serve_kwargs
        # the primary's segment schedule, preserved across backends:
        # a pallas primary windows its traces (quiescence barrier
        # every `window` entries), so a jax migration target replays
        # the same schedule via jax_window; a jax primary is
        # unwindowed, so a pallas target gets one whole-trace window.
        # Either way the migrated jobs' dumps stay byte-identical.
        self.sched_window = (
            None if self.primary["backend"] == "jax"
            else int(serve_kwargs.get("window", 16)))
        self.log = RecoveryLog()
        self.injector = (
            FailureInjector(plan, detect_after=detect_after)
            if plan is not None and plan.enabled else None
        )
        self._results: Dict[str, JobResult] = {}
        self._last_ck: Optional[Tuple[int, str, Dict, Dict]] = None
        self._tix = 0

    # -- plumbing ------------------------------------------------------

    def _emit(self, res: JobResult) -> None:
        """Exactly-once result fanout: a job that completed both before
        a checkpoint-window failure and again after replay (the window
        between snapshot and detection) publishes only its first copy."""
        if res.job_id in self._results:
            return
        self._results[res.job_id] = res
        if self.user_emit is not None:
            self.user_emit(res)

    def _spec_kwargs(self, spec: Dict) -> Dict:
        kw = dict(self.kwargs)
        kw.update({k: spec[k] for k in SPEC_KEYS})
        if spec["backend"] == "jax":
            kw.pop("window", None)
            kw["jax_window"] = self.sched_window
        elif self.sched_window is None:
            # jax primary migrating onto a pallas target: a single
            # whole-trace window reproduces the unwindowed schedule
            kw["window"] = int(kw.get("max_trace_len", 1024))
        return kw

    # -- checkpointing -------------------------------------------------

    def _checkpoint(self, k: int, driver, spec: Dict) -> None:
        if self.ck_dir is None:
            return
        completed = sorted(self._results)
        manifest = {
            "interval": k,
            "spec": dict(spec),
            "completed": completed,
            "in_flight": [j.job_id for j in self.recorder.seen
                          if j.job_id not in self._results],
            "recovery": self.log.counters(),
        }
        state = getattr(getattr(driver, "session", None), "state", None)
        row_sys = getattr(driver, "row_sys", None)
        if state is not None and row_sys is not None:
            # jax batch backend: full mid-state snapshot (schema v2)
            from hpa2_tpu.parallel.sharding import fetch_host_state

            jobs = driver._jobs
            manifest["rows"] = [
                jobs[int(s)].job_id if int(s) >= 0 else None
                for s in row_sys
            ]
            manifest["wait_of"] = {
                jobs[int(s)].job_id: int(w)
                for s, w in driver.wait_of.items()
                if int(s) < len(jobs)
            }
            path = os.path.join(self.ck_dir, f"recovery_{k}.npz")
            save_state(path, fetch_host_state(state), self.config,
                       extra_meta={"recovery": self.log.counters(),
                                   "serving": manifest})
        else:
            path = os.path.join(self.ck_dir, f"recovery_{k}.json")
            _write_json(path, manifest)
        self.log.checkpoints += 1
        self._last_ck = (k, path, dict(spec), manifest)

    def _hook(self, k: int, driver, spec: Dict) -> None:
        if k % self.ck_every == 0:
            self._checkpoint(k, driver, spec)
        if self.injector is not None:
            self.injector.hook(k, driver)

    # -- mid-state resume ----------------------------------------------

    def _resume_rows(self, next_spec: Dict) -> set:
        """jax → jax live migration: re-arm the last npz checkpoint's
        live rows on a fresh :class:`BatchLaneSession` (possibly a
        different ``data_shards``) and drive them to quiescence.
        Returns the resumed job ids; empty when the checkpoint or the
        target can't exchange mid-state (→ replay evacuation)."""
        if self._last_ck is None or next_spec["backend"] != "jax":
            return set()
        k, path, ck_spec, manifest = self._last_ck
        if ck_spec.get("backend") != "jax" or not path.endswith(".npz"):
            return set()
        from hpa2_tpu.ops.engine import BatchLaneSession

        state, _, meta = load_state(path, with_meta=True)
        serving = meta.get("serving", manifest)
        rows = serving.get("rows") or []
        wait_of = serving.get("wait_of") or {}
        by_id = {j.job_id: j for j in self.recorder.seen}
        live = [(i, jid) for i, jid in enumerate(rows)
                if jid is not None and jid not in self._results
                and jid in by_id]
        if not live:
            return set()
        sess = BatchLaneSession(
            self.config, len(rows),
            self.kwargs.get("max_trace_len", 1024),
            interval=self.kwargs.get("interval", 256),
            max_cycles=self.kwargs.get("max_cycles", 1_000_000),
            data_shards=next_spec.get("data_shards", 1),
        )
        import jax

        host = jax.tree_util.tree_map(np.asarray, state)
        for i, _ in live:
            sess.admit(i, jax.tree_util.tree_map(
                lambda x: x[i], host))
        resumed: set = set()
        pending = dict(live)
        max_chunks = 2 + (-(-sess.max_cycles // sess.interval))
        chunks = 0
        while pending:
            sess.advance()
            quiet = sess.quiescent_rows()
            for i in [i for i in pending if quiet[i]]:
                jid = pending.pop(i)
                row = sess.take_row(i)
                job = by_id[jid]
                counters = sess.counters_of(row)
                res = JobResult(
                    job_id=jid,
                    dumps=sess.dumps_of(row),
                    counters=counters,
                    submitted_s=self.recorder.seen_at.get(
                        jid, time.perf_counter()),
                    retired_s=time.perf_counter(),
                    wait_intervals=int(wait_of.get(jid, 0)),
                    tenant=job.tenant,
                )
                sess.retire(i)
                self._emit(res)
                resumed.add(jid)
            chunks += 1
            if chunks > max_chunks:
                raise StallError(
                    f"resumed rows made no quiescence within "
                    f"~{sess.max_cycles} cycles after migration")
        self.log.lanes_resumed += len(resumed)
        self.log.record(
            "lanes_resumed", interval=k, count=len(resumed),
            jobs=sorted(resumed), target=dict(next_spec))
        return resumed

    # -- recovery ------------------------------------------------------

    def _next_spec(self, failed: Dict, kind: str) -> Dict:
        if kind == "poison" or not self.targets:
            # corruption: same spec, fresh session (an evacuation,
            # not a migration)
            return dict(failed)
        spec = dict(failed)
        spec.update(self.targets[self._tix % len(self.targets)])
        self._tix += 1
        for key in SPEC_KEYS:
            spec.setdefault(key, 1 if key != "backend" else "pallas")
        return spec

    def _recover(self, exc: Exception, spec: Dict
                 ) -> Tuple[Dict, List[Job]]:
        self.log.failures_detected += 1
        self.log.retries += 1
        if isinstance(exc, InjectedFailure):
            kind, at = exc.event.kind, exc.interval
            via = ("watchdog" if exc.event.kind == "hang"
                   else "interval_hook")
            diag = exc.diagnostic
        else:  # a genuine stall caught by the watchdog path
            kind, at, via, diag = "hang", -1, "watchdog", exc
        self.log.record(
            "failure_detected", kind=kind, interval=at, via=via,
            spec=dict(spec),
            diagnostic=(str(diag).splitlines()[0] if diag else None))
        nxt = self._next_spec(spec, kind)
        if nxt != spec:
            self.log.migrations += 1
            self.log.record("migration", interval=at,
                            source=dict(spec), target=dict(nxt))
        resumed = self._resume_rows(nxt)
        replay = [j for j in self.recorder.seen
                  if j.job_id not in self._results
                  and j.job_id not in resumed]
        self.log.evacuations += len(replay) + len(resumed)
        self.log.jobs_replayed += len(replay)
        self.log.record(
            "evacuation", interval=at, replayed=len(replay),
            resumed=len(resumed), target=dict(nxt))
        return nxt, replay

    # -- the run -------------------------------------------------------

    def run(self) -> Tuple[List[JobResult], ServingStats]:
        spec = dict(self.primary)
        replay: List[Job] = []
        attempt = 0
        while True:
            source: JobSource = (
                _ReplaySource(replay, self.recorder) if replay
                else self.recorder)
            cur = dict(spec)
            drv = build_serving(
                self.config, source, emit=self._emit,
                interval_hook=lambda k, d, _s=cur: self._hook(k, d, _s),
                **self._spec_kwargs(cur),
            )
            try:
                _, stats = drv.run()
                break
            except (InjectedFailure, StallError) as exc:
                attempt += 1
                if attempt > self.max_recoveries:
                    raise
                spec, replay = self._recover(exc, cur)
        # supervisor-wide totals over the last attempt's stats shell
        results = list(self._results.values())
        stats.jobs_submitted = len(self.recorder.seen)
        stats.jobs_completed = len(results)
        stats.instructions = sum(
            r.counters.get("instructions", 0) for r in results)
        stats.latencies_s = [r.latency_s for r in results]
        self.log.shed_jobs = int(
            getattr(self.recorder.inner, "shed_jobs", 0) or 0)
        rec = self.log.as_dict()
        if any(v for v in rec.values()):
            stats.occupancy = dict(stats.occupancy)
            stats.occupancy["recovery"] = rec
        return results, stats


def supervised_serve(config: SystemConfig, source: JobSource,
                     **kwargs) -> Tuple[List[JobResult], ServingStats]:
    """:func:`~hpa2_tpu.serving.loop.serve` with the fault-tolerance
    supervisor around it — accepts every serve keyword plus ``plan``,
    ``targets``, ``checkpoint_dir``, ``checkpoint_every``,
    ``detect_after`` and ``max_recoveries``."""
    return ServeSupervisor(config, source, **kwargs).run()
