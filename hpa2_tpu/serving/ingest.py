"""Job sources for the always-on serving loop.

A :class:`JobSource` hands the loop whatever jobs have *arrived* by
now; the loop polls it once per admission opportunity (segment
barrier) so ingest never blocks the device.  Three sources:

- :class:`ListJobSource` — an in-memory feed, optionally released on
  each job's ``arrival`` offset (``timed=True``) or all at once.
  Deterministic replay uses ``timed=False``: arrival *order* is
  whatever order the list is in, independent of wall clock.
- :class:`FileJobSource` — a JSONL jobs file, released on arrival
  offsets (or immediately with ``timed=False``).
- :class:`SocketJobSource` — a TCP listener; each client connection
  streams JSONL job records.  A reader thread parses into a queue so
  the serving loop's poll stays non-blocking.

Arrival processes for benchmarks live here too: Poisson
(:func:`poisson_arrivals`) and heavy-tail burst
(:func:`zipf_burst_arrivals`) offsets, both seeded.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from typing import Iterable, List, Optional, Sequence

import numpy as np

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.serving.jobs import Job, job_from_record


class JobSource:
    """Poll-based job feed: ``poll()`` returns the jobs that arrived
    since the last call; ``exhausted`` turns true once the feed is
    done AND everything has been handed out."""

    def poll(self) -> List[Job]:
        raise NotImplementedError

    @property
    def exhausted(self) -> bool:
        raise NotImplementedError

    def wait(self, timeout_s: float) -> None:
        """Idle until the next job might arrive (the loop calls this
        when all lanes are free and poll() came back empty)."""
        time.sleep(min(timeout_s, 0.005))

    def close(self) -> None:
        pass


class ListJobSource(JobSource):
    def __init__(self, jobs: Sequence[Job], *, timed: bool = False):
        self._jobs = sorted(jobs, key=lambda j: j.arrival) if timed \
            else list(jobs)
        self._timed = timed
        self._next = 0
        self._t0 = time.perf_counter()

    def poll(self) -> List[Job]:
        if not self._timed:
            out, self._next = self._jobs[self._next:], len(self._jobs)
            return out
        now = time.perf_counter() - self._t0
        out = []
        while (self._next < len(self._jobs)
               and self._jobs[self._next].arrival <= now):
            out.append(self._jobs[self._next])
            self._next += 1
        return out

    @property
    def exhausted(self) -> bool:
        return self._next >= len(self._jobs)

    def wait(self, timeout_s: float) -> None:
        if not self._timed or self.exhausted:
            return
        now = time.perf_counter() - self._t0
        dt = self._jobs[self._next].arrival - now
        if dt > 0:
            time.sleep(min(dt, timeout_s))


class FileJobSource(ListJobSource):
    def __init__(self, config: SystemConfig, path: str, *,
                 timed: bool = True):
        from hpa2_tpu.serving.jobs import load_jobs_file

        super().__init__(load_jobs_file(config, path), timed=timed)


class SocketJobSource(JobSource):
    """TCP JSONL feed: one job record per line, any number of client
    connections.  ``poll()`` drains the parse queue; the feed is done
    when a client sends ``{"eof": true}`` (or after ``close()``)."""

    def __init__(self, config: SystemConfig, host: str = "127.0.0.1",
                 port: int = 0, *, backlog: int = 4):
        self._config = config
        self._queue: "queue.Queue[Job]" = queue.Queue()
        self._eof = threading.Event()
        self._closed = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(backlog)
        self._srv.settimeout(0.1)
        self.address = self._srv.getsockname()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(
                target=self._read_conn, args=(conn,), daemon=True)
            t.start()

    def _read_conn(self, conn: socket.socket) -> None:
        # An abrupt client disconnect (RST mid-line, half-open reset)
        # surfaces as ConnectionResetError / OSError from the iterator
        # or the close; swallow it so the reader thread dies quietly —
        # every complete record already parsed stays in the queue, and
        # a partial final line simply fails json.loads and is dropped.
        try:
            with conn, conn.makefile("r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("eof"):
                        self._eof.set()
                        break
                    try:
                        self._queue.put(
                            job_from_record(self._config, rec)
                        )
                    except ValueError:
                        continue
        except (OSError, ValueError):
            pass

    def poll(self) -> List[Job]:
        out = []
        while True:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                return out

    @property
    def exhausted(self) -> bool:
        return ((self._eof.is_set() or self._closed.is_set())
                and self._queue.empty())

    def wait(self, timeout_s: float) -> None:
        try:
            job = self._queue.get(timeout=min(timeout_s, 0.05))
            self._queue.put(job)
        except queue.Empty:
            pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._srv.close()
        except OSError:
            pass


def poisson_arrivals(
    count: int, rate: float, seed: int = 0
) -> np.ndarray:
    """Cumulative arrival offsets (seconds) of a Poisson process with
    ``rate`` jobs/sec — exponential inter-arrival gaps."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / rate, size=count))


def zipf_burst_arrivals(
    count: int, rate: float, seed: int = 0, *, alpha: float = 2.0
) -> np.ndarray:
    """Heavy-tail bursty arrivals at the same mean ``rate``: jobs come
    in Zipf(alpha)-sized bursts (whole burst arrives at one instant),
    with exponential gaps between bursts scaled so the long-run mean
    rate matches the Poisson feed.  The serving tail (p99) under this
    feed is the overload robustness number."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    rng = np.random.default_rng(seed)
    sizes: List[int] = []
    total = 0
    while total < count:
        k = int(np.clip(rng.zipf(alpha), 1, max(1, count - total)))
        sizes.append(k)
        total += k
    # mean burst size compensates the gap so jobs/sec stays = rate
    gaps = rng.exponential(1.0 / rate, size=len(sizes))
    out = np.empty(count, np.float64)
    t, ix = 0.0, 0
    for k, g in zip(sizes, gaps):
        t += g * k
        out[ix:ix + k] = t
        ix += k
    return out
