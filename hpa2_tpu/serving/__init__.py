"""Always-on serving: continuous-batching ingest over resident lanes.

- :mod:`hpa2_tpu.serving.jobs` — the JSONL job format and generators.
- :mod:`hpa2_tpu.serving.ingest` — file / socket / in-memory job
  sources and seeded arrival processes.
- :mod:`hpa2_tpu.serving.loop` — the serving loop itself: trace pool,
  overlapped admission pipeline, phase timers, zero-recompile guard.
- :mod:`hpa2_tpu.serving.recovery` — the fault-tolerance supervisor:
  checkpointed live migration / evacuation between backends and shard
  counts under a seeded :class:`~hpa2_tpu.config.FailurePlan`.

Quick start::

    from hpa2_tpu.serving import serve, ListJobSource, synthetic_jobs
    jobs = synthetic_jobs(config, 64, 96, seed=7)
    results, stats = serve(config, ListJobSource(jobs),
                           backend="pallas", resident=16, window=16)
"""

from hpa2_tpu.serving.ingest import (
    FileJobSource, JobSource, ListJobSource, SocketJobSource,
    poisson_arrivals, zipf_burst_arrivals)
from hpa2_tpu.serving.jobs import (
    Job, JobResult, job_from_record, job_to_record, load_jobs_file,
    parse_jobs_lines, synthetic_jobs)
from hpa2_tpu.serving.loop import (
    BatchServingSession, ServingSession, ServingStats, TracePool,
    build_serving, serve)
from hpa2_tpu.serving.recovery import (
    ServeSupervisor, default_targets, supervised_serve)

__all__ = [
    "BatchServingSession", "FileJobSource", "Job", "JobResult",
    "JobSource", "ListJobSource", "ServeSupervisor", "ServingSession",
    "ServingStats", "SocketJobSource", "TracePool", "build_serving",
    "default_targets", "job_from_record", "job_to_record",
    "load_jobs_file", "parse_jobs_lines", "poisson_arrivals", "serve",
    "supervised_serve", "synthetic_jobs", "zipf_burst_arrivals",
]
