"""Multi-tenant service plane over the always-on serving loop.

- :mod:`hpa2_tpu.service.wire` — the length-prefixed framed protocol:
  versioned frames for SUBMIT / ACK / RESULT streaming / NACK, with
  credit-based backpressure (overflow is a loud NACK, never a silent
  drop).
- :mod:`hpa2_tpu.service.admission` — tenant weights, deadline
  classes, and the thread-safe admission ledger that fixes job order
  by ack sequence.
- :mod:`hpa2_tpu.service.frontend` — :class:`WireJobSource`, the
  framed TCP listener the serving loop polls; results stream back to
  the owning *session* (HELLO-negotiated ids that survive reconnects).
- :mod:`hpa2_tpu.service.failover` — deterministic failure injection
  above the link layer (:class:`FailureInjector` driving the seeded
  :class:`~hpa2_tpu.config.FailurePlan`) and the structured recovery
  log the supervisor publishes.

Quick start (server side)::

    from hpa2_tpu.service import TenantTable, WireJobSource
    from hpa2_tpu.serving import serve

    source = WireJobSource(config, tenants=TenantTable.parse("a:2,b:1"))
    print("listening on", source.address)
    results, stats = serve(config, source, policy="fair-drr",
                           emit=source.deliver,
                           tenant_weights=source.tenant_weights)

and the client::

    from hpa2_tpu.service import WireClient
    with WireClient(host, port) as cli:
        ack = cli.submit({"id": "j0", "tenant": "a", "traces": ...})
        results = cli.finish()
"""

from hpa2_tpu.service.admission import (
    DEADLINE_CLASSES, AdmissionLedger, AdmissionReject, AdmissionShed,
    TenantTable, resolve_deadline)
from hpa2_tpu.service.failover import (
    FailureInjector, InjectedFailure, RecoveryLog, recovery_record)
from hpa2_tpu.service.frontend import WireJobSource
from hpa2_tpu.service.wire import (
    ACK, BYE, CREDIT, EOF, HEARTBEAT, HELLO, NACK, RESULT, SUBMIT,
    ConnectionLost, Frame, FrameReader, WireClient, WireError,
    WireNack, backoff_delay, encode_frame)

__all__ = [
    "ACK", "BYE", "CREDIT", "DEADLINE_CLASSES", "EOF", "Frame",
    "FrameReader", "HEARTBEAT", "HELLO", "NACK", "RESULT", "SUBMIT",
    "AdmissionLedger", "AdmissionReject", "AdmissionShed",
    "ConnectionLost", "FailureInjector", "InjectedFailure",
    "RecoveryLog", "TenantTable", "WireClient", "WireError",
    "WireJobSource", "WireNack", "backoff_delay", "encode_frame",
    "recovery_record", "resolve_deadline",
]
