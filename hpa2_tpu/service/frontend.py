"""The wire frontend: a framed TCP listener that feeds the serving
loop through the admission ledger.

:class:`WireJobSource` is a drop-in
:class:`~hpa2_tpu.serving.ingest.JobSource`: the serving loop polls it
once per admission opportunity exactly like the JSONL socket feed, but
every submission is acknowledged (ACK with the global admission seq)
or rejected loudly (NACK with a reason), and each connection is
credit-clocked so overload pushes back instead of silently dropping.

``poll()`` drains **one admission wave** from the ledger in seq order
— many small jobs arriving between two scheduler intervals enter the
scheduler as one batch, ordered by their ack sequence numbers, not by
reader-thread timing.  Results stream back to the *owning* session
as RESULT frames via :meth:`WireJobSource.deliver` (pass it as the
serving loop's ``emit`` callback); once a session has sent EOF and
its last result is delivered the server answers BYE and closes.

Sessions and resilience (ISSUE-16).  Ownership lives on a *session*,
not a TCP connection: the accept-time HELLO names a deterministic
session id (``s0, s1, ...`` in accept order) and a reconnecting
client re-attaches with HELLO ``{"resume": sid}``.  Admission credits
(the ledger is keyed by session id), the ack-replay cache that makes
SUBMIT idempotent, and results the server could not deliver all
survive the dead socket and flush on resume.  A session that dies
mid-conversation with work in flight keeps the source non-exhausted
until it resumes and finishes — a severed client can always come
back for its results.  Optional extras: ``heartbeat_s`` starts a
beacon thread (HEARTBEAT frames on every live connection) so clients
can tell a stalled server from a slow one; ``shed_threshold`` arms
the ledger's graceful degradation (batch-class jobs shed first with a
structured ``"shed": true`` NACK); ``failures`` injects the *sever*
events of a :class:`~hpa2_tpu.config.FailurePlan` — when a SUBMIT's
ack seq matches a planned ``sever@seq``, the server writes a torn
partial ACK header and hard-closes the socket, exactly the mid-frame
cut the resume path must survive.
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Dict, List, Optional

from hpa2_tpu.config import FailurePlan, SystemConfig
from hpa2_tpu.serving.ingest import JobSource
from hpa2_tpu.serving.jobs import Job, JobResult, job_from_record
from hpa2_tpu.service.admission import (
    AdmissionLedger, AdmissionReject, AdmissionShed, TenantTable,
    resolve_deadline)
from hpa2_tpu.service.wire import (
    ACK, BYE, CREDIT, EOF, HEARTBEAT, HELLO, NACK, RESULT, SUBMIT,
    VERSION, FrameReader, WireError, encode_frame)


class _Conn:
    """One client connection: socket + send lock (the reader thread
    answers ACK/NACK while the serving thread streams RESULT/CREDIT)."""

    def __init__(self, conn_id: int, sock: socket.socket):
        self.id = conn_id
        self.sock = sock
        self.lock = threading.Lock()
        self.dead = False

    def send(self, ftype: int, payload: Optional[dict] = None) -> bool:
        data = encode_frame(ftype, payload)
        with self.lock:
            if self.dead:
                return False
            try:
                self.sock.sendall(data)
                return True
            except OSError:
                self.dead = True
                return False

    def sever(self, data: bytes) -> None:
        """Injected fault: write a torn prefix, then hard-close — the
        peer sees a partial frame followed by EOF mid-stream."""
        with self.lock:
            if not self.dead:
                try:
                    self.sock.sendall(data)
                except OSError:
                    pass
            self.dead = True
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self.lock:
            self.dead = True
            try:
                self.sock.close()
            except OSError:
                pass


class _Session:
    """One client *conversation*, surviving reconnects: admissions,
    result ownership, the ack-replay cache and any undelivered results
    live here, keyed by the HELLO-negotiated session id."""

    def __init__(self, sid: str, conn: _Conn):
        self.id = sid
        self.conn: Optional[_Conn] = conn
        self.acks: Dict[str, dict] = {}   # job id -> original ACK
        self.undelivered: List[dict] = [] # results awaiting resume
        self.outstanding = 0              # admitted, result not sent
        self.eof = False

    def send(self, ftype: int, payload: Optional[dict] = None) -> bool:
        c = self.conn
        return c is not None and c.send(ftype, payload)


class WireJobSource(JobSource):
    """Framed multi-tenant TCP feed (see the module docstring)."""

    def __init__(self, config: SystemConfig, host: str = "127.0.0.1",
                 port: int = 0, *, credits: int = 64, backlog: int = 8,
                 tenants: Optional[TenantTable] = None,
                 shed_threshold: int = 0, heartbeat_s: float = 0.0,
                 failures: Optional[FailurePlan] = None):
        self._config = config
        self.tenants = tenants or TenantTable()
        self.ledger = AdmissionLedger(credits,
                                      shed_threshold=shed_threshold)
        if failures is None:
            failures = config.failures
        self._severs = sorted(
            failures.of_kind("sever"), key=lambda ev: ev.at
        ) if failures is not None else []
        self._severed: set = set()   # seqs already fired
        self._lock = threading.Lock()
        self._conns: Dict[int, _Conn] = {}
        self._sessions: Dict[str, _Session] = {}
        self._owner: Dict[str, _Session] = {}
        self._open: set = set()    # session ids with a live, pre-EOF conn
        self._saw_conn = False
        self._ids = itertools.count()
        self._sids = itertools.count()
        self._closed = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(backlog)
        self._srv.settimeout(0.1)
        self.address = self._srv.getsockname()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._beacon: Optional[threading.Thread] = None
        if heartbeat_s > 0:
            self._beacon = threading.Thread(
                target=self._heartbeat, args=(heartbeat_s,), daemon=True)
            self._beacon.start()

    @property
    def tenant_weights(self) -> Optional[Dict[str, float]]:
        """The weight dict ``serve(tenant_weights=...)`` wants."""
        return dict(self.tenants.weights) or None

    @property
    def shed_jobs(self) -> int:
        """Batch-class jobs shed under overload (ledger counter)."""
        return self.ledger.shed_jobs

    # -- listener ------------------------------------------------------

    def _serve(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            c = _Conn(next(self._ids), sock)
            sess = _Session(f"s{next(self._sids)}", c)
            with self._lock:
                self._conns[c.id] = c
                self._sessions[sess.id] = sess
                self._open.add(sess.id)
                self._saw_conn = True
            budget = self.ledger.register(sess.id)
            c.send(HELLO, {"version": VERSION, "credits": budget,
                           "session": sess.id})
            threading.Thread(
                target=self._read_conn, args=(c, sess), daemon=True
            ).start()

    def _heartbeat(self, period_s: float) -> None:
        while not self._closed.wait(period_s):
            with self._lock:
                conns = [s.conn for s in self._sessions.values()
                         if s.conn is not None and not s.conn.dead]
            for c in conns:
                c.send(HEARTBEAT)

    def _resume(self, c: _Conn, fresh: _Session,
                payload: dict) -> _Session:
        """Re-attach ``c`` to the session the client asks to resume;
        falls back to the fresh accept-time session if it's unknown."""
        sid = str(payload.get("resume"))
        with self._lock:
            old = self._sessions.get(sid)
            resumable = (old is not None and old is not fresh
                         and not old.eof)
            if resumable:
                old.conn = c
                self._open.add(old.id)
                # the provisional session never admitted anything
                self._sessions.pop(fresh.id, None)
                self._open.discard(fresh.id)
        if not resumable:
            c.send(HELLO, {"version": VERSION, "resumed": False,
                           "session": fresh.id,
                           "credits": self.ledger.balance(fresh.id)})
            return fresh
        self.ledger.forget(fresh.id)
        c.send(HELLO, {"version": VERSION, "resumed": True,
                       "session": old.id,
                       "credits": self.ledger.balance(old.id)})
        # flush results that died with the previous socket
        with self._lock:
            stale, old.undelivered = old.undelivered, []
        for rec in stale:
            if not old.send(RESULT, rec):
                with self._lock:
                    old.undelivered.append(rec)
        self._maybe_bye(old)
        return old

    def _read_conn(self, c: _Conn, sess: _Session) -> None:
        reader = FrameReader()
        try:
            while not sess.eof:
                data = c.sock.recv(65536)
                if not data:
                    break
                for fr in reader.feed(data):
                    if fr.ftype == HELLO:
                        sess = self._resume(c, sess, fr.payload)
                    elif fr.ftype == SUBMIT:
                        self._on_submit(c, sess, fr.payload)
                    elif fr.ftype == EOF:
                        with self._lock:
                            sess.eof = True
                            self._open.discard(sess.id)
                        self._maybe_bye(sess)
                        break
                    else:
                        raise WireError(
                            f"unexpected client frame {fr.ftype}")
                if c.dead:
                    break   # severed under this reader's feet
        except (OSError, WireError, ValueError):
            # abrupt disconnect or framing violation: drop the
            # connection; everything already ACK'd stays admitted and
            # the session stays resumable while work is in flight
            c.close()
        finally:
            with self._lock:
                if sess.conn is c and c.dead:
                    self._open.discard(sess.id)
        # reader exits after EOF with the socket open — the serving
        # thread still streams RESULT frames and the closing BYE

    def _on_submit(self, c: _Conn, sess: _Session,
                   record: dict) -> None:
        job_id = str(record.get("id"))
        replay = sess.acks.get(job_id)
        if replay is not None:
            # idempotent SUBMIT: the client resent after losing our
            # ack — replay the original seq instead of NACKing
            c.send(ACK, {**replay, "dup": True})
            return
        try:
            seq, pos = self.ledger.try_submit(sess.id, record)
        except AdmissionShed as e:
            c.send(NACK, {"id": record.get("id"), "reason": str(e),
                          "shed": True})
            return
        except AdmissionReject as e:
            c.send(NACK, {"id": record.get("id"), "reason": str(e)})
            return
        ack = {"id": record.get("id"), "seq": seq, "queue_pos": pos}
        with self._lock:
            self._owner[job_id] = sess
            sess.outstanding += 1
            sess.acks[job_id] = ack
        if self._sever_at(seq):
            # planned mid-frame cut: the job IS admitted and the ack
            # cached — the client must recover it via resume + resubmit
            c.sever(encode_frame(ACK, ack)[:5])
            return
        c.send(ACK, ack)

    def _sever_at(self, seq: int) -> bool:
        for ev in self._severs:
            if ev.at == seq and seq not in self._severed:
                self._severed.add(seq)
                return True
        return False

    # -- the serving loop side ----------------------------------------

    def poll(self) -> List[Job]:
        wave, back = self.ledger.take_wave()
        jobs: List[Job] = []
        for p in wave:
            rec = dict(p.record)
            rec["deadline"] = resolve_deadline(rec)
            try:
                jobs.append(job_from_record(self._config, rec))
            except ValueError as e:
                # malformed past the ledger's checks (bad trace body):
                # still loud — a post-ack NACK, never a silent drop
                sess = self._owner.pop(str(rec.get("id")), None)
                if sess is not None:
                    sess.send(NACK,
                              {"id": rec.get("id"), "reason": str(e)})
                    with self._lock:
                        sess.outstanding -= 1
                    self._maybe_bye(sess)
        for key, n in back.items():
            sess = self._sessions.get(key)
            if sess is not None:
                sess.send(CREDIT, {"credits": n})
        return jobs

    def deliver(self, result: JobResult) -> None:
        """Stream one result to its owning session (pass as the
        serving loop's ``emit`` callback).  If the session's socket is
        down, the record parks on the session and flushes on resume."""
        sess = self._owner.pop(result.job_id, None)
        if sess is None:
            return
        rec = result.to_record()
        if not sess.send(RESULT, rec):
            with self._lock:
                sess.undelivered.append(rec)
        with self._lock:
            sess.outstanding -= 1
        self._maybe_bye(sess)

    def _maybe_bye(self, sess: _Session) -> None:
        with self._lock:
            done = (sess.eof and sess.outstanding <= 0
                    and not sess.undelivered)
        if done and sess.send(BYE):
            if sess.conn is not None:
                sess.conn.close()
            self.ledger.forget(sess.id)
            with self._lock:
                self._sessions.pop(sess.id, None)

    @property
    def exhausted(self) -> bool:
        if self._closed.is_set():
            return self.ledger.pending == 0
        with self._lock:
            resumable = any(
                not s.eof
                and (s.conn is None or s.conn.dead)
                and (s.outstanding > 0 or s.undelivered)
                for s in self._sessions.values())
            drained = self._saw_conn and not self._open and not resumable
        return drained and self.ledger.pending == 0

    def close(self) -> None:
        self._closed.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            c.close()
