"""The wire frontend: a framed TCP listener that feeds the serving
loop through the admission ledger.

:class:`WireJobSource` is a drop-in
:class:`~hpa2_tpu.serving.ingest.JobSource`: the serving loop polls it
once per admission opportunity exactly like the JSONL socket feed, but
every submission is acknowledged (ACK with the global admission seq)
or rejected loudly (NACK with a reason), and each connection is
credit-clocked so overload pushes back instead of silently dropping.

``poll()`` drains **one admission wave** from the ledger in seq order
— many small jobs arriving between two scheduler intervals enter the
scheduler as one batch, ordered by their ack sequence numbers, not by
reader-thread timing.  Results stream back to the *owning* connection
as RESULT frames via :meth:`WireJobSource.deliver` (pass it as the
serving loop's ``emit`` callback); once a connection has sent EOF and
its last result is delivered the server answers BYE and closes.
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Dict, List, Optional

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.serving.ingest import JobSource
from hpa2_tpu.serving.jobs import Job, JobResult, job_from_record
from hpa2_tpu.service.admission import (
    AdmissionLedger, AdmissionReject, TenantTable, resolve_deadline)
from hpa2_tpu.service.wire import (
    ACK, BYE, CREDIT, EOF, HELLO, NACK, RESULT, SUBMIT, VERSION,
    FrameReader, WireError, encode_frame)


class _Conn:
    """One client connection: socket + send lock (the reader thread
    answers ACK/NACK while the serving thread streams RESULT/CREDIT)."""

    def __init__(self, conn_id: int, sock: socket.socket):
        self.id = conn_id
        self.sock = sock
        self.lock = threading.Lock()
        self.outstanding = 0   # accepted submits awaiting RESULT
        self.eof = False       # client finished submitting
        self.dead = False

    def send(self, ftype: int, payload: Optional[dict] = None) -> None:
        data = encode_frame(ftype, payload)
        with self.lock:
            if self.dead:
                return
            try:
                self.sock.sendall(data)
            except OSError:
                self.dead = True

    def close(self) -> None:
        with self.lock:
            self.dead = True
            try:
                self.sock.close()
            except OSError:
                pass


class WireJobSource(JobSource):
    """Framed multi-tenant TCP feed (see the module docstring)."""

    def __init__(self, config: SystemConfig, host: str = "127.0.0.1",
                 port: int = 0, *, credits: int = 64, backlog: int = 8,
                 tenants: Optional[TenantTable] = None):
        self._config = config
        self.tenants = tenants or TenantTable()
        self.ledger = AdmissionLedger(credits)
        self._lock = threading.Lock()
        self._conns: Dict[int, _Conn] = {}
        self._owner: Dict[str, _Conn] = {}
        self._open: set = set()    # conn ids still submitting
        self._saw_conn = False
        self._ids = itertools.count()
        self._closed = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(backlog)
        self._srv.settimeout(0.1)
        self.address = self._srv.getsockname()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def tenant_weights(self) -> Optional[Dict[str, float]]:
        """The weight dict ``serve(tenant_weights=...)`` wants."""
        return dict(self.tenants.weights) or None

    # -- listener ------------------------------------------------------

    def _serve(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            c = _Conn(next(self._ids), sock)
            with self._lock:
                self._conns[c.id] = c
                self._open.add(c.id)
                self._saw_conn = True
            budget = self.ledger.register(c.id)
            c.send(HELLO, {"version": VERSION, "credits": budget})
            threading.Thread(
                target=self._read_conn, args=(c,), daemon=True
            ).start()

    def _read_conn(self, c: _Conn) -> None:
        reader = FrameReader()
        try:
            while not c.eof:
                data = c.sock.recv(65536)
                if not data:
                    break
                for fr in reader.feed(data):
                    if fr.ftype == SUBMIT:
                        self._on_submit(c, fr.payload)
                    elif fr.ftype == EOF:
                        with self._lock:
                            c.eof = True
                            self._open.discard(c.id)
                        self._maybe_bye(c)
                        break
                    else:
                        raise WireError(
                            f"unexpected client frame {fr.ftype}")
        except (OSError, WireError, ValueError):
            # abrupt disconnect or framing violation: drop the
            # connection; everything already ACK'd stays admitted
            c.close()
        finally:
            with self._lock:
                self._open.discard(c.id)
            if c.dead:
                self.ledger.forget(c.id)
        # reader exits after EOF with the socket open — the serving
        # thread still streams RESULT frames and the closing BYE

    def _on_submit(self, c: _Conn, record: dict) -> None:
        job_id = record.get("id")
        try:
            seq, pos = self.ledger.try_submit(c.id, record)
        except AdmissionReject as e:
            c.send(NACK, {"id": job_id, "reason": str(e)})
            return
        with self._lock:
            self._owner[str(job_id)] = c
            c.outstanding += 1
        c.send(ACK, {"id": job_id, "seq": seq, "queue_pos": pos})

    # -- the serving loop side ----------------------------------------

    def poll(self) -> List[Job]:
        wave, back = self.ledger.take_wave()
        jobs: List[Job] = []
        for p in wave:
            rec = dict(p.record)
            rec["deadline"] = resolve_deadline(rec)
            try:
                jobs.append(job_from_record(self._config, rec))
            except ValueError as e:
                # malformed past the ledger's checks (bad trace body):
                # still loud — a post-ack NACK, never a silent drop
                c = self._owner.pop(str(rec.get("id")), None)
                if c is not None:
                    c.send(NACK,
                           {"id": rec.get("id"), "reason": str(e)})
                    with self._lock:
                        c.outstanding -= 1
                    self._maybe_bye(c)
        for conn_id, n in back.items():
            c = self._conns.get(conn_id)
            if c is not None:
                c.send(CREDIT, {"credits": n})
        return jobs

    def deliver(self, result: JobResult) -> None:
        """Stream one result to its owning connection (pass as the
        serving loop's ``emit`` callback)."""
        c = self._owner.pop(result.job_id, None)
        if c is None:
            return
        c.send(RESULT, result.to_record())
        with self._lock:
            c.outstanding -= 1
        self._maybe_bye(c)

    def _maybe_bye(self, c: _Conn) -> None:
        with self._lock:
            done = c.eof and c.outstanding <= 0
        if done:
            c.send(BYE)
            c.close()
            self.ledger.forget(c.id)

    @property
    def exhausted(self) -> bool:
        if self._closed.is_set():
            return self.ledger.pending == 0
        with self._lock:
            drained = self._saw_conn and not self._open
        return drained and self.ledger.pending == 0

    def close(self) -> None:
        self._closed.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            c.close()
