"""Multi-tenant admission: who gets in, in what order, and when to
push back.

Two halves:

- :class:`TenantTable` — the static tenant configuration (weights for
  the fair-drr scheduler policy, parsed from the CLI's
  ``--tenant-weights name:w,name:w`` spec).
- :class:`AdmissionLedger` — the thread-safe meeting point between the
  wire frontend's per-connection reader threads and the serving
  loop's single-threaded poll.  ``try_submit`` either assigns the
  global admission sequence number (the ACK ``seq``) or rejects
  *loudly* (credit exhaustion, duplicate id, malformed record — every
  rejection carries a reason; nothing is silently dropped).
  ``take_wave`` drains pending submissions **in seq order** — one
  admission wave per scheduler interval — so the order jobs enter the
  :class:`~hpa2_tpu.ops.schedule.LaneScheduler` is fixed by the ack
  transcript, not by reader-thread timing.

Deadline classes map service-level names onto the scheduler's
deadline-in-intervals unit so clients don't need to know interval
granularity: ``interactive`` (8), ``standard`` (32), ``batch`` (no
deadline).  An explicit ``deadline`` field on a record always wins.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterator, List, Optional, Tuple

DEADLINE_CLASSES: Dict[str, int] = {
    "interactive": 8,
    "standard": 32,
    "batch": -1,
}


def resolve_deadline(record: dict) -> int:
    """The deadline (in scheduling intervals, -1 = none) a job record
    asks for: explicit ``deadline`` wins, else its ``class`` name."""
    if "deadline" in record:
        return int(record["deadline"])
    cls = record.get("class")
    if cls is None:
        return -1
    try:
        return DEADLINE_CLASSES[cls]
    except KeyError:
        raise ValueError(
            f"unknown deadline class {cls!r}; expected one of "
            f"{sorted(DEADLINE_CLASSES)}"
        ) from None


@dataclasses.dataclass(frozen=True)
class TenantTable:
    """Per-tenant fair-share weights (default tenant weighs 1.0)."""

    weights: Dict[str, float] = dataclasses.field(default_factory=dict)

    @classmethod
    def parse(cls, spec: str) -> "TenantTable":
        """Parse the CLI spec ``"alice:4,bob:1"`` (weight > 0)."""
        weights: Dict[str, float] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, w = part.partition(":")
            if not sep or not name:
                raise ValueError(
                    f"bad tenant weight {part!r}; expected name:weight"
                )
            try:
                weight = float(w)
            except ValueError:
                raise ValueError(
                    f"bad tenant weight {part!r}; expected name:weight"
                ) from None
            if weight <= 0:
                raise ValueError(
                    f"tenant {name!r} weight must be > 0, got {weight}"
                )
            weights[name] = weight
        return cls(weights)

    def weight_of(self, name: str) -> float:
        return self.weights.get(name, 1.0)

    def __bool__(self) -> bool:
        return bool(self.weights)


class AdmissionReject(Exception):
    """A submission the ledger refused — the message is the NACK
    reason sent back on the wire."""


class AdmissionShed(AdmissionReject):
    """A submission dropped by graceful degradation: the ledger is over
    its shed threshold and the job is batch-class (no deadline), so it
    is shed first to protect deadline traffic.  The NACK carries
    ``"shed": true`` — the client may safely resubmit later."""


@dataclasses.dataclass
class _Pending:
    seq: int
    conn: int
    record: dict


class AdmissionLedger:
    """Thread-safe pending-submission ledger with per-connection
    admission credits.

    Reader threads call :meth:`try_submit`; the serving loop's poll
    calls :meth:`take_wave`.  Credits bound how far a connection may
    run ahead of admission: each accepted SUBMIT consumes one, each
    job drained by ``take_wave`` returns one to its connection (the
    frontend turns those into CREDIT frames).

    ``shed_threshold > 0`` arms graceful degradation: once the pending
    queue reaches the threshold, batch-class submissions (resolved
    deadline -1) are shed with :class:`AdmissionShed` — deadline
    traffic keeps admitting until credits push back.  ``shed_jobs``
    counts them for the occupancy model.

    Connection keys are opaque hashables: the framed frontend keys the
    ledger by *session* id so admissions survive a TCP reconnect
    (:meth:`transfer` re-points a balance when a resumed session
    changes key), and :meth:`ack_of` replays the original ack of a job
    this ledger already admitted — the idempotent-SUBMIT half of
    session resume."""

    def __init__(self, credits: int = 64, shed_threshold: int = 0):
        if credits <= 0:
            raise ValueError(f"credits must be > 0, got {credits}")
        if shed_threshold < 0:
            raise ValueError(
                f"shed_threshold must be >= 0, got {shed_threshold}")
        self.credits = int(credits)
        self.shed_threshold = int(shed_threshold)
        self.shed_jobs = 0
        self._lock = threading.Lock()
        self._seq = 0
        self._pending: List[_Pending] = []
        self._seen_ids: set = set()
        self._acks: Dict[str, Tuple[int, int]] = {}
        self._conn_credits: Dict = {}

    # -- connection lifecycle -----------------------------------------

    def register(self, conn) -> int:
        """A new connection: returns its starting credit budget."""
        with self._lock:
            self._conn_credits[conn] = self.credits
            return self.credits

    def forget(self, conn) -> None:
        with self._lock:
            self._conn_credits.pop(conn, None)

    def balance(self, conn) -> int:
        """The connection's current credit balance (0 if unknown)."""
        with self._lock:
            return self._conn_credits.get(conn, 0)

    def transfer(self, old, new) -> int:
        """Re-point a credit balance (and pending entries) from key
        ``old`` to key ``new`` — a session resuming under a different
        ledger key keeps its admission state.  Returns the balance."""
        with self._lock:
            bal = self._conn_credits.pop(old, self.credits)
            self._conn_credits[new] = bal
            for p in self._pending:
                if p.conn == old:
                    p.conn = new
            return bal

    # -- the submit side (reader threads) ------------------------------

    def try_submit(self, conn, record: dict) -> Tuple[int, int]:
        """Admit one record: returns ``(seq, queue_pos)`` or raises
        :class:`AdmissionReject` with the NACK reason."""
        job_id = record.get("id")
        if not job_id:
            raise AdmissionReject("job record needs an 'id'")
        if ("traces" in record) == ("workload" in record):
            raise AdmissionReject(
                f"job {job_id!r} needs exactly one of 'traces'/'workload'"
            )
        try:
            deadline = resolve_deadline(record)
        except ValueError as e:
            raise AdmissionReject(str(e)) from None
        with self._lock:
            left = self._conn_credits.get(conn, 0)
            if left <= 0:
                raise AdmissionReject(
                    "backpressure: no admission credits "
                    "(wait for CREDIT)"
                )
            if job_id in self._seen_ids:
                raise AdmissionReject(f"duplicate job id {job_id!r}")
            if (self.shed_threshold
                    and len(self._pending) >= self.shed_threshold
                    and deadline < 0):
                self.shed_jobs += 1
                raise AdmissionShed(
                    f"overload: shedding batch-class job {job_id!r} "
                    f"({len(self._pending)} pending >= "
                    f"{self.shed_threshold} threshold)"
                )
            self._conn_credits[conn] = left - 1
            self._seen_ids.add(job_id)
            seq = self._seq
            self._seq += 1
            self._pending.append(_Pending(seq, conn, record))
            self._acks[str(job_id)] = (seq, len(self._pending) - 1)
            return seq, len(self._pending) - 1

    def ack_of(self, job_id: str) -> Optional[Tuple[int, int]]:
        """The ``(seq, queue_pos)`` this ledger originally acked for an
        already-admitted job id, or None — lets the frontend replay an
        ack for an idempotent resubmit instead of NACKing it."""
        with self._lock:
            return self._acks.get(str(job_id))

    # -- the drain side (the serving loop's poll) ----------------------

    def take_wave(
        self, limit: Optional[int] = None
    ) -> Tuple[List[_Pending], Dict[int, int]]:
        """Drain up to ``limit`` pending submissions in seq order.
        Returns ``(wave, credits_back)`` — credits_back maps each
        connection to how many credits it regained."""
        with self._lock:
            if limit is None or limit >= len(self._pending):
                wave, self._pending = self._pending, []
            else:
                wave = self._pending[:limit]
                self._pending = self._pending[limit:]
            back: Dict[int, int] = {}
            for p in wave:
                if p.conn in self._conn_credits:
                    self._conn_credits[p.conn] += 1
                    back[p.conn] = back.get(p.conn, 0) + 1
            return wave, back

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)
