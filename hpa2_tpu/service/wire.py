"""The framed wire protocol of the multi-tenant service plane.

The JSONL socket feed (:class:`~hpa2_tpu.serving.ingest.\
SocketJobSource`) is fire-and-forget: a client never learns whether a
job was admitted, results don't come back, and overload is a silent
drop at the TCP buffer.  The service plane replaces it with a
length-prefixed *framed* protocol with explicit acknowledgement and
credit-based backpressure:

- every frame is an 8-byte header + a JSON payload::

      >BBBxI  = magic (0xA2) | version (1) | type | pad | payload len

- the server opens with HELLO advertising this connection's admission
  *credits*; each SUBMIT consumes one credit and draws either an ACK
  (``{"id", "seq", "queue_pos", "credits"}``) or a loud NACK
  (``{"id", "reason"}``) — **never** a silent drop;
- credits replenish via CREDIT frames as submitted jobs are admitted
  into the scheduler, so a well-behaved client self-clocks to the
  server's admission rate;
- results stream back as RESULT frames while the connection is still
  submitting; EOF (client) / BYE (server) close the conversation.

ACK ``seq`` is the global admission sequence number — the order jobs
enter the scheduler, fixed at SUBMIT time by the server, independent
of client thread timing.  That is what makes multi-client ingest
deterministic *given the ack transcript*.

The JSONL feed remains for offline replay (jobs files); this module is
the live path.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, List, Optional, Tuple

MAGIC = 0xA2
VERSION = 1

# frame types
HELLO = 1    # server -> client: {"version", "credits"}
SUBMIT = 2   # client -> server: a job record (jobs.py JSONL schema)
ACK = 3      # server -> client: {"id", "seq", "queue_pos", "credits"}
NACK = 4     # server -> client: {"id", "reason"}
RESULT = 5   # server -> client: a JobResult record chunk
CREDIT = 6   # server -> client: {"credits": n} replenish
EOF = 7      # client -> server: done submitting on this connection
BYE = 8      # server -> client: all results delivered, closing

FRAME_NAMES = {
    HELLO: "HELLO", SUBMIT: "SUBMIT", ACK: "ACK", NACK: "NACK",
    RESULT: "RESULT", CREDIT: "CREDIT", EOF: "EOF", BYE: "BYE",
}

_HEADER = struct.Struct(">BBBxI")
MAX_PAYLOAD = 1 << 24  # 16 MiB — far beyond any job record


class WireError(Exception):
    """Framing violation: bad magic/version/type or oversized frame."""


class WireNack(Exception):
    """A SUBMIT was rejected by the server (the payload says why)."""

    def __init__(self, payload: dict):
        super().__init__(payload.get("reason", "rejected"))
        self.payload = payload


def encode_frame(ftype: int, payload: Optional[dict] = None) -> bytes:
    if ftype not in FRAME_NAMES:
        raise WireError(f"unknown frame type {ftype}")
    body = b"" if payload is None else json.dumps(
        payload, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_PAYLOAD:
        raise WireError(
            f"frame payload {len(body)} bytes exceeds {MAX_PAYLOAD}")
    return _HEADER.pack(MAGIC, VERSION, ftype, len(body)) + body


class Frame:
    __slots__ = ("ftype", "payload")

    def __init__(self, ftype: int, payload: dict):
        self.ftype = ftype
        self.payload = payload

    def __repr__(self) -> str:
        name = FRAME_NAMES.get(self.ftype, self.ftype)
        return f"Frame({name}, {self.payload!r})"


class FrameReader:
    """Incremental frame parser: ``feed(chunk)`` returns every frame
    completed by that chunk, buffering any partial tail.  Byte-at-a-
    time feeding reassembles identically — framing never depends on
    TCP segmentation."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Frame]:
        self._buf.extend(data)
        out: List[Frame] = []
        while True:
            if len(self._buf) < _HEADER.size:
                return out
            magic, version, ftype, length = _HEADER.unpack_from(self._buf)
            if magic != MAGIC:
                raise WireError(f"bad magic 0x{magic:02x}")
            if version != VERSION:
                raise WireError(
                    f"wire version {version} != {VERSION}")
            if ftype not in FRAME_NAMES:
                raise WireError(f"unknown frame type {ftype}")
            if length > MAX_PAYLOAD:
                raise WireError(
                    f"frame payload {length} bytes exceeds {MAX_PAYLOAD}")
            if len(self._buf) < _HEADER.size + length:
                return out
            body = bytes(self._buf[_HEADER.size:_HEADER.size + length])
            del self._buf[:_HEADER.size + length]
            payload = json.loads(body.decode("utf-8")) if body else {}
            out.append(Frame(ftype, payload))


class WireClient:
    """A blocking framed client for tests, benchmarks and the CLI.

    ``submit()`` consumes one local credit (blocking on CREDIT
    replenishment when out) and returns the server's ACK payload;
    a NACK raises :class:`WireNack`.  ``force=True`` skips the local
    credit gate — the way to *prove* the server NACKs over-submission
    instead of dropping it.  RESULT frames that arrive interleaved are
    collected on :attr:`results`; ``finish()`` sends EOF and drains to
    BYE."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0):
        self._sock = socket.create_connection(
            (host, port), timeout=timeout_s)
        self._reader = FrameReader()
        self._inbox: List[Frame] = []
        self.results: List[dict] = []
        self.credits = 0
        hello = self._next_frame((HELLO,))
        if hello.payload.get("version") != VERSION:
            raise WireError(
                f"server wire version {hello.payload.get('version')}"
                f" != {VERSION}")
        self.credits = int(hello.payload.get("credits", 0))

    # -- frame plumbing -----------------------------------------------

    def _pump(self) -> None:
        data = self._sock.recv(65536)
        if not data:
            raise WireError("server closed the connection mid-stream")
        self._inbox.extend(self._reader.feed(data))

    def _next_frame(self, wanted: Tuple[int, ...]) -> Frame:
        """Return the next frame of a wanted type, absorbing RESULT
        and CREDIT frames that arrive in between."""
        while True:
            while self._inbox:
                fr = self._inbox.pop(0)
                if fr.ftype == RESULT:
                    self.results.append(fr.payload)
                elif fr.ftype == CREDIT:
                    self.credits += int(fr.payload.get("credits", 0))
                if fr.ftype in wanted:
                    return fr
            self._pump()

    # -- the conversation ---------------------------------------------

    def submit(self, record: dict, *, force: bool = False) -> dict:
        if not force:
            while self.credits <= 0:
                # blocked on backpressure: wait for a CREDIT frame
                self._next_frame((CREDIT,))
        self._sock.sendall(encode_frame(SUBMIT, record))
        self.credits -= 1
        fr = self._next_frame((ACK, NACK))
        if fr.ftype == NACK:
            # a rejected submit never consumed a server credit
            self.credits += 1
            raise WireNack(fr.payload)
        return fr.payload

    def finish(self) -> List[dict]:
        """EOF, then drain RESULT frames until the server says BYE."""
        self._sock.sendall(encode_frame(EOF))
        self._next_frame((BYE,))
        return self.results

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "WireClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
