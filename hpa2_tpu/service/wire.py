"""The framed wire protocol of the multi-tenant service plane.

The JSONL socket feed (:class:`~hpa2_tpu.serving.ingest.\
SocketJobSource`) is fire-and-forget: a client never learns whether a
job was admitted, results don't come back, and overload is a silent
drop at the TCP buffer.  The service plane replaces it with a
length-prefixed *framed* protocol with explicit acknowledgement and
credit-based backpressure:

- every frame is an 8-byte header + a JSON payload::

      >BBBxI  = magic (0xA2) | version (1) | type | pad | payload len

- the server opens with HELLO advertising this connection's admission
  *credits* and a ``session`` id; each SUBMIT consumes one credit and
  draws either an ACK (``{"id", "seq", "queue_pos"}``) or a loud NACK
  (``{"id", "reason"}``) — **never** a silent drop;
- credits replenish via CREDIT frames as submitted jobs are admitted
  into the scheduler, so a well-behaved client self-clocks to the
  server's admission rate;
- results stream back as RESULT frames while the connection is still
  submitting; EOF (client) / BYE (server) close the conversation.

ACK ``seq`` is the global admission sequence number — the order jobs
enter the scheduler, fixed at SUBMIT time by the server, independent
of client thread timing.  That is what makes multi-client ingest
deterministic *given the ack transcript*.

Resilience (ISSUE-16).  The TCP connection is no longer the
conversation: the server's HELLO names a *session*, and a client that
loses its socket mid-stream reconnects and sends its own HELLO
``{"resume": session, "last_seq": n}`` to re-attach — admission
credits, result ownership, and any results the server could not
deliver all survive on the session.  SUBMIT is *idempotent within a
session*: resubmitting an id the server already ACK'd replays the
original ACK (same ``seq``, flagged ``"dup": true``) instead of
NACKing, so a client that never saw its ACK can blindly resend.
:class:`WireClient` wires this up end to end: every socket op carries
a timeout, a dead server raises :class:`ConnectionLost` instead of
blocking forever, and ``retries > 0`` makes ``submit()``/``finish()``
transparently reconnect-resume under capped exponential backoff whose
jitter derives from a *seed*, not a runtime RNG.  The server emits
HEARTBEAT frames on idle connections so a stalled backend is
distinguishable from a slow one.

The JSONL feed remains for offline replay (jobs files); this module is
the live path.
"""

from __future__ import annotations

import json
import socket
import struct
import time
import zlib
from typing import List, Optional, Tuple

MAGIC = 0xA2
VERSION = 1

# frame types
HELLO = 1      # server -> client: {"version", "credits", "session"};
#                client -> server: {"resume": session, "last_seq": n}
SUBMIT = 2     # client -> server: a job record (jobs.py JSONL schema)
ACK = 3        # server -> client: {"id", "seq", "queue_pos"[, "dup"]}
NACK = 4       # server -> client: {"id", "reason"[, "shed"]}
RESULT = 5     # server -> client: a JobResult record chunk
CREDIT = 6     # server -> client: {"credits": n} replenish
EOF = 7        # client -> server: done submitting on this connection
BYE = 8        # server -> client: all results delivered, closing
HEARTBEAT = 9  # server -> client: liveness beacon on idle connections

FRAME_NAMES = {
    HELLO: "HELLO", SUBMIT: "SUBMIT", ACK: "ACK", NACK: "NACK",
    RESULT: "RESULT", CREDIT: "CREDIT", EOF: "EOF", BYE: "BYE",
    HEARTBEAT: "HEARTBEAT",
}

_HEADER = struct.Struct(">BBBxI")
MAX_PAYLOAD = 1 << 24  # 16 MiB — far beyond any job record


class WireError(Exception):
    """Framing violation: bad magic/version/type or oversized frame."""


class ConnectionLost(WireError):
    """The transport died under the conversation: connect refused, a
    socket timeout (dead or hung server), or the peer closing
    mid-stream.  Retryable — :class:`WireClient` with ``retries > 0``
    reconnects and resumes the session instead of surfacing this."""


class WireNack(Exception):
    """A SUBMIT was rejected by the server (the payload says why)."""

    def __init__(self, payload: dict):
        super().__init__(payload.get("reason", "rejected"))
        self.payload = payload

    @property
    def shed(self) -> bool:
        """True when the job was load-shed (overload degradation),
        not malformed — safe to resubmit later."""
        return bool(self.payload.get("shed"))


def encode_frame(ftype: int, payload: Optional[dict] = None) -> bytes:
    if ftype not in FRAME_NAMES:
        raise WireError(f"unknown frame type {ftype}")
    body = b"" if payload is None else json.dumps(
        payload, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_PAYLOAD:
        raise WireError(
            f"frame payload {len(body)} bytes exceeds {MAX_PAYLOAD}")
    return _HEADER.pack(MAGIC, VERSION, ftype, len(body)) + body


def backoff_delay(attempt: int, *, base_s: float = 0.05,
                  cap_s: float = 2.0, seed: int = 0) -> float:
    """Capped exponential backoff with deterministic jitter.

    The jitter fraction is a pure function of ``(seed, attempt)``
    (CRC32 — no RNG, no clock), so a retry schedule is reproducible
    from its seed: delay = min(cap, base * 2^attempt) * [0.5, 1.0).
    """
    ceiling = min(cap_s, base_s * (2.0 ** attempt))
    frac = (zlib.crc32(f"{seed}:{attempt}".encode()) % 1000) / 1000.0
    return ceiling * (0.5 + 0.5 * frac)


class Frame:
    __slots__ = ("ftype", "payload")

    def __init__(self, ftype: int, payload: dict):
        self.ftype = ftype
        self.payload = payload

    def __repr__(self) -> str:
        name = FRAME_NAMES.get(self.ftype, self.ftype)
        return f"Frame({name}, {self.payload!r})"


class FrameReader:
    """Incremental frame parser: ``feed(chunk)`` returns every frame
    completed by that chunk, buffering any partial tail.  Byte-at-a-
    time feeding reassembles identically — framing never depends on
    TCP segmentation."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Frame]:
        self._buf.extend(data)
        out: List[Frame] = []
        while True:
            if len(self._buf) < _HEADER.size:
                return out
            magic, version, ftype, length = _HEADER.unpack_from(self._buf)
            if magic != MAGIC:
                raise WireError(f"bad magic 0x{magic:02x}")
            if version != VERSION:
                raise WireError(
                    f"wire version {version} != {VERSION}")
            if ftype not in FRAME_NAMES:
                raise WireError(f"unknown frame type {ftype}")
            if length > MAX_PAYLOAD:
                raise WireError(
                    f"frame payload {length} bytes exceeds {MAX_PAYLOAD}")
            if len(self._buf) < _HEADER.size + length:
                return out
            body = bytes(self._buf[_HEADER.size:_HEADER.size + length])
            del self._buf[:_HEADER.size + length]
            payload = json.loads(body.decode("utf-8")) if body else {}
            out.append(Frame(ftype, payload))


class WireClient:
    """A blocking framed client for tests, benchmarks and the CLI.

    ``submit()`` consumes one local credit (blocking on CREDIT
    replenishment when out) and returns the server's ACK payload;
    a NACK raises :class:`WireNack`.  ``force=True`` skips the local
    credit gate — the way to *prove* the server NACKs over-submission
    instead of dropping it.  RESULT frames that arrive interleaved are
    collected on :attr:`results`; ``finish()`` sends EOF and drains to
    BYE.

    Every socket operation carries ``timeout_s`` — a dead or hung
    server raises :class:`ConnectionLost` instead of blocking forever.
    With ``retries > 0``, ``submit()`` and ``finish()`` survive a lost
    connection: the client sleeps a seeded backoff
    (:func:`backoff_delay`), reconnects, resumes its server session
    (HELLO ``{"resume": ...}``) and resends — idempotent SUBMIT means
    a resend of an already-admitted id draws the *original* ACK seq.
    :attr:`retries` counts reconnections actually performed.
    """

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, retries: int = 0,
                 backoff_s: float = 0.05, backoff_cap_s: float = 2.0,
                 backoff_seed: int = 0):
        self._host, self._port = host, port
        self._timeout_s = timeout_s
        self._retries = int(retries)
        self._backoff_s = backoff_s
        self._backoff_cap_s = backoff_cap_s
        self._backoff_seed = int(backoff_seed)
        self._sock: Optional[socket.socket] = None
        self._reader = FrameReader()
        self._inbox: List[Frame] = []
        self.results: List[dict] = []
        self.credits = 0
        self.session: Optional[str] = None
        self.last_seq = -1
        self.retries = 0      # reconnections performed
        self.heartbeats = 0   # HEARTBEAT frames absorbed
        self._with_retry(lambda: None)  # connect (with backoff)

    # -- connection lifecycle -----------------------------------------

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._reader = FrameReader()
        self._inbox = []

    def _connect(self) -> None:
        """Dial, read the server HELLO, and (re)attach the session."""
        resume = self.session
        try:
            self._sock = socket.create_connection(
                (self._host, self._port), timeout=self._timeout_s)
        except OSError as e:
            self._sock = None
            raise ConnectionLost(
                f"connect to {self._host}:{self._port} failed: {e}"
            ) from None
        self._reader = FrameReader()
        self._inbox = []
        hello = self._next_frame((HELLO,))
        if hello.payload.get("version") != VERSION:
            raise WireError(
                f"server wire version {hello.payload.get('version')}"
                f" != {VERSION}")
        self.credits = int(hello.payload.get("credits", 0))
        self.session = hello.payload.get("session")
        if resume is not None:
            # ask the server to re-attach the old conversation; its
            # reply HELLO reports the surviving credit balance (and
            # re-sends any results it could not deliver)
            self._send(encode_frame(
                HELLO, {"resume": resume, "last_seq": self.last_seq}))
            hello = self._next_frame((HELLO,))
            self.credits = int(hello.payload.get("credits", 0))
            if hello.payload.get("resumed"):
                self.session = resume
            else:
                self.session = hello.payload.get("session", self.session)

    def _with_retry(self, op):
        """Run ``op`` with the (re)connect-resume-backoff loop around
        it; ``op`` runs on a live connection."""
        attempt = 0
        while True:
            try:
                if self._sock is None:
                    self._connect()
                return op()
            except ConnectionLost:
                self._teardown()
                if attempt >= self._retries:
                    raise
                time.sleep(backoff_delay(
                    attempt, base_s=self._backoff_s,
                    cap_s=self._backoff_cap_s, seed=self._backoff_seed))
                attempt += 1
                self.retries += 1

    # -- frame plumbing -----------------------------------------------

    def _send(self, data: bytes) -> None:
        try:
            self._sock.sendall(data)
        except (socket.timeout, OSError) as e:
            raise ConnectionLost(f"send failed: {e}") from None

    def _pump(self) -> None:
        try:
            data = self._sock.recv(65536)
        except socket.timeout:
            raise ConnectionLost(
                f"server silent for {self._timeout_s}s"
            ) from None
        except OSError as e:
            raise ConnectionLost(f"recv failed: {e}") from None
        if not data:
            raise ConnectionLost(
                "server closed the connection mid-stream")
        self._inbox.extend(self._reader.feed(data))

    def _next_frame(self, wanted: Tuple[int, ...]) -> Frame:
        """Return the next frame of a wanted type, absorbing RESULT,
        CREDIT and HEARTBEAT frames that arrive in between."""
        while True:
            while self._inbox:
                fr = self._inbox.pop(0)
                if fr.ftype == RESULT:
                    self.results.append(fr.payload)
                elif fr.ftype == CREDIT:
                    self.credits += int(fr.payload.get("credits", 0))
                elif fr.ftype == HEARTBEAT:
                    self.heartbeats += 1
                if fr.ftype in wanted:
                    return fr
            self._pump()

    # -- the conversation ---------------------------------------------

    def _submit_once(self, record: dict, force: bool) -> dict:
        if not force:
            while self.credits <= 0:
                # blocked on backpressure: wait for a CREDIT frame
                self._next_frame((CREDIT,))
        self._send(encode_frame(SUBMIT, record))
        fr = self._next_frame((ACK, NACK))
        if fr.ftype == NACK:
            raise WireNack(fr.payload)
        if not fr.payload.get("dup"):
            # a replayed ack never consumed a fresh server credit
            self.credits -= 1
        self.last_seq = max(self.last_seq,
                            int(fr.payload.get("seq", -1)))
        return fr.payload

    def submit(self, record: dict, *, force: bool = False) -> dict:
        return self._with_retry(
            lambda: self._submit_once(record, force))

    def _finish_once(self) -> List[dict]:
        self._send(encode_frame(EOF))
        self._next_frame((BYE,))
        return self.results

    def finish(self) -> List[dict]:
        """EOF, then drain RESULT frames until the server says BYE."""
        return self._with_retry(self._finish_once)

    def close(self) -> None:
        self._teardown()

    def __enter__(self) -> "WireClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
