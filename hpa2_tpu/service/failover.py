"""Failure injection above the link layer.

The spec engine's :class:`~hpa2_tpu.config.FaultModel` perturbs
*messages* (drop/duplicate/reorder inside the interconnect); this
module injects *infrastructure* failures into a live serving run — the
kind a production stack must survive, not merely detect:

- ``kill@k``   — the backend engine dies at serving interval ``k``
  (process loss: the device state is gone, only checkpoints survive);
- ``hang@k[:t]`` — node shard ``t`` stops making progress at interval
  ``k``: the exchange never quiesces, so nothing fails loudly until
  the supervisor's *watchdog* notices N barriers with no completions
  and raises with a :class:`StallDiagnostic`-style postmortem;
- ``poison@k[:s]`` — lane block corruption detected at interval ``k``:
  the resident session can no longer be trusted, in-flight jobs must
  evacuate to a fresh session;
- ``sever@seq`` — the wire frontend cuts a client connection mid-frame
  at global ack ``seq`` (handled in
  :class:`~hpa2_tpu.service.frontend.WireJobSource`, not here).

Everything is driven by the deterministic, seeded
:class:`~hpa2_tpu.config.FailurePlan` — no RNG and no clocks at
runtime (the same purity rule the interconnect lint enforces), so a
chaos run is exactly reproducible from its config.
:class:`FailureInjector` turns the plan into the serving loops'
``interval_hook``: at each interval barrier it raises
:class:`InjectedFailure` for any event that has come due.  Each event
fires **once** per injector — the supervisor reuses one injector
across recovery attempts, so a kill at interval 3 does not re-kill the
migrated-to session when *its* interval counter passes 3.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from hpa2_tpu.config import FailureEvent, FailurePlan


class InjectedFailure(Exception):
    """A planned infrastructure failure fired at an interval barrier.
    Carries the :class:`FailureEvent` and the barrier it fired at so
    the recovery supervisor can decide migrate-vs-evacuate and log a
    structured record."""

    def __init__(self, event: FailureEvent, interval: int,
                 diagnostic: Optional[object] = None):
        self.event = event
        self.interval = interval
        self.diagnostic = diagnostic   # StallDiagnostic for hangs
        msg = (f"injected {event.kind} at interval {interval} "
               f"(planned {event.spec()})")
        if diagnostic is not None:
            msg = f"{msg}\n{diagnostic}"
        super().__init__(msg)


def recovery_record(event: str, **fields) -> Dict:
    """One structured recovery-event record (the observability unit
    flowing through ``ServingStats`` and the bench artifact): a dict
    with a stable ``"event"`` discriminator first, JSON-able values
    only."""
    rec = {"event": str(event)}
    for k, v in fields.items():
        rec[k] = v if isinstance(v, (int, float, str, bool, list,
                                     dict, type(None))) else str(v)
    return rec


class FailureInjector:
    """The serving loops' ``interval_hook`` for one failure plan.

    ``kill`` and ``poison`` events raise the moment their barrier is
    reached.  A ``hang`` first puts the injector into a hung phase —
    the target shard has silently stopped — and only raises after
    ``detect_after`` further barriers with no harvest progress, the
    deterministic analog of a watchdog timeout; the raise carries a
    stall postmortem gathered from the still-live session when the
    backend can produce one.
    """

    def __init__(self, plan: FailurePlan, *, detect_after: int = 2):
        self.plan = plan
        self.detect_after = int(detect_after)
        self._due: List[FailureEvent] = sorted(
            plan.of_kind("kill", "hang", "poison"),
            key=lambda ev: (ev.at, ev.kind),
        )
        self._fired: set = set()
        self._hang: Optional[FailureEvent] = None
        self._hang_at = 0

    @property
    def pending(self) -> int:
        """Events not yet fired (sever events excluded — the wire
        frontend owns those)."""
        return len(self._due) + (1 if self._hang is not None else 0)

    def _diagnose_hang(self, ev: FailureEvent, driver):
        """Best-effort stall postmortem through the backend's own
        diagnostic path (the jax session can gather a row; pallas
        kernels have no mid-flight readback)."""
        sess = getattr(driver, "session", None)
        stall_of = getattr(sess, "stall_of", None)
        if stall_of is None:
            return None
        try:
            import numpy as np

            rows = getattr(driver, "row_sys", None)
            live = np.nonzero(np.asarray(rows) >= 0)[0] if rows is not None else []
            idx = int(live[0]) if len(live) else 0
            return stall_of(
                idx,
                f"injected shard hang (node shard {ev.target}): "
                f"exchange never quiesced; watchdog fired after "
                f"{self.detect_after} barriers with no progress",
            )
        except Exception:
            return None

    def hook(self, k: int, driver) -> None:
        """The ``interval_hook``: raise any failure due at barrier
        ``k``.  ``driver`` is the live serving session driver."""
        if self._hang is not None and k >= self._hang_at + self.detect_after:
            ev, self._hang = self._hang, None
            raise InjectedFailure(ev, k, self._diagnose_hang(ev, driver))
        while self._due and self._due[0].at <= k:
            ev = self._due.pop(0)
            key = ev.spec()
            if key in self._fired:
                continue
            self._fired.add(key)
            if ev.kind == "hang":
                # the shard goes silent now; the watchdog raises later
                if self._hang is None:
                    self._hang, self._hang_at = ev, k
                continue
            raise InjectedFailure(ev, k)


@dataclasses.dataclass
class RecoveryLog:
    """Accumulates structured recovery events + the counters that ride
    checkpoint metadata (schema v2) and the serving artifact."""

    failures_detected: int = 0
    checkpoints: int = 0
    migrations: int = 0
    evacuations: int = 0
    lanes_resumed: int = 0
    jobs_replayed: int = 0
    shed_jobs: int = 0
    retries: int = 0
    events: List[Dict] = dataclasses.field(default_factory=list)

    def record(self, event: str, **fields) -> Dict:
        rec = recovery_record(event, **fields)
        self.events.append(rec)
        return rec

    def counters(self) -> Dict[str, int]:
        """The schema-v2 checkpoint counter quartet."""
        return {
            "migrations": self.migrations,
            "evacuations": self.evacuations,
            "shed_jobs": self.shed_jobs,
            "retries": self.retries,
        }

    def as_dict(self) -> Dict:
        out = {
            "failures_detected": self.failures_detected,
            "checkpoints": self.checkpoints,
            "migrations": self.migrations,
            "evacuations": self.evacuations,
            "lanes_resumed": self.lanes_resumed,
            "jobs_replayed": self.jobs_replayed,
            "shed_jobs": self.shed_jobs,
            "retries": self.retries,
        }
        if self.events:
            out["events"] = list(self.events)
        return out
