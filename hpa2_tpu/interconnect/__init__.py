"""Topology-aware interconnect model (deterministic, jitter-free).

Static topologies (hop paths + base latencies) live in
:mod:`hpa2_tpu.interconnect.topology`; the per-cycle link-occupancy
reference walk lives in :mod:`hpa2_tpu.interconnect.delay`.  Both
engines consume them: the spec engine scalar-by-scalar, the JAX step
as baked constants.  Everything here must stay a pure function of
config + trace — no ``random``, no ``time`` (lint-enforced).
"""

from hpa2_tpu.interconnect.delay import LinkTracker
from hpa2_tpu.interconnect.topology import (
    TOPOLOGIES,
    Topology,
    build_topology,
)

__all__ = ["TOPOLOGIES", "Topology", "build_topology", "LinkTracker"]
