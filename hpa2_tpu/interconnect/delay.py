"""Deterministic per-cycle link occupancy: the sequential reference.

One :class:`LinkTracker` instance models contention for one system.
Per delivery cycle, every *accepted* message is walked in the global
deterministic candidate order (phase, sender, emission order — exactly
the spec engine's ``_deliver`` walk, which the JAX step's candidate-grid
id order provably equals) and charged

    ``deliver_at = cycle + max(1, base_lat[src, dst]) + penalty``

where ``penalty`` is the queueing cost of finite link bandwidth:
each link carries ``bandwidth`` messages per cycle, so a message pays
``floor(prior_traversals / bandwidth)`` extra cycles per link on its
path, with ``prior_traversals`` counting the *earlier* accepted
messages this cycle that traversed that link (FIFO per link, tie-break
by walk position — i.e. by (node, mailbox order)).  The model is
memoryless across cycles: occupancy resets every cycle, so delivery
cycles are a pure function of config + trace — no RNG, no clocks
(enforced by the interconnect lint rule), and the JAX step computes
the identical function vectorially (ops/step.py ``topo_on`` block).

Variants:

  ``multicast``  one INV fan-out payload traverses a shared link once
                 for all destinations (the AXI-crossbar model,
                 PAPERS.md): within a fan-out, only the first receiver
                 (ascending) to use a link contributes occupancy;
                 later receivers ride along.  Riders still *see* the
                 group's single traversal in their own penalty prefix —
                 they queue behind the shared transfer, a deliberate
                 conservative-by-<=1-slot simplification that keeps the
                 spec walk and the JAX cumsum trivially identical.
  ``combining``  same-address READ_REQUESTs merge in the network
                 (Ultracomputer-style): only the first request this
                 cycle per address traverses; merged riders contribute
                 zero occupancy on every link (and are counted).

The tracker also keeps the per-link observability the stats schema
exports: total traversals, max single-cycle load, and an occupancy
histogram (spec side only — the JAX state carries traversals/max).
"""

from __future__ import annotations

import collections
from typing import Dict, Set, Tuple

import numpy as np

from hpa2_tpu.interconnect.topology import Topology


class LinkTracker:
    def __init__(
        self,
        topo: Topology,
        bandwidth: int = 1,
        multicast: bool = False,
        combining: bool = False,
    ):
        if bandwidth < 1:
            raise ValueError("link bandwidth must be >= 1")
        self.topo = topo
        self.bandwidth = bandwidth
        self.multicast = multicast
        self.combining = combining
        L = topo.num_links
        # per-cycle state
        self._load = np.zeros(L, dtype=np.int64)
        self._mcast_links: Dict[Tuple[int, int], Set[int]] = {}
        self._combined_seen: Set[int] = set()
        # cumulative observability
        self.traversals = np.zeros(L, dtype=np.int64)
        self.max_load = np.zeros(L, dtype=np.int64)
        self.occupancy_hist: Dict[int, collections.Counter] = {
            l: collections.Counter() for l in range(L)
        }
        self.n_topo_delay = 0
        self.n_multicast_saved = 0
        self.n_combined = 0
        # paths as index lists (dense path_mat rows are slow to re-scan)
        self._paths = [
            [
                np.nonzero(topo.path_mat[s, d])[0].tolist()
                for d in range(topo.n)
            ]
            for s in range(topo.n)
        ]

    def begin_cycle(self) -> None:
        self._load[:] = 0
        self._mcast_links.clear()
        self._combined_seen.clear()

    def on_accept(
        self, cycle: int, sender: int, receiver: int,
        msg_type: int, addr: int, is_inv: bool, is_read_request: bool,
    ) -> int:
        """Charge one accepted message (called in walk order); returns
        its delivery cycle."""
        path = self._paths[sender][receiver]
        base = max(1, int(self.topo.base_lat[sender, receiver]))
        penalty = 0
        bw = self.bandwidth
        for l in path:
            penalty += int(self._load[l]) // bw
        combined = (
            self.combining
            and is_read_request
            and addr in self._combined_seen
        )
        if combined:
            self.n_combined += 1
        elif self.multicast and is_inv:
            used = self._mcast_links.setdefault((sender, addr), set())
            for l in path:
                if l in used:
                    self.n_multicast_saved += 1
                else:
                    used.add(l)
                    self._load[l] += 1
                    self.traversals[l] += 1
        else:
            for l in path:
                self._load[l] += 1
                self.traversals[l] += 1
        if self.combining and is_read_request:
            self._combined_seen.add(addr)
        delay = base + penalty
        self.n_topo_delay += delay - 1
        return cycle + delay

    def end_cycle(self) -> None:
        np.maximum(self.max_load, self._load, out=self.max_load)
        for l in np.nonzero(self._load)[0]:
            self.occupancy_hist[int(l)][int(self._load[l])] += 1

    # -- observability -------------------------------------------------

    def counters(self) -> Dict[str, int]:
        """Aggregate counters, only-when-nonzero (the one-stats-schema
        pattern: fault-free/ideal parity stays key-for-key exact)."""
        out = {}
        for key, val in (
            ("topo_delay_cycles", self.n_topo_delay),
            ("topo_multicast_saved", self.n_multicast_saved),
            ("topo_combined", self.n_combined),
        ):
            if val:
                out[key] = int(val)
        return out

    def link_stats(self) -> Dict[str, dict]:
        names = self.topo.link_names
        return {
            "traversals": {
                names[l]: int(self.traversals[l])
                for l in range(len(names))
                if self.traversals[l]
            },
            "max_load": {
                names[l]: int(self.max_load[l])
                for l in range(len(names))
                if self.max_load[l]
            },
            "occupancy_hist": {
                names[l]: dict(sorted(h.items()))
                for l, h in self.occupancy_hist.items()
                if h
            },
        }

    # -- checkpoint support (spec crash-resume) ------------------------

    def dump_state(self) -> dict:
        return {
            "traversals": self.traversals.tolist(),
            "max_load": self.max_load.tolist(),
            "hist": {
                str(l): {str(k): v for k, v in h.items()}
                for l, h in self.occupancy_hist.items()
                if h
            },
            "n_topo_delay": self.n_topo_delay,
            "n_multicast_saved": self.n_multicast_saved,
            "n_combined": self.n_combined,
        }

    def load_state(self, doc: dict) -> None:
        self.traversals[:] = np.asarray(doc["traversals"], dtype=np.int64)
        self.max_load[:] = np.asarray(doc["max_load"], dtype=np.int64)
        for l, h in doc.get("hist", {}).items():
            self.occupancy_hist[int(l)] = collections.Counter(
                {int(k): int(v) for k, v in h.items()}
            )
        self.n_topo_delay = int(doc["n_topo_delay"])
        self.n_multicast_saved = int(doc["n_multicast_saved"])
        self.n_combined = int(doc["n_combined"])
