"""Topology registry: per-(src, dst) hop paths and base latencies.

The reference simulator teleports messages between mailboxes (SURVEY.md
§0 — no interconnect at all); the fault layer injects loss/reorder but
no *cost*.  This module is the static half of the interconnect model: a
named topology is compiled once into dense numpy tensors —

  ``hops[src, dst]``      number of links on the routed path,
  ``base_lat[src, dst]``  sum of per-link latencies along the path,
  ``path_mat[src, dst, l]`` link-incidence of the path (bool),

which both engines consume: the spec engine walks them scalar-by-scalar
(:class:`hpa2_tpu.interconnect.delay.LinkTracker`) and the JAX step
bakes them into the jitted program as constants (ops/step.py).  Every
function here is pure and deterministic — no RNG, no clocks — so
delivery cycles stay a pure function of config + trace (the lint rule
in hpa2_tpu/analysis/lint.py enforces this for the whole package).

Registered topologies (mirrored by ``config.TOPOLOGIES``):

  ``ideal``         zero links, zero base latency — today's behavior
                    (a message accepted in cycle c is handled in c+1).
  ``mesh2d``        R x C grid (R = largest divisor of N with R <= C),
                    XY dimension-ordered routing, one directed link per
                    neighbor direction, each ``hop_latency`` cycles.
  ``torus2d``       the mesh plus wraparound links; per dimension the
                    shorter direction is taken, ties broken positive.
  ``hierarchical``  two-tier ICI/DCN split: G groups (divisor of N
                    nearest sqrt(N)) of nodes around a group switch
                    (up/down links at ``hop_latency``) with all-to-all
                    inter-switch links at ``4 * hop_latency`` — the DCN
                    tier costs 4x the ICI tier.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Dict, List, Tuple

import numpy as np

TOPOLOGIES = ("ideal", "mesh2d", "torus2d", "hierarchical")

# DCN (inter-switch) links cost this many ICI hops (hierarchical only)
DCN_LATENCY_FACTOR = 4


@dataclasses.dataclass(frozen=True)
class Topology:
    """One compiled topology (immutable; arrays are never mutated)."""

    name: str
    n: int
    hop_latency: int
    link_names: Tuple[str, ...]
    link_latency: np.ndarray  # [L] int32
    hops: np.ndarray          # [N, N] int32
    base_lat: np.ndarray      # [N, N] int32 (0 on the diagonal)
    path_mat: np.ndarray      # [N, N, L] bool

    @property
    def num_links(self) -> int:
        return len(self.link_names)


class _Builder:
    """Accumulates directed links + routed paths into the dense form."""

    def __init__(self, name: str, n: int, hop_latency: int):
        self.name = name
        self.n = n
        self.hop_latency = hop_latency
        self._idx: Dict[str, int] = {}
        self._lat: List[int] = []

    def link(self, label: str, latency: int) -> int:
        if label not in self._idx:
            self._idx[label] = len(self._lat)
            self._lat.append(latency)
        return self._idx[label]

    def finish(self, paths: Dict[Tuple[int, int], List[int]]) -> Topology:
        n, L = self.n, len(self._lat)
        lat = np.asarray(self._lat, dtype=np.int32).reshape(L)
        hops = np.zeros((n, n), dtype=np.int32)
        base = np.zeros((n, n), dtype=np.int32)
        pmat = np.zeros((n, n, L), dtype=bool)
        for (s, d), links in paths.items():
            hops[s, d] = len(links)
            base[s, d] = int(sum(lat[l] for l in links))
            for l in links:
                pmat[s, d, l] = True
        names = tuple(
            sorted(self._idx, key=self._idx.__getitem__)
        )
        return Topology(
            name=self.name, n=n, hop_latency=self.hop_latency,
            link_names=names, link_latency=lat, hops=hops,
            base_lat=base, path_mat=pmat,
        )


def _grid_shape(n: int) -> Tuple[int, int]:
    """R x C with R the largest divisor of n not exceeding sqrt(n)."""
    r = 1
    for d in range(1, int(math.isqrt(n)) + 1):
        if n % d == 0:
            r = d
    return r, n // r


def _build_grid(name: str, n: int, hop: int, wrap: bool) -> Topology:
    rows, cols = _grid_shape(n)
    b = _Builder(name, n, hop)

    def step_link(u: int, v: int) -> int:
        return b.link(f"n{u}->n{v}", hop)

    def walk_axis(cur: int, tgt: int, size: int) -> List[int]:
        """Steps (+1/-1 in grid coordinates) from cur to tgt along one
        axis; torus takes the shorter way round, ties positive."""
        if cur == tgt:
            return []
        fwd = (tgt - cur) % size
        if wrap and fwd > size - fwd:
            return [-1] * (size - fwd)
        if not wrap and tgt < cur:
            return [-1] * (cur - tgt)
        return [+1] * (fwd if wrap else tgt - cur)

    # register every neighbor link (both directions) so link ids are
    # stable regardless of which paths happen to use them
    for r in range(rows):
        for c in range(cols):
            u = r * cols + c
            for dr, dc in ((0, 1), (0, -1), (1, 0), (-1, 0)):
                rr, cc = r + dr, c + dc
                if wrap:
                    rr, cc = rr % rows, cc % cols
                elif not (0 <= rr < rows and 0 <= cc < cols):
                    continue
                v = rr * cols + cc
                if v != u:
                    step_link(u, v)

    paths: Dict[Tuple[int, int], List[int]] = {}
    for s in range(n):
        r0, c0 = divmod(s, cols)
        for d in range(n):
            if s == d:
                continue
            r1, c1 = divmod(d, cols)
            links: List[int] = []
            r, c = r0, c0
            # XY dimension-ordered routing: columns first, then rows
            for dc in walk_axis(c, c1, cols):
                nc = (c + dc) % cols if wrap else c + dc
                links.append(step_link(r * cols + c, r * cols + nc))
                c = nc
            for dr in walk_axis(r, r1, rows):
                nr = (r + dr) % rows if wrap else r + dr
                links.append(step_link(r * cols + c, nr * cols + c))
                r = nr
            paths[(s, d)] = links
    return b.finish(paths)


def _build_hierarchical(n: int, hop: int) -> Topology:
    root = math.sqrt(n)
    groups = min(
        (d for d in range(1, n + 1) if n % d == 0),
        key=lambda d: (abs(d - root), -d),
    )
    m = n // groups
    b = _Builder("hierarchical", n, hop)
    dcn = DCN_LATENCY_FACTOR * hop
    for i in range(n):
        g = i // m
        b.link(f"n{i}->s{g}", hop)
        b.link(f"s{g}->n{i}", hop)
    for g in range(groups):
        for h in range(groups):
            if g != h:
                b.link(f"s{g}->s{h}", dcn)
    paths: Dict[Tuple[int, int], List[int]] = {}
    for s in range(n):
        g = s // m
        for d in range(n):
            if s == d:
                continue
            h = d // m
            links = [b.link(f"n{s}->s{g}", hop)]
            if g != h:
                links.append(b.link(f"s{g}->s{h}", dcn))
            links.append(b.link(f"s{h}->n{d}", hop))
            paths[(s, d)] = links
    return b.finish(paths)


@functools.lru_cache(maxsize=32)
def build_topology(name: str, n: int, hop_latency: int = 1) -> Topology:
    """Compile topology ``name`` for ``n`` nodes (cached: the tensors
    are baked into jitted programs, so identity matters for the jit
    caches keyed on config)."""
    if name not in TOPOLOGIES:
        raise ValueError(
            f"unknown topology {name!r}; registered: {TOPOLOGIES}"
        )
    if n < 1:
        raise ValueError("topology needs n >= 1")
    if hop_latency < 1:
        raise ValueError("hop_latency must be >= 1")
    if name == "ideal":
        return _Builder("ideal", n, hop_latency).finish({})
    if name == "mesh2d":
        return _build_grid("mesh2d", n, hop_latency, wrap=False)
    if name == "torus2d":
        return _build_grid("torus2d", n, hop_latency, wrap=True)
    return _build_hierarchical(n, hop_latency)
