"""ctypes binding to the native C++/OpenMP backend (native/).

pybind11 is not available in this environment, so the boundary is the
small C API in native/src/capi.cpp: run a trace directory (the engine
writes reference-format ``core_<n>_output.txt`` files) or a synthetic
benchmark.  Build with ``make -C native`` (done on demand here).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from hpa2_tpu.config import SystemConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libhpa2sim.so")
_BIN_PATH = os.path.join(_NATIVE_DIR, "build", "hpa2sim")


class Hpa2Result(ctypes.Structure):
    _fields_ = [
        ("instructions", ctypes.c_ulonglong),
        ("messages", ctypes.c_ulonglong),
        ("cycles", ctypes.c_ulonglong),
        ("seconds", ctypes.c_double),
        ("ok", ctypes.c_int),
        ("error", ctypes.c_char * 256),
    ]


class NativeError(RuntimeError):
    pass


def ensure_built(force: bool = False) -> str:
    """Build the native backend; returns the library path.

    make runs unconditionally (a no-op when timestamps are current):
    an existing .so built from older sources would otherwise be
    loaded across a C-ABI change and corrupt memory."""
    del force  # retained for API compatibility; make decides
    subprocess.run(
        ["make", "-C", _NATIVE_DIR], check=True, capture_output=True
    )
    return _LIB_PATH


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.hpa2_run_dir.restype = ctypes.c_int
        lib.hpa2_run_dir.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_ulonglong, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(Hpa2Result),
        ]
        lib.hpa2_bench_random.restype = ctypes.c_int
        lib.hpa2_bench_random.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_ulonglong, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(Hpa2Result),
        ]
        lib.hpa2_probe_transition.restype = ctypes.c_int
        lib.hpa2_probe_transition.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ]
        _lib = lib
    return _lib


def _check_config(config: SystemConfig) -> None:
    if config.num_procs > 64:
        raise NativeError(
            "native backend supports up to 64 nodes (single-word sharer "
            "mask); use the JAX backend beyond"
        )
    if config.messages_per_cycle != 1:
        raise NativeError(
            "the native backend drains one message per node per cycle "
            "(lockstep) / free-runs (omp); messages_per_cycle > 1 runs "
            "on the spec engine"
        )


def _sem_flags(config: SystemConfig) -> int:
    """Semantics bitmask for the C API (capi.cpp apply_sem_flags).
    Bit 0 is the historical 0/1 'robust' encoding, so the mask stays
    ABI-compatible with older callers/libraries."""
    sem = config.semantics
    return (
        (1 if sem.intervention_miss_policy == "nack" else 0)
        | (2 if sem.eager_write_request_memory else 0)
        | (4 if sem.flush_invack_fills_old_value else 0)
        | (8 if sem.overloaded_evict_shared_notify else 0)
    )


def run_trace_dir(
    config: SystemConfig,
    trace_dir: str,
    out_dir: str,
    mode: str = "lockstep",
    replay_path: Optional[str] = None,
    candidates: bool = False,
    final_dump: bool = False,
    max_cycles: int = 100_000_000,
    threads: int = 0,
    record_order_path: Optional[str] = None,
    msg_trace_path: Optional[str] = None,
) -> Hpa2Result:
    """Run the native engine on a trace directory.  Dump files are
    written to ``out_dir`` in the reference format.

    ``record_order_path`` writes the executed issue interleaving in
    DEBUG_INSTR format (assignment.c:596-597) — replayable on any
    lockstep engine (the record->replay->verify workflow that produced
    the reference's multi-run fixtures, SURVEY.md §4).
    ``msg_trace_path`` writes a per-message send/receive log in the
    reference's DEBUG_MSG format (assignment.c:170-174, 734-738)."""
    _check_config(config)
    lib = _load()
    res = Hpa2Result()
    rc = lib.hpa2_run_dir(
        trace_dir.encode(), out_dir.encode(),
        1 if mode == "omp" else 0,
        config.num_procs, config.cache_size, config.mem_size,
        config.msg_buffer_size, config.max_instr_num,
        _sem_flags(config),
        (replay_path or "").encode(), int(candidates), int(final_dump),
        max_cycles, threads, (record_order_path or "").encode(),
        (msg_trace_path or "").encode(),
        ctypes.byref(res),
    )
    if rc != 0 or not res.ok:
        raise NativeError(res.error.decode() or "native run failed")
    return res


def probe_transition(config: SystemConfig, probe_in) -> list:
    """Stage and run one transition on the native engine.

    ``probe_in`` is the packed 22-slot scenario built by
    ``hpa2_tpu.analysis.extract._native_packed``; the return value is
    the raw output block (8 header slots + 5 per emission) that
    ``extract.probe_native`` unpacks.  Used only by the static-analysis
    cross-backend equivalence pass."""
    _check_config(config)
    lib = _load()
    if len(probe_in) != 22:
        raise NativeError(f"probe input must be 22 slots, got {len(probe_in)}")
    in_arr = (ctypes.c_longlong * 22)(*probe_in)
    out_cap = 8 + 5 * 8
    out_arr = (ctypes.c_longlong * out_cap)()
    rc = lib.hpa2_probe_transition(
        config.num_procs, config.cache_size, config.mem_size,
        config.msg_buffer_size, _sem_flags(config),
        in_arr, out_arr, out_cap,
    )
    if rc != 0:
        raise NativeError(f"native probe failed (rc={rc})")
    return list(out_arr)


def bench_random(
    config: SystemConfig,
    instrs_per_core: int,
    seed: int = 0,
    mode: str = "omp",
    threads: int = 0,
) -> Hpa2Result:
    """Synthetic uniform-random benchmark; returns counters + wall time."""
    _check_config(config)
    lib = _load()
    res = Hpa2Result()
    rc = lib.hpa2_bench_random(
        1 if mode == "omp" else 0,
        config.num_procs, config.cache_size, config.mem_size,
        config.msg_buffer_size, instrs_per_core, seed,
        _sem_flags(config),
        threads, ctypes.byref(res),
    )
    if rc != 0 or not res.ok:
        raise NativeError(res.error.decode() or "native bench failed")
    return res
