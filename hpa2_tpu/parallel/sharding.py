"""Multi-chip execution: mesh construction, `SimState` partition specs,
and sharded run loops.

The reference scales by OpenMP threads inside one address space
(assignment.c:125, 135-137) and communicates through locked
shared-memory mailboxes (assignment.c:63-68, 711-739).  On TPU the two
scaling axes become mesh axes:

* ``data`` — the ensemble/batch axis: B independent simulated systems,
  embarrassingly parallel (the DP analog).  Sharding the leading batch
  axis with a ``NamedSharding`` is enough; XLA needs no collectives.
* ``node`` — the simulated-node axis *within* one system (the TP/SP
  analog): each device owns a contiguous block of nodes — their
  caches, directory slices, memory slices and mailboxes.  Cross-device
  message delivery is the *targeted* exchange of ``ops/exchange.py``:
  outgoing messages are bucketed by destination shard and moved in
  ``D-1`` ppermute rounds (plus a feedback round each), so ICI carries
  only the messages that actually cross shards — never a per-cycle
  ``all_gather`` of the whole candidate tensor.  Delivery order is
  arranged so the sharded engine is *bit-identical* to the single-chip
  engine.

Both axes compose: ``shard_map(vmap(step))`` over a 2-D
``Mesh(('data', 'node'))`` runs a sharded ensemble of sharded systems.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpa2_tpu import hostenv
from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import Instr
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.ops.engine import (
    JaxEngine, _node_dump_from, engine_stats, stack_states)
from hpa2_tpu.ops.pallas_engine import (
    PallasEngine, PallasLaneSession, choose_block)
from hpa2_tpu.ops.state import SimState, init_state
from hpa2_tpu.ops.step import (
    build_fast_forward, build_propose, build_step, quiescent)
from hpa2_tpu.utils.dump import NodeDump

# SimState fields whose leading (non-batch) axis is the node axis;
# everything else (cycle, counters, replay schedule, fault/watchdog
# bookkeeping) is replicated.
_NODE_LEADING = frozenset(
    f
    for f in SimState._fields
    if f not in ("order_node", "order_pos", "order_len",
                 "cycle", "n_instr", "n_msgs", "overflow",
                 "n_read_hits", "n_read_miss", "n_write_hits",
                 "n_write_miss", "n_evictions", "n_invalidations",
                 "msg_counts", "rng_key", "last_progress",
                 "n_retrans", "n_dup_filtered", "n_reorder_fixed",
                 "n_delays", "n_wire_stalls",
                 # interconnect fields lead with the link axis (or are
                 # scalar counters), never the node axis
                 "link_traversals", "link_max_load", "n_topo_delay",
                 "n_multicast_saved", "n_combined",
                 "n_elided", "n_multi_hit",
                 # protocol-variant scalar counters (dir_owner and
                 # snap_dir_owner ARE node-leading, so not listed)
                 "n_forwards", "n_owner_xfer", "n_dir_overflow",
                 # cross-shard exchange telemetry (replicated scalars)
                 "n_exch_sent", "n_exch_hwm", "n_exch_mc_saved",
                 "n_exch_combined")
)


def make_mesh(
    node_shards: int = 1,
    data_shards: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A ``(data, node)`` mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if node_shards < 1 or node_shards > len(devices):
        raise ValueError(
            f"node_shards={node_shards} outside 1..{len(devices)} "
            "available devices"
        )
    if data_shards is None:
        data_shards = len(devices) // node_shards
    need = data_shards * node_shards
    if need < 1:
        raise ValueError(
            f"empty mesh: data_shards={data_shards} x "
            f"node_shards={node_shards}"
        )
    if need > len(devices):
        raise ValueError(
            f"mesh {data_shards}x{node_shards} needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(data_shards, node_shards)
    return Mesh(grid, ("data", "node"))


def state_specs(
    batched: bool = False,
    node_axis: Optional[str] = "node",
    batch_axis: Optional[str] = "data",
) -> SimState:
    """PartitionSpecs for every SimState leaf.

    ``batched=True`` expects a leading ensemble axis on every leaf
    (from ``stack_states``) sharded over ``batch_axis``; the node axis
    (leading axis of per-system arrays) shards over ``node_axis``.
    """
    lead = (batch_axis,) if batched else ()
    specs = {}
    for f in SimState._fields:
        if f in _NODE_LEADING:
            specs[f] = P(*lead, node_axis)
        else:
            specs[f] = P(*lead)
    return SimState(**specs)


def _place(state: SimState, mesh: Mesh, specs: SimState) -> SimState:
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


def fetch_host_state(state: SimState) -> SimState:
    """Gather a (possibly sharded) device state tree onto the host as
    plain numpy — the barrier snapshot the recovery supervisor hands
    to ``save_state``.  Works for single-device, data-sharded and
    node-sharded layouts alike (``np.asarray`` forces the cross-shard
    gather)."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x), state)


@functools.lru_cache(maxsize=16)
def build_node_sharded_run(
    config: SystemConfig,
    mesh: Mesh,
    batched: bool,
    max_cycles: int = 1_000_000,
    watchdog_cycles: int = 0,
):
    """Jitted run-to-quiescence with the node axis sharded over the
    mesh's ``node`` axis (and, if ``batched``, the ensemble over
    ``data``).

    The ``lax.while_loop`` lives *outside* the ``shard_map``: the loop
    body is the manually-sharded SPMD step (the targeted exchange of
    ``ops/exchange.py`` on the ``config.exchange_mode`` collective
    schedule — one batched ``all_to_all`` each way by default, see
    ``exchange.plan_collectives`` — plus one stacked counter psum and
    one telemetry pmax per cycle, no per-cycle all_gather), while the
    quiescence condition is computed on the global view so XLA inserts
    the cross-device reductions itself.

    ``watchdog_cycles`` > 0 adds the stall watchdog to the loop
    condition exactly as in ops/step.py's ``build_run``: stop once no
    still-live system has made progress for that many cycles, so the
    host can raise a :class:`StallDiagnostic` instead of burning to
    ``max_cycles``.

    Cycle elision (ISSUE-12) composes with BOTH mesh axes (ISSUE-15):
    each shard reduces its own lanes'/nodes' proposals and one
    ``lax.pmin`` (over ``data``, plus ``node`` when the node axis is
    actually sharded) makes the jump the global minimum — exactly the
    unsharded jump, so dumps and per-lane cycle counters stay
    bit-identical to the single-device run.  Under node sharding the
    watchdog candidate in ``propose`` keys on each shard's *local*
    issuers, which can only shrink the jump (extra device steps, never
    an overshoot), so only ``n_elided`` may differ from the unsharded
    elided run — cycle counts, dumps, and every architectural stat
    stay exact.
    """
    node_shards = mesh.shape["node"]
    step = build_step(
        config, replay=False, axis_name="node", shards=node_shards
    )
    specs = state_specs(batched=batched)
    body = step
    if batched:
        body = jax.vmap(step)
    if config.elide:
        propose = build_propose(config, max_cycles, watchdog_cycles)
        ff = build_fast_forward(
            config, axis_name="node" if node_shards > 1 else None
        )
        lockstep = body
        # the jump must be the global minimum so every shard takes the
        # same branch (the predicate is replicated — required for the
        # collectives inside the cond branches)
        axes = "data" if node_shards == 1 else ("data", "node")
        if batched:
            vff = jax.vmap(ff, in_axes=(0, None))
            vprop = jax.vmap(propose)

            def body(st):
                j = jax.lax.pmin(jnp.min(vprop(st)), axes)
                return jax.lax.cond(
                    j > 0, lambda s: vff(s, j), lockstep, st
                )

        else:

            def body(st):
                j = jax.lax.pmin(jnp.min(propose(st)), axes)
                return jax.lax.cond(
                    j > 0, lambda s: ff(s, j), lockstep, st
                )

    wrapped = hostenv.shard_map(
        body,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=specs,
        check_replication=False,
    )

    if batched:
        vq = jax.vmap(quiescent)

        def cond(st):
            live = ~vq(st)
            go = (
                jnp.any(live)
                & jnp.all(st.cycle < max_cycles)
                & ~jnp.any(st.overflow)
            )
            if watchdog_cycles:
                fresh = (st.cycle - st.last_progress) < watchdog_cycles
                go = go & jnp.any(live & fresh)
            return go

    else:

        def cond(st):
            go = (
                (~quiescent(st))
                & (st.cycle < max_cycles)
                & (~st.overflow)
            )
            if watchdog_cycles:
                go = go & (
                    (st.cycle - st.last_progress) < watchdog_cycles
                )
            return go

    def run(st: SimState) -> SimState:
        return jax.lax.while_loop(cond, wrapped, st)

    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs
    )
    return jax.jit(run, in_shardings=(shardings,), out_shardings=shardings)


class NodeShardedEngine:
    """One large system with its node axis sharded across devices.

    The scaling analog of the reference's thread-per-node OpenMP region
    (assignment.c:135-137) when one chip is not enough nodes: each
    device simulates ``num_procs / node_shards`` nodes; mailbox traffic
    crosses ICI through the targeted per-destination exchange
    (``ops/exchange.py``).  Dump readback and quiescence semantics
    match :class:`JaxEngine` exactly.
    """

    def __init__(
        self,
        config: SystemConfig,
        traces: Sequence[Sequence[Instr]],
        mesh: Optional[Mesh] = None,
        max_cycles: int = 1_000_000,
        watchdog_cycles: int = 10_000,
    ):
        if mesh is None:
            mesh = make_mesh(node_shards=len(jax.devices()))
        if config.interconnect.enabled:
            raise ValueError(
                "non-ideal interconnect topologies run single-shard "
                "only; node sharding composes with topology='ideal'"
            )
        if config.num_procs % mesh.shape["node"] != 0:
            raise ValueError(
                f"num_procs={config.num_procs} not divisible by node "
                f"shards={mesh.shape['node']}"
            )
        self.config = config
        self.mesh = mesh
        self.max_cycles = max_cycles
        self.watchdog_cycles = watchdog_cycles
        self._specs = state_specs(batched=False)
        self.state = _place(init_state(config, traces), mesh, self._specs)
        self._run = build_node_sharded_run(
            config, mesh, batched=False, max_cycles=max_cycles,
            watchdog_cycles=watchdog_cycles,
        )

    def run(self) -> "NodeShardedEngine":
        st = self._run(self.state)
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        self.state = st
        if bool(st.overflow):
            raise StallError("internal invariant violated: mailbox overflow despite backpressure")
        if not bool(quiescent(st)):
            cycle = int(st.cycle)
            stalled_for = cycle - int(st.last_progress)
            if (
                self.watchdog_cycles
                and cycle < self.max_cycles
                and stalled_for >= self.watchdog_cycles
            ):
                # same diagnostic (and trip cycle) as the single-chip
                # engine: the watchdog counts simulated cycles, which
                # sharding and elision both preserve exactly
                from hpa2_tpu.ops.engine import stall_diagnostic

                raise stall_diagnostic(
                    self.config, st,
                    "watchdog: no instruction retired and no mailbox "
                    f"drained for {stalled_for} cycles",
                )
            raise StallError(
                f"no quiescence after {cycle} cycles (livelock?)"
            )
        return self

    def snapshots(self) -> List[NodeDump]:
        arrs = JaxEngine._snap_arrays(self.state)
        return [
            _node_dump_from(arrs, i) for i in range(self.config.num_procs)
        ]

    def final_dumps(self) -> List[NodeDump]:
        arrs = JaxEngine._live_arrays(self.state)
        return [
            _node_dump_from(arrs, i) for i in range(self.config.num_procs)
        ]

    @property
    def cycle(self) -> int:
        return int(self.state.cycle)

    @property
    def instructions(self) -> int:
        return int(self.state.n_instr)

    @property
    def messages(self) -> int:
        return int(self.state.n_msgs)

    def stats(self) -> dict:
        out = engine_stats(self.state)
        sent = int(np.asarray(self.state.n_exch_sent))
        if sent:
            # ICI traffic model: every shipped exchange entry is one
            # [10 + sharer_words + 1]-row i32 column (ops/step.py
            # payload + combining key)
            rows = 10 + self.config.sharer_words + 1
            out["exchange_bytes_per_cycle"] = round(
                sent * rows * 4 / max(self.cycle, 1), 2
            )
        return out


class GridEngine:
    """A sharded ensemble of (optionally) sharded systems: the full 2-D
    ``(data, node)`` mesh — DP x model-parallel in one jitted loop."""

    def __init__(
        self,
        config: SystemConfig,
        batch_traces: Sequence[Sequence[Sequence[Instr]]],
        mesh: Optional[Mesh] = None,
        max_cycles: int = 1_000_000,
    ):
        if mesh is None:
            mesh = make_mesh(node_shards=1)
        if config.interconnect.enabled:
            raise ValueError(
                "non-ideal interconnect topologies run single-shard "
                "only; the grid engine composes with topology='ideal'"
            )
        b = len(batch_traces)
        if b % mesh.shape["data"] != 0:
            raise ValueError(
                f"batch {b} not divisible by data shards "
                f"{mesh.shape['data']}"
            )
        if config.num_procs % mesh.shape["node"] != 0:
            raise ValueError(
                f"num_procs={config.num_procs} not divisible by node "
                f"shards={mesh.shape['node']}"
            )
        self.config = config
        self.mesh = mesh
        max_t = max(
            (len(tr) for traces in batch_traces for tr in traces), default=1
        )
        self._specs = state_specs(batched=True)
        state = stack_states(
            [init_state(config, t, max_trace_len=max_t) for t in batch_traces]
        )
        self.state = _place(state, mesh, self._specs)
        self._run = build_node_sharded_run(
            config, mesh, batched=True, max_cycles=max_cycles
        )

    def run(self) -> "GridEngine":
        st = self._run(self.state)
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        self.state = st
        if bool(jnp.any(st.overflow)):
            raise StallError("internal invariant violated: mailbox overflow despite backpressure")
        if not bool(jnp.all(jax.vmap(quiescent)(st))):
            raise StallError("batch did not reach quiescence (livelock?)")
        return self

    def system_snapshots(self, b: int) -> List[NodeDump]:
        st_b = jax.tree_util.tree_map(lambda x: np.asarray(x)[b], self.state)
        arrs = JaxEngine._snap_arrays(st_b)
        return [
            _node_dump_from(arrs, i) for i in range(self.config.num_procs)
        ]

    @property
    def instructions(self) -> int:
        return int(jnp.sum(self.state.n_instr))


# ---------------------------------------------------------------------------
# Data-parallel Pallas: the ensemble (lane) axis sharded over a 1-D
# ``data`` mesh.  Unlike SimState (leading batch axis), the Pallas
# layout keeps the ensemble LAST (TPU vector lanes), so every
# PartitionSpec here shards the trailing axis.  Shards are fully
# independent systems: each device runs its own block grid, HBM window
# prefetch, and while-to-quiescence loop with ZERO cross-shard
# collectives in the per-cycle hot loop; the only cross-shard op of a
# whole run is the final OR-reduce of the per-shard status words
# (tests/test_data_sharded_pallas.py pins both properties).
# ---------------------------------------------------------------------------


def make_data_mesh(
    data_shards: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A 1-D ``('data',)`` mesh for lane-axis ensemble sharding
    (the Pallas engine has no node axis to shard — nodes live in
    sublanes)."""
    devices = list(devices if devices is not None else jax.devices())
    if data_shards is None:
        data_shards = len(devices)
    if data_shards < 1 or data_shards > len(devices):
        raise ValueError(
            f"data_shards={data_shards} outside 1..{len(devices)} "
            "available devices"
        )
    return Mesh(np.array(devices[:data_shards]), ("data",))


def _lane_spec(ndim: int) -> P:
    """Shard the trailing (lane/ensemble) axis over ``data``."""
    return P(*([None] * (ndim - 1)), "data")


@functools.lru_cache(maxsize=16)
def build_data_sharded_pallas_run(
    config: SystemConfig,
    shard_b: int,
    bb: int,
    k: int,
    interpret: bool,
    snapshots: bool,
    window: int,
    n_seg: int,
    max_calls: int,
    mesh: Mesh,
    stream: bool = True,
    ablate: frozenset = frozenset(),
    gate: bool = True,
    packed: bool = False,
):
    """The whole-run Pallas program of ``pallas_engine._build_stream_run``
    (or the legacy ``_build_run``) built at the per-shard lane count and
    wrapped in ``hostenv.shard_map``: every device drives its own
    ``shard_b``-lane run loop end to end.  The carried state is donated
    through the jit boundary (TPU only; CPU has no donation), so HBM
    state/trace planes are reused across trace segments and runs
    instead of reallocated."""
    from hpa2_tpu.ops import pallas_engine as pe

    build = pe._build_stream_run if stream else pe._build_run
    per_shard = build(
        config, shard_b, bb, k, interpret, snapshots, window, n_seg,
        max_calls, ablate, gate, packed,
    )
    shapes = pe.state_shapes(config, snapshots, packed)
    state_sp = {f: _lane_spec(len(sh) + 1) for f, sh in shapes.items()}

    def shard_body(state, tr, tr_len):
        st, status = per_shard(state, tr, tr_len)
        return st, status[None]  # one status lane per shard

    wrapped = hostenv.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(state_sp, P(None, None, "data"), P(None, "data")),
        out_specs=(state_sp, P("data")),
        check_replication=False,
    )

    def run_all(state, tr, tr_len):
        state, statuses = wrapped(state, tr, tr_len)
        # the run's ONLY cross-shard communication: OR-reduce the
        # per-shard stalled/overflow bits once, after every shard has
        # finished its independent quiescence loop
        stalled = jnp.any((statuses & 1) != 0)
        overflow = jnp.any((statuses & 2) != 0)
        return state, (
            stalled.astype(jnp.int32) | (overflow.astype(jnp.int32) << 1)
        )

    donate = () if interpret else (0,)
    return jax.jit(run_all, donate_argnums=donate)


@functools.lru_cache(maxsize=16)
def build_fused_sharded_pallas_run(
    config: SystemConfig,
    r_shard: int,
    bsys_shard: int,
    bb: int,
    k: int,
    interpret: bool,
    window: int,
    nseg_max: int,
    max_calls: int,
    mesh: Mesh,
    stream: bool = True,
    ablate: frozenset = frozenset(),
    gate: bool = True,
    packed: bool = False,
):
    """The fused scheduled run (``pallas_engine._make_fused_run``)
    built at per-shard lane/system counts and wrapped in
    ``hostenv.shard_map``: each device scans the whole plan over ITS
    contiguous lane group.  The scheduler's groups are shard-local
    (block-diagonal permutations, group-local admission queues), so
    the caller hands each shard its slice of the plan rows — localized
    to the shard frame by ``DataShardedPallasEngine._fused_plan_arrays``
    — and lanes never migrate across devices.  The sole cross-shard op
    stays the final status OR-reduce."""
    from hpa2_tpu.ops import pallas_engine as pe

    per_shard = pe._make_fused_run(
        config, r_shard, bsys_shard, bb, k, interpret, window, nseg_max,
        max_calls, ablate, gate, stream, packed,
    )
    shapes = pe.state_shapes(config, snapshots=False, packed=packed)
    state_sp = {f: _lane_spec(len(sh) + 1) for f, sh in shapes.items()}
    plan_sp = P(None, "data")

    def shard_body(state, tr, tr_len, sys, seg, perm, reset):
        st, status = per_shard(state, tr, tr_len, sys, seg, perm, reset)
        return st, status[None]  # one status lane per shard

    wrapped = hostenv.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            state_sp, P(None, None, "data"), P(None, "data"),
            plan_sp, plan_sp, plan_sp, plan_sp,
        ),
        out_specs=(state_sp, P("data")),
        check_replication=False,
    )

    def run_all(state, tr, tr_len, sys, seg, perm, reset):
        state, statuses = wrapped(
            state, tr, tr_len, sys, seg, perm, reset
        )
        stalled = jnp.any((statuses & 1) != 0)
        overflow = jnp.any((statuses & 2) != 0)
        return state, (
            stalled.astype(jnp.int32) | (overflow.astype(jnp.int32) << 1)
        )

    donate = () if interpret else (0,)
    return jax.jit(run_all, donate_argnums=donate)


class DataShardedPallasEngine(PallasEngine):
    """The Pallas fast path, data-parallel over the local devices.

    An ensemble of B systems splits into ``data_shards`` equal lane
    groups, one per device; each shard runs the full streamed kernel
    (block grid, HBM prefetch, quiescence loop) independently, so
    throughput scales with the device count while staying bit-exact
    with the single-device :class:`PallasEngine` — same dumps, cycle
    counts, and stall semantics (the per-shard status bits OR into the
    same stalled/overflow word).  Construction, ``run()``, and all
    readback accessors are inherited; only operand placement and the
    runner differ.
    """

    def __init__(
        self,
        config: SystemConfig,
        tr_op: np.ndarray,
        tr_addr: np.ndarray,
        tr_val: np.ndarray,
        tr_len: np.ndarray,
        data_shards: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        block: int = 1024,
        **kwargs,
    ):
        if mesh is None:
            mesh = make_data_mesh(data_shards)
        if tuple(mesh.axis_names) != ("data",):
            raise ValueError(
                f"need a 1-D ('data',) mesh, got axes {mesh.axis_names}"
            )
        shards = mesh.shape["data"]
        b = tr_op.shape[0]
        if b % shards != 0:
            raise ValueError(
                f"batch {b} not divisible by data_shards={shards}"
            )
        shard_b = b // shards
        # the per-shard grid tiles shard_b lanes, so the block must
        # divide the SHARD lane count (any divisor of it divides b,
        # so the base class keeps the choice).  Under the occupancy
        # scheduler the device carries `resident` lanes instead, split
        # the same way.
        sched = kwargs.get("schedule")
        if sched is not None:
            resident = sched.resident or b
            if resident % shards:
                raise ValueError(
                    f"schedule.resident={resident} not divisible by "
                    f"data_shards={shards}"
                )
            block = choose_block(resident // shards, block)
        else:
            block = choose_block(shard_b, block)
        super().__init__(
            config, tr_op, tr_addr, tr_val, tr_len, block=block, **kwargs
        )
        self.mesh = mesh
        self.data_shards = shards
        self._shard_b = shard_b
        # shard-local scheduling: each shard is one group with its own
        # admission queue; compaction permutations are block-diagonal
        # over groups, so lanes never migrate across devices
        self._sched_groups = shards

        def put(x):
            return jax.device_put(
                x, NamedSharding(mesh, _lane_spec(x.ndim))
            )

        self.state = {f: put(v) for f, v in self.state.items()}
        self._tr_full = put(self._tr_full)
        self._tr_len_full = put(self._tr_len_full)

    def _runner(self, max_cycles: int):
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        return build_data_sharded_pallas_run(
            self.config, self._shard_b, self.block, self.cycles_per_call,
            self._interpret, self._snapshots, self._window, self._n_seg,
            max_calls, self.mesh, self._stream, self._ablate, self._gate,
            self._packed,
        )

    def _interval_runner(self, max_cycles: int):
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        return build_data_sharded_pallas_run(
            self.config, self._resident // self.data_shards, self.block,
            self.cycles_per_call, self._interpret, False, self._window,
            1, max_calls, self.mesh, self._stream, self._ablate,
            self._gate, self._packed,
        )

    def _fused_runner(self, max_cycles: int):
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        return build_fused_sharded_pallas_run(
            self.config, self._resident // self.data_shards,
            self._shard_b, self.block, self.cycles_per_call,
            self._interpret, self._window, self._n_seg, max_calls,
            self.mesh, self._stream, self._ablate, self._gate,
            self._packed,
        )

    def _fused_plan_arrays(self, plan):
        # Rebase the plan rows into each shard's local frame.  Groups
        # are shard-local (one per device, `_sched_groups = shards`),
        # so lane l belongs to group g = l // gl and its system /
        # permutation indices all live inside that group's slice:
        # system ids in [g*gs, (g+1)*gs), permutation targets in
        # [g*gl, (g+1)*gl) (block-diagonal by construction).  The
        # P(None, "data") sharding then hands shard g exactly its
        # contiguous gl columns, already 0-based.
        shards = self.data_shards
        gl = self._resident // shards
        gs = self.b // shards
        g = np.arange(self._resident, dtype=np.int64) // gl
        sys_l = np.where(plan.sys >= 0, plan.sys - g[None, :] * gs, -1)
        perm_l = plan.perm - g[None, :] * gl
        return (
            jnp.asarray(sys_l.astype(np.int32)),
            jnp.asarray(plan.seg),
            jnp.asarray(perm_l.astype(np.int32)),
            jnp.asarray(plan.reset),
        )

    def _sched_put(self, x):
        return jax.device_put(
            x, NamedSharding(self.mesh, _lane_spec(x.ndim))
        )


class DataShardedLaneSession(PallasLaneSession):
    """The resident-lane serving session, data-parallel over the local
    devices: each shard runs its own interval program over a contiguous
    lane group (the serving scheduler is built with ``groups=shards``,
    so barrier permutations stay block-diagonal and lanes never migrate
    across devices).  Same serving protocol as the base session; only
    operand placement and the runner differ, exactly mirroring
    :class:`DataShardedPallasEngine` vs :class:`PallasEngine`."""

    def __init__(
        self,
        config,
        resident: int,
        window: int,
        *,
        data_shards: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        block: int = 1024,
        **kwargs,
    ):
        if mesh is None:
            mesh = make_data_mesh(data_shards)
        if tuple(mesh.axis_names) != ("data",):
            raise ValueError(
                f"need a 1-D ('data',) mesh, got axes {mesh.axis_names}"
            )
        shards = mesh.shape["data"]
        if resident % shards:
            raise ValueError(
                f"resident={resident} not divisible by "
                f"data_shards={shards}"
            )
        self.mesh = mesh
        self.data_shards = shards
        block = choose_block(resident // shards, block)
        super().__init__(
            config, resident, window, block=block, **kwargs
        )

    def _build_runner(self):
        max_calls = max(1, -(-self.max_cycles // self.cycles_per_call))
        return build_data_sharded_pallas_run(
            self.config, self.r // self.data_shards, self.block,
            self.cycles_per_call, self._interpret, False, self.window,
            1, max_calls, self.mesh, self._stream, frozenset(),
            self._gate, self._packed,
        )

    def _put(self, x):
        return jax.device_put(
            x, NamedSharding(self.mesh, _lane_spec(x.ndim))
        )

    def _donate_barrier(self) -> bool:
        # the barrier output is re-placed onto the mesh anyway; skip
        # donation so XLA never has to reconcile donated layouts with
        # the resharding device_put
        return False


# ---------------------------------------------------------------------------
# Node-axis sharding for the Pallas fast path: one giant system (or an
# ensemble of them) split into contiguous node blocks over the mesh's
# ``node`` axis, composing with ``data`` lane sharding on the same 2-D
# mesh.  Collectives cannot run inside a Mosaic kernel, so this path
# runs ``build_cycle`` at the XLA level under ``shard_map``: phase C is
# the targeted exchange of ``ops/exchange.py`` on the
# ``config.exchange_mode`` collective schedule (one batched
# ``all_to_all`` each way by default; see
# ``exchange.plan_collectives``) plus ONE stacked counter psum and ONE
# telemetry pmax per cycle, no per-cycle all_gather
# (tests/test_node_sharded_pallas.py pins the counts) — and
# quiescence rides the psum'd ``activeg`` row for free.
# ---------------------------------------------------------------------------

# transient [1, lanes] rows threaded through the node-sharded cycle in
# the state dict (never part of pallas_engine.state_shapes): psum'd
# global activity (the quiescence gate), cumulative cross-shard
# messages, sticky exchange-overflow flag, and the ISSUE-15 exchange
# telemetry — slot high-water mark (running max), multicast/combining
# savings (accumulators), and the packed worst-overflow diagnostic
# words (demand<<16|src<<8|dst and demand<<16|cycle, running max)
_PALLAS_TRANSIENTS = (
    "activeg", "xmsgs", "exchov",
    "exchhw", "exchmc", "exchcb", "exchdg", "exchdc",
)


def _pallas_exchange_stats(config: SystemConfig, state: dict) -> dict:
    """The ISSUE-15 exchange-telemetry block from the transient rows,
    same only-when-nonzero keys as ``engine_stats`` on the jax path.
    Every shipped entry is one [W + SW + 3]-row i32 column (candidate
    words + INV fan-mask words + recv/isa/ckey)."""
    from hpa2_tpu.ops.pallas_engine import (
        _SC_CYCLE, _mb_layout, _sharer_words,
    )

    out = {}
    sent = int(np.sum(np.asarray(state["xmsgs"])))
    if sent:
        out["exchange_sent"] = sent
        rows = _mb_layout(config)[1] + _sharer_words(config) + 3
        cyc = int(np.max(np.asarray(state["scalars"])[_SC_CYCLE]))
        out["exchange_bytes_per_cycle"] = round(
            sent * rows * 4 / max(cyc, 1), 2
        )
    hwm = int(np.max(np.asarray(state["exchhw"])))
    if hwm:
        out["exchange_slot_hwm"] = hwm
    mc = int(np.sum(np.asarray(state["exchmc"])))
    if mc:
        out["exchange_multicast_saved"] = mc
    cb = int(np.sum(np.asarray(state["exchcb"])))
    if cb:
        out["exchange_combined"] = cb
    return out


def _exchange_overflow_error(state: dict, exchange_slots) -> StallError:
    """Decode the pmax'd worst-overflow diagnostic words into a LOUD,
    actionable message naming the cycle, the shard pair, and demand vs
    capacity (both words lead with the demand in the top 16 bits, so
    the two maxima describe the same event)."""
    dg = int(np.max(np.asarray(state["exchdg"])))
    dc = int(np.max(np.asarray(state["exchdc"])))
    detail = ""
    if dg > 0:
        demand = dg >> 16
        more = "+" if demand >= 0xFFFF else ""
        detail = (
            f" — worst cycle {dc & 0xFFFF}: shard "
            f"{(dg >> 8) & 0xFF} -> {dg & 0xFF} demanded "
            f"{demand}{more} slots"
        )
    return StallError(
        "cross-shard exchange overflow: a cycle had more out-bound "
        "candidates for one peer shard than "
        f"exchange_slots={exchange_slots}; raise it (the "
        f"capacity-exact default never overflows){detail}"
    )


def _node_plane_spec(key: str, ndim: int) -> P:
    """Spec for one Pallas state plane on the 2-D (data, node) mesh:
    node-leading planes split their leading axis over ``node``; the
    replicated planes (scalars, msg_counts, transients) only shard the
    trailing lane axis over ``data``."""
    if key in ("scalars", "msg_counts") or key in _PALLAS_TRANSIENTS:
        return P(*([None] * (ndim - 1)), "data")
    return P("node", *([None] * (ndim - 2)), "data")


def _make_node_pallas_interval(
    config: SystemConfig,
    bb: int,
    snapshots: bool,
    window: int,
    n_seg: int,
    max_calls: int,
    k: int,
    node_shards: int,
    exchange_slots: Optional[int],
    packed: bool,
):
    """The per-shard (state, tr_full, tr_len_full) -> (state, status)
    interval program — ``pallas_engine._make_run.run_all`` rebuilt at
    the XLA level around the node-sharded cycle.  ``state`` carries the
    ``_PALLAS_TRANSIENTS`` rows; quiescence is ``any(activeg > 0)``
    (the previous cycle's stacked psum), seeded once per trace window
    by a single psum OUTSIDE the cycle loop.  Overshoot cycles on a
    quiescent state are value-no-ops (and ``_SC_CYCLE`` only accrues
    while active), so checking every ``k``-cycle granule keeps results
    bit-identical to the single-chip engine."""
    from hpa2_tpu.ops import pallas_engine as pe

    cycle = pe.build_cycle(
        config, bb, snapshots, frozenset(), packed, "node", node_shards,
        exchange_slots,
    )
    slsc = pe._scalar_layout(config, window)

    def local_activity(st, tl):
        nswv = st["nsw"]
        pc = (nswv >> slsc["off_pc"]) & slsc["pc_mask"]
        waiting = (nswv >> slsc["off_wait"]) & 1
        cnt = nswv & slsc["count_mask"]
        dv = pe.deferred_valid(config, st)
        return (
            jnp.sum(jnp.maximum(tl - pc, 0), axis=0, keepdims=True)
            + jnp.sum(waiting, axis=0, keepdims=True)
            + jnp.sum(cnt, axis=0, keepdims=True)
            + jnp.sum(dv.astype(jnp.int32), axis=(0, 1))[None, :]
        )

    def run_all(state, tr_full, tr_len_full):
        def seg_body(si, carry):
            st, stalled, calls0 = carry
            tr_seg = jax.lax.dynamic_slice_in_dim(
                tr_full, si * window, window, axis=1
            )
            tl_seg = jnp.clip(tr_len_full - si * window, 0, window)
            st = {
                **st,
                "nsw": st["nsw"]
                & ~(slsc["pc_mask"] << slsc["off_pc"]),
            }
            st["activeg"] = jax.lax.psum(
                local_activity(st, tl_seg), "node"
            )

            # The quiescence gate must be uniform across the WHOLE mesh,
            # not just the node axis: the exchange ppermutes inside the
            # cycle are single program-wide collectives, so every device
            # has to take the same number of while iterations even
            # though each data row carries different systems.  One tiny
            # pmax over "data" per k-cycle call (outside the cycle
            # loop) makes the carried gate replicated; overshoot calls
            # on an already-quiescent data row are value-no-ops.
            def live(s2):
                return (
                    jax.lax.pmax(
                        jnp.any(s2["activeg"] > 0).astype(jnp.int32),
                        "data",
                    )
                    > 0
                )

            def cond(c):
                s2, calls, go = c
                return go & (calls < max_calls)

            def body(c):
                s2, calls, _ = c
                full = {**s2, "tr": tr_seg, "tr_len": tl_seg}
                full = jax.lax.fori_loop(
                    0, k, lambda i, x: cycle(x), full
                )
                s2n = {f: full[f] for f in s2}
                return s2n, calls + 1, live(s2n)

            st, calls1, _ = jax.lax.while_loop(
                cond, body, (st, calls0, live(st))
            )
            stalled = stalled | jnp.any(st["activeg"] > 0)
            return st, stalled, calls1

        state, stalled, _ = jax.lax.fori_loop(
            0, n_seg, seg_body,
            (dict(state), jnp.bool_(False), jnp.int32(0)),
        )
        overflow = jnp.any(state["scalars"][pe._SC_OVERFLOW] > 0)
        exch = jnp.any(state["exchov"] > 0)
        status = (
            stalled.astype(jnp.int32)
            | (overflow.astype(jnp.int32) << 1)
            | (exch.astype(jnp.int32) << 2)
        )
        return state, status

    return run_all


@functools.lru_cache(maxsize=16)
def build_node_sharded_pallas_run(
    config: SystemConfig,
    shard_b: int,
    snapshots: bool,
    window: int,
    n_seg: int,
    max_calls: int,
    k: int,
    mesh: Mesh,
    exchange_slots: Optional[int] = None,
    packed: bool = False,
    interpret: bool = False,
):
    """The node-sharded whole-run program: the XLA interval body under
    ``shard_map`` over the 2-D (data, node) mesh, while/fori loops per
    shard (iteration counts agree across shards — the gate is the
    replicated psum'd ``activeg``), one status word out."""
    from hpa2_tpu.ops import pallas_engine as pe

    node_shards = mesh.shape["node"]
    run = _make_node_pallas_interval(
        config, shard_b, snapshots, window, n_seg, max_calls, k,
        node_shards, exchange_slots, packed,
    )
    shapes = pe.state_shapes(config, snapshots, packed)
    state_sp = {
        f: _node_plane_spec(f, len(sh) + 1) for f, sh in shapes.items()
    }
    for f in _PALLAS_TRANSIENTS:
        state_sp[f] = P(None, "data")

    def shard_body(state, tr, tr_len):
        st, status = run(state, tr, tr_len)
        return st, status[None]  # one status lane per data shard

    wrapped = hostenv.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(state_sp, P("node", None, "data"), P("node", "data")),
        out_specs=(state_sp, P("data")),
        check_replication=False,
    )

    def run_all(state, tr, tr_len):
        state, statuses = wrapped(state, tr, tr_len)
        stalled = jnp.any((statuses & 1) != 0)
        overflow = jnp.any((statuses & 2) != 0)
        exch = jnp.any((statuses & 4) != 0)
        return state, (
            stalled.astype(jnp.int32)
            | (overflow.astype(jnp.int32) << 1)
            | (exch.astype(jnp.int32) << 2)
        )

    donate = () if interpret else (0,)
    return jax.jit(run_all, donate_argnums=donate)


@functools.lru_cache(maxsize=16)
def build_node_fused_pallas_run(
    config: SystemConfig,
    r_shard: int,
    bsys_shard: int,
    k: int,
    window: int,
    nseg_max: int,
    max_calls: int,
    mesh: Mesh,
    exchange_slots: Optional[int] = None,
    packed: bool = False,
    interpret: bool = False,
):
    """The fused scheduled run for the node-sharded path — the exact
    scan/barrier structure of ``pallas_engine._make_fused_run`` rebuilt
    around the node-sharded XLA interval body.  Differences forced by
    the geometry: the admission-reset init is the INITIAL STATE OPERAND
    (its memory plane differs per node row, so the host-side
    ``_init_state`` closure of the single-chip builder — built at
    global ``num_procs`` — cannot be captured per shard), and the
    transient rows ride the scan carry untouched by the barrier
    (``activeg`` is reseeded per interval; ``xmsgs``/``exchov`` are
    whole-run accumulators, permutation-invariant under readback)."""
    from hpa2_tpu.ops import pallas_engine as pe

    node_shards = mesh.shape["node"]
    raw = _make_node_pallas_interval(
        config, r_shard, False, window, 1, max_calls, k, node_shards,
        exchange_slots, packed,
    )
    shapes = pe.state_shapes(config, snapshots=False, packed=packed)
    dtypes = pe.state_dtypes(config, snapshots=False, packed=packed)
    fields = tuple(shapes)
    nl = config.num_procs // node_shards

    def loc_shape(f):
        sh = tuple(shapes[f])
        if f in ("scalars", "msg_counts"):
            return sh
        return (sh[0] // node_shards,) + sh[1:]

    def shard_fused(state, tr_full, tr_len_full, sys, seg, perm, reset):
        init = {f: state[f] for f in fields}  # t=0 state IS the init
        trans = {
            f: jnp.zeros((1, r_shard), jnp.int32)
            for f in _PALLAS_TRANSIENTS
        }
        trf = jnp.transpose(
            tr_full.reshape(nl, nseg_max, window, bsys_shard),
            (1, 3, 0, 2),
        ).reshape(nseg_max * bsys_shard, nl, window)
        store = {
            f: jnp.zeros(loc_shape(f) + (bsys_shard + 1,), dtypes[f])
            for f in fields
        }

        def step(carry, xs):
            st, tr_c, acc, status = carry
            sys_i, seg_i, perm_i, reset_i = xs
            st = {
                f: jnp.where(
                    reset_i != 0, init[f], jnp.take(v, perm_i, axis=-1)
                )
                for f, v in st.items()
            }
            sysc = jnp.clip(sys_i, 0, bsys_shard - 1)
            gidx = jnp.clip(seg_i, 0, nseg_max - 1) * bsys_shard + sysc
            tr_i = jnp.transpose(trf[gidx], (1, 2, 0))
            tl_i = jnp.where(
                sys_i >= 0,
                jnp.clip(
                    tr_len_full[:, sysc] - seg_i[None, :] * window,
                    0, window,
                ),
                0,
            )
            full, s_int = raw({**st, **tr_c}, tr_i, tl_i)
            st = {f: full[f] for f in fields}
            tr_c = {f: full[f] for f in _PALLAS_TRANSIENTS}
            tgt = jnp.where(sys_i >= 0, sys_i, bsys_shard)
            acc = {f: acc[f].at[..., tgt].set(st[f]) for f in fields}
            return (st, tr_c, acc, status | s_int), None

        (st, trans, store, status), _ = jax.lax.scan(
            step, ({f: state[f] for f in fields}, trans, store,
                   jnp.int32(0)),
            (sys, seg, perm, reset),
        )
        out = {f: store[f][..., :bsys_shard] for f in fields}
        out.update(trans)
        return out, status[None]

    state_sp = {
        f: _node_plane_spec(f, len(sh) + 1) for f, sh in shapes.items()
    }
    out_sp = dict(state_sp)
    for f in _PALLAS_TRANSIENTS:
        out_sp[f] = P(None, "data")
    plan_sp = P(None, "data")

    wrapped = hostenv.shard_map(
        shard_fused,
        mesh=mesh,
        in_specs=(
            state_sp, P("node", None, "data"), P("node", "data"),
            plan_sp, plan_sp, plan_sp, plan_sp,
        ),
        out_specs=(out_sp, P("data")),
        check_replication=False,
    )

    def run_all(state, tr, tr_len, sys, seg, perm, reset):
        state, statuses = wrapped(state, tr, tr_len, sys, seg, perm,
                                  reset)
        stalled = jnp.any((statuses & 1) != 0)
        overflow = jnp.any((statuses & 2) != 0)
        exch = jnp.any((statuses & 4) != 0)
        return state, (
            stalled.astype(jnp.int32)
            | (overflow.astype(jnp.int32) << 1)
            | (exch.astype(jnp.int32) << 2)
        )

    donate = () if interpret else (0,)
    return jax.jit(run_all, donate_argnums=donate)


class NodeShardedPallasEngine(PallasEngine):
    """The Pallas fast path with the NODE axis sharded over a device
    mesh: one giant system (or a lane-sharded ensemble of them — 2-D
    ``data x node`` mesh) whose per-node planes split into contiguous
    node blocks, one block per device.

    Phase C's cross-shard message delivery is the targeted exchange of
    ``ops/exchange.py`` — ICI traffic proportional to the candidates
    that actually cross shards (bounded by ``exchange_slots``), never a
    per-cycle ``all_gather`` of the world.  The cycle program is the
    same ``build_cycle`` body, built in sharded mode and run at the XLA
    level under ``shard_map`` (collectives cannot live inside a Mosaic
    kernel); results — dumps, snapshots, counters, stall semantics —
    stay bit-identical to the single-device :class:`PallasEngine`,
    including under the fused occupancy scheduler and packed planes.

    ``exchange_slots`` caps the per-peer exchange buffer (default: the
    capacity-exact ``5 * n_local``, which cannot overflow).  A tighter
    cap reduces ICI bytes per cycle and trips a LOUD whole-run
    :class:`StallError` on overflow — never a silent drop, because
    acceptance is not determinable sender-side.
    """

    def __init__(
        self,
        config: SystemConfig,
        tr_op: np.ndarray,
        tr_addr: np.ndarray,
        tr_val: np.ndarray,
        tr_len: np.ndarray,
        node_shards: Optional[int] = None,
        data_shards: int = 1,
        mesh: Optional[Mesh] = None,
        exchange_slots: Optional[int] = None,
        block: int = 1024,
        **kwargs,
    ):
        if mesh is None:
            if node_shards is None:
                raise ValueError("pass node_shards or an explicit mesh")
            mesh = make_mesh(
                node_shards=node_shards, data_shards=data_shards
            )
        if tuple(mesh.axis_names) != ("data", "node"):
            raise ValueError(
                f"need a ('data', 'node') mesh, got {mesh.axis_names}"
            )
        node_shards = mesh.shape["node"]
        data_shards = mesh.shape["data"]
        if node_shards < 2:
            raise ValueError(
                "node_shards=1 is the unsharded fast path — use "
                "PallasEngine / DataShardedPallasEngine"
            )
        if config.num_procs % node_shards != 0:
            raise ValueError(
                f"num_procs={config.num_procs} not divisible by node "
                f"shards={node_shards}"
            )
        b = tr_op.shape[0]
        if b % data_shards != 0:
            raise ValueError(
                f"batch {b} not divisible by data_shards={data_shards}"
            )
        sched = kwargs.get("schedule")
        if sched is not None:
            if not sched.fused:
                raise NotImplementedError(
                    "node-sharded Pallas supports the fused occupancy "
                    "scheduler only (Schedule(fused=True)); the "
                    "host-barrier loop would round-trip the sharded "
                    "planes every interval"
                )
            resident = sched.resident or b
            if resident % data_shards:
                raise ValueError(
                    f"schedule.resident={resident} not divisible by "
                    f"data_shards={data_shards}"
                )
        # the fused plan's groups are data-shard-local, so the lane
        # block must tile the per-shard lane count, not the full batch
        if sched is not None:
            block = choose_block(
                (sched.resident or b) // data_shards, block
            )
        else:
            block = choose_block(b // data_shards, block)
        super().__init__(
            config, tr_op, tr_addr, tr_val, tr_len, block=block, **kwargs
        )
        self.mesh = mesh
        self.node_shards = node_shards
        self.data_shards = data_shards
        self._shard_b = b // data_shards
        self._exchange_slots = exchange_slots
        self._sched_groups = data_shards

        def put(key, v):
            return jax.device_put(
                v, NamedSharding(mesh, _node_plane_spec(key, v.ndim))
            )

        self.state = {f: put(f, v) for f, v in self.state.items()}
        for f in _PALLAS_TRANSIENTS:
            self.state[f] = put(
                f, jnp.zeros((1, b), jnp.int32)
            )
        self._tr_full = jax.device_put(
            self._tr_full, NamedSharding(mesh, P("node", None, "data"))
        )
        self._tr_len_full = jax.device_put(
            self._tr_len_full, NamedSharding(mesh, P("node", "data"))
        )

    @property
    def cross_shard_msgs(self) -> int:
        """Total exchange entries shipped across node shards over the
        run (summed over lanes; candidates headed to multiple peers
        count once per peer)."""
        return int(np.sum(np.asarray(self.state["xmsgs"])))

    def _runner(self, max_cycles: int):
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        return build_node_sharded_pallas_run(
            self.config, self._shard_b, self._snapshots, self._window,
            self._n_seg, max_calls, self.cycles_per_call, self.mesh,
            self._exchange_slots, self._packed, self._interpret,
        )

    def _fused_runner(self, max_cycles: int):
        max_calls = max(1, -(-max_cycles // self.cycles_per_call))
        return build_node_fused_pallas_run(
            self.config, self._resident // self.data_shards,
            self._shard_b, self.cycles_per_call, self._window,
            self._n_seg, max_calls, self.mesh, self._exchange_slots,
            self._packed, self._interpret,
        )

    def _fused_plan_arrays(self, plan):
        # identical rebasing to DataShardedPallasEngine: groups are
        # data-shard-local, so system/permutation indices localize to
        # each shard's contiguous slice; plan rows replicate over node
        shards = self.data_shards
        gl = self._resident // shards
        gs = self.b // shards
        g = np.arange(self._resident, dtype=np.int64) // gl
        sys_l = np.where(plan.sys >= 0, plan.sys - g[None, :] * gs, -1)
        perm_l = plan.perm - g[None, :] * gl
        put = lambda x: jax.device_put(
            jnp.asarray(x), NamedSharding(self.mesh, P(None, "data"))
        )
        return (
            put(sys_l.astype(np.int32)),
            put(plan.seg),
            put(perm_l.astype(np.int32)),
            put(plan.reset.astype(np.int32)),
        )

    def _sched_put(self, x):
        # only reached for the fused initial state (fused=False raises
        # in the ctor); keyless, so infer the plane class by leading
        # axis — every node-leading plane starts with num_procs rows,
        # and no replicated plane does (scalars/msg_counts rows are
        # enum-sized)
        from hpa2_tpu.ops import pallas_engine as pe

        lead = x.shape[0] if x.ndim else 0
        if x.ndim >= 2 and lead == self.config.num_procs:
            spec = P("node", *([None] * (x.ndim - 2)), "data")
        else:
            spec = P(*([None] * (x.ndim - 1)), "data")
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def _check_status(self, status: int, max_cycles: int) -> None:
        if status & 4:
            self._poisoned = True
            raise _exchange_overflow_error(
                self.state, self._exchange_slots
            )
        super()._check_status(status, max_cycles)

    def stats(self) -> dict:
        out = super().stats()
        out.update(_pallas_exchange_stats(self.config, self.state))
        return out


class NodeShardedLaneSession(PallasLaneSession):
    """The resident-lane serving session on the node-sharded engine:
    every resident lane's NODE axis is split into contiguous blocks
    over the mesh's ``node`` axis (composing with ``data`` lane
    sharding on the same 2-D mesh), so one always-on service hosts
    jobs bigger than a chip.  Same serving protocol as the base
    session; operand placement, the runner, and the exchange-overflow
    status bit mirror :class:`NodeShardedPallasEngine` vs
    :class:`PallasEngine`, and served dumps stay byte-identical to a
    one-shot node-sharded run."""

    def __init__(
        self,
        config: SystemConfig,
        resident: int,
        window: int,
        *,
        node_shards: Optional[int] = None,
        data_shards: int = 1,
        mesh: Optional[Mesh] = None,
        exchange_slots: Optional[int] = None,
        block: int = 1024,
        **kwargs,
    ):
        if mesh is None:
            if node_shards is None:
                raise ValueError("pass node_shards or an explicit mesh")
            mesh = make_mesh(
                node_shards=node_shards, data_shards=data_shards
            )
        if tuple(mesh.axis_names) != ("data", "node"):
            raise ValueError(
                f"need a ('data', 'node') mesh, got {mesh.axis_names}"
            )
        node_shards = mesh.shape["node"]
        data_shards = mesh.shape["data"]
        if node_shards < 2:
            raise ValueError(
                "node_shards=1 is the unsharded serving path — use "
                "PallasLaneSession / DataShardedLaneSession"
            )
        if config.num_procs % node_shards != 0:
            raise ValueError(
                f"num_procs={config.num_procs} not divisible by node "
                f"shards={node_shards}"
            )
        if resident % data_shards:
            raise ValueError(
                f"resident={resident} not divisible by "
                f"data_shards={data_shards}"
            )
        self.mesh = mesh
        self.node_shards = node_shards
        self.data_shards = data_shards
        self._exchange_slots = exchange_slots
        block = choose_block(resident // data_shards, block)
        super().__init__(
            config, resident, window, block=block, **kwargs
        )
        # thread the node-sharded transient rows through the carried
        # state AND the admission-reset init: the barrier closure reads
        # `self._init` by reference at trace time, so growing the dict
        # here is visible to the already-built jit.  Resetting a lane's
        # transients on admission is correct — `activeg` is reseeded
        # every interval, and `xmsgs`/`exchov` are per-job accumulators
        # in serving (each lane column belongs to one job at a time).
        for f in _PALLAS_TRANSIENTS:
            self._init[f] = jnp.zeros((1, self.r), jnp.int32)
        self.fields = list(self._init.keys())
        self.state = {
            f: self._plane_put(f, v) for f, v in self._init.items()
        }

    # -- backend hooks --------------------------------------------------

    def _plane_put(self, key: str, v):
        return jax.device_put(
            jnp.asarray(v),
            NamedSharding(self.mesh, _node_plane_spec(key, v.ndim)),
        )

    def _build_runner(self):
        max_calls = max(1, -(-self.max_cycles // self.cycles_per_call))
        return build_node_sharded_pallas_run(
            self.config, self.r // self.data_shards, False,
            self.window, 1, max_calls, self.cycles_per_call, self.mesh,
            self._exchange_slots, self._packed, self._interpret,
        )

    def _put(self, x):
        # trailing-lane operands (perm / reset): replicate over node,
        # shard lanes over data
        x = jnp.asarray(x)
        return jax.device_put(
            x,
            NamedSharding(
                self.mesh, P(*([None] * (x.ndim - 1)), "data")
            ),
        )

    def _donate_barrier(self) -> bool:
        # barrier output is re-placed plane-by-plane anyway; skip
        # donation so XLA never reconciles donated layouts with the
        # resharding device_put
        return False

    # -- serving protocol overrides -------------------------------------

    def advance(self, tr, tl):
        # re-place the runner's output through the SAME key-aware
        # placement the barrier uses: jit outputs come back with
        # jax-canonicalized specs (e.g. a size-1 "data" axis dropped),
        # and alternating input shardings would recompile the runner /
        # barrier every interval, tripping the zero-recompile guard.
        # Equivalent-sharding device_puts are transfer-free.
        self.state, status = self._runner(self.state, tr, tl)
        self.state = {
            f: self._plane_put(f, v) for f, v in self.state.items()
        }
        return status

    def stage(self, tr_int, tl_int):
        tr = jax.device_put(
            jnp.asarray(tr_int),
            NamedSharding(self.mesh, P("node", None, "data")),
        )
        tl = jax.device_put(
            jnp.asarray(tl_int),
            NamedSharding(self.mesh, P("node", "data")),
        )
        return tr, tl

    def barrier(self, perm, reset) -> None:
        st = self._barrier_jit(
            self.state,
            self._put(jnp.asarray(perm)),
            self._put(jnp.asarray(reset)),
        )
        self.state = {f: self._plane_put(f, v) for f, v in st.items()}

    def check(self, status) -> None:
        if int(status) & 4:
            raise _exchange_overflow_error(
                self.state, self._exchange_slots
            )
        super().check(status)

    def counters_of(self, cols) -> dict:
        out = super().counters_of(cols)
        out["cross_shard_msgs"] = int(np.sum(np.asarray(cols["xmsgs"])))
        out["exchange_slot_hwm"] = int(np.max(np.asarray(cols["exchhw"])))
        out["exchange_multicast_saved"] = int(
            np.sum(np.asarray(cols["exchmc"]))
        )
        out["exchange_combined"] = int(np.sum(np.asarray(cols["exchcb"])))
        return out
