"""Multi-chip execution: mesh construction, `SimState` partition specs,
and sharded run loops.

The reference scales by OpenMP threads inside one address space
(assignment.c:125, 135-137) and communicates through locked
shared-memory mailboxes (assignment.c:63-68, 711-739).  On TPU the two
scaling axes become mesh axes:

* ``data`` — the ensemble/batch axis: B independent simulated systems,
  embarrassingly parallel (the DP analog).  Sharding the leading batch
  axis with a ``NamedSharding`` is enough; XLA needs no collectives.
* ``node`` — the simulated-node axis *within* one system (the TP/SP
  analog): each device owns a contiguous block of nodes — their
  caches, directory slices, memory slices and mailboxes.  Cross-device
  message delivery is one ``all_gather`` of the fixed-shape send
  candidate tensor per cycle over ICI (see ops/step.py phase C); the
  gather order is chosen so the sharded engine is *bit-identical* to
  the single-chip engine.

Both axes compose: ``shard_map(vmap(step))`` over a 2-D
``Mesh(('data', 'node'))`` runs a sharded ensemble of sharded systems.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpa2_tpu import hostenv
from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import Instr
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.ops.engine import JaxEngine, _node_dump_from, stack_states
from hpa2_tpu.ops.state import SimState, init_state
from hpa2_tpu.ops.step import build_step, quiescent
from hpa2_tpu.utils.dump import NodeDump

# SimState fields whose leading (non-batch) axis is the node axis;
# everything else (cycle, counters, replay schedule, fault/watchdog
# bookkeeping) is replicated.
_NODE_LEADING = frozenset(
    f
    for f in SimState._fields
    if f not in ("order_node", "order_pos", "order_len",
                 "cycle", "n_instr", "n_msgs", "overflow",
                 "n_read_hits", "n_read_miss", "n_write_hits",
                 "n_write_miss", "n_evictions", "n_invalidations",
                 "msg_counts", "rng_key", "last_progress",
                 "n_retrans", "n_dup_filtered", "n_reorder_fixed",
                 "n_delays", "n_wire_stalls")
)


def make_mesh(
    node_shards: int = 1,
    data_shards: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A ``(data, node)`` mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if node_shards < 1 or node_shards > len(devices):
        raise ValueError(
            f"node_shards={node_shards} outside 1..{len(devices)} "
            "available devices"
        )
    if data_shards is None:
        data_shards = len(devices) // node_shards
    need = data_shards * node_shards
    if need < 1:
        raise ValueError(
            f"empty mesh: data_shards={data_shards} x "
            f"node_shards={node_shards}"
        )
    if need > len(devices):
        raise ValueError(
            f"mesh {data_shards}x{node_shards} needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(data_shards, node_shards)
    return Mesh(grid, ("data", "node"))


def state_specs(
    batched: bool = False,
    node_axis: Optional[str] = "node",
    batch_axis: Optional[str] = "data",
) -> SimState:
    """PartitionSpecs for every SimState leaf.

    ``batched=True`` expects a leading ensemble axis on every leaf
    (from ``stack_states``) sharded over ``batch_axis``; the node axis
    (leading axis of per-system arrays) shards over ``node_axis``.
    """
    lead = (batch_axis,) if batched else ()
    specs = {}
    for f in SimState._fields:
        if f in _NODE_LEADING:
            specs[f] = P(*lead, node_axis)
        else:
            specs[f] = P(*lead)
    return SimState(**specs)


def _place(state: SimState, mesh: Mesh, specs: SimState) -> SimState:
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


@functools.lru_cache(maxsize=16)
def build_node_sharded_run(
    config: SystemConfig,
    mesh: Mesh,
    batched: bool,
    max_cycles: int = 1_000_000,
):
    """Jitted run-to-quiescence with the node axis sharded over the
    mesh's ``node`` axis (and, if ``batched``, the ensemble over
    ``data``).

    The ``lax.while_loop`` lives *outside* the ``shard_map``: the loop
    body is the manually-sharded SPMD step (one ICI all_gather per
    cycle), while the quiescence condition is computed on the global
    view so XLA inserts the cross-device reductions itself.
    """
    node_shards = mesh.shape["node"]
    step = build_step(
        config, replay=False, axis_name="node", shards=node_shards
    )
    specs = state_specs(batched=batched)
    body = step
    if batched:
        body = jax.vmap(step)
    wrapped = hostenv.shard_map(
        body,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=specs,
        check_replication=False,
    )

    if batched:
        vq = jax.vmap(quiescent)

        def cond(st):
            return (
                jnp.any(~vq(st))
                & jnp.all(st.cycle < max_cycles)
                & ~jnp.any(st.overflow)
            )

    else:

        def cond(st):
            return (
                (~quiescent(st))
                & (st.cycle < max_cycles)
                & (~st.overflow)
            )

    def run(st: SimState) -> SimState:
        return jax.lax.while_loop(cond, wrapped, st)

    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs
    )
    return jax.jit(run, in_shardings=(shardings,), out_shardings=shardings)


class NodeShardedEngine:
    """One large system with its node axis sharded across devices.

    The scaling analog of the reference's thread-per-node OpenMP region
    (assignment.c:135-137) when one chip is not enough nodes: each
    device simulates ``num_procs / node_shards`` nodes; mailbox traffic
    crosses ICI as an all-gathered candidate tensor.  Dump readback and
    quiescence semantics match :class:`JaxEngine` exactly.
    """

    def __init__(
        self,
        config: SystemConfig,
        traces: Sequence[Sequence[Instr]],
        mesh: Optional[Mesh] = None,
        max_cycles: int = 1_000_000,
    ):
        if mesh is None:
            mesh = make_mesh(node_shards=len(jax.devices()))
        if config.num_procs % mesh.shape["node"] != 0:
            raise ValueError(
                f"num_procs={config.num_procs} not divisible by node "
                f"shards={mesh.shape['node']}"
            )
        self.config = config
        self.mesh = mesh
        self._specs = state_specs(batched=False)
        self.state = _place(init_state(config, traces), mesh, self._specs)
        self._run = build_node_sharded_run(
            config, mesh, batched=False, max_cycles=max_cycles
        )

    def run(self) -> "NodeShardedEngine":
        st = self._run(self.state)
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        self.state = st
        if bool(st.overflow):
            raise StallError("internal invariant violated: mailbox overflow despite backpressure")
        if not bool(quiescent(st)):
            raise StallError(
                f"no quiescence after {int(st.cycle)} cycles (livelock?)"
            )
        return self

    def snapshots(self) -> List[NodeDump]:
        arrs = JaxEngine._snap_arrays(self.state)
        return [
            _node_dump_from(arrs, i) for i in range(self.config.num_procs)
        ]

    def final_dumps(self) -> List[NodeDump]:
        arrs = JaxEngine._live_arrays(self.state)
        return [
            _node_dump_from(arrs, i) for i in range(self.config.num_procs)
        ]

    @property
    def cycle(self) -> int:
        return int(self.state.cycle)

    @property
    def instructions(self) -> int:
        return int(self.state.n_instr)

    @property
    def messages(self) -> int:
        return int(self.state.n_msgs)


class GridEngine:
    """A sharded ensemble of (optionally) sharded systems: the full 2-D
    ``(data, node)`` mesh — DP x model-parallel in one jitted loop."""

    def __init__(
        self,
        config: SystemConfig,
        batch_traces: Sequence[Sequence[Sequence[Instr]]],
        mesh: Optional[Mesh] = None,
        max_cycles: int = 1_000_000,
    ):
        if mesh is None:
            mesh = make_mesh(node_shards=1)
        b = len(batch_traces)
        if b % mesh.shape["data"] != 0:
            raise ValueError(
                f"batch {b} not divisible by data shards "
                f"{mesh.shape['data']}"
            )
        if config.num_procs % mesh.shape["node"] != 0:
            raise ValueError(
                f"num_procs={config.num_procs} not divisible by node "
                f"shards={mesh.shape['node']}"
            )
        self.config = config
        self.mesh = mesh
        max_t = max(
            (len(tr) for traces in batch_traces for tr in traces), default=1
        )
        self._specs = state_specs(batched=True)
        state = stack_states(
            [init_state(config, t, max_trace_len=max_t) for t in batch_traces]
        )
        self.state = _place(state, mesh, self._specs)
        self._run = build_node_sharded_run(
            config, mesh, batched=True, max_cycles=max_cycles
        )

    def run(self) -> "GridEngine":
        st = self._run(self.state)
        st = jax.tree_util.tree_map(lambda x: x.block_until_ready(), st)
        self.state = st
        if bool(jnp.any(st.overflow)):
            raise StallError("internal invariant violated: mailbox overflow despite backpressure")
        if not bool(jnp.all(jax.vmap(quiescent)(st))):
            raise StallError("batch did not reach quiescence (livelock?)")
        return self

    def system_snapshots(self, b: int) -> List[NodeDump]:
        st_b = jax.tree_util.tree_map(lambda x: np.asarray(x)[b], self.state)
        arrs = JaxEngine._snap_arrays(st_b)
        return [
            _node_dump_from(arrs, i) for i in range(self.config.num_procs)
        ]

    @property
    def instructions(self) -> int:
        return int(jnp.sum(self.state.n_instr))
