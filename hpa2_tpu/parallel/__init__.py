"""Multi-chip parallelism: meshes, shardings, and sharded run loops."""

from hpa2_tpu.parallel.sharding import (
    GridEngine,
    NodeShardedEngine,
    build_node_sharded_run,
    make_mesh,
    state_specs,
)

__all__ = [
    "GridEngine",
    "NodeShardedEngine",
    "build_node_sharded_run",
    "make_mesh",
    "state_specs",
]
