"""Device-mesh sharding of the batch and node axes (shard_map / pjit)."""
