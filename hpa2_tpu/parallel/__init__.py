"""Multi-chip parallelism: meshes, shardings, and sharded run loops."""

from hpa2_tpu.parallel.sharding import (
    DataShardedPallasEngine,
    GridEngine,
    NodeShardedEngine,
    build_data_sharded_pallas_run,
    build_node_sharded_run,
    make_data_mesh,
    make_mesh,
    state_specs,
)

__all__ = [
    "DataShardedPallasEngine",
    "GridEngine",
    "NodeShardedEngine",
    "build_data_sharded_pallas_run",
    "build_node_sharded_run",
    "make_data_mesh",
    "make_mesh",
    "state_specs",
]
