"""Static protocol analysis: declarative transition table, whole-table
checks, cross-backend equivalence extraction, and a JAX-pitfall lint.

The MESI/directory transition logic lives in four executable places —
``models/spec_engine.py``, the JAX ``ops/step.py``, the Pallas kernel,
and ``native/src/sim.cpp`` — guarded so far only by *dynamic*
differential tests.  This package makes the transition relation a
first-class artifact:

* ``table``   — the declarative ``TransitionTable`` (one ``Row`` per
  role x state x event x guard-case), built per ``Semantics`` variant.
* ``checks``  — static whole-table checks: completeness, determinism,
  no-silent-drop, state-product consistency, reply-guarantee.
* ``extract`` — probe-based extraction of the *effective* table from
  each backend (spec / JAX / native via a C API probe), diffed against
  the declarative table.
* ``mutate``  — seeded table mutations for the analyzer self-test.
* ``lint``    — AST lint for JAX pitfalls and dead spec handlers.

CLI: ``python -m hpa2_tpu.analysis {check,lint,equiv,mutation-test}``.
"""

from hpa2_tpu.analysis.table import Emit, Row, TransitionTable, Unreachable, build_table
from hpa2_tpu.analysis.checks import run_static_checks

__all__ = [
    "Emit",
    "Row",
    "TransitionTable",
    "Unreachable",
    "build_table",
    "run_static_checks",
]
