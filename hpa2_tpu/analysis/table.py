"""The declarative protocol transition table.

One ``Row`` per (role, current-state, event, guard-case) describes the
complete observable effect of handling one message or issuing one
instruction: next state, symbolic sharer-set update, emissions, memory
write, waiting-flag changes.  ``build_table(semantics)`` materializes
the table for one ``Semantics`` variant — policy switches change row
content, never the case universe, so every variant is checked against
the same exhaustive grid (``CASE_UNIVERSE``).

Two roles partition the protocol:

* ``home``  — the directory FSM (state = ``DirState`` name) reacting to
  messages addressed to the block's home node.
* ``cache`` — the cache-line FSM (state = ``CacheState`` name) reacting
  to replies/interventions/notifications and to the two instruction
  events ``INSTR_R`` / ``INSTR_W``.

A message that touches both (e.g. FLUSH when the requester *is* the
home) composes the two roles' rows — the handlers apply the directory
part and the cache part independently, so the table stays a product of
the two FSMs.

Symbolic vocabulary (resolved to concrete values by
``analysis.extract`` when diffing against backends):

* sharers update: ``same  empty  requester  +requester  -sender
  second  +second``
* emission target: ``requester  owner  home  second  survivor
  sharers  victim_home`` (``sharers`` fans out one copy per set bit,
  excluding the emitting node)
* payload value source: ``mem  line  instr`` (line = the cache line's
  value *before* the transition)
* line fill source (``value_src``): ``msg  pending  instr
  placeholder`` (placeholder = the miss-path invalid fill, value 0)

Guard-cases within a cell are named, mutually exclusive, and must
exactly tile the cell's entry in ``CASE_UNIVERSE`` (or be absorbed by
an ``Unreachable`` declaration carrying a reason) — that is the
completeness check's whole job.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from hpa2_tpu.config import Semantics

HOME_STATES: Tuple[str, ...] = ("U", "S", "EM")
CACHE_STATES: Tuple[str, ...] = ("M", "E", "S", "I")

#: protocol variants shipped as tables (hpa2_tpu/protocols lowers them
#: into the int-indexed planes the kernels execute)
PROTOCOLS: Tuple[str, ...] = ("mesi", "moesi", "mesif")

#: per-protocol state vocabularies.  MOESI adds the dirty-shared OWNED
#: line state and the SO ("shared, dirty owner") directory state; MESIF
#: adds the clean FORWARD line state (the designated cache-to-cache
#: responder) with the forwarder tracked in the home's owner pointer.
PROTOCOL_CACHE_STATES: Dict[str, Tuple[str, ...]] = {
    "mesi": CACHE_STATES,
    "moesi": ("M", "E", "S", "I", "O"),
    "mesif": ("M", "E", "S", "I", "F"),
}
PROTOCOL_HOME_STATES: Dict[str, Tuple[str, ...]] = {
    "mesi": HOME_STATES,
    "moesi": ("U", "S", "EM", "SO"),
    "mesif": HOME_STATES,
}

#: all message events + the two instruction events
MSG_EVENTS: Tuple[str, ...] = (
    "READ_REQUEST", "WRITE_REQUEST", "REPLY_RD", "REPLY_WR", "REPLY_ID",
    "INV", "UPGRADE", "WRITEBACK_INV", "WRITEBACK_INT", "FLUSH",
    "FLUSH_INVACK", "EVICT_SHARED", "EVICT_MODIFIED", "UPGRADE_NOTIFY",
    "NACK",
)
INSTR_EVENTS: Tuple[str, ...] = ("INSTR_R", "INSTR_W")

REQUEST_EVENTS: Tuple[str, ...] = ("READ_REQUEST", "WRITE_REQUEST", "UPGRADE")
REPLY_TYPES: Tuple[str, ...] = ("REPLY_RD", "REPLY_WR", "REPLY_ID")


@dataclasses.dataclass(frozen=True)
class Emit:
    """One emission: message ``type`` sent to the ``to`` target class.

    ``to`` adds ``tracked_owner`` (the directory's owner/forwarder
    pointer) beyond the MESI target classes; ``sharers`` adds the
    ``fwdf`` REPLY_RD flag (fill the line in FORWARD state, MESIF).
    """

    type: str
    to: str
    value: str = ""    # ''|'mem'|'line'|'instr' — payload value source
    sharers: str = ""  # ''|'excl'|'shared'|'fwdf'|'others'|'none'|'rd'|'wr'
    second: str = ""   # ''|'requester'|'fwd' (fwd = copy msg.second)


@dataclasses.dataclass(frozen=True)
class Row:
    role: str          # 'home' | 'cache'
    state: str         # DirState name | CacheState letter
    event: str         # MsgType name | 'INSTR_R' | 'INSTR_W'
    case: str          # guard-case name, unique within the cell
    next_state: str
    emits: Tuple[Emit, ...] = ()
    sharers: str = ""        # home rows: symbolic sharer-set update
    writes_memory: bool = False
    value_src: str = ""      # cache rows: line fill source
    clears_waiting: bool = False
    sets_waiting: bool = False
    drop: str = ""           # non-empty iff the row is a no-op; cites why
    note: str = ""
    # home rows: symbolic owner/forwarder-pointer update.  '' leaves the
    # pointer untouched (every MESI row); 'none' clears it; 'requester' /
    # 'second' point it at the request's originator; 'owner' points it at
    # find_owner(sharers) before the update; 'same' is an explicit keep;
    # 'drop_sender' clears it iff it currently names the sender.
    owner: str = ""

    @property
    def is_noop(self) -> bool:
        return (
            self.next_state == self.state
            and not self.emits
            and self.sharers in ("", "same")
            and not self.writes_memory
            and self.value_src == ""
            and not self.clears_waiting
            and not self.sets_waiting
            and self.owner in ("", "same")
        )

    @property
    def key(self) -> Tuple[str, str, str, str]:
        return (self.role, self.state, self.event, self.case)


@dataclasses.dataclass(frozen=True)
class Unreachable:
    """Declares a cell (or case) that cannot occur, with a reason.

    ``state``/``case`` may be ``'*'`` to cover every state of an event
    or every case of a cell.  The completeness check requires a reason;
    the determinism check rejects rows inside a covered cell.
    """

    role: str
    event: str
    state: str = "*"
    case: str = "*"
    reason: str = ""

    def covers(self, role: str, state: str, event: str, case: str) -> bool:
        return (
            self.role == role
            and self.event == event
            and self.state in ("*", state)
            and self.case in ("*", case)
        )


# ---------------------------------------------------------------------------
# the guard-case universe: every (role, event) -> {state: cases} cell
# grid the table must tile.  Constant across Semantics variants.
# ---------------------------------------------------------------------------

def _uniform(states: Tuple[str, ...], cases: Tuple[str, ...]) -> Dict[str, Tuple[str, ...]]:
    return {s: cases for s in states}


CASE_UNIVERSE: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {
    # ---- home (directory) role ----
    ("home", "READ_REQUEST"): {
        "U": ("any",), "S": ("any",),
        "EM": ("owner_is_requester", "owner_is_other"),
    },
    ("home", "WRITE_REQUEST"): {
        "U": ("any",), "S": ("any",),
        "EM": ("owner_is_requester", "owner_is_other"),
    },
    ("home", "UPGRADE"): _uniform(HOME_STATES, ("any",)),
    ("home", "EVICT_SHARED"): {
        "U": ("any",),
        "S": ("sender_only_sharer", "two_sharers", "many_sharers",
              "sender_not_sharer"),
        "EM": ("sender_is_owner", "sender_not_owner"),
    },
    ("home", "EVICT_MODIFIED"): {
        "U": ("any",), "S": ("any",),
        "EM": ("sender_is_owner", "sender_not_owner"),
    },
    ("home", "FLUSH"): _uniform(HOME_STATES, ("any",)),
    ("home", "FLUSH_INVACK"): _uniform(HOME_STATES, ("any",)),
    ("home", "NACK"): _uniform(
        HOME_STATES, ("read_intervention", "write_intervention")
    ),
    # cache-bound messages never consult the directory
    ("home", "REPLY_RD"): _uniform(HOME_STATES, ("any",)),
    ("home", "REPLY_WR"): _uniform(HOME_STATES, ("any",)),
    ("home", "REPLY_ID"): _uniform(HOME_STATES, ("any",)),
    ("home", "INV"): _uniform(HOME_STATES, ("any",)),
    ("home", "WRITEBACK_INT"): _uniform(HOME_STATES, ("any",)),
    ("home", "WRITEBACK_INV"): _uniform(HOME_STATES, ("any",)),
    ("home", "UPGRADE_NOTIFY"): _uniform(HOME_STATES, ("any",)),
    # ---- cache (line) role ----
    ("cache", "REPLY_RD"): {
        "I": ("excl", "shared"),
        **_uniform(("M", "E", "S"),
                   ("match_excl", "match_shared",
                    "victim_excl", "victim_shared")),
    },
    ("cache", "FLUSH"): {
        "I": ("any",),
        **_uniform(("M", "E", "S"), ("match", "victim")),
    },
    ("cache", "REPLY_WR"): {
        "I": ("any",),
        **_uniform(("M", "E", "S"), ("match", "victim")),
    },
    ("cache", "FLUSH_INVACK"): {
        "I": ("any",),
        **_uniform(("M", "E", "S"), ("match", "victim")),
    },
    ("cache", "REPLY_ID"): _uniform(CACHE_STATES, ("match", "other")),
    ("cache", "INV"): _uniform(CACHE_STATES, ("match", "other")),
    ("cache", "WRITEBACK_INT"): {
        **_uniform(("M", "E"),
                   ("match_second_other", "match_second_home", "other")),
        "S": ("any",), "I": ("any",),
    },
    ("cache", "WRITEBACK_INV"): {
        **_uniform(("M", "E"),
                   ("match_second_other", "match_second_home", "other")),
        "S": ("any",), "I": ("any",),
    },
    ("cache", "UPGRADE_NOTIFY"): {
        "S": ("match_from_home", "match_not_home", "other"),
        **_uniform(("M", "E", "I"), ("any",)),
    },
    ("cache", "EVICT_SHARED"): {
        "S": ("match_from_home", "match_not_home", "other"),
        **_uniform(("M", "E", "I"), ("any",)),
    },
    ("cache", "INSTR_R"): {
        **_uniform(("M", "E", "S"), ("hit", "miss_victim")),
        "I": ("miss",),
    },
    ("cache", "INSTR_W"): {
        **_uniform(("M", "E", "S"), ("hit", "miss_victim")),
        "I": ("miss",),
    },
    # directory-bound messages never touch a remote cache line
    ("cache", "READ_REQUEST"): _uniform(CACHE_STATES, ("any",)),
    ("cache", "WRITE_REQUEST"): _uniform(CACHE_STATES, ("any",)),
    ("cache", "UPGRADE"): _uniform(CACHE_STATES, ("any",)),
    ("cache", "EVICT_MODIFIED"): _uniform(CACHE_STATES, ("any",)),
    ("cache", "NACK"): _uniform(CACHE_STATES, ("any",)),
}


@dataclasses.dataclass
class TransitionTable:
    semantics: Semantics
    rows: List[Row]
    unreachable: List[Unreachable]
    protocol: str = "mesi"
    cache_states: Tuple[str, ...] = CACHE_STATES
    home_states: Tuple[str, ...] = HOME_STATES
    case_universe: Optional[
        Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]]] = None

    @property
    def universe(self) -> Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]]:
        """The guard-case grid this table must tile."""
        return self.case_universe if self.case_universe is not None \
            else CASE_UNIVERSE

    def cell(self, role: str, state: str, event: str) -> List[Row]:
        return [
            r for r in self.rows
            if r.role == role and r.state == state and r.event == event
        ]

    def row(self, role: str, state: str, event: str, case: str) -> Row:
        for r in self.rows:
            if r.key == (role, state, event, case):
                return r
        raise KeyError((role, state, event, case))

    def is_unreachable(
        self, role: str, state: str, event: str, case: str
    ) -> bool:
        return any(
            u.covers(role, state, event, case) for u in self.unreachable
        )

    def replaced(self, old: Row, new: Row) -> "TransitionTable":
        rows = [new if r is old else r for r in self.rows]
        return dataclasses.replace(self, rows=rows)


# ---------------------------------------------------------------------------
# table construction
# ---------------------------------------------------------------------------

_DROP_STALE_EVICT = (
    "stale eviction: sender no longer in the sharer set; removing it "
    "again is idempotent (assignment.c:548-560 release build)"
)
_DROP_POLICY = 'Semantics.intervention_miss_policy == "drop"'


def build_table(sem: Semantics, protocol: str = "mesi") -> TransitionTable:
    """Materialize the declarative table for one Semantics variant.

    ``protocol`` selects the row set ("mesi", "moesi", "mesif"); MESI is
    byte-for-byte the historical table.  Non-MESI protocols reject the
    overloaded-notify HEAD quirk (a MESI-fixture artifact).
    """
    if protocol not in PROTOCOLS:
        raise ValueError(
            f"unknown protocol {protocol!r}; expected one of {PROTOCOLS}")
    if protocol == "mesi":
        rows, unreachable = _mesi_rows(sem)
        universe = CASE_UNIVERSE
    else:
        if sem.overloaded_evict_shared_notify:
            raise ValueError(
                "overloaded_evict_shared_notify is a MESI-fixture quirk; "
                f"the {protocol} table does not model it")
        builder = _moesi_rows if protocol == "moesi" else _mesif_rows
        rows, unreachable = builder(sem)
        universe = protocol_case_universe(protocol)
    return TransitionTable(
        semantics=sem, rows=rows, unreachable=unreachable,
        protocol=protocol,
        cache_states=PROTOCOL_CACHE_STATES[protocol],
        home_states=PROTOCOL_HOME_STATES[protocol],
        case_universe=universe)


def _mesi_rows(sem: Semantics) -> Tuple[List[Row], List[Unreachable]]:
    rows: List[Row] = []
    unreachable: List[Unreachable] = []
    nack = sem.intervention_miss_policy == "nack"
    notify = "EVICT_SHARED" if sem.overloaded_evict_shared_notify else "UPGRADE_NOTIFY"

    def home(state, event, case, next_state=None, **kw):
        rows.append(Row("home", state, event, case,
                        next_state if next_state is not None else state, **kw))

    def cache(state, event, case, next_state=None, **kw):
        rows.append(Row("cache", state, event, case,
                        next_state if next_state is not None else state, **kw))

    # ---- home: READ_REQUEST (assignment.c:187-232) ----
    home("U", "READ_REQUEST", "any", "EM", sharers="requester",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="excl"),))
    home("S", "READ_REQUEST", "any", "S", sharers="+requester",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="shared"),))
    home("EM", "READ_REQUEST", "owner_is_requester", "EM", sharers="same",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="excl"),),
         note="owner re-requesting after silent loss (assignment.c:215-221)")
    home("EM", "READ_REQUEST", "owner_is_other", "S", sharers="+requester",
         emits=(Emit("WRITEBACK_INT", "owner", second="requester"),),
         note="optimistic pre-flush S transition (assignment.c:230-231)")

    # ---- home: WRITE_REQUEST (assignment.c:362-430) ----
    eager = sem.eager_write_request_memory
    home("U", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("REPLY_WR", "requester"),))
    home("S", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("REPLY_ID", "requester", sharers="others"),))
    home("EM", "WRITE_REQUEST", "owner_is_requester", "EM", sharers="same",
         writes_memory=eager,
         emits=(Emit("REPLY_WR", "requester"),))
    home("EM", "WRITE_REQUEST", "owner_is_other", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("WRITEBACK_INV", "owner", second="requester"),),
         note="sharers optimistically = requester (assignment.c:429)")

    # ---- home: UPGRADE (assignment.c:300-326) ----
    home("S", "UPGRADE", "any", "EM", sharers="requester",
         emits=(Emit("REPLY_ID", "requester", sharers="others"),))
    for st in ("U", "EM"):
        home(st, "UPGRADE", "any", "EM", sharers="requester",
             emits=(Emit("REPLY_ID", "requester", sharers="none"),),
             note="directory lost track fallback (assignment.c:317-326)")

    # ---- home: EVICT_SHARED (assignment.c:498-521) ----
    home("U", "EVICT_SHARED", "any", drop=_DROP_STALE_EVICT)
    home("S", "EVICT_SHARED", "sender_only_sharer", "U", sharers="empty")
    home("S", "EVICT_SHARED", "two_sharers", "EM", sharers="-sender",
         emits=(Emit(notify, "survivor"),),
         note="last survivor silently upgraded S->E")
    home("S", "EVICT_SHARED", "many_sharers", "S", sharers="-sender")
    home("S", "EVICT_SHARED", "sender_not_sharer", drop=_DROP_STALE_EVICT)
    home("EM", "EVICT_SHARED", "sender_is_owner", "U", sharers="empty")
    home("EM", "EVICT_SHARED", "sender_not_owner", drop=_DROP_STALE_EVICT)

    # ---- home: EVICT_MODIFIED (assignment.c:541-566) ----
    home("U", "EVICT_MODIFIED", "any", writes_memory=True,
         note="stale eviction: memory still updated")
    home("S", "EVICT_MODIFIED", "any", writes_memory=True,
         note="stale eviction: memory still updated, directory untouched")
    home("EM", "EVICT_MODIFIED", "sender_is_owner", "U", sharers="empty",
         writes_memory=True)
    home("EM", "EVICT_MODIFIED", "sender_not_owner", writes_memory=True,
         note="stale eviction: directory untouched (assignment.c:548-560)")

    # ---- home: FLUSH / FLUSH_INVACK directory parts ----
    for st in HOME_STATES:
        home(st, "FLUSH", "any", writes_memory=True,
             note="home part: commit the flushed value")
        home(st, "FLUSH_INVACK", "any", "EM", sharers="second",
             writes_memory=True,
             note="home part: new owner = msg.second_receiver")

    # ---- home: NACK (robust policy only) ----
    if nack:
        for st in ("S", "EM"):
            home(st, "NACK", "read_intervention", "S", sharers="+second",
                 emits=(Emit("REPLY_RD", "second", value="mem",
                             sharers="shared"),),
                 note="re-serve the read from memory")
            home(st, "NACK", "write_intervention", "EM", sharers="second",
                 emits=(Emit("REPLY_WR", "second"),),
                 note="re-serve the write from memory")
        unreachable.append(Unreachable(
            "home", "NACK", "U",
            reason="the home cannot be U while an intervention it "
                   "initiated is outstanding (it moved to S/EM when "
                   "forwarding the WRITEBACK_*)"))
    else:
        unreachable.append(Unreachable(
            "home", "NACK",
            reason="NACK is never emitted under "
                   'Semantics.intervention_miss_policy == "drop"'))

    # cache-bound messages never consult the directory role
    for ev in ("REPLY_RD", "REPLY_WR", "REPLY_ID", "INV",
               "WRITEBACK_INT", "WRITEBACK_INV", "UPGRADE_NOTIFY"):
        unreachable.append(Unreachable(
            "home", ev,
            reason="addressed to a cache line; a home node receiving it "
                   "uses the cache-role rows for its own cache"))

    # ---- cache: REPLY_RD (assignment.c:234-251) ----
    def _victim_emit(state: str) -> Tuple[Emit, ...]:
        if state == "M":
            return (Emit("EVICT_MODIFIED", "victim_home", value="line"),)
        return (Emit("EVICT_SHARED", "victim_home"),)

    cache("I", "REPLY_RD", "excl", "E", value_src="msg", clears_waiting=True)
    cache("I", "REPLY_RD", "shared", "S", value_src="msg", clears_waiting=True)
    for st in ("M", "E", "S"):
        cache(st, "REPLY_RD", "match_excl", "E", value_src="msg",
              clears_waiting=True)
        cache(st, "REPLY_RD", "match_shared", "S", value_src="msg",
              clears_waiting=True)
        cache(st, "REPLY_RD", "victim_excl", "E", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))
        cache(st, "REPLY_RD", "victim_shared", "S", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))

    # ---- cache: FLUSH second-receiver part (assignment.c:286-298) ----
    cache("I", "FLUSH", "any", "S", value_src="msg", clears_waiting=True)
    for st in ("M", "E", "S"):
        cache(st, "FLUSH", "match", "S", value_src="msg", clears_waiting=True)
        cache(st, "FLUSH", "victim", "S", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))

    # ---- cache: REPLY_WR (assignment.c:432-441) ----
    cache("I", "REPLY_WR", "any", "M", value_src="pending",
          clears_waiting=True)
    for st in ("M", "E", "S"):
        cache(st, "REPLY_WR", "match", "M", value_src="pending",
              clears_waiting=True)
        unreachable.append(Unreachable(
            "cache", "REPLY_WR", st, "victim",
            reason="engine asserts the slot is ours or invalid: a "
                   "REPLY_WR can only follow our own WRITE_REQUEST, "
                   "whose placeholder fill owns the slot"))

    # ---- cache: FLUSH_INVACK second-receiver part (assignment.c:474-496) --
    fia_src = "msg" if sem.flush_invack_fills_old_value else "pending"
    cache("I", "FLUSH_INVACK", "any", "M", value_src=fia_src,
          clears_waiting=True)
    for st in ("M", "E", "S"):
        cache(st, "FLUSH_INVACK", "match", "M", value_src=fia_src,
              clears_waiting=True)
        unreachable.append(Unreachable(
            "cache", "FLUSH_INVACK", st, "victim",
            reason="engine asserts the slot is ours or invalid (same "
                   "argument as REPLY_WR)"))

    # ---- cache: REPLY_ID (assignment.c:328-360) ----
    for st in ("I", "E", "S"):
        cache(st, "REPLY_ID", "match", "M", value_src="pending",
              clears_waiting=True,
              emits=(Emit("INV", "sharers"),))
    cache("M", "REPLY_ID", "match", "M", clears_waiting=True,
          emits=(Emit("INV", "sharers"),),
          note="write already applied locally on the S-hit path")
    for st in CACHE_STATES:
        cache(st, "REPLY_ID", "other", clears_waiting=True,
              note="line replaced while waiting: INV fan-out suppressed "
                   "(assignment.c:339-347)")

    # ---- cache: INV (assignment.c:292-299) ----
    for st in ("E", "S"):
        cache(st, "INV", "match", "I")
    cache("M", "INV", "match",
          drop="stale INV: our write raced ahead and the line is "
               "already M (assignment.c:292 guards S/E only)")
    cache("I", "INV", "match",
          drop="stale INV: line already invalid; invalidating again "
               "is idempotent")
    for st in CACHE_STATES:
        cache(st, "INV", "other",
              drop="stale INV: line already replaced by another address")

    # ---- cache: WRITEBACK_INT / WRITEBACK_INV (owner side) ----
    def _miss_row(st, event, case, wr: bool):
        if nack:
            cache(st, event, case,
                  emits=(Emit("NACK", "home", sharers="wr" if wr else "rd",
                              second="fwd"),),
                  note="stale intervention bounced to home")
        else:
            cache(st, event, case, drop=_DROP_POLICY,
                  note="stale intervention silently dropped: the "
                       "requester hangs (assignment.c:265-270)")

    for st in ("M", "E"):
        cache(st, "WRITEBACK_INT", "match_second_other", "S",
              emits=(Emit("FLUSH", "home", value="line", second="fwd"),
                     Emit("FLUSH", "second", value="line", second="fwd")))
        cache(st, "WRITEBACK_INT", "match_second_home", "S",
              emits=(Emit("FLUSH", "home", value="line", second="fwd"),),
              note="requester is the home: single FLUSH")
        _miss_row(st, "WRITEBACK_INT", "other", wr=False)
    cache_states_miss = (("S", "any"), ("I", "any"))
    for st, case in cache_states_miss:
        _miss_row(st, "WRITEBACK_INT", case, wr=False)

    for st in ("M", "E"):
        cache(st, "WRITEBACK_INV", "match_second_other", "I",
              emits=(Emit("FLUSH_INVACK", "home", value="line",
                          second="fwd"),
                     Emit("FLUSH_INVACK", "second", value="line",
                          second="fwd")))
        cache(st, "WRITEBACK_INV", "match_second_home", "I",
              emits=(Emit("FLUSH_INVACK", "home", value="line",
                          second="fwd"),),
              note="requester is the home: single FLUSH_INVACK")
        _miss_row(st, "WRITEBACK_INV", "other", wr=True)
    for st, case in cache_states_miss:
        _miss_row(st, "WRITEBACK_INV", case, wr=True)

    # ---- cache: survivor upgrade notification ----
    _notify_rows = (
        ("match_from_home", "E", ""),
        ("match_not_home", "S",
         "notify must come from the home (spoof guard)"),
        ("other", "S", "stale notify: line already replaced"),
    )

    def _notify_cell(event: str):
        for case, nxt, why in _notify_rows:
            if nxt == "E":
                cache("S", event, case, "E",
                      note="last survivor: silent S->E upgrade")
            else:
                cache("S", event, case, drop=why)
        for st in ("M", "E", "I"):
            cache(st, event, "any",
                  drop="stale notify: line no longer SHARED")

    if sem.overloaded_evict_shared_notify:
        _notify_cell("EVICT_SHARED")
        unreachable.append(Unreachable(
            "cache", "UPGRADE_NOTIFY",
            reason="overloaded-HEAD semantics never emit the distinct "
                   "UPGRADE_NOTIFY type"))
    else:
        _notify_cell("UPGRADE_NOTIFY")
        unreachable.append(Unreachable(
            "cache", "EVICT_SHARED",
            reason="under fixture semantics the survivor notify is the "
                   "distinct UPGRADE_NOTIFY type; EVICT_SHARED is only "
                   "ever addressed to the home"))

    # directory-bound messages never reach the cache role
    for ev in ("READ_REQUEST", "WRITE_REQUEST", "UPGRADE", "EVICT_MODIFIED"):
        unreachable.append(Unreachable(
            "cache", ev,
            reason="requests and evictions are addressed to the home "
                   "directory; the home's own cache is untouched"))
    unreachable.append(Unreachable(
        "cache", "NACK",
        reason="NACK is addressed to the home directory (re-serve path)"))

    # ---- cache: instruction issue (assignment.c:590-697) ----
    for st in ("M", "E", "S"):
        cache(st, "INSTR_R", "hit", note="read hit: no traffic")
        cache(st, "INSTR_R", "miss_victim", "I", value_src="placeholder",
              sets_waiting=True,
              emits=_victim_emit(st) + (Emit("READ_REQUEST", "home"),))
    cache("I", "INSTR_R", "miss", "I", value_src="placeholder",
          sets_waiting=True,
          emits=(Emit("READ_REQUEST", "home"),))

    cache("M", "INSTR_W", "hit", "M", value_src="instr",
          note="write hit on M: local update")
    cache("E", "INSTR_W", "hit", "M", value_src="instr",
          note="silent E->M upgrade")
    cache("S", "INSTR_W", "hit", "M", value_src="instr", sets_waiting=True,
          emits=(Emit("UPGRADE", "home"),),
          note="write applied locally before REPLY_ID (assignment.c:656-658)")
    for st in ("M", "E", "S"):
        cache(st, "INSTR_W", "miss_victim", "I", value_src="placeholder",
              sets_waiting=True,
              emits=_victim_emit(st)
              + (Emit("WRITE_REQUEST", "home", value="instr"),))
    cache("I", "INSTR_W", "miss", "I", value_src="placeholder",
          sets_waiting=True,
          emits=(Emit("WRITE_REQUEST", "home", value="instr"),))

    return rows, unreachable


# ---------------------------------------------------------------------------
# protocol-variant case universes
# ---------------------------------------------------------------------------

def protocol_case_universe(
    protocol: str,
) -> Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]]:
    """The exhaustive guard-case grid for one protocol's table."""
    if protocol == "mesi":
        return CASE_UNIVERSE
    C = PROTOCOL_CACHE_STATES[protocol]
    H = PROTOCOL_HOME_STATES[protocol]
    valid = tuple(s for s in C if s != "I")
    u: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {}

    if protocol == "moesi":
        u[("home", "READ_REQUEST")] = {
            "U": ("any",), "S": ("any",),
            "EM": ("owner_is_requester", "owner_is_other"),
            "SO": ("owner_is_requester", "owner_is_other"),
        }
        u[("home", "WRITE_REQUEST")] = {
            "U": ("any",), "S": ("any",),
            "EM": ("owner_is_requester", "owner_is_other"),
            "SO": ("any",),
        }
        u[("home", "EVICT_SHARED")] = {
            "U": ("any",),
            "S": ("sender_only_sharer", "two_sharers", "many_sharers",
                  "sender_not_sharer"),
            "EM": ("sender_is_owner", "sender_not_owner"),
            "SO": ("none_left", "one_left", "several_left",
                   "sender_not_sharer"),
        }
        u[("home", "EVICT_MODIFIED")] = {
            "U": ("any",), "S": ("any",),
            "EM": ("sender_is_owner", "sender_not_owner"),
            "SO": ("sender_is_owner_last", "sender_is_owner_more",
                   "sender_not_owner"),
        }
        wbint_resp = ("M", "E", "O")
        notify_states = ("S", "O")
        rd_flags = ("excl", "shared")
    else:  # mesif
        u[("home", "READ_REQUEST")] = {
            "U": ("any",),
            "S": ("no_fwd", "fwd_is_requester", "fwd_other"),
            "EM": ("owner_is_requester", "owner_is_other"),
        }
        u[("home", "WRITE_REQUEST")] = {
            "U": ("any",), "S": ("any",),
            "EM": ("owner_is_requester", "owner_is_other"),
        }
        u[("home", "EVICT_SHARED")] = {
            "U": ("any",),
            "S": ("sender_only_sharer", "two_sharers", "many_sharers",
                  "sender_not_sharer"),
            "EM": ("sender_is_owner", "sender_not_owner"),
        }
        u[("home", "EVICT_MODIFIED")] = {
            "U": ("any",), "S": ("any",),
            "EM": ("sender_is_owner", "sender_not_owner"),
        }
        wbint_resp = ("M", "E", "F")
        notify_states = ("S", "F")
        rd_flags = ("excl", "fwd")

    u[("home", "UPGRADE")] = _uniform(H, ("any",))
    u[("home", "FLUSH")] = _uniform(H, ("any",))
    u[("home", "FLUSH_INVACK")] = _uniform(H, ("any",))
    u[("home", "NACK")] = _uniform(
        H, ("read_intervention", "write_intervention"))
    for ev in ("REPLY_RD", "REPLY_WR", "REPLY_ID", "INV",
               "WRITEBACK_INT", "WRITEBACK_INV", "UPGRADE_NOTIFY"):
        u[("home", ev)] = _uniform(H, ("any",))

    u[("cache", "REPLY_RD")] = {
        "I": rd_flags,
        **_uniform(valid, tuple(f"match_{f}" for f in rd_flags)
                   + tuple(f"victim_{f}" for f in rd_flags)),
    }
    u[("cache", "FLUSH")] = {
        "I": ("any",), **_uniform(valid, ("match", "victim")),
    }
    u[("cache", "REPLY_WR")] = {
        "I": ("any",), **_uniform(valid, ("match", "victim")),
    }
    u[("cache", "FLUSH_INVACK")] = {
        "I": ("any",), **_uniform(valid, ("match", "victim")),
    }
    u[("cache", "REPLY_ID")] = _uniform(C, ("match", "other"))
    u[("cache", "INV")] = _uniform(C, ("match", "other"))
    u[("cache", "WRITEBACK_INT")] = {
        **_uniform(wbint_resp,
                   ("match_second_other", "match_second_home", "other")),
        **_uniform(tuple(s for s in C if s not in wbint_resp), ("any",)),
    }
    u[("cache", "WRITEBACK_INV")] = {
        **_uniform(("M", "E"),
                   ("match_second_other", "match_second_home", "other")),
        **_uniform(tuple(s for s in C if s not in ("M", "E")), ("any",)),
    }
    u[("cache", "UPGRADE_NOTIFY")] = {
        **_uniform(notify_states,
                   ("match_from_home", "match_not_home", "other")),
        **_uniform(tuple(s for s in C if s not in notify_states), ("any",)),
    }
    u[("cache", "EVICT_SHARED")] = _uniform(C, ("any",))
    u[("cache", "INSTR_R")] = {
        **_uniform(valid, ("hit", "miss_victim")), "I": ("miss",),
    }
    u[("cache", "INSTR_W")] = {
        **_uniform(valid, ("hit", "miss_victim")), "I": ("miss",),
    }
    for ev in ("READ_REQUEST", "WRITE_REQUEST", "UPGRADE",
               "EVICT_MODIFIED", "NACK"):
        u[("cache", ev)] = _uniform(C, ("any",))
    return u


# ---------------------------------------------------------------------------
# MOESI rows: the OWNED state keeps dirty data cache-resident after a
# read intervention — the owner answers reads with a cache-to-cache
# FLUSH (requester only; memory stays stale) and the home tracks it in
# the SO directory state's owner pointer.
# ---------------------------------------------------------------------------

def _moesi_rows(sem: Semantics) -> Tuple[List[Row], List[Unreachable]]:
    rows: List[Row] = []
    unreachable: List[Unreachable] = []
    nack = sem.intervention_miss_policy == "nack"
    eager = sem.eager_write_request_memory

    def home(state, event, case, next_state=None, **kw):
        rows.append(Row("home", state, event, case,
                        next_state if next_state is not None else state, **kw))

    def cache(state, event, case, next_state=None, **kw):
        rows.append(Row("cache", state, event, case,
                        next_state if next_state is not None else state, **kw))

    valid = ("M", "E", "S", "O")

    # ---- home: READ_REQUEST ----
    home("U", "READ_REQUEST", "any", "EM", sharers="requester",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="excl"),))
    home("S", "READ_REQUEST", "any", "S", sharers="+requester",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="shared"),))
    home("EM", "READ_REQUEST", "owner_is_requester", "EM", sharers="same",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="excl"),),
         note="owner re-requesting after silent loss")
    home("EM", "READ_REQUEST", "owner_is_other", "SO", sharers="+requester",
         owner="owner",
         emits=(Emit("WRITEBACK_INT", "owner", second="requester"),),
         note="owner keeps the dirty line as OWNED; home tracks it in SO")
    home("SO", "READ_REQUEST", "owner_is_other", "SO", sharers="+requester",
         owner="same",
         emits=(Emit("WRITEBACK_INT", "tracked_owner", second="requester"),),
         note="owner serves every read cache-to-cache while SO")
    home("SO", "READ_REQUEST", "owner_is_requester", "S",
         sharers="+requester", owner="none",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="shared"),),
         note="owner lost its line (eviction in flight): demote to clean-"
              "shared; the in-flight EVICT_MODIFIED updates memory as a "
              "stale eviction")

    # ---- home: WRITE_REQUEST ----
    home("U", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("REPLY_WR", "requester"),))
    home("S", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("REPLY_ID", "requester", sharers="others"),))
    home("EM", "WRITE_REQUEST", "owner_is_requester", "EM", sharers="same",
         writes_memory=eager,
         emits=(Emit("REPLY_WR", "requester"),))
    home("EM", "WRITE_REQUEST", "owner_is_other", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("WRITEBACK_INV", "owner", second="requester"),))
    home("SO", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager, owner="none",
         emits=(Emit("REPLY_ID", "requester", sharers="others"),),
         note="writer invalidates everyone incl. the old owner")

    # ---- home: UPGRADE ----
    home("S", "UPGRADE", "any", "EM", sharers="requester",
         emits=(Emit("REPLY_ID", "requester", sharers="others"),))
    home("SO", "UPGRADE", "any", "EM", sharers="requester", owner="none",
         emits=(Emit("REPLY_ID", "requester", sharers="others"),),
         note="write hit on OWNED upgrades in place; old owner tracking "
              "dissolves")
    for st in ("U", "EM"):
        home(st, "UPGRADE", "any", "EM", sharers="requester",
             emits=(Emit("REPLY_ID", "requester", sharers="none"),),
             note="directory lost track fallback")

    # ---- home: EVICT_SHARED ----
    home("U", "EVICT_SHARED", "any", drop=_DROP_STALE_EVICT)
    home("S", "EVICT_SHARED", "sender_only_sharer", "U", sharers="empty")
    home("S", "EVICT_SHARED", "two_sharers", "EM", sharers="-sender",
         emits=(Emit("UPGRADE_NOTIFY", "survivor"),),
         note="last survivor silently upgraded S->E")
    home("S", "EVICT_SHARED", "many_sharers", "S", sharers="-sender")
    home("S", "EVICT_SHARED", "sender_not_sharer", drop=_DROP_STALE_EVICT)
    home("EM", "EVICT_SHARED", "sender_is_owner", "U", sharers="empty")
    home("EM", "EVICT_SHARED", "sender_not_owner", drop=_DROP_STALE_EVICT)
    home("SO", "EVICT_SHARED", "none_left", "U", sharers="empty",
         owner="none",
         note="stale tracking collapsed: fall back to uncached")
    home("SO", "EVICT_SHARED", "one_left", "EM", sharers="-sender",
         owner="none",
         emits=(Emit("UPGRADE_NOTIFY", "survivor"),),
         note="only the owner remains: promote OWNED->MODIFIED in place")
    home("SO", "EVICT_SHARED", "several_left", "SO", sharers="-sender",
         owner="same")
    home("SO", "EVICT_SHARED", "sender_not_sharer", drop=_DROP_STALE_EVICT)

    # ---- home: EVICT_MODIFIED ----
    home("U", "EVICT_MODIFIED", "any", writes_memory=True,
         note="stale eviction: memory still updated")
    home("S", "EVICT_MODIFIED", "any", writes_memory=True,
         note="stale eviction: memory still updated, directory untouched")
    home("EM", "EVICT_MODIFIED", "sender_is_owner", "U", sharers="empty",
         writes_memory=True)
    home("EM", "EVICT_MODIFIED", "sender_not_owner", writes_memory=True,
         note="stale eviction: directory untouched")
    home("SO", "EVICT_MODIFIED", "sender_is_owner_last", "U",
         sharers="empty", owner="none", writes_memory=True)
    home("SO", "EVICT_MODIFIED", "sender_is_owner_more", "S",
         sharers="-sender", owner="none", writes_memory=True,
         note="owner wrote back: remaining sharers are clean-shared")
    home("SO", "EVICT_MODIFIED", "sender_not_owner", writes_memory=True,
         note="stale eviction: directory untouched")

    # ---- home: FLUSH / FLUSH_INVACK directory parts ----
    for st in PROTOCOL_HOME_STATES["moesi"]:
        home(st, "FLUSH", "any", writes_memory=True,
             note="home part: commit the flushed value")
        home(st, "FLUSH_INVACK", "any", "EM", sharers="second",
             writes_memory=True, owner="none",
             note="home part: new owner = msg.second_receiver")

    # ---- home: NACK (robust policy only) ----
    if nack:
        for st in PROTOCOL_HOME_STATES["moesi"]:
            home(st, "NACK", "read_intervention", "S", sharers="+second",
                 owner="none",
                 emits=(Emit("REPLY_RD", "second", value="mem",
                             sharers="shared"),),
                 note="re-serve the read from memory; owner tracking is "
                      "stale by construction")
            home(st, "NACK", "write_intervention", "EM", sharers="second",
                 owner="none",
                 emits=(Emit("REPLY_WR", "second"),),
                 note="re-serve the write from memory")
    else:
        unreachable.append(Unreachable(
            "home", "NACK",
            reason="NACK is never emitted under "
                   'Semantics.intervention_miss_policy == "drop"'))

    for ev in ("REPLY_RD", "REPLY_WR", "REPLY_ID", "INV",
               "WRITEBACK_INT", "WRITEBACK_INV", "UPGRADE_NOTIFY"):
        unreachable.append(Unreachable(
            "home", ev,
            reason="addressed to a cache line; a home node receiving it "
                   "uses the cache-role rows for its own cache"))

    # ---- cache: fills ----
    def _victim_emit(state: str) -> Tuple[Emit, ...]:
        if state in ("M", "O"):
            return (Emit("EVICT_MODIFIED", "victim_home", value="line"),)
        return (Emit("EVICT_SHARED", "victim_home"),)

    cache("I", "REPLY_RD", "excl", "E", value_src="msg", clears_waiting=True)
    cache("I", "REPLY_RD", "shared", "S", value_src="msg",
          clears_waiting=True)
    for st in valid:
        cache(st, "REPLY_RD", "match_excl", "E", value_src="msg",
              clears_waiting=True)
        cache(st, "REPLY_RD", "match_shared", "S", value_src="msg",
              clears_waiting=True)
        cache(st, "REPLY_RD", "victim_excl", "E", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))
        cache(st, "REPLY_RD", "victim_shared", "S", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))

    cache("I", "FLUSH", "any", "S", value_src="msg", clears_waiting=True)
    for st in valid:
        cache(st, "FLUSH", "match", "S", value_src="msg",
              clears_waiting=True)
        cache(st, "FLUSH", "victim", "S", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))

    cache("I", "REPLY_WR", "any", "M", value_src="pending",
          clears_waiting=True)
    fia_src = "msg" if sem.flush_invack_fills_old_value else "pending"
    cache("I", "FLUSH_INVACK", "any", "M", value_src=fia_src,
          clears_waiting=True)
    for st in valid:
        cache(st, "REPLY_WR", "match", "M", value_src="pending",
              clears_waiting=True)
        cache(st, "FLUSH_INVACK", "match", "M", value_src=fia_src,
              clears_waiting=True)
        for ev in ("REPLY_WR", "FLUSH_INVACK"):
            unreachable.append(Unreachable(
                "cache", ev, st, "victim",
                reason="engine asserts the slot is ours or invalid: the "
                       "reply can only follow our own request, whose "
                       "placeholder fill owns the slot"))

    # ---- cache: REPLY_ID ----
    for st in ("I", "E", "S", "O"):
        cache(st, "REPLY_ID", "match", "M", value_src="pending",
              clears_waiting=True,
              emits=(Emit("INV", "sharers"),))
    cache("M", "REPLY_ID", "match", "M", clears_waiting=True,
          emits=(Emit("INV", "sharers"),),
          note="write already applied locally on the upgrade-hit path")
    for st in PROTOCOL_CACHE_STATES["moesi"]:
        cache(st, "REPLY_ID", "other", clears_waiting=True,
              note="line replaced while waiting: INV fan-out suppressed")

    # ---- cache: INV ----
    for st in ("E", "S", "O"):
        cache(st, "INV", "match", "I")
    cache("M", "INV", "match",
          drop="stale INV: our write raced ahead and the line is "
               "already M")
    cache("I", "INV", "match",
          drop="stale INV: line already invalid; invalidating again "
               "is idempotent")
    for st in PROTOCOL_CACHE_STATES["moesi"]:
        cache(st, "INV", "other",
              drop="stale INV: line already replaced by another address")

    # ---- cache: interventions ----
    def _miss_row(st, event, case, wr: bool):
        if nack:
            cache(st, event, case,
                  emits=(Emit("NACK", "home", sharers="wr" if wr else "rd",
                              second="fwd"),),
                  note="stale intervention bounced to home")
        else:
            cache(st, event, case, drop=_DROP_POLICY,
                  note="stale intervention silently dropped: the "
                       "requester hangs")

    for st in ("M", "E", "O"):
        cache(st, "WRITEBACK_INT", "match_second_other", "O",
              emits=(Emit("FLUSH", "second", value="line", second="fwd"),),
              note="cache-to-cache fill; memory stays stale (OWNED keeps "
                   "the dirty copy)")
        cache(st, "WRITEBACK_INT", "match_second_home", "O",
              emits=(Emit("FLUSH", "second", value="line", second="fwd"),),
              note="requester is the home: single FLUSH (its home part "
                   "also freshens memory)")
        _miss_row(st, "WRITEBACK_INT", "other", wr=False)
    for st in ("S", "I"):
        _miss_row(st, "WRITEBACK_INT", "any", wr=False)

    for st in ("M", "E"):
        cache(st, "WRITEBACK_INV", "match_second_other", "I",
              emits=(Emit("FLUSH_INVACK", "home", value="line",
                          second="fwd"),
                     Emit("FLUSH_INVACK", "second", value="line",
                          second="fwd")))
        cache(st, "WRITEBACK_INV", "match_second_home", "I",
              emits=(Emit("FLUSH_INVACK", "home", value="line",
                          second="fwd"),),
              note="requester is the home: single FLUSH_INVACK")
        _miss_row(st, "WRITEBACK_INV", "other", wr=True)
    for st in ("S", "I", "O"):
        _miss_row(st, "WRITEBACK_INV", "any", wr=True)

    # ---- cache: survivor upgrade notification ----
    cache("S", "UPGRADE_NOTIFY", "match_from_home", "E",
          note="last survivor: silent S->E upgrade")
    cache("S", "UPGRADE_NOTIFY", "match_not_home",
          drop="notify must come from the home (spoof guard)")
    cache("S", "UPGRADE_NOTIFY", "other",
          drop="stale notify: line already replaced")
    cache("O", "UPGRADE_NOTIFY", "match_from_home", "M",
          note="sole survivor owns the only copy: promote OWNED->MODIFIED")
    cache("O", "UPGRADE_NOTIFY", "match_not_home",
          drop="notify must come from the home (spoof guard)")
    cache("O", "UPGRADE_NOTIFY", "other",
          drop="stale notify: line already replaced")
    for st in ("M", "E", "I"):
        cache(st, "UPGRADE_NOTIFY", "any",
              drop="stale notify: line no longer shared")
    unreachable.append(Unreachable(
        "cache", "EVICT_SHARED",
        reason="the survivor notify is the distinct UPGRADE_NOTIFY type; "
               "EVICT_SHARED is only ever addressed to the home"))

    for ev in ("READ_REQUEST", "WRITE_REQUEST", "UPGRADE",
               "EVICT_MODIFIED"):
        unreachable.append(Unreachable(
            "cache", ev,
            reason="requests and evictions are addressed to the home "
                   "directory; the home's own cache is untouched"))
    unreachable.append(Unreachable(
        "cache", "NACK",
        reason="NACK is addressed to the home directory (re-serve path)"))

    # ---- cache: instruction issue ----
    for st in valid:
        cache(st, "INSTR_R", "hit", note="read hit: no traffic")
        cache(st, "INSTR_R", "miss_victim", "I", value_src="placeholder",
              sets_waiting=True,
              emits=_victim_emit(st) + (Emit("READ_REQUEST", "home"),))
    cache("I", "INSTR_R", "miss", "I", value_src="placeholder",
          sets_waiting=True,
          emits=(Emit("READ_REQUEST", "home"),))

    cache("M", "INSTR_W", "hit", "M", value_src="instr",
          note="write hit on M: local update")
    cache("E", "INSTR_W", "hit", "M", value_src="instr",
          note="silent E->M upgrade")
    for st in ("S", "O"):
        cache(st, "INSTR_W", "hit", "M", value_src="instr",
              sets_waiting=True,
              emits=(Emit("UPGRADE", "home"),),
              note="write applied locally before REPLY_ID")
    for st in valid:
        cache(st, "INSTR_W", "miss_victim", "I", value_src="placeholder",
              sets_waiting=True,
              emits=_victim_emit(st)
              + (Emit("WRITE_REQUEST", "home", value="instr"),))
    cache("I", "INSTR_W", "miss", "I", value_src="placeholder",
          sets_waiting=True,
          emits=(Emit("WRITE_REQUEST", "home", value="instr"),))

    return rows, unreachable


# ---------------------------------------------------------------------------
# MESIF rows: the FORWARD state is a single clean designated responder —
# reads in dir-S are served cache-to-cache by the forwarder (tracked in
# the home's owner pointer), and the forwarder role migrates to the most
# recent reader.  Memory is never stale (F is clean).
# ---------------------------------------------------------------------------

def _mesif_rows(sem: Semantics) -> Tuple[List[Row], List[Unreachable]]:
    rows: List[Row] = []
    unreachable: List[Unreachable] = []
    nack = sem.intervention_miss_policy == "nack"
    eager = sem.eager_write_request_memory

    def home(state, event, case, next_state=None, **kw):
        rows.append(Row("home", state, event, case,
                        next_state if next_state is not None else state, **kw))

    def cache(state, event, case, next_state=None, **kw):
        rows.append(Row("cache", state, event, case,
                        next_state if next_state is not None else state, **kw))

    valid = ("M", "E", "S", "F")

    # ---- home: READ_REQUEST ----
    home("U", "READ_REQUEST", "any", "EM", sharers="requester",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="excl"),))
    home("S", "READ_REQUEST", "no_fwd", "S", sharers="+requester",
         owner="requester",
         emits=(Emit("REPLY_RD", "requester", value="mem",
                     sharers="fwdf"),),
         note="no live forwarder: serve from memory, reader becomes F")
    home("S", "READ_REQUEST", "fwd_is_requester", "S", sharers="+requester",
         owner="same",
         emits=(Emit("REPLY_RD", "requester", value="mem",
                     sharers="fwdf"),),
         note="forwarder re-requesting after silent loss")
    home("S", "READ_REQUEST", "fwd_other", "S", sharers="+requester",
         owner="requester",
         emits=(Emit("WRITEBACK_INT", "tracked_owner",
                     second="requester"),),
         note="forwarder serves cache-to-cache; the newest reader "
              "becomes the forwarder")
    home("EM", "READ_REQUEST", "owner_is_requester", "EM", sharers="same",
         emits=(Emit("REPLY_RD", "requester", value="mem", sharers="excl"),),
         note="owner re-requesting after silent loss")
    home("EM", "READ_REQUEST", "owner_is_other", "S", sharers="+requester",
         owner="requester",
         emits=(Emit("WRITEBACK_INT", "owner", second="requester"),),
         note="optimistic pre-flush S transition; reader will fill F")

    # ---- home: WRITE_REQUEST ----
    home("U", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("REPLY_WR", "requester"),))
    home("S", "WRITE_REQUEST", "any", "EM", sharers="requester",
         writes_memory=eager, owner="none",
         emits=(Emit("REPLY_ID", "requester", sharers="others"),))
    home("EM", "WRITE_REQUEST", "owner_is_requester", "EM", sharers="same",
         writes_memory=eager,
         emits=(Emit("REPLY_WR", "requester"),))
    home("EM", "WRITE_REQUEST", "owner_is_other", "EM", sharers="requester",
         writes_memory=eager,
         emits=(Emit("WRITEBACK_INV", "owner", second="requester"),))

    # ---- home: UPGRADE ----
    home("S", "UPGRADE", "any", "EM", sharers="requester", owner="none",
         emits=(Emit("REPLY_ID", "requester", sharers="others"),))
    for st in ("U", "EM"):
        home(st, "UPGRADE", "any", "EM", sharers="requester",
             emits=(Emit("REPLY_ID", "requester", sharers="none"),),
             note="directory lost track fallback")

    # ---- home: EVICT_SHARED ----
    home("U", "EVICT_SHARED", "any", drop=_DROP_STALE_EVICT)
    home("S", "EVICT_SHARED", "sender_only_sharer", "U", sharers="empty",
         owner="none")
    home("S", "EVICT_SHARED", "two_sharers", "EM", sharers="-sender",
         owner="none",
         emits=(Emit("UPGRADE_NOTIFY", "survivor"),),
         note="last survivor silently upgraded to E (F included)")
    home("S", "EVICT_SHARED", "many_sharers", "S", sharers="-sender",
         owner="drop_sender",
         note="an evicting forwarder abdicates; next reader re-seeds F")
    home("S", "EVICT_SHARED", "sender_not_sharer", drop=_DROP_STALE_EVICT)
    home("EM", "EVICT_SHARED", "sender_is_owner", "U", sharers="empty")
    home("EM", "EVICT_SHARED", "sender_not_owner", drop=_DROP_STALE_EVICT)

    # ---- home: EVICT_MODIFIED ----
    home("U", "EVICT_MODIFIED", "any", writes_memory=True,
         note="stale eviction: memory still updated")
    home("S", "EVICT_MODIFIED", "any", writes_memory=True,
         note="stale eviction: memory still updated, directory untouched")
    home("EM", "EVICT_MODIFIED", "sender_is_owner", "U", sharers="empty",
         writes_memory=True)
    home("EM", "EVICT_MODIFIED", "sender_not_owner", writes_memory=True,
         note="stale eviction: directory untouched")

    # ---- home: FLUSH / FLUSH_INVACK directory parts ----
    for st in HOME_STATES:
        home(st, "FLUSH", "any", writes_memory=True,
             note="home part: commit the flushed value")
        home(st, "FLUSH_INVACK", "any", "EM", sharers="second",
             writes_memory=True, owner="none",
             note="home part: new owner = msg.second_receiver")

    # ---- home: NACK (robust policy only) ----
    if nack:
        for st in ("S", "EM"):
            home(st, "NACK", "read_intervention", "S", sharers="+second",
                 owner="second",
                 emits=(Emit("REPLY_RD", "second", value="mem",
                             sharers="fwdf"),),
                 note="re-serve the read from memory; reader becomes F")
            home(st, "NACK", "write_intervention", "EM", sharers="second",
                 owner="none",
                 emits=(Emit("REPLY_WR", "second"),),
                 note="re-serve the write from memory")
        unreachable.append(Unreachable(
            "home", "NACK", "U",
            reason="the home cannot be U while an intervention it "
                   "initiated is outstanding (it moved to S/EM when "
                   "forwarding the WRITEBACK_*)"))
    else:
        unreachable.append(Unreachable(
            "home", "NACK",
            reason="NACK is never emitted under "
                   'Semantics.intervention_miss_policy == "drop"'))

    for ev in ("REPLY_RD", "REPLY_WR", "REPLY_ID", "INV",
               "WRITEBACK_INT", "WRITEBACK_INV", "UPGRADE_NOTIFY"):
        unreachable.append(Unreachable(
            "home", ev,
            reason="addressed to a cache line; a home node receiving it "
                   "uses the cache-role rows for its own cache"))

    # ---- cache: fills ----
    def _victim_emit(state: str) -> Tuple[Emit, ...]:
        if state == "M":
            return (Emit("EVICT_MODIFIED", "victim_home", value="line"),)
        return (Emit("EVICT_SHARED", "victim_home"),)

    cache("I", "REPLY_RD", "excl", "E", value_src="msg", clears_waiting=True)
    cache("I", "REPLY_RD", "fwd", "F", value_src="msg", clears_waiting=True)
    for st in valid:
        cache(st, "REPLY_RD", "match_excl", "E", value_src="msg",
              clears_waiting=True)
        cache(st, "REPLY_RD", "match_fwd", "F", value_src="msg",
              clears_waiting=True)
        cache(st, "REPLY_RD", "victim_excl", "E", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))
        cache(st, "REPLY_RD", "victim_fwd", "F", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))

    cache("I", "FLUSH", "any", "F", value_src="msg", clears_waiting=True)
    for st in valid:
        cache(st, "FLUSH", "match", "F", value_src="msg",
              clears_waiting=True)
        cache(st, "FLUSH", "victim", "F", value_src="msg",
              clears_waiting=True, emits=_victim_emit(st))

    cache("I", "REPLY_WR", "any", "M", value_src="pending",
          clears_waiting=True)
    fia_src = "msg" if sem.flush_invack_fills_old_value else "pending"
    cache("I", "FLUSH_INVACK", "any", "M", value_src=fia_src,
          clears_waiting=True)
    for st in valid:
        cache(st, "REPLY_WR", "match", "M", value_src="pending",
              clears_waiting=True)
        cache(st, "FLUSH_INVACK", "match", "M", value_src=fia_src,
              clears_waiting=True)
        for ev in ("REPLY_WR", "FLUSH_INVACK"):
            unreachable.append(Unreachable(
                "cache", ev, st, "victim",
                reason="engine asserts the slot is ours or invalid: the "
                       "reply can only follow our own request, whose "
                       "placeholder fill owns the slot"))

    # ---- cache: REPLY_ID ----
    for st in ("I", "E", "S", "F"):
        cache(st, "REPLY_ID", "match", "M", value_src="pending",
              clears_waiting=True,
              emits=(Emit("INV", "sharers"),))
    cache("M", "REPLY_ID", "match", "M", clears_waiting=True,
          emits=(Emit("INV", "sharers"),),
          note="write already applied locally on the upgrade-hit path")
    for st in PROTOCOL_CACHE_STATES["mesif"]:
        cache(st, "REPLY_ID", "other", clears_waiting=True,
              note="line replaced while waiting: INV fan-out suppressed")

    # ---- cache: INV ----
    for st in ("E", "S", "F"):
        cache(st, "INV", "match", "I")
    cache("M", "INV", "match",
          drop="stale INV: our write raced ahead and the line is "
               "already M")
    cache("I", "INV", "match",
          drop="stale INV: line already invalid; invalidating again "
               "is idempotent")
    for st in PROTOCOL_CACHE_STATES["mesif"]:
        cache(st, "INV", "other",
              drop="stale INV: line already replaced by another address")

    # ---- cache: interventions ----
    def _miss_row(st, event, case, wr: bool):
        if nack:
            cache(st, event, case,
                  emits=(Emit("NACK", "home", sharers="wr" if wr else "rd",
                              second="fwd"),),
                  note="stale intervention bounced to home")
        else:
            cache(st, event, case, drop=_DROP_POLICY,
                  note="stale intervention silently dropped: the "
                       "requester hangs")

    for st in ("M", "E"):
        cache(st, "WRITEBACK_INT", "match_second_other", "S",
              emits=(Emit("FLUSH", "home", value="line", second="fwd"),
                     Emit("FLUSH", "second", value="line", second="fwd")))
        cache(st, "WRITEBACK_INT", "match_second_home", "S",
              emits=(Emit("FLUSH", "home", value="line", second="fwd"),),
              note="requester is the home: single FLUSH")
        _miss_row(st, "WRITEBACK_INT", "other", wr=False)
    cache("F", "WRITEBACK_INT", "match_second_other", "S",
          emits=(Emit("FLUSH", "second", value="line", second="fwd"),),
          note="clean cache-to-cache forward: memory is already current, "
               "home copy unnecessary; forwarder demotes to S")
    cache("F", "WRITEBACK_INT", "match_second_home", "S",
          emits=(Emit("FLUSH", "second", value="line", second="fwd"),),
          note="requester is the home: single FLUSH")
    _miss_row("F", "WRITEBACK_INT", "other", wr=False)
    for st in ("S", "I"):
        _miss_row(st, "WRITEBACK_INT", "any", wr=False)

    for st in ("M", "E"):
        cache(st, "WRITEBACK_INV", "match_second_other", "I",
              emits=(Emit("FLUSH_INVACK", "home", value="line",
                          second="fwd"),
                     Emit("FLUSH_INVACK", "second", value="line",
                          second="fwd")))
        cache(st, "WRITEBACK_INV", "match_second_home", "I",
              emits=(Emit("FLUSH_INVACK", "home", value="line",
                          second="fwd"),),
              note="requester is the home: single FLUSH_INVACK")
        _miss_row(st, "WRITEBACK_INV", "other", wr=True)
    for st in ("S", "I", "F"):
        _miss_row(st, "WRITEBACK_INV", "any", wr=True)

    # ---- cache: survivor upgrade notification ----
    for st in ("S", "F"):
        cache(st, "UPGRADE_NOTIFY", "match_from_home", "E",
              note="last survivor: silent upgrade to E")
        cache(st, "UPGRADE_NOTIFY", "match_not_home",
              drop="notify must come from the home (spoof guard)")
        cache(st, "UPGRADE_NOTIFY", "other",
              drop="stale notify: line already replaced")
    for st in ("M", "E", "I"):
        cache(st, "UPGRADE_NOTIFY", "any",
              drop="stale notify: line no longer shared")
    unreachable.append(Unreachable(
        "cache", "EVICT_SHARED",
        reason="the survivor notify is the distinct UPGRADE_NOTIFY type; "
               "EVICT_SHARED is only ever addressed to the home"))

    for ev in ("READ_REQUEST", "WRITE_REQUEST", "UPGRADE",
               "EVICT_MODIFIED"):
        unreachable.append(Unreachable(
            "cache", ev,
            reason="requests and evictions are addressed to the home "
                   "directory; the home's own cache is untouched"))
    unreachable.append(Unreachable(
        "cache", "NACK",
        reason="NACK is addressed to the home directory (re-serve path)"))

    # ---- cache: instruction issue ----
    for st in valid:
        cache(st, "INSTR_R", "hit", note="read hit: no traffic")
        cache(st, "INSTR_R", "miss_victim", "I", value_src="placeholder",
              sets_waiting=True,
              emits=_victim_emit(st) + (Emit("READ_REQUEST", "home"),))
    cache("I", "INSTR_R", "miss", "I", value_src="placeholder",
          sets_waiting=True,
          emits=(Emit("READ_REQUEST", "home"),))

    cache("M", "INSTR_W", "hit", "M", value_src="instr",
          note="write hit on M: local update")
    cache("E", "INSTR_W", "hit", "M", value_src="instr",
          note="silent E->M upgrade")
    for st in ("S", "F"):
        cache(st, "INSTR_W", "hit", "M", value_src="instr",
              sets_waiting=True,
              emits=(Emit("UPGRADE", "home"),),
              note="write applied locally before REPLY_ID")
    for st in valid:
        cache(st, "INSTR_W", "miss_victim", "I", value_src="placeholder",
              sets_waiting=True,
              emits=_victim_emit(st)
              + (Emit("WRITE_REQUEST", "home", value="instr"),))
    cache("I", "INSTR_W", "miss", "I", value_src="placeholder",
          sets_waiting=True,
          emits=(Emit("WRITE_REQUEST", "home", value="instr"),))

    return rows, unreachable
