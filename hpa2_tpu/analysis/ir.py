"""Canonical IR traversal for the compiled-program contracts.

One walker to rule out six: before ISSUE-17 every jaxpr pin in the
repo (tests/test_elision.py, test_node_sharded_pallas.py,
test_data_sharded_pallas.py, test_vmem_budget.py, test_occupancy.py)
carried its own copy of the subjaxpr recursion.  This module is now
the only traversal — everything that inspects a lowered program
(primitive census, collective census, while/cond closure extraction,
HLO text probes, jit-cache counts) goes through here, so ROADMAP's
lowering churn (in-kernel DMA exchange, per-block jumps) changes one
walker, not six.

Everything is pure inspection: no tracing happens here (callers hand
in `jax.make_jaxpr(...)` output or compiled-HLO text), so the module
imports without jax.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Iterator, List, Sequence, Tuple

# -- jaxpr layer ------------------------------------------------------

#: collective families, keyed the way ``exchange.plan_collectives``
#: keys its schedule counts.  psum lowers to psum2/psum_invariant on
#: recent jax; the gather family is the banned "gather-the-world"
#: delivery relapse.
PSUM_PRIMS = ("psum", "psum2", "psum_invariant")
GATHER_PRIMS = ("all_gather", "all_gather_invariant")
COLLECTIVE_PRIMS = (
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter",
)


def unwrap(jaxpr):
    """Accept a ClosedJaxpr or a Jaxpr; return the Jaxpr."""
    return getattr(jaxpr, "jaxpr", jaxpr)


def subvalues(eqn) -> Iterator[object]:
    """Yield the sub-jaxprs carried in an equation's params (pjit /
    while / cond / scan / shard_map / pallas_call / custom_* all stash
    them differently: bare Jaxpr, ClosedJaxpr, or lists of either)."""
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr"):
                yield x.jaxpr
            elif hasattr(x, "eqns"):
                yield x


def find_subjaxprs(jaxpr, prim_name: str) -> List[object]:
    """All sub-jaxprs carried by equations named ``prim_name``,
    searching recursively but NOT descending into the matches
    themselves (a while inside a while body is not re-reported)."""
    jaxpr = unwrap(jaxpr)
    found = []
    for eqn in jaxpr.eqns:
        subs = list(subvalues(eqn))
        if eqn.primitive.name == prim_name:
            found += subs
        else:
            for sub in subs:
                found += find_subjaxprs(sub, prim_name)
    return found


def count_prims(jaxpr, names: Sequence[str]) -> int:
    """Recursive census: equations named in ``names`` at every depth."""
    jaxpr = unwrap(jaxpr)
    n = sum(1 for eqn in jaxpr.eqns if eqn.primitive.name in names)
    for eqn in jaxpr.eqns:
        for sub in subvalues(eqn):
            n += count_prims(sub, names)
    return n


def count_eqns(jaxpr) -> int:
    """Total equation count at every depth — the op-budget metric."""
    jaxpr = unwrap(jaxpr)
    n = len(jaxpr.eqns)
    for eqn in jaxpr.eqns:
        for sub in subvalues(eqn):
            n += count_eqns(sub)
    return n


def top_counts(jaxpr, names: Iterable[str]) -> Dict[str, int]:
    """Per-name census of the TOP LEVEL only — pins structure like
    "exactly one reduce_min and one cond at the loop-body top level"."""
    jaxpr = unwrap(jaxpr)
    return {
        n: sum(1 for e in jaxpr.eqns if e.primitive.name == n)
        for n in names
    }


def prim_paths(jaxpr, names: Sequence[str], limit: int = 6,
               _prefix: str = "") -> List[str]:
    """Human-readable paths to the first ``limit`` occurrences of the
    named primitives — the "path into the jaxpr" half of a drift diff,
    e.g. ``eqns[3]:while > eqns[17]:ppermute``."""
    jaxpr = unwrap(jaxpr)
    out: List[str] = []
    for i, eqn in enumerate(jaxpr.eqns):
        here = f"{_prefix}eqns[{i}]:{eqn.primitive.name}"
        if eqn.primitive.name in names:
            out.append(here)
            if len(out) >= limit:
                return out
        for sub in subvalues(eqn):
            out += prim_paths(sub, names, limit - len(out), here + " > ")
            if len(out) >= limit:
                return out
    return out


def largest_body(jaxpr, prim_name: str = "while"):
    """The biggest sub-jaxpr under equations named ``prim_name`` — a
    while carries [cond, body]; the body is the big one."""
    subs = find_subjaxprs(jaxpr, prim_name)
    if not subs:
        return None
    return max(subs, key=lambda j: len(unwrap(j).eqns))


def collective_counts(bodies: Sequence[object]) -> Dict[str, int]:
    """Collective census keyed like ``exchange.plan_collectives``:
    ppermute / all_to_all exactly as planned, psum folded over its
    lowering aliases, pmax for telemetry, gather == the banned
    family."""
    return {
        "ppermute": sum(count_prims(b, ("ppermute",)) for b in bodies),
        "all_to_all": sum(
            count_prims(b, ("all_to_all",)) for b in bodies
        ),
        "psum": sum(count_prims(b, PSUM_PRIMS) for b in bodies),
        "pmax": sum(count_prims(b, ("pmax",)) for b in bodies),
        "gather": sum(count_prims(b, GATHER_PRIMS) for b in bodies),
    }


def narrow_outvars(jaxpr) -> int:
    """How many of a jaxpr's outputs stay on the narrow packed planes
    (uint8/uint16) — the dtype rule: packed state must leave the cycle
    as narrow as it entered (widening is transient, inside `_widen*`)."""
    jaxpr = unwrap(jaxpr)
    n = 0
    for v in jaxpr.outvars:
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None and str(dt) in ("uint8", "uint16"):
            n += 1
    return n


# -- compiled-HLO layer -----------------------------------------------

HLO_COLLECTIVES = (
    "all-reduce(", "all-gather(", "collective-permute(",
    "all-to-all(", "reduce-scatter(",
)

_HLO_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s+\(")
_HLO_LOOP_ROOT_RE = re.compile(r"(?:condition|body)=%?([\w.\-]+)")
_HLO_REF_RE = re.compile(r"%([\w.\-]+)")


def hlo_computations(text: str) -> Dict[str, List[str]]:
    """Split compiled-HLO text into {computation name: body lines}."""
    comps: Dict[str, List[str]] = {}
    name = None
    for line in text.splitlines():
        m = _HLO_COMP_RE.match(line)
        if m and line.rstrip().endswith("{"):
            name = m.group(1)
            comps[name] = []
        elif name is not None:
            comps[name].append(line)
    return comps


def hlo_loop_closure(comps: Dict[str, List[str]], text: str):
    """Every computation reachable from a while condition/body — the
    SPMD partitioner inlines the cycle loop here, so an op in this
    closure runs once per cycle (or per call), not once per run."""
    seen = set(_HLO_LOOP_ROOT_RE.findall(text)) & set(comps)
    todo = list(seen)
    while todo:
        for line in comps[todo.pop()]:
            for ref in _HLO_REF_RE.findall(line):
                if ref in comps and ref not in seen:
                    seen.add(ref)
                    todo.append(ref)
    return seen


def hlo_loop_collectives(text: str) -> List[Tuple[str, str]]:
    """(computation, line) for every collective inside the transitive
    closure of the compiled while loops.  The final status reduce
    compiles to an all-reduce in ENTRY — outside every loop — which
    this probe deliberately permits."""
    comps = hlo_computations(text)
    closure = hlo_loop_closure(comps, text)
    return [
        (name, line.strip())
        for name in sorted(closure)
        for line in comps[name]
        if any(c in line for c in HLO_COLLECTIVES)
    ]


def hlo_aliased_outputs(text: str) -> int:
    """Donation/aliasing probe: the number of input→output aliases the
    compiler committed to (``input_output_alias={...}`` in the module
    header).  Zero means every donated buffer was silently copied."""
    m = re.search(r"input_output_alias=\{([^}]*(?:\}[^}]*)*?)\}\s*[,)]",
                  text)
    if m is None:
        m = re.search(r"input_output_alias=\{(.*)$", text, re.MULTILINE)
        if m is None:
            return 0
    return len(re.findall(r"\(\s*\d+\s*,", m.group(1)))


# -- jit-cache layer --------------------------------------------------

def cache_size(fn) -> int:
    """Compiled-entry count of a jitted callable, via the same
    ``_cache_size`` probe the serving sessions' zero-recompile guards
    use; -1 if the callable exposes no cache probe."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return -1
    return int(probe())
