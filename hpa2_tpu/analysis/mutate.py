"""Seeded table mutations: the analyzer's self-test.

A static analyzer that has never been seen to *fail* proves nothing.
Each mutation below injects one realistic protocol bug into the
declarative table — the kinds of defect the reference implementation
actually shipped (silently unhandled pairs, lost wakeups, wrong fill
sources) — and the self-test asserts the analyzer catches every one,
either statically (``run_static_checks`` errors) or by the spec
equivalence diff.

CLI: ``python -m hpa2_tpu.analysis mutation-test``.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, List, NamedTuple, Optional, Tuple

from hpa2_tpu.config import Semantics
from hpa2_tpu.analysis.table import Emit, Row, TransitionTable, build_table
from hpa2_tpu.analysis.checks import run_static_checks
from hpa2_tpu.analysis.extract import diff_backend


class Mutation(NamedTuple):
    name: str
    description: str
    apply: Callable[[TransitionTable], TransitionTable]


def _swap(table: TransitionTable, key, **changes) -> TransitionTable:
    old = table.row(*key)
    return table.replaced(old, dataclasses.replace(old, **changes))


def _delete(table: TransitionTable, key) -> TransitionTable:
    old = table.row(*key)
    return dataclasses.replace(
        table, rows=[r for r in table.rows if r is not old]
    )


def _append(table: TransitionTable, row: Row) -> TransitionTable:
    return dataclasses.replace(table, rows=list(table.rows) + [row])


MUTATIONS: List[Mutation] = [
    Mutation(
        "swap-next-state",
        "first read of an uncached block grants S instead of EM — the "
        "directory forgets it has an exclusive owner",
        lambda t: _swap(t, ("home", "U", "READ_REQUEST", "any"),
                        next_state="S"),
    ),
    Mutation(
        "delete-row",
        "drop the cache-side INV/match row — invalidations are silently "
        "ignored and stale lines survive",
        lambda t: _delete(t, ("cache", "S", "INV", "match")),
    ),
    Mutation(
        "drop-emission",
        "home handles a READ_REQUEST but never sends the REPLY_RD — "
        "the requester waits forever",
        lambda t: _swap(t, ("home", "U", "READ_REQUEST", "any"), emits=()),
    ),
    Mutation(
        "remove-drop-citation",
        "strip the policy citation from the stale-INV drop — the drop "
        "becomes silent",
        lambda t: _swap(t, ("cache", "S", "INV", "other"), drop=""),
    ),
    Mutation(
        "duplicate-case",
        "claim the same guard-case twice with different outcomes — the "
        "transition relation becomes ambiguous",
        lambda t: _append(
            t, dataclasses.replace(
                t.row("cache", "I", "REPLY_WR", "any"), next_state="E")),
    ),
    Mutation(
        "wrong-receiver",
        "send the read reply to the current owner instead of the "
        "requester",
        lambda t: _swap(t, ("home", "U", "READ_REQUEST", "any"),
                        emits=(Emit("REPLY_RD", "owner", value="mem",
                                    sharers="excl"),)),
    ),
    Mutation(
        "corrupt-sharers",
        "FLUSH_INVACK leaves the directory EM with an empty sharer set "
        "— an owned block with no owner",
        lambda t: _swap(t, ("home", "EM", "FLUSH_INVACK", "any"),
                        sharers="empty"),
    ),
    Mutation(
        "premature-modified",
        "an exclusive read fill installs M instead of E — a clean line "
        "the directory will now ask to flush",
        lambda t: _swap(t, ("cache", "I", "REPLY_RD", "excl"),
                        next_state="M"),
    ),
    Mutation(
        "phantom-emission",
        "the write fill also broadcasts a spurious INV",
        lambda t: _swap(
            t, ("cache", "I", "REPLY_WR", "any"),
            emits=(Emit("INV", "home"),)),
    ),
    Mutation(
        "wrong-fill-source",
        "REPLY_WR fills the line from the (stale) message payload "
        "instead of the requester's pending write",
        lambda t: _swap(t, ("cache", "I", "REPLY_WR", "any"),
                        value_src="msg"),
    ),
    Mutation(
        "contradict-unreachable",
        "add a row in a cell explicitly declared unreachable",
        lambda t: _append(
            t, Row("home", "U", "NACK", "read_intervention",
                   next_state="U")),
    ),
    Mutation(
        "lost-wakeup",
        "REPLY_WR fills the line but never clears the waiting flag — "
        "the classic lost-wakeup hang",
        lambda t: _swap(t, ("cache", "I", "REPLY_WR", "any"),
                        clears_waiting=False),
    ),
]


@dataclasses.dataclass
class MutationResult:
    name: str
    caught: bool
    caught_by: str       # 'static' | 'spec-diff' | ''
    evidence: List[str]  # first few findings / diff lines


def run_mutation(
    mut: Mutation, sem: Semantics, protocol: str = "mesi"
) -> MutationResult:
    table = mut.apply(build_table(sem, protocol))
    static_errors = [
        str(f) for f in run_static_checks(table) if f.severity == "error"
    ]
    if static_errors:
        return MutationResult(mut.name, True, "static", static_errors[:3])
    # statically plausible table — the behavioral diff must object
    mutated_keys = _changed_keys(build_table(sem, protocol), table)
    rows = [r for r in table.rows
            if r.key in mutated_keys and not table.is_unreachable(*r.key)]
    diffs = diff_backend(table, "spec", rows=rows or None)
    if diffs:
        return MutationResult(mut.name, True, "spec-diff", diffs[:3])
    return MutationResult(mut.name, False, "", [])


def _changed_keys(base: TransitionTable, mutated: TransitionTable):
    base_rows = {r.key: r for r in base.rows}
    return {
        r.key for r in mutated.rows
        if base_rows.get(r.key) != r
    }


def run_all_mutations(
    sem: Semantics = None, protocol: str = "mesi"
) -> List[MutationResult]:
    sem = sem if sem is not None else Semantics()
    return [run_mutation(m, sem, protocol) for m in MUTATIONS]


# ---------------------------------------------------------------------------
# seeded cross-protocol fuzzing.  The curated set above encodes twelve
# KNOWN defect shapes; the fuzzer samples the space between them: it
# draws a random probeable row from any protocol's table and applies a
# random surgical corruption chosen to be semantically visible (the
# generators reject identity rewrites, e.g. a sharer update whose
# resolution equals the original under the probe scenario).  Every
# sample must be caught — statically, by the spec probe diff, or by
# the JAX probe diff — so the assertion is the same as the curated
# set's, over hundreds of machine-chosen bugs per protocol.
# ---------------------------------------------------------------------------

_FUZZ_EMIT_TYPES = (
    "REPLY_RD", "REPLY_WR", "REPLY_ID", "INV", "WRITEBACK_INT",
    "WRITEBACK_INV", "UPGRADE_NOTIFY", "FLUSH", "FLUSH_INVACK",
)
_FUZZ_FILLS = ("msg", "pending", "instr", "placeholder")
_FUZZ_SHARERS = ("empty", "requester", "+requester", "-sender", "same")
_FUZZ_OWNERS = ("none", "requester", "same", "second")


def _fuzz_next_state(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    states = (table.home_states if row.role == "home"
              else table.cache_states)
    choices = [s for s in states if s != row.next_state]
    if row.drop or not choices:
        return None
    return dataclasses.replace(row, next_state=rng.choice(choices))


def _fuzz_drop_emits(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    if not row.emits:
        return None
    return dataclasses.replace(row, emits=())


def _fuzz_emit_type(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    if not row.emits:
        return None
    i = rng.randrange(len(row.emits))
    e = row.emits[i]
    new_type = rng.choice([t for t in _FUZZ_EMIT_TYPES if t != e.type])
    emits = list(row.emits)
    emits[i] = dataclasses.replace(e, type=new_type)
    return dataclasses.replace(row, emits=tuple(emits))


def _fuzz_fill_source(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    if row.role != "cache" or not row.value_src:
        return None
    return dataclasses.replace(
        row, value_src=rng.choice(
            [f for f in _FUZZ_FILLS if f != row.value_src]))


def _fuzz_lost_wakeup(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    if not row.clears_waiting:
        return None
    return dataclasses.replace(row, clears_waiting=False)


def _fuzz_forget_memory(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    if not row.writes_memory:
        return None
    return dataclasses.replace(row, writes_memory=False)


def _fuzz_sharers(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    from hpa2_tpu.analysis.extract import _resolve_sharers, scenario_for

    if row.role != "home" or row.drop:
        return None
    scn = scenario_for(row, table.protocol)
    old = _resolve_sharers(row.sharers, scn.dir_sharers, scn.msg_second)
    choices = [
        s for s in _FUZZ_SHARERS
        if _resolve_sharers(s, scn.dir_sharers, scn.msg_second) != old
    ]
    if not choices:
        return None
    return dataclasses.replace(row, sharers=rng.choice(choices))


def _fuzz_owner(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    from hpa2_tpu.protocols.compiler import planes_for
    from hpa2_tpu.analysis.extract import _resolve_owner, scenario_for

    if row.role != "home" or row.drop:
        return None
    if not planes_for(table.protocol, table.semantics).has_owner_plane:
        return None
    scn = scenario_for(row, table.protocol)
    old = _resolve_owner(row.owner, scn)
    choices = [s for s in _FUZZ_OWNERS
               if _resolve_owner(s, scn) != old]
    if not choices:
        return None
    return dataclasses.replace(row, owner=rng.choice(choices))


def _fuzz_delete(
    rng: random.Random, table: TransitionTable, row: Row
) -> Optional[Row]:
    return None  # sentinel handled in random_mutation (row removal)


_FUZZ_KINDS: List[Tuple[str, Callable]] = [
    ("next-state", _fuzz_next_state),
    ("drop-emits", _fuzz_drop_emits),
    ("emit-type", _fuzz_emit_type),
    ("fill-source", _fuzz_fill_source),
    ("lost-wakeup", _fuzz_lost_wakeup),
    ("forget-memory", _fuzz_forget_memory),
    ("sharers-update", _fuzz_sharers),
    ("owner-update", _fuzz_owner),
    ("delete-row", _fuzz_delete),
]


def random_mutation(
    rng: random.Random, table: TransitionTable, max_tries: int = 64
) -> Tuple[str, TransitionTable]:
    """One random visible corruption of ``table`` (name, mutated)."""
    candidates = [r for r in table.rows
                  if not table.is_unreachable(*r.key)]
    for _ in range(max_tries):
        row = rng.choice(candidates)
        kind, gen = _FUZZ_KINDS[rng.randrange(len(_FUZZ_KINDS))]
        name = f"{kind}@{'/'.join(row.key)}"
        if kind == "delete-row":
            if row.drop:
                continue  # deleting a drop row may be a silent no-op
            return name, dataclasses.replace(
                table, rows=[r for r in table.rows if r is not row])
        new = gen(rng, table, row)
        if new is None or new == row:
            continue
        return name, table.replaced(row, new)
    raise RuntimeError("no applicable mutation found (table too small?)")


def run_fuzz(
    sem: Semantics,
    protocol: str = "mesi",
    seed: int = 0,
    count: int = 100,
    with_jax: bool = True,
) -> List[MutationResult]:
    """``count`` seeded random corruptions of one protocol's table;
    each must be caught statically or by a backend probe diff."""
    from hpa2_tpu.analysis.extract import JaxProber

    rng = random.Random(seed)
    base = build_table(sem, protocol)
    prober = JaxProber(sem, protocol) if with_jax else None
    results = []
    for _ in range(count):
        name, table = random_mutation(rng, base)
        static_errors = [
            str(f) for f in run_static_checks(table)
            if f.severity == "error"
        ]
        if static_errors:
            results.append(
                MutationResult(name, True, "static", static_errors[:3]))
            continue
        mutated_keys = _changed_keys(base, table)
        rows = [r for r in table.rows
                if r.key in mutated_keys
                and not table.is_unreachable(*r.key)]
        diffs = diff_backend(table, "spec", rows=rows or None)
        if diffs:
            results.append(
                MutationResult(name, True, "spec-diff", diffs[:3]))
            continue
        if prober is not None:
            diffs = diff_backend(
                table, "jax", rows=rows or None, prober=prober)
            if diffs:
                results.append(
                    MutationResult(name, True, "jax-diff", diffs[:3]))
                continue
        results.append(MutationResult(name, False, "", []))
    return results
