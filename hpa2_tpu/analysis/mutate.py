"""Seeded table mutations: the analyzer's self-test.

A static analyzer that has never been seen to *fail* proves nothing.
Each mutation below injects one realistic protocol bug into the
declarative table — the kinds of defect the reference implementation
actually shipped (silently unhandled pairs, lost wakeups, wrong fill
sources) — and the self-test asserts the analyzer catches every one,
either statically (``run_static_checks`` errors) or by the spec
equivalence diff.

CLI: ``python -m hpa2_tpu.analysis mutation-test``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, NamedTuple

from hpa2_tpu.config import Semantics
from hpa2_tpu.analysis.table import Emit, Row, TransitionTable, build_table
from hpa2_tpu.analysis.checks import run_static_checks
from hpa2_tpu.analysis.extract import diff_backend


class Mutation(NamedTuple):
    name: str
    description: str
    apply: Callable[[TransitionTable], TransitionTable]


def _swap(table: TransitionTable, key, **changes) -> TransitionTable:
    old = table.row(*key)
    return table.replaced(old, dataclasses.replace(old, **changes))


def _delete(table: TransitionTable, key) -> TransitionTable:
    old = table.row(*key)
    return dataclasses.replace(
        table, rows=[r for r in table.rows if r is not old]
    )


def _append(table: TransitionTable, row: Row) -> TransitionTable:
    return dataclasses.replace(table, rows=list(table.rows) + [row])


MUTATIONS: List[Mutation] = [
    Mutation(
        "swap-next-state",
        "first read of an uncached block grants S instead of EM — the "
        "directory forgets it has an exclusive owner",
        lambda t: _swap(t, ("home", "U", "READ_REQUEST", "any"),
                        next_state="S"),
    ),
    Mutation(
        "delete-row",
        "drop the cache-side INV/match row — invalidations are silently "
        "ignored and stale lines survive",
        lambda t: _delete(t, ("cache", "S", "INV", "match")),
    ),
    Mutation(
        "drop-emission",
        "home handles a READ_REQUEST but never sends the REPLY_RD — "
        "the requester waits forever",
        lambda t: _swap(t, ("home", "U", "READ_REQUEST", "any"), emits=()),
    ),
    Mutation(
        "remove-drop-citation",
        "strip the policy citation from the stale-INV drop — the drop "
        "becomes silent",
        lambda t: _swap(t, ("cache", "S", "INV", "other"), drop=""),
    ),
    Mutation(
        "duplicate-case",
        "claim the same guard-case twice with different outcomes — the "
        "transition relation becomes ambiguous",
        lambda t: _append(
            t, dataclasses.replace(
                t.row("cache", "I", "REPLY_WR", "any"), next_state="E")),
    ),
    Mutation(
        "wrong-receiver",
        "send the read reply to the current owner instead of the "
        "requester",
        lambda t: _swap(t, ("home", "U", "READ_REQUEST", "any"),
                        emits=(Emit("REPLY_RD", "owner", value="mem",
                                    sharers="excl"),)),
    ),
    Mutation(
        "corrupt-sharers",
        "FLUSH_INVACK leaves the directory EM with an empty sharer set "
        "— an owned block with no owner",
        lambda t: _swap(t, ("home", "EM", "FLUSH_INVACK", "any"),
                        sharers="empty"),
    ),
    Mutation(
        "premature-modified",
        "an exclusive read fill installs M instead of E — a clean line "
        "the directory will now ask to flush",
        lambda t: _swap(t, ("cache", "I", "REPLY_RD", "excl"),
                        next_state="M"),
    ),
    Mutation(
        "phantom-emission",
        "the write fill also broadcasts a spurious INV",
        lambda t: _swap(
            t, ("cache", "I", "REPLY_WR", "any"),
            emits=(Emit("INV", "home"),)),
    ),
    Mutation(
        "wrong-fill-source",
        "REPLY_WR fills the line from the (stale) message payload "
        "instead of the requester's pending write",
        lambda t: _swap(t, ("cache", "I", "REPLY_WR", "any"),
                        value_src="msg"),
    ),
    Mutation(
        "contradict-unreachable",
        "add a row in a cell explicitly declared unreachable",
        lambda t: _append(
            t, Row("home", "U", "NACK", "read_intervention",
                   next_state="U")),
    ),
    Mutation(
        "lost-wakeup",
        "REPLY_WR fills the line but never clears the waiting flag — "
        "the classic lost-wakeup hang",
        lambda t: _swap(t, ("cache", "I", "REPLY_WR", "any"),
                        clears_waiting=False),
    ),
]


@dataclasses.dataclass
class MutationResult:
    name: str
    caught: bool
    caught_by: str       # 'static' | 'spec-diff' | ''
    evidence: List[str]  # first few findings / diff lines


def run_mutation(mut: Mutation, sem: Semantics) -> MutationResult:
    table = mut.apply(build_table(sem))
    static_errors = [
        str(f) for f in run_static_checks(table) if f.severity == "error"
    ]
    if static_errors:
        return MutationResult(mut.name, True, "static", static_errors[:3])
    # statically plausible table — the behavioral diff must object
    mutated_keys = _changed_keys(build_table(sem), table)
    rows = [r for r in table.rows
            if r.key in mutated_keys and not table.is_unreachable(*r.key)]
    diffs = diff_backend(table, "spec", rows=rows or None)
    if diffs:
        return MutationResult(mut.name, True, "spec-diff", diffs[:3])
    return MutationResult(mut.name, False, "", [])


def _changed_keys(base: TransitionTable, mutated: TransitionTable):
    base_rows = {r.key: r for r in base.rows}
    return {
        r.key for r in mutated.rows
        if base_rows.get(r.key) != r
    }


def run_all_mutations(sem: Semantics = None) -> List[MutationResult]:
    sem = sem if sem is not None else Semantics()
    return [run_mutation(m, sem) for m in MUTATIONS]
