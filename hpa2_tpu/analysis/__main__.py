"""CLI for the static protocol analysis suite.

Subcommands::

    python -m hpa2_tpu.analysis check          # static checks + spec equiv
    python -m hpa2_tpu.analysis lint           # JAX-pitfall / dead-handler lint
    python -m hpa2_tpu.analysis equiv          # cross-backend table diff
    python -m hpa2_tpu.analysis mutation-test  # analyzer self-test

``check`` is the cheap gate (pure Python, no JAX import): whole-table
static checks plus the spec-engine equivalence diff, on both the
default and robust semantics.  ``equiv`` extends the diff to the JAX
and native backends.  All subcommands exit non-zero on failure.
"""

from __future__ import annotations

import argparse
import os
import sys

from hpa2_tpu.config import Semantics

_SEMS = {
    "default": lambda: Semantics(),
    "robust": lambda: Semantics().robust(),
    "head": lambda: Semantics().head_quirks(),
}


def _table_report(name: str, sem: Semantics, verbose: bool) -> int:
    from hpa2_tpu.analysis.table import build_table
    from hpa2_tpu.analysis.checks import run_static_checks

    table = build_table(sem)
    findings = run_static_checks(table)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]
    print(f"[{name}] {len(table.rows)} rows, "
          f"{len(table.unreachable)} unreachable declarations, "
          f"{len(errors)} errors, {len(warnings)} warnings")
    shown = findings if verbose else errors
    for f in shown:
        print(f"  {f}")
    return len(errors)


def cmd_check(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.table import build_table
    from hpa2_tpu.analysis.extract import diff_backend

    rc = 0
    for name in args.sem:
        sem = _SEMS[name]()
        rc += _table_report(name, sem, args.verbose)
        diffs = diff_backend(build_table(sem), "spec")
        print(f"[{name}] spec equivalence: {len(diffs)} diffs")
        for d in diffs[:20]:
            print(f"  {d}")
        rc += len(diffs)
    return 1 if rc else 0


def cmd_lint(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.lint import run_lint

    findings = run_lint(args.root)
    for f in findings:
        print(f)
    print(f"{len(findings)} lint findings")
    return 1 if findings else 0


def cmd_equiv(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.table import build_table
    from hpa2_tpu.analysis.extract import diff_backend

    total = 0
    for name in args.sem:
        sem = _SEMS[name]()
        table = build_table(sem)
        for backend in args.backends:
            if backend == "jax" and sem.overloaded_evict_shared_notify:
                # the JAX backend refuses to build the overloaded
                # notify quirk; nothing to extract
                print(f"[{name}] jax: skipped (overloaded quirk "
                      f"unsupported by the JAX backend)")
                continue
            try:
                diffs = diff_backend(table, backend)
            except Exception as e:  # e.g. native toolchain missing
                if backend == "native" and args.allow_missing_native:
                    print(f"[{name}] native: skipped ({e})")
                    continue
                raise
            print(f"[{name}] {backend}: {len(diffs)} diffs")
            for d in diffs[:20]:
                print(f"  {d}")
            total += len(diffs)
    return 1 if total else 0


def cmd_mutation_test(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.mutate import run_all_mutations

    results = run_all_mutations(_SEMS[args.sem[0]]())
    missed = 0
    for r in results:
        status = f"caught by {r.caught_by}" if r.caught else "MISSED"
        print(f"{r.name:24s} {status}")
        if args.verbose or not r.caught:
            for e in r.evidence:
                print(f"    {e}")
        missed += 0 if r.caught else 1
    print(f"{len(results) - missed}/{len(results)} mutations caught")
    return 1 if missed else 0


def main(argv=None) -> int:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = argparse.ArgumentParser(prog="python -m hpa2_tpu.analysis")
    p.add_argument("--sem", default="default,robust",
                   help="comma-separated semantics variants "
                        "(default,robust,head)")
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("check", help="static checks + spec equivalence")
    lp = sub.add_parser("lint", help="JAX-pitfall / dead-handler lint")
    lp.add_argument("--root", default=repo_root)
    ep = sub.add_parser("equiv", help="cross-backend table diff")
    ep.add_argument("--backends", default="spec,jax,native",
                    help="comma-separated: spec,jax,native")
    ep.add_argument("--allow-missing-native", action="store_true",
                    help="skip (not fail) when the native build is "
                         "unavailable")
    sub.add_parser("mutation-test", help="analyzer self-test")
    args = p.parse_args(argv)
    args.sem = [s.strip() for s in args.sem.split(",") if s.strip()]
    for s in args.sem:
        if s not in _SEMS:
            p.error(f"unknown semantics variant {s!r}")
    if hasattr(args, "backends"):
        args.backends = [b.strip() for b in args.backends.split(",")]
        for b in args.backends:
            if b not in ("spec", "jax", "native"):
                p.error(f"unknown backend {b!r}")
    return {
        "check": cmd_check,
        "lint": cmd_lint,
        "equiv": cmd_equiv,
        "mutation-test": cmd_mutation_test,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
