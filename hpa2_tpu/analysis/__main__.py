"""CLI for the static protocol analysis suite.

Subcommands::

    python -m hpa2_tpu.analysis check          # static checks + spec equiv
    python -m hpa2_tpu.analysis lint           # 8-rule AST lint
    python -m hpa2_tpu.analysis equiv          # cross-backend table diff
    python -m hpa2_tpu.analysis mutation-test  # analyzer self-test
    python -m hpa2_tpu.analysis contracts      # compiled-program contracts
    python -m hpa2_tpu.analysis vmem           # static VMEM budget model
    python -m hpa2_tpu.analysis occupancy      # occupancy scheduler model
    python -m hpa2_tpu.analysis elision        # cycle-elision exact replay
    python -m hpa2_tpu.analysis topology       # interconnect sensitivity

``check`` is the cheap gate (pure Python, no JAX import): whole-table
static checks plus the spec-engine equivalence diff, on both the
default and robust semantics.  ``equiv`` extends the diff to the JAX,
native, and Pallas (interpret-mode single-transition probes of the
real kernel program) backends.  All subcommands exit non-zero on
failure.
"""

from __future__ import annotations

import argparse
import os
import sys

from hpa2_tpu.config import Semantics

_SEMS = {
    "default": lambda: Semantics(),
    "robust": lambda: Semantics().robust(),
    "head": lambda: Semantics().head_quirks(),
}


def _table_report(
    name: str, sem: Semantics, verbose: bool, protocol: str = "mesi"
) -> int:
    from hpa2_tpu.analysis.table import build_table
    from hpa2_tpu.analysis.checks import run_static_checks

    table = build_table(sem, protocol)
    findings = run_static_checks(table)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]
    print(f"[{name}/{protocol}] {len(table.rows)} rows, "
          f"{len(table.unreachable)} unreachable declarations, "
          f"{len(errors)} errors, {len(warnings)} warnings")
    shown = findings if verbose else errors
    for f in shown:
        print(f"  {f}")
    return len(errors)


def cmd_check(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.table import build_table
    from hpa2_tpu.analysis.extract import diff_backend

    rc = 0
    for name in args.sem:
        sem = _SEMS[name]()
        for protocol in ("mesi", "moesi", "mesif"):
            rc += _table_report(name, sem, args.verbose, protocol)
            diffs = diff_backend(build_table(sem, protocol), "spec")
            print(f"[{name}/{protocol}] spec equivalence: "
                  f"{len(diffs)} diffs")
            for d in diffs[:20]:
                print(f"  {d}")
            rc += len(diffs)
    return 1 if rc else 0


def cmd_lint(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.lint import run_lint

    findings = run_lint(args.root)
    for f in findings:
        print(f)
    print(f"{len(findings)} lint findings")
    return 1 if findings else 0


def cmd_equiv(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.table import build_table
    from hpa2_tpu.analysis.extract import diff_backend, diff_multi_backend

    total = 0
    for name in args.sem:
        sem = _SEMS[name]()
        for protocol in args.protocol:
            tag = f"{name}/{protocol}"
            table = build_table(sem, protocol)
            for backend in args.backends:
                if (backend in ("jax", "pallas")
                        and sem.overloaded_evict_shared_notify):
                    # the JAX and Pallas backends refuse to build the
                    # overloaded notify quirk; nothing to extract
                    print(f"[{tag}] {backend}: skipped (overloaded "
                          f"quirk unsupported by this backend)")
                    continue
                if protocol != "mesi" and backend in ("native", "pallas"):
                    print(f"[{tag}] {backend}: skipped (backend is "
                          f"specialized to MESI)")
                    continue
                try:
                    diffs = diff_backend(table, backend)
                except Exception as e:  # e.g. native toolchain missing
                    if backend == "native" and args.allow_missing_native:
                        print(f"[{tag}] native: skipped ({e})")
                        continue
                    raise
                print(f"[{tag}] {backend}: {len(diffs)} diffs")
                for d in diffs[:20]:
                    print(f"  {d}")
                total += len(diffs)
            if "jax" in args.backends \
                    and not sem.overloaded_evict_shared_notify:
                diffs = diff_multi_backend(sem, protocol)
                print(f"[{tag}] multi-message spec<->jax: "
                      f"{len(diffs)} diffs")
                for d in diffs[:20]:
                    print(f"  {d}")
                total += len(diffs)
    return 1 if total else 0


def cmd_contracts(args: argparse.Namespace) -> int:
    # the sharded contract points need a device mesh; re-exec onto the
    # 8-device virtual CPU mesh (no-op when a device-count flag is
    # already set, e.g. under run_tier1.sh or after the re-exec).
    # Under ``python -m`` the re-exec re-runs this file by path, which
    # drops the cwd from sys.path — pin the package root first.
    from hpa2_tpu import hostenv

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pp = os.environ.get("PYTHONPATH", "")
    if pkg_root not in pp.split(os.pathsep):
        os.environ["PYTHONPATH"] = (
            pkg_root + (os.pathsep + pp if pp else ""))
    hostenv.reexec_with_virtual_mesh(8)
    from hpa2_tpu.analysis import contracts as contracts_mod

    if args.list:
        for c in contracts_mod.registry():
            pinned = sum(1 for r in c.rules if r.expect is None)
            print(f"{c.name:28s} [{c.engine:7s}] {len(c.rules)} rules "
                  f"({pinned} pinned, needs {c.needs_devices} "
                  f"device(s)) — {c.title}")
        return 0
    if args.repin:
        # refuse on a dirty tree outside contracts/ so a repin diff
        # reviews as ONLY the pin churn, never mixed with source edits
        import subprocess

        proc = subprocess.run(
            ["git", "-C", args.root, "status", "--porcelain"],
            capture_output=True, text=True)
        if proc.returncode == 0:
            pin_dir = "hpa2_tpu/analysis/contracts/"
            dirty = []
            for line in proc.stdout.splitlines():
                path = line[3:].split(" -> ")[-1].strip().strip('"')
                if path and not path.startswith(pin_dir):
                    dirty.append(path)
            if dirty:
                print("--repin refused: working tree dirty outside "
                      f"{pin_dir}:", file=sys.stderr)
                for path in dirty[:10]:
                    print(f"  {path}", file=sys.stderr)
                print("commit or stash source changes first, so the "
                      "pin refresh lands as its own reviewable diff",
                      file=sys.stderr)
                return 2
    results = contracts_mod.run_contracts(
        engine=args.engine, repin=args.repin)
    drifted = [r for r in results if r.status == "drift"]
    checked = sum(1 for r in results if r.status == "ok")
    skipped = sum(1 for r in results if r.status == "skip")
    print(f"{checked} contract(s) "
          f"{'repinned' if args.repin else 'clean'}, "
          f"{len(drifted)} drifted, {skipped} skipped")
    return 1 if drifted else 0


def cmd_vmem(args: argparse.Namespace) -> int:
    from hpa2_tpu.config import SystemConfig
    from hpa2_tpu.analysis.vmem import budget_table, vmem_budget

    cfg = SystemConfig(
        num_procs=args.procs, msg_buffer_size=args.cap,
        semantics=_SEMS[args.sem[0]](),
    )
    if args.node_shards < 1 or args.procs % args.node_shards:
        print(
            f"--node-shards {args.node_shards} must divide --procs "
            f"{args.procs} (shards own contiguous equal node blocks)",
            file=sys.stderr,
        )
        return 2
    blocks = tuple(int(b) for b in args.blocks.split(","))
    print(budget_table(cfg, blocks, args.window,
                       snapshots=args.snapshots, gate=args.gate,
                       packed=args.packed,
                       node_shards=args.node_shards))
    worst = vmem_budget(cfg, max(blocks), args.window,
                        snapshots=args.snapshots, gate=args.gate,
                        packed=args.packed,
                        node_shards=args.node_shards)
    return 0 if worst.fits else 1


def cmd_occupancy(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.occupancy import occupancy_table

    table, rc = occupancy_table(
        args.batch, args.instrs, args.window, args.block,
        dists=[d.strip() for d in args.dists.split(",") if d.strip()],
        spreads=tuple(float(s) for s in args.spreads.split(",")),
        threshold=args.threshold,
        resident=args.resident,
        groups=args.groups,
        seed=args.seed,
        fused=not args.host_barriers,
        policies=[s.strip() for s in args.policy.split(",") if s.strip()],
    )
    print(table)
    if rc:
        print("MODEL VIOLATION: scheduler predicted to exceed the "
              "lockstep bound")
    return rc


def cmd_elision(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.elision import elision_table

    table, rc = elision_table(
        procs=args.procs,
        instrs=args.instrs,
        spreads=tuple(float(s) for s in args.spreads.split(",")),
        tail=args.tail,
        write_frac=args.write_frac,
        seed=args.seed,
        topology=args.topology,
        verify=not args.no_verify,
    )
    print(table)
    if rc:
        print("MODEL VIOLATION: predicted elision counters diverge "
              "from the device run")
    return rc


def cmd_topology(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.topology import topology_table

    topos = [t.strip() for t in args.topologies.split(",") if t.strip()]
    print(topology_table(
        nodes=args.nodes, rounds=args.rounds,
        hop_latency=args.hop_latency, bandwidth=args.bandwidth,
        topologies=topos,
    ))
    return 0


def cmd_table(args: argparse.Namespace) -> int:
    """Lower each requested protocol's TransitionTable and print the
    compiled plane digests — the same planes the JAX step and the spec
    dispatch run from, so a digest change here means the kernels
    changed protocol behavior."""
    import dataclasses as _dc

    from hpa2_tpu.protocols.compiler import TableCompileError, planes_for

    rc = 0
    for name in args.sem:
        sem = _SEMS[name]()
        for proto in args.protocol:
            try:
                planes = planes_for(proto, sem)
            except TableCompileError as e:
                print(f"[{name}] {proto}: COMPILE FAILED: {e}")
                rc += 1
                continue
            print(f"[{name}] {proto}: "
                  f"cache states {','.join(planes.cache_state_names)} | "
                  f"home states {','.join(planes.home_state_names)} | "
                  f"digest {planes.digest()}")
            if args.verbose:
                for f in _dc.fields(planes):
                    if f.name in ("protocol", "cache_state_names",
                                  "home_state_names"):
                        continue
                    print(f"    {f.name} = {getattr(planes, f.name)}")
    return rc


def cmd_mutation_test(args: argparse.Namespace) -> int:
    from hpa2_tpu.analysis.mutate import run_all_mutations

    sem = _SEMS[args.sem[0]]()
    missed = total = 0
    for protocol in ("mesi", "moesi", "mesif"):
        results = run_all_mutations(sem, protocol)
        for r in results:
            status = f"caught by {r.caught_by}" if r.caught else "MISSED"
            print(f"[{protocol}] {r.name:24s} {status}")
            if args.verbose or not r.caught:
                for e in r.evidence:
                    print(f"    {e}")
            missed += 0 if r.caught else 1
        total += len(results)
    print(f"{total - missed}/{total} mutations caught")
    return 1 if missed else 0


def main(argv=None) -> int:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = argparse.ArgumentParser(prog="python -m hpa2_tpu.analysis")
    p.add_argument("--sem", default="default,robust",
                   help="comma-separated semantics variants "
                        "(default,robust,head)")
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("check", help="static checks + spec equivalence")
    lp = sub.add_parser(
        "lint",
        help="AST lint: traced-branch, nondeterminism, dtype-drift, "
             "dtype-widening, dead-handler, interconnect-purity, "
             "hand-written-state, counter-backfill")
    lp.add_argument("--root", default=repo_root)
    cp = sub.add_parser(
        "contracts",
        help="compiled-program contracts: declarative jaxpr/HLO pins "
             "per engine x config point, with structural drift diffs")
    cp.add_argument("--check", action="store_true",
                    help="verify every contract point (the default)")
    cp.add_argument("--repin", action="store_true",
                    help="refresh hpa2_tpu/analysis/contracts/*.json "
                         "from the current lowerings (refuses on a "
                         "dirty tree outside contracts/)")
    cp.add_argument("--list", action="store_true",
                    help="list registered contract points")
    cp.add_argument("--engine", default=None,
                    help="restrict to one engine tag (xla, pallas, "
                         "serving, sharded) or contract name")
    cp.add_argument("--root", default=repo_root)
    ep = sub.add_parser("equiv", help="cross-backend table diff")
    ep.add_argument("--backends", default="spec,jax,native,pallas",
                    help="comma-separated: spec,jax,native,pallas")
    ep.add_argument("--protocol", default="mesi,moesi,mesif",
                    help="comma-separated: mesi,moesi,mesif (native/"
                         "pallas rows are extracted for mesi only)")
    ep.add_argument("--allow-missing-native", action="store_true",
                    help="skip (not fail) when the native build is "
                         "unavailable")
    sub.add_parser("mutation-test", help="analyzer self-test")
    tbl = sub.add_parser("table", help="print compiled protocol planes")
    tbl.add_argument("--protocol", default="mesi,moesi,mesif",
                     help="comma-separated: mesi,moesi,mesif")
    vp = sub.add_parser("vmem", help="static VMEM budget model")
    vp.add_argument("--blocks", default="512,1024,2048",
                    help="comma-separated block widths")
    vp.add_argument("--window", type=int, default=32)
    vp.add_argument("--procs", type=int, default=8)
    vp.add_argument("--cap", type=int, default=16,
                    help="mailbox capacity (msg_buffer_size)")
    vp.add_argument("--snapshots", action="store_true")
    vp.add_argument("--gate", action="store_true")
    vp.add_argument("--packed", action="store_true",
                    help="model the packed uint8/uint16 state planes")
    vp.add_argument("--node-shards", type=int, default=1,
                    help="model one shard of the node-sharded engine "
                         "(num_procs/node_shards local nodes per "
                         "device; must divide --procs)")
    op = sub.add_parser("occupancy", help="occupancy scheduler model")
    op.add_argument("--batch", type=int, default=64)
    op.add_argument("--instrs", type=int, default=96,
                    help="longest per-core trace (max_instrs)")
    op.add_argument("--window", type=int, default=16)
    op.add_argument("--block", type=int, default=16)
    op.add_argument("--dists", default="uniform,zipf",
                    help="comma-separated: uniform,zipf")
    op.add_argument("--spreads", default="2,4,8",
                    help="comma-separated max/min length ratios")
    op.add_argument("--threshold", type=float, default=0.5,
                    help="compaction occupancy threshold")
    op.add_argument("--resident", type=int, default=None,
                    help="device-resident lanes (default: whole batch)")
    op.add_argument("--groups", type=int, default=1,
                    help="scheduling groups (data shards)")
    op.add_argument("--seed", type=int, default=0)
    op.add_argument("--host-barriers", action="store_true",
                    help="model the PR-5 one-launch-per-interval host "
                         "loop instead of the fused single-program run")
    op.add_argument("--policy", default="fcfs",
                    help="comma-separated admission policies to "
                         "compare (fcfs,longest-first) — one table "
                         "row per policy")
    lp2 = sub.add_parser("elision", help="event-driven cycle-elision "
                         "model (exact replay vs device counters)")
    lp2.add_argument("--procs", type=int, default=4)
    lp2.add_argument("--instrs", type=int, default=400,
                     help="per-core trace length")
    lp2.add_argument("--spreads", default="2,4,8",
                     help="comma-separated Zipf hot-set spreads")
    lp2.add_argument("--tail", type=float, default=0.01,
                     help="uniform-random miss-traffic fraction")
    lp2.add_argument("--write-frac", type=float, default=0.3)
    lp2.add_argument("--seed", type=int, default=3)
    lp2.add_argument("--topology", default="ideal",
                     help="interconnect topology for the modeled run")
    lp2.add_argument("--no-verify", action="store_true",
                     help="model only; skip the device cross-check")
    tp = sub.add_parser("topology", help="interconnect sensitivity "
                        "(invalidation-storm cost per topology)")
    tp.add_argument("--nodes", type=int, default=8)
    tp.add_argument("--rounds", type=int, default=6,
                    help="storm rounds (each: all-read then one write)")
    tp.add_argument("--hop-latency", type=int, default=1)
    tp.add_argument("--bandwidth", type=int, default=1,
                    help="messages per link per cycle")
    tp.add_argument("--topologies", default="mesh2d,torus2d,hierarchical")
    args = p.parse_args(argv)
    args.sem = [s.strip() for s in args.sem.split(",") if s.strip()]
    for s in args.sem:
        if s not in _SEMS:
            p.error(f"unknown semantics variant {s!r}")
    if getattr(args, "cmd", None) in ("table", "equiv"):
        args.protocol = [x.strip() for x in args.protocol.split(",")
                         if x.strip()]
        for x in args.protocol:
            if x not in ("mesi", "moesi", "mesif"):
                p.error(f"unknown protocol {x!r}")
    if hasattr(args, "backends"):
        args.backends = [b.strip() for b in args.backends.split(",")]
        for b in args.backends:
            if b not in ("spec", "jax", "native", "pallas"):
                p.error(f"unknown backend {b!r}")
    return {
        "check": cmd_check,
        "lint": cmd_lint,
        "equiv": cmd_equiv,
        "mutation-test": cmd_mutation_test,
        "contracts": cmd_contracts,
        "table": cmd_table,
        "vmem": cmd_vmem,
        "occupancy": cmd_occupancy,
        "elision": cmd_elision,
        "topology": cmd_topology,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
