"""Static VMEM budget model for the Pallas engine.

The 16 MB per-core VMEM cap is the binding constraint on block width
(PERF.md: block 1024 missed the cap by ~0.5-1.6 MB with the trace
plane resident).  This module predicts the kernel's structural VMEM
footprint from a :class:`SystemConfig` plus the kernel shape — block,
trace window, mailbox capacity, sharer words, gate, snapshots,
streaming on/off — WITHOUT compiling anything, so budget regressions
fail in tier-1 unit tests instead of on a dead TPU tunnel weeks later.

Accounting (every plane has the lane axis minor, so a "row" is one
element per lane; bytes are dtype-aware — int32 rows cost 4 bytes,
the ``packed=True`` uint8/uint16 planes cost 1-2):

* carried planes (``state_shapes``): each blocked in/out pair is
  charged ``PIPELINE_COPIES`` buffers (pallas double-buffers blocked
  operands across grid steps; input/output aliasing makes the pair
  share), plus the live while-loop carry — doubled under ``gate=True``
  because the ``lax.cond`` burst keeps both branch carries live.
* trace plane: under streaming it leaves the blocked operands
  entirely — HBM (``memory_space=ANY``) costs no VMEM — and is charged
  as the 2-slot DMA scratch plus the live window carry.  The legacy
  path charges the full blocked window like any other operand.
* snapshot planes: streamed through single-copy VMEM scratch (plus
  live carry) instead of pipelined blocked operands.

The model is structural: XLA/Mosaic temporaries for the cycle body are
not modeled (they are lane-width-independent vector registers to first
order).  ``scripts/probe_compile.py`` prints the model next to the
compiler-measured figure on a real TPU so the 10%-agreement acceptance
check is one tunnel session away.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from hpa2_tpu.config import SystemConfig

#: per-core VMEM on the target parts (v4/v5 generation: 16 MiB)
VMEM_CAP_BYTES = 16 * 1024 * 1024
BYTES_PER_ROW_PER_LANE = 4  # i32 planes (packed planes use itemsize)

#: blocked pallas operands are pipelined across grid steps: one buffer
#: being computed on, one in flight (input/output aliasing folds the
#: in/out pair into the same double-buffered allocation)
PIPELINE_COPIES = 2


#: planes that are NOT node-leading (replicated enum-sized rows); every
#: other plane's leading axis is the node/directory axis and shrinks to
#: ``num_procs / node_shards`` rows per shard under node sharding
#: (mirrors ``parallel.sharding``'s per-shard plane shapes)
_REPLICATED_PLANES = ("scalars", "msg_counts")


@dataclasses.dataclass(frozen=True)
class VmemBudget:
    """Predicted structural VMEM footprint of one kernel block."""

    config: SystemConfig
    block: int
    window: int
    snapshots: bool
    gate: bool
    stream: bool
    packed: bool
    node_shards: int
    rows: Dict[str, int]        # carried rows/lane per plane
    lane_bytes: Dict[str, int]  # dtype-aware bytes/lane per plane
    carried_rows: int           # sum over carried (non-snapshot) planes
    snap_rows: int              # sum over snapshot planes
    trace_rows: int             # trace window rows/lane (tr + tr_len)
    operand_rows: int           # pipelined blocked-operand rows/lane
    live_rows: int              # live loop-carry rows/lane
    scratch_rows: int           # DMA scratch rows/lane (streaming)
    total_rows: int             # everything, rows per lane
    total_lane_bytes: int       # everything, BYTES per lane (dtype-aware)

    @property
    def total_bytes(self) -> int:
        return self.total_lane_bytes * self.block

    @property
    def fits(self) -> bool:
        return self.total_bytes <= VMEM_CAP_BYTES

    @property
    def headroom_bytes(self) -> int:
        return VMEM_CAP_BYTES - self.total_bytes


def _plane_rows(config: SystemConfig, snapshots: bool,
                packed: bool = False,
                node_shards: int = 1) -> Dict[str, int]:
    from hpa2_tpu.ops.pallas_engine import state_shapes

    shapes = state_shapes(config, snapshots, packed)
    rows = {}
    for name, prefix in shapes.items():
        if node_shards > 1 and name not in _REPLICATED_PLANES:
            prefix = (prefix[0] // node_shards,) + tuple(prefix[1:])
        r = 1
        for d in prefix:
            r *= d
        rows[name] = r
    return rows


def _plane_lane_bytes(config: SystemConfig, snapshots: bool,
                      packed: bool = False,
                      node_shards: int = 1) -> Dict[str, int]:
    """Per-plane BYTES per lane: rows times the carried dtype width
    (all 4 for the legacy int32 layout; the packed cache/dir planes
    drop to 1-2)."""
    import numpy as np

    from hpa2_tpu.ops.pallas_engine import state_dtypes

    rows = _plane_rows(config, snapshots, packed, node_shards)
    dtypes = state_dtypes(config, snapshots, packed)
    return {f: r * np.dtype(dtypes[f]).itemsize for f, r in rows.items()}


#: plane-name predicate for the protocol word planes (MESI cache words
#: + directory words, legacy or packed, plus the split-mode sharer
#: words) — the planes the ``packed=`` flag shrinks
_WORD_PLANES = ("cachew", "dirw", "cvalw", "cmetaw", "dmemw", "dmetaw")


def state_plane_bytes(config: SystemConfig, *,
                      packed: bool = False) -> int:
    """Per-lane bytes of the MESI/dir-state/value word planes — the
    quantity the packed layout is pinned to cut by >= 1.8x (ISSUE 6
    acceptance)."""
    lb = _plane_lane_bytes(config, snapshots=False, packed=packed)
    return sum(
        b for f, b in lb.items()
        if f in _WORD_PLANES or f.startswith("dirs")
    )


def vmem_budget(
    config: SystemConfig,
    block: int,
    window: int,
    *,
    snapshots: bool = False,
    gate: bool = False,
    stream: bool = True,
    packed: bool = False,
    node_shards: int = 1,
) -> VmemBudget:
    """Predict the per-block VMEM footprint of the run kernel.

    ``node_shards > 1`` models one device of the node-sharded engine:
    every node-leading plane (and the trace window) carries only the
    shard's ``num_procs / node_shards`` local rows, while the
    replicated ``scalars``/``msg_counts`` rows stay whole — the same
    per-shard geometry ``parallel.sharding`` places on the mesh.
    """
    if node_shards < 1 or config.num_procs % node_shards:
        raise ValueError(
            f"node_shards={node_shards} must divide "
            f"num_procs={config.num_procs}"
        )
    n = config.num_procs // node_shards
    rows = _plane_rows(config, snapshots, packed, node_shards)
    lane_bytes = _plane_lane_bytes(config, snapshots, packed, node_shards)
    snap_rows = sum(r for f, r in rows.items() if f.startswith("snap_"))
    carried_rows = sum(
        r for f, r in rows.items() if not f.startswith("snap_")
    )
    snap_b = sum(b for f, b in lane_bytes.items() if f.startswith("snap_"))
    carried_b = sum(
        b for f, b in lane_bytes.items() if not f.startswith("snap_")
    )
    trace_rows = n * window + n  # tr + tr_len
    trace_b = trace_rows * BYTES_PER_ROW_PER_LANE  # trace stays int32

    live_copies = 2 if gate else 1

    if stream:
        # blocked operands: carried state + tr_len + the status plane
        # (trace and snapshot planes moved to HBM: zero blocked copies)
        operand = (carried_rows + n + 1) * PIPELINE_COPIES
        operand_b = (
            carried_b + (n + 1) * BYTES_PER_ROW_PER_LANE
        ) * PIPELINE_COPIES
        # the window plane is closed over by the burst loops, not
        # carried — one live copy regardless of the gate's lax.cond
        live = (carried_rows + snap_rows) * live_copies + trace_rows
        live_b = (carried_b + snap_b) * live_copies + trace_b
        # 2-slot trace double buffer; snapshots staged in 1-copy scratch
        scratch = 2 * n * window + snap_rows
        scratch_b = 2 * n * window * BYTES_PER_ROW_PER_LANE + snap_b
    else:
        operand = (carried_rows + snap_rows + trace_rows) * PIPELINE_COPIES
        operand_b = (carried_b + snap_b + trace_b) * PIPELINE_COPIES
        live = (carried_rows + snap_rows + trace_rows) * live_copies
        live_b = (carried_b + snap_b + trace_b) * live_copies
        scratch = 0
        scratch_b = 0

    total = operand + live + scratch
    total_b = operand_b + live_b + scratch_b
    return VmemBudget(
        config=config, block=block, window=window, snapshots=snapshots,
        gate=gate, stream=stream, packed=packed,
        node_shards=node_shards, rows=rows,
        lane_bytes=lane_bytes, carried_rows=carried_rows,
        snap_rows=snap_rows, trace_rows=trace_rows, operand_rows=operand,
        live_rows=live, scratch_rows=scratch, total_rows=total,
        total_lane_bytes=total_b,
    )


def _fmt_mb(b: int) -> str:
    return f"{b / (1024 * 1024):6.2f}"


def budget_table(
    config: SystemConfig,
    blocks: Tuple[int, ...] = (512, 1024, 2048),
    window: int = 32,
    *,
    snapshots: bool = False,
    gate: bool = False,
    packed: bool = False,
    node_shards: int = 1,
) -> str:
    """The ``analysis vmem`` report: streamed vs legacy footprint per
    block width against the 16 MiB cap.  With ``node_shards > 1`` the
    figures are per shard (``num_procs / node_shards`` local nodes)."""
    n_local = config.num_procs // max(node_shards, 1)
    lines = [
        f"VMEM budget model  (n={config.num_procs} cap="
        f"{config.msg_buffer_size} window={window} "
        f"snapshots={snapshots} gate={gate} packed={packed}"
        + (
            f" node_shards={node_shards} [{n_local} local nodes/shard]"
            if node_shards > 1 else ""
        )
        + f"; cap {_fmt_mb(VMEM_CAP_BYTES).strip()} MiB)",
        f"{'block':>6} {'mode':>8} {'B/lane':>8} {'MiB':>7} "
        f"{'headroom':>9}  fits",
    ]
    for block in blocks:
        for stream in (True, False):
            bud = vmem_budget(
                config, block, window,
                snapshots=snapshots, gate=gate, stream=stream,
                packed=packed, node_shards=node_shards,
            )
            lines.append(
                f"{block:>6} {'stream' if stream else 'legacy':>8} "
                f"{bud.total_lane_bytes:>8} {_fmt_mb(bud.total_bytes)} "
                f"{_fmt_mb(bud.headroom_bytes)}  "
                f"{'yes' if bud.fits else 'NO'}"
            )
    if node_shards > 1:
        m1 = max_fitting_block(
            config, window, snapshots=snapshots, gate=gate,
            packed=packed, node_shards=1,
        )
        ms = max_fitting_block(
            config, window, snapshots=snapshots, gate=gate,
            packed=packed, node_shards=node_shards,
        )
        lines.append(
            f"max fitting block: {m1} (1 shard) -> {ms} "
            f"({node_shards} shards)"
        )
    return "\n".join(lines)


def max_fitting_block(
    config: SystemConfig,
    window: int = 32,
    *,
    snapshots: bool = False,
    gate: bool = False,
    stream: bool = True,
    packed: bool = False,
    node_shards: int = 1,
    limit: int = 1 << 20,
) -> int:
    """Largest power-of-two lane block the model predicts under the
    VMEM cap — the block ladder's top rung.  Halving the node-leading
    plane rows (node sharding) widens it: the per-shard working set
    per lane shrinks, so more lanes fit the same 16 MiB."""
    best = 0
    block = 1
    while block <= limit:
        if vmem_budget(
            config, block, window, snapshots=snapshots, gate=gate,
            stream=stream, packed=packed, node_shards=node_shards,
        ).fits:
            best = block
        block *= 2
    return best


def measured_vmem_bytes(compiled) -> Optional[int]:
    """Best-effort compiler-reported VMEM figure from a compiled
    jax executable (``lowered.compile()``).  Returns None when the
    backend does not expose a memory analysis (e.g. CPU interpret
    builds)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    for attr in ("temp_size_in_bytes", "temp_bytes"):
        v = getattr(ma, attr, None)
        if v:
            return int(v)
    return None
