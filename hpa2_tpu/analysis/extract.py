"""Probe-based cross-backend transition-table extraction.

For every declared ``Row`` this module builds one concrete single-node
scenario (states, sentinel values, one message or instruction), runs it
through a backend, and diffs the observed effect against the row's
symbolic claim resolved over the same scenario.  Three backends share
the scenario set:

* **spec**   — ``SpecEngine._handle`` / ``_issue`` called directly on a
  crafted node; emissions read from the engine outbox.
* **jax**    — one ``build_step_jitted`` cycle over a crafted
  ``SimState``; emissions read from the other nodes' mailboxes after
  end-of-cycle delivery (nobody else acts: empty traces, empty boxes).
* **native** — the ``hpa2_probe_transition`` C API (a packed
  setup/observe probe added to ``capi.cpp`` for exactly this purpose).
* **pallas** — ONE cycle of the real Pallas kernel program
  (``_build_call`` at batch 1, block 1, k=1, gate off) run through
  pallas interpret mode; the scenario is staged into the engine's
  packed word planes and emissions read back out of the other nodes'
  packed mailboxes, so the diff covers the word packing and the
  candidate-grid delivery, not just the cycle math.

Sentinel values make data-flow claims checkable: memory holds 77, the
preloaded line 55, ``pending_write`` 66, the message payload 88, the
instruction payload 99 — so e.g. a REPLY_WR that filled the line from
the message instead of the pending write is a visible diff, not a
coincidence.

The reference geometry (4 nodes / 4 lines / 16 blocks) fixes the cast:
address 19 lives at home 1 / block 3 / line 3; the victim address 51
shares line 3 but homes at node 3.  The probed home node is 1, the
probed cache node 2, the requester 2, the displaced owner 3.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import (
    CacheState,
    DirState,
    Instr,
    INVALID_ADDR,
    Message,
    MsgType,
    NO_PROC,
    bit,
)
from hpa2_tpu.analysis.table import Row, TransitionTable, build_table

# sentinels (see module docstring)
MEM_SENTINEL = 77
LINE_SENTINEL = 55
PENDING_SENTINEL = 66
MSG_SENTINEL = 88
INSTR_SENTINEL = 99

# cast and geometry (reference config 4/4/16)
ADDR = 19          # home 1, block 3, cache line 3
VICTIM_ADDR = 51   # home 3, block 3, cache line 3 (same line, other home)
HOME = 1
CACHE_NODE = 2
REQUESTER = 2
OWNER = 3
OTHER = 0

_CACHE_NUM = {"M": 0, "E": 1, "S": 2, "I": 3, "O": 4, "F": 5}
_DIR_NUM = {"EM": 0, "S": 1, "U": 2, "SO": 3}

#: initial directory sharer masks per (event, state, case) — chosen so
#: every symbolic update resolves to a distinct concrete mask
_HOME_SHARERS: Dict[Tuple[str, str], int] = {
    ("READ_REQUEST", "U"): 0,
    ("READ_REQUEST", "S"): bit(OTHER) | bit(OWNER),
    ("WRITE_REQUEST", "U"): 0,
    ("WRITE_REQUEST", "S"): bit(OTHER) | bit(OWNER),
    ("UPGRADE", "U"): 0,
    ("UPGRADE", "S"): bit(OTHER) | bit(REQUESTER) | bit(OWNER),
    ("UPGRADE", "EM"): bit(OWNER),
    ("EVICT_SHARED", "U"): 0,
    ("EVICT_MODIFIED", "U"): 0,
    ("EVICT_MODIFIED", "S"): bit(OTHER) | bit(OWNER),
    ("FLUSH", "U"): 0,
    ("FLUSH", "S"): bit(OTHER) | bit(REQUESTER),
    ("FLUSH", "EM"): bit(OWNER),
    ("FLUSH_INVACK", "U"): 0,
    ("FLUSH_INVACK", "S"): bit(OTHER) | bit(REQUESTER),
    ("FLUSH_INVACK", "EM"): bit(OWNER),
    ("NACK", "S"): bit(OTHER) | bit(OWNER),
    ("NACK", "EM"): bit(OWNER),
    # MOESI SO cells (the tracked owner stays a sharer by invariant)
    ("WRITE_REQUEST", "SO"): bit(OTHER) | bit(OWNER),
    ("UPGRADE", "SO"): bit(OTHER) | bit(REQUESTER) | bit(OWNER),
    ("FLUSH", "SO"): bit(OTHER) | bit(REQUESTER) | bit(OWNER),
    ("FLUSH_INVACK", "SO"): bit(OTHER) | bit(REQUESTER) | bit(OWNER),
    ("NACK", "SO"): bit(OTHER) | bit(OWNER),
    ("EVICT_MODIFIED", "SO"): bit(OWNER),
}
_HOME_SHARERS_BY_CASE: Dict[str, int] = {
    "owner_is_requester": bit(REQUESTER),
    "owner_is_other": bit(OWNER),
    "sender_only_sharer": bit(REQUESTER),
    "two_sharers": bit(REQUESTER) | bit(OWNER),
    "many_sharers": bit(OTHER) | bit(REQUESTER) | bit(OWNER),
    "sender_not_sharer": bit(OTHER) | bit(OWNER),
    "sender_is_owner": bit(REQUESTER),
    "sender_not_owner": bit(OWNER),
    # MOESI SO eviction cases (sender = REQUESTER, tracked owner = the
    # staged dir_owner; "sender_is_owner_*" stage the sender as owner)
    "none_left": bit(REQUESTER),
    "one_left": bit(REQUESTER) | bit(OWNER),
    "several_left": bit(OTHER) | bit(REQUESTER) | bit(OWNER),
    "sender_is_owner_last": bit(REQUESTER),
    "sender_is_owner_more": bit(REQUESTER) | bit(OTHER),
    # MESIF forwarder cases on a shared line (requester not a sharer)
    "no_fwd": bit(OTHER) | bit(OWNER),
    "fwd_is_requester": bit(REQUESTER) | bit(OTHER),
    "fwd_other": bit(OTHER) | bit(OWNER),
}

#: REPLY_ID fan-out mask: includes the receiver itself to prove the
#: self-exclusion — expected INVs go to OTHER and OWNER only
_FANOUT_MASK = bit(OTHER) | bit(CACHE_NODE) | bit(OWNER)
_FANOUT_TARGETS = (OTHER, OWNER)


@dataclasses.dataclass
class Scenario:
    """One concrete probe setup (receiver-node state + stimulus)."""

    receiver: int
    is_instr: bool = False
    instr_op: str = "R"
    instr_addr: int = ADDR
    instr_value: int = 0
    msg_type: int = 0
    msg_sender: int = HOME
    msg_addr: int = ADDR
    msg_value: int = 0
    msg_sharers: int = 0
    msg_second: int = NO_PROC
    line_index: int = 3
    line_addr: int = INVALID_ADDR
    line_value: int = 0
    line_state: int = int(CacheState.INVALID)
    dir_blk: int = 3
    dir_state: int = int(DirState.U)
    dir_sharers: int = 0
    dir_owner: int = NO_PROC
    mem_blk: int = 3
    mem_value: int = MEM_SENTINEL
    pending: int = PENDING_SENTINEL
    waiting: bool = False


@dataclasses.dataclass
class Observed:
    """Post-transition state of the probed node plus its emissions.

    ``emits`` entries are ``(receiver, type, value, second, sharers)``
    with ``None`` meaning "don't care" (only produced on the expected
    side)."""

    line_addr: int
    line_value: int
    line_state: int
    dir_state: int
    dir_sharers: int
    mem_value: int
    waiting: bool
    emits: List[Tuple]
    dir_owner: int = NO_PROC

    def normalized(self) -> "Observed":
        return dataclasses.replace(
            self, emits=sorted(self.emits, key=lambda e: (e[0], e[1]))
        )


# ---------------------------------------------------------------------------
# scenario construction
# ---------------------------------------------------------------------------


def scenario_for(row: Row, protocol: str = "mesi") -> Optional[Scenario]:
    """Concrete probe setup for one declared row (None = not probeable:
    the row's guard needs multi-node context the probe cannot stage)."""
    if row.role == "home":
        return _home_scenario(row, protocol)
    return _cache_scenario(row)


def _home_owner(row: Row, protocol: str) -> int:
    """Initial tracked-owner/forwarder pointer for an owner-plane
    protocol, chosen so every symbolic owner update resolves to a
    transition the probe can see (set->cleared, set->moved, kept)."""
    if row.state == "SO":
        # invariant: the tracked owner is a sharer; "sender_is_owner_*"
        # cases make the evicting sender (REQUESTER) that owner
        if row.case in ("owner_is_requester", "sender_is_owner_last",
                        "sender_is_owner_more"):
            return REQUESTER
        return OWNER
    if protocol == "moesi" or row.state == "U":
        # MOESI tracks an owner only while SO; staging one elsewhere
        # would probe an unreachable configuration
        return NO_PROC
    if row.case in ("fwd_is_requester", "sender_only_sharer",
                    "two_sharers", "many_sharers"):
        return REQUESTER
    if row.case == "no_fwd":
        return NO_PROC
    if row.case == "fwd_other" or row.event in (
            "NACK", "FLUSH", "FLUSH_INVACK", "WRITE_REQUEST", "UPGRADE"):
        return OWNER
    return NO_PROC


def _home_scenario(row: Row, protocol: str = "mesi") -> Scenario:
    from hpa2_tpu.protocols.compiler import planes_for

    scn = Scenario(receiver=HOME)
    scn.dir_state = _DIR_NUM[row.state]
    scn.dir_sharers = _HOME_SHARERS_BY_CASE.get(
        row.case, _HOME_SHARERS.get((row.event, row.state), 0)
    )
    if planes_for(protocol, Semantics()).has_owner_plane:
        scn.dir_owner = _home_owner(row, protocol)
    scn.msg_type = int(MsgType[row.event])
    scn.msg_sender = REQUESTER
    if row.event in ("FLUSH", "FLUSH_INVACK"):
        scn.msg_sender = OWNER
        scn.msg_second = OTHER
        scn.msg_value = MSG_SENTINEL
    elif row.event == "NACK":
        scn.msg_sender = OWNER
        scn.msg_second = REQUESTER
        scn.msg_sharers = 1 if row.case == "write_intervention" else 0
    elif row.event in ("WRITE_REQUEST", "EVICT_MODIFIED"):
        scn.msg_value = MSG_SENTINEL
    return scn


def _cache_scenario(row: Row) -> Scenario:
    scn = Scenario(receiver=CACHE_NODE)
    case = row.case
    # line setup: match cases hold the probed address, victim/other
    # cases a displaced one, INVALID-state cells the placeholder fill
    if row.state == "I":
        scn.line_state = int(CacheState.INVALID)
        scn.line_addr = VICTIM_ADDR if case == "other" else ADDR
        scn.line_value = 0
    else:
        scn.line_state = _CACHE_NUM[row.state]
        scn.line_value = LINE_SENTINEL
        if case.startswith(("victim", "miss_victim")) or case == "other":
            scn.line_addr = VICTIM_ADDR
        else:
            scn.line_addr = ADDR
    if row.event in ("INSTR_R", "INSTR_W"):
        scn.is_instr = True
        scn.instr_op = "R" if row.event == "INSTR_R" else "W"
        scn.instr_value = INSTR_SENTINEL if row.event == "INSTR_W" else 0
        return scn
    scn.msg_type = int(MsgType[row.event])
    if row.event in ("REPLY_RD", "REPLY_WR", "REPLY_ID", "FLUSH",
                     "FLUSH_INVACK"):
        scn.waiting = True
    if row.event == "REPLY_RD":
        scn.msg_value = MSG_SENTINEL
        # fill-flag wire values: 2 = exclusive, 1 = forward (MESIF), 0
        # = plain shared
        scn.msg_sharers = (2 if case.endswith("excl")
                           else 1 if case.endswith("fwd") else 0)
    elif row.event == "REPLY_ID":
        scn.msg_sharers = _FANOUT_MASK
    elif row.event in ("FLUSH", "FLUSH_INVACK"):
        scn.msg_sender = OWNER
        scn.msg_second = CACHE_NODE
        scn.msg_value = MSG_SENTINEL
    elif row.event in ("WRITEBACK_INT", "WRITEBACK_INV"):
        scn.msg_sender = HOME
        scn.msg_second = HOME if case == "match_second_home" else OTHER
    elif row.event in ("UPGRADE_NOTIFY", "EVICT_SHARED"):
        scn.msg_sender = OWNER if case == "match_not_home" else HOME
    elif row.event == "INV":
        scn.msg_sender = OTHER
    return scn


# ---------------------------------------------------------------------------
# expected observation (the row's symbolic claim resolved over the
# scenario)
# ---------------------------------------------------------------------------


def _resolve_sharers(update: str, init: int, second: int) -> int:
    if update in ("", "same"):
        return init
    if update == "empty":
        return 0
    if update == "requester":
        return bit(REQUESTER)
    if update == "+requester":
        return init | bit(REQUESTER)
    if update == "-sender":
        return init & ~bit(REQUESTER)
    if update == "second":
        return bit(second)
    if update == "+second":
        return init | bit(second)
    raise ValueError(f"unknown sharer update {update!r}")


def _emit_value(src: str) -> Optional[int]:
    return {"": None, "mem": MEM_SENTINEL, "line": LINE_SENTINEL,
            "instr": INSTR_SENTINEL}[src]


def _emit_sharers(sym: str, init_sharers: int) -> Optional[int]:
    if sym == "":
        return None
    if sym == "excl":
        return 2
    if sym == "fwdf":  # MESIF fill-as-forwarder flag
        return 1
    if sym in ("shared", "none", "rd"):
        return 0
    if sym == "wr":
        return 1
    if sym == "others":
        return init_sharers & ~bit(REQUESTER)
    raise ValueError(f"unknown emission sharer symbol {sym!r}")


def _resolve_owner(update: str, scn: Scenario) -> int:
    from hpa2_tpu.models.protocol import find_owner

    if update in ("", "same"):
        return scn.dir_owner
    if update == "none":
        return NO_PROC
    if update == "requester":
        return REQUESTER
    if update == "second":
        return scn.msg_second
    if update == "owner":  # the EM owner, found from the sharer mask
        return find_owner(scn.dir_sharers)
    if update == "drop_sender":
        return (NO_PROC if scn.dir_owner == scn.msg_sender
                else scn.dir_owner)
    raise ValueError(f"unknown owner update {update!r}")


def expected_for(row: Row, scn: Scenario) -> Observed:
    if row.role == "home":
        dir_state = _DIR_NUM[row.next_state]
        dir_sharers = _resolve_sharers(
            row.sharers, scn.dir_sharers, scn.msg_second
        )
        dir_owner = _resolve_owner(row.owner, scn)
        line = (scn.line_addr, scn.line_value, scn.line_state)
    else:
        dir_state = scn.dir_state
        dir_sharers = scn.dir_sharers
        dir_owner = scn.dir_owner
        fill = {"msg": MSG_SENTINEL, "pending": PENDING_SENTINEL,
                "instr": INSTR_SENTINEL, "placeholder": 0}
        if row.value_src:
            tgt = scn.instr_addr if scn.is_instr else scn.msg_addr
            line = (tgt, fill[row.value_src], _CACHE_NUM[row.next_state])
        else:
            line = (scn.line_addr, scn.line_value,
                    _CACHE_NUM[row.next_state])
    mem = MSG_SENTINEL if row.writes_memory else scn.mem_value
    waiting = row.sets_waiting or (scn.waiting and not row.clears_waiting)

    emits: List[Tuple] = []
    targets = {
        "requester": REQUESTER, "owner": OWNER, "home": HOME,
        "second": scn.msg_second, "survivor": OWNER,
        "victim_home": VICTIM_ADDR // 16,
        "tracked_owner": scn.dir_owner,
    }
    seconds = {"": None, "requester": REQUESTER, "fwd": scn.msg_second}
    for e in row.emits:
        mtype = int(MsgType[e.type])
        value = _emit_value(e.value)
        second = seconds[e.second]
        sharers = _emit_sharers(e.sharers, scn.dir_sharers)
        if e.to == "sharers":
            emits.extend(
                (t, mtype, value, second, sharers) for t in _FANOUT_TARGETS
            )
        else:
            emits.append((targets[e.to], mtype, value, second, sharers))
    return Observed(
        line_addr=line[0], line_value=line[1], line_state=line[2],
        dir_state=dir_state, dir_sharers=dir_sharers, mem_value=mem,
        waiting=waiting, emits=emits, dir_owner=dir_owner,
    ).normalized()


# ---------------------------------------------------------------------------
# backend probes
# ---------------------------------------------------------------------------


def _stage_spec_node(eng, scn: Scenario) -> None:
    """Write one scenario's receiver-node state into a SpecEngine."""
    node = eng.nodes[scn.receiver]
    line = node.cache[scn.line_index]
    line.address = scn.line_addr
    line.value = scn.line_value
    line.state = CacheState(scn.line_state)
    entry = node.directory[scn.dir_blk]
    entry.state = DirState(scn.dir_state)
    entry.sharers = scn.dir_sharers
    entry.owner = scn.dir_owner
    node.memory[scn.mem_blk] = scn.mem_value
    node.pending_write = scn.pending
    node.waiting = scn.waiting


def probe_spec(
    scn: Scenario, sem: Semantics, protocol: str = "mesi"
) -> Observed:
    from hpa2_tpu.models.spec_engine import SpecEngine

    cfg = SystemConfig(semantics=sem, protocol=protocol)
    eng = SpecEngine(cfg, [[] for _ in range(cfg.num_procs)])
    _stage_spec_node(eng, scn)
    node = eng.nodes[scn.receiver]
    line = node.cache[scn.line_index]
    entry = node.directory[scn.dir_blk]
    if scn.is_instr:
        node.trace = [Instr(scn.instr_op, scn.instr_addr, scn.instr_value)]
        node.pc = 0
        eng._issue(node)
    else:
        eng._handle(node, Message(
            MsgType(scn.msg_type), scn.msg_sender, scn.msg_addr,
            value=scn.msg_value, sharers=scn.msg_sharers,
            second_receiver=scn.msg_second,
        ))
    emits = [
        (recv, int(m.type), m.value, m.second_receiver, m.sharers)
        for (_ph, _snd, recv, m) in eng._outbox
    ]
    return Observed(
        line_addr=line.address, line_value=line.value,
        line_state=int(line.state), dir_state=int(entry.state),
        dir_sharers=entry.sharers, dir_owner=entry.owner,
        mem_value=node.memory[scn.mem_blk], waiting=node.waiting,
        emits=emits,
    ).normalized()


def probe_native(scn: Scenario, sem: Semantics) -> Observed:
    from hpa2_tpu import native

    cfg = SystemConfig(semantics=sem)
    out = native.probe_transition(cfg, _native_packed(scn))
    emits = [
        tuple(out[8 + 5 * i: 8 + 5 * (i + 1)]) for i in range(out[7])
    ]
    return Observed(
        line_addr=out[0], line_value=out[1], line_state=out[2],
        dir_state=out[3], dir_sharers=out[4], mem_value=out[5],
        waiting=bool(out[6]), emits=emits,
    ).normalized()


def _native_packed(scn: Scenario) -> List[int]:
    """Input layout of the hpa2_probe_transition C API (capi.cpp)."""
    return [
        scn.receiver, int(scn.is_instr),
        1 if scn.instr_op == "W" else 0, scn.instr_addr, scn.instr_value,
        scn.msg_type, scn.msg_sender, scn.msg_addr, scn.msg_value,
        scn.msg_sharers, scn.msg_second,
        scn.line_index, scn.line_addr, scn.line_value, scn.line_state,
        scn.dir_blk, scn.dir_state, scn.dir_sharers,
        scn.mem_blk, scn.mem_value,
        scn.pending, int(scn.waiting),
    ]


class JaxProber:
    """Shared jitted step for a batch of JAX probes (one compile)."""

    def __init__(self, sem: Semantics, protocol: str = "mesi"):
        from hpa2_tpu.ops.step import build_step_jitted
        from hpa2_tpu.ops.state import init_state

        self.cfg = SystemConfig(semantics=sem, protocol=protocol)
        self.step = build_step_jitted(self.cfg)
        # one instruction slot so msg- and instr-probes share shapes
        # (init_state pads empty traces to length 1)
        self.base = init_state(
            self.cfg, [[] for _ in range(self.cfg.num_procs)]
        )

    def _stage(self, st, scn: Scenario):
        """Write one scenario's receiver-node state into a SimState."""
        import numpy as np

        r = scn.receiver
        st = st._replace(
            cache_addr=st.cache_addr.at[r, scn.line_index].set(scn.line_addr),
            cache_val=st.cache_val.at[r, scn.line_index].set(scn.line_value),
            cache_state=st.cache_state.at[r, scn.line_index].set(
                scn.line_state),
            dir_state=st.dir_state.at[r, scn.dir_blk].set(scn.dir_state),
            dir_sharers=st.dir_sharers.at[r, scn.dir_blk, 0].set(
                scn.dir_sharers),
            dir_owner=st.dir_owner.at[r, scn.dir_blk].set(scn.dir_owner),
            mem=st.mem.at[r, scn.mem_blk].set(scn.mem_value),
            pending_write=st.pending_write.at[r].set(scn.pending),
            waiting=st.waiting.at[r].set(scn.waiting),
        )
        if scn.is_instr:
            st = st._replace(
                tr_op=st.tr_op.at[r, 0].set(
                    0 if scn.instr_op == "R" else 1),
                tr_addr=st.tr_addr.at[r, 0].set(scn.instr_addr),
                tr_val=st.tr_val.at[r, 0].set(scn.instr_value),
                tr_len=st.tr_len.at[r].set(1),
            )
        else:
            packed = [scn.msg_type, scn.msg_sender, scn.msg_addr,
                      scn.msg_value, scn.msg_second, scn.msg_sharers]
            st = st._replace(
                mb_data=st.mb_data.at[r, 0, :6].set(
                    np.asarray(packed, dtype=np.int32)),
                mb_count=st.mb_count.at[r].set(1),
            )
        return st

    def probe(self, scn: Scenario) -> Observed:
        import numpy as np

        from hpa2_tpu.ops.state import (
            MB_ADDR, MB_SECOND, MB_SENDER, MB_SHARERS, MB_TYPE, MB_VALUE,
        )

        r = scn.receiver
        nxt = self.step(self._stage(self.base, scn))
        emits = []
        for j in range(self.cfg.num_procs):
            if j == r:
                continue
            for k in range(int(nxt.mb_count[j])):
                row = np.asarray(nxt.mb_data[j, k])
                emits.append((j, int(row[MB_TYPE]), int(row[MB_VALUE]),
                              int(row[MB_SECOND]), int(row[MB_SHARERS])))
        del MB_SENDER, MB_ADDR  # sender/addr are fixed by the scenario
        return Observed(
            line_addr=int(nxt.cache_addr[r, scn.line_index]),
            line_value=int(nxt.cache_val[r, scn.line_index]),
            line_state=int(nxt.cache_state[r, scn.line_index]),
            dir_state=int(nxt.dir_state[r, scn.dir_blk]),
            dir_sharers=int(nxt.dir_sharers[r, scn.dir_blk, 0]),
            dir_owner=int(nxt.dir_owner[r, scn.dir_blk]),
            mem_value=int(nxt.mem[r, scn.mem_blk]),
            waiting=bool(nxt.waiting[r]),
            emits=emits,
        ).normalized()


class PallasProber:
    """Single-transition probes against the Pallas engine.

    Each probe stages the scenario directly into the kernel's packed
    planes (cache word, directory word, scalar row, mailbox wire
    words), runs exactly one cycle of the REAL kernel program —
    ``_build_call`` at batch 1 / block 1 / ``k=1`` with the quiescence
    gate off, lowered through pallas interpret mode — and decodes the
    resulting planes back into an :class:`Observed`.  The builder's
    ``lru_cache`` plus jit shape-caching mean one compile serves the
    whole row set.

    The ``aux`` wire union is type-dependent (value | excl flag for
    REPLY_RD, the sharer/fan mask for REPLY_ID, the rd/wr flag for
    NACK, the byte value otherwise); the stage/decode here mirrors the
    kernel's own pack sites so a packing regression shows up as a
    table diff naming the row."""

    def __init__(self, sem: Semantics):
        from hpa2_tpu.ops import pallas_engine as pe

        self.pe = pe
        self.cfg = SystemConfig(semantics=sem)
        if pe._split_mode(self.cfg):
            raise ValueError(
                "probe geometry is the packed-word 4-node reference")
        self.layout, self.W = pe._mb_layout(self.cfg)
        # t_dim 1: one instruction slot, shared by msg probes
        self.slsc = pe._scalar_layout(self.cfg, 1)
        self.call = pe._build_call(
            self.cfg, 1, 1, 1, True, False, frozenset(), False)

    # -- wire-word helpers --------------------------------------------

    def _dec(self, words: Sequence[int], name: str) -> int:
        w, off, wd = self.layout[name]
        return (words[w] >> off) & ((1 << wd) - 1)

    def _msg_words(self, scn: Scenario) -> List[int]:
        from hpa2_tpu.models.protocol import MsgType as MT

        mt = scn.msg_type
        if mt == int(MT.REPLY_RD):
            aux = (scn.msg_value & 0xFF) | (
                256 if scn.msg_sharers == 2 else 0)
        elif mt in (int(MT.REPLY_ID), int(MT.NACK)):
            aux = scn.msg_sharers
        else:
            aux = scn.msg_value & 0xFF
        vals = {"type": mt, "sender": scn.msg_sender,
                "second": scn.msg_second + 1, "addr": scn.msg_addr,
                "aux": aux}
        words = [0] * self.W
        for name, x in vals.items():
            w, off, wd = self.layout[name]
            words[w] |= (x & ((1 << wd) - 1)) << off
        return words

    def _emit_from_words(self, recv: int, words: Sequence[int]) -> Tuple:
        from hpa2_tpu.models.protocol import MsgType as MT

        mtype = self._dec(words, "type")
        second = self._dec(words, "second") - 1
        aux = self._dec(words, "aux")
        if mtype == int(MT.REPLY_RD):
            value, sharers = aux & 0xFF, (2 if (aux >> 8) & 1 else 0)
        elif mtype in (int(MT.REPLY_ID), int(MT.NACK)):
            value, sharers = 0, aux
        else:
            value, sharers = aux & 0xFF, 0
        return (recv, mtype, value, second, sharers)

    # -- the probe ----------------------------------------------------

    def probe(self, scn: Scenario) -> Observed:
        import numpy as np

        pe = self.pe
        cfg, slsc = self.cfg, self.slsc
        n = cfg.num_procs
        r = scn.receiver
        st = {k: v.copy()
              for k, v in pe._init_state(cfg, 1, snapshots=False).items()}

        st["cachew"][r, scn.line_index, 0] = (
            scn.line_state
            | (scn.line_value << pe._CW_VAL_SHIFT)
            | ((scn.line_addr + 1) << pe._CW_ADDR_SHIFT))
        # dir fields first, then the memory byte: correct whether or
        # not the scenario's dir_blk and mem_blk coincide
        dw = int(st["dirw"][r, scn.dir_blk, 0])
        st["dirw"][r, scn.dir_blk, 0] = (
            (dw & 0xFF)
            | (scn.dir_state << pe._DW_STATE_SHIFT)
            | (scn.dir_sharers << pe._DW_SH_SHIFT))
        mw = int(st["dirw"][r, scn.mem_blk, 0])
        st["dirw"][r, scn.mem_blk, 0] = (mw & ~0xFF) | scn.mem_value

        tr = np.zeros((n, 1, 1), np.int32)
        tr_len = np.zeros((n, 1), np.int32)
        mb_count = 0
        if scn.is_instr:
            tr[r, 0, 0] = (
                (0 if scn.instr_op == "R" else 1)
                | (scn.instr_value << 1)
                | (scn.instr_addr << pe._TR_ADDR_SHIFT))
            tr_len[r, 0] = 1
        else:
            mb_count = 1
            for w, word in enumerate(self._msg_words(scn)):
                st[f"mb{w}"][r, 0, 0] = word
        st["nsw"][r, 0] = (
            mb_count
            | (int(scn.waiting) << slsc["off_wait"])
            | (scn.pending << slsc["off_pw"]))

        out = self.call(st, {"tr": tr, "tr_len": tr_len})
        out = {k: np.asarray(v) for k, v in out.items()}

        addr_mask = (1 << 21) - 1
        cw = int(out["cachew"][r, scn.line_index, 0])
        dw = int(out["dirw"][r, scn.dir_blk, 0])
        nsw = int(out["nsw"][r, 0])
        emits = []
        for j in range(n):
            if j == r:
                continue
            cnt = int(out["nsw"][j, 0]) & slsc["count_mask"]
            for k in range(cnt):
                words = [int(out[f"mb{w}"][j, k, 0])
                         for w in range(self.W)]
                emits.append(self._emit_from_words(j, words))
        return Observed(
            line_addr=((cw >> pe._CW_ADDR_SHIFT) & addr_mask) - 1,
            line_value=(cw >> pe._CW_VAL_SHIFT) & 0xFF,
            line_state=cw & 3,
            dir_state=(dw >> pe._DW_STATE_SHIFT) & 3,
            dir_sharers=(dw >> pe._DW_SH_SHIFT) & ((1 << n) - 1),
            mem_value=int(out["dirw"][r, scn.mem_blk, 0]) & 0xFF,
            waiting=bool((nsw >> slsc["off_wait"]) & 1),
            emits=emits,
        ).normalized()


# ---------------------------------------------------------------------------
# diffing
# ---------------------------------------------------------------------------


def _diff_observed(where: str, exp: Observed, obs: Observed) -> List[str]:
    out = []
    for field in ("line_addr", "line_value", "line_state", "dir_state",
                  "dir_sharers", "dir_owner", "mem_value", "waiting"):
        e, o = getattr(exp, field), getattr(obs, field)
        if e != o:
            out.append(f"{where}: {field} expected {e} observed {o}")
    if len(exp.emits) != len(obs.emits):
        out.append(
            f"{where}: expected {len(exp.emits)} emissions "
            f"{[(e[0], e[1]) for e in exp.emits]}, observed "
            f"{len(obs.emits)} {[(o[0], o[1]) for o in obs.emits]}")
        return out
    names = ("receiver", "type", "value", "second", "sharers")
    for e, o in zip(exp.emits, obs.emits):
        for i, name in enumerate(names):
            if e[i] is not None and e[i] != o[i]:
                out.append(
                    f"{where}: emission {names[1]}={e[1]} "
                    f"{name} expected {e[i]} observed {o[i]}")
    return out


def probeable_rows(table: TransitionTable) -> List[Row]:
    return [r for r in table.rows
            if not table.is_unreachable(*r.key)]


def diff_backend(
    table: TransitionTable,
    backend: str,
    rows: Optional[Sequence[Row]] = None,
    prober=None,
) -> List[str]:
    """Diff the backend's effective table against the declared one.

    Returns one human-readable line per mismatch (empty = equivalent).
    ``prober`` lets callers reuse a compiled ``JaxProber`` /
    ``PallasProber`` across many diffs (e.g. the fuzzer).
    """
    sem = table.semantics
    protocol = table.protocol
    if protocol != "mesi" and backend in ("native", "pallas"):
        raise ValueError(
            f"the {backend} backend is specialized to MESI; "
            f"cannot extract a {protocol} table from it")
    rows = list(rows) if rows is not None else probeable_rows(table)
    diffs: List[str] = []
    if prober is None:
        if backend == "jax":
            prober = JaxProber(sem, protocol)
        elif backend == "pallas":
            prober = PallasProber(sem)
    for row in rows:
        scn = scenario_for(row, protocol)
        if scn is None:
            continue
        exp = expected_for(row, scn)
        if backend == "spec":
            obs = probe_spec(scn, sem, protocol)
        elif backend in ("jax", "pallas"):
            obs = prober.probe(scn)
        elif backend == "native":
            obs = probe_native(scn, sem)
        else:
            raise ValueError(f"unknown backend {backend!r}")
        diffs.extend(_diff_observed("/".join(row.key), exp, obs))
    return diffs


def extract_and_diff(
    sem: Semantics, backends: Sequence[str], protocol: str = "mesi"
) -> Dict[str, List[str]]:
    table = build_table(sem, protocol)
    return {b: diff_backend(table, b) for b in backends}


# ---------------------------------------------------------------------------
# multi-stimulus probes: several deliveries in one phase.  The per-row
# probes above stage exactly one stimulus, so they can never see how
# concurrent handlers interact — emission ordering into a shared
# mailbox, two directory mutations racing an in-flight intervention.
# These scenarios stage stimuli at two or three DISTINCT receivers
# (the lockstep step handles one message per node per cycle), run one
# full cycle on both backends, and diff the ENTIRE system: every
# node's architectural state plus every mailbox's exact content and
# order.  The spec engine is the pivot; zero diffs expected.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MultiScenario:
    """Named bundle of single-node scenarios fired in the same cycle."""

    name: str
    stimuli: Tuple[Scenario, ...]


def multi_scenarios(protocol: str, sem: Semantics) -> List[MultiScenario]:
    """Same-phase interaction scenarios for one protocol (all same
    address unless noted, receivers always distinct)."""
    C, D = _CACHE_NUM, _DIR_NUM
    mesif = protocol == "mesif"
    moesi = protocol == "moesi"
    # MESIF read fills always carry a flag (2 = exclusive, 1 = as-
    # forwarder); a plain shared fill (0) exists only in MESI/MOESI
    rd_fill_flag = 1 if mesif else 0

    def msg(receiver: int, mtype: str, sender: int, **kw) -> Scenario:
        return Scenario(receiver=receiver, msg_type=int(MsgType[mtype]),
                        msg_sender=sender, **kw)

    out = [
        # a new read arrives at home while the owner is already
        # answering an earlier intervention for the same line
        MultiScenario("read_x_owner_wbint", (
            msg(HOME, "READ_REQUEST", OTHER,
                dir_state=D["EM"], dir_sharers=bit(OWNER)),
            msg(OWNER, "WRITEBACK_INT", HOME, msg_second=REQUESTER,
                line_addr=ADDR, line_value=LINE_SENTINEL,
                line_state=C["M"]),
        )),
        # a sharer eviction reaches home while another sharer handles
        # the INV of a racing write fan-out
        MultiScenario("evict_x_inv", (
            msg(HOME, "EVICT_SHARED", OTHER,
                dir_state=D["S"],
                dir_sharers=bit(OTHER) | bit(REQUESTER) | bit(OWNER),
                dir_owner=OWNER if mesif else NO_PROC),
            msg(REQUESTER, "INV", OWNER,
                line_addr=ADDR, line_value=LINE_SENTINEL,
                line_state=C["S"]),
        )),
        # home serves a write while the last-survivor notify of an
        # earlier eviction is still being absorbed
        MultiScenario("write_x_notify", (
            msg(HOME, "WRITE_REQUEST", REQUESTER, msg_value=MSG_SENTINEL,
                dir_state=D["S"], dir_sharers=bit(OTHER) | bit(OWNER),
                dir_owner=OWNER if mesif else NO_PROC),
            msg(OWNER, "UPGRADE_NOTIFY", HOME,
                line_addr=ADDR, line_value=LINE_SENTINEL,
                line_state=C["S"]),
        )),
        # a cache upgrades (hit on S) in the same cycle home shrinks
        # the sharer set under it
        MultiScenario("upgrade_x_evict", (
            Scenario(receiver=REQUESTER, is_instr=True, instr_op="W",
                     instr_value=INSTR_SENTINEL, line_addr=ADDR,
                     line_value=LINE_SENTINEL, line_state=C["S"]),
            msg(HOME, "EVICT_SHARED", OTHER,
                dir_state=D["S"],
                dir_sharers=bit(OTHER) | bit(REQUESTER),
                dir_owner=REQUESTER if mesif else NO_PROC),
        )),
        # two homes answer the same requester in one phase: pins the
        # cross-backend delivery order into a shared mailbox
        MultiScenario("two_replies_one_requester", (
            msg(HOME, "READ_REQUEST", REQUESTER, dir_state=D["U"]),
            msg(VICTIM_ADDR // 16, "WRITE_REQUEST", REQUESTER,
                msg_addr=VICTIM_ADDR, msg_value=MSG_SENTINEL,
                dir_state=D["U"]),
        )),
    ]
    if sem.intervention_miss_policy == "nack":
        # home re-serves a NACKed read while the requester is filling
        # from an earlier (stale) reply; NACK is never emitted under
        # the drop policy, so the race only exists on robust builds
        out.append(MultiScenario("nack_x_fill", (
            msg(HOME, "NACK", OWNER, msg_second=REQUESTER,
                dir_state=D["S"], dir_sharers=bit(OTHER),
                dir_owner=OWNER if mesif else NO_PROC),
            msg(REQUESTER, "REPLY_RD", HOME, msg_value=MSG_SENTINEL,
                msg_sharers=rd_fill_flag, line_addr=ADDR,
                line_state=C["I"], waiting=True),
        )))
    if moesi or mesif:
        # a tracked owner/forwarder answers one intervention while
        # home, still pointing at it, forwards the next
        out.append(MultiScenario("tracked_read_x_owner_wbint", (
            msg(HOME, "READ_REQUEST", REQUESTER,
                dir_state=D["SO"] if moesi else D["S"],
                dir_sharers=bit(OTHER) | bit(OWNER), dir_owner=OWNER),
            msg(OWNER, "WRITEBACK_INT", HOME, msg_second=OTHER,
                line_addr=ADDR, line_value=LINE_SENTINEL,
                line_state=C["O"] if moesi else C["F"]),
        )))
    return out


def _spec_system_obs(eng) -> List[dict]:
    return [
        {
            "mem": [int(x) for x in n.memory],
            "dir": [[int(e.state), int(e.sharers), int(e.owner)]
                    for e in n.directory],
            "cache": [[int(l.address), int(l.value), int(l.state)]
                      for l in n.cache],
            "pc": int(n.pc),
            "waiting": bool(n.waiting),
            "pending": int(n.pending_write),
            "mailbox": [
                [int(m.type), int(m.sender), int(m.address),
                 int(m.value), int(m.sharers), int(m.second_receiver)]
                for m in n.mailbox
            ],
        }
        for n in eng.nodes
    ]


def probe_spec_multi(
    ms: MultiScenario, sem: Semantics, protocol: str = "mesi"
) -> List[dict]:
    from hpa2_tpu.models.spec_engine import SpecEngine

    cfg = SystemConfig(semantics=sem, protocol=protocol)
    eng = SpecEngine(cfg, [[] for _ in range(cfg.num_procs)])
    for scn in ms.stimuli:
        _stage_spec_node(eng, scn)
        node = eng.nodes[scn.receiver]
        if scn.is_instr:
            node.trace = [
                Instr(scn.instr_op, scn.instr_addr, scn.instr_value)
            ]
            node.pc = 0
        else:
            node.mailbox.append(Message(
                MsgType(scn.msg_type), scn.msg_sender, scn.msg_addr,
                value=scn.msg_value, sharers=scn.msg_sharers,
                second_receiver=scn.msg_second,
            ))
    eng.step()
    return _spec_system_obs(eng)


def _jax_system_obs(prober: JaxProber, nxt) -> List[dict]:
    import numpy as np

    from hpa2_tpu.ops.state import (
        MB_ADDR, MB_SECOND, MB_SENDER, MB_SHARERS, MB_TYPE, MB_VALUE,
    )

    out = []
    for j in range(prober.cfg.num_procs):
        box = []
        for k in range(int(nxt.mb_count[j])):
            row = np.asarray(nxt.mb_data[j, k])
            box.append([int(row[MB_TYPE]), int(row[MB_SENDER]),
                        int(row[MB_ADDR]), int(row[MB_VALUE]),
                        int(row[MB_SHARERS]), int(row[MB_SECOND])])
        out.append({
            "mem": [int(x) for x in np.asarray(nxt.mem[j])],
            "dir": [[int(s), int(sh), int(ow)] for s, sh, ow in zip(
                np.asarray(nxt.dir_state[j]),
                np.asarray(nxt.dir_sharers[j, :, 0]),
                np.asarray(nxt.dir_owner[j]))],
            "cache": [[int(a), int(v), int(s)] for a, v, s in zip(
                np.asarray(nxt.cache_addr[j]),
                np.asarray(nxt.cache_val[j]),
                np.asarray(nxt.cache_state[j]))],
            "pc": int(nxt.pc[j]),
            "waiting": bool(nxt.waiting[j]),
            "pending": int(nxt.pending_write[j]),
            "mailbox": box,
        })
    return out


def probe_jax_multi(ms: MultiScenario, prober: JaxProber) -> List[dict]:
    st = prober.base
    for scn in ms.stimuli:
        st = prober._stage(st, scn)
    return _jax_system_obs(prober, prober.step(st))


def diff_multi_backend(
    sem: Semantics, protocol: str = "mesi"
) -> List[str]:
    """Spec-vs-JAX whole-system diff over the same-phase interaction
    scenarios.  One line per mismatching (node, plane); empty list =
    the backends agree on every concurrent-handler interaction."""
    prober = JaxProber(sem, protocol)
    diffs: List[str] = []
    for ms in multi_scenarios(protocol, sem):
        receivers = [s.receiver for s in ms.stimuli]
        if len(set(receivers)) != len(receivers):
            raise ValueError(
                f"{ms.name}: stimuli must target distinct receivers")
        spec = probe_spec_multi(ms, sem, protocol)
        jax = probe_jax_multi(ms, prober)
        for j, (a, b) in enumerate(zip(spec, jax)):
            for key in a:
                if a[key] != b[key]:
                    diffs.append(
                        f"{ms.name}: node {j} {key} "
                        f"spec {a[key]} jax {b[key]}")
    return diffs
