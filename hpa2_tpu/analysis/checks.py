"""Static whole-table checks over the declarative transition table.

Five check families, each returning ``Finding`` records:

* **completeness** — every (role, state, event) cell of
  ``CASE_UNIVERSE`` is tiled exactly: each guard-case has a row or an
  ``Unreachable`` declaration carrying a reason.  This is the static
  form of the reference's own bug class — silently unhandled
  (state, msg) pairs (SURVEY.md §6.3) — caught before any trace runs.
* **determinism** — no guard-case is claimed twice, no row names a
  case outside its cell's universe, and no row contradicts an
  ``Unreachable`` declaration.
* **no-silent-drop** — a row with zero observable effect must carry a
  ``drop`` citation; a citation that names a ``Semantics`` policy must
  reference a real attribute; conversely a row with effects must not
  carry one.
* **state-product** — every transition's cache x directory product
  stays legal: U directories have empty sharer sets, EM/S non-empty
  updates, cache next-states come from the event's legal set, fills
  clear the waiting flag.
* **reply-guarantee** — every request row has a response path: a
  REPLY_* straight back, or a forwarded intervention whose owner-side
  rows all either FLUSH (home + requester) or NACK back to a home row
  that re-serves.  A policy-cited drop breaks the chain *visibly*
  (warning, not error — it is the documented hang of the drop policy).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from hpa2_tpu.config import Semantics
from hpa2_tpu.analysis.table import (
    MSG_EVENTS,
    REQUEST_EVENTS,
    REPLY_TYPES,
    Row,
    TransitionTable,
)

VALID_MSG_TYPES = set(MSG_EVENTS)
VALID_TARGETS = {
    "requester", "owner", "home", "second", "survivor", "sharers",
    "victim_home", "tracked_owner",
}
VALID_SHARER_UPDATES = {
    "", "same", "empty", "requester", "+requester", "-sender", "second",
    "+second",
}
VALID_OWNER_UPDATES = {
    "", "same", "none", "requester", "second", "owner", "drop_sender",
}
VALID_VALUE_SRC = {"", "msg", "pending", "instr", "placeholder"}

#: legal next cache states per event (same-state no-ops always legal)
LEGAL_CACHE_NEXT: Dict[str, Tuple[str, ...]] = {
    "REPLY_RD": ("E", "S"),
    "FLUSH": ("S",),
    "REPLY_WR": ("M",),
    "FLUSH_INVACK": ("M",),
    "REPLY_ID": ("M",),
    "INV": ("I",),
    "WRITEBACK_INT": ("S",),
    "WRITEBACK_INV": ("I",),
    "UPGRADE_NOTIFY": ("E",),
    "EVICT_SHARED": ("E",),
    "INSTR_R": ("I",),
    "INSTR_W": ("M", "I"),
}

#: protocol deltas on top of the MESI legal-next sets
_LEGAL_CACHE_NEXT_DELTA: Dict[str, Dict[str, Tuple[str, ...]]] = {
    "moesi": {
        "WRITEBACK_INT": ("O",),        # owner keeps the line as OWNED
        "UPGRADE_NOTIFY": ("E", "M"),   # O promotes to M, S to E
    },
    "mesif": {
        "REPLY_RD": ("E", "F"),         # fwdf flag fills FORWARD
        "FLUSH": ("F",),                # cache-to-cache fill becomes F
    },
}


def legal_cache_next(protocol: str) -> Dict[str, Tuple[str, ...]]:
    """The per-event legal next-cache-state sets for one protocol."""
    return {**LEGAL_CACHE_NEXT, **_LEGAL_CACHE_NEXT_DELTA.get(protocol, {})}


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str      # which check family fired
    severity: str   # 'error' | 'warning'
    where: str      # cell / row key rendered for humans
    message: str

    def __str__(self) -> str:
        return f"[{self.severity}] {self.check}: {self.where}: {self.message}"


def _where(role: str, state: str, event: str, case: str = "") -> str:
    s = f"{role}/{state}/{event}"
    return f"{s}/{case}" if case else s


# ---------------------------------------------------------------------------


def check_completeness(table: TransitionTable) -> List[Finding]:
    out: List[Finding] = []
    claimed = {r.key for r in table.rows}
    for (role, event), per_state in table.universe.items():
        for state, cases in per_state.items():
            for case in cases:
                if (role, state, event, case) in claimed:
                    continue
                if table.is_unreachable(role, state, event, case):
                    continue
                out.append(Finding(
                    "completeness", "error", _where(role, state, event, case),
                    "guard-case neither handled by a row nor declared "
                    "unreachable — a message in this state would be "
                    "silently ignored"))
    for u in table.unreachable:
        if not u.reason.strip():
            out.append(Finding(
                "completeness", "error",
                _where(u.role, u.state, u.event, u.case),
                "unreachable declaration carries no reason"))
        if (u.role, u.event) not in table.universe:
            out.append(Finding(
                "completeness", "error",
                _where(u.role, u.state, u.event, u.case),
                "unreachable declaration names an unknown event"))
    return out


def check_determinism(table: TransitionTable) -> List[Finding]:
    out: List[Finding] = []
    seen: Dict[Tuple[str, str, str, str], Row] = {}
    for r in table.rows:
        if r.key in seen:
            out.append(Finding(
                "determinism", "error", _where(*r.key),
                "guard-case claimed by two rows — the transition is "
                "ambiguous"))
        seen[r.key] = r
        universe = table.universe.get((r.role, r.event))
        if universe is None or r.state not in universe:
            out.append(Finding(
                "determinism", "error", _where(*r.key),
                "row names a state/event outside the case universe"))
        elif r.case not in universe[r.state]:
            out.append(Finding(
                "determinism", "error", _where(*r.key),
                f"case {r.case!r} is not in the cell's universe "
                f"{universe[r.state]}"))
        if table.is_unreachable(*r.key):
            out.append(Finding(
                "determinism", "error", _where(*r.key),
                "row contradicts an unreachable declaration for the "
                "same cell"))
    return out


def check_no_silent_drop(table: TransitionTable) -> List[Finding]:
    out: List[Finding] = []
    sem_fields = {f.name for f in dataclasses.fields(Semantics)}
    for r in table.rows:
        if r.event.startswith("INSTR_"):
            # an instruction is never dropped: a zero-traffic row is a
            # hit that retires locally, not a discarded message
            continue
        if r.is_noop and not r.drop.strip():
            out.append(Finding(
                "no-silent-drop", "error", _where(*r.key),
                "row has zero observable effect but no drop citation — "
                "silent drops must say why (policy or idempotence)"))
        if r.drop and not r.is_noop:
            out.append(Finding(
                "no-silent-drop", "error", _where(*r.key),
                "row carries a drop citation but has observable effects"))
        if "Semantics." in r.drop:
            attr = r.drop.split("Semantics.", 1)[1].split()[0].split("=")[0]
            attr = attr.strip(".,;:()\"'")
            if attr not in sem_fields:
                out.append(Finding(
                    "no-silent-drop", "error", _where(*r.key),
                    f"drop cites unknown Semantics attribute {attr!r}"))
    return out


def check_state_product(table: TransitionTable) -> List[Finding]:
    out: List[Finding] = []
    for r in table.rows:
        if r.owner not in VALID_OWNER_UPDATES:
            out.append(Finding(
                "state-product", "error", _where(*r.key),
                f"unknown owner-pointer update {r.owner!r}"))
        if r.role == "cache" and r.owner not in ("", "same"):
            out.append(Finding(
                "state-product", "error", _where(*r.key),
                "only home rows may update the owner pointer"))
        if table.protocol == "mesi" and r.owner != "":
            out.append(Finding(
                "state-product", "error", _where(*r.key),
                "MESI has no owner pointer; the row must leave it alone"))
        if r.role == "home":
            if r.sharers not in VALID_SHARER_UPDATES:
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"unknown sharer update {r.sharers!r}"))
                continue
            nxt, upd = r.next_state, r.sharers
            if nxt == "U" and upd not in ("", "empty", "same"):
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"directory U must have an empty sharer set, got "
                    f"update {upd!r}"))
            if nxt == "U" and upd == "same" and r.state != "U":
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    "transition into U must clear the sharer set"))
            if nxt in ("EM", "S", "SO") and upd == "empty":
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"directory {nxt} requires a non-empty sharer set"))
            if nxt == "EM" and upd in ("+requester", "+second", "-sender"):
                # EM = exactly one holder: additive/subtractive updates
                # cannot guarantee a singleton — except -sender leaving
                # exactly one, which the two_sharers / one_left cases
                # encode.
                if r.case not in ("two_sharers", "one_left"):
                    out.append(Finding(
                        "state-product", "error", _where(*r.key),
                        f"directory EM requires a singleton sharer set; "
                        f"update {upd!r} cannot guarantee that"))
            if nxt == "SO" and r.owner in ("none",):
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    "directory SO requires a live owner pointer"))
            if nxt == "U" and r.owner not in ("", "none", "same"):
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    "directory U cannot track an owner"))
        else:
            if r.value_src not in VALID_VALUE_SRC:
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"unknown value source {r.value_src!r}"))
            legal = legal_cache_next(table.protocol).get(r.event)
            if legal is not None and r.next_state != r.state \
                    and r.next_state not in legal:
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"illegal next cache state {r.next_state} for "
                    f"{r.event} (legal: {legal} or unchanged)"))
            if r.event in REPLY_TYPES and not r.drop \
                    and not r.clears_waiting:
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    "a handled reply must clear the waiting flag or the "
                    "requester hangs"))
            if r.value_src in ("msg", "pending") and r.next_state == "I":
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    "a data fill cannot leave the line INVALID"))
        for e in r.emits:
            if e.type not in VALID_MSG_TYPES:
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"emission names unknown message type {e.type!r}"))
            if e.to not in VALID_TARGETS:
                out.append(Finding(
                    "state-product", "error", _where(*r.key),
                    f"emission names unknown target class {e.to!r}"))
    return out


def check_reply_guarantee(table: TransitionTable) -> List[Finding]:
    out: List[Finding] = []

    def intervention_closes(wb_event: str) -> List[Finding]:
        """Do the owner-side rows of a forwarded intervention always
        answer someone?"""
        local: List[Finding] = []
        for r in table.rows:
            if r.role != "cache" or r.event != wb_event:
                continue
            if table.is_unreachable(*r.key):
                continue
            flushes = any(e.type in ("FLUSH", "FLUSH_INVACK")
                          for e in r.emits)
            nacks = any(e.type == "NACK" and e.to == "home"
                        for e in r.emits)
            if flushes or nacks:
                continue
            if r.drop and "Semantics." in r.drop:
                local.append(Finding(
                    "reply-guarantee", "warning", _where(*r.key),
                    f"response chain for {wb_event} ends in a "
                    f"policy-cited drop — the requester hangs (the "
                    f"documented cost of {r.drop})"))
            else:
                local.append(Finding(
                    "reply-guarantee", "error", _where(*r.key),
                    f"owner-side {wb_event} row neither flushes nor "
                    f"NACKs: the requester can never be answered"))
        return local

    def nack_closes() -> List[Finding]:
        local: List[Finding] = []
        rows = [r for r in table.rows
                if r.role == "home" and r.event == "NACK"]
        for r in rows:
            if not any(e.type in ("REPLY_RD", "REPLY_WR")
                       and e.to == "second" for e in r.emits):
                local.append(Finding(
                    "reply-guarantee", "error", _where(*r.key),
                    "home NACK row does not re-serve the stalled "
                    "requester (msg.second_receiver)"))
        return local

    chained = set()
    for r in table.rows:
        if r.role != "home" or r.event not in REQUEST_EVENTS:
            continue
        replies = any(e.type in REPLY_TYPES and e.to == "requester"
                      for e in r.emits)
        forwards = [e.type for e in r.emits
                    if e.type in ("WRITEBACK_INT", "WRITEBACK_INV")
                    and e.to in ("owner", "tracked_owner")]
        if replies:
            continue
        if forwards:
            for wb in forwards:
                if wb not in chained:
                    chained.add(wb)
                    out.extend(intervention_closes(wb))
            continue
        out.append(Finding(
            "reply-guarantee", "error", _where(*r.key),
            f"request row neither replies to the requester nor forwards "
            f"an intervention — {r.event} would hang its sender"))
    if any(r.event == "NACK" and r.role == "home" for r in table.rows):
        out.extend(nack_closes())
    return out


ALL_CHECKS = (
    check_completeness,
    check_determinism,
    check_no_silent_drop,
    check_state_product,
    check_reply_guarantee,
)


def run_static_checks(table: TransitionTable) -> List[Finding]:
    """Run every check family; errors first, then warnings."""
    findings: List[Finding] = []
    for chk in ALL_CHECKS:
        findings.extend(chk(table))
    findings.sort(key=lambda f: (f.severity != "error", f.check, f.where))
    return findings
