"""Static occupancy model for the ensemble scheduler.

Predicts the block-segments a run executes with and without the
occupancy scheduler (``schedule=`` on both ensemble backends) from a
trace-length distribution alone — WITHOUT running a simulator.  The
prediction replays the *exact* deterministic barrier policy the
engines drive (:class:`hpa2_tpu.ops.schedule.LaneScheduler`), so the
modeled block-segment count equals a real scheduled run's counter not
within a tolerance band but bit-for-bit (tests/test_occupancy.py pins
the equality, which trivially satisfies the 10% acceptance band).

The unit of cost is the **block-segment**: one grid block executing
one trace-window segment's while-to-quiescence loop.  Blocks whose
lanes have all drained are skipped by the in-kernel gate for ~free, so
block-segments with >= 1 live lane is the device work the gate cannot
remove — and the quantity the scheduler minimizes by compacting live
lanes into dense blocks and backfilling freed lanes from the
admission queue.

``python -m hpa2_tpu.analysis occupancy`` renders the model as a
table over workload shapes, in the style of ``analysis vmem``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from hpa2_tpu.ops.schedule import (
    OccupancyStats, TenantWeights, simulate,
)


def predicted_stats(
    lengths: np.ndarray,
    window: int,
    block: int,
    *,
    resident: Optional[int] = None,
    groups: int = 1,
    threshold: float = 0.5,
    fused: bool = True,
    policy: str = "fcfs",
    deadline: Optional[np.ndarray] = None,
    tenant: Optional[np.ndarray] = None,
    tenant_weights: TenantWeights = None,
) -> OccupancyStats:
    """Model a scheduled run over per-system trace lengths: convert
    lengths to segment counts and replay the barrier policy.  ``fused``
    selects the launch accounting: the fused path costs one device
    program and zero host barriers per run; the PR-5 host loop pays
    one of each per scheduling interval."""
    nseg = np.maximum(
        1, -(-np.asarray(lengths, dtype=np.int64) // int(window))
    )
    return simulate(
        nseg, resident=resident, block=block, groups=groups,
        threshold=threshold, fused=fused, policy=policy,
        deadline=deadline, tenant=tenant,
        tenant_weights=tenant_weights,
    )


#: Synthetic multi-tenant metadata for the policy-comparison table:
#: four tenants round-robin with 1:2:4:8 weights, and (seeded) a third
#: of the systems carrying a tight deadline.  Deterministic in
#: (batch, seed) so the table is reproducible.
TABLE_TENANTS = 4
TABLE_WEIGHTS = (1.0, 2.0, 4.0, 8.0)


def table_metadata(
    lengths: np.ndarray, window: int, resident: int, seed: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(deadline, tenant) arrays used by ``occupancy_table`` whenever a
    row's policy consumes them.  Deadlines: roughly one system in three
    (seeded) must finish within the perfect-packing drain estimate
    ``ceil(total segments / resident)`` — tight enough that admission
    order decides hit-vs-miss, so the column separates policies."""
    lengths = np.asarray(lengths, dtype=np.int64)
    batch = len(lengths)
    nseg = np.maximum(1, -(-lengths // int(window)))
    drain = max(1, -(-int(nseg.sum()) // max(1, int(resident))))
    rng = np.random.default_rng(seed)
    tenant = np.arange(batch, dtype=np.int64) % TABLE_TENANTS
    deadline = np.full(batch, -1, dtype=np.int64)
    tight = rng.random(batch) < (1.0 / 3.0)
    deadline[tight] = drain
    return deadline, tenant


def occupancy_table(
    batch: int,
    max_instrs: int,
    window: int,
    block: int,
    *,
    dists: Sequence[str] = ("uniform", "zipf"),
    spreads: Sequence[float] = (2.0, 4.0, 8.0),
    threshold: float = 0.5,
    resident: Optional[int] = None,
    groups: int = 1,
    seed: int = 0,
    fused: bool = True,
    policies: Sequence[str] = ("fcfs",),
) -> Tuple[str, int]:
    """The ``analysis occupancy`` report: scheduled vs lockstep
    block-segments per workload shape, plus the launch cost — host
    barriers and device programs per run (0 / 1 on the fused path,
    n_intervals / n_intervals on the PR-5 host loop).  Passing more
    than one admission policy renders one row per policy, turning the
    table into a side-by-side policy comparison (the ``--policy``
    flag).  The deadline/tenant-aware policies (``deadline-edf``,
    ``fair-drr``) run over deterministic synthetic metadata
    (:func:`table_metadata`: 4 round-robin tenants, 1:2:4:8 weights,
    ~1/3 of systems deadlined at the drain estimate) and fill the
    ``dlmiss`` / ``maxshr%`` columns; the legacy policies print "-"
    there.  Returns (table, rc) — rc is nonzero if the model ever
    predicts the scheduler doing MORE work than lockstep (a policy
    bug, not a modeling error)."""
    from hpa2_tpu.utils.trace import heterogeneous_lengths

    r = resident if resident else batch
    lines = [
        f"Occupancy scheduler model  (batch={batch} resident={r} "
        f"block={block} window={window} max_instrs={max_instrs} "
        f"threshold={threshold} groups={groups} fused={fused})",
        f"{'dist':>8} {'spread':>6} {'policy':>13} {'lockstep':>9} "
        f"{'scheduled':>9} {'speedup':>8} {'live%':>6} {'wait':>6} "
        f"{'compact':>7} {'admit':>6} {'barrier':>7} {'progrm':>6} "
        f"{'dlmiss':>6} {'maxshr%':>7}",
    ]
    rc = 0
    for dist in dists:
        for spread in spreads:
            lens = heterogeneous_lengths(
                batch, max_instrs, dist, spread, seed
            )
            for policy in policies:
                tenanted = policy in ("deadline-edf", "fair-drr")
                if tenanted:
                    deadline, tenant = table_metadata(
                        lens, window, r, seed
                    )
                    st = predicted_stats(
                        lens, window, block, resident=resident,
                        groups=groups, threshold=threshold,
                        fused=fused, policy=policy, deadline=deadline,
                        tenant=tenant, tenant_weights=TABLE_WEIGHTS,
                    )
                    miss = f"{st.deadline_missed:>6}"
                    shares = st.tenant_live
                    total = sum(shares.values()) or 1
                    shr = f"{100 * max(shares.values()) / total:>7.1f}"
                else:
                    st = predicted_stats(
                        lens, window, block, resident=resident,
                        groups=groups, threshold=threshold,
                        fused=fused, policy=policy,
                    )
                    miss, shr = f"{'-':>6}", f"{'-':>7}"
                if st.block_segments > st.lockstep_block_segments:
                    rc = 1
                lines.append(
                    f"{dist:>8} {spread:>6.1f} {policy:>13} "
                    f"{st.lockstep_block_segments:>9} "
                    f"{st.block_segments:>9} {st.speedup:>7.2f}x "
                    f"{100 * st.mean_live_fraction:>5.1f} "
                    f"{st.wait_intervals_mean:>6.1f} "
                    f"{st.compactions:>7} {st.admissions:>6} "
                    f"{st.host_barriers:>7} {st.device_programs:>6} "
                    f"{miss} {shr}"
                )
    return "\n".join(lines), rc
