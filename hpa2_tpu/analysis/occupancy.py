"""Static occupancy model for the ensemble scheduler.

Predicts the block-segments a run executes with and without the
occupancy scheduler (``schedule=`` on both ensemble backends) from a
trace-length distribution alone — WITHOUT running a simulator.  The
prediction replays the *exact* deterministic barrier policy the
engines drive (:class:`hpa2_tpu.ops.schedule.LaneScheduler`), so the
modeled block-segment count equals a real scheduled run's counter not
within a tolerance band but bit-for-bit (tests/test_occupancy.py pins
the equality, which trivially satisfies the 10% acceptance band).

The unit of cost is the **block-segment**: one grid block executing
one trace-window segment's while-to-quiescence loop.  Blocks whose
lanes have all drained are skipped by the in-kernel gate for ~free, so
block-segments with >= 1 live lane is the device work the gate cannot
remove — and the quantity the scheduler minimizes by compacting live
lanes into dense blocks and backfilling freed lanes from the
admission queue.

``python -m hpa2_tpu.analysis occupancy`` renders the model as a
table over workload shapes, in the style of ``analysis vmem``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from hpa2_tpu.ops.schedule import OccupancyStats, simulate


def predicted_stats(
    lengths: np.ndarray,
    window: int,
    block: int,
    *,
    resident: Optional[int] = None,
    groups: int = 1,
    threshold: float = 0.5,
    fused: bool = True,
    policy: str = "fcfs",
) -> OccupancyStats:
    """Model a scheduled run over per-system trace lengths: convert
    lengths to segment counts and replay the barrier policy.  ``fused``
    selects the launch accounting: the fused path costs one device
    program and zero host barriers per run; the PR-5 host loop pays
    one of each per scheduling interval."""
    nseg = np.maximum(
        1, -(-np.asarray(lengths, dtype=np.int64) // int(window))
    )
    return simulate(
        nseg, resident=resident, block=block, groups=groups,
        threshold=threshold, fused=fused, policy=policy,
    )


def occupancy_table(
    batch: int,
    max_instrs: int,
    window: int,
    block: int,
    *,
    dists: Sequence[str] = ("uniform", "zipf"),
    spreads: Sequence[float] = (2.0, 4.0, 8.0),
    threshold: float = 0.5,
    resident: Optional[int] = None,
    groups: int = 1,
    seed: int = 0,
    fused: bool = True,
    policies: Sequence[str] = ("fcfs",),
) -> Tuple[str, int]:
    """The ``analysis occupancy`` report: scheduled vs lockstep
    block-segments per workload shape, plus the launch cost — host
    barriers and device programs per run (0 / 1 on the fused path,
    n_intervals / n_intervals on the PR-5 host loop).  Passing more
    than one admission policy renders one row per policy, turning the
    table into a side-by-side policy comparison (the ``--policy``
    flag).  Returns (table, rc) — rc is nonzero if the model ever
    predicts the scheduler doing MORE work than lockstep (a policy
    bug, not a modeling error)."""
    from hpa2_tpu.utils.trace import heterogeneous_lengths

    r = resident if resident else batch
    lines = [
        f"Occupancy scheduler model  (batch={batch} resident={r} "
        f"block={block} window={window} max_instrs={max_instrs} "
        f"threshold={threshold} groups={groups} fused={fused})",
        f"{'dist':>8} {'spread':>6} {'policy':>13} {'lockstep':>9} "
        f"{'scheduled':>9} {'speedup':>8} {'live%':>6} {'wait':>6} "
        f"{'compact':>7} {'admit':>6} {'barrier':>7} {'progrm':>6}",
    ]
    rc = 0
    for dist in dists:
        for spread in spreads:
            lens = heterogeneous_lengths(
                batch, max_instrs, dist, spread, seed
            )
            for policy in policies:
                st = predicted_stats(
                    lens, window, block, resident=resident,
                    groups=groups, threshold=threshold, fused=fused,
                    policy=policy,
                )
                if st.block_segments > st.lockstep_block_segments:
                    rc = 1
                lines.append(
                    f"{dist:>8} {spread:>6.1f} {policy:>13} "
                    f"{st.lockstep_block_segments:>9} "
                    f"{st.block_segments:>9} {st.speedup:>7.2f}x "
                    f"{100 * st.mean_live_fraction:>5.1f} "
                    f"{st.wait_intervals_mean:>6.1f} "
                    f"{st.compactions:>7} {st.admissions:>6} "
                    f"{st.host_barriers:>7} {st.device_programs:>6}"
                )
    return "\n".join(lines), rc
