"""Compiled-program contracts: declarative pins on lowered artifacts.

Every performance claim the repo makes — O(1)-depth exchange
collectives, the elided hot loop's "+1 reduce_min +1 cond" structure,
packed-plane dtypes, zero-recompile serving — is a property of the
*lowered program* (jaxpr or compiled HLO), not the source.  A
:class:`Contract` names one engine × config point, a ``measure``
function that traces it through :mod:`hpa2_tpu.analysis.ir` (the one
canonical walker), and a list of :class:`Rule` bounds over the
measured values.

Two rule flavors:

* **invariant** (``expect`` is an int): a hard structural law —
  ``gather == 0``, ``psum == 2``, the 2172/2194 cycle-op ceilings.
  Never repinned; weakening one is a deliberate source edit.
* **pinned** (``expect`` is None): the expected value lives in
  ``hpa2_tpu/analysis/contracts/<name>.json``, digest-keyed against
  the rule spec like the protocol planes.  ``--repin`` refreshes the
  files so a benign lowering change lands as a reviewable JSON diff.

On violation the checker emits a structural **drift diff** — contract,
key, expected vs found, a path into the jaxpr to the offending
primitive, and the rule's rationale — instead of a bare assert.

CLI: ``python -m hpa2_tpu.analysis contracts [--check|--repin|--list]
[--engine TAG]``.  Points needing a device mesh (the sharded engines)
skip cleanly when the host exposes too few devices; the CLI re-execs
onto the 8-device virtual CPU mesh so they normally all run.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from hpa2_tpu.analysis import ir

CONTRACT_DIR = os.path.join(os.path.dirname(__file__), "contracts")

_OPS = {
    "==": lambda got, want: got == want,
    "<=": lambda got, want: got <= want,
    ">=": lambda got, want: got >= want,
}


@dataclasses.dataclass(frozen=True)
class Rule:
    """One bound over a measured value.  ``expect is None`` marks a
    pinned rule whose expected value lives in the contract's JSON."""

    key: str
    op: str
    expect: Optional[int]
    why: str


@dataclasses.dataclass
class Observation:
    """What a measure function saw: the value census plus, for keys
    whose violation has a location, a path into the jaxpr."""

    values: Dict[str, int]
    where: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Contract:
    name: str
    engine: str  # grouping tag: xla | pallas | serving | sharded
    title: str
    measure: Callable[[], Observation]
    rules: Tuple[Rule, ...]
    needs_devices: int = 1


@dataclasses.dataclass(frozen=True)
class Drift:
    """One line of a structural drift diff."""

    contract: str
    key: str
    op: str
    expected: object
    found: object
    where: str = ""
    why: str = ""

    def render(self) -> str:
        lines = [f"  {self.key}: expected {self.op} {self.expected}, "
                 f"found {self.found}"]
        if self.where:
            lines.append(f"    at: {self.where}")
        if self.why:
            lines.append(f"    why: {self.why}")
        return "\n".join(lines)


@dataclasses.dataclass
class CheckResult:
    contract: str
    engine: str
    status: str  # ok | drift | skip
    drifts: List[Drift] = dataclasses.field(default_factory=list)
    note: str = ""


# -- pin files --------------------------------------------------------


def spec_digest(c: Contract) -> str:
    """Digest of the rule spec — a pin file minted for an older rule
    set is stale and must be regenerated, exactly like the compiled
    protocol planes' digest pin."""
    blob = json.dumps(
        {"contract": c.name, "engine": c.engine,
         "rules": [[r.key, r.op, r.expect] for r in c.rules]},
        sort_keys=True, separators=(",", ":"),
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def _pin_path(name: str) -> str:
    return os.path.join(CONTRACT_DIR, f"{name}.json")


def load_pins(c: Contract) -> Optional[dict]:
    try:
        with open(_pin_path(c.name)) as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None


def write_pins(c: Contract, obs: Observation) -> str:
    os.makedirs(CONTRACT_DIR, exist_ok=True)
    doc = {
        "contract": c.name,
        "engine": c.engine,
        "digest": spec_digest(c),
        "pins": {r.key: int(obs.values[r.key])
                 for r in c.rules if r.expect is None},
        # informational: everything measured at repin time, so a
        # contract diff reviews as "what moved", not just "what broke"
        "observed": {k: int(v) for k, v in sorted(obs.values.items())},
    }
    path = _pin_path(c.name)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


# -- checking ---------------------------------------------------------


def check_contract(c: Contract, obs: Observation) -> List[Drift]:
    drifts: List[Drift] = []
    pins = None
    if any(r.expect is None for r in c.rules):
        doc = load_pins(c)
        if doc is None:
            return [Drift(c.name, "<pin-file>", "==", "present",
                          "missing",
                          why="no pinned expectations on disk — run "
                              "`analysis contracts --repin`")]
        if doc.get("digest") != spec_digest(c):
            return [Drift(c.name, "<pin-file>", "==", spec_digest(c)[:12],
                          str(doc.get("digest"))[:12],
                          why="rule spec changed since the pin file was "
                              "minted — run `analysis contracts --repin`")]
        pins = doc.get("pins", {})
    for r in c.rules:
        expected = r.expect
        if expected is None:
            if r.key not in pins:
                drifts.append(Drift(c.name, r.key, r.op, "<pinned>",
                                    "missing pin",
                                    why="run `analysis contracts --repin`"))
                continue
            expected = pins[r.key]
        found = obs.values.get(r.key)
        if found is None:
            drifts.append(Drift(c.name, r.key, r.op, expected,
                                "not measured", why=r.why))
        elif not _OPS[r.op](found, expected):
            drifts.append(Drift(c.name, r.key, r.op, expected, found,
                                where=obs.where.get(r.key, ""),
                                why=r.why))
    return drifts


def run_contracts(engine: Optional[str] = None,
                  names: Optional[Sequence[str]] = None,
                  repin: bool = False,
                  out: Callable[[str], None] = print
                  ) -> List[CheckResult]:
    """Measure every registered contract point (optionally filtered by
    engine tag or name) and check — or, with ``repin``, refresh — its
    pins.  Prints drift diffs through ``out``; returns per-contract
    results."""
    import jax

    ndev = len(jax.devices())
    results: List[CheckResult] = []
    for c in registry():
        if engine and c.engine != engine and c.name != engine:
            continue
        if names and c.name not in names:
            continue
        if ndev < c.needs_devices:
            results.append(CheckResult(
                c.name, c.engine, "skip",
                note=f"needs {c.needs_devices} devices, have {ndev}"))
            out(f"SKIP  {c.name} [{c.engine}] — needs "
                f"{c.needs_devices} devices, have {ndev}")
            continue
        obs = c.measure()
        if repin:
            path = write_pins(c, obs)
            results.append(CheckResult(c.name, c.engine, "ok",
                                       note=f"pinned -> {path}"))
            out(f"PIN   {c.name} [{c.engine}] -> {os.path.relpath(path)}")
            continue
        drifts = check_contract(c, obs)
        if drifts:
            results.append(CheckResult(c.name, c.engine, "drift", drifts))
            out(f"DRIFT {c.name} [{c.engine}] — {c.title}")
            for d in drifts:
                out(d.render())
        else:
            results.append(CheckResult(c.name, c.engine, "ok"))
            out(f"OK    {c.name} [{c.engine}]")
    return results


# -- measure functions ------------------------------------------------
#
# Each traces one engine × config point and reduces the lowered
# program to a flat {key: int} census through analysis/ir.py.  Module
# attributes (step.build_run, exchange.make_plan, ...) are resolved at
# call time so the seeded-mutation harness can intercept them.


def _base_cfg(n=4, **kw):
    from hpa2_tpu.config import Semantics, SystemConfig

    return SystemConfig(num_procs=n, semantics=Semantics().robust(),
                        **kw)


def _paths(where: Dict[str, str], key: str, jaxpr,
           prims: Sequence[str]) -> None:
    p = ir.prim_paths(jaxpr, prims, limit=4)
    if p:
        where[key] = "; ".join(p)


def _run_body(cfg):
    """The outer while body of the XLA run program (the big sub-jaxpr;
    the other one is the cond)."""
    import jax

    from hpa2_tpu.ops import state as state_mod
    from hpa2_tpu.ops import step as step_mod
    from hpa2_tpu.utils import trace as trace_mod

    traces = trace_mod.gen_hot_hit_zipf(cfg, 8, seed=0)
    jx = jax.make_jaxpr(step_mod.build_run(cfg))(
        state_mod.init_state(cfg, traces))
    body = ir.largest_body(jx.jaxpr, "while")
    assert body is not None, "run program lost its while_loop"
    return body


_TOP_PRIMS = ("reduce_min", "cond", "while", "scan", "dot_general",
              "sort")


def measure_run_loop(cfg=None) -> Observation:
    """PR-12 pin: the event-driven loop body adds ONE reduction (the
    jump min) and ONE cond at its top level; the lockstep escape hatch
    rebuilds the bigger cond-free body."""
    import dataclasses as dc

    cfg = cfg or _base_cfg()
    body = _run_body(cfg)
    values = {f"elided.{k}": v
              for k, v in ir.top_counts(body, _TOP_PRIMS).items()}
    values["elided.eqns"] = len(body.eqns)
    lockstep = _run_body(dc.replace(cfg, elide=False))
    values["lockstep.cond"] = ir.top_counts(lockstep, ("cond",))["cond"]
    values["lockstep.extra_eqns"] = len(lockstep.eqns) - len(body.eqns)
    where: Dict[str, str] = {}
    for k in ("while", "scan", "dot_general", "sort"):
        if values[f"elided.{k}"]:
            _paths(where, f"elided.{k}", body, (k,))
    return Observation(values, where)


def measure_interconnect_loop() -> Observation:
    """The PR-11 interconnect JAX step (mesh2d) under elision —
    previously unguarded: same one-reduction/one-cond shape, no
    gather-the-world delivery."""
    from hpa2_tpu.config import InterconnectConfig

    cfg = _base_cfg(interconnect=InterconnectConfig(topology="mesh2d"))
    body = _run_body(cfg)
    values = {k: v for k, v in ir.top_counts(body, _TOP_PRIMS).items()}
    values["eqns"] = len(body.eqns)
    values["gather"] = ir.count_prims(body, ir.GATHER_PRIMS)
    where: Dict[str, str] = {}
    if values["gather"]:
        _paths(where, "gather", body, ir.GATHER_PRIMS)
    return Observation(values, where)


def measure_cycle_ops() -> Observation:
    """The per-cycle op budget at the bench shape (PR-6/PR-9 ceilings):
    streaming and every later feature must not grow the hot loop."""
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.ops import pallas_engine as pe

    cfg = _base_cfg(8, msg_buffer_size=16)
    values: Dict[str, int] = {}
    where: Dict[str, str] = {}
    for snapshots, key in ((False, "eqns.plain"), (True, "eqns.snap")):
        st = {k: jnp.asarray(v)
              for k, v in pe._init_state(cfg, 8, snapshots).items()}
        st["tr"] = jnp.zeros((8, 8, 8), jnp.int32)
        st["tr_len"] = jnp.zeros((8, 8), jnp.int32)
        jx = jax.make_jaxpr(pe.build_cycle(cfg, 8, snapshots))(st)
        values[key] = ir.count_eqns(jx.jaxpr)
        if not snapshots:
            coll = "collectives"
            values[coll] = ir.count_prims(jx.jaxpr, ir.COLLECTIVE_PRIMS)
            if values[coll]:
                _paths(where, coll, jx.jaxpr, ir.COLLECTIVE_PRIMS)
    return Observation(values, where)


def measure_stream_dma() -> Observation:
    """Streaming copies live at window boundaries only: the quiescence
    while loop carries no DMA primitives; the kernel overall streams."""
    import jax

    from hpa2_tpu.ops import pallas_engine as pe
    from hpa2_tpu.utils import trace as trace_mod

    cfg = _base_cfg(8, msg_buffer_size=16)
    arrays = trace_mod.gen_uniform_random_arrays(cfg, 8, 16, seed=1)
    eng = pe.PallasEngine(cfg, *arrays, interpret=True, stream=True,
                          snapshots=False, trace_window=8,
                          gate=False, block=8)
    jx = jax.make_jaxpr(eng._runner(10_000))(
        eng.state, eng._tr_full, eng._tr_len_full)
    kernels = ir.find_subjaxprs(jx.jaxpr, "pallas_call")
    values = {
        "kernels": len(kernels),
        "dma_start.total": sum(
            ir.count_prims(k, ("dma_start",)) for k in kernels),
        "dma.in_while": sum(
            ir.count_prims(wh, ("dma_start", "dma_wait"))
            for k in kernels for wh in ir.find_subjaxprs(k, "while")),
    }
    where: Dict[str, str] = {}
    if values["dma.in_while"]:
        for k in kernels:
            for wh in ir.find_subjaxprs(k, "while"):
                _paths(where, "dma.in_while", wh,
                       ("dma_start", "dma_wait"))
    return Observation(values, where)


def measure_packed_planes() -> Observation:
    """Dtype rule: the packed cycle's carried state leaves as narrow
    as it entered (u8/u16 planes; widening is transient, inside the
    `_widen*` helpers), with a bounded number of dtype converts."""
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.ops import pallas_engine as pe

    cfg = _base_cfg(4, cache_size=4, mem_size=64, msg_buffer_size=4)
    st = {k: jnp.asarray(v)
          for k, v in pe._init_state(cfg, 8, snapshots=False,
                                     packed=True).items()}
    st["tr"] = jnp.zeros((4, 8, 8), jnp.int32)
    st["tr_len"] = jnp.zeros((4, 8), jnp.int32)
    jx = jax.make_jaxpr(
        pe.build_cycle(cfg, 8, snapshots=False, packed=True))(st)
    return Observation({
        "narrow_outvars": ir.narrow_outvars(jx.jaxpr),
        "converts": ir.count_prims(jx.jaxpr, ("convert_element_type",)),
        "eqns": ir.count_eqns(jx.jaxpr),
    })


def measure_vmem_budget() -> Observation:
    """VMEM byte budgets, delegated to analysis/vmem.py: the bench
    block widths must fit, packing must save bytes, node sharding must
    shrink the per-shard footprint."""
    from hpa2_tpu.analysis.vmem import vmem_budget

    cfg = _base_cfg(8, msg_buffer_size=16)
    kw = dict(snapshots=False, gate=False, stream=True)
    b1024 = vmem_budget(cfg, 1024, 32, **kw)
    b2048 = vmem_budget(cfg, 2048, 32, **kw)
    pcfg = _base_cfg(4, cache_size=4, mem_size=64, msg_buffer_size=4)
    plain = vmem_budget(pcfg, 8, 4, snapshots=False)
    packed = vmem_budget(pcfg, 8, 4, snapshots=False, packed=True)
    shard = vmem_budget(cfg, 1024, 32, node_shards=2, **kw)
    return Observation({
        "fits.block1024": int(b1024.fits),
        "fits.block2048": int(b2048.fits),
        "packed.saves_bytes": int(packed.total_bytes < plain.total_bytes),
        "shard.shrinks": int(shard.total_bytes < b1024.total_bytes),
    })


def measure_serving_session() -> Observation:
    """The Pallas serving session: a full stage/advance/harvest/
    barrier round compiles each program exactly once (the
    zero-recompile pin) and its runner stays a gathered-collective-free
    single-kernel program."""
    import jax
    import numpy as np

    from hpa2_tpu.ops import pallas_engine as pe

    cfg = _base_cfg()
    r, w = 4, 8
    sess = pe.PallasLaneSession(cfg, r, w, block=4, cycles_per_call=16,
                                max_cycles=64)
    n = cfg.num_procs
    tr, tl = sess.stage(np.zeros((n, w, r), np.int32),
                        np.zeros((n, r), np.int32))
    status = sess.advance(tr, tl)
    sess.harvest(0)
    sess.barrier(np.arange(r), np.zeros(r, bool))
    sess.check(status)
    counts = sess.compile_counts()
    jx = jax.make_jaxpr(sess._runner)(sess.state, tr, tl)
    values = {f"compiles.{k}": v for k, v in counts.items()}
    values["runner.pallas_call"] = ir.count_prims(
        jx.jaxpr, ("pallas_call",))
    values["runner.gather"] = ir.count_prims(jx.jaxpr, ir.GATHER_PRIMS)
    values["runner.eqns"] = ir.count_eqns(jx.jaxpr)
    where: Dict[str, str] = {}
    if values["runner.gather"]:
        _paths(where, "runner.gather", jx.jaxpr, ir.GATHER_PRIMS)
    return Observation(values, where)


def measure_recovery_resume() -> Observation:
    """The recovery supervisor's jax→jax mid-state resume program
    (serving/recovery.py `_resume_rows`): a BatchLaneSession driven
    admit → advance → take_row → retire, each program compiled once,
    the chunk runner carrying the elision reduce_min and no gathers."""
    import jax

    from hpa2_tpu.ops import engine as engine_mod
    from hpa2_tpu.utils import trace as trace_mod

    cfg = _base_cfg()
    sess = engine_mod.BatchLaneSession(cfg, 2, 16, interval=32,
                                       max_cycles=4096)
    row = sess.fresh_row(trace_mod.gen_uniform_random(cfg, 4, seed=0))
    sess.admit(0, row)
    for _ in range(64):
        sess.advance()
        if sess.quiescent_rows()[0]:
            break
    else:
        raise AssertionError("resume row never quiesced")
    sess.take_row(0)
    sess.retire(0)
    counts = sess.compile_counts()
    jx = jax.make_jaxpr(sess._runner)(sess.state)
    values = {f"compiles.{k}": v for k, v in counts.items()}
    values["runner.reduce_min"] = ir.count_prims(jx.jaxpr,
                                                 ("reduce_min",))
    values["runner.gather"] = ir.count_prims(jx.jaxpr, ir.GATHER_PRIMS)
    values["runner.while"] = ir.count_prims(jx.jaxpr, ("while",))
    values["runner.eqns"] = ir.count_eqns(jx.jaxpr)
    where: Dict[str, str] = {}
    if values["runner.gather"]:
        _paths(where, "runner.gather", jx.jaxpr, ir.GATHER_PRIMS)
    return Observation(values, where)


def measure_node_sharded(kind: str, mode: str,
                         node_shards: int) -> Observation:
    """Collective census of a node-sharded run program's shard bodies,
    keyed like `exchange.plan_collectives` — the PR-15 pin.  ``kind``
    picks the Pallas fast path or the retrofitted ops/step.py path."""
    import dataclasses as dc

    import jax

    from hpa2_tpu.ops import exchange
    from hpa2_tpu.parallel import sharding
    from hpa2_tpu.utils import trace as trace_mod

    cfg = dc.replace(_base_cfg(8), exchange_mode=mode)
    if kind == "pallas":
        arrays = trace_mod.gen_uniform_random_arrays(cfg, 4, 12, seed=1)
        eng = sharding.NodeShardedPallasEngine(
            cfg, *arrays, node_shards=node_shards, cycles_per_call=16)
        jx = jax.make_jaxpr(eng._runner(10_000))(
            eng.state, eng._tr_full, eng._tr_len_full).jaxpr
    else:
        traces = trace_mod.gen_uniform_random(cfg, 12, seed=7)
        eng = sharding.NodeShardedEngine(
            cfg, traces, mesh=sharding.make_mesh(node_shards=node_shards))
        jx = jax.make_jaxpr(eng._run)(eng.state).jaxpr
    bodies = ir.find_subjaxprs(jx, "shard_map")
    assert bodies, "node-sharded run lost its shard_map"
    values = dict(ir.collective_counts(bodies))
    plan = exchange.plan_collectives(
        exchange.make_plan(node_shards, mode, 0))
    values["plan.ppermute"] = plan["ppermute"]
    values["plan.all_to_all"] = plan["all_to_all"]
    where: Dict[str, str] = {}
    for key, prims in (("gather", ir.GATHER_PRIMS),
                       ("ppermute", ("ppermute",)),
                       ("all_to_all", ("all_to_all",))):
        if values[key]:
            for b in bodies:
                _paths(where, key, b, prims)
    return Observation(values, where)


def measure_data_sharded() -> Observation:
    """The data-sharded Pallas path: per-shard run program collective-
    free at the jaxpr layer AND in the compiled-HLO loop closure, with
    the donation aliases the zero-copy carry depends on."""
    import jax

    from hpa2_tpu.parallel import sharding
    from hpa2_tpu.utils import trace as trace_mod

    cfg = _base_cfg()
    arrays = trace_mod.gen_uniform_random_arrays(cfg, 32, 8, seed=1)
    eng = sharding.DataShardedPallasEngine(cfg, *arrays, data_shards=8,
                                           block=4)
    jx = jax.make_jaxpr(eng._runner(10_000))(
        eng.state, eng._tr_full, eng._tr_len_full)
    bodies = ir.find_subjaxprs(jx.jaxpr, "shard_map")
    assert bodies, "sharded runner lost its shard_map"
    text = eng.lower_run(10_000).compile().as_text()
    offenders = ir.hlo_loop_collectives(text)
    values = {
        "shard_map": len(bodies),
        "shard_body.pallas_call": sum(
            ir.count_prims(b, ("pallas_call",)) for b in bodies),
        "shard_body.collectives": sum(
            ir.count_prims(b, ir.COLLECTIVE_PRIMS) for b in bodies),
        "hlo.loop_collectives": len(offenders),
        "hlo.aliased_outputs": ir.hlo_aliased_outputs(text),
    }
    where: Dict[str, str] = {}
    if values["shard_body.collectives"]:
        for b in bodies:
            _paths(where, "shard_body.collectives", b,
                   ir.COLLECTIVE_PRIMS)
    if offenders:
        where["hlo.loop_collectives"] = "; ".join(
            f"{name}: {line}" for name, line in offenders[:4])
    return Observation(values, where)


# -- registry ---------------------------------------------------------


def registry() -> List[Contract]:
    """Every contract point, in check order.  Literal expectations are
    invariants; ``None`` expectations are pinned in contracts/*.json."""
    elide_why = ("the event-driven loop adds exactly one jump-min "
                 "reduction and one fast-forward cond at the body top "
                 "level (PR 12)")
    coll_why = ("the exchange ships exactly the planned collectives "
                "(PR 15); gather-the-world delivery is banned")
    compile_why = "zero-recompile serving: one jit entry per program"
    return [
        Contract(
            "xla-run-loop", "xla",
            "event-driven XLA run loop structure",
            measure_run_loop,
            (
                Rule("elided.reduce_min", "==", 1, elide_why),
                Rule("elided.cond", "==", 1, elide_why),
                Rule("elided.while", "==", 0, elide_why),
                Rule("elided.scan", "==", 0, elide_why),
                Rule("elided.dot_general", "==", 0, elide_why),
                Rule("elided.sort", "==", 0, elide_why),
                Rule("lockstep.cond", "==", 0,
                     "the escape hatch rebuilds the pure lockstep body"),
                Rule("lockstep.extra_eqns", ">=", 1,
                     "the lockstep body inlines what elision hides "
                     "behind the cond"),
                Rule("elided.eqns", "<=", None,
                     "top-level op budget of the elided body"),
            ),
        ),
        Contract(
            "xla-run-interconnect", "xla",
            "interconnect (mesh2d) JAX step under elision",
            measure_interconnect_loop,
            (
                Rule("reduce_min", "==", 1, elide_why),
                Rule("cond", "==", 1, elide_why),
                Rule("while", "==", 0, elide_why),
                Rule("scan", "==", 0, elide_why),
                Rule("sort", "==", 0, elide_why),
                Rule("dot_general", "==", 0, elide_why),
                Rule("gather", "==", 0,
                     "topology delivery is link-priced, never "
                     "gather-the-world"),
                Rule("eqns", "<=", None,
                     "top-level op budget of the mesh2d body"),
            ),
        ),
        Contract(
            "pallas-cycle-body", "pallas",
            "per-cycle op budget at the bench shape",
            measure_cycle_ops,
            (
                Rule("eqns.plain", "<=", 2172,
                     "the hot loop must not pay for streaming (or "
                     "anything else) per cycle — historical ceiling"),
                Rule("eqns.snap", "<=", 2194,
                     "snapshot variant of the same ceiling"),
                Rule("collectives", "==", 0,
                     "the single-system cycle body is collective-free"),
            ),
        ),
        Contract(
            "pallas-stream-dma", "pallas",
            "streaming DMA outside the quiescence loop",
            measure_stream_dma,
            (
                Rule("kernels", ">=", 1,
                     "streaming runner keeps its pallas_call"),
                Rule("dma_start.total", ">=", 2,
                     "warm-up + prefetch copies must stream"),
                Rule("dma.in_while", "==", 0,
                     "copies live at window boundaries only — never "
                     "inside the per-cycle quiescence loop"),
            ),
        ),
        Contract(
            "pallas-packed-planes", "pallas",
            "packed-plane dtype rule (u8/u16 never escape)",
            measure_packed_planes,
            (
                Rule("narrow_outvars", ">=", 1,
                     "the packed cycle carries narrow planes at all"),
                Rule("narrow_outvars", "==", None,
                     "every narrow plane that enters the cycle leaves "
                     "it narrow — widening is transient"),
                Rule("converts", "<=", None,
                     "dtype converts stay bounded (no accidental "
                     "widen/narrow churn)"),
            ),
        ),
        Contract(
            "pallas-vmem-budget", "pallas",
            "VMEM byte budgets (analysis/vmem.py)",
            measure_vmem_budget,
            (
                Rule("fits.block1024", "==", 1,
                     "bench block 1024 fits the 16 MiB cap"),
                Rule("fits.block2048", "==", 1,
                     "bench block 2048 fits the 16 MiB cap"),
                Rule("packed.saves_bytes", "==", 1,
                     "packing must shrink the per-lane footprint"),
                Rule("shard.shrinks", "==", 1,
                     "node sharding must shrink the per-shard "
                     "footprint"),
            ),
        ),
        Contract(
            "pallas-serving-session", "serving",
            "Pallas serving session (stage/advance/harvest/barrier)",
            measure_serving_session,
            (
                Rule("compiles.runner", "==", 1, compile_why),
                Rule("compiles.barrier", "==", 1, compile_why),
                Rule("compiles.take_lane", "==", 1, compile_why),
                Rule("runner.pallas_call", ">=", 1,
                     "session runner keeps its kernel"),
                Rule("runner.gather", "==", 0, coll_why),
                Rule("runner.eqns", "<=", None,
                     "op budget of the session interval program"),
            ),
        ),
        Contract(
            "serving-recovery-resume", "serving",
            "recovery supervisor's jax→jax mid-state resume",
            measure_recovery_resume,
            (
                Rule("compiles.runner", "==", 1, compile_why),
                Rule("compiles.admit", "==", 1, compile_why),
                Rule("compiles.take_row", "==", 1, compile_why),
                Rule("compiles.quiescent", "==", 1, compile_why),
                Rule("runner.while", ">=", 1,
                     "the chunk program keeps its while loop"),
                Rule("runner.gather", "==", 0, coll_why),
                Rule("runner.reduce_min", "==", None,
                     "the batched chunk carries the elision jump-min "
                     "per row"),
                Rule("runner.eqns", "<=", None,
                     "op budget of the resume chunk program"),
            ),
        ),
        Contract(
            "data-sharded-pallas", "sharded",
            "data-sharded per-shard program collective-free",
            measure_data_sharded,
            (
                Rule("shard_map", ">=", 1,
                     "sharded runner keeps its shard_map"),
                Rule("shard_body.pallas_call", ">=", 1,
                     "shard body keeps its kernel"),
                Rule("shard_body.collectives", "==", 0,
                     "each shard's whole run is independent; the "
                     "status reduce lives outside the shard_map"),
                Rule("hlo.loop_collectives", "==", 0,
                     "no collective in the compiled while-loop closure "
                     "(the ENTRY status all-reduce is permitted)"),
                Rule("hlo.aliased_outputs", ">=", None,
                     "donation floor: the compiler must keep aliasing "
                     "the carried state"),
            ),
            needs_devices=8,
        ),
        Contract(
            "node-sharded-pallas-a2a", "sharded",
            "node-sharded Pallas collectives (a2a schedule, D=4)",
            lambda: measure_node_sharded("pallas", "a2a", 4),
            (
                Rule("ppermute", "==", 0, coll_why),
                Rule("all_to_all", "==", 2, coll_why),
                Rule("psum", "==", 2,
                     "one stacked counter/quiescence psum in the cycle "
                     "+ the per-segment activity seed psum"),
                Rule("pmax", "==", 3,
                     "telemetry pmax + whole-mesh loop gate traced in "
                     "while seed and loop body"),
                Rule("gather", "==", 0, coll_why),
            ),
            needs_devices=4,
        ),
        Contract(
            "node-sharded-jax-a2a", "sharded",
            "node-sharded ops/step.py collectives (a2a schedule, D=4)",
            lambda: measure_node_sharded("jax", "a2a", 4),
            (
                Rule("ppermute", "==", 0, coll_why),
                Rule("all_to_all", "==", 2, coll_why),
                Rule("psum", "==", 2, coll_why),
                Rule("pmax", "==", 1, coll_why),
                Rule("gather", "==", 0, coll_why),
            ),
            needs_devices=4,
        ),
        Contract(
            "node-sharded-jax-pairwise", "sharded",
            "node-sharded ops/step.py collectives (pairwise, D=4)",
            lambda: measure_node_sharded("jax", "pairwise", 4),
            (
                Rule("ppermute", "==", 6,
                     "the pairwise schedule ships 2*(D-1) serial "
                     "ppermutes at D=4 — the PR-15 baseline shape"),
                Rule("all_to_all", "==", 0, coll_why),
                Rule("psum", "==", 2, coll_why),
                Rule("pmax", "==", 1, coll_why),
                Rule("gather", "==", 0, coll_why),
            ),
            needs_devices=4,
        ),
    ]


# -- seeded mutation (negative-test harness) --------------------------


@contextlib.contextmanager
def seeded_mutation(seed: int):
    """Perturb one op module (monkeypatched, restored on exit) so a
    contract check MUST fail with a drift diff — the negative test
    that proves the contracts actually bite.

    seed % 2 == 0: force every exchange plan to the serial pairwise
    schedule (``ops/exchange.make_plan``) — the a2a contracts drift on
    ``ppermute``/``all_to_all``.
    seed % 2 == 1: strip elision from the XLA run builder
    (``ops/step.build_run``) — xla-run-loop drifts on
    ``reduce_min``/``cond``.
    """
    import dataclasses as dc

    from hpa2_tpu.ops import exchange, step

    if seed % 2 == 0:
        mod, attr = exchange, "make_plan"
        orig = exchange.make_plan

        def mutant(d, mode="pairwise", inner=0):
            return orig(d, "pairwise", inner)
    else:
        mod, attr = step, "build_run"
        orig = step.build_run

        def mutant(cfg, *a, **kw):
            return orig(dc.replace(cfg, elide=False), *a, **kw)

    setattr(mod, attr, mutant)
    try:
        yield f"{mod.__name__}.{attr}"
    finally:
        setattr(mod, attr, orig)
