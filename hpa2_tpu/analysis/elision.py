"""Exact-replay model for event-driven cycle elision.

Predicts how many simulated cycles the event-driven device loop
(hpa2_tpu/ops/step.py, ISSUE-12) elides — and how many instructions it
retires inside aggregated multi-hit fast-forwards — WITHOUT running
the JAX engine.  The prediction replays the *exact* jump policy the
device `propose` reduction implements, evaluated against the pure-
Python spec engine's state at every aligned cycle boundary, so the
modeled counters equal a real run's ``elided_cycles`` /
``multi_hit_retired`` stats not within a tolerance band but
bit-for-bit (the same contract :mod:`hpa2_tpu.analysis.occupancy`
gives the scheduler counters; tests/test_elision.py and the tier-1
smoke pin the equality).

Model structure: drive a :class:`~hpa2_tpu.models.spec_engine.
SpecEngine` one cycle at a time.  Before each cycle, mirror the
device's candidate classes host-side —

* per-node **must-step** (0 when the node is send-blocked or its
  mailbox head is deliverable now),
* per-node **topology gate** (head ``deliver_at - cycle`` under a
  non-ideal interconnect),
* per-node **issuer hit-run length** (prefix of the next
  ``_ELISION_WINDOW`` trace entries that are silent cache hits
  against the current cache planes),
* the **watchdog** and **max_cycles** boundary scalars —

take the minimum ``j``, and account one fast-forward (``j - 1``
elided cycles, ``j`` retired instructions per ready issuer) when
``j > 0`` or one lockstep step otherwise.  The spec engine then
advances ``max(j, 1)`` real cycles, keeping model and device state
aligned for the next proposal.

``python -m hpa2_tpu.analysis elision`` renders the model as a table
over workload shapes and asserts model == device on each row.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.models.protocol import CacheState

# mirror of the device constants (ops/step.py): the static multi-hit
# scan window and the "no constraint" distance marker
_ELISION_WINDOW = 64
_FAR = 2**31 - 1


@dataclasses.dataclass
class ElisionPrediction:
    """Modeled counters for one run (field names match the stats
    schema keys the device engines emit)."""

    cycles: int = 0            # final simulated-cycle count
    device_steps: int = 0      # loop iterations the elided run pays
    elided_cycles: int = 0     # cycles skipped by fast-forwards
    multi_hit_retired: int = 0  # instructions retired inside them
    #: per-scheduling-interval elided-cycle totals (empty for the
    #: unchunked whole-run loop) — the occupancy-model extension:
    #: sums to ``elided_cycles``
    per_interval: Tuple[int, ...] = ()

    @property
    def step_reduction(self) -> float:
        """Lockstep device steps over elided device steps."""
        if not self.device_steps:
            return 0.0
        return self.cycles / self.device_steps


def _propose_spec(
    eng: SpecEngine,
    max_cycles: int,
    watchdog_cycles: int,
) -> Tuple[int, int]:
    """The device ``propose`` reduction evaluated on spec state at a
    cycle boundary -> (j, n_issuers)."""
    cfg = eng.config
    topo_on = cfg.interconnect.enabled
    cands: List[int] = [max_cycles - eng.cycle]
    issuers = 0
    any_issuer = False
    for node in eng.nodes:
        blocked = bool(node.pending_sends)
        has_mail = bool(node.mailbox)
        if topo_on and has_mail:
            head_at = node.mailbox[0].deliver_at
            ready_now = head_at <= eng.cycle
            if not ready_now:
                cands.append(head_at - eng.cycle)
        else:
            ready_now = has_mail
        if blocked or ready_now:
            cands.append(0)
        if (
            not has_mail
            and not node.waiting
            and not blocked
            and node.pc < len(node.trace)
        ):
            any_issuer = True
            run = _hit_run(node)
            cands.append(run)
            if run:
                issuers += 1
        # a zero-run issuer forces must-step; count only hit-running
        # issuers toward multi_hit (j > 0 implies all issuers hit-run)
    if watchdog_cycles and not any_issuer:
        gap = eng.last_activity_cycle + watchdog_cycles - eng.cycle
        if gap >= 1:
            cands.append(gap)
    return min(cands), issuers


def _hit_run(node) -> int:
    """Prefix length of silent cache hits from ``node.pc``, capped at
    the device's static scan window (a longer run retires in several
    fast-forwards device-side, and the model sees the same cap)."""
    run = 0
    for k in range(min(_ELISION_WINDOW, len(node.trace) - node.pc)):
        instr = node.trace[node.pc + k]
        line = node.line_for(instr.address)
        if line.address != instr.address:
            break
        if instr.op == "W":
            if line.state not in (CacheState.MODIFIED, CacheState.EXCLUSIVE):
                break
        elif line.state == CacheState.INVALID:
            break
        run += 1
    return run


def predicted_elision(
    config: SystemConfig,
    traces: Sequence[Sequence],
    max_cycles: int = 1_000_000,
    watchdog_cycles: int = 10_000,
    interval: Optional[int] = None,
) -> ElisionPrediction:
    """Replay one system's run through the event-driven jump policy.

    ``interval`` models the *chunked* scheduled loop instead of the
    whole-run loop: jumps are additionally capped at the interval
    barrier (``chunk - c``, exactly ``ops.engine._chunk_loop``) and
    the prediction carries per-interval elided totals — the
    occupancy-model extension for scheduled runs.  The chunk loop's
    propose uses no watchdog/max_cycles boundary (both are enforced
    host-side at barriers), which the model mirrors.
    """
    eng = SpecEngine(config, traces)
    pred = ElisionPrediction()
    per_interval: List[int] = []
    c_in_interval = 0
    interval_elided = 0
    while not eng.quiescent() and eng.cycle < max_cycles:
        if watchdog_cycles and (
            eng.cycle - eng.last_activity_cycle >= watchdog_cycles
        ):
            break
        if interval:
            j, issuers = _propose_spec(eng, _FAR, 0)
            j = min(j, interval - c_in_interval)
        else:
            j, issuers = _propose_spec(eng, max_cycles, watchdog_cycles)
        pred.device_steps += 1
        if j > 0:
            pred.elided_cycles += j - 1
            interval_elided += j - 1
            pred.multi_hit_retired += j * issuers
            for _ in range(j):
                eng.step()
        else:
            eng.step()
        c_in_interval += max(j, 1)
        if interval and c_in_interval >= interval:
            per_interval.append(interval_elided)
            c_in_interval = 0
            interval_elided = 0
    if interval and (c_in_interval or not per_interval):
        per_interval.append(interval_elided)
    pred.cycles = eng.cycle
    pred.per_interval = tuple(per_interval)
    return pred


def predicted_batch_elision(
    config: SystemConfig,
    batch_traces: Sequence[Sequence[Sequence]],
    interval: int,
    max_cycles: int = 1_000_000,
) -> ElisionPrediction:
    """Replay a *batched scheduled* run (all rows resident, one
    group — ``BatchJaxEngine(schedule=Schedule(interval=...,
    resident=None), data_shards=1)``) through the chunked shared-jump
    loop: lanes share one cycle counter, so the device jump is the
    minimum over every lane's candidates and EVERY lane's
    ``n_elided`` advances by ``j - 1`` per jump.  The prediction's
    ``elided_cycles`` therefore equals the lane-summed
    ``elided_cycles`` stat of the scheduled ensemble, and
    ``per_interval`` carries the per-scheduling-interval totals the
    static occupancy model cannot see (it has no protocol state)."""
    lanes = [SpecEngine(config, t) for t in batch_traces]
    b = len(lanes)
    pred = ElisionPrediction()
    per_interval: List[int] = []
    while any(not l.quiescent() for l in lanes):
        interval_elided = 0
        c = 0
        while c < interval and any(not l.quiescent() for l in lanes):
            j = min(
                min(_propose_spec(l, _FAR, 0)[0] for l in lanes),
                interval - c,
            )
            pred.device_steps += 1
            if j > 0:
                pred.elided_cycles += b * (j - 1)
                interval_elided += b * (j - 1)
                for lane in lanes:
                    pred.multi_hit_retired += (
                        j * _propose_spec(lane, _FAR, 0)[1]
                    )
                    for _ in range(j):
                        lane.step()
            else:
                for lane in lanes:
                    lane.step()
            c += max(j, 1)
            if max(l.cycle for l in lanes) >= max_cycles:
                break
        per_interval.append(interval_elided)
        if max(l.cycle for l in lanes) >= max_cycles:
            break
    pred.cycles = max(l.cycle for l in lanes)
    pred.per_interval = tuple(per_interval)
    return pred


def elision_table(
    procs: int = 4,
    instrs: int = 400,
    *,
    spreads: Sequence[float] = (2.0, 4.0, 8.0),
    tail: float = 0.01,
    write_frac: float = 0.3,
    seed: int = 3,
    topology: str = "ideal",
    verify: bool = True,
) -> Tuple[str, int]:
    """The ``analysis elision`` report: predicted elided cycles and
    device-step reduction per Zipf hot-set spread, checked against a
    real device run when ``verify`` (model counters must equal the
    engine's ``elided_cycles`` / ``multi_hit_retired`` stats AND the
    final cycle count, bit-for-bit).  Returns (table, rc) — rc
    nonzero on any model/device mismatch."""
    import numpy as np

    from hpa2_tpu.config import InterconnectConfig, Semantics
    from hpa2_tpu.utils.trace import gen_hot_hit_zipf

    config = SystemConfig(
        num_procs=procs,
        semantics=Semantics().robust(),
        interconnect=InterconnectConfig(topology=topology),
    )
    lines = [
        f"Cycle-elision model  (procs={procs} instrs={instrs} "
        f"tail={tail} write_frac={write_frac} topology={topology} "
        f"seed={seed})",
        f"{'spread':>6} {'cycles':>7} {'steps':>7} {'elided':>7} "
        f"{'multihit':>8} {'reduction':>9}  {'device':>14}",
    ]
    rc = 0
    for spread in spreads:
        traces = gen_hot_hit_zipf(
            config, instrs, seed=seed, write_frac=write_frac,
            spread=spread, tail=tail,
        )
        pred = predicted_elision(config, traces)
        status = "unverified"
        if verify:
            from hpa2_tpu.ops.engine import JaxEngine

            eng = JaxEngine(config, traces).run()
            stats = eng.stats()
            dev = (
                int(np.asarray(eng.state.cycle)),
                stats.get("elided_cycles", 0),
                stats.get("multi_hit_retired", 0),
            )
            mod = (pred.cycles, pred.elided_cycles, pred.multi_hit_retired)
            if dev == mod:
                status = "exact match"
            else:
                status = f"MISMATCH {dev}"
                rc = 1
        lines.append(
            f"{spread:>6.1f} {pred.cycles:>7} {pred.device_steps:>7} "
            f"{pred.elided_cycles:>7} {pred.multi_hit_retired:>8} "
            f"{pred.step_reduction:>8.2f}x  {status:>14}"
        )
    return "\n".join(lines), rc
