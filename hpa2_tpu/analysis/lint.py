"""AST lint for JAX pitfalls and dead spec handlers.

Eight rules, all tuned to be zero-finding on clean engine code:

* **traced-branch** — a Python ``if``/``while``/``assert``/ternary in a
  JAX op module whose test reads a value derived from a ``SimState``
  parameter.  Under ``jit`` such a branch either crashes
  (ConcretizationTypeError) or, worse, bakes in the tracer's abstract
  truthiness; data-dependent control flow must go through
  ``jnp.where``/``lax.select``.  Static facts (``.shape``/``.dtype``/
  ``.ndim``/``is None``) are exempt.
* **nondeterminism** — wall-clock or unseeded randomness in an engine
  path (``models/``, ``ops/``): ``time.*``, module-level ``random.*``,
  ``np.random.*``, ``datetime.now``.  The simulator's claim is
  bit-reproducibility; the reference's thread-timing nondeterminism is
  exactly what this rebuild removed.  Seeded ``random.Random(seed)``
  instances and keyed ``jax.random.*`` are allowed — both are
  deterministic functions of a recorded seed.
* **dtype-drift** — 64-bit JAX dtypes (``jnp.int64`` & co) or
  platform-width ``dtype=int``/``astype(int)`` in op modules.  With
  ``jax_enable_x64`` off these silently narrow to 32 bits, so the code
  computes in a different width than it names.  Host-side ``np.int64``
  is fine (and used deliberately for trace packing).
* **dtype-widening** — arithmetic (or an ``astype``) on a packed
  uint8/uint16 state plane (``cvalw``/``cmetaw``/``dmemw``/``dmetaw``
  and their ``snap_`` twins) outside the sanctioned ``_widen*`` /
  ``_narrow*`` helpers.  JAX promotes the narrow operand silently, so
  a stray ``cmetaw + 1`` computes in int32 and re-materialises the
  plane at 4 bytes/row — exactly the VMEM rent the packed layout pays
  down.  All promotion must funnel through the audited helpers so the
  cycle body stays narrow.
* **dead-handler** — ``spec_engine.py``'s ``_on_*`` methods must all be
  registered in the ``_DISPATCH`` map, every registration must resolve
  to a real method, and every ``MsgType`` must be dispatched.  An
  unregistered handler is dead code that *looks* like protocol
  coverage.
* **interconnect-purity** — ``hpa2_tpu/interconnect/`` may not even
  *import* ``random``/``time``/``datetime``/``uuid``/``secrets``.  The
  interconnect's contract is stronger than the engines': delivery
  cycles are a pure function of config + trace — the fault layer keeps
  a *seeded* RNG, the topology model keeps **none** (its spec/JAX
  agreement proof depends on it), so in this package a seeded
  ``random.Random`` is banned too.
* **hand-written-state** — the device step and the Pallas kernel
  (``ops/step.py``, ``ops/pallas_engine.py``) may not import or spell
  ``CacheState``/``DirState`` enum constants; every protocol state
  must resolve through the compiled ``ProtocolPlanes`` so the
  TransitionTable stays the single source of truth.
* **counter-backfill** — every only-when-nonzero stats counter read
  from a ``SimState`` field in ``ops/engine.py::engine_stats`` must be
  zero-backfilled by the checkpoint loader's ``_ZERO_BACKFILL`` set
  (``utils/checkpoint.py``).  A counter field added without the
  backfill makes every pre-existing checkpoint unloadable — PRs 15
  and 16 both had to hand-patch exactly this.

CLI: ``python -m hpa2_tpu.analysis lint`` (a tier-1 test runs it).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, List, Optional, Set

#: directories (repo-relative) whose files are engine paths
ENGINE_DIRS = (
    os.path.join("hpa2_tpu", "models"),
    os.path.join("hpa2_tpu", "ops"),
    os.path.join("hpa2_tpu", "interconnect"),
)
#: op modules additionally subject to traced-branch and dtype-drift
OPS_DIR = os.path.join("hpa2_tpu", "ops")
#: the interconnect package: subject to the strict purity rule
INTERCONNECT_DIR = os.path.join("hpa2_tpu", "interconnect")

#: parameter names / annotations treated as traced state roots
STATE_PARAM_NAMES = {"st", "state", "sim_state", "nxt", "prev_state"}
STATE_ANNOTATIONS = {"SimState"}
#: attribute leaves that are static under jit (safe to branch on)
STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}

JNP_ALIASES = {"jnp", "jax.numpy"}
WIDE_DTYPES = {"int64", "float64", "uint64"}


@dataclasses.dataclass(frozen=True)
class LintFinding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _attr_root(node: ast.AST) -> Optional[str]:
    """Root Name id of an attribute/subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_static_read(node: ast.AST) -> bool:
    """True if the expression only reads static array facts."""
    return isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS


# ---------------------------------------------------------------------------
# traced-branch
# ---------------------------------------------------------------------------


class _TracedBranchVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_FunctionDef(self, fn: ast.FunctionDef) -> None:
        tainted = self._state_params(fn)
        if tainted:
            self._scan_function(fn, tainted)
        self.generic_visit(fn)

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _state_params(fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for arg in fn.args.args + fn.args.kwonlyargs:
            ann = arg.annotation
            ann_name = ""
            if isinstance(ann, ast.Name):
                ann_name = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                ann_name = ann.value
            if arg.arg in STATE_PARAM_NAMES or ann_name in STATE_ANNOTATIONS:
                out.add(arg.arg)
        return out

    def _scan_function(self, fn: ast.FunctionDef, tainted: Set[str]) -> None:
        # single forward pass: names assigned from tainted expressions
        # join the taint set (good enough for straight-line op code)
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and self._reads_taint(
                stmt.value, tainted
            ):
                for tgt in stmt.targets:
                    for name in ast.walk(tgt):
                        if isinstance(name, ast.Name):
                            tainted.add(name.id)
        for node in ast.walk(fn):
            test = None
            kind = None
            if isinstance(node, (ast.If, ast.While)):
                test, kind = node.test, type(node).__name__.lower()
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "ternary"
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            if test is not None and self._reads_taint(test, tainted):
                self.findings.append(LintFinding(
                    "traced-branch", self.path, node.lineno,
                    f"Python {kind} on a value derived from traced "
                    f"SimState — under jit this is a concretization "
                    f"error; use jnp.where/lax.select"))

    @classmethod
    def _reads_taint(cls, expr: ast.AST, tainted: Set[str]) -> bool:
        # `x is None` / `x is not None` checks identity of the pytree
        # object itself — static under jit
        if isinstance(expr, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
        ):
            return False
        # an explicit bool()/int()/float() cast is deliberate host-side
        # concretization: under a tracer it raises loudly at the cast,
        # the silent footgun this rule exists for is the bare read
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
                and expr.func.id in ("bool", "int", "float"):
            return False
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            return cls._reads_taint(expr.operand, tainted)
        if isinstance(expr, ast.BoolOp):
            return any(cls._reads_taint(v, tainted) for v in expr.values)
        for node in ast.walk(expr):
            if _is_static_read(node):
                continue
            if isinstance(node, ast.Name) and node.id in tainted:
                # direct bare use of the pytree object (truthiness of
                # the NamedTuple) is fine; attribute reads are not
                continue
            if isinstance(node, (ast.Attribute, ast.Subscript)):
                if _is_static_read(node):
                    continue
                root = _attr_root(node)
                if root in tainted:
                    return True
        return False


# ---------------------------------------------------------------------------
# nondeterminism
# ---------------------------------------------------------------------------

_BANNED_CALLS = {
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("time", "time_ns"), ("time", "sleep"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("os", "urandom"), ("uuid", "uuid4"), ("uuid", "uuid1"),
}


class _NondeterminismVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            parent = f.value
            if isinstance(parent, ast.Name):
                pair = (parent.id, f.attr)
                if pair in _BANNED_CALLS:
                    self.findings.append(LintFinding(
                        "nondeterminism", self.path, node.lineno,
                        f"{parent.id}.{f.attr}() in an engine path — "
                        f"simulation results must be a pure function of "
                        f"config + traces + seed"))
                elif parent.id == "random" and f.attr != "Random":
                    # module-level random.* shares hidden global state;
                    # a seeded random.Random(seed) instance is fine
                    self.findings.append(LintFinding(
                        "nondeterminism", self.path, node.lineno,
                        f"module-level random.{f.attr}() — use a seeded "
                        f"random.Random(seed) instance"))
            elif (isinstance(parent, ast.Attribute)
                  and isinstance(parent.value, ast.Name)):
                if (parent.value.id in ("np", "numpy")
                        and parent.attr == "random"):
                    self.findings.append(LintFinding(
                        "nondeterminism", self.path, node.lineno,
                        f"np.random.{f.attr}() uses the hidden global "
                        f"RNG — thread a seeded generator instead"))
                if (parent.value.id == "datetime"
                        and f.attr in ("now", "utcnow", "today")):
                    self.findings.append(LintFinding(
                        "nondeterminism", self.path, node.lineno,
                        f"datetime.{parent.attr}.{f.attr}() in an "
                        f"engine path"))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# interconnect-purity
# ---------------------------------------------------------------------------

#: modules whose mere import is a determinism hazard in the
#: interconnect package (delivery cycles must be a pure function of
#: config + trace — even a seeded PRNG is banned here)
_PURITY_BANNED_MODULES = {"random", "time", "datetime", "uuid", "secrets"}


class _InterconnectPurityVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(LintFinding(
            "interconnect-purity", self.path, node.lineno,
            f"{what} in hpa2_tpu/interconnect/ — delivery delays must "
            f"be a pure function of config + trace (no clocks, no RNG; "
            f"even a seeded random.Random is banned here)"))

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            top = alias.name.split(".")[0]
            if top in _PURITY_BANNED_MODULES:
                self._flag(node, f"import {alias.name}")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        top = (node.module or "").split(".")[0]
        if top in _PURITY_BANNED_MODULES:
            self._flag(node, f"from {node.module} import ...")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # catches uses that dodge the import scan (e.g. np.random.*)
        if isinstance(node.value, ast.Name) and (
            node.value.id in _PURITY_BANNED_MODULES
            or (node.value.id in ("np", "numpy") and node.attr == "random")
        ):
            self._flag(node, f"{node.value.id}.{node.attr}")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# dtype-drift
# ---------------------------------------------------------------------------


class _DtypeDriftVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in WIDE_DTYPES:
            root = node.value
            name = root.id if isinstance(root, ast.Name) else None
            if name in JNP_ALIASES:
                self.findings.append(LintFinding(
                    "dtype-drift", self.path, node.lineno,
                    f"jnp.{node.attr} silently narrows to 32 bits when "
                    f"jax_enable_x64 is off — name the width you get"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg == "dtype" and isinstance(kw.value, ast.Name) \
                    and kw.value.id in ("int", "float"):
                self.findings.append(LintFinding(
                    "dtype-drift", self.path, node.lineno,
                    f"dtype={kw.value.id} is platform-width — spell "
                    f"out the 32-bit dtype"))
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "astype" \
                and node.args and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in ("int", "float"):
            self.findings.append(LintFinding(
                "dtype-drift", self.path, node.lineno,
                f"astype({node.args[0].id}) is platform-width — spell "
                f"out the 32-bit dtype"))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# dtype-widening (packed state planes)
# ---------------------------------------------------------------------------

#: the packed uint8/uint16 state planes (ops/pallas_engine.py
#: ``_PACKED_CACHE`` + ``_PACKED_DIR``), plus their snapshot twins
PACKED_PLANES = frozenset(
    p for base in ("cvalw", "cmetaw", "dmemw", "dmetaw")
    for p in (base, f"snap_{base}")
)
#: the only functions allowed to do arithmetic on packed planes: the
#: in-kernel widen/narrow pairs and the host-side numpy converters
SANCTIONED_WIDENERS = frozenset({
    "_widen", "_narrow",
    "_widen_cache", "_narrow_cache", "_widen_dir", "_narrow_dir",
    "_split_word_planes_np", "_join_word_planes_np",
})


class _DtypeWideningVisitor(ast.NodeVisitor):
    """Flags arithmetic on packed-plane reads outside the sanctioned
    widen/narrow helpers.  A packed-plane read is a Name spelled like
    the plane or a ``Constant``-string subscript of one (``s["cvalw"]``);
    structural ops (gather/where/stack/indexing) pass through
    untouched, so only BinOp/Compare/UnaryOp — and a stray
    ``.astype`` — count as promotion sites."""

    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_FunctionDef(self, fn: ast.FunctionDef) -> None:
        if fn.name in SANCTIONED_WIDENERS:
            return  # the audited promotion sites
        self.generic_visit(fn)

    visit_AsyncFunctionDef = visit_FunctionDef

    @classmethod
    def _packed_read(cls, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name) and node.id in PACKED_PLANES:
            return node.id
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Constant) and sl.value in PACKED_PLANES:
                return sl.value
        return None

    @classmethod
    def _find_packed_read(cls, expr: ast.AST) -> Optional[str]:
        hit = cls._packed_read(expr)
        if hit:
            return hit
        # a call boundary hands the plane to a callee (usually a
        # sanctioned helper) — the callee body is scanned on its own,
        # so the argument read itself is not a promotion
        if isinstance(expr, ast.Call):
            return None
        for child in ast.iter_child_nodes(expr):
            hit = cls._find_packed_read(child)
            if hit:
                return hit
        return None

    def _flag(self, node: ast.AST, plane: str, what: str) -> None:
        self.findings.append(LintFinding(
            "dtype-widening", self.path, node.lineno,
            f"{what} on packed plane {plane!r} outside the sanctioned "
            f"_widen*/_narrow* helpers — the uint8/uint16 plane "
            f"silently promotes to int32 in the kernel body"))

    def visit_BinOp(self, node: ast.BinOp) -> None:
        for side in (node.left, node.right):
            hit = self._find_packed_read(side)
            if hit:
                self._flag(node, hit, "arithmetic")
                break
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for side in [node.left] + list(node.comparators):
            hit = self._find_packed_read(side)
            if hit:
                self._flag(node, hit, "comparison")
                break
        self.generic_visit(node)

    def visit_UnaryOp(self, node: ast.UnaryOp) -> None:
        if isinstance(node.op, (ast.Invert, ast.USub)):
            hit = self._find_packed_read(node.operand)
            if hit:
                self._flag(node, hit, "arithmetic")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "astype":
            hit = self._find_packed_read(f.value) or self._packed_read(
                f.value
            )
            if hit:
                self._flag(node, hit, "astype")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# dead-handler (spec_engine dispatch registration)
# ---------------------------------------------------------------------------


class _HandWrittenStateVisitor(ast.NodeVisitor):
    """Ban hand-written protocol state constants in the device step
    and the Pallas kernel (ISSUE-13).  Those modules must resolve
    every cache/directory state through the compiled ``ProtocolPlanes``
    (hpa2_tpu/protocols/compiler.py) so the TransitionTable stays the
    single source of truth; a ``CacheState.MODIFIED`` literal here is
    a second, silently divergent copy of the protocol."""

    _BANNED = ("CacheState", "DirState")

    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            if alias.name in self._BANNED:
                self.findings.append(LintFinding(
                    "hand-written-state", self.path, node.lineno,
                    f"imports {alias.name} — kernel state constants "
                    f"must come from the compiled ProtocolPlanes "
                    f"(hpa2_tpu/protocols), not the enums"))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name)
                and node.value.id in self._BANNED):
            self.findings.append(LintFinding(
                "hand-written-state", self.path, node.lineno,
                f"hand-written state constant {node.value.id}."
                f"{node.attr} — use the compiled ProtocolPlanes "
                f"lookup instead"))
        self.generic_visit(node)


#: ops modules that must be fully plane-driven (relative paths)
_PLANE_DRIVEN = (
    os.path.join("hpa2_tpu", "ops", "step.py"),
    os.path.join("hpa2_tpu", "ops", "pallas_engine.py"),
)


def _lint_dispatch(path: str, tree: ast.Module) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for cls in tree.body:
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SpecEngine"):
            continue
        handlers = {
            m.name for m in cls.body
            if isinstance(m, ast.FunctionDef) and m.name.startswith("_on_")
        }
        registered: Set[str] = set()
        dispatched_types: Set[str] = set()
        dispatch_line = cls.lineno
        for item in cls.body:
            if isinstance(item, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_DISPATCH"
                for t in item.targets
            ) and isinstance(item.value, ast.Dict):
                dispatch_line = item.lineno
                for k, v in zip(item.value.keys, item.value.values):
                    if isinstance(v, ast.Constant) and isinstance(
                        v.value, str
                    ):
                        registered.add(v.value)
                    if isinstance(k, ast.Attribute):
                        dispatched_types.add(k.attr)
        if not registered:
            findings.append(LintFinding(
                "dead-handler", path, dispatch_line,
                "SpecEngine has no _DISPATCH dict literal — handler "
                "registration is not statically checkable"))
            continue
        for h in sorted(handlers - registered):
            findings.append(LintFinding(
                "dead-handler", path, dispatch_line,
                f"handler method {h} is not registered in _DISPATCH — "
                f"dead code that looks like protocol coverage"))
        for r in sorted(registered - handlers):
            findings.append(LintFinding(
                "dead-handler", path, dispatch_line,
                f"_DISPATCH registers {r} but SpecEngine defines no "
                f"such method"))
        try:
            from hpa2_tpu.models.protocol import MsgType
            missing = {m.name for m in MsgType} - dispatched_types
        except Exception:  # pragma: no cover — protocol must import
            missing = set()
        for m in sorted(missing):
            findings.append(LintFinding(
                "dead-handler", path, dispatch_line,
                f"MsgType.{m} has no _DISPATCH entry — the message "
                f"would hit the unknown-type assertion at runtime"))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _is_engine_path(rel: str) -> bool:
    return any(rel.startswith(d + os.sep) for d in ENGINE_DIRS)


def _is_ops_path(rel: str) -> bool:
    return rel.startswith(OPS_DIR + os.sep)


def lint_file(repo_root: str, rel: str) -> List[LintFinding]:
    path = os.path.join(repo_root, rel)
    with open(path, "r") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [LintFinding("parse", rel, e.lineno or 0, str(e))]
    findings: List[LintFinding] = []
    if _is_engine_path(rel):
        v = _NondeterminismVisitor(rel)
        v.visit(tree)
        findings.extend(v.findings)
    if rel.startswith(INTERCONNECT_DIR + os.sep):
        ip = _InterconnectPurityVisitor(rel)
        ip.visit(tree)
        findings.extend(ip.findings)
    if _is_ops_path(rel):
        tb = _TracedBranchVisitor(rel)
        tb.visit(tree)
        findings.extend(tb.findings)
        dd = _DtypeDriftVisitor(rel)
        dd.visit(tree)
        findings.extend(dd.findings)
        dw = _DtypeWideningVisitor(rel)
        dw.visit(tree)
        findings.extend(dw.findings)
    if rel.endswith(os.path.join("models", "spec_engine.py")):
        findings.extend(_lint_dispatch(rel, tree))
    if any(rel.endswith(p) or rel == p for p in _PLANE_DRIVEN):
        hs = _HandWrittenStateVisitor(rel)
        hs.visit(tree)
        findings.extend(hs.findings)
    return findings


# ---------------------------------------------------------------------------
# counter-backfill (cross-file: ops/engine.py stats vs utils/checkpoint.py)
# ---------------------------------------------------------------------------

_STATS_FILE = os.path.join("hpa2_tpu", "ops", "engine.py")
_CHECKPOINT_FILE = os.path.join("hpa2_tpu", "utils", "checkpoint.py")


def _is_st_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "st")


def _stats_optional_fields(tree: ast.Module):
    """SimState fields feeding only-when-nonzero keys in
    ``engine_stats``: every ``st.<field>`` the function reads OUTSIDE
    the always-present ``core = {...}`` literal (``msg_counts`` is an
    original schema-v1 plane, exempt).  Returns {field: lineno} or
    None when the function is missing."""
    fn = next((n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "engine_stats"), None)
    if fn is None:
        return None
    always: Set[str] = {"msg_counts"}
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)
                and sub.targets[0].id == "core"
                and isinstance(sub.value, ast.Dict)):
            for v in ast.walk(sub.value):
                if _is_st_attr(v):
                    always.add(v.attr)
    fields = {}
    for sub in ast.walk(fn):
        if _is_st_attr(sub) and sub.attr not in always:
            fields.setdefault(sub.attr, sub.lineno)
    return fields


def _checkpoint_backfill(tree: ast.Module) -> Optional[Set[str]]:
    """The names in checkpoint.py's ``_ZERO_BACKFILL`` frozenset
    literal, or None when the assignment is missing."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_ZERO_BACKFILL"):
            return {
                c.value for c in ast.walk(node.value)
                if isinstance(c, ast.Constant)
                and isinstance(c.value, str)
            }
    return None


def lint_counter_backfill(repo_root: str) -> List[LintFinding]:
    """Cross-file rule: only-when-nonzero stats counters must be
    checkpoint-backfilled.  Zero findings when either file is absent
    (synthetic lint-test roots carry only the files they probe)."""
    paths = {}
    for rel in (_STATS_FILE, _CHECKPOINT_FILE):
        full = os.path.join(repo_root, rel)
        if not os.path.isfile(full):
            return []
        with open(full, "r") as f:
            try:
                paths[rel] = ast.parse(f.read(), filename=rel)
            except SyntaxError:
                return []  # the per-file pass reports the parse error
    fields = _stats_optional_fields(paths[_STATS_FILE])
    if fields is None:
        return [LintFinding(
            "counter-backfill", _STATS_FILE, 0,
            "engine_stats() not found — the counter-backfill rule "
            "needs updating for the new stats entry point")]
    backfill = _checkpoint_backfill(paths[_CHECKPOINT_FILE])
    if backfill is None:
        return [LintFinding(
            "counter-backfill", _CHECKPOINT_FILE, 0,
            "_ZERO_BACKFILL frozenset not found — the checkpoint "
            "loader lost its telemetry-counter backfill")]
    return [
        LintFinding(
            "counter-backfill", _STATS_FILE, lineno,
            f"optional stats counter reads st.{field} but "
            f"utils/checkpoint.py::_ZERO_BACKFILL does not backfill "
            f"{field!r} — checkpoints written before the counter "
            f"existed become unloadable")
        for field, lineno in sorted(fields.items())
        if field not in backfill
    ]


def default_targets(repo_root: str) -> List[str]:
    out: List[str] = []
    for d in ENGINE_DIRS:
        full = os.path.join(repo_root, d)
        if not os.path.isdir(full):
            # synthetic lint-test roots carry only the dirs they probe
            continue
        for name in sorted(os.listdir(full)):
            if name.endswith(".py"):
                out.append(os.path.join(d, name))
    return out


def run_lint(repo_root: str, targets: Optional[Iterable[str]] = None
             ) -> List[LintFinding]:
    rels = list(targets) if targets is not None else default_targets(repo_root)
    findings: List[LintFinding] = []
    for rel in rels:
        findings.extend(lint_file(repo_root, rel))
    findings.extend(lint_counter_backfill(repo_root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
