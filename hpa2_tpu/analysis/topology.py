"""Topology sensitivity study: invalidation-storm cost per topology.

Runs one fixed coherence workload — the *invalidation storm*: every
node reads the same line (building a full sharer set), then one node
writes it (a directory fan-out of INVs to everyone), with the writer
rotating per round — through the spec engine under every interconnect
topology and delivery variant, and renders the sensitivity as a table:
how many extra cycles each topology costs over ``ideal``, and how much
of that the ``multicast`` / ``combining`` delivery variants claw back.

Everything here is deterministic (the interconnect model has no RNG —
hpa2_tpu/interconnect/), so the numbers are a pure function of the
arguments and the table is pin-testable (tests/test_interconnect.py).

``python -m hpa2_tpu.analysis topology`` renders the table in the
style of ``analysis vmem`` / ``analysis occupancy``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

from hpa2_tpu.config import InterconnectConfig, SystemConfig
from hpa2_tpu.interconnect.topology import TOPOLOGIES
from hpa2_tpu.models.protocol import Instr

#: delivery variants rendered per topology, name -> config kwargs
VARIANTS = (
    ("unicast", {}),
    ("multicast", {"multicast": True}),
    ("combining", {"combining": True}),
    ("mcast+comb", {"multicast": True, "combining": True}),
)


def storm_traces(config: SystemConfig, rounds: int) -> List[List[Instr]]:
    """The invalidation-storm workload: per round, every node reads a
    shared line, then one node (rotating) writes it — the write's INV
    fan-out hits every other sharer at once, the worst case for a
    topology without multicast, and the all-read phase is the best
    case for request combining."""
    n = config.num_procs
    traces: List[List[Instr]] = [[] for _ in range(n)]
    for r in range(rounds):
        addr = r % (config.num_procs * config.mem_size)
        writer = r % n
        for i in range(n):
            traces[i].append(Instr("R", addr))
        traces[writer].append(Instr("W", addr, value=(r + 1) % 128))
    return traces


def storm_run(
    config: SystemConfig, traces: Sequence[Sequence[Instr]]
) -> Tuple[int, Dict[str, int], dict]:
    """-> (cycles, aggregate counters, per-link stats) for one spec
    run of the storm under ``config``'s interconnect."""
    from hpa2_tpu.models.spec_engine import SpecEngine

    eng = SpecEngine(config, [list(t) for t in traces])
    eng.run()
    link = eng.link_stats() if eng.link_tracker is not None else {}
    return eng.cycle, dict(eng.stats()), link


def topology_table(
    nodes: int = 8,
    rounds: int = 6,
    hop_latency: int = 1,
    bandwidth: int = 1,
    topologies: Sequence[str] = TOPOLOGIES,
) -> str:
    """The ``analysis topology`` report: one row per (topology,
    delivery variant) with run cycles, slowdown over ideal, total
    added delay cycles, the variants' savings counters, and the
    hottest link's peak single-cycle load."""
    base_cfg = SystemConfig(
        num_procs=nodes,
        max_instr_num=0,  # uncapped: the storm sets trace lengths
    )
    traces = storm_traces(base_cfg, rounds)
    ideal_cycles, _, _ = storm_run(base_cfg, traces)

    header = (
        f"{'topology':<14}{'variant':<12}{'cycles':>8}{'xideal':>8}"
        f"{'delay_cyc':>10}{'mc_saved':>9}{'combined':>9}{'peak_link':>10}"
    )
    lines = [
        f"invalidation storm: {nodes} nodes x {rounds} rounds, "
        f"hop={hop_latency}, bw={bandwidth} (ideal: {ideal_cycles} "
        "cycles)",
        header,
        "-" * len(header),
    ]
    for topo in topologies:
        if topo == "ideal":
            continue
        for vname, kw in VARIANTS:
            cfg = dataclasses.replace(
                base_cfg,
                interconnect=InterconnectConfig(
                    topology=topo,
                    hop_latency=hop_latency,
                    link_bandwidth=bandwidth,
                    **kw,
                ),
            )
            cycles, stats, link = storm_run(cfg, traces)
            peak = max(link["max_load"].values(), default=0)
            lines.append(
                f"{topo:<14}{vname:<12}{cycles:>8}"
                f"{cycles / ideal_cycles:>8.2f}"
                f"{stats.get('topo_delay_cycles', 0):>10}"
                f"{stats.get('topo_multicast_saved', 0):>9}"
                f"{stats.get('topo_combined', 0):>9}"
                f"{peak:>10}"
            )
    return "\n".join(lines)
