"""Command-line interface.

The reference binary takes one positional arg (a directory under
``tests/``, assignment.c:119-123) and writes ``core_<n>_output.txt``
into the CWD (assignment.c:831).  This CLI keeps that I/O contract and
adds what the reference hard-codes at compile time: backend selection,
runtime geometry, semantics toggles, replay, and a synthetic benchmark
mode (SURVEY.md §7.2 item 5).

Examples::

    python -m hpa2_tpu run tests/test_1 --backend jax
    python -m hpa2_tpu run tests/test_3 --backend spec \
        --replay tests/test_3/run_1/instruction_order.txt
    python -m hpa2_tpu bench --backend jax --nodes 8 --instrs 1000 \
        --batch 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from hpa2_tpu.config import FaultModel, Semantics, SystemConfig


_QUIRK_FIELDS = {
    "eager-write": "eager_write_request_memory",
    "flush-old-fill": "flush_invack_fills_old_value",
    "overloaded-notify": "overloaded_evict_shared_notify",
}


def _build_config(args) -> SystemConfig:
    import dataclasses

    sem = Semantics()
    if args.head_quirks:
        sem = sem.head_quirks()
    for name in args.quirks.split(",") if args.quirks else []:
        field = _QUIRK_FIELDS.get(name.strip())
        if field is None:
            raise SystemExit(
                f"unknown quirk {name.strip()!r}; choose from "
                + ", ".join(sorted(_QUIRK_FIELDS))
            )
        sem = dataclasses.replace(sem, **{field: True})
    # per-quirk backend validation: the two value quirks are
    # implemented by every backend; only the overloaded EVICT_SHARED
    # upgrade-notify is spec/omp-only (the jit engines' fixed-shape
    # handler grid has no lowering for HEAD's receiver==home
    # disambiguation — ops/step.py:183-188)
    backend = getattr(args, "backend", "spec")
    if sem.overloaded_evict_shared_notify and backend in ("jax", "pallas"):
        raise SystemExit(
            "the overloaded-notify quirk is implemented by the spec and "
            "omp backends only; the jax/pallas engines support the "
            "eager-write and flush-old-fill quirks "
            "(--quirks eager-write,flush-old-fill)"
        )
    if args.robust:
        sem = sem.robust()
    k = getattr(args, "messages_per_cycle", 1)
    if k != 1 and backend != "spec":
        raise SystemExit(
            "--messages-per-cycle > 1 is a spec-engine schedule knob "
            "(PERF.md lever 4); the other backends drain one message "
            "per node per cycle"
        )
    edge_sender = edge_receiver = -1
    if args.fault_edge:
        try:
            s, r = args.fault_edge.split(":")
            edge_sender, edge_receiver = int(s), int(r)
        except ValueError:
            raise SystemExit(
                "--fault-edge takes SENDER:RECEIVER (node ids, -1 = any)"
            )
    fault = FaultModel(
        drop=args.fault_drop,
        duplicate=args.fault_dup,
        reorder=args.fault_reorder,
        delay=args.fault_delay,
        seed=args.fault_seed,
        max_retries=args.fault_max_retries,
        edge_sender=edge_sender,
        edge_receiver=edge_receiver,
    )
    if fault.enabled and backend in ("pallas", "omp"):
        raise SystemExit(
            "fault injection is implemented by the spec and jax "
            "backends (the pallas kernel and the native engine have "
            "no link-layer fault model)"
        )
    from hpa2_tpu.config import InterconnectConfig

    topology = getattr(args, "topology", "ideal")
    interconnect = InterconnectConfig(
        topology=topology,
        hop_latency=getattr(args, "hop_latency", 1),
        link_bandwidth=getattr(args, "link_bandwidth", 1),
        multicast=getattr(args, "multicast", False),
        combining=getattr(args, "combining", False),
        fault=fault,
    )
    if interconnect.enabled:
        if backend not in ("spec", "jax"):
            raise SystemExit(
                "non-ideal topologies are implemented by the spec and "
                "jax backends (the pallas kernel and the native engine "
                "deliver every message next cycle)"
            )
        if getattr(args, "node_shards", 1) != 1:
            raise SystemExit(
                "non-ideal topologies run single-shard only; "
                "--node-shards composes with --topology ideal"
            )
    protocol = getattr(args, "protocol", "mesi")
    directory_format = getattr(args, "directory_format", "full")
    if protocol != "mesi" or directory_format != "full":
        if backend in ("pallas", "omp"):
            raise SystemExit(
                "protocol/directory-format variants are implemented by "
                "the spec and jax backends (the pallas kernel and the "
                "native engine are specialized to MESI/full-bitvector)"
            )
        if getattr(args, "node_shards", 1) != 1:
            raise SystemExit(
                "--node-shards runs the MESI/full-bitvector build only; "
                "protocol variants compose with single-shard jax/spec"
            )
    return SystemConfig(
        num_procs=args.nodes,
        cache_size=args.cache_size,
        mem_size=args.mem_size,
        msg_buffer_size=args.msg_buffer_size,
        max_instr_num=args.max_instr,
        messages_per_cycle=k,
        semantics=sem,
        interconnect=interconnect,
        protocol=protocol,
        directory_format=directory_format,
    )


def _write_dumps(dumps, config, out_dir: str) -> List[str]:
    from hpa2_tpu.utils.dump import format_processor_state

    paths = []
    for d in dumps:
        path = os.path.join(out_dir, f"core_{d.proc_id}_output.txt")
        with open(path, "w") as fh:
            fh.write(format_processor_state(d, config))
        paths.append(path)
    return paths


def _check_shard_args(args) -> None:
    if (args.node_shards > 1 or args.data_shards > 1) and args.backend not in (
        "jax", "pallas"
    ):
        raise SystemExit(
            "--node-shards/--data-shards are jax/pallas-backend "
            "features (device-mesh sharding; the omp/spec backends "
            "are single-host)"
        )
    if args.node_shards > 1 and args.nodes % args.node_shards != 0:
        raise SystemExit(
            f"--node-shards {args.node_shards} must divide --nodes "
            f"{args.nodes} (shards own contiguous equal node blocks)"
        )


def cmd_run(args) -> int:
    config = _build_config(args)
    _check_shard_args(args)
    if (args.crash_at or args.resume) and args.backend != "spec":
        raise SystemExit(
            "--crash-at/--resume checkpoint the spec engine's Python "
            "state (the jax bench path has its own --checkpoint-every)"
        )
    if args.data_shards > 1:
        raise SystemExit(
            "--data-shards applies to bench (--batch > 1 ensembles); "
            "run simulates one system"
        )
    out_dir = args.out or os.getcwd()
    os.makedirs(out_dir, exist_ok=True)

    if args.backend == "omp":
        from hpa2_tpu import native

        res = native.run_trace_dir(
            config,
            args.trace_dir,
            out_dir,
            mode="omp" if args.free_running else "lockstep",
            replay_path=args.replay,
            final_dump=args.final_dump,
            max_cycles=args.max_cycles,
            record_order_path=args.record_order,
            msg_trace_path=args.trace_msgs,
        )
        print(
            f"[omp] {res.instructions} instrs, {res.messages} msgs, "
            f"{res.seconds:.4f}s",
            file=sys.stderr,
        )
        return 0

    from hpa2_tpu.utils.trace import load_instruction_order, load_trace_dir

    # a --resume checkpoint carries its own traces; trace_dir is unused
    traces = None if args.resume else load_trace_dir(args.trace_dir, config)
    replay = load_instruction_order(args.replay) if args.replay else None

    t0 = time.perf_counter()
    if args.backend == "spec":
        from hpa2_tpu.models.spec_engine import SpecEngine
        from hpa2_tpu.utils.checkpoint import (
            load_spec_state,
            save_spec_state,
        )

        if args.resume:
            eng = load_spec_state(args.resume)
            config = eng.config  # the checkpoint's config wins
            print(
                f"resumed from {args.resume} at cycle {eng.cycle}",
                file=sys.stderr,
            )
        else:
            eng = SpecEngine(config, traces, replay_order=replay,
                             trace_msgs=bool(args.trace_msgs))
        if args.crash_at:
            # simulate a mid-run crash: advance to the cycle, persist,
            # exit.  A later --resume run finishes byte-identically.
            while eng.cycle < args.crash_at and not (
                eng.quiescent() and all(n.dumped for n in eng.nodes)
            ):
                eng.step()
            path = args.crash_checkpoint
            save_spec_state(path, eng)
            print(
                f"checkpointed at cycle {eng.cycle} -> {path} "
                "(resume with --resume)",
                file=sys.stderr,
            )
            return 0
        eng.run(max_cycles=args.max_cycles,
                watchdog_cycles=args.watchdog_cycles)
        if args.trace_msgs:
            with open(args.trace_msgs, "w") as f:
                f.writelines(line + "\n" for line in eng.msg_log)
        if args.record_order:
            from hpa2_tpu.utils.trace import format_instruction_order

            with open(args.record_order, "w") as f:
                f.write(format_instruction_order(eng.issue_log))
    else:
        if args.trace_msgs:
            raise SystemExit(
                "--trace-msgs is supported by the spec and omp "
                "backends (the jax engines run entirely on device)"
            )
        if args.record_order:
            raise SystemExit(
                "--record-order is supported by the spec and omp "
                "backends (the jax backend runs entirely on device; "
                "its deterministic schedule is identical to the spec "
                "engine's, so record there)"
            )
        if args.backend == "pallas":
            # the TPU fast path on a single system (batch 1; Mosaic
            # on TPU, interpret elsewhere) — same dumps as the others
            if replay is not None:
                raise SystemExit(
                    "--replay runs on the spec/jax/omp lockstep "
                    "engines (the pallas kernel has no replay mode)"
                )
            from hpa2_tpu.ops.pallas_engine import PallasEngine
            from hpa2_tpu.utils.trace import traces_to_arrays

            if args.node_shards > 1:
                # one system's node axis split over the mesh; delivery
                # is the targeted cross-shard exchange, bit-identical
                # to the single-chip kernel
                from hpa2_tpu.parallel.sharding import (
                    NodeShardedPallasEngine,
                )

                eng = NodeShardedPallasEngine(
                    config, *traces_to_arrays(config, [traces]),
                    node_shards=args.node_shards,
                    snapshots=not args.final_dump,
                )
            else:
                eng = PallasEngine(
                    config, *traces_to_arrays(config, [traces]),
                    snapshots=not args.final_dump,
                )
            eng.run(args.max_cycles)
        elif args.node_shards > 1:
            # multi-chip: shard the simulated-node axis over the mesh
            # (cross-shard delivery = one ICI all_gather per cycle);
            # bit-identical to the single-chip engine
            if replay is not None:
                raise SystemExit(
                    "--replay is single-shard only (fixture replays "
                    "are tiny 4-node systems)"
                )
            from hpa2_tpu.parallel.sharding import (
                NodeShardedEngine,
                make_mesh,
            )

            eng = NodeShardedEngine(
                config,
                traces,
                mesh=make_mesh(node_shards=args.node_shards,
                               data_shards=1),
                max_cycles=args.max_cycles,
            )
            eng.run()
        else:
            from hpa2_tpu.ops.engine import JaxEngine

            eng = JaxEngine(
                config, traces, replay_order=replay,
                max_cycles=args.max_cycles,
                watchdog_cycles=args.watchdog_cycles,
            )
            eng.run()
    dt = time.perf_counter() - t0

    dumps = eng.final_dumps() if args.final_dump else eng.snapshots()
    _write_dumps(dumps, config, out_dir)
    print(
        f"[{args.backend}] {eng.instructions} instrs, {eng.messages} msgs, "
        f"{eng.cycle} cycles, {dt:.4f}s",
        file=sys.stderr,
    )
    return 0


def cmd_bench(args) -> int:
    config = _build_config(args)
    _check_shard_args(args)
    resumed_instrs = 0  # nonzero only when a checkpointed run resumes
    if args.data_shards > 1 and args.batch <= 1:
        raise SystemExit(
            "--data-shards > 1 needs --batch > 1 (an ensemble to "
            "shard); a single system would only be replicated"
        )
    from hpa2_tpu.utils.trace import (
        gen_local_only,
        gen_producer_consumer,
        gen_uniform_random,
    )

    gen = {
        "uniform": gen_uniform_random,
        "producer-consumer": gen_producer_consumer,
        "local": gen_local_only,
    }[args.workload]

    if args.backend == "spec":
        # the executable-spec engine: not a performance path, but the
        # reference point for schedule experiments
        # (--messages-per-cycle, PERF.md lever 4)
        if args.batch > 1:
            raise SystemExit("the spec backend benchmarks batch 1 only")
        from hpa2_tpu.models.spec_engine import SpecEngine

        traces = gen(config, args.instrs, seed=args.seed)
        eng = SpecEngine(config, traces)
        t0 = time.perf_counter()
        eng.run(max_cycles=args.max_cycles,
                watchdog_cycles=args.watchdog_cycles)
        dt = time.perf_counter() - t0
        instrs = eng.instructions
        print(f"[spec] {eng.cycle} cycles", file=sys.stderr)
    elif args.backend == "omp":
        if args.workload != "uniform" or args.batch > 1:
            raise SystemExit(
                "the omp backend benchmarks the uniform workload at "
                "batch 1 only (native trace generation)"
            )
        from hpa2_tpu import native

        res = native.bench_random(
            config,
            instrs_per_core=args.instrs,
            seed=args.seed,
            mode="omp" if args.free_running else "lockstep",
        )
        instrs, dt = int(res.instructions), float(res.seconds)
    elif args.backend == "pallas":
        from hpa2_tpu.ops.pallas_engine import PallasEngine
        from hpa2_tpu.utils.trace import (
            gen_uniform_random_arrays,
            traces_to_arrays,
        )

        if args.workload == "uniform":
            arrays = gen_uniform_random_arrays(
                config, args.batch, args.instrs, seed=args.seed
            )
        else:
            arrays = traces_to_arrays(
                config,
                [
                    gen(config, args.instrs, seed=args.seed + b)
                    for b in range(args.batch)
                ],
            )
        if args.node_shards > 1:
            from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine

            mk = lambda: NodeShardedPallasEngine(
                config, *arrays, node_shards=args.node_shards,
                data_shards=args.data_shards,
            )
        elif args.data_shards > 1:
            from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

            mk = lambda: DataShardedPallasEngine(
                config, *arrays, data_shards=args.data_shards
            )
        else:
            mk = lambda: PallasEngine(config, *arrays)
        mk().run(args.max_cycles)  # warmup
        eng = mk()
        t0 = time.perf_counter()
        eng.run(args.max_cycles)
        dt = time.perf_counter() - t0
        instrs = eng.instructions
        if args.node_shards > 1 and eng.cycle:
            print(
                f"[pallas] cross-shard msgs: {eng.cross_shard_msgs} "
                f"({eng.cross_shard_msgs / eng.cycle:.2f}/cycle)",
                file=sys.stderr,
            )
    elif args.node_shards > 1 or args.data_shards > 1:
        # multi-chip bench: node axis and/or ensemble axis sharded over
        # the device mesh (GridEngine = shard_map(vmap(step)))
        from hpa2_tpu.parallel.sharding import (
            GridEngine,
            NodeShardedEngine,
            make_mesh,
        )

        mesh = make_mesh(
            node_shards=args.node_shards, data_shards=args.data_shards
        )
        if args.batch > 1:
            batch_traces = [
                gen(config, args.instrs, seed=args.seed + b)
                for b in range(args.batch)
            ]
            mk = lambda: GridEngine(
                config, batch_traces, mesh=mesh, max_cycles=args.max_cycles
            )
        else:
            traces = gen(config, args.instrs, seed=args.seed)
            mk = lambda: NodeShardedEngine(
                config, traces, mesh=mesh, max_cycles=args.max_cycles
            )
        mk().run()  # warmup/compile
        eng = mk()
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        instrs = eng.instructions
    elif args.batch > 1:
        import jax
        import jax.numpy as jnp

        from hpa2_tpu.models.spec_engine import StallError
        from hpa2_tpu.ops.engine import build_batched_run
        from hpa2_tpu.ops.state import init_state_batched
        from hpa2_tpu.ops.step import quiescent
        from hpa2_tpu.utils.trace import (
            gen_uniform_random_arrays,
            traces_to_arrays,
        )

        if args.workload == "uniform":
            arrays = gen_uniform_random_arrays(
                config, args.batch, args.instrs, seed=args.seed
            )
        else:
            arrays = traces_to_arrays(
                config,
                [
                    gen(config, args.instrs, seed=args.seed + b)
                    for b in range(args.batch)
                ],
            )
        state = init_state_batched(config, *arrays)
        if args.checkpoint_every:
            # chunked advance with periodic durable checkpoints (and
            # auto-resume), so long runs survive TPU-tunnel flakiness
            from hpa2_tpu.ops.engine import build_batched_run_chunk
            from hpa2_tpu.utils.checkpoint import (
                latest_checkpoint,
                load_state,
                save_state,
            )

            ckdir = args.checkpoint_dir
            os.makedirs(ckdir, exist_ok=True)
            workload_meta = {
                "batch": args.batch, "instrs": args.instrs,
                "workload": args.workload, "seed": args.seed,
            }
            resume = latest_checkpoint(ckdir)
            if resume is not None:
                state, ck_config, ck_meta = load_state(
                    resume, with_meta=True
                )
                # schema-v2 checkpoints always carry the recovery
                # counters in meta; they are history, not workload
                # identity, so they don't participate in the staleness
                # check
                ck_workload = {
                    k: v for k, v in ck_meta.items() if k != "recovery"
                }
                if ck_config != config or ck_workload != workload_meta:
                    raise SystemExit(
                        f"checkpoint {resume} was written for a "
                        "different config/workload; use a fresh "
                        "--checkpoint-dir"
                    )
                print(f"resumed from {resume}", file=sys.stderr)
            run_chunk = build_batched_run_chunk(
                config, args.checkpoint_every
            )
            vq = jax.vmap(quiescent)
            jax.block_until_ready(run_chunk(state))  # warmup/compile
            out = state
            # work already in the checkpoint must not count toward
            # this process's measured rate (read back before the clock
            # starts: the sum forces a device round trip)
            resumed_instrs = int(jnp.sum(out.n_instr))
            t0 = time.perf_counter()
            k = int(jnp.max(out.cycle)) // args.checkpoint_every
            while not bool(jnp.all(vq(out))):
                if bool(jnp.any(out.overflow)):
                    raise StallError(
                        "internal invariant violated: mailbox overflow "
                        "despite backpressure"
                    )
                if int(jnp.max(out.cycle)) >= args.max_cycles:
                    raise StallError("batch did not reach quiescence")
                out = jax.block_until_ready(run_chunk(out))
                k += 1
                save_state(os.path.join(ckdir, f"ckpt_{k}.npz"), out,
                           config, extra_meta=workload_meta)
                # GC during the run: keep the newest two (the previous
                # one guards against a crash mid-write of the newest);
                # tolerate foreign ckpt_*.npz names like
                # latest_checkpoint does
                def _ck_seq(nm):
                    try:
                        return int(nm[5:-4])
                    except ValueError:
                        return None

                stale = sorted(
                    (
                        nm for nm in os.listdir(ckdir)
                        if nm.startswith("ckpt_") and nm.endswith(".npz")
                        and _ck_seq(nm) is not None
                    ),
                    key=_ck_seq,
                )[:-2]
                for old in stale:
                    os.remove(os.path.join(ckdir, old))
            dt = time.perf_counter() - t0
            # completed: clear the checkpoints so a rerun starts fresh
            # instead of instantly "resuming" the quiescent final state
            for name in os.listdir(ckdir):
                if name.startswith("ckpt_") and name.endswith(".npz"):
                    os.remove(os.path.join(ckdir, name))
        else:
            run = build_batched_run(config, max_cycles=args.max_cycles)
            jax.block_until_ready(run(state))  # warmup/compile
            t0 = time.perf_counter()
            out = jax.block_until_ready(run(state))
            dt = time.perf_counter() - t0
        if bool(jnp.any(out.overflow)) or not bool(
            jnp.all(jax.vmap(quiescent)(out))
        ):
            raise StallError("batch did not reach quiescence")
        instrs = int(jnp.sum(out.n_instr)) - resumed_instrs
    else:
        from hpa2_tpu.ops.engine import JaxEngine

        traces = gen(config, args.instrs, seed=args.seed)
        JaxEngine(config, traces, max_cycles=args.max_cycles,
                  watchdog_cycles=args.watchdog_cycles).run()
        eng = JaxEngine(config, traces, max_cycles=args.max_cycles,
                        watchdog_cycles=args.watchdog_cycles)
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        instrs = eng.instructions

    print(
        json.dumps(
            {
                "backend": args.backend,
                "workload": args.workload,
                "nodes": config.num_procs,
                "batch": args.batch,
                "node_shards": args.node_shards,
                "data_shards": args.data_shards,
                "instrs": instrs,
                "resumed_instrs": resumed_instrs,
                "seconds": round(dt, 4),
                "ops_per_sec": round(instrs / dt, 1),
            }
        )
    )
    return 0


def cmd_serve(args) -> int:
    config = _build_config(args)
    backend = args.backend
    if args.node_shards > 1:
        if backend != "pallas":
            raise SystemExit(
                "--node-shards serving runs on the pallas backend "
                "(the resident NodeShardedLaneSession; the jax rows "
                "are single-shard)"
            )
        if args.nodes % args.node_shards != 0:
            raise SystemExit(
                f"--node-shards {args.node_shards} must divide "
                f"--nodes {args.nodes} (shards own contiguous equal "
                "node blocks)"
            )
        backend = "pallas-node-sharded"
    elif backend == "pallas" and args.data_shards > 1:
        backend = "pallas-sharded"
    if (args.jobs is None) == (args.listen is None):
        raise SystemExit(
            "serve needs exactly one job feed: a JOBS.jsonl path or "
            "--listen HOST:PORT"
        )
    if args.wire and not args.listen:
        raise SystemExit(
            "--wire frames the TCP feed; it needs --listen HOST:PORT"
        )
    from hpa2_tpu.service import TenantTable
    from hpa2_tpu.serving import FileJobSource, SocketJobSource, serve

    try:
        tenants = TenantTable.parse(args.tenant_weights or "")
    except ValueError as e:
        raise SystemExit(f"--tenant-weights: {e}")

    plan = None
    if args.failure_plan:
        import dataclasses

        from hpa2_tpu.config import FailurePlan

        try:
            plan = FailurePlan.parse(
                args.failure_plan, seed=args.failure_seed)
        except ValueError as e:
            raise SystemExit(f"--failure-plan: {e}")
        # the plan is config data: record it where checkpoints (and
        # anything else hashing the run) can see it
        config = dataclasses.replace(config, failures=plan)

    targets = None
    if args.migrate_to:
        targets = []
        for part in args.migrate_to.split(","):
            bits = part.strip().split(":")
            if not bits[0] or bits[0] not in ("jax", "pallas"):
                raise SystemExit(
                    "--migrate-to takes backend[:data_shards"
                    "[:node_shards]] entries (backend jax|pallas)")
            t = {"backend": bits[0]}
            try:
                if len(bits) > 1:
                    t["data_shards"] = int(bits[1])
                if len(bits) > 2:
                    t["node_shards"] = int(bits[2])
                    if t["node_shards"] > 1:
                        t["backend"] = "pallas-node-sharded"
            except ValueError:
                raise SystemExit(f"--migrate-to: bad shard count in "
                                 f"{part!r}")
            targets.append(t)

    wire_source = None
    if args.listen:
        host, _, port = args.listen.rpartition(":")
        try:
            port_n = int(port)
        except ValueError:
            raise SystemExit("--listen takes HOST:PORT")
        if args.wire:
            from hpa2_tpu.service import WireJobSource

            source = wire_source = WireJobSource(
                config, host or "127.0.0.1", port_n,
                credits=args.credits, tenants=tenants,
                shed_threshold=args.shed_threshold,
                heartbeat_s=args.heartbeat,
                failures=plan,
            )
            print(
                f"[serve] framed wire on "
                f"{source.address[0]}:{source.address[1]} "
                f"({args.credits} admission credits per connection)",
                file=sys.stderr,
            )
        else:
            source = SocketJobSource(
                config, host or "127.0.0.1", port_n
            )
            print(
                f"[serve] listening on "
                f"{source.address[0]}:{source.address[1]} "
                "(JSONL job records; {\"eof\": true} ends the feed)",
                file=sys.stderr,
            )
    else:
        source = FileJobSource(
            config, args.jobs, timed=not args.immediate
        )

    out = args.out
    results_fh = (
        open(args.results_jsonl, "w") if args.results_jsonl else None
    )

    def emit(res):
        # stream each job's dumps/record the moment its lane retires
        if out:
            d = os.path.join(out, res.job_id)
            os.makedirs(d, exist_ok=True)
            _write_dumps(res.dumps, config, d)
        if results_fh:
            results_fh.write(json.dumps(res.to_record()) + "\n")
            results_fh.flush()
        if wire_source is not None:
            wire_source.deliver(res)

    serve_fn = serve
    serve_kw = {}
    supervised = (plan is not None and plan.enabled
                  ) or args.checkpoint_dir is not None
    if supervised:
        from hpa2_tpu.serving import supervised_serve

        serve_fn = supervised_serve
        if args.checkpoint_dir:
            os.makedirs(args.checkpoint_dir, exist_ok=True)
        serve_kw = dict(
            plan=plan, targets=targets,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
        )
    try:
        _, stats = serve_fn(
            config, source,
            backend=backend,
            resident=args.resident,
            window=args.window,
            block=args.block,
            policy=args.policy,
            data_shards=args.data_shards,
            node_shards=args.node_shards,
            overlap=not args.no_overlap,
            interval=args.interval,
            max_trace_len=args.max_instr,
            max_cycles=args.max_cycles,
            decode_dumps=bool(out),
            emit=emit,
            tenant_weights=tenants.weights or None,
            **serve_kw,
        )
    finally:
        source.close()
        if results_fh:
            results_fh.close()
    print(json.dumps(stats.as_dict()))
    return 0


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--node-shards", type=int, default=1,
        help="jax/pallas backends: shard the simulated-node axis over "
        "this many devices (cross-shard mailbox delivery is a targeted "
        "ppermute exchange — ICI bytes scale with actual crossings, "
        "not num_procs; bit-identical to single-chip)",
    )
    p.add_argument(
        "--data-shards", type=int, default=1,
        help="jax/pallas bench with --batch > 1: shard the ensemble "
        "axis over this many devices (the DP analog; composes with "
        "--node-shards as a data x node mesh)",
    )
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--cache-size", type=int, default=4)
    p.add_argument("--mem-size", type=int, default=16)
    p.add_argument("--msg-buffer-size", type=int, default=256)
    p.add_argument(
        "--max-instr", type=int, default=32,
        help="per-core trace cap (reference MAX_INSTR_NUM)",
    )
    p.add_argument("--max-cycles", type=int, default=1_000_000)
    p.add_argument(
        "--messages-per-cycle", type=int, default=1,
        help="lockstep schedule: messages drained per node per cycle "
        "(spec backend; >1 shortens latency chains on queue-bound "
        "workloads)",
    )
    p.add_argument(
        "--protocol", default="mesi",
        choices=("mesi", "moesi", "mesif"),
        help="coherence protocol variant, compiled from its "
        "TransitionTable into the kernels (hpa2_tpu/protocols/); "
        "'mesi' is the reference protocol and stays bit-identical to "
        "the hand-written build.  moesi/mesif run on the spec and jax "
        "backends",
    )
    p.add_argument(
        "--directory-format", default="full", metavar="FMT",
        help="directory sharer representation: 'full' (exact "
        "bitvector, the reference), 'limited:K' (K pointers, "
        "overflow -> broadcast), 'coarse:G' (G-node groups).  "
        "Non-full formats run on the spec and jax backends",
    )
    p.add_argument(
        "--robust", action="store_true",
        help="NACK/retry on stale interventions (sound at scale; "
        "SURVEY.md §6.3)",
    )
    p.add_argument(
        "--head-quirks", action="store_true",
        help="emulate ALL reference-HEAD divergences from its own "
        "fixtures (SURVEY.md §6.2); spec and omp backends",
    )
    p.add_argument(
        "--quirks", default="", metavar="LIST",
        help="comma-separated HEAD quirks to enable individually: "
        "eager-write, flush-old-fill (all backends), "
        "overloaded-notify (spec/omp only)",
    )
    p.add_argument(
        "--free-running", action="store_true",
        help="omp backend: thread-per-node free-running mode like the "
        "reference (nondeterministic interleavings)",
    )
    fg = p.add_argument_group(
        "fault injection (spec/jax backends; faults are masked by "
        "link-layer retry — dumps stay byte-identical to a fault-free "
        "run unless a link is fully severed)"
    )
    fg.add_argument(
        "--fault-drop", type=float, default=0.0, metavar="P",
        help="per-hop drop probability (each dropped copy is "
        "retransmitted in-cycle, up to --fault-max-retries)",
    )
    fg.add_argument(
        "--fault-dup", type=float, default=0.0, metavar="P",
        help="per-delivery duplicate probability (duplicates are "
        "filtered by sequence number; counted in stats)",
    )
    fg.add_argument(
        "--fault-reorder", type=float, default=0.0, metavar="P",
        help="per-delivery reorder probability (reassembled back to "
        "FIFO order at the receiver; counted in stats)",
    )
    fg.add_argument(
        "--fault-delay", type=float, default=0.0, metavar="P",
        help="per-delivery extra-latency probability (absorbed within "
        "the delivery cycle; counted in stats)",
    )
    fg.add_argument("--fault-seed", type=int, default=0)
    fg.add_argument(
        "--fault-max-retries", type=int, default=64,
        help="in-cycle retransmission budget per message; exhaustion "
        "defers the send to the next cycle (backpressure path)",
    )
    fg.add_argument(
        "--fault-edge", default="", metavar="S:R",
        help="restrict faults to the directed link S->R (-1 = any); "
        "e.g. --fault-drop 1.0 --fault-edge 1:0 severs one link to "
        "exercise the watchdog",
    )
    tg = p.add_argument_group(
        "interconnect topology (spec/jax backends; the default "
        "'ideal' delivers every message next cycle — byte-identical "
        "to the pre-topology engines)"
    )
    tg.add_argument(
        "--topology", default="ideal",
        choices=("ideal", "mesh2d", "torus2d", "hierarchical"),
        help="per-message delivery delay model: base hop latency "
        "along the routed path plus deterministic per-link queueing "
        "under finite bandwidth (hpa2_tpu/interconnect/)",
    )
    tg.add_argument(
        "--hop-latency", type=int, default=1, metavar="CYC",
        help="cycles per hop (DCN tier of 'hierarchical' costs 4x)",
    )
    tg.add_argument(
        "--link-bandwidth", type=int, default=1, metavar="MSGS",
        help="messages per link per cycle before queueing delay "
        "accrues (deterministic FIFO, tie-break by walk order)",
    )
    tg.add_argument(
        "--multicast", action="store_true",
        help="invalidation fan-outs traverse each shared path link "
        "once instead of once per destination",
    )
    tg.add_argument(
        "--combining", action="store_true",
        help="same-address read requests merge in-network (only the "
        "first occupies the links)",
    )
    p.add_argument(
        "--watchdog-cycles", type=int, default=10_000, metavar="K",
        help="raise a structured StallDiagnostic when no instruction "
        "retires and no mailbox drains for K cycles (0 disables); "
        "spec and jax backends",
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hpa2_tpu",
        description="TPU-native directory-MESI DSM simulator",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser("run", help="run a trace directory, write dumps")
    rp.add_argument("trace_dir")
    rp.add_argument(
        "--backend", choices=("spec", "jax", "omp", "pallas"),
        default="jax",
    )
    rp.add_argument("--out", help="output directory (default: CWD)")
    rp.add_argument(
        "--replay", help="instruction_order.txt to replay", default=None
    )
    rp.add_argument(
        "--trace-msgs", metavar="PATH", default=None,
        help="write a per-message send/receive log in the reference's "
             "DEBUG_MSG format (assignment.c:170-174, 734-738); spec "
             "and omp backends",
    )
    rp.add_argument(
        "--record-order", default=None, metavar="PATH",
        help="write the executed issue interleaving in DEBUG_INSTR "
        "format (replayable via --replay; mints new fixture run-sets)",
    )
    rp.add_argument(
        "--final-dump", action="store_true",
        help="dump final quiescent state instead of at local completion",
    )
    rp.add_argument(
        "--crash-at", type=int, default=0, metavar="CYCLE",
        help="spec backend: simulate a crash — advance to CYCLE, "
        "write --crash-checkpoint, exit (no dumps)",
    )
    rp.add_argument(
        "--crash-checkpoint", default="hpa2_spec_ckpt.json",
        metavar="PATH",
        help="where --crash-at persists the engine state (JSON)",
    )
    rp.add_argument(
        "--resume", default=None, metavar="PATH",
        help="spec backend: resume from a --crash-at checkpoint and "
        "finish the run (byte-identical to an uninterrupted run, "
        "fault stream included; trace_dir is ignored)",
    )
    _add_common(rp)
    rp.set_defaults(fn=cmd_run)

    bp = sub.add_parser("bench", help="synthetic benchmark, JSON result")
    bp.add_argument(
        "--backend", choices=("jax", "pallas", "omp", "spec"),
        default="jax",
    )
    bp.add_argument(
        "--workload",
        choices=("uniform", "producer-consumer", "local"),
        default="uniform",
    )
    bp.add_argument("--instrs", type=int, default=1000)
    bp.add_argument("--batch", type=int, default=1)
    bp.add_argument("--seed", type=int, default=0)
    bp.add_argument(
        "--checkpoint-every", type=int, default=0, metavar="CYCLES",
        help="jax backend with --batch > 1: checkpoint the full state "
        "every CYCLES cycles and auto-resume from the latest "
        "checkpoint in --checkpoint-dir (long runs survive TPU-tunnel "
        "flakiness)",
    )
    bp.add_argument("--checkpoint-dir", default="hpa2_ckpt")
    _add_common(bp)
    bp.set_defaults(fn=cmd_bench)

    sp = sub.add_parser(
        "serve",
        help="always-on serving: admit a continuous JSONL job feed "
        "into resident lanes without recompiling",
    )
    sp.add_argument(
        "jobs", nargs="?", default=None,
        help="JSONL jobs file (one job per line; see README "
        "'Always-on serving'); omit when using --listen",
    )
    sp.add_argument(
        "--listen", metavar="HOST:PORT", default=None,
        help="accept JSONL job records over TCP instead of a file; "
        "a {\"eof\": true} record ends the feed",
    )
    sp.add_argument(
        "--wire", action="store_true",
        help="with --listen: speak the framed wire protocol "
        "(hpa2_tpu/service/) instead of raw JSONL — every SUBMIT is "
        "ACK'd with its admission seq or NACK'd with a reason, "
        "results stream back to the submitting connection, and "
        "overload pushes back via admission credits",
    )
    sp.add_argument(
        "--credits", type=int, default=64,
        help="--wire: admission credits per connection (how far a "
        "client may run ahead of the scheduler before drawing NACKs)",
    )
    sp.add_argument(
        "--tenant-weights", default=None, metavar="NAME:W,...",
        help="fair-share weights for --policy fair-drr (e.g. "
        "'alice:4,bob:1'; unlisted tenants weigh 1.0)",
    )
    sp.add_argument(
        "--backend", choices=("pallas", "jax"), default="pallas",
        help="pallas = resident-lane fast path (--data-shards > 1 "
        "shards lanes over the device mesh, --node-shards > 1 splits "
        "each system's node axis — jobs bigger than a chip); jax = "
        "XLA batch rows (the backend with fault injection)",
    )
    sp.add_argument(
        "--resident", type=int, default=16,
        help="device-resident lanes/rows (the fixed serving shape)",
    )
    sp.add_argument(
        "--window", type=int, default=16,
        help="pallas backend: trace-window segment length",
    )
    sp.add_argument(
        "--block", type=int, default=1024,
        help="pallas backend: lane block width (clamped to resident)",
    )
    sp.add_argument(
        "--interval", type=int, default=256,
        help="jax backend: cycles per chunk between completion checks",
    )
    sp.add_argument(
        "--policy", default="fcfs",
        choices=("fcfs", "longest-first", "deadline-edf", "fair-drr"),
        help="admission queue order at segment barriers: fcfs, "
        "longest-first, deadline-edf (earliest absolute deadline "
        "first), fair-drr (per-tenant weighted deficit round robin; "
        "see --tenant-weights)",
    )
    sp.add_argument(
        "--immediate", action="store_true",
        help="ignore per-job arrival offsets; release the whole jobs "
        "file at once (deterministic replay mode)",
    )
    sp.add_argument(
        "--no-overlap", action="store_true",
        help="sync the device after every dispatch instead of "
        "pipelining host staging one interval ahead (the serial "
        "baseline the benchmark compares against)",
    )
    sp.add_argument(
        "--out", default=None,
        help="write each job's dumps to OUT/<job-id>/"
        "core_<n>_output.txt as its lane retires",
    )
    sp.add_argument(
        "--results-jsonl", default=None, metavar="PATH",
        help="stream one JSON result record (latency, counters) per "
        "completed job",
    )
    fp = sp.add_argument_group("fault tolerance")
    fp.add_argument(
        "--failure-plan", default=None, metavar="SPEC",
        help="seeded failure injection: 'kind@interval[:target]' "
        "events joined by ';' — kinds kill (backend dies at the "
        "interval barrier), hang (shard stalls; the watchdog "
        "detects), poison (lane block corrupted; re-run same spec), "
        "sever (wire connection cut mid-frame at ack seq TARGET). "
        "Arms the recovery supervisor (checkpointed live migration)",
    )
    fp.add_argument("--failure-seed", type=int, default=0,
                    help="seed folded into the failure plan (jitters "
                    "client backoff; the plan itself is deterministic)")
    fp.add_argument(
        "--migrate-to", default=None, metavar="B[:D[:N]],...",
        help="migration target rotation: backend[:data_shards"
        "[:node_shards]] entries tried in order on each kill/hang "
        "(default: cross the pallas<->jax divide)",
    )
    fp.add_argument(
        "--checkpoint-dir", default=None, metavar="DIR",
        help="supervisor checkpoints (schema-v2 npz mid-state on the "
        "jax backend, JSON manifests on pallas) land here every "
        "--checkpoint-every interval barriers",
    )
    fp.add_argument("--checkpoint-every", type=int, default=1,
                    metavar="K")
    fp.add_argument(
        "--shed-threshold", type=int, default=0, metavar="N",
        help="--wire: graceful degradation — once N jobs are pending, "
        "batch-class SUBMITs draw a structured 'shed' NACK instead of "
        "queueing (interactive traffic keeps flowing)",
    )
    fp.add_argument(
        "--heartbeat", type=float, default=0.0, metavar="SECS",
        help="--wire: emit HEARTBEAT frames to idle connections every "
        "SECS seconds so clients can tell a slow server from a dead "
        "one",
    )
    _add_common(sp)
    sp.set_defaults(fn=cmd_serve)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
