import sys

from hpa2_tpu.cli import main

sys.exit(main())
