"""The executable spec: a deterministic lockstep directory-MESI engine.

This pure-Python engine defines the protocol semantics that every
production backend (JAX in ``hpa2_tpu.ops``, C++/OpenMP in ``native/``)
must match — it is the differential-test oracle (SURVEY.md §7.1).

Semantics are the reference's (assignment.c:187-697) with the
fixture-semantics deviations of SURVEY.md §6.2 as the default (see
``hpa2_tpu.config.Semantics``).  Scheduling replaces the reference's
free-running OpenMP threads (one thread per node, racy, non-terminating
— assignment.c:135-153) with a deterministic global-cycle lockstep:

  Each cycle:
    1. *handle*: every node with a non-empty mailbox pops exactly ONE
       message (FIFO) and runs the protocol handler for it.
    2. *issue*: every node whose mailbox is now empty and that is not
       waiting for a reply issues at most one instruction — this is
       exactly the reference's drain-all-then-issue loop shape
       (assignment.c:153-699) unrolled one message per cycle.  In
       *replay* mode only the node matching the next record of a
       recorded ``instruction_order.txt`` may issue, pinning the
       interleaving that produced a given fixture set (SURVEY.md §4).
    3. *deliver*: all messages sent in 1-2 are appended to receiver
       mailboxes in deterministic order (handle-phase sends first,
       then issue-phase sends; within a phase by sender id, preserving
       each sender's emission order).
    4. *dump*: a node whose trace is exhausted, that is not waiting and
       whose mailbox is empty (including this cycle's deliveries)
       snapshots its state once — the reference's
       dump-at-local-completion semantics (assignment.c:688-697),
       which still drains in-flight messages first (observed in
       tests/sample: node 0's dump contains node 1's later
       EVICT_MODIFIED value).

  Termination = global quiescence: all traces exhausted, nobody
  waiting, all mailboxes empty (the reference never terminates,
  assignment.c:153; SURVEY.md §2.3).
"""

from __future__ import annotations

import collections
import dataclasses
import random
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import (
    CacheState,
    DirState,
    Instr,
    INVALID_ADDR,
    Message,
    MsgType,
    NO_PROC,
    REPLY_RD_SHARED,
    bit,
    count_sharers,
    find_owner,
    is_bit_set,
)
from hpa2_tpu.protocols.compiler import generated_dispatch, planes_for
from hpa2_tpu.protocols.directory import dir_mask_int, parse_format
from hpa2_tpu.utils.dump import NodeDump
from hpa2_tpu.utils.trace import IssueRecord, TraceRing


@dataclasses.dataclass
class CacheLine:
    address: int = INVALID_ADDR
    value: int = 0
    state: CacheState = CacheState.INVALID


@dataclasses.dataclass
class DirEntry:
    state: DirState = DirState.U
    sharers: int = 0
    # tracked owner/forwarder node (NO_PROC = none).  MOESI: the OWNED
    # cache while state == SO; MESIF: the FORWARD cache while state ==
    # S.  MESI never writes it.
    owner: int = NO_PROC


class Node:
    """Private state of one processor node (assignment.c:70-81)."""

    def __init__(self, node_id: int, config: SystemConfig, trace: Sequence[Instr]):
        self.id = node_id
        self.config = config
        # owner-plane protocols carry dir_owner in their dumps; MESI
        # keeps NodeDump.dir_owner = None so parity comparisons against
        # native/fixture dumps stay field-for-field exact
        self._dump_owner = planes_for(
            config.protocol, config.semantics
        ).has_owner_plane
        # memory init: 20 * id + i, byte-wrapped (assignment.c:779)
        self.memory: List[int] = [
            (20 * node_id + i) % 256 for i in range(config.mem_size)
        ]
        self.directory: List[DirEntry] = [
            DirEntry() for _ in range(config.mem_size)
        ]
        self.cache: List[CacheLine] = [CacheLine() for _ in range(config.cache_size)]
        self.trace: List[Instr] = list(trace)
        self.pc = 0
        self.waiting = False
        self.pending_write = 0
        self.mailbox: Deque[Message] = collections.deque()
        # deferred sends: messages from this node's last action that
        # did not fit their receiver's mailbox (capacity backpressure).
        # While non-empty the node is BLOCKED — it neither handles nor
        # issues — the lockstep analog of the reference's blocking
        # enqueue (assignment.c:715-724, busy-wait on full buffer).
        # Entries are (phase, receiver, Message) in emission order.
        self.pending_sends: List[Tuple[int, int, Message]] = []
        self.dumped = False
        self.snapshot: Optional[NodeDump] = None
        # every legal dump-at-local-completion state: the state at
        # completion plus the state after each later handled message.
        # The reference's dump timing is OS-scheduling-dependent (a
        # thread may be descheduled between finishing its trace and
        # dumping, so the dump can include effects of arbitrarily many
        # later messages — fixture evidence: tests/test_3/run_1 core_1
        # reflects an INV issued 13 records after core_1's last
        # instruction).  Parity therefore matches fixtures against the
        # candidate set.
        self.dump_candidates: List[NodeDump] = []

    # -- helpers ------------------------------------------------------

    def line_for(self, addr: int) -> CacheLine:
        return self.cache[self.config.cache_index_of(addr)]

    def dump(self) -> NodeDump:
        return NodeDump(
            proc_id=self.id,
            memory=list(self.memory),
            dir_state=[d.state for d in self.directory],
            dir_sharers=[d.sharers for d in self.directory],
            cache_addr=[l.address for l in self.cache],
            cache_value=[l.value for l in self.cache],
            cache_state=[l.state for l in self.cache],
            dir_owner=(
                [d.owner for d in self.directory]
                if self._dump_owner else None
            ),
        )


class StallError(RuntimeError):
    """Raised when the engine stops making progress (protocol livelock,
    or an unachievable replay order)."""


class StallDiagnostic(StallError):
    """Structured stall/watchdog diagnostic.

    A ``StallError`` subclass (every existing ``except StallError``
    keeps working) carrying the machine-readable state a livelock
    post-mortem needs: per-node mailbox depth, waiting/send-blocked
    sets, cache-line states, the recent-delivery flight recorder, an
    advisory mid-flight invariant check, and the engine counters.
    """

    def __init__(
        self,
        reason: str,
        cycle: int,
        mailbox_depths: Dict[int, int],
        waiting: List[int],
        blocked: List[int],
        line_states: Dict[int, List[str]],
        recent_msgs: List[str],
        invariant_violations: List[str],
        counters: Dict[str, int],
    ):
        self.reason = reason
        self.cycle = cycle
        self.mailbox_depths = mailbox_depths
        self.waiting = waiting
        self.blocked = blocked
        self.line_states = line_states
        self.recent_msgs = recent_msgs
        self.invariant_violations = invariant_violations
        self.counters = counters
        super().__init__(self._render())

    def _render(self) -> str:
        out = [
            f"{self.reason} (cycle {self.cycle})",
            f"  waiting nodes: {self.waiting}; "
            f"send-blocked nodes: {self.blocked}",
            "  mailbox depths: "
            + ", ".join(f"{n}:{d}" for n, d in self.mailbox_depths.items()),
        ]
        for node, lines in self.line_states.items():
            if lines:
                out.append(f"  node {node} cache: " + ", ".join(lines))
        if self.recent_msgs:
            out.append(f"  last {len(self.recent_msgs)} deliveries:")
            out.extend("    " + m for m in self.recent_msgs)
        if self.invariant_violations:
            out.append("  invariant check (mid-flight subset):")
            out.extend("    " + m for m in self.invariant_violations)
        return "\n".join(out)


class SpecEngine:
    def __init__(
        self,
        config: SystemConfig,
        traces: Sequence[Sequence[Instr]],
        replay_order: Optional[Sequence[IssueRecord]] = None,
        replay_batched: bool = False,
        trace_msgs: bool = False,
        debug_invariants: bool = False,
    ):
        if len(traces) != config.num_procs:
            raise ValueError("need one trace per node")
        self.config = config
        self.sem: Semantics = config.semantics
        # the compiled protocol: every state-set guard and reply kind
        # below reads these planes, never a hand-written constant that
        # differs between protocols
        self.planes = planes_for(config.protocol, config.semantics)
        self._dir_kind, self._dir_param = parse_format(
            config.directory_format, config.num_procs
        )
        self._rd_fill = dict(self.planes.reply_rd_fill)
        self._notify_map = dict(self.planes.notify_pairs)
        self.nodes = [Node(i, config, t) for i, t in enumerate(traces)]
        self.replay_order = list(replay_order) if replay_order is not None else None
        # "batched" replay lets consecutive order records issue in the
        # same cycle (one per node) — modeling near-simultaneous issues
        # whose requests race to a home in sender-id order rather than
        # strictly in recorded-log order (the DEBUG_INSTR log captures
        # issue order, not message-arrival order; SURVEY.md §7.4.2).
        self.replay_batched = replay_batched
        self.order_pos = 0
        self.cycle = 0
        # pending sends for the current cycle: (phase, sender, receiver, Message)
        self._outbox: List[Tuple[int, int, int, Message]] = []
        # observability (the reference has none — SURVEY.md §5)
        self.counters: Dict[str, int] = collections.defaultdict(int)
        self.max_mailbox_depth = 0
        # the executed issue interleaving, one IssueRecord per issued
        # instruction — the DEBUG_INSTR log (assignment.c:596-597)
        self.issue_log: List[IssueRecord] = []
        # per-message send/receive log in the reference's DEBUG_MSG
        # format (assignment.c:170-174 receive, 734-738 send); sends
        # log at mailbox enqueue (the sendMessage analog), receives at
        # dequeue
        self.trace_msgs = trace_msgs
        self.msg_log: List[str] = []
        # link-layer fault injection (None when all rates are 0: the
        # fault-free hot path stays draw-free and branch-free)
        self._fault_rng: Optional[random.Random] = (
            random.Random(config.fault.seed) if config.fault.enabled else None
        )
        # topology-aware interconnect (None for the ideal topology:
        # delivery stays next-cycle and zero-cost, byte-identical to
        # the pre-topology engine)
        ic = config.interconnect
        self.link_tracker = None
        if ic.enabled:
            from hpa2_tpu.interconnect.delay import LinkTracker
            from hpa2_tpu.interconnect.topology import build_topology

            self.link_tracker = LinkTracker(
                build_topology(ic.topology, config.num_procs,
                               ic.hop_latency),
                bandwidth=ic.link_bandwidth,
                multicast=ic.multicast,
                combining=ic.combining,
            )
        # watchdog bookkeeping: last cycle that retired an instruction
        # or drained a mailbox, plus the delivery flight recorder
        self.last_activity_cycle = 0
        self.recent_msgs = TraceRing()
        # per-step mid-flight invariant checking (debug aid; O(N*M)
        # per cycle, keep off in sweeps)
        self.debug_invariants = debug_invariants

    @property
    def instructions(self) -> int:
        return self.counters["instructions"]

    @property
    def messages(self) -> int:
        return self.counters["msgs_total"]

    # -- transport ----------------------------------------------------

    def _send(self, phase: int, receiver: int, msg: Message) -> None:
        """Buffer a send for end-of-cycle delivery (the lockstep analog
        of sendMessage's locked enqueue, assignment.c:711-739)."""
        self.counters[f"msg_{msg.type.name}"] += 1
        self.counters["msgs_total"] += 1
        self._outbox.append((phase, msg.sender, receiver, msg))

    def _wire(self, sender: int, receiver: int) -> bool:
        """Simulate one message crossing the faulty link (link-layer
        reliable transport: seq/ack with in-cycle retransmission).

        Drops are retried with fresh randomness up to
        ``fault.max_retries`` rounds; duplicate, reorder and delay
        events are absorbed by the receiver's link layer (dup filter,
        reassembly window, skew buffer) and surface only as counters.
        Returns True once a copy gets through; False when the retry
        budget is exhausted — the caller then defers the message to
        the sender's pending queue and the link retries next cycle.
        """
        fm = self.config.fault
        if not fm.applies(sender, receiver):
            return True
        rng = self._fault_rng
        rounds = 0
        while rng.random() < fm.drop:
            rounds += 1
            if rounds >= fm.max_retries:
                self.counters["fault_drops"] += rounds
                self.counters["fault_link_stalls"] += 1
                return False
        if rounds:
            self.counters["fault_drops"] += rounds
            self.counters["fault_retransmissions"] += rounds
        if fm.duplicate > 0.0 and rng.random() < fm.duplicate:
            self.counters["fault_dups_filtered"] += 1
        if fm.reorder > 0.0 and rng.random() < fm.reorder:
            self.counters["fault_reorders_fixed"] += 1
        if fm.delay > 0.0 and rng.random() < fm.delay:
            self.counters["fault_delays"] += 1
        return True

    def _deliver(self) -> bool:
        """End-of-cycle delivery with capacity backpressure.

        Candidates are walked in the global deterministic order
        (phase, sender, emission order) — pending (deferred) sends at
        their original positions, this cycle's new sends at theirs (a
        node never has both: blocked nodes don't act).  A candidate is
        accepted iff its receiver's mailbox has a free slot at that
        point of the walk AND it crosses the (possibly faulty) link
        within the retry budget; rejected candidates become (stay) the
        sender's pending_sends, preserving order.  Once an edge stalls
        this cycle, every later candidate on the same (sender,
        receiver) edge defers too, keeping per-edge FIFO exact.
        Returns True if any message was delivered (progress).
        """
        cap = self.config.msg_buffer_size
        merged: List[Tuple[int, int, int, Message]] = []
        for node in self.nodes:
            for ph, receiver, msg in node.pending_sends:
                merged.append((ph, node.id, receiver, msg))
            node.pending_sends = []
        merged.extend(self._outbox)
        self._outbox.clear()
        merged.sort(key=lambda t: (t[0], t[1]))  # stable
        delivered_any = False
        fault_on = self._fault_rng is not None
        tracker = self.link_tracker
        if tracker is not None:
            tracker.begin_cycle()
        stalled_edges = set()
        for ph, sender, receiver, msg in merged:
            box = self.nodes[receiver].mailbox
            ok = len(box) < cap
            if ok and fault_on:
                edge = (sender, receiver)
                if edge in stalled_edges:
                    ok = False
                elif not self._wire(sender, receiver):
                    stalled_edges.add(edge)
                    ok = False
            if ok:
                if tracker is not None:
                    msg.deliver_at = tracker.on_accept(
                        self.cycle, sender, receiver, int(msg.type),
                        msg.address,
                        is_inv=msg.type == MsgType.INV,
                        is_read_request=msg.type == MsgType.READ_REQUEST,
                    )
                box.append(msg)
                delivered_any = True
                self.recent_msgs.record(
                    self.cycle, msg.sender, receiver,
                    int(msg.type), msg.address,
                )
                if self.trace_msgs:
                    self.msg_log.append(
                        f"Processor {msg.sender} sent msg to: "
                        f"{receiver}, type: {int(msg.type)}, "
                        f"address: 0x{msg.address:02X}"
                    )
                if len(box) > self.max_mailbox_depth:
                    self.max_mailbox_depth = len(box)
            else:
                self.nodes[sender].pending_sends.append((ph, receiver, msg))
        if tracker is not None:
            tracker.end_cycle()
        return delivered_any

    # -- cache replacement (assignment.c:742-773) ---------------------

    def _replace(self, phase: int, node: Node, line: CacheLine) -> None:
        if line.state == CacheState.INVALID or line.address == INVALID_ADDR:
            return
        home = self.config.home_of(line.address)
        self.counters["evictions"] += 1
        if int(line.state) in self.planes.dirty_evict_states:
            self._send(
                phase,
                home,
                Message(
                    MsgType.EVICT_MODIFIED, node.id, line.address, value=line.value
                ),
            )
        else:
            self._send(
                phase,
                home,
                Message(MsgType.EVICT_SHARED, node.id, line.address),
            )

    # -- owner-plane / directory-format helpers -----------------------

    def _set_owner(self, dir_entry: DirEntry, new: int) -> None:
        """Update the tracked owner/forwarder, counting migrations
        (clearing to NO_PROC is a release, not a transfer)."""
        if new >= 0 and new != dir_entry.owner:
            self.counters["owner_transfers"] += 1
        dir_entry.owner = new

    def _fanout_mask(self, sharers: int, requester: int) -> int:
        """The REPLY_ID invalidation fan-out through the configured
        directory format (the one place format precision matters)."""
        mask, overflowed = dir_mask_int(
            self._dir_kind, self._dir_param, sharers, requester,
            self.config.num_procs,
        )
        if overflowed:
            self.counters["dir_overflows"] += 1
        return mask

    # -- protocol handler (assignment.c:187-566) ----------------------
    #
    # One method per message type, dispatched through _DISPATCH.  The
    # map is the runtime mirror of the declarative transition table in
    # hpa2_tpu.analysis.table — the static analyzer probes each method
    # through _handle and diffs the observed transitions against the
    # table, and the dead-handler lint checks every _on_* method is
    # reachable from here.

    _DISPATCH = {
        MsgType.READ_REQUEST: "_on_read_request",
        MsgType.WRITE_REQUEST: "_on_write_request",
        MsgType.REPLY_RD: "_on_reply_rd",
        MsgType.REPLY_WR: "_on_reply_wr",
        MsgType.REPLY_ID: "_on_reply_id",
        MsgType.INV: "_on_inv",
        MsgType.UPGRADE: "_on_upgrade",
        MsgType.WRITEBACK_INV: "_on_writeback_inv",
        MsgType.WRITEBACK_INT: "_on_writeback_int",
        MsgType.FLUSH: "_on_flush",
        MsgType.FLUSH_INVACK: "_on_flush_invack",
        MsgType.EVICT_SHARED: "_on_evict_shared",
        MsgType.EVICT_MODIFIED: "_on_evict_modified",
        MsgType.UPGRADE_NOTIFY: "_on_upgrade_notify",
        MsgType.NACK: "_on_nack",
    }

    def _handle(self, node: Node, msg: Message) -> None:
        name = self._DISPATCH.get(msg.type)
        if name is None:
            raise AssertionError(f"unknown message type {msg.type}")
        cfg = self.config
        home = cfg.home_of(msg.address)
        blk = cfg.block_of(msg.address)
        line = node.line_for(msg.address)
        dir_entry = node.directory[blk] if node.id == home else None
        getattr(self, name)(node, msg, home, blk, line, dir_entry)

    def _on_read_request(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        P = self.planes
        assert dir_entry is not None, "READ_REQUEST must arrive at home"
        reply = Message(
            MsgType.REPLY_RD, node.id, msg.address,
            value=node.memory[blk], sharers=REPLY_RD_SHARED,
        )
        if dir_entry.state == DirState.U:
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(msg.sender)
            reply.sharers = P.rr_u_flag
            self._send(PH, msg.sender, reply)
        elif dir_entry.state == DirState.S:
            fwd = dir_entry.owner if P.has_fwd else NO_PROC
            if fwd >= 0 and fwd != msg.sender:
                # live forwarder serves cache-to-cache; the newest
                # reader becomes the forwarder
                self._send(
                    PH, fwd,
                    Message(
                        MsgType.WRITEBACK_INT, node.id, msg.address,
                        second_receiver=msg.sender,
                    ),
                )
                dir_entry.sharers |= bit(msg.sender)
                self._set_owner(dir_entry, msg.sender)
            else:
                dir_entry.sharers |= bit(msg.sender)
                reply.sharers = P.rr_s_flag
                self._send(PH, msg.sender, reply)
                if P.has_fwd and fwd != msg.sender:
                    # no live forwarder: the reader seeds F
                    self._set_owner(dir_entry, msg.sender)
        elif P.has_so and dir_entry.state == DirState.SO:
            owner = dir_entry.owner
            if owner == msg.sender:
                # owner lost its line (eviction in flight): demote to
                # clean-shared and serve from memory
                dir_entry.state = DirState.S
                self._set_owner(dir_entry, NO_PROC)
                dir_entry.sharers |= bit(msg.sender)
                reply.sharers = P.rr_s_flag
                self._send(PH, msg.sender, reply)
            else:
                # the owner answers every read cache-to-cache while SO
                self._send(
                    PH, owner,
                    Message(
                        MsgType.WRITEBACK_INT, node.id, msg.address,
                        second_receiver=msg.sender,
                    ),
                )
                dir_entry.sharers |= bit(msg.sender)
        else:  # EM
            owner = find_owner(dir_entry.sharers)
            assert owner != -1
            if owner == msg.sender:
                # owner re-requesting (its copy was evicted-silently
                # or lost): serve data, keep EM (assignment.c:215-221)
                reply.sharers = P.rr_u_flag
                self._send(PH, msg.sender, reply)
            else:
                self._send(
                    PH, owner,
                    Message(
                        MsgType.WRITEBACK_INT, node.id, msg.address,
                        second_receiver=msg.sender,
                    ),
                )
                if P.has_so:
                    # the owner keeps its dirty line as OWNED
                    dir_entry.state = DirState.SO
                    self._set_owner(dir_entry, owner)
                else:
                    # optimistic pre-flush transition
                    # (assignment.c:230-231)
                    dir_entry.state = DirState.S
                    if P.has_fwd:
                        self._set_owner(dir_entry, msg.sender)
                dir_entry.sharers |= bit(msg.sender)

    def _on_reply_rd(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        if (
            line.address != INVALID_ADDR
            and line.address != msg.address
            and line.state != CacheState.INVALID
        ):
            self._replace(PH, node, line)
        line.address = msg.address
        line.value = msg.value
        line.state = CacheState(self._rd_fill[msg.sharers])
        node.waiting = False

    def _on_writeback_int(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        P = self.planes
        if line.address == msg.address and int(line.state) in P.wbint_resp_states:
            flush = Message(
                MsgType.FLUSH, node.id, msg.address,
                value=line.value, second_receiver=msg.second_receiver,
            )
            if int(line.state) in P.wbint_home_flush_states:
                self._send(PH, home, flush)
                if msg.second_receiver != home:
                    self._send(PH, msg.second_receiver, flush.copy())
            else:
                # cache-to-cache fill without a home copy (MOESI OWNED
                # keeps the dirty line; MESIF FORWARD is already clean)
                self.counters["forwards"] += 1
                self._send(PH, msg.second_receiver, flush)
            line.state = CacheState(P.wbint_next_state)
        elif self.sem.intervention_miss_policy == "nack":
            self._send(
                PH, home,
                Message(
                    MsgType.NACK, node.id, msg.address,
                    sharers=0,  # 0 = read intervention
                    second_receiver=msg.second_receiver,
                ),
            )
        # else: silent drop (assignment.c:265-270) — requester hangs

    def _on_flush(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        if node.id == home:
            node.memory[blk] = msg.value
        if node.id == msg.second_receiver:
            if (
                line.address != INVALID_ADDR
                and line.address != msg.address
                and line.state != CacheState.INVALID
            ):
                self._replace(PH, node, line)
            line.address = msg.address
            line.value = msg.value
            line.state = CacheState(self.planes.flush_fill_state)
            node.waiting = False

    def _on_upgrade(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        P = self.planes
        assert dir_entry is not None, "UPGRADE must arrive at home"
        if dir_entry.state == DirState.S or (
            P.has_so and dir_entry.state == DirState.SO
        ):
            self._send(
                PH, msg.sender,
                Message(
                    MsgType.REPLY_ID, node.id, msg.address,
                    sharers=self._fanout_mask(dir_entry.sharers, msg.sender),
                ),
            )
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(msg.sender)
            if P.has_owner_plane:
                self._set_owner(dir_entry, NO_PROC)
        else:
            # fallback: directory lost track (assignment.c:317-326)
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(msg.sender)
            self._send(
                PH, msg.sender,
                Message(MsgType.REPLY_ID, node.id, msg.address, sharers=0),
            )

    def _on_reply_id(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        fan_out = True
        if line.address == msg.address and line.state != CacheState.MODIFIED:
            line.value = node.pending_write
            line.state = CacheState.MODIFIED
        elif line.address == msg.address and line.state == CacheState.MODIFIED:
            pass  # write already applied locally on the S-hit path
        else:
            # line was replaced while waiting: drop, no INVs
            # (assignment.c:339-347)
            fan_out = False
        if fan_out:
            for i in range(self.config.num_procs):
                if i != node.id and is_bit_set(msg.sharers, i):
                    self._send(
                        PH, i, Message(MsgType.INV, node.id, msg.address)
                    )
        node.waiting = False

    def _on_inv(self, node, msg, home, blk, line, dir_entry):
        if (
            line.address == msg.address
            and int(line.state) in self.planes.inv_states
        ):
            line.state = CacheState.INVALID
            self.counters["invalidations"] += 1

    def _on_write_request(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        P = self.planes
        assert dir_entry is not None, "WRITE_REQUEST must arrive at home"
        if self.sem.eager_write_request_memory:
            # HEAD quirk (assignment.c:379); fixtures update memory
            # only on FLUSH/FLUSH_INVACK/EVICT_MODIFIED
            node.memory[blk] = msg.value
        if dir_entry.state == DirState.U:
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(msg.sender)
            self._send(
                PH, msg.sender,
                Message(MsgType.REPLY_WR, node.id, msg.address),
            )
        elif dir_entry.state == DirState.S or (
            P.has_so and dir_entry.state == DirState.SO
        ):
            # the writer invalidates everyone, incl. any tracked
            # owner/forwarder
            self._send(
                PH, msg.sender,
                Message(
                    MsgType.REPLY_ID, node.id, msg.address,
                    sharers=self._fanout_mask(dir_entry.sharers, msg.sender),
                ),
            )
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(msg.sender)
            if P.has_owner_plane:
                self._set_owner(dir_entry, NO_PROC)
        else:  # EM
            owner = find_owner(dir_entry.sharers)
            assert owner != -1
            if owner == msg.sender:
                self._send(
                    PH, msg.sender,
                    Message(MsgType.REPLY_WR, node.id, msg.address),
                )
            else:
                self._send(
                    PH, owner,
                    Message(
                        MsgType.WRITEBACK_INV, node.id, msg.address,
                        second_receiver=msg.sender,
                    ),
                )
                # state stays EM; sharers optimistically = requester
                # (assignment.c:429)
                dir_entry.sharers = bit(msg.sender)

    def _on_reply_wr(self, node, msg, home, blk, line, dir_entry):
        assert (
            line.address == msg.address
            or line.address == INVALID_ADDR
            or line.state == CacheState.INVALID
        ), "REPLY_WR arrived but the slot holds another valid line"
        line.address = msg.address
        line.value = node.pending_write
        line.state = CacheState.MODIFIED
        node.waiting = False

    def _on_writeback_inv(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        if (
            line.address == msg.address
            and int(line.state) in self.planes.wbinv_resp_states
        ):
            ack = Message(
                MsgType.FLUSH_INVACK, node.id, msg.address,
                value=line.value, second_receiver=msg.second_receiver,
            )
            self._send(PH, home, ack)
            if msg.second_receiver != home:
                self._send(PH, msg.second_receiver, ack.copy())
            line.state = CacheState.INVALID
        elif self.sem.intervention_miss_policy == "nack":
            self._send(
                PH, home,
                Message(
                    MsgType.NACK, node.id, msg.address,
                    sharers=1,  # 1 = write intervention
                    second_receiver=msg.second_receiver,
                ),
            )
        # else: silent drop (assignment.c:467-472)

    def _on_flush_invack(self, node, msg, home, blk, line, dir_entry):
        if node.id == home:
            assert dir_entry is not None
            node.memory[blk] = msg.value
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(msg.second_receiver)
            if self.planes.has_owner_plane:
                self._set_owner(dir_entry, NO_PROC)
        if node.id == msg.second_receiver:
            assert (
                line.address == msg.address
                or line.address == INVALID_ADDR
                or line.state == CacheState.INVALID
            ), "FLUSH_INVACK arrived but the slot holds another valid line"
            line.address = msg.address
            # fixtures: the requester's own pending write survives;
            # HEAD installs the flushed old value (SURVEY.md §6.2.3)
            line.value = (
                msg.value
                if self.sem.flush_invack_fills_old_value
                else node.pending_write
            )
            line.state = CacheState.MODIFIED
            node.waiting = False

    def _on_evict_shared(self, node, msg, home, blk, line, dir_entry):
        PH = 0
        P = self.planes
        if node.id == home:
            assert dir_entry is not None
            if is_bit_set(dir_entry.sharers, msg.sender):
                was_s = dir_entry.state == DirState.S
                in_so = P.has_so and dir_entry.state == DirState.SO
                dir_entry.sharers &= ~bit(msg.sender)
                remaining = count_sharers(dir_entry.sharers)
                if remaining == 0:
                    dir_entry.state = DirState.U
                    if in_so or (P.has_fwd and was_s):
                        self._set_owner(dir_entry, NO_PROC)
                elif remaining == 1 and (was_s or in_so):
                    dir_entry.state = DirState.EM
                    if in_so or (P.has_fwd and was_s):
                        self._set_owner(dir_entry, NO_PROC)
                    survivor = find_owner(dir_entry.sharers)
                    notify_type = (
                        MsgType.EVICT_SHARED
                        if self.sem.overloaded_evict_shared_notify
                        else MsgType.UPGRADE_NOTIFY
                    )
                    self._send(
                        PH, survivor,
                        Message(notify_type, node.id, msg.address),
                    )
                elif (
                    P.has_fwd and was_s
                    and dir_entry.owner == msg.sender
                ):
                    # an evicting forwarder abdicates; the next reader
                    # re-seeds F
                    self._set_owner(dir_entry, NO_PROC)
                # several_left in SO: sharers shrink, SO + owner stay
        elif self.sem.overloaded_evict_shared_notify:
            # HEAD's overloaded upgrade-notify (assignment.c:522-538)
            if msg.sender == home:
                if (
                    line.address == msg.address
                    and line.state == CacheState.SHARED
                ):
                    line.state = CacheState.EXCLUSIVE
        # else: a non-home EVICT_SHARED cannot occur in fixture
        # semantics (the notify is UPGRADE_NOTIFY)

    def _on_upgrade_notify(self, node, msg, home, blk, line, dir_entry):
        # home -> surviving sharer: silent promotion (MESI/MESIF S->E,
        # MOESI also O->M).  Distinct type fixes the home-is-a-sharer
        # livelock (SURVEY.md §6.3); the home itself receives it
        # through its own mailbox too.
        if msg.sender == home and line.address == msg.address:
            nxt = self._notify_map.get(int(line.state))
            if nxt is not None:
                line.state = CacheState(nxt)

    def _on_evict_modified(self, node, msg, home, blk, line, dir_entry):
        P = self.planes
        assert dir_entry is not None, "EVICT_MODIFIED must arrive at home"
        node.memory[blk] = msg.value
        if dir_entry.state == DirState.EM and is_bit_set(
            dir_entry.sharers, msg.sender
        ):
            dir_entry.sharers = 0
            dir_entry.state = DirState.U
        elif (
            P.has_so
            and dir_entry.state == DirState.SO
            and dir_entry.owner == msg.sender
        ):
            # the OWNED cache wrote back: remaining sharers (if any)
            # are clean-shared against the freshened memory
            dir_entry.sharers &= ~bit(msg.sender)
            self._set_owner(dir_entry, NO_PROC)
            dir_entry.state = (
                DirState.U
                if count_sharers(dir_entry.sharers) == 0
                else DirState.S
            )
        # else: stale eviction — release-build HEAD leaves the
        # directory untouched (recovery exists only under DEBUG_MSG,
        # assignment.c:548-560)

    def _on_nack(self, node, msg, home, blk, line, dir_entry):
        # robust mode only: re-serve the original request from
        # memory.  The stale owner no longer holds the line, so the
        # home can satisfy the requester directly.
        PH = 0
        P = self.planes
        assert dir_entry is not None, "NACK must arrive at home"
        requester = msg.second_receiver
        if msg.sharers == 0:  # read
            dir_entry.state = DirState.S
            dir_entry.sharers |= bit(requester)
            if P.has_fwd:
                # the re-served reader becomes the forwarder
                self._set_owner(dir_entry, requester)
            elif P.has_so:
                # owner tracking is stale by construction
                self._set_owner(dir_entry, NO_PROC)
            self._send(
                PH, requester,
                Message(
                    MsgType.REPLY_RD, node.id, msg.address,
                    value=node.memory[blk], sharers=P.nack_rd_flag,
                ),
            )
        else:  # write
            dir_entry.state = DirState.EM
            dir_entry.sharers = bit(requester)
            if P.has_owner_plane:
                self._set_owner(dir_entry, NO_PROC)
            self._send(
                PH, requester,
                Message(MsgType.REPLY_WR, node.id, msg.address),
            )

    # -- instruction issue (assignment.c:590-697) ---------------------

    def _issue(self, node: Node) -> None:
        instr = node.trace[node.pc]
        node.pc += 1
        self.counters["instructions"] += 1
        self.issue_log.append(
            IssueRecord(
                proc=node.id, op=instr.op, address=instr.address,
                value=instr.value,
            )
        )
        PH = 1  # issue phase
        P = self.planes
        cfg = self.config
        home = cfg.home_of(instr.address)
        line = node.line_for(instr.address)

        if instr.op == "R":
            if (
                line.address == instr.address
                and int(line.state) in P.read_hit_states
            ):
                self.counters["read_hits"] += 1
            else:
                self.counters["read_misses"] += 1
                if line.address != INVALID_ADDR and line.state != CacheState.INVALID:
                    self._replace(PH, node, line)
                self._send(
                    PH, home,
                    Message(MsgType.READ_REQUEST, node.id, instr.address),
                )
                node.waiting = True
                # placeholder fill (assignment.c:626-628)
                line.state = CacheState.INVALID
                line.address = instr.address
                line.value = 0
        else:
            node.pending_write = instr.value
            if line.address == instr.address and line.state != CacheState.INVALID:
                self.counters["write_hits"] += 1
                if int(line.state) in P.silent_write_states:
                    line.value = instr.value
                    line.state = CacheState.MODIFIED  # silent E->M upgrade
                elif int(line.state) in P.upgrade_write_states:
                    self._send(
                        PH, home,
                        Message(MsgType.UPGRADE, node.id, instr.address),
                    )
                    # write applied locally before the REPLY_ID arrives
                    # (assignment.c:656-658)
                    line.value = instr.value
                    line.state = CacheState.MODIFIED
                    node.waiting = True
            else:
                self.counters["write_misses"] += 1
                if line.address != INVALID_ADDR and line.state != CacheState.INVALID:
                    self._replace(PH, node, line)
                self._send(
                    PH, home,
                    Message(
                        MsgType.WRITE_REQUEST, node.id, instr.address,
                        value=instr.value,
                    ),
                )
                node.waiting = True
                line.state = CacheState.INVALID
                line.address = instr.address
                line.value = 0

    # -- the lockstep cycle -------------------------------------------

    def step(self) -> bool:
        """Run one global cycle.  Returns True if any progress was made."""
        progress = False
        active = False  # watchdog progress: retired instr / drained msg
        handled = [False] * len(self.nodes)

        # 1. handle: up to messages_per_cycle messages per node, in
        # FIFO order (blocked nodes — those with deferred sends —
        # stall entirely, like a reference thread blocked inside
        # sendMessage, assignment.c:715-724).  The blocked check is a
        # cycle-start property: a node's own sends defer only at
        # end-of-cycle delivery, so they never gate its later drains
        # within the same cycle.
        for node in self.nodes:
            if node.pending_sends:
                continue
            for _ in range(self.config.messages_per_cycle):
                if not node.mailbox:
                    break
                # interconnect gating: the mailbox is an ordered virtual
                # channel — the head blocks until its delivery cycle
                # (later entries wait behind it, preserving FIFO)
                if node.mailbox[0].deliver_at > self.cycle:
                    break
                msg = node.mailbox.popleft()
                if self.trace_msgs:
                    self.msg_log.append(
                        f"Processor {node.id} msg from: {msg.sender}, "
                        f"type: {int(msg.type)}, "
                        f"address: 0x{msg.address:02X}"
                    )
                self._handle(node, msg)
                handled[node.id] = True
                progress = True
                active = True

        # 2. issue
        if self.replay_order is not None:
            issued: set = set()
            while self.order_pos < len(self.replay_order):
                rec = self.replay_order[self.order_pos]
                node = self.nodes[rec.proc]
                ready = (
                    node.id not in issued
                    and not node.mailbox
                    and not node.waiting
                    and not node.pending_sends
                    and node.pc < len(node.trace)
                )
                if not ready:
                    break
                nxt = node.trace[node.pc]
                if (nxt.op, nxt.address) != (rec.op, rec.address):
                    raise StallError(
                        f"replay order mismatch at {self.order_pos}: "
                        f"trace has {nxt}, order has {rec}"
                    )
                self._issue(node)
                issued.add(node.id)
                self.order_pos += 1
                progress = True
                active = True
                if not self.replay_batched:
                    break
        else:
            for node in self.nodes:
                if (
                    not node.mailbox
                    and not node.waiting
                    and not node.pending_sends
                    and node.pc < len(node.trace)
                ):
                    self._issue(node)
                    progress = True
                    active = True

        # 3. deliver (capacity backpressure; delivering a previously
        # deferred send is progress even in an otherwise idle cycle)
        if self._outbox or any(n.pending_sends for n in self.nodes):
            if self._deliver():
                progress = True

        # 4. dump-at-local-completion snapshots.  The canonical dump is
        # the *earliest* legal one; every later post-completion state is
        # kept as a candidate (see Node.dump_candidates).
        for node in self.nodes:
            if (
                node.pc >= len(node.trace)
                and not node.waiting
                and not node.pending_sends
            ):
                if not node.dumped:
                    if not node.mailbox:
                        node.dumped = True
                        node.snapshot = node.dump()
                        node.dump_candidates.append(node.snapshot)
                        progress = True
                elif handled[node.id]:
                    node.dump_candidates.append(node.dump())

        if active:
            self.last_activity_cycle = self.cycle
        if self.debug_invariants:
            from hpa2_tpu.utils.invariants import check_invariants

            bad = check_invariants(
                [n.dump() for n in self.nodes], self.config, mid_flight=True
            )
            if bad:
                raise self.stall_diagnostic(
                    "mid-flight invariant violation"
                )
        self.cycle += 1
        return progress

    def quiescent(self) -> bool:
        return all(
            n.pc >= len(n.trace)
            and not n.waiting
            and not n.mailbox
            and not n.pending_sends
            for n in self.nodes
        ) and (self.replay_order is None or self.order_pos >= len(self.replay_order))

    def stall_diagnostic(self, reason: str) -> StallDiagnostic:
        """Snapshot the structured post-mortem for a stalled system."""
        from hpa2_tpu.utils.invariants import check_invariants

        line_states: Dict[int, List[str]] = {}
        for n in self.nodes:
            lines = []
            for idx, ln in enumerate(n.cache):
                if ln.address == INVALID_ADDR:
                    continue
                lines.append(
                    f"[{idx}] 0x{ln.address:02X}="
                    f"{CacheState(ln.state).name}({ln.value})"
                )
            line_states[n.id] = lines
        return StallDiagnostic(
            reason=reason,
            cycle=self.cycle,
            mailbox_depths={n.id: len(n.mailbox) for n in self.nodes},
            waiting=[n.id for n in self.nodes if n.waiting],
            blocked=[n.id for n in self.nodes if n.pending_sends],
            line_states=line_states,
            recent_msgs=self.recent_msgs.lines(),
            invariant_violations=check_invariants(
                self.final_dumps(), self.config, mid_flight=True
            ),
            counters=self.stats(),
        )

    def stats(self) -> Dict[str, int]:
        """Counter dict in the shared one-stats-schema shape: engine
        counters plus the interconnect aggregates (only-when-nonzero,
        so ideal/fault-free parity with the JAX engines stays
        key-for-key exact)."""
        out = dict(self.counters)
        if self.link_tracker is not None:
            out.update(self.link_tracker.counters())
        return out

    def link_stats(self) -> Dict[str, dict]:
        """Per-link interconnect observability (empty for ideal)."""
        if self.link_tracker is None:
            return {}
        return self.link_tracker.link_stats()

    def run(
        self,
        max_cycles: int = 10_000_000,
        watchdog_cycles: int = 10_000,
    ) -> None:
        """Run to quiescence.

        Two stall detectors guard the loop.  The fast detector fires
        after 3 consecutive zero-progress cycles — sound when the
        transport is reliable, because an idle non-quiescent system
        can never move again.  Under fault injection a zero-progress
        cycle still retries stalled links with fresh randomness, so
        the fast detector is replaced by the watchdog: no instruction
        retired AND no mailbox drained for ``watchdog_cycles``
        consecutive cycles (0 disables it).  Both raise a structured
        :class:`StallDiagnostic` instead of spinning to ``max_cycles``.
        """
        stall = 0
        fault_on = self._fault_rng is not None
        while not (self.quiescent() and all(n.dumped for n in self.nodes)):
            progress = self.step()
            if self.cycle >= max_cycles:
                raise StallError(f"no quiescence after {max_cycles} cycles")
            if (
                watchdog_cycles
                and self.cycle - self.last_activity_cycle >= watchdog_cycles
            ):
                raise self.stall_diagnostic(
                    "watchdog: no instruction retired and no mailbox "
                    f"drained for {watchdog_cycles} cycles"
                )
            if not progress:
                # a cycle that only waited on in-flight interconnect
                # delays is not a livelock: gated heads become
                # handleable once their delivery cycle arrives
                gated = any(
                    n.mailbox and n.mailbox[0].deliver_at > self.cycle
                    for n in self.nodes
                )
                stall = 0 if gated else stall + 1
                if stall > 2 and not fault_on:
                    raise self.stall_diagnostic(
                        f"livelock at cycle {self.cycle}: stale "
                        "intervention dropped? cyclic full mailboxes? "
                        "use Semantics.intervention_miss_policy='nack' "
                        "/ a larger msg_buffer_size"
                    )
            else:
                stall = 0

    def continue_with(self, traces: Sequence[Sequence[Instr]]) -> None:
        """Swap in the next per-node instruction window after
        quiescence and restart the program counters — the spec-side
        mirror of PallasEngine's ``trace_window`` schedule (a legal
        re-scheduling of one long program as successive quiesced
        windows)."""
        if not self.quiescent():
            raise StallError("continue_with requires a quiescent system")
        if len(traces) != self.config.num_procs:
            raise ValueError("need one trace per node")
        for nd, tr in zip(self.nodes, traces):
            nd.trace = list(tr)
            nd.pc = 0

    # -- results ------------------------------------------------------

    def snapshots(self) -> List[NodeDump]:
        return [
            n.snapshot if n.snapshot is not None else n.dump() for n in self.nodes
        ]

    def final_dumps(self) -> List[NodeDump]:
        """Final quiescent state (a mode the reference lacks)."""
        return [n.dump() for n in self.nodes]


# _DISPATCH stays a literal dict (the analyzer's dispatch lint pins
# that), but it cannot drift from the table's event vocabulary: the
# compiled-table derivation must agree with it exactly.
assert SpecEngine._DISPATCH == generated_dispatch(), (
    "SpecEngine._DISPATCH disagrees with the dispatch generated from "
    "the transition table's event vocabulary"
)
