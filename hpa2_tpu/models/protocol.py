"""Core protocol data model: states, message types, message/instruction records.

Mirrors the reference's data model (assignment.c:15-81) with two
deliberate departures:

* ``MsgType.UPGRADE_NOTIFY`` is a distinct message type for the
  home -> last-remaining-sharer "your SHARED copy is now EXCLUSIVE"
  notification.  The reference overloads ``EVICT_SHARED`` for this and
  disambiguates by receiver==home (assignment.c:498-539), which
  misfires when the home node is itself a sharer and livelocks
  (SURVEY.md §6.3).  The shipped fixtures show the cleanly-resolved
  outcome, so the distinct type is the default semantics;
  ``Semantics.overloaded_evict_shared_notify`` restores HEAD behavior.
* ``MsgType.NACK`` exists for the robust intervention policy
  (``Semantics.intervention_miss_policy == "nack"``): an owner that
  receives a WRITEBACK_INT/WRITEBACK_INV for a line it no longer holds
  answers NACK instead of silently dropping it (the reference drops,
  assignment.c:265-270, leaving the requester waiting forever).

Enum *values* of the shared members match the reference enums
(assignment.c:17-34) so array-encoded state is directly comparable
across all backends and the dump formatter can index state names by
value.
"""

from __future__ import annotations

import dataclasses
import enum


class CacheState(enum.IntEnum):
    """Cache-line states.

    The first four are the MESI states with reference enum values
    (assignment.c:17).  The protocol-variant states append after them
    so MESI-encoded arrays stay bit-identical: ``OWNED`` is MOESI's
    dirty-shared responder, ``FORWARD`` is MESIF's clean designated
    responder.
    """

    MODIFIED = 0
    EXCLUSIVE = 1
    SHARED = 2
    INVALID = 3
    OWNED = 4    # MOESI only: dirty, shared, answers reads
    FORWARD = 5  # MESIF only: clean, shared, answers reads


class DirState(enum.IntEnum):
    """Directory entry states (assignment.c:18, README.md:20-23).

    ``SO`` appends after the reference values: MOESI's "shared with a
    dirty owner" state, whose owner is tracked in the separate
    dir-owner pointer plane.
    """

    EM = 0  # exactly one cache holds the block (clean or dirty)
    S = 1   # one or more caches hold it shared
    U = 2   # no cache holds it
    SO = 3  # MOESI only: shared, one OWNED cache holds the dirty copy


class MsgType(enum.IntEnum):
    """Coherence transactions (assignment.c:20-34) + rebuild extensions."""

    READ_REQUEST = 0
    WRITE_REQUEST = 1
    REPLY_RD = 2
    REPLY_WR = 3
    REPLY_ID = 4
    INV = 5
    UPGRADE = 6
    WRITEBACK_INV = 7
    WRITEBACK_INT = 8
    FLUSH = 9
    FLUSH_INVACK = 10
    EVICT_SHARED = 11
    EVICT_MODIFIED = 12
    # --- rebuild extensions (not in the reference enum) ---
    UPGRADE_NOTIFY = 13  # home -> surviving sharer: S line becomes E
    NACK = 14            # stale-intervention bounce (robust mode only)


#: Sentinel for an empty cache line.  The reference uses byte 0xFF
#: (assignment.c:785-787); the rebuild uses -1 so it can never collide
#: with a valid address at any scale.  The dump formatter renders it as
#: 0xFF for parity.
INVALID_ADDR = -1

#: "no second receiver" sentinel (assignment.c: secondReceiver = -1).
NO_PROC = -1


@dataclasses.dataclass
class Message:
    """One coherence message (assignment.c:53-61).

    ``sharers`` unifies the reference's overloaded ``bitVector`` field:
    for REPLY_RD it carries the exclusivity flag (2 = exclusive, 0 =
    shared — assignment.c:201/207/245), for REPLY_ID the sharer set to
    invalidate (assignment.c:306, 397).  It is an int bitmask of
    arbitrary width, so node count is not capped at 8.
    """

    type: MsgType
    sender: int
    address: int
    value: int = 0
    sharers: int = 0
    second_receiver: int = NO_PROC
    # Delivery cycle assigned by the interconnect model at acceptance
    # (hpa2_tpu/interconnect/): the receiver handles this message only
    # once ``cycle >= deliver_at``.  0 (the ideal topology) means
    # "next cycle", today's behavior.
    deliver_at: int = 0

    def copy(self) -> "Message":
        return dataclasses.replace(self)


#: REPLY_RD exclusivity flag values (assignment.c:201, 207, 245).
#: REPLY_RD_FORWARD is the MESIF extension: fill the line in FORWARD
#: state (clean designated responder); never emitted by MESI/MOESI.
REPLY_RD_EXCLUSIVE = 2
REPLY_RD_SHARED = 0
REPLY_RD_FORWARD = 1


@dataclasses.dataclass(frozen=True)
class Instr:
    """One trace instruction: RD addr / WR addr value (README.md:55-68)."""

    op: str  # 'R' or 'W'
    address: int
    value: int = 0

    def __post_init__(self) -> None:
        if self.op not in ("R", "W"):
            raise ValueError(f"bad instruction op {self.op!r}")


def bit(proc: int) -> int:
    return 1 << proc


def is_bit_set(mask: int, proc: int) -> bool:
    """assignment.c:94-96."""
    return bool((mask >> proc) & 1)


def find_owner(mask: int) -> int:
    """Lowest set bit, -1 if empty (assignment.c:98-105)."""
    if mask == 0:
        return -1
    return (mask & -mask).bit_length() - 1


def count_sharers(mask: int) -> int:
    """Popcount (assignment.c:107-115)."""
    return bin(mask).count("1")
