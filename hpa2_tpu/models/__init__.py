"""Protocol data model and the pure-Python reference-semantics engine."""

from hpa2_tpu.models.protocol import (
    CacheState,
    DirState,
    MsgType,
    Message,
    Instr,
    INVALID_ADDR,
)

__all__ = [
    "CacheState",
    "DirState",
    "MsgType",
    "Message",
    "Instr",
    "INVALID_ADDR",
]
