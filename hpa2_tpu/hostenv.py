"""Host-process JAX environment recipes (jax-free; safe to import
before backend init).

The axon sitecustomize registers the TPU PJRT plugin at interpreter
startup and pins the backend, and its init can hang on the tunnel —
an in-process ``JAX_PLATFORMS`` override is too late.  Every entry
point that needs a guaranteed-CPU JAX (tests, the driver dryrun, the
bench fallback) therefore re-execs or spawns a fresh interpreter with
THIS environment.  Keep the recipe here only: it has three consumers
(tests/conftest.py, __graft_entry__.py, bench.py) and drift between
them reintroduces the round-1 rc=124 hang in whichever copy is stale.
"""

from __future__ import annotations

import os
from typing import Optional

_DEVICE_COUNT_FLAG = "xla_force_host_platform_device_count"


def cache_env(env: dict) -> dict:
    """Persistent XLA compile cache (the jitted programs are identical
    across runs, so recompiles dominate otherwise)."""
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/hpa2_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return env


def forced_cpu_env(
    base: Optional[dict] = None, n_devices: Optional[int] = None
) -> dict:
    """A copy of ``base`` (default: os.environ) forcing the CPU backend
    with ``n_devices`` virtual devices (None = leave any existing
    device-count flag untouched)."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable axon TPU registration
    if n_devices is not None:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if _DEVICE_COUNT_FLAG not in f
        ]
        flags.append(f"--{_DEVICE_COUNT_FLAG}={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return cache_env(env)


def has_device_count_flag(env: Optional[dict] = None) -> bool:
    source = os.environ if env is None else env
    return _DEVICE_COUNT_FLAG in source.get("XLA_FLAGS", "")


def reexec_with_virtual_mesh(
    n_devices: int = 8, guard_var: str = "_HPA2_VMESH_REEXEC"
) -> None:
    """Re-exec the current script under a forced-CPU env exposing
    ``n_devices`` virtual devices — for entry points that need a
    multi-device mesh without TPU hardware (scripts/scale_runs.py
    multichip mode).  No-op when the device-count flag is already set
    or after the re-exec (``guard_var``); call BEFORE importing jax,
    since the flag cannot take effect once the backend initialized."""
    import sys

    if os.environ.get(guard_var) == "1" or has_device_count_flag():
        return
    env = forced_cpu_env(n_devices=n_devices)
    env[guard_var] = "1"
    os.execvpe(sys.executable, [sys.executable] + sys.argv, env)


def shard_map(f, mesh, in_specs, out_specs, check_replication=False):
    """Version-compatible ``shard_map`` (jax is imported lazily so this
    module stays safe to import before backend init).

    The API moved twice across the JAX releases this repo meets:
    ``jax.experimental.shard_map.shard_map`` (kwarg ``check_rep``) is
    the only spelling in older installs, while newer ones promote it to
    ``jax.shard_map`` (kwarg ``check_vma``) and deprecate — then remove
    — the experimental path.  Resolve the public name first so the
    deprecated import is never touched when the modern one exists.
    """
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_replication,
        )
    from jax.experimental.shard_map import shard_map as fn

    return fn(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_replication,
    )
